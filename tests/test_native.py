"""Native C++ loader tests: build from source, compare against the numpy path.

Reference parity: Harp's native IO layer had no tests at all; here the native and
pure-python paths are cross-checked on the same files.
"""

import os

import numpy as np
import pytest

from harp_tpu.io import loaders, native_bridge, native_build


@pytest.fixture(scope="module")
def native_lib():
    path = native_build.build()
    if path is None:
        pytest.skip("no C++ compiler available")
    native_bridge.reset()
    assert native_bridge.available()
    return path


def _write(tmp, name, text):
    p = os.path.join(tmp, name)
    with open(p, "w") as f:
        f.write(text)
    return p


def test_parse_csv_matches_numpy(native_lib, tmp_path):
    rng = np.random.default_rng(5)
    mat = (rng.standard_normal((37, 11)) * 100).astype(np.float32)
    lines = "\n".join(",".join(f"{v:.6g}" for v in row) for row in mat)
    p = _write(str(tmp_path), "m.csv", lines + "\n")
    got = native_bridge.parse_csv(p, ",")
    assert got is not None and got.shape == (37, 11)
    ref = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_parse_csv_no_trailing_newline_and_exponents(native_lib, tmp_path):
    p = _write(str(tmp_path), "e.csv", "1.5e2,-3,0.25\n-1e-3,4,5")
    got = native_bridge.parse_csv(p, ",")
    np.testing.assert_allclose(
        got, np.array([[150.0, -3.0, 0.25], [-0.001, 4.0, 5.0]], np.float32))


def test_parse_coo_matches_numpy(native_lib, tmp_path):
    rng = np.random.default_rng(6)
    n = 500
    rows = rng.integers(0, 1000, n)
    cols = rng.integers(0, 800, n)
    vals = rng.standard_normal(n).astype(np.float32)
    text = "\n".join(f"{r} {c} {v:.6g}" for r, c, v in zip(rows, cols, vals))
    p = _write(str(tmp_path), "c.coo", text + "\n")
    triple = native_bridge.parse_coo(p)
    assert triple is not None
    np.testing.assert_array_equal(triple[0], rows)
    np.testing.assert_array_equal(triple[1], cols)
    np.testing.assert_allclose(triple[2], vals, rtol=1e-5)


def test_loaders_use_native_path(native_lib, tmp_path):
    mats = []
    paths = []
    for i in range(3):
        m = np.full((4, 3), float(i), np.float32)
        mats.append(m)
        paths.append(_write(str(tmp_path), f"f{i}.csv",
                            "\n".join(",".join(map(str, r)) for r in m) + "\n"))
    out = loaders.load_dense_csv(paths, num_threads=2)
    np.testing.assert_allclose(out, np.concatenate(mats, axis=0))


def test_csr_roundtrip():
    rows = np.array([2, 0, 1, 0, 2], np.int64)
    cols = np.array([1, 0, 2, 1, 0], np.int64)
    vals = np.array([5, 1, 3, 2, 4], np.float32)
    indptr, idx, v = loaders.coo_to_csr(rows, cols, vals)
    assert indptr.tolist() == [0, 2, 3, 5]
    # row 0 entries: cols {0,1} vals {1,2}
    np.testing.assert_array_equal(np.sort(idx[0:2]), [0, 1])


def test_native_coo_to_csr_matches_numpy_and_is_stable(native_lib):
    from harp_tpu.io import native_bridge

    rng = np.random.default_rng(4)
    n, r = 50000, 700
    rows = rng.integers(0, r, n)
    cols = rng.integers(0, 900, n)
    vals = rng.random(n).astype(np.float32)
    out = native_bridge.coo_to_csr(rows, cols, vals, r)
    assert out is not None
    indptr, idx, v = out
    order = np.argsort(rows, kind="stable")       # the stability oracle
    ref_ptr = np.zeros(r + 1, np.int64)
    np.add.at(ref_ptr, rows + 1, 1)
    np.cumsum(ref_ptr, out=ref_ptr)
    np.testing.assert_array_equal(indptr, ref_ptr)
    np.testing.assert_array_equal(idx, cols[order])
    np.testing.assert_array_equal(v, vals[order])


def test_native_coo_to_csr_rejects_out_of_range(native_lib):
    from harp_tpu.io import native_bridge

    rows = np.array([0, 7], np.int64)
    cols = np.array([0, 0], np.int64)
    vals = np.ones(2, np.float32)
    assert native_bridge.coo_to_csr(rows, cols, vals, 7) is None   # row == R
    assert native_bridge.coo_to_csr(-rows, cols, vals, 7) is None  # negative


def test_load_coo_multi_file_pool(native_lib, tmp_path):
    """MTReader parity: files read by the thread pool, concatenated in path
    order regardless of completion order."""
    paths = []
    for i in range(5):
        lines = "\n".join(f"{i} {j} {i}.5" for j in range(4)) + "\n"
        paths.append(_write(str(tmp_path), f"c{i}.coo", lines))
    rows, cols, vals = loaders.load_coo(paths, num_threads=3)
    assert rows.tolist() == sum(([i] * 4 for i in range(5)), [])
    assert cols.tolist() == list(range(4)) * 5
    np.testing.assert_allclose(vals, np.repeat(np.arange(5) + 0.5, 4))
