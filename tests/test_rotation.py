"""Rotation pipeline tests: the dymoro-equivalent must visit every block on every
worker exactly once per epoch and return blocks home."""

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu.collectives import lax_ops, rotation

W = 8


def test_rotate_scan_visits_all_blocks(session):
    # Each worker stamps (worker_id, step, src_block) while holding a block.
    blocks = np.arange(W, dtype=np.float32).reshape(W, 1)

    def body(carry, blk, t):
        # carry: (W,) visit-count per source block, indexed by block value
        idx = blk[0].astype(jnp.int32)
        carry = carry.at[idx].add(1)
        return carry, blk

    def f(b):
        carry = jnp.zeros((W,), jnp.int32)
        carry, out = rotation.rotate_scan(body, carry, b, W)
        return carry[None], out

    counts, final = session.spmd(
        f, in_specs=(session.shard(),),
        out_specs=(session.shard(), session.shard()))(blocks)
    counts = np.asarray(counts).reshape(W, W)
    # every worker saw every block exactly once
    np.testing.assert_array_equal(counts, np.ones((W, W), np.int32))
    # blocks returned home
    np.testing.assert_array_equal(np.asarray(final), blocks)


def test_pipelined_rotation_double_buffer(session):
    # Two slices; over 2W micro-steps each worker must see all 2W slice-blocks.
    # Block layout: [immutable id, mutable payload].
    a = np.stack([np.arange(W), np.zeros(W)], axis=1).astype(np.float32)
    b = np.stack([np.arange(W, 2 * W), np.zeros(W)], axis=1).astype(np.float32)

    def body(carry, blk, t):
        idx = blk[0, 0].astype(jnp.int32)
        carry = carry.at[idx].add(1)
        return carry, blk.at[0, 1].add(1.0)  # mutate payload, keep id

    def f(ba, bb):
        carry = jnp.zeros((2 * W,), jnp.int32)
        carry, sa, sb = rotation.pipelined_rotation(body, carry, ba, bb, 2 * W)
        return carry[None], sa, sb

    counts, sa, sb = session.spmd(
        f, in_specs=(session.shard(), session.shard()),
        out_specs=(session.shard(), session.shard(), session.shard()))(a, b)
    counts = np.asarray(counts).reshape(W, 2 * W)
    np.testing.assert_array_equal(counts, np.ones((W, 2 * W), np.int32))
    # every block visited once per worker (payload == W) and returned home (id intact)
    np.testing.assert_array_equal(np.asarray(sa)[:, 0], a[:, 0])
    np.testing.assert_array_equal(np.asarray(sa)[:, 1], np.full(W, float(W)))
    np.testing.assert_array_equal(np.asarray(sb)[:, 0], b[:, 0])
    np.testing.assert_array_equal(np.asarray(sb)[:, 1], np.full(W, float(W)))


def test_rotator_class(session):
    r = rotation.Rotator(num_workers=W, num_slices=2)
    a = np.ones((W, 2), np.float32)
    b = np.ones((W, 2), np.float32)

    def body(carry, blk, t):
        return carry + jnp.sum(blk), blk

    def f(ba, bb):
        carry, (sa, sb) = r.run(body, jnp.zeros(()), (ba, bb), epochs=1)
        return carry[None], sa, sb

    carry, sa, sb = session.spmd(
        f, in_specs=(session.shard(), session.shard()),
        out_specs=(session.shard(), session.shard(), session.shard()))(a, b)
    np.testing.assert_allclose(np.asarray(carry), np.full(W, 2.0 * 2 * W))


def test_rotator_rejects_bad_slices():
    import pytest
    with pytest.raises(ValueError, match="num_slices"):
        rotation.Rotator(num_workers=W, num_slices=3)
