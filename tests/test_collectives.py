"""Property tests for every collective against numpy references on an 8-worker mesh.

Reference test-strategy parity (SURVEY §4): Harp tested collectives via standalone
multi-JVM mains; here each op is asserted against the mathematically expected result.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import harp_tpu
from harp_tpu import combiner as cb
from harp_tpu import partitioner as pt
from harp_tpu.collectives import lax_ops, table_ops
from harp_tpu.table import Dist, Table

W = 8
P_TOTAL = 16  # partitions
SHAPE = (P_TOTAL, 3, 5)


def spmd(session, fn, n_shard_args=0, n_rep_args=1, out="rep"):
    in_specs = tuple([session.shard()] * n_shard_args + [session.replicate()] * n_rep_args)
    out_specs = session.shard() if out == "shard" else session.replicate()
    return session.spmd(fn, in_specs=in_specs, out_specs=out_specs)


def per_worker_contributions(rng):
    # contributions[w] = worker w's LOCAL table data
    return rng.normal(size=(W,) + SHAPE).astype(np.float32)


def run_local_op(session, contribs, fn, out="rep"):
    """Feed worker w its own contribution: shard a (W, P, ...) array on axis 0."""
    def wrapper(c):
        t = Table.local(c[0], num_workers=W)  # c: (1, P, ...) local block
        return fn(t)
    return session.spmd(
        wrapper, in_specs=(session.shard(),), out_specs=(session.shard() if out == "shard" else session.replicate()),
    )(contribs)


class TestAllreduce:
    @pytest.mark.parametrize("op,ref", [
        (cb.SUM, lambda c: c.sum(0)),
        (cb.MAX, lambda c: c.max(0)),
        (cb.MIN, lambda c: c.min(0)),
        (cb.AVG, lambda c: c.mean(0)),
        (cb.MULTIPLY, lambda c: c.prod(0)),
        (cb.MINUS, lambda c: c[0] - c[1:].sum(0)),
    ])
    def test_allreduce(self, session, rng, op, ref):
        contribs = per_worker_contributions(rng)

        def f(c):
            t = Table.local(c[0], combiner=op, num_workers=W)
            return table_ops.allreduce(t).data

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.replicate())(contribs)
        np.testing.assert_allclose(np.asarray(out), ref(contribs), rtol=2e-5)


class TestReduceBroadcastGather:
    def test_reduce_root_gets_sum_others_identity(self, session, rng):
        contribs = per_worker_contributions(rng)

        def f(c):
            t = Table.local(c[0], num_workers=W)
            return table_ops.reduce(t, root=2).data

        # out_specs sharded: recover each worker's private view
        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.shard())(contribs)
        out = np.asarray(out).reshape((W,) + SHAPE)
        np.testing.assert_allclose(out[2], contribs.sum(0), rtol=2e-5)
        for w in range(W):
            if w != 2:
                np.testing.assert_array_equal(out[w], np.zeros(SHAPE, np.float32))

    def test_broadcast(self, session, rng):
        contribs = per_worker_contributions(rng)

        def f(c):
            t = Table.local(c[0], num_workers=W)
            return table_ops.broadcast(t, root=3).data

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.replicate())(contribs)
        np.testing.assert_allclose(np.asarray(out), contribs[3], rtol=1e-6)

    def test_gather(self, session, rng):
        blocks = rng.normal(size=SHAPE).astype(np.float32)  # block w = partitions of w

        def f(b):
            t = Table.sharded(b, num_workers=W)
            return table_ops.gather(t, root=0).data

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.shard())(blocks)
        out = np.asarray(out).reshape((W,) + SHAPE)
        np.testing.assert_allclose(out[0], blocks, rtol=1e-6)
        assert np.all(out[1:] == 0)


class TestRegroupAllgather:
    @pytest.mark.parametrize("op,ref", [
        (cb.SUM, lambda c: c.sum(0)),
        (cb.MAX, lambda c: c.max(0)),
    ])
    def test_regroup_block(self, session, rng, op, ref):
        contribs = per_worker_contributions(rng)

        def f(c):
            t = Table.local(c[0], combiner=op, num_workers=W)
            return table_ops.regroup(t).data

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.shard())(contribs)
        # sharded out: concatenated blocks in worker order = combined table in ID order
        np.testing.assert_allclose(np.asarray(out), ref(contribs), rtol=2e-5)

    def test_aggregate_equals_allreduce(self, session, rng):
        contribs = per_worker_contributions(rng)

        def f(c):
            t = Table.local(c[0], num_workers=W)
            return table_ops.aggregate(t).data

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.replicate())(contribs)
        np.testing.assert_allclose(np.asarray(out), contribs.sum(0), rtol=2e-5)

    def test_regroup_allgather_modulo_partitioner(self, session, rng):
        contribs = per_worker_contributions(rng)
        part = pt.ModuloPartitioner(P_TOTAL, W)

        def f(c):
            t = Table.local(c[0], num_workers=W)
            g = table_ops.regroup(t, part)
            return table_ops.allgather(g, part).data

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.replicate())(contribs)
        # ID order must be restored exactly
        np.testing.assert_allclose(np.asarray(out), contribs.sum(0), rtol=2e-5)

    def test_modulo_partitioner_places_partitions_on_owners(self, session, rng):
        contribs = per_worker_contributions(rng)
        part = pt.ModuloPartitioner(P_TOTAL, W)

        def f(c):
            t = Table.local(c[0], num_workers=W)
            return table_ops.regroup(t, part).data

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.shard())(contribs)
        out = np.asarray(out).reshape((W, P_TOTAL // W) + SHAPE[1:])
        total = contribs.sum(0)
        for w in range(W):
            # worker w owns partitions with pid % W == w, in ascending pid order
            pids = [pid for pid in range(P_TOTAL) if pid % W == w]
            np.testing.assert_allclose(out[w], total[pids], rtol=2e-5)


class TestRotate:
    def test_rotate_ring(self, session, rng):
        blocks = rng.normal(size=SHAPE).astype(np.float32)

        def f(b):
            t = Table.sharded(b, num_workers=W)
            return table_ops.rotate(t, steps=1).data

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.shard())(blocks)
        out = np.asarray(out).reshape((W, P_TOTAL // W) + SHAPE[1:])
        src = blocks.reshape((W, P_TOTAL // W) + SHAPE[1:])
        for w in range(W):
            np.testing.assert_allclose(out[(w + 1) % W], src[w], rtol=1e-6)

    def test_full_rotation_cycle_restores(self, session, rng):
        blocks = rng.normal(size=SHAPE).astype(np.float32)

        def f(b):
            t = Table.sharded(b, num_workers=W)
            def body(i, tt):
                return table_ops.rotate(tt, steps=1)
            return jax.lax.fori_loop(0, W, body, t).data

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.shard())(blocks)
        np.testing.assert_allclose(np.asarray(out), blocks, rtol=1e-6)

    def test_rotate_with_map(self, session, rng):
        blocks = rng.normal(size=SHAPE).astype(np.float32)
        mapping = {i: (i + 3) % W for i in range(W)}

        def f(b):
            t = Table.sharded(b, num_workers=W)
            return table_ops.rotate_with_map(t, mapping).data

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.shard())(blocks)
        out = np.asarray(out).reshape((W, P_TOTAL // W) + SHAPE[1:])
        src = blocks.reshape((W, P_TOTAL // W) + SHAPE[1:])
        for w in range(W):
            np.testing.assert_allclose(out[(w + 3) % W], src[w], rtol=1e-6)


class TestPushPull:
    def test_push_pull_parameter_server(self, session, rng):
        global_init = rng.normal(size=SHAPE).astype(np.float32)
        contribs = per_worker_contributions(rng)

        def f(g_block, c):
            g = Table.sharded(g_block, num_workers=W)
            local = Table.local(c[0], num_workers=W)
            g2 = table_ops.push(local, g)
            return table_ops.pull(g2).data

        out = session.spmd(
            f, in_specs=(session.shard(), session.shard()),
            out_specs=session.replicate())(global_init, contribs)
        np.testing.assert_allclose(np.asarray(out), global_init + contribs.sum(0),
                                   rtol=2e-5)


class TestGroupByKey:
    def test_group_by_key_sum(self, session, rng):
        keys = rng.integers(0, 10, size=(W, 6)).astype(np.int32)
        vals = rng.normal(size=(W, 6, 4)).astype(np.float32)

        def f(k, v):
            return table_ops.group_by_key(k[0], v[0], num_keys=10)

        out = session.spmd(f, in_specs=(session.shard(), session.shard()),
                           out_specs=session.replicate())(keys, vals)
        ref = np.zeros((10, 4), np.float32)
        for w in range(W):
            for i in range(6):
                ref[keys[w, i]] += vals[w, i]
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=1e-5)


class TestLaxOps:
    def test_barrier_and_ids(self, session):
        def f():
            lax_ops.barrier()
            return lax_ops.worker_id()[None]

        out = session.spmd(f, in_specs=(), out_specs=session.shard())()
        np.testing.assert_array_equal(np.asarray(out), np.arange(W))

    def test_all_to_all_transpose(self, session, rng):
        x = rng.normal(size=(W, W, 2)).astype(np.float32)  # worker w sends row j to j

        def f(xl):
            return lax_ops.all_to_all(xl[0])

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.shard())(x)
        out = np.asarray(out).reshape(W, W, 2)
        np.testing.assert_allclose(out, x.transpose(1, 0, 2), rtol=1e-6)

    def test_send_recv(self, session, rng):
        x = rng.normal(size=(W, 3)).astype(np.float32)

        def f(xl):
            return lax_ops.send_recv(xl[0], [(0, 5)])

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.shard())(x)
        out = np.asarray(out).reshape(W, 3)
        np.testing.assert_allclose(out[5], x[0], rtol=1e-6)
        assert np.all(out[np.arange(W) != 5] == 0)


class TestTablePadding:
    def test_ragged_partition_count_pads_with_identity(self, session, rng):
        # 13 partitions on 8 workers -> padded to 16; MAX identity = -inf
        contribs = rng.normal(size=(W, 13, 4)).astype(np.float32)

        def f(c):
            t = Table.local(c[0], combiner=cb.MAX, num_workers=W)
            out = table_ops.allreduce(t)
            return out.trim()

        out = session.spmd(f, in_specs=(session.shard(),),
                           out_specs=session.replicate())(contribs)
        assert out.shape == (13, 4)
        np.testing.assert_allclose(np.asarray(out), contribs.max(0), rtol=2e-5)


class TestJoin:
    def test_join_colocates_with_static(self, session, rng):
        """GraphCollective.join parity: dynamic partitions land where the
        matching static partitions live, combining contributions."""
        import jax.numpy as jnp

        from harp_tpu import Table
        from harp_tpu.collectives import table_ops

        w = session.num_workers
        p = 2 * w

        def prog(static_block, contrib):
            static = Table.sharded(static_block, num_workers=w)
            dynamic = Table.local(contrib, num_workers=w, name="dyn")
            joined = table_ops.join(dynamic, static)
            # joined block i must sit beside static block i: same local shape
            return joined.data + 0.0 * static.data

        static_full = np.arange(p * 3, dtype=np.float32).reshape(p, 3)
        contrib = np.ones((p, 3), np.float32)
        out = session.run(
            prog, session.scatter(jnp.asarray(static_full)),
            session.replicate_put(jnp.asarray(contrib)),
            in_specs=(session.shard(), session.replicate()),
            out_specs=session.shard())
        # every worker contributed 1s for every partition -> combined value = W
        np.testing.assert_allclose(np.asarray(out), np.full((p, 3), w))

    def test_join_requires_matching_counts(self, session):
        import jax.numpy as jnp

        from harp_tpu import Table
        from harp_tpu.collectives import table_ops

        w = session.num_workers

        def prog(static_block):
            static = Table.sharded(static_block, num_workers=w)
            dynamic = Table.local(jnp.ones((4 * w, 2)), num_workers=w)
            return table_ops.join(dynamic, static).data

        import pytest

        with pytest.raises(ValueError, match="matching partition counts"):
            session.run(prog, session.scatter(jnp.ones((w, 2))),
                        in_specs=(session.shard(),), out_specs=session.shard())


class TestGroupByKeySharded:
    """Owner-partitioned shuffle (VERDICT #8): parity with the allgather
    implementation, O(N/W + K/W) intermediate shapes, overflow accounting."""

    def _run(self, session, keys, vals, num_keys, combiner=None, cap=0,
             replicate=True):
        from harp_tpu import combiner as cb

        combiner = combiner or cb.SUM

        def f(k, v):
            out, ovf = table_ops.group_by_key_sharded(
                k[0], v[0], num_keys=num_keys, combiner=combiner,
                capacity=cap, replicate_result=replicate)
            return (out if replicate else out[None]), ovf

        out_spec = session.replicate() if replicate else session.shard()
        return session.spmd(
            f, in_specs=(session.shard(), session.shard()),
            out_specs=(out_spec, session.replicate()))(keys, vals)

    def test_parity_with_allgather_group_by_key(self, session, rng):
        from harp_tpu import combiner as cb

        keys = rng.integers(0, 16, size=(W, 12)).astype(np.int32)
        vals = rng.normal(size=(W, 12, 3)).astype(np.float32)

        def ref_f(k, v):
            return table_ops.group_by_key(k[0], v[0], num_keys=16)

        ref = np.asarray(session.spmd(
            ref_f, in_specs=(session.shard(), session.shard()),
            out_specs=session.replicate())(keys, vals))
        flat_k = keys.reshape(-1)
        flat_v = vals.reshape(-1, 3)
        refs = {}
        refs[cb.SUM.op] = ref
        cnt = np.maximum(np.bincount(flat_k, minlength=16), 1)[:, None]
        refs[cb.AVG.op] = ref / cnt
        mx = np.full((16, 3), -np.inf, np.float32)
        mn = np.full((16, 3), np.inf, np.float32)
        np.maximum.at(mx, flat_k, flat_v)
        np.minimum.at(mn, flat_k, flat_v)
        refs[cb.MAX.op] = mx
        refs[cb.MIN.op] = mn
        present = np.bincount(flat_k, minlength=16) > 0
        for comb in (cb.SUM, cb.AVG, cb.MAX, cb.MIN):
            out, ovf = self._run(session, keys, vals, 16, comb, cap=12)
            assert int(ovf) == 0
            out = np.asarray(out)
            assert out.shape == (16, 3)
            np.testing.assert_allclose(out[present], refs[comb.op][present],
                                       rtol=2e-5, atol=1e-5)

    def test_sharded_result_block_and_footprint(self, session, rng):
        # replicate_result=False keeps only this worker's K/W key block, and
        # the bucket capacity (the only N-dependent intermediate) is the
        # requested O(N/W) size
        n_local, num_keys = 16, 32
        keys = rng.integers(0, num_keys, size=(W, n_local)).astype(np.int32)
        vals = rng.normal(size=(W, n_local)).astype(np.float32)
        cap = 2 * n_local // W + n_local % W + 4     # O(N/W), not O(N)
        out, ovf = self._run(session, keys, vals, num_keys, cap=cap,
                             replicate=False)
        assert int(ovf) == 0
        out = np.asarray(out)
        assert out.shape == (W, num_keys // W)       # per-worker key block
        ref = np.zeros(num_keys, np.float32)
        np.add.at(ref, keys.reshape(-1), vals.reshape(-1))
        np.testing.assert_allclose(out.reshape(-1), ref, rtol=2e-5, atol=1e-5)

    def test_overflow_is_counted_not_silent(self, session):
        # every record targets key 0 → destination bucket 0 overflows
        keys = np.zeros((W, 8), np.int32)
        vals = np.ones((W, 8), np.float32)
        out, ovf = self._run(session, keys, vals, 16, cap=2)
        assert int(ovf) == W * 8 - W * 2             # 2 survive per worker
        assert float(np.asarray(out)[0]) == W * 2.0

    def test_negative_keys_dropped_not_misrouted(self, session, rng):
        # advisor r2: a negative dest used to pass the d_s < w check and land
        # (clamped) in worker 0's bucket as a phantom delivery; now negatives
        # route to the virtual drop destination like valid=False rows
        keys = rng.integers(0, 16, size=(W, 10)).astype(np.int32)
        keys[:, ::3] = -rng.integers(1, 50, size=keys[:, ::3].shape)
        vals = np.ones((W, 10), np.float32)
        out, ovf = self._run(session, keys, vals, 16, cap=16)
        assert int(ovf) == 0                         # dropped, not overflow
        ref = np.zeros(16, np.float32)
        good = keys >= 0
        np.add.at(ref, keys[good], vals[good])
        np.testing.assert_allclose(np.asarray(out).reshape(-1), ref,
                                   rtol=1e-6)


class TestQuantizedBenchRows:
    """The collectives_quantized bench group (bench.py --only
    collectives_quantized): row schema + wire-byte pricing."""

    def test_quant_bytes_moved_prices_the_codec_wire_format(self):
        from harp_tpu.benchmark import collectives as bc

        s = 1 << 20
        f32_ar = bc._bytes_moved("allreduce", s, 8)
        bf16_ar = bc._quant_bytes_moved("allreduce", s, 8, "bf16")
        int8_ar = bc._quant_bytes_moved("allreduce", s, 8, "int8")
        assert bf16_ar == f32_ar / 2
        # int8 = 1/4 payload + per-256-elem f32 scales (~1.6% overhead)
        assert f32_ar / 4 < int8_ar < f32_ar / 4 * 1.05
        assert bc._quant_bytes_moved("rotate", s, 8, "bf16") == s / 2

    def test_bench_rows_emit_convention_and_all_codecs(self, session):
        from harp_tpu.benchmark import collectives as bc

        rows = bc.bench_collectives_quantized(session, sizes_kb=[4],
                                              loops=2)
        assert {r["codec"] for r in rows} == {"f32", "int8", "bf16"}
        assert {r["op"] for r in rows} == {"allreduce", "rotate"}
        for r in rows:
            assert r["payload_bytes_per_worker"] > 0
            assert r["busbw_gbps"] > 0
            assert r["link_class"] == "ici"
            assert "busbw" in r["convention"]
