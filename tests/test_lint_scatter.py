"""Hot-path scatter lint (tools/lint_scatter.py) — tier-1.

XLA's indexed-update lowering serializes on the TPU scatter unit (measured
8.8× slower than the one-hot-GEMM form, PERF.md r4/r5); hot code must route
through ops/lane_pack. This test keeps the device trees clean and the
allowlist honest.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import lint_scatter  # noqa: E402


def test_hot_trees_have_no_unallowlisted_scatters():
    violations = lint_scatter.check(REPO)
    assert not violations, "\n".join(str(v) for v in violations)


def test_allowlist_entries_are_all_live():
    """An allowlist row whose code no longer scatters must be pruned —
    otherwise it silently exempts FUTURE scatters in that function."""
    assert lint_scatter.stale_allowlist_entries(REPO) == []


def test_detects_a_new_hot_scatter():
    src = (
        "def hot_loop(x, idx, v):\n"
        "    return x.at[idx].add(v)\n"
    )
    got = lint_scatter._scan_source(src, "harp_tpu/models/fake.py")
    assert len(got) == 1
    assert got[0].func == "hot_loop" and got[0].method == "add"
    # .at[].set counts too; plain getitem (a gather) does not
    src2 = ("def f(x, idx):\n"
            "    y = x.at[idx].set(0.0)\n"
            "    return y[idx]\n")
    got2 = lint_scatter._scan_source(src2, "harp_tpu/ops/fake2.py")
    assert [v.method for v in got2] == ["set"]


def test_allowlisted_function_is_exempt_but_siblings_are_not():
    src = ("def densify(x, idx, v):\n"
           "    return x.at[idx].add(v)\n"
           "def other(x, idx, v):\n"
           "    return x.at[idx].add(v)\n")
    got = lint_scatter._scan_source(src, "harp_tpu/models/sgd_mf.py")
    assert [v.func for v in got] == ["other"]


def test_cli_main_is_clean_on_this_repo(capsys):
    assert lint_scatter.main([REPO]) == 0
    assert "clean" in capsys.readouterr().out
