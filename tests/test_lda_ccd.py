"""LDA-CGS and CCD++ convergence tests (ml/java lda + ccd parity).

Statistical-parity strategy (SURVEY §7): both are stochastic/coordinate methods —
assert objective improvement and structure recovery, not bitwise trajectories.
"""

import numpy as np
import pytest

from harp_tpu.io import datagen
from harp_tpu.models import ccd, lda


def test_lda_likelihood_improves_and_topics_sharpen(session):
    docs = datagen.lda_corpus(num_docs=64, vocab=48, num_topics=4, doc_len=24,
                              seed=5)
    cfg = lda.LDAConfig(num_topics=4, vocab=48, alpha=0.5, beta=0.1, epochs=15)
    doc_topic, word_topic, ll = lda.LDA(session, cfg).fit(docs, seed=1)

    assert ll.shape == (cfg.epochs,)
    assert np.all(np.isfinite(ll))
    assert ll[-1] > ll[0]          # joint likelihood term improves
    # counts stay consistent: every token is assigned exactly once
    assert np.isclose(doc_topic.sum(), docs.size, atol=1e-2)
    assert np.isclose(word_topic.sum(), docs.size, atol=1e-2)
    assert doc_topic.min() >= -1e-4 and word_topic.min() >= -1e-4
    # topics sharpen: mean per-word topic entropy drops vs uniform
    p = word_topic / np.maximum(word_topic.sum(1, keepdims=True), 1e-9)
    ent = -(p * np.log(np.maximum(p, 1e-12))).sum(1).mean()
    assert ent < 0.95 * np.log(cfg.num_topics)


def test_lda_device_ll_matches_reference_formula(session):
    """The per-epoch device likelihood IS the reference formula: recompute it
    on the host from the returned final counts and compare the last epoch."""
    docs = datagen.lda_corpus(num_docs=32, vocab=32, num_topics=3, doc_len=16,
                              seed=2)
    cfg = lda.LDAConfig(num_topics=3, vocab=32, alpha=0.5, beta=0.1, epochs=6)
    dt, word_topic, ll = lda.LDA(session, cfg).fit(docs, seed=4)
    host_ll = lda.reference_log_likelihood(word_topic, cfg.beta, cfg.vocab)
    np.testing.assert_allclose(ll[-1], host_ll, rtol=1e-4)
    # full-model LL adds a finite doc term
    full = lda.full_model_log_likelihood(dt, word_topic, cfg.alpha, cfg.beta,
                                         cfg.vocab)
    assert np.isfinite(full) and full < host_ll  # doc term is negative here


def test_lda_gemm_scatter_bitwise_matches_segment_sum(session):
    """The r5 MXU count-write path (wt_access='gemm_scatter': chunked bf16
    one-hot GEMMs, 2.5× the hop on the real chip) is BITWISE identical to
    the segment_sum path — one-hots are 0/1 and CGS deltas ±1/0, both
    bf16-exact, and integer count sums are exact in the f32 accumulator
    regardless of reduction order."""
    docs = datagen.lda_corpus(num_docs=64, vocab=96, num_topics=4,
                              doc_len=24, seed=6)
    outs = {}
    for wa in ("gather", "gemm_scatter"):
        cfg = lda.LDAConfig(num_topics=4, vocab=96, epochs=8, wt_access=wa)
        outs[wa] = lda.LDA(session, cfg).fit(docs, seed=3)
    for a, b in zip(outs["gather"], outs["gemm_scatter"]):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # cvb0 soft deltas are NOT bf16-exact: the combination must refuse
    with pytest.raises(ValueError, match="cgs"):
        lda.LDA(session, lda.LDAConfig(method="cvb0",
                                       wt_access="gemm_scatter"))


def test_lda_auto_wt_access_vpb_crossover_guard(session):
    """wt_access='auto' falls back to the gather path when the vocab block
    is wider than wt_gemm_scatter_max_vpb (ADVICE r5: the one-hot GEMM
    write costs vpb*K FLOPs per token — a vpb~1M config must not regress),
    while the sub-block layout keeps gemm_scatter at ANY width (its one-hot
    is 128 lanes regardless of vpb) and an explicit request is never
    overridden."""
    docs = datagen.lda_corpus(num_docs=32, vocab=96, num_topics=4,
                              doc_len=12, seed=9)

    def built_path(cfg):
        model = lda.LDA(session, cfg)
        model.fit(docs, seed=2)
        return model.last_layout_stats["wt_path"]

    # vocab=96 over 8 workers -> vpb=12: within any sane threshold
    assert built_path(lda.LDAConfig(num_topics=4, vocab=96,
                                    epochs=1)) == "gemm_scatter"
    # force the crossover with a tiny threshold: auto must pick gather
    assert built_path(lda.LDAConfig(
        num_topics=4, vocab=96, epochs=1,
        wt_gemm_scatter_max_vpb=8)) == "gather"
    # sub-block layout ignores the guard (scatter width is 128, not vpb)
    assert built_path(lda.LDAConfig(
        num_topics=4, vocab=96, epochs=1, vocab_sub_block=4,
        wt_gemm_scatter_max_vpb=8)) == "gemm_scatter_subblock"
    # explicit gemm_scatter is never overridden by the guard
    assert built_path(lda.LDAConfig(
        num_topics=4, vocab=96, epochs=1, wt_access="gemm_scatter",
        wt_gemm_scatter_max_vpb=8)) == "gemm_scatter"


def test_lda_convergence_parity_with_sequential_cgs(session):
    """VERDICT #6: the 8-worker blocked CGS reaches the same likelihood as a
    single-device token-sequential CGS within tolerance at equal epochs.

    At this toy scale (K=4, 64 docs) CGS chains of EITHER kind are bimodal —
    some seeds collapse a topic — so both sides use the standard multi-start
    protocol (best of 3 seeds) before comparing converged likelihoods."""
    docs = datagen.lda_corpus(num_docs=64, vocab=48, num_topics=4, doc_len=20,
                              seed=7)
    cfg = lda.LDAConfig(num_topics=4, vocab=48, alpha=0.5, beta=0.1, epochs=16)
    model = lda.LDA(session, cfg)
    best_mesh = max(float(model.fit(docs, seed=s)[2][-1]) for s in (1, 2, 3))
    best_seq = max(float(
        lda.sequential_cgs_reference(docs, cfg, seed=s)[2][-1])
        for s in (1, 2))
    # same converged likelihood within 5% (both sides use the reference's
    # formula, so this is a direct time-to-likelihood parity check)
    assert abs(best_mesh - best_seq) < 0.05 * abs(best_seq)


def test_lda_zipf_vocab_bounded_padding(session):
    """VERDICT #4: a Zipf vocabulary must not blow up token-bucket padding."""
    rng = np.random.default_rng(3)
    v, d, l = 96, 64, 64
    p = np.arange(1, v + 1, dtype=np.float64) ** -1.2
    docs = rng.choice(v, size=(d, l), p=p / p.sum()).astype(np.int32)
    cfg = lda.LDAConfig(num_topics=4, vocab=v, alpha=0.5, beta=0.1, epochs=4)
    model = lda.LDA(session, cfg)
    _, _, ll = model.fit(docs, seed=0)
    assert model.last_layout_stats["overhead"] <= 4.0
    assert np.all(np.isfinite(ll))
    # contiguous id ranges (round-1 layout) pad at least as much
    import dataclasses as _dc
    plain = lda.LDA(session, _dc.replace(cfg, balance=False))
    plain.fit(docs, seed=0)
    assert (model.last_layout_stats["overhead"]
            <= plain.last_layout_stats["overhead"] + 1e-9)


def test_ccd_converges(session):
    rows, cols, vals = datagen.sparse_ratings(80, 64, rank=4, density=0.3,
                                              seed=13, noise=0.01)
    cfg = ccd.CCDConfig(rank=8, lam=0.02, outer_iterations=8,
                        inner_iterations=2)
    u, v, rmse = ccd.CCD(session, cfg).fit(rows, cols, vals, 80, 64)
    assert rmse[-1] < 0.12
    assert rmse[-1] < 0.4 * rmse[0]
    pred = np.einsum("ij,ij->i", u[rows], v[cols])
    assert np.sqrt(np.mean((vals - pred) ** 2)) < 0.12


def test_lda_cvb0_deterministic_and_improves(session):
    docs = datagen.lda_corpus(num_docs=48, vocab=40, num_topics=3, doc_len=20,
                              seed=8)
    cfg = lda.LDAConfig(num_topics=3, vocab=40, alpha=0.5, beta=0.1, epochs=10,
                        method="cvb0")
    model = lda.LDA(session, cfg)
    dt1, wt1, ll1 = model.fit(docs, seed=2)
    dt2, wt2, ll2 = model.fit(docs, seed=2)
    np.testing.assert_allclose(ll1, ll2)        # CVB0 is deterministic
    assert ll1[-1] > ll1[0]
    assert np.isclose(dt1.sum(), docs.size, atol=1e-1)
    assert np.isclose(wt1.sum(), docs.size, atol=1e-1)


def test_pivoted_qr(session):
    from harp_tpu.models import stats
    rng = np.random.default_rng(3)
    # rank-deficient-ish: last column nearly dependent
    x = rng.standard_normal((64, 6)).astype(np.float32)
    x[:, 5] = x[:, 0] * 2.0 + 1e-3 * rng.standard_normal(64)
    q, r, piv = stats.PivotedQR(session).compute(x)
    np.testing.assert_allclose(q @ r, x[:, piv], rtol=1e-3, atol=1e-3)
    assert sorted(piv.tolist()) == list(range(6))
    # pivoting pushes the near-dependent direction last: |R| diag decreasing-ish
    d = np.abs(np.diag(r))
    assert d[0] >= d[-1]


def test_lda_fit_checkpointed_resume_equivalence(session, tmp_path):
    from harp_tpu.utils.checkpoint import Checkpointer

    docs = datagen.lda_corpus(32, 40, 3, 12, seed=0)
    cfg = lda.LDAConfig(num_topics=4, vocab=40, epochs=6)
    model = lda.LDA(session, cfg)
    state = model.prepare(docs, seed=3)

    ck_a = Checkpointer(str(tmp_path / "a"), use_orbax=False)
    dt_a, wt_a, ll_a, s0 = model.fit_checkpointed(state, ck_a, save_every=2)
    assert s0 == 0 and len(ll_a) == 6
    assert np.isfinite(ll_a).all()
    # the checkpoint holds the word-topic model (printModel parity)
    import os

    assert any(d.startswith("step_") for d in os.listdir(str(tmp_path / "a")))

    # interrupt after 4 of 6 epochs; resume is bitwise the uninterrupted run
    ck_b = Checkpointer(str(tmp_path / "b"), use_orbax=False)
    model.fit_checkpointed(state, ck_b, save_every=2, epochs=4)
    dt_b, wt_b, ll_b, s_b = model.fit_checkpointed(state, ck_b, save_every=2)
    assert s_b == 4 and len(ll_b) == 2
    np.testing.assert_array_equal(wt_a, wt_b)
    np.testing.assert_array_equal(dt_a, dt_b)
    np.testing.assert_array_equal(ll_a[4:], ll_b)


def test_lda_two_slice_pipelined_rotation(session):
    """numModelSlices=2 (LDAMPCollectiveMapper wTableMap): half-width vocab
    blocks double-buffered on pipelined_rotation. Same convergence story as
    single-slice, and the device LL must match the host reference formula
    (which proves the interleaved [a; b] shard layout un-permutes right)."""
    docs = datagen.lda_corpus(num_docs=64, vocab=48, num_topics=4, doc_len=24,
                              seed=0)
    cfg = lda.LDAConfig(num_topics=4, vocab=48, alpha=0.5, beta=0.1, epochs=15,
                        num_model_slices=2)
    model = lda.LDA(session, cfg)
    dt, wt, ll = model.fit(docs, seed=1)
    assert ll[-1] > ll[0]
    host_ll = lda.reference_log_likelihood(wt, cfg.beta, cfg.vocab)
    np.testing.assert_allclose(ll[-1], host_ll, rtol=1e-5)
    assert np.isclose(dt.sum(), docs.size, atol=1e-1)
    assert np.isclose(wt.sum(), docs.size, atol=1e-1)
    # parity with the single-slice schedule (statistical, not bitwise). A
    # single CGS chain on this tiny corpus is bimodal — any one seed can trap
    # either schedule in the stuck mode, and the mode a given seed lands in
    # shifts with the jax.random version — so give each schedule a few chains
    # and compare the best LL each found.
    import dataclasses as _dc

    cfg1 = _dc.replace(cfg, num_model_slices=1)
    # seed 1's two-slice chain already ran above — reuse its LL
    best2 = max(float(ll[-1]),
                *(float(model.fit(docs, seed=s)[2][-1]) for s in (2, 3)))
    best1 = max(float(lda.LDA(session, cfg1).fit(docs, seed=s)[2][-1])
                for s in (1, 2, 3))
    assert abs(best2 - best1) < 0.1 * abs(best1)


def test_lda_two_slice_checkpoint_resume(session, tmp_path):
    from harp_tpu.utils.checkpoint import Checkpointer

    docs = datagen.lda_corpus(32, 40, 3, 12, seed=0)
    cfg = lda.LDAConfig(num_topics=4, vocab=40, epochs=4, num_model_slices=2)
    model = lda.LDA(session, cfg)
    state = model.prepare(docs, seed=3)
    ck_a = Checkpointer(str(tmp_path / "a"), use_orbax=False)
    dt_a, wt_a, ll_a, _ = model.fit_checkpointed(state, ck_a, save_every=2)
    ck_b = Checkpointer(str(tmp_path / "b"), use_orbax=False)
    model.fit_checkpointed(state, ck_b, save_every=2, epochs=2)
    dt_b, wt_b, ll_b, s_b = model.fit_checkpointed(state, ck_b, save_every=2)
    assert s_b == 2
    np.testing.assert_array_equal(wt_a, wt_b)
    np.testing.assert_array_equal(dt_a, dt_b)


def test_lda_checkpoint_full_resume_rebuilds_doc_topic(session, tmp_path):
    """start == total: no chunk runs; doc_topic must be rebuilt from the
    restored z, not fabricated as zeros (code-review r3)."""
    from harp_tpu.utils.checkpoint import Checkpointer

    docs = datagen.lda_corpus(32, 40, 3, 12, seed=0)
    cfg = lda.LDAConfig(num_topics=4, vocab=40, epochs=4)
    model = lda.LDA(session, cfg)
    state = model.prepare(docs, seed=3)
    ck = Checkpointer(str(tmp_path / "c"), use_orbax=False)
    dt_full, wt_full, _, _ = model.fit_checkpointed(state, ck, save_every=2)
    dt_again, wt_again, ll_again, s = model.fit_checkpointed(
        state, ck, save_every=2)
    assert s == 4 and len(ll_again) == 0
    np.testing.assert_array_equal(wt_full, wt_again)
    np.testing.assert_array_equal(dt_full, dt_again)
    assert dt_again.sum() > 0
