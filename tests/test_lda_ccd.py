"""LDA-CGS and CCD++ convergence tests (ml/java lda + ccd parity).

Statistical-parity strategy (SURVEY §7): both are stochastic/coordinate methods —
assert objective improvement and structure recovery, not bitwise trajectories.
"""

import numpy as np

from harp_tpu.io import datagen
from harp_tpu.models import ccd, lda


def test_lda_likelihood_improves_and_topics_sharpen(session):
    docs = datagen.lda_corpus(num_docs=64, vocab=48, num_topics=4, doc_len=24,
                              seed=5)
    cfg = lda.LDAConfig(num_topics=4, vocab=48, alpha=0.5, beta=0.1, epochs=15)
    doc_topic, word_topic, ll = lda.LDA(session, cfg).fit(docs, seed=1)

    assert ll.shape == (cfg.epochs,)
    assert np.all(np.isfinite(ll))
    assert ll[-1] > ll[0]          # joint likelihood term improves
    # counts stay consistent: every token is assigned exactly once
    assert np.isclose(doc_topic.sum(), docs.size, atol=1e-2)
    assert np.isclose(word_topic.sum(), docs.size, atol=1e-2)
    assert doc_topic.min() >= -1e-4 and word_topic.min() >= -1e-4
    # topics sharpen: mean per-word topic entropy drops vs uniform
    p = word_topic / np.maximum(word_topic.sum(1, keepdims=True), 1e-9)
    ent = -(p * np.log(np.maximum(p, 1e-12))).sum(1).mean()
    assert ent < 0.95 * np.log(cfg.num_topics)


def test_ccd_converges(session):
    rows, cols, vals = datagen.sparse_ratings(80, 64, rank=4, density=0.3,
                                              seed=13, noise=0.01)
    cfg = ccd.CCDConfig(rank=8, lam=0.02, outer_iterations=8,
                        inner_iterations=2)
    u, v, rmse = ccd.CCD(session, cfg).fit(rows, cols, vals, 80, 64)
    assert rmse[-1] < 0.12
    assert rmse[-1] < 0.4 * rmse[0]
    pred = np.einsum("ij,ij->i", u[rows], v[cols])
    assert np.sqrt(np.mean((vals - pred) ** 2)) < 0.12


def test_lda_cvb0_deterministic_and_improves(session):
    docs = datagen.lda_corpus(num_docs=48, vocab=40, num_topics=3, doc_len=20,
                              seed=8)
    cfg = lda.LDAConfig(num_topics=3, vocab=40, alpha=0.5, beta=0.1, epochs=10,
                        method="cvb0")
    model = lda.LDA(session, cfg)
    dt1, wt1, ll1 = model.fit(docs, seed=2)
    dt2, wt2, ll2 = model.fit(docs, seed=2)
    np.testing.assert_allclose(ll1, ll2)        # CVB0 is deterministic
    assert ll1[-1] > ll1[0]
    assert np.isclose(dt1.sum(), docs.size, atol=1e-1)
    assert np.isclose(wt1.sum(), docs.size, atol=1e-1)


def test_pivoted_qr(session):
    from harp_tpu.models import stats
    rng = np.random.default_rng(3)
    # rank-deficient-ish: last column nearly dependent
    x = rng.standard_normal((64, 6)).astype(np.float32)
    x[:, 5] = x[:, 0] * 2.0 + 1e-3 * rng.standard_normal(64)
    q, r, piv = stats.PivotedQR(session).compute(x)
    np.testing.assert_allclose(q @ r, x[:, piv], rtol=1e-3, atol=1e-3)
    assert sorted(piv.tolist()) == list(range(6))
    # pivoting pushes the near-dependent direction last: |R| diag decreasing-ish
    d = np.abs(np.diag(r))
    assert d[0] >= d[-1]
