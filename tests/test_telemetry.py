"""Gang telemetry subsystem (ISSUE 7): step log, comm ledger, straggler
detection, xprof windows, metrics reservoir, and the no-drift guarantees.

The single-process legs of every gang path run here on the 8-worker virtual
mesh; the true multi-process exchange (snapshot gather over the control
plane, the events-triggered xprof window across ranks) runs in
``parallel.mp_smoke`` / tests/test_multiprocess.py."""

import json
import os
import sys
import time

import numpy as np
import pytest

from harp_tpu import telemetry
from harp_tpu.telemetry import comm_ledger, gang, step_log
from harp_tpu.utils.metrics import Metrics, TimerReservoir, log_device_mem_usage

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry_off():
    """Every test starts and ends with telemetry disabled (module state)."""
    telemetry.disable()
    yield
    telemetry.disable()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f.read().strip().splitlines()]


# --------------------------------------------------------------------------- #
# Metrics: bounded reservoir + percentiles (satellite: unbounded-growth fix)
# --------------------------------------------------------------------------- #

def test_timer_reservoir_is_bounded_with_exact_aggregates():
    r = TimerReservoir(cap=64)
    for i in range(10_000):
        r.add(float(i))
    assert len(r.samples) == 64            # bounded: RAM can't grow
    assert r.count == 10_000               # aggregates stay exact
    assert r.total == sum(range(10_000))
    assert r.last == 9999.0


def test_timer_percentiles_track_the_stream():
    m = Metrics()
    for i in range(1, 1001):
        m.observe("t", i / 1000.0)
    t = m.timing("t")
    assert set(t) == {"count", "total_s", "mean_s", "last_s",
                      "p50_s", "p90_s", "p99_s"}
    # uniform 1..1000 ms: reservoir percentiles land near the true ones
    assert abs(t["p50_s"] - 0.5) < 0.05
    assert abs(t["p90_s"] - 0.9) < 0.05
    assert t["p99_s"] <= 1.0 and t["p99_s"] > t["p50_s"]


def test_percentiles_single_sort_matches_percentile():
    r = TimerReservoir(cap=128)
    for i in range(100):
        r.add(float(i))
    assert r.percentiles([0.5, 0.9, 0.99]) == [r.percentile(0.5),
                                               r.percentile(0.9),
                                               r.percentile(0.99)]


def test_timer_context_still_works_and_snapshot_carries_percentiles():
    m = Metrics()
    with m.timer("phase"):
        pass
    snap = m.snapshot()
    assert snap["timers"]["phase"]["count"] == 1
    assert "p50_s" in snap["timers"]["phase"]


def test_log_device_mem_usage_cpu_is_quiet_and_narrow():
    # CPU devices return None from memory_stats (no broad except needed):
    # the result is empty, nothing raises
    assert log_device_mem_usage() == {}


def test_log_device_mem_usage_gauges_peak(monkeypatch):
    import jax

    class FakeDev:
        id = 0

        def memory_stats(self):
            return {"bytes_in_use": 100, "peak_bytes_in_use": 250}

        def __str__(self):
            return "FakeTPU:0"

    monkeypatch.setattr(jax, "devices", lambda: [FakeDev()])
    m = Metrics()
    out = log_device_mem_usage(m)
    assert out == {"FakeTPU:0": {"bytes_in_use": 100,
                                 "peak_bytes_in_use": 250}}
    assert m.gauges["device.0.peak_bytes_in_use"] == 250


# --------------------------------------------------------------------------- #
# Step log: bounded ring, JSONL schema, no-op fast path
# --------------------------------------------------------------------------- #

def test_record_chunk_is_noop_when_disabled(tmp_path):
    telemetry.record_chunk("kmeans", start=0, losses=[1.0], wall_s=0.1)
    assert telemetry.active() is None


def test_step_events_flush_as_jsonl_with_schema(tmp_path):
    m = Metrics()
    telemetry.configure(str(tmp_path), interval=100, metrics=m, rank=3)
    telemetry.record_chunk("kmeans", start=4, losses=[9.0, 8.0], wall_s=0.2,
                           extra={"comm": "allreduce"})
    telemetry.active().flush()
    events = _read_jsonl(tmp_path / "rank3" / "steps.jsonl")
    assert [e["step"] for e in events] == [4, 5]
    for e in events:
        assert e["v"] == step_log.EVENT_VERSION
        assert e["model"] == "kmeans" and e["rank"] == 3
        assert e["comm"] == "allreduce"
        assert e["chunk_steps"] == 2
        assert abs(e["step_s"] - 0.1) < 1e-9     # amortized chunk wall
    assert events[0]["loss"] == 9.0 and events[1]["loss"] == 8.0
    # per-step samples landed in the straggler timer
    assert m.timing("telemetry.step.kmeans")["count"] == 2


def test_record_timing_surfaces_timing_schema_in_steps_jsonl(tmp_path):
    """ISSUE 10 satellite: timing() percentile output rides steps.jsonl as
    `kind: "timing"` events — the serving bench's latency rows and the
    straggler report's per-rank rows share ONE latency format (the
    Metrics.timing() dict), instead of two drifting schemas."""
    m = Metrics()
    telemetry.configure(str(tmp_path), interval=100, rank=1)
    # no samples yet: record_timing is a no-op, never a malformed event
    telemetry.record_timing("serve.latency.mixed", metrics=m)
    for v in (0.001, 0.002, 0.003):
        m.observe("serve.latency.mixed", v)
    telemetry.record_timing("serve.latency.mixed", metrics=m,
                            extra={"mix": "mixed", "qps": 123.0})
    telemetry.active().flush()
    events = _read_jsonl(tmp_path / "rank1" / "steps.jsonl")
    assert len(events) == 1
    ev = events[0]
    assert ev["kind"] == "timing" and ev["rank"] == 1
    assert ev["name"] == "serve.latency.mixed"
    assert ev["mix"] == "mixed" and ev["qps"] == 123.0
    # the event's latency fields are EXACTLY the timing() dict — the same
    # keys gang.straggler_report reads from each rank's snapshot
    timing = m.timing("serve.latency.mixed")
    assert {k: ev[k] for k in timing} == timing
    assert set(timing) <= set(ev)


def test_record_timing_noop_when_disabled():
    m = Metrics()
    m.observe("serve.latency.mixed", 0.001)
    telemetry.record_timing("serve.latency.mixed", metrics=m)
    assert telemetry.active() is None


def test_ring_is_bounded_and_drops_are_counted(tmp_path):
    m = Metrics()
    log = step_log.StepLog(str(tmp_path), capacity=8, rank=0, metrics=m)
    for i in range(20):
        log.emit({"step": i})
    assert log.dropped == 12
    assert m.counters["telemetry.events_dropped"] == 12
    log.flush()
    events = _read_jsonl(log.path)
    assert [e["step"] for e in events] == list(range(12, 20))  # newest kept


def test_flush_cadence_follows_the_boundary_interval(tmp_path):
    m = Metrics()
    telemetry.configure(str(tmp_path), interval=3, metrics=m)
    for i in range(2):
        telemetry.record_chunk("m", start=i, losses=[0.0])
    assert not os.path.exists(telemetry.active().path)   # below cadence
    telemetry.record_chunk("m", start=2, losses=[0.0])   # 3rd boundary
    assert len(_read_jsonl(telemetry.active().path)) == 3


def test_phase_timer_records_only_when_enabled(tmp_path):
    with telemetry.phase("x.checkpoint"):
        pass                               # disabled: pure no-op
    m = Metrics()
    telemetry.configure(str(tmp_path), metrics=m)
    with telemetry.phase("x.checkpoint"):
        pass
    assert m.timing("telemetry.phase.x.checkpoint")["count"] == 1


# --------------------------------------------------------------------------- #
# Comm ledger: manifest join, gauges, quant twins
# --------------------------------------------------------------------------- #

def _manifest():
    with open(os.path.join(REPO, "tools", "collective_budget.json")) as f:
        return json.load(f)


def test_manifest_target_resolution():
    assert comm_ledger.manifest_target("kmeans", comm="allreduce") == \
        "kmeans_allreduce"
    # quantized twin pinned in the manifest wins ...
    assert comm_ledger.manifest_target("kmeans", comm="allreduce",
                                       quant="int8") == "kmeans_allreduce_int8"
    # ... and falls back to the f32 row when no twin is pinned
    assert comm_ledger.manifest_target("kmeans", comm="rotation",
                                       quant="int8") == "kmeans_rotation"
    assert comm_ledger.manifest_target("lda", sub_block=True) == \
        "lda_cgs_subblock128"
    assert comm_ledger.manifest_target("sgd_mf", quant="int8") == \
        "sgd_mf_dense_int8"
    assert comm_ledger.manifest_target("nn") == "nn_mlp"
    assert comm_ledger.manifest_target("nonsuch") is None


def test_ledger_prices_steps_from_the_manifest():
    row = _manifest()["targets"]["kmeans_allreduce"]
    m = Metrics()
    led = comm_ledger.CommLedger("kmeans_allreduce", metrics=m)
    led.on_steps(10, wall_s=2.0)
    assert led.bytes_per_step == row["bytes_per_step"]
    assert led.cumulative_bytes == row["bytes_per_step"] * 10
    g = m.gauges
    assert g["comm.kmeans_allreduce.wire_bytes_per_step"] == \
        row["bytes_per_step"]
    assert g["comm.kmeans_allreduce.cumulative_gb"] == pytest.approx(
        row["bytes_per_step"] * 10 / 1e9)
    assert g["comm.kmeans_allreduce.busbw_gbps"] == pytest.approx(
        row["bytes_per_step"] * 10 / 2.0 / 1e9)


def test_ledger_quantized_row_prices_below_f32():
    t = _manifest()["targets"]
    led_q = comm_ledger.CommLedger("kmeans_allreduce_int8")
    led_f = comm_ledger.CommLedger("kmeans_allreduce")
    assert led_q.bytes_per_step < led_f.bytes_per_step / 2
    assert t["kmeans_allreduce_int8"]["bytes_per_step"] == led_q.bytes_per_step


def test_ledger_unknown_target_is_inert():
    m = Metrics()
    led = comm_ledger.CommLedger("no_such_row", metrics=m)
    led.on_steps(5, wall_s=1.0)
    assert led.bytes_per_step is None and m.gauges == {}


def test_ledger_scale_reprices_the_row():
    row = _manifest()["targets"]["kmeans_allreduce"]
    led = comm_ledger.CommLedger("kmeans_allreduce", scale=2.5)
    assert led.bytes_per_step == pytest.approx(row["bytes_per_step"] * 2.5)


def test_ledger_pricing_exactness_is_machine_readable(tmp_path):
    """A model that computed its payload scale (kmeans) gets exact pricing;
    one that didn't (lda/sgd_mf/als/nn) gets traced-shape reference pricing,
    flagged in the gauge and in every step event — a dashboard cannot
    mistake the reference counter for a measurement."""
    m = Metrics()
    telemetry.configure(str(tmp_path), interval=100, metrics=m)
    exact = comm_ledger.ledger_for("kmeans", comm="allreduce", scale=1.0)
    ref = comm_ledger.ledger_for("sgd_mf")
    assert exact.exact is True and ref.exact is False
    telemetry.record_chunk("kmeans", start=0, losses=[0.0], wall_s=0.01,
                           ledger=exact)
    telemetry.record_chunk("sgd_mf", start=0, losses=[0.0], wall_s=0.01,
                           ledger=ref)
    assert m.gauges["comm.kmeans_allreduce.pricing_exact"] == 1.0
    assert m.gauges["comm.sgd_mf_dense.pricing_exact"] == 0.0
    telemetry.active().flush()
    events = _read_jsonl(tmp_path / "rank0" / "steps.jsonl")
    pricing = {e["model"]: e["wire_pricing"] for e in events}
    assert pricing == {"kmeans": "scaled", "sgd_mf": "traced_shape"}


def test_ledger_for_is_none_when_telemetry_off():
    assert comm_ledger.ledger_for("kmeans", comm="allreduce") is None


# --------------------------------------------------------------------------- #
# Straggler detection (pure function) + slow fault grammar
# --------------------------------------------------------------------------- #

def _snap(p50, count=10):
    return {"timers": {"telemetry.step.kmeans":
                       {"count": count, "p50_s": p50, "p99_s": p50 * 1.2}}}


def test_straggler_report_flags_exactly_the_slow_rank():
    snaps = {r: _snap(0.010) for r in range(8)}
    snaps[5] = _snap(0.055)
    rep = gang.straggler_report(snaps, k=2.0)
    assert rep["suspects"] == [5]
    assert rep["gang_median_p50_s"] == pytest.approx(0.010)
    assert rep["num_ranks"] == 8


def test_straggler_bsp_signature_flags_the_rank_not_waiting():
    # BULK-SYNCHRONOUS loop: the victims' timers absorb the straggler's
    # delay (they wait in the chunk's first collective) and the straggler is
    # the one rank far BELOW the median — the signature the 3-member gang
    # drive measured (victims ~131 ms, scripted slow rank ~15 ms)
    snaps = {0: _snap(0.131), 1: _snap(0.015), 2: _snap(0.136)}
    rep = gang.straggler_report(snaps, k=2.0)
    assert rep["bsp_suspects"] == [1]
    assert rep["suspects"] == []


def test_straggler_report_spread_below_k_is_clean():
    snaps = {r: _snap(0.010 + 0.001 * r) for r in range(8)}
    assert gang.straggler_report(snaps, k=2.0)["suspects"] == []


def test_straggler_min_gap_ignores_microsecond_jitter():
    # 2x the median but only microseconds apart: drags nothing, not flagged
    snaps = {0: _snap(1e-6), 1: _snap(1e-6), 2: _snap(3e-6)}
    assert gang.straggler_report(snaps, k=2.0)["suspects"] == []


def test_straggler_cold_ranks_are_excluded_not_suspected():
    snaps = {r: _snap(0.010) for r in range(4)}
    snaps[2] = _snap(0.500, count=1)        # 1 sample < min_samples
    rep = gang.straggler_report(snaps, k=2.0, min_samples=3)
    assert rep["suspects"] == []
    assert rep["ranks"][2]["measurable"] is False


def test_straggler_single_measurable_rank_has_no_median():
    rep = gang.straggler_report({0: _snap(0.01)})
    assert rep["gang_median_p50_s"] is None and rep["suspects"] == []


def test_gather_snapshots_single_process_returns_local(session):
    m = Metrics()
    m.observe("telemetry.step.kmeans", 0.01)
    snaps = gang.gather_snapshots(session, metrics=m)
    assert list(snaps) == [0]
    assert snaps[0]["timers"]["telemetry.step.kmeans"]["count"] == 1


def test_slow_fault_grammar_and_sustained_fire(monkeypatch):
    from harp_tpu.parallel import faults

    specs = faults.parse_faults("slow@epoch=2:rank=1:ms=7")
    assert specs[0].kind == "slow" and specs[0].ms == 7
    with pytest.raises(ValueError):
        faults.parse_faults("crash@epoch=1:ms=7")   # ms is slow-only
    with pytest.raises(ValueError):
        faults.parse_faults("slow@epoch=1:ms=abc")
    monkeypatch.setenv("HARP_FAULT", "slow@epoch=2:ms=15")
    monkeypatch.setenv("HARP_PROCESS_ID", "0")
    t0 = time.perf_counter()
    faults.fire(1)
    before = time.perf_counter() - t0
    walls = []
    for epoch in (2, 3, 4):                 # SUSTAINED: every due boundary
        t0 = time.perf_counter()
        faults.fire(epoch)
        walls.append(time.perf_counter() - t0)
    assert before < 0.010
    assert all(w >= 0.014 for w in walls), walls


def test_supervisor_journal_attaches_straggler_report(tmp_path):
    from harp_tpu.parallel import supervisor

    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    (tdir / gang.REPORT_NAME).write_text(json.dumps(
        {"v": 1, "ts": 1.0, "suspects": [3], "gang_median_p50_s": 0.1}))
    outcome = supervisor.supervise_local(
        [sys.executable, "-c", "import sys; sys.exit(1)"],
        policy=supervisor.RestartPolicy(max_restarts=1, backoff_base_s=0.0),
        telemetry_dir=str(tdir), sleep=lambda s: None)
    assert not outcome.ok
    events = {r["event"]: r for r in outcome.journal}
    assert events["restart"]["straggler"]["suspects"] == [3]
    assert events["give-up"]["straggler"]["suspects"] == [3]


# --------------------------------------------------------------------------- #
# No-drift guarantees: the pinned budget with telemetry ON
# --------------------------------------------------------------------------- #

def test_budget_manifest_zero_drift_with_telemetry_on(tmp_path):
    """The telemetry gate (satellite): tracing the instrumented models' step
    programs with telemetry ENABLED must reproduce the committed manifest
    exactly — counts, kinds, AND bytes (JL201/JL203 zero drift). The full
    14-target sweep runs in ci_checks.sh; two representative rows keep the
    gate in tier-1."""
    from tools.jaxlint import checkers_jaxpr

    telemetry.configure(str(tmp_path), interval=4)
    targets = _manifest()["targets"]
    for name in ("kmeans_regroupallgather", "sgd_mf_dense"):
        counts, dtype_bad, nbytes = checkers_jaxpr.trace_target(name)
        assert counts == targets[name]["collectives"], name
        assert nbytes == targets[name]["bytes_by_kind"], name
        assert sum(nbytes.values()) == targets[name]["bytes_per_step"], name
        assert not dtype_bad


def test_kmeans_fit_checkpointed_emits_telemetry_and_stays_bitwise(
        session, rng, tmp_path):
    """End-to-end: the kmeans loop with telemetry on (1) trains bitwise
    identically to telemetry off, (2) emits one event per iteration with the
    host-synced loss, (3) prices comm volume off the manifest row."""
    from harp_tpu.models import kmeans as km
    from harp_tpu.utils.checkpoint import Checkpointer

    cfg = km.KMeansConfig(8, 16, iterations=4)
    pts = rng.normal(size=(64, 16)).astype(np.float32)
    cen0 = pts[:8].copy()

    model = km.KMeans(session, cfg)
    p, c = model.prepare(pts, cen0)
    cen_off, costs_off, _ = model.fit_checkpointed(
        p, c, Checkpointer(str(tmp_path / "off")), save_every=2)

    m = Metrics()
    telemetry.configure(str(tmp_path / "tele"), interval=1, metrics=m)
    cen_on, costs_on, _ = model.fit_checkpointed(
        p, c, Checkpointer(str(tmp_path / "on")), save_every=2)
    telemetry.disable()

    np.testing.assert_array_equal(np.asarray(cen_off), np.asarray(cen_on))
    np.testing.assert_array_equal(costs_off, costs_on)

    events = _read_jsonl(tmp_path / "tele" / "rank0" / "steps.jsonl")
    assert [e["step"] for e in events] == [0, 1, 2, 3]
    assert [e["loss"] for e in events] == pytest.approx(costs_on.tolist())
    assert all(e["model"] == "kmeans" and e["comm"] == cfg.comm
               for e in events)
    assert m.timing("telemetry.step.kmeans")["count"] == 4
    assert m.timing("telemetry.phase.kmeans.checkpoint")["count"] == 2
    # this config IS the manifest trace shape: scale 1.0, gauge == the row
    row = _manifest()["targets"]["kmeans_regroupallgather"]
    assert model.comm_scale() == pytest.approx(1.0)
    assert m.gauges["comm.kmeans_regroupallgather.wire_bytes_per_step"] == \
        pytest.approx(row["bytes_per_step"])
    assert m.gauges["comm.kmeans_regroupallgather.cumulative_gb"] == \
        pytest.approx(row["bytes_per_step"] * 4 / 1e9)


def test_lda_and_nn_fits_emit_per_epoch_events(session, rng, tmp_path):
    from harp_tpu.models import lda as plda
    from harp_tpu.models import nn as pnn

    m = Metrics()
    telemetry.configure(str(tmp_path), interval=1, metrics=m)
    docs = rng.integers(0, 48, size=(16, 8))
    model = plda.LDA(session, plda.LDAConfig(num_topics=4, vocab=48,
                                             epochs=3))
    _, _, ll = model.fit(docs, seed=0)
    x = rng.normal(size=(64, 10)).astype(np.float32)
    y = rng.integers(0, 3, size=64).astype(np.int32)
    clf = pnn.MLPClassifier(session, pnn.NNConfig(layers=(8,), num_classes=3,
                                                  epochs=2))
    losses = clf.fit(x, y, seed=0)
    telemetry.disable()
    events = _read_jsonl(tmp_path / "rank0" / "steps.jsonl")
    by_model = {}
    for e in events:
        by_model.setdefault(e["model"], []).append(e)
    assert [e["loss"] for e in by_model["lda"]] == pytest.approx(
        np.asarray(ll).tolist())
    assert [e["loss"] for e in by_model["nn"]] == pytest.approx(
        losses.tolist())
    assert m.gauges["comm.lda_cgs.wire_bytes_per_step"] > 0
    assert m.gauges["comm.nn_mlp.wire_bytes_per_step"] > 0


def test_xprof_window_single_process(session, tmp_path):
    from harp_tpu.telemetry.xprof import XprofController, request_xprof

    ctrl = XprofController(session, rank=0)
    try:
        request_xprof(session, steps=2, directory=str(tmp_path / "xprof"))
        ctrl(1)
        assert ctrl.tracing
        import jax.numpy as jnp

        jnp.square(jnp.arange(64.0)).block_until_ready()  # something to trace
        ctrl(2)
        ctrl(3)
        assert not ctrl.tracing
        found = [os.path.join(r, f) for r, _, fs in os.walk(ctrl.trace_dir)
                 for f in fs]
        assert found, f"no trace artifacts under {ctrl.trace_dir}"
    finally:
        ctrl.close()
        session.close_events()


def test_xprof_file_trigger_operator_path(session, tmp_path):
    """The run.py CLI path: an operator drops DIR/xprof_request.json while
    the job runs; the controller opens a window at the next boundary. A file
    left over from a previous run must NOT arm at startup, and a malformed
    file must not kill training."""
    from harp_tpu.telemetry.xprof import XprofController

    trig = tmp_path / "xprof_request.json"
    trig.write_text(json.dumps({"steps": 1}))     # pre-existing: stale
    ctrl = XprofController(session, rank=0, trigger_path=str(trig),
                           default_dir=str(tmp_path / "xprof"))
    try:
        ctrl(1)
        assert not ctrl.tracing                    # stale file ignored
        trig.write_text("{not json")
        ctrl(2)
        assert not ctrl.tracing                    # malformed: noted, not fatal
        trig.write_text(json.dumps({"steps": 2}))  # rewritten: re-armed
        ctrl(3)
        assert ctrl.tracing
        ctrl(4)
        ctrl(5)
        assert not ctrl.tracing
        found = [os.path.join(r, f) for r, _, fs in os.walk(ctrl.trace_dir)
                 for f in fs]
        assert found
        ctrl(6)
        assert not ctrl.tracing                    # same content: consumed
    finally:
        ctrl.close()
        session.close_events()


def test_xprof_window_open_at_exit_is_closed_by_steplog(session, tmp_path):
    """A window still open when the run ends (request arrived with fewer
    boundaries left than requested) must stop its trace at StepLog.close()
    — the atexit path — or the profile is never written."""
    from harp_tpu.telemetry.xprof import XprofController, request_xprof

    log = telemetry.configure(str(tmp_path), interval=100, metrics=Metrics())
    ctrl = XprofController(session, rank=0)
    log.add_boundary_hook(ctrl)
    try:
        request_xprof(session, steps=50, directory=str(tmp_path / "xprof"))
        telemetry.record_chunk("m", start=0, losses=[0.0])   # boundary 1
        assert ctrl.tracing                                  # 49 left, run ends
        telemetry.disable()                                  # = atexit close
        assert not ctrl.tracing
        found = [os.path.join(r, f) for r, _, fs in os.walk(ctrl.trace_dir)
                 for f in fs]
        assert found, "open window lost its trace at exit"
    finally:
        ctrl.close()
        session.close_events()


def test_kmeans_pricing_inexact_off_the_traced_worker_count(rng, tmp_path):
    """comm_scale rescales table elements, but the sharded variants' traced
    operands also depend on the worker count — a mesh narrower than the
    manifest's w=8 must be flagged as reference pricing, not exact."""
    from harp_tpu.models import kmeans as km
    from harp_tpu.session import HarpSession
    from harp_tpu.utils.checkpoint import Checkpointer

    sess4 = HarpSession(num_workers=4)
    m = Metrics()
    telemetry.configure(str(tmp_path), interval=1, metrics=m)
    model = km.KMeans(sess4, km.KMeansConfig(8, 16, iterations=2))
    pts = rng.normal(size=(64, 16)).astype(np.float32)
    p, c = model.prepare(pts, pts[:8].copy())
    model.fit_checkpointed(p, c, Checkpointer(str(tmp_path / "ck")),
                           save_every=2)
    telemetry.disable()
    assert m.gauges["comm.kmeans_regroupallgather.pricing_exact"] == 0.0


def test_supervisor_command_flag_parse():
    from harp_tpu.parallel.supervisor import _command_flag

    cmd = ["python", "-m", "harp_tpu.run", "kmeans",
           "--telemetry-dir", "/a", "--telemetry-dir=/b"]
    assert _command_flag(cmd, "--telemetry-dir") == "/b"
    assert _command_flag(["python"], "--telemetry-dir") is None


def test_xprof_nonrequest_events_are_requeued(session):
    from harp_tpu.telemetry.xprof import XprofController

    try:
        session.send_event({"note": "operator-ping"})
        ctrl = XprofController(session, rank=0)
        ctrl(1)                       # no request: the ping must survive
        assert not ctrl.tracing
        ev = session.get_event()
        assert ev is not None and ev.payload == {"note": "operator-ping"}
    finally:
        session.close_events()


@pytest.mark.large
def test_telemetry_overhead_cpu_smoke(session, rng, tmp_path):
    """The <2% overhead contract, CPU flavor (the on-chip assert lives in the
    bench row): the telemetry layer's measured per-step cost must be < 2% of
    a real measured kmeans step on this mesh. The layer's cost is host-side
    and shape-independent, so this bounds the on-chip overhead too (on-chip
    steps at bench shapes are far longer than these)."""
    from harp_tpu.models import kmeans as km

    cfg = km.KMeansConfig(32, 64, iterations=6)
    pts = rng.normal(size=(16384, 64)).astype(np.float32)
    model = km.KMeans(session, cfg)
    p, c = model.prepare(pts, pts[:32].copy())
    model.fit_prepared(p, c)                      # compile + warm
    t0 = time.perf_counter()
    _, costs = model.fit_prepared(p, c)
    np.asarray(costs)
    step_s = (time.perf_counter() - t0) / cfg.iterations

    m = Metrics()
    telemetry.configure(str(tmp_path), interval=10**6, capacity=4096,
                        metrics=m)
    led = telemetry.ledger_for("kmeans", comm=cfg.comm,
                               scale=model.comm_scale())
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        telemetry.record_chunk("kmeans", start=i, losses=[0.0],
                               wall_s=step_s, ledger=led,
                               extra={"comm": cfg.comm})
    per_event = (time.perf_counter() - t0) / n
    telemetry.disable()
    overhead_pct = 100.0 * per_event / step_s
    assert overhead_pct < 2.0, (
        f"telemetry per-step cost {per_event * 1e6:.1f}us is "
        f"{overhead_pct:.2f}% of the {step_s * 1e3:.2f}ms kmeans step")


# --------------------------------------------------------------------------- #
# Metrics thread safety (ISSUE 13 satellite: one lock over the registry,
# reservoir adds lock-guarded — the load generator's per-thread-reservoir
# workaround is now isolation, not a correctness requirement)
# --------------------------------------------------------------------------- #

def test_metrics_registry_loses_no_updates_under_contention():
    import threading as th

    m = Metrics()
    n_threads, per = 8, 400
    barrier = th.Barrier(n_threads)

    def worker(i):
        barrier.wait()
        for j in range(per):
            m.count("requests")
            m.count("bytes", 3.0)
            m.observe("latency", 0.001)
            m.gauge(f"g{i}", float(j))

    threads = [th.Thread(target=worker, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # counters: every increment survives (the JL302 lost-update class)
    assert m.counters["requests"] == n_threads * per
    assert m.counters["bytes"] == 3.0 * n_threads * per
    # timers: exact count/total even though all threads shared ONE
    # reservoir (pre-v3 this undercounted, hence the per-thread pattern)
    assert m.timers["latency"].count == n_threads * per
    assert abs(m.timers["latency"].total - 0.001 * n_threads * per) < 1e-6
    snap = m.snapshot()
    assert snap["counters"]["requests"] == n_threads * per
    assert snap["timers"]["latency"]["count"] == n_threads * per


def test_timer_reservoir_concurrent_adds_stay_exact_and_bounded():
    import threading as th

    r = TimerReservoir(cap=64)
    n_threads, per = 8, 500
    barrier = th.Barrier(n_threads)

    def adder(i):
        barrier.wait()
        for j in range(per):
            r.add(float(i * per + j))

    threads = [th.Thread(target=adder, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert r.count == n_threads * per
    assert r.total == sum(range(n_threads * per))
    assert len(r.samples) == 64


def test_metrics_snapshot_is_consistent_while_writers_insert():
    # pre-v3 this raised "dictionary changed size during iteration" (the
    # exporter mid-scrape race); now a snapshot is lock-consistent
    import threading as th

    m = Metrics()
    stop = th.Event()

    def writer():
        i = 0
        while not stop.is_set():
            m.observe(f"timer.{i % 97}", 0.001)
            m.count(f"counter.{i % 89}")
            i += 1

    t = th.Thread(target=writer, daemon=True)
    t.start()
    try:
        for _ in range(60):
            snap = m.snapshot()           # must never raise
            assert isinstance(snap["timers"], dict)
    finally:
        stop.set()
        t.join(5.0)


def test_gang_collector_publish_is_scrape_consistent(session, tmp_path):
    # the PR 12 hand-review race, now fixed + linted (JL301): the
    # collector publishes (snapshots, report) atomically under its lock,
    # and the exporter's gang= source reads through the same lock
    from harp_tpu.telemetry.gang import GangCollector

    m = Metrics()
    for _ in range(4):
        m.observe("telemetry.step.fake", 0.01)
    log = step_log.StepLog(str(tmp_path), interval=1, rank=0, metrics=m)
    collector = GangCollector(session, str(tmp_path), every=1)
    assert collector.snapshots() is None and collector.last_report is None
    collector(1 * log.interval, log)      # one boundary publish
    # the pair-consistent accessor: (snapshots, report) from ONE publish
    snaps, report = collector.last_exchange()
    assert snaps is not None and 0 in snaps
    assert snaps[0]["timers"]["telemetry.step.fake"]["count"] == 4
    assert report is not None and report["num_ranks"] == 1
    # the property surface and the exporter source return the same object
    assert collector.snapshots() is snaps or collector.snapshots() == snaps
    assert collector.last_snapshots is snaps or \
        collector.last_snapshots == snaps
