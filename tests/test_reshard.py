"""On-device live resharding (collectives.reshard) — ISSUE 11.

The device engine must be BITWISE the numpy oracle
(collectives.repartition) on the same maps, its traced program must never
carry more than ``chunk_bytes`` of row payload per collective (the
arXiv:2112.01075 memory-efficient bound, pinned by the jaxlint
``reshard_factor_*`` manifest rows), and the resume paths that ride it
(SGD-MF W/H incl. the previously-rejected 2-slice resize, the LDA chain,
serving KV shard restore/rebalance) must complete with NO host gather of a
sharded leaf.
"""

import json
import os

import jax
import numpy as np
import pytest

from harp_tpu.collectives import repartition as rep
from harp_tpu.collectives import reshard as rs
from harp_tpu.io import datagen
from harp_tpu.models.sgd_mf import identity_assign, serpentine_assign
from harp_tpu.session import HarpSession
from harp_tpu.utils.checkpoint import Checkpointer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEDULES = ("alltoall", "ring")


@pytest.fixture(scope="module")
def sess8():
    return HarpSession(num_workers=8)


@pytest.fixture(scope="module")
def sess4():
    return HarpSession(num_workers=4)


def _collectives(fn, args):
    """(name, operand bytes) of every cross-worker collective in the traced
    program (the walker mirrors tools/jaxlint/checkers_jaxpr)."""
    out = []

    def walk(j):
        for eqn in j.eqns:
            if eqn.primitive.name in ("all_to_all", "ppermute", "psum",
                                      "all_gather", "psum_scatter",
                                      "reduce_scatter"):
                out.append((eqn.primitive.name, sum(
                    int(np.prod(v.aval.shape, initial=1))
                    * v.aval.dtype.itemsize for v in eqn.invars)))
            for v in eqn.params.values():
                items = v if isinstance(v, (list, tuple)) else [v]
                for it in items:
                    if hasattr(it, "eqns"):
                        walk(it)
                    elif hasattr(it, "jaxpr") and hasattr(it.jaxpr, "eqns"):
                        walk(it.jaxpr)

    walk(jax.make_jaxpr(fn)(*args).jaxpr)
    return out


# --------------------------------------------------------------------------- #
# engine: bitwise vs the numpy oracle, bounded rounds
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("schedule", SCHEDULES)
@pytest.mark.parametrize("old_world,new_world,n", [
    (4, 8, 97),      # grow, prime valid rows
    (8, 8, 64),      # same world, different maps
    (2, 8, 61),      # steep grow
])
def test_engine_bitwise_vs_oracle(sess8, rng, schedule, old_world,
                                  new_world, n):
    assert new_world == 8    # the module mesh
    old_rpb = -(-n // old_world) + 3      # padded slots on the old side too
    new_rpb = -(-n // new_world) + 2
    old_assign = serpentine_assign(rng.integers(1, 9, n), old_world)
    new_assign = identity_assign(n, new_world)
    saved = rng.standard_normal((old_world * old_rpb, 5)).astype(np.float32)
    fill_host = rng.standard_normal(
        (new_world * new_rpb, 5)).astype(np.float32)
    oracle = rep.repartition_factor(saved, old_assign, old_rpb, new_assign,
                                    new_rpb, n, fill_host.copy())
    old = rs.block_layout(old_assign, old_rpb, old_world)
    new = rs.block_layout(new_assign, new_rpb, new_world)
    out = rs.reshard_factor(sess8, saved, old, old_world, new, n,
                            sess8.scatter(fill_host), chunk_bytes=256,
                            schedule=schedule)
    np.testing.assert_array_equal(np.asarray(out), oracle)


@pytest.mark.parametrize("schedule", SCHEDULES)
def test_engine_shrink_on_4worker_mesh(sess4, rng, schedule):
    # W8 -> W4: the supervisor's shrink-relaunch direction
    n = 53
    old_assign = serpentine_assign(rng.integers(1, 9, n), 8)
    new_assign = serpentine_assign(rng.integers(1, 9, n), 4)
    old_rpb, new_rpb = 7, 14
    saved = rep.permute_rows(
        rng.standard_normal((n, 3)).astype(np.float32), old_assign[0],
        old_assign[1], old_rpb, np.zeros((8 * old_rpb, 3), np.float32))
    fill = rng.standard_normal((4 * new_rpb, 3)).astype(np.float32)
    oracle = rep.repartition_factor(saved, old_assign, old_rpb, new_assign,
                                    new_rpb, n, fill.copy())
    out = rs.reshard_factor(
        sess4, saved, rs.block_layout(old_assign, old_rpb, 8), 8,
        rs.block_layout(new_assign, new_rpb, 4), n, sess4.scatter(fill),
        chunk_bytes=128, schedule=schedule)
    np.testing.assert_array_equal(np.asarray(out), oracle)


def test_padded_slots_keep_fill_bitwise(sess8, rng):
    # rows no id maps to are the FILL's (fresh-init semantics)
    n = 10
    old_assign = identity_assign(n, 4)
    new_assign = identity_assign(n, 8)
    fill = rng.standard_normal((8 * 4, 2)).astype(np.float32)
    saved = rng.standard_normal((4 * 3, 2)).astype(np.float32)
    out = np.asarray(rs.reshard_factor(
        sess8, saved, rs.block_layout(old_assign, 3, 4), 4,
        rs.block_layout(new_assign, 4, 8), n, sess8.scatter(fill)))
    new_pos = rs.block_layout(new_assign, 4, 8).device_positions(n)
    untouched = np.setdiff1d(np.arange(32), new_pos)
    np.testing.assert_array_equal(out[untouched], fill[untouched])


@pytest.mark.parametrize("schedule", SCHEDULES)
def test_traced_rounds_respect_chunk_bytes(sess8, rng, schedule):
    # the acceptance bound: per-collective payload <= chunk_bytes in the
    # TRACED program (what jaxlint pins via the reshard_factor_* rows)
    n, r, chunk = 97, 8, 512
    old = rs.block_layout(serpentine_assign(rng.integers(1, 9, n), 4),
                          28, 4)
    new = rs.block_layout(identity_assign(n, 8), 16, 8)
    saved = rng.standard_normal((4 * 28, r)).astype(np.float32)
    plan = rs.plan_factor_reshard(old, 4, new, 8, n, r * 4,
                                  chunk_bytes=chunk, schedule=schedule)
    assert plan.rounds > 1, "shape must force multiple rounds"
    fn, args = rs.prepare_reshard(
        sess8, saved, plan, sess8.scatter(np.zeros((8 * 16, r),
                                                   np.float32)))
    colls = _collectives(fn, args)
    assert colls, "program must move rows through collectives"
    assert all(b <= chunk for _, b in colls), colls
    # and the manifest pins exactly these per-round bytes
    with open(os.path.join(REPO, "tools", "collective_budget.json")) as f:
        budget = json.load(f)["targets"]
    key = ("reshard_factor_a2a" if schedule == "alltoall"
           else "reshard_factor_ring")
    assert key in budget, "reshard step program must be jaxlint-pinned"
    assert budget[key]["bytes_per_step"] <= 512 * (
        1 if schedule == "alltoall" else 7)


def test_plan_validation_is_loud(rng):
    with pytest.raises(ValueError, match="alltoall|ring"):
        rs.plan_moves(np.arange(4), np.arange(4), 8, 8, 4, 4,
                      schedule="gather")
    with pytest.raises(ValueError, match="collide"):
        rs.plan_moves(np.arange(4), np.zeros(4, np.int64), 8, 8, 4, 4)
    with pytest.raises(ValueError, match="outside the new layout"):
        rs.plan_moves(np.arange(4), np.array([0, 1, 2, 99]), 8, 8, 4, 4)
    with pytest.raises(ValueError, match="outside the flat leaf"):
        rs.plan_moves(np.array([99]), np.array([0]), 8, 8, 4, 4)


def test_bytes_moved_accounting(rng):
    # moved_rows counts only cross-worker rows; the host path's cost is the
    # full table on every worker — the asymmetry the bench rows report
    n = 32
    old = rs.block_layout(identity_assign(n, 4), 8, 4)
    new = rs.block_layout(identity_assign(n, 8), 4, 8)
    plan = rs.plan_factor_reshard(old, 4, new, 8, n, 16)
    assert plan.moved_rows + plan.local_rows_moved == n
    assert plan.bytes_moved == plan.moved_rows * 16


# --------------------------------------------------------------------------- #
# sgd_mf: device resume bitwise, incl. the 2-slice resize, NO host gather
# --------------------------------------------------------------------------- #

def _ratings():
    return datagen.sparse_ratings(64, 64, rank=4, density=0.25, seed=3)


def _mf_cfg(**kw):
    from harp_tpu.models import sgd_mf

    base = dict(rank=4, epochs=2, layout="sparse", minibatches_per_hop=2)
    base.update(kw)
    return sgd_mf.SGDMFConfig(**base)


@pytest.mark.parametrize("direction", ["shrink", "grow"])
def test_sgd_mf_device_resume_bitwise(tmp_path, sess8, sess4, direction):
    from harp_tpu.models import sgd_mf

    rows, cols, vals = _ratings()
    a, b = (sess8, sess4) if direction == "shrink" else (sess4, sess8)
    m_a = sgd_mf.SGDMF(a, _mf_cfg())
    ck = Checkpointer(str(tmp_path / "ck"))
    w_a, h_a, _, _ = m_a.fit_checkpointed(
        m_a.prepare(rows, cols, vals, 64, 64, seed=0), ck, save_every=1)

    m_dev = sgd_mf.SGDMF(b, _mf_cfg(reshard="device"))
    w_b, h_b, rmse_b, start = m_dev.fit_checkpointed(
        m_dev.prepare(rows, cols, vals, 64, 64, seed=0),
        Checkpointer(str(tmp_path / "ck")), save_every=1)
    assert start == 2 and len(rmse_b) == 0
    np.testing.assert_array_equal(w_b, w_a)
    np.testing.assert_array_equal(h_b, h_a)

    # device path leaf-for-leaf vs the host oracle path
    m_host = sgd_mf.SGDMF(b, _mf_cfg(reshard="host"))
    w_c, h_c, _, _ = m_host.fit_checkpointed(
        m_host.prepare(rows, cols, vals, 64, 64, seed=0),
        Checkpointer(str(tmp_path / "ck")), save_every=1)
    np.testing.assert_array_equal(w_c, w_b)
    np.testing.assert_array_equal(h_c, h_b)


def test_sgd_mf_2slice_resize_now_supported(tmp_path, sess8, sess4):
    # the PR 8 loud rejection, turned into a tested supported case: a
    # 2-slice W8 checkpoint resumes into a 2-slice W4 gang (and the
    # finalized factors are bitwise), through the worker-major half-slice
    # layout on BOTH sides
    from harp_tpu.models import sgd_mf

    rows, cols, vals = _ratings()
    m8 = sgd_mf.SGDMF(sess8, _mf_cfg(num_slices=2))
    ck = Checkpointer(str(tmp_path / "ck"))
    w_a, h_a, _, _ = m8.fit_checkpointed(
        m8.prepare(rows, cols, vals, 64, 64, seed=0), ck, save_every=1)

    m4 = sgd_mf.SGDMF(sess4, _mf_cfg(num_slices=2, reshard="device"))
    w_b, h_b, _, start = m4.fit_checkpointed(
        m4.prepare(rows, cols, vals, 64, 64, seed=0),
        Checkpointer(str(tmp_path / "ck")), save_every=1)
    assert start == 2
    np.testing.assert_array_equal(w_b, w_a)
    np.testing.assert_array_equal(h_b, h_a)


def test_sgd_mf_slice_count_change_resume(tmp_path, sess8, sess4):
    # 2-slice checkpoint into a 1-slice config across a resize: the layouts
    # differ in bin placement AND bin count — the maps route it exactly
    from harp_tpu.models import sgd_mf

    rows, cols, vals = _ratings()
    m8 = sgd_mf.SGDMF(sess8, _mf_cfg(num_slices=2))
    ck = Checkpointer(str(tmp_path / "ck"))
    w_a, h_a, _, _ = m8.fit_checkpointed(
        m8.prepare(rows, cols, vals, 64, 64, seed=0), ck, save_every=1)
    m4 = sgd_mf.SGDMF(sess4, _mf_cfg(num_slices=1))
    w_b, h_b, _, start = m4.fit_checkpointed(
        m4.prepare(rows, cols, vals, 64, 64, seed=0),
        Checkpointer(str(tmp_path / "ck")), save_every=1)
    assert start == 2
    np.testing.assert_array_equal(w_b, w_a)
    np.testing.assert_array_equal(h_b, h_a)


def test_sgd_mf_device_resume_never_gathers_factors(tmp_path, sess8, sess4,
                                                    monkeypatch):
    # the acceptance assert: the device reshard path never fetches a
    # factor-table device array to host — mesh.fetch (the only
    # sharded-leaf gather seam) is poisoned during the resume restore
    from harp_tpu.models import sgd_mf

    rows, cols, vals = _ratings()
    m8 = sgd_mf.SGDMF(sess8, _mf_cfg())
    ck = Checkpointer(str(tmp_path / "ck"))
    m8.fit_checkpointed(m8.prepare(rows, cols, vals, 64, 64, seed=0), ck,
                        save_every=1)

    from harp_tpu.utils import checkpoint as ckpt_lib

    m4 = sgd_mf.SGDMF(sess4, _mf_cfg(reshard="device"))
    st4 = m4.prepare(rows, cols, vals, 64, 64, seed=0)

    def poisoned_fetch(x):
        raise AssertionError(
            "device reshard path gathered a sharded leaf to host")

    _, saved, meta = Checkpointer(str(tmp_path / "ck")).restore_latest_valid(
        like_from_meta=lambda m: ckpt_lib.meta_like(m), return_meta=True)
    monkeypatch.setattr(sgd_mf, "fetch", poisoned_fetch)
    out = m4._repartition_saved(saved, meta, st4)
    assert isinstance(out["w"], jax.Array)
    assert isinstance(out["h"], jax.Array)
    # while the host oracle path DOES fetch (the behavior being replaced)
    m4h = sgd_mf.SGDMF(sess4, _mf_cfg(reshard="host"))
    st4h = m4h.prepare(rows, cols, vals, 64, 64, seed=0)
    with pytest.raises(AssertionError, match="gathered a sharded leaf"):
        m4h._repartition_saved(saved, meta, st4h)


def test_reshard_mode_validation(sess8):
    from harp_tpu.models import sgd_mf

    m = sgd_mf.SGDMF(sess8, _mf_cfg(reshard="teleport"))
    with pytest.raises(ValueError, match="auto\\|device\\|ring\\|host"):
        m._reshard_mode()


# --------------------------------------------------------------------------- #
# lda + kmeans parity
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("direction", ["shrink", "grow"])
def test_lda_device_resume_exact(tmp_path, sess8, sess4, direction):
    from harp_tpu.models import lda

    docs = datagen.lda_corpus(16, 32, 4, 12, seed=5)
    a, b = (sess8, sess4) if direction == "shrink" else (sess4, sess8)
    cfg = lda.LDAConfig(num_topics=4, vocab=32, epochs=2)
    m_a = lda.LDA(a, cfg)
    ck = Checkpointer(str(tmp_path / "ck"))
    dt_a, wt_a, _, _ = m_a.fit_checkpointed(m_a.prepare(docs, seed=0), ck,
                                            save_every=1)
    m_b = lda.LDA(b, lda.LDAConfig(num_topics=4, vocab=32, epochs=2,
                                   reshard="device"))
    dt_b, wt_b, ll_b, start = m_b.fit_checkpointed(
        m_b.prepare(docs, seed=0), Checkpointer(str(tmp_path / "ck")),
        save_every=1)
    assert start == 2 and len(ll_b) == 0
    np.testing.assert_array_equal(np.asarray(dt_b), np.asarray(dt_a))
    np.testing.assert_array_equal(np.asarray(wt_b), np.asarray(wt_a))
    # and leaf-for-leaf vs the host rematch/rebuild oracle
    m_c = lda.LDA(b, lda.LDAConfig(num_topics=4, vocab=32, epochs=2,
                                   reshard="host"))
    dt_c, wt_c, _, _ = m_c.fit_checkpointed(
        m_c.prepare(docs, seed=0), Checkpointer(str(tmp_path / "ck")),
        save_every=1)
    np.testing.assert_array_equal(np.asarray(dt_c), np.asarray(dt_b))
    np.testing.assert_array_equal(np.asarray(wt_c), np.asarray(wt_b))


def test_lda_2slice_resize_now_supported(tmp_path, sess8, sess4):
    from harp_tpu.models import lda

    docs = datagen.lda_corpus(16, 32, 4, 12, seed=5)
    cfg = lda.LDAConfig(num_topics=4, vocab=32, epochs=2,
                        num_model_slices=2)
    m8 = lda.LDA(sess8, cfg)
    ck = Checkpointer(str(tmp_path / "ck"))
    dt_a, wt_a, _, _ = m8.fit_checkpointed(m8.prepare(docs, seed=0), ck,
                                           save_every=1)
    m4 = lda.LDA(sess4, lda.LDAConfig(num_topics=4, vocab=32, epochs=2,
                                      num_model_slices=2))
    dt_b, wt_b, _, start = m4.fit_checkpointed(
        m4.prepare(docs, seed=0), Checkpointer(str(tmp_path / "ck")),
        save_every=1)
    assert start == 2
    np.testing.assert_array_equal(np.asarray(dt_b), np.asarray(dt_a))
    np.testing.assert_array_equal(np.asarray(wt_b), np.asarray(wt_a))


def test_kmeans_resize_is_replicated_identity(tmp_path, sess8, sess4):
    # the kmeans leg of the parity matrix: replicated leaves re-shard as
    # the identity — a W8 checkpoint's centroids land bitwise in a W4 gang
    from harp_tpu.models import kmeans as km

    pts = datagen.dense_points(256, 8, seed=0, num_clusters=4)
    cen0 = datagen.initial_centroids(pts, 4, seed=1)
    cfg = km.KMeansConfig(4, 8, iterations=2)
    m8 = km.KMeans(sess8, cfg)
    ck = Checkpointer(str(tmp_path / "ck"))
    cen_a, _, _ = m8.fit_checkpointed(*m8.prepare(pts, cen0), ck,
                                      save_every=1)
    _, saved = Checkpointer(str(tmp_path / "ck")).restore_latest_valid(
        like={"centroids": np.zeros_like(np.asarray(cen_a))})
    m4 = km.KMeans(sess4, cfg)
    cen_b, costs_b, start = m4.fit_checkpointed(*m4.prepare(pts, cen0),
                                                Checkpointer(
                                                    str(tmp_path / "ck")),
                                                save_every=1)
    assert start == 2 and len(costs_b) == 0
    np.testing.assert_array_equal(np.asarray(cen_b),
                                  np.asarray(saved["centroids"]))


# --------------------------------------------------------------------------- #
# serving: shard restore + rebalance
# --------------------------------------------------------------------------- #

def _endpoint(sess, rng, name="mf"):
    from harp_tpu.serve import endpoints as serve_ep

    uf = rng.normal(size=(64, 8)).astype(np.float32)
    items = rng.normal(size=(32, 8)).astype(np.float32)
    return serve_ep.TopKEndpoint(sess, name, uf, items, k=4), uf


def test_topk_restore_shard_only_touches_lost_rank(sess8, rng):
    ep, uf = _endpoint(sess8, rng)
    ids = np.arange(0, 64, 3)
    baseline = ep.dispatch(ids[:8])
    keys_d, vals_d, counts_d, items_d = ep._state[:4]
    vals_h = np.asarray(vals_d)
    wiped = vals_h.copy()
    wiped[2] = 0.0                       # rank 2's shard is lost
    ep._state = (keys_d, ep.session.scatter(wiped), counts_d, items_d)
    assert ep.dispatch(ids[:8]) != baseline
    n = ep.restore_shard(2, uf)
    assert n == int(np.sum(np.arange(64) % 8 == 2))
    assert ep.dispatch(ids[:8]) == baseline
    after = np.asarray(ep._state[1])
    others = [r for r in range(8) if r != 2]
    np.testing.assert_array_equal(after[others], vals_h[others])


def test_topk_rebalance_moves_shards_and_keeps_answers(sess8, rng):
    ep, _ = _endpoint(sess8, rng)
    ids = np.arange(0, 64, 3)
    baseline = ep.dispatch(ids[:8])
    info = ep.rebalance(1)
    assert info["owners"][1] == 0, "straggler must own nothing after"
    assert info["moved"] >= int(np.sum(np.arange(64) % 8 == 1))
    assert ep.dispatch(ids[:8]) == baseline
    unk = ep.dispatch(np.array([999]))
    assert unk[0]["found"] is False
    # the owner-routed dispatch keeps the pinned collective shape: exactly
    # the 3 all_to_alls (+ 4 B overflow psum) of serve_topk_mf
    fn, args, _, _ = ep.prepared(np.arange(8))
    kinds = {}
    for name, b in _collectives(fn, args):
        kinds[name] = kinds.get(name, 0) + 1
    assert kinds == {"all_to_all": 3, "psum": 1}, kinds


def test_topk_rebalance_validation(sess8, rng):
    ep, _ = _endpoint(sess8, rng)
    with pytest.raises(ValueError, match="at least one rank"):
        ep.rebalance(list(range(8)))
    with pytest.raises(ValueError, match="outside the"):
        ep.rebalance(9)
    with pytest.raises(ValueError, match="outside the"):
        ep.restore_shard(8, np.zeros((64, 8), np.float32))
    with pytest.raises(ValueError, match="canonical factors"):
        ep.restore_shard(0, np.zeros((3, 8), np.float32))


def test_rebalance_from_report(sess8, rng, tmp_path):
    import time

    from harp_tpu.serve import endpoints as serve_ep

    ep, _ = _endpoint(sess8, rng, name="mf-report")
    ids = np.arange(0, 64, 3)
    baseline = ep.dispatch(ids[:8])
    # no report -> no-op
    assert serve_ep.rebalance_from_report(ep, str(tmp_path)) == []
    report_path = os.path.join(str(tmp_path), "straggler_report.json")
    # a STALE report (dead gang's leftover) earns no shard migration
    with open(report_path, "w") as f:
        json.dump({"suspects": [3], "bsp_suspects": [5], "num_ranks": 8,
                   "ts": 1}, f)
    assert serve_ep.rebalance_from_report(ep, str(tmp_path)) == []
    assert not ep._owner_routed
    # a fresh report drives the move
    with open(report_path, "w") as f:
        json.dump({"suspects": [3], "bsp_suspects": [5], "num_ranks": 8,
                   "ts": time.time()}, f)
    moved = serve_ep.rebalance_from_report(ep, str(tmp_path))
    assert moved == [3, 5]
    assert ep.dispatch(ids[:8]) == baseline
    assert ep._counts[3] == 0 and ep._counts[5] == 0


def test_rebalance_is_safe_under_live_dispatch(sess8, rng):
    # the "nothing restarts" contract under traffic: dispatch threads keep
    # answering (correctly) while rebalance swaps the (state, program)
    # pair — the resident lock makes the snapshot atomic
    import threading

    ep, _ = _endpoint(sess8, rng, name="mf-live")
    ids = np.arange(0, 64, 3)
    baseline = ep.dispatch(ids[:8])
    errors = []
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            try:
                if ep.dispatch(ids[:8]) != baseline:
                    errors.append("wrong answer")
                    return
            except Exception as e:      # noqa: BLE001 — the test's assert
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=loop) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        ep.rebalance(2)
        ep.restore_shard(0, _endpoint_uf(ep))
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors


def _endpoint_uf(ep):
    # reconstruct the canonical factors from the endpoint's live store (the
    # test built ids 0..63 dense, so owner/slot invert exactly)
    vals = np.asarray(ep._state[1])
    return vals[ep._owner, ep._slot]


def test_supervisor_straggler_ranks(tmp_path):
    import time

    from harp_tpu.parallel.supervisor import straggler_ranks

    assert straggler_ranks(None) == []
    assert straggler_ranks(str(tmp_path)) == []
    with open(os.path.join(str(tmp_path), "straggler_report.json"),
              "w") as f:
        json.dump({"suspects": [1, 9], "bsp_suspects": [2], "ts": 1}, f)
    assert straggler_ranks(str(tmp_path)) == [1, 2, 9]
    assert straggler_ranks(str(tmp_path), world=8) == [1, 2]
    # freshness gate: a 1970 report is stale for any sane bound, a fresh
    # one passes, a missing ts never passes a bounded read
    assert straggler_ranks(str(tmp_path), max_age_s=600.0) == []
    with open(os.path.join(str(tmp_path), "straggler_report.json"),
              "w") as f:
        json.dump({"suspects": [1], "bsp_suspects": [],
                   "ts": time.time()}, f)
    assert straggler_ranks(str(tmp_path), max_age_s=600.0) == [1]
    with open(os.path.join(str(tmp_path), "straggler_report.json"),
              "w") as f:
        json.dump({"suspects": [1], "bsp_suspects": []}, f)
    assert straggler_ranks(str(tmp_path), max_age_s=600.0) == []


# --------------------------------------------------------------------------- #
# bench row + manifest schema
# --------------------------------------------------------------------------- #

def test_bench_reshard_row_schema():
    with open(os.path.join(REPO, "BENCH_local.json")) as f:
        rec = json.load(f)
    row = rec["reshard"]
    cpu = row["cpu_mesh"]
    for key in ("reshard_seconds", "reshard_ring_seconds",
                "reshard_bytes_moved", "host_gather_seconds", "rounds",
                "parity", "device"):
        assert key in cpu, key
    assert cpu["reshard_bytes_moved"] > 0
    # GB-scale on-chip leg: measured dict, or null WITH the note (the
    # committed-null-with-note convention every on-chip row follows)
    if row["gb_scale"] is None:
        assert "gb_scale_note" in row


def test_manifest_pins_reshard_targets():
    with open(os.path.join(REPO, "tools", "collective_budget.json")) as f:
        targets = json.load(f)["targets"]
    a2a = targets["reshard_factor_a2a"]
    assert a2a["collectives"] == {"all_to_all": 1}
    assert a2a["bytes_per_step"] == 512        # == the traced chunk budget
    ring = targets["reshard_factor_ring"]
    assert set(ring["collectives"]) == {"ppermute"}
    reb = targets["serve_topk_mf_rebalanced"]
    assert reb["collectives"] == targets["serve_topk_mf"]["collectives"], \
        "rebalancing must not change the dispatch's collective shape"
