"""End-to-end elastic re-placement: scripted vanish -> spare swap or shrink.

The mp_smoke-style acceptance legs for ISSUE 8: a local gang training K-means
under the supervisor loses a member to a scripted ``vanish`` fault
(parallel.faults — the member exits and its host is treated as unreachable),
and the supervisor either re-places it onto a ``#spare``-pool host (same
world size -> the resumed run is BITWISE the clean run, extending PR 1's
kill-relaunch-resume contract across a host swap) or, with no spares left,
relaunches the gang one member smaller (world-size-agnostic checkpoint
resume) and still converges.
"""

import json
import os
import re
import sys

import pytest

from harp_tpu.parallel import faults, launch, supervisor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _nodes(n):
    return [launch.Node("localhost", 0) for _ in range(n)]


def _journal(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def _km_cmd(work):
    # each member holds 2 virtual devices; 512 points divide over every
    # world size this file relaunches at (8, 4, 2 devices)
    return [sys.executable, "-m", "harp_tpu.run", "kmeans", "--cpu-mesh",
            "--num-workers", "2", "--num-points", "512",
            "--num-centroids", "4", "--dim", "8", "--iterations", "6",
            "--work-dir", str(work), "--save-every", "1"]


def _with_fault(spec):
    class _Env:
        def __enter__(self):
            self.backup = os.environ.get("HARP_FAULT")
            os.environ["HARP_FAULT"] = spec
            return self

        def __exit__(self, *exc):
            if self.backup is None:
                os.environ.pop("HARP_FAULT", None)
            else:
                os.environ["HARP_FAULT"] = self.backup
    return _Env()


def test_gang_vanish_replaced_on_spare_resumes_bitwise(tmp_path):
    """vanish@rank=1 with a spare in the pool: the supervisor swaps the
    vanished member for the spare, the relaunch resumes from the newest
    VERIFIED checkpoint at the SAME world size, and the final model is
    bitwise the clean run's — the PR 1 kill-relaunch-resume contract, now
    across a host swap."""
    ref_work = tmp_path / "ref"
    results = launch.launch(_nodes(2), _km_cmd(ref_work), timeout=420.0,
                            cwd=REPO)
    assert results.ok, list(results)

    work = tmp_path / "faulted"
    with _with_fault("vanish@epoch=3:rank=1"):
        out = supervisor.supervise(
            _nodes(2), _km_cmd(work),
            policy=supervisor.RestartPolicy(max_restarts=2,
                                            on_suspect="replace"),
            spares=[launch.Node("127.0.0.1", 0)],
            timeout=420.0, cwd=REPO,
            checkpoint_dir=str(work / "ckpt"),
            journal_path=str(work / "restart_journal.jsonl"))
    assert out.ok and out.attempts == 2
    assert (work / "centroids.csv").read_bytes() == \
        (ref_work / "centroids.csv").read_bytes()
    restarts = [r for r in _journal(work / "restart_journal.jsonl")
                if r["event"] == "restart"]
    assert len(restarts) == 1
    r = restarts[0]
    assert r["cause"] == "vanish"
    assert r["first_rank"] == 1 and r["first_rc"] == faults.FAULT_VANISH_EXIT
    assert r["resumed_step"] == 2            # vanish fired BEFORE epoch 3 ran
    assert r["placement"] == {"action": "replace", "rank": 1,
                              "reason": "vanish", "old_host": "localhost",
                              "new_host": "127.0.0.1"}
    assert r["hosts"] == ["localhost", "127.0.0.1"] and r["world"] == 2
    assert "straggler" in r                  # the PR 7 report rides along


def test_gang_vanish_no_spares_shrinks_and_converges(tmp_path):
    """Zero spares: the vanished member is dropped and the gang relaunches
    one smaller. K-means resumes the W-written checkpoint into the smaller
    mesh (replicated centroids — exact) and converges."""
    work = tmp_path / "shrink"
    with _with_fault("vanish@epoch=3:rank=0"):
        out = supervisor.supervise(
            _nodes(2), _km_cmd(work),
            policy=supervisor.RestartPolicy(max_restarts=2,
                                            on_suspect="replace"),
            timeout=420.0, cwd=REPO,
            checkpoint_dir=str(work / "ckpt"),
            journal_path=str(work / "restart_journal.jsonl"))
    assert out.ok and out.attempts == 2
    restarts = [r for r in _journal(work / "restart_journal.jsonl")
                if r["event"] == "restart"]
    assert len(restarts) == 1
    r = restarts[0]
    assert r["cause"] == "vanish" and r["resumed_step"] == 2
    assert r["placement"]["action"] == "shrink"
    assert r["world"] == 1 and r["hosts"] == ["localhost"]
    assert (work / "centroids.csv").exists()
    # convergence: the resumed (smaller) gang's cost kept descending
    text = "".join(outp for _, outp in out.results)
    m = re.search(r"cost ([\d.eE+-]+) -> ([\d.eE+-]+)", text)
    assert m, text
    assert float(m.group(2)) <= float(m.group(1))


@pytest.mark.slow
def test_gang_acceptance_4_members_1_spare_vanish_rank2(tmp_path):
    """The full ISSUE 8 acceptance scenario: gang of 4 + 1 spare, scripted
    vanish@epoch=2:rank=2 -> the supervisor relaunches with the spare, the
    journal records the placement swap + straggler report, and the resumed
    run's result is bitwise-equal to an uninterrupted run."""
    ref_work = tmp_path / "ref"
    assert launch.launch(_nodes(4), _km_cmd(ref_work), timeout=600.0,
                         cwd=REPO).ok

    work = tmp_path / "faulted"
    with _with_fault("vanish@epoch=2:rank=2"):
        out = supervisor.supervise(
            _nodes(4), _km_cmd(work),
            policy=supervisor.RestartPolicy(max_restarts=2,
                                            on_suspect="replace"),
            spares=[launch.Node("127.0.0.1", 0)],
            timeout=600.0, cwd=REPO,
            checkpoint_dir=str(work / "ckpt"),
            journal_path=str(work / "restart_journal.jsonl"))
    assert out.ok and out.attempts == 2
    assert (work / "centroids.csv").read_bytes() == \
        (ref_work / "centroids.csv").read_bytes()
    r = next(rec for rec in _journal(work / "restart_journal.jsonl")
             if rec["event"] == "restart")
    assert r["placement"] == {"action": "replace", "rank": 2,
                              "reason": "vanish", "old_host": "localhost",
                              "new_host": "127.0.0.1"}
    assert r["hosts"] == ["localhost", "localhost", "127.0.0.1",
                          "localhost"]
    assert r["resumed_step"] == 1 and "straggler" in r
