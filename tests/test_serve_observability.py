"""Serving observability plane tests (ISSUE 12).

Covers the request-tracing tentpole (trace-id propagation and span
completeness across the forwarding hop on a 2-worker gang, the
partition-exact breakdown, the zero-drift budget gate with tracing ON),
the pull exporter (/metrics Prometheus schema, /snapshot JSON, /gang
aggregation, the per-worker wiring), the per-owner lookup-skew histogram
vs a known Zipfian id batch, the SLO watchdog (fires exactly once per
burn window; live integration under an injected slow@ fault with the
xprof trigger + snapshot chain), the batcher's pre-dispatch queue-depth
gauges, the deadline-exceeded reply detail, and the serving-load row's
observability keys.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from harp_tpu import telemetry
from harp_tpu.serve import (OP_CLASSIFY, OP_TOPK, MicroBatcher,
                            TopKEndpoint, classify_from_nn, local_gang,
                            protocol)
from harp_tpu.telemetry import spans
from harp_tpu.telemetry.exporter import (MetricsExporter,
                                         aggregate_snapshots,
                                         prometheus_text)
from harp_tpu.telemetry.watchdog import SLOWatchdog
from harp_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry_off_after():
    yield
    telemetry.disable()


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def _nn_model(session, dim=12, classes=3, seed=0):
    from harp_tpu.models import nn

    model = nn.MLPClassifier(session, nn.NNConfig(layers=(8,),
                                                  num_classes=classes))
    model.params = nn.init_params((dim, 8, classes), seed=seed)
    return model


def _two_worker_gang(session, rng, **gang_kw):
    ep_cls = classify_from_nn(session, _nn_model(session), name="classify")
    uf = rng.normal(size=(64, 8)).astype(np.float32)
    items = rng.normal(size=(32, 8)).astype(np.float32)
    ep_topk = TopKEndpoint(session, "topk", uf, items, k=4,
                           metrics=gang_kw.get("metrics"))
    return local_gang(session, [{"classify": ep_cls}, {"topk": ep_topk}],
                      **gang_kw), ep_topk


# --------------------------------------------------------------------------- #
# Tentpole: request tracing
# --------------------------------------------------------------------------- #

def test_trace_propagation_and_span_completeness_across_forward(
        session, rng, tmp_path):
    """A traced request forwarded worker 0 → worker 1 comes back with ONE
    trace id (the request id) and a complete stamp sequence; the direct
    leg completes too; both land as kind:"span" JSONL events."""
    m = Metrics()
    telemetry.configure(str(tmp_path), interval=1, metrics=m)
    (workers, make_client), _ep = _two_worker_gang(
        session, rng, metrics=m, trace_sample=1)
    client = make_client()
    try:
        # dest=0 but topk lives on worker 1: the forwarding leg
        row = client.request(OP_TOPK, "topk", 7, dest=0, timeout=30.0)
        assert row["found"]
        client.request(OP_CLASSIFY, "classify",
                       rng.normal(size=12).astype(np.float32), timeout=30.0)
    finally:
        client.close()
        for w in workers:
            w.close()
    log = telemetry.active()
    log.flush()
    events = [e for e in _read_jsonl(log.path) if e.get("kind") == "span"]
    assert len(events) == 2, events
    fwd = next(e for e in events if e["op"] == OP_TOPK)
    direct = next(e for e in events if e["op"] == OP_CLASSIFY)
    # trace id IS the request id: client rank, first two submits
    assert fwd["trace_id"] == f"{client.rank}-0"
    assert direct["trace_id"] == f"{client.rank}-1"
    assert fwd["forwarded"] and fwd["forward_hop_s"] >= 0.0
    assert not direct["forwarded"]
    for ev in events:
        stage_sum = sum(ev[f"{s}_s"] for s in spans.STAGES)
        assert ev["total_s"] == pytest.approx(stage_sum, abs=1e-6)
        assert ev["dispatch_s"] > 0.0 and ev["coalesce_s"] >= 0.0
    # the client-side per-stage timers observed both spans
    assert m.timing("serve.span.total")["count"] == 2
    assert m.counters["serve.spans"] == 2
    assert m.counters.get("serve.spans_forwarded", 0) == 1


def test_breakdown_partitions_total_and_rejects_incomplete():
    tr = {"id": "c-0", "op": "topk", "model": "m", "stamps": []}
    for stage, ts in ((spans.SUBMIT, 1.0), (spans.RECV, 1.010),
                      (spans.FORWARD, 1.011), (spans.RECV, 1.020),
                      (spans.ENQUEUE, 1.021), (spans.DISPATCH_START, 1.023),
                      (spans.DISPATCH_END, 1.027), (spans.REPLY_SEND, 1.028),
                      (spans.REPLY_RECV, 1.030)):
        tr["stamps"].append((stage, ts))
    bd = spans.breakdown(tr)
    assert bd["forwarded"] and bd["trace_id"] == "c-0"
    assert bd["total_s"] == pytest.approx(0.030)
    assert sum(bd[f"{s}_s"] for s in spans.STAGES) == pytest.approx(
        bd["total_s"])
    # route covers recv→enqueue INCLUDING the forward hop
    assert bd["route_s"] == pytest.approx(0.011)
    assert bd["forward_hop_s"] == pytest.approx(0.009)
    # a request rejected before the batcher has no dispatch stamps
    half = {"id": "c-1", "stamps": [(spans.SUBMIT, 1.0), (spans.RECV, 1.1),
                                    (spans.REPLY_SEND, 1.2),
                                    (spans.REPLY_RECV, 1.3)]}
    assert spans.breakdown(half) is None


def test_untraced_requests_carry_no_trace_key(session, rng):
    (workers, make_client), _ep = _two_worker_gang(session, rng,
                                                   trace_sample=0)
    client = make_client()
    try:
        assert client.trace_sample == 0
        pending = client.submit(OP_TOPK, "topk", 3)
        assert pending.result(30.0)["found"]
        assert spans.TRACE_KEY not in pending.reply
    finally:
        client.close()
        for w in workers:
            w.close()


def test_budget_manifest_zero_drift_with_request_tracing_on(
        tmp_path, monkeypatch):
    """The r13 CI gate, in-process: the serve dispatch programs traced
    with telemetry AND request tracing enabled must reproduce the pinned
    manifest exactly (stamps live in host router/batcher code — nothing
    enters the resident jitted dispatch). Full sweep in ci_checks.sh
    stage 2."""
    from tools.jaxlint import checkers_jaxpr

    monkeypatch.setenv(spans.ENV_SAMPLE, "1")
    telemetry.configure(str(tmp_path), interval=4)
    with open(os.path.join(REPO, "tools", "collective_budget.json")) as f:
        targets = json.load(f)["targets"]
    for name in ("serve_classify_nn", "serve_topk_mf"):
        counts, dtype_bad, nbytes = checkers_jaxpr.trace_target(name)
        assert counts == targets[name]["collectives"], name
        assert nbytes == targets[name]["bytes_by_kind"], name
        assert sum(nbytes.values()) == targets[name]["bytes_per_step"], name
        assert not dtype_bad


# --------------------------------------------------------------------------- #
# Exporter: /metrics, /snapshot, /gang
# --------------------------------------------------------------------------- #

def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode()


def test_exporter_metrics_snapshot_and_gang_schema():
    m = Metrics()
    m.count("serve.requests", 7)
    m.gauge("serve.queue_depth.topk", 3.0)
    m.count("telemetry.events_dropped", 2)
    for v in (0.001, 0.002, 0.004):
        m.observe("serve.span.total", v)
    other = Metrics()
    other.count("serve.requests", 5)
    other.observe("serve.span.total", 0.008)
    with MetricsExporter(m, rank=0,
                         gang=lambda: {0: m.snapshot(),
                                       1: other.snapshot()}) as ex:
        base = f"http://{ex.host}:{ex.port}"
        text = _get(base + "/metrics")
        lines = text.splitlines()
        assert "# TYPE harp_serve_requests counter" in lines
        assert "harp_serve_requests 7" in lines
        assert "# TYPE harp_serve_queue_depth_topk gauge" in lines
        assert "harp_telemetry_events_dropped 2" in lines
        assert "# TYPE harp_serve_span_total_seconds summary" in lines
        assert any(l.startswith(
            'harp_serve_span_total_seconds{quantile="0.99"}')
            for l in lines)
        assert "harp_serve_span_total_seconds_count 3" in lines
        snap = json.loads(_get(base + "/snapshot"))
        assert snap["rank"] == 0 and snap["counters"][
            "serve.requests"] == 7
        assert snap["timers"]["serve.span.total"]["count"] == 3
        gang = json.loads(_get(base + "/gang"))
        agg = gang["aggregated"]
        assert agg["num_ranks"] == 2
        assert agg["counters"]["serve.requests"] == 12
        t = agg["timers"]["serve.span.total"]
        assert t["count"] == 4 and t["worst_p99_s"] == pytest.approx(0.008)
        assert set(gang["ranks"]) == {"0", "1"}
        with pytest.raises(urllib.error.HTTPError):
            _get(base + "/nope")
    # closed: the socket is released
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        _get(base + "/metrics")


def test_exporter_gang_view_absent_is_404():
    with MetricsExporter(Metrics(), rank=3) as ex:
        with pytest.raises(urllib.error.HTTPError):
            _get(f"http://{ex.host}:{ex.port}/gang")


def test_prometheus_text_is_pure_and_sanitizes():
    out = prometheus_text({"counters": {"a.b-c/d": 1.0}, "gauges": {},
                           "timers": {"t": {}}})
    assert "harp_a_b_c_d 1" in out          # empty timer rows are skipped
    assert "_seconds" not in out


def test_aggregate_snapshots_rolls_up_exact_sums():
    a = Metrics()
    a.count("x", 2)
    a.observe("t", 0.010)
    b = Metrics()
    b.count("x", 3)
    b.observe("t", 0.030)
    b.observe("t", 0.030)
    agg = aggregate_snapshots({0: a.snapshot(), 1: b.snapshot()})
    assert agg["counters"]["x"] == 5
    assert agg["timers"]["t"]["count"] == 3
    assert agg["timers"]["t"]["total_s"] == pytest.approx(0.070)
    assert agg["timers"]["t"]["worst_p99_s"] == pytest.approx(0.030)
    assert agg["timers"]["t"]["mean_s"] == pytest.approx(0.070 / 3)


def test_worker_exporter_serves_live_serving_counters(session, rng):
    m = Metrics()
    (workers, make_client), _ep = _two_worker_gang(
        session, rng, metrics=m, metrics_port=0)
    client = make_client()
    try:
        assert all(w.exporter is not None for w in workers)
        ports = {w.exporter.port for w in workers}
        assert len(ports) == 2                # one exporter per worker
        client.request(OP_TOPK, "topk", 3, timeout=30.0)
        text = _get(f"http://127.0.0.1:{workers[1].exporter.port}/metrics")
        assert "harp_serve_requests" in text
        assert "harp_serve_queue_depth_topk" in text
    finally:
        client.close()
        for w in workers:
            w.close()
    # the worker's close released the exporter socket too
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        _get(f"http://127.0.0.1:{workers[0].exporter.port}/metrics")


# --------------------------------------------------------------------------- #
# Per-owner lookup-skew histogram (the hot-key measurement)
# --------------------------------------------------------------------------- #

def test_topk_lookup_skew_flags_zipfian_batch(session, rng):
    m = Metrics()
    uf = rng.normal(size=(64, 4)).astype(np.float32)
    items = rng.normal(size=(16, 4)).astype(np.float32)
    ep = TopKEndpoint(session, "mf", uf, items, k=3, metrics=m)
    w = session.num_workers
    # a Zipf-shaped batch: 7 of 8 ids hit owner 5 (id ≡ 5 mod 8), one id
    # lands elsewhere — the modulo placement's hot-key worst case
    hot = np.asarray([5, 13, 21, 29, 37, 45, 53, 2])
    ep.dispatch(hot)
    skew = ep.lookup_skew()
    assert skew["total"] == 8
    assert skew["hottest"] == 5
    assert skew["counts"][5] == 7 and sum(skew["counts"]) == 8
    assert skew["skew"] == pytest.approx(7 * w / 8)
    assert m.counters["serve.lookup_owner.mf.r5"] == 7
    assert m.gauges["serve.lookup_skew.mf"] == pytest.approx(7 * w / 8)
    # a uniform batch drags the cumulative skew back down
    ep.dispatch(np.arange(8))
    assert ep.lookup_skew()["skew"] == pytest.approx(8 * w / 16)
    ep.reset_lookup_skew()
    assert ep.lookup_skew()["total"] == 0 and ep.lookup_skew()["skew"] == 0.0


def test_lookup_skew_follows_rebalanced_owner_map(session, rng):
    uf = rng.normal(size=(64, 4)).astype(np.float32)
    items = rng.normal(size=(16, 4)).astype(np.float32)
    m = Metrics()
    ep = TopKEndpoint(session, "mf", uf, items, k=3, metrics=m)
    ep.rebalance(5)               # ids leave rank 5 for healthy workers
    ep.reset_lookup_skew()
    ep.dispatch(np.asarray([5, 13, 21, 29, 37, 45, 53, 61]))
    skew = ep.lookup_skew()
    # every one of those ids USED to live on rank 5; after the rebalance
    # the histogram must follow the moved shard map, not the modulo
    assert skew["counts"][5] == 0 and skew["total"] == 8


# --------------------------------------------------------------------------- #
# SLO watchdog
# --------------------------------------------------------------------------- #

def test_watchdog_fires_exactly_once_per_burn_window(tmp_path):
    m = Metrics()
    wd = SLOWatchdog(0.010, window_s=5.0, min_samples=5, sustain=2,
                     eval_interval_s=0.0, telemetry_dir=str(tmp_path),
                     metrics=m)
    t = 100.0
    for i in range(30):                       # sustained burn: 50ms >> 10ms
        wd.observe(0.050, now=t + i * 0.01)
    assert wd.incidents == 1 and wd.burning
    for i in range(30):                       # still the SAME burn window
        wd.observe(0.050, now=t + 1 + i * 0.01)
    assert wd.incidents == 1
    for i in range(150):                      # recovery: fast samples
        wd.observe(0.001, now=t + 10 + i * 0.05)
    assert not wd.burning and wd.incidents == 1
    for i in range(30):                       # a SECOND burn fires again
        wd.observe(0.050, now=t + 30 + i * 0.01)
    assert wd.incidents == 2
    incidents = _read_jsonl(tmp_path / "slo_incidents.jsonl")
    assert [r["incident"] for r in incidents] == [1, 2]
    assert incidents[0]["p99_s"] > incidents[0]["p99_target_s"]
    assert "xprof_request" in incidents[0]["triggered"]
    assert "metrics_snapshot" in incidents[0]["triggered"]
    # the xprof trigger file is the PR 7 operator-path format
    trig = json.loads((tmp_path / "xprof_request.json").read_text())
    assert trig["steps"] >= 1
    snap = json.loads((tmp_path / "slo_snapshot_rank0_1.json").read_text())
    assert "counters" in snap and "timers" in snap
    assert m.counters["slo.incidents"] == 2


def test_watchdog_error_budget_burns_without_latency(tmp_path):
    wd = SLOWatchdog(10.0, window_s=5.0, min_samples=5, sustain=1,
                     error_budget=0.2, eval_interval_s=0.0,
                     telemetry_dir=str(tmp_path), metrics=Metrics())
    t = 10.0
    for i in range(20):                       # fast but 50% errors
        wd.observe(0.001, ok=(i % 2 == 0), now=t + i * 0.01)
    assert wd.incidents == 1
    rec = _read_jsonl(tmp_path / "slo_incidents.jsonl")[0]
    assert rec["error_fraction"] > rec["error_budget"]


def test_watchdog_under_min_samples_never_fires():
    wd = SLOWatchdog(0.001, min_samples=50, sustain=1, eval_interval_s=0.0,
                     metrics=Metrics())
    for i in range(40):
        wd.observe(1.0, now=10.0 + i * 0.01)
    assert wd.incidents == 0 and not wd.burning


def test_watchdog_fires_under_slow_fault_and_triggers_pr7_chain(
        session, rng, tmp_path, monkeypatch):
    """The acceptance leg, live: a kmeans loop dragged by the slow@ fault
    grammar burns the chunk-boundary SLO; the watchdog journals ONE
    incident, arms the xprof trigger file, dumps the snapshot, attaches
    the published straggler report — and the XprofController boundary
    hook picks the trigger up and actually writes a profiler trace."""
    from harp_tpu.models import kmeans as km
    from harp_tpu.telemetry.gang import write_straggler_report
    from harp_tpu.telemetry.xprof import XprofController
    from harp_tpu.utils.checkpoint import Checkpointer

    tdir = str(tmp_path / "tele")
    m = Metrics()
    log = telemetry.configure(tdir, interval=1, metrics=m)
    # a previously-published straggler report (the GangCollector's cadence
    # output): the incident must attach it
    write_straggler_report(tdir, {"v": 1, "ts": time.time(),
                                  "suspects": [0], "bsp_suspects": []})
    ctl = XprofController(
        session, trigger_path=os.path.join(tdir, "xprof_request.json"),
        default_dir=os.path.join(tdir, "xprof"))
    log.add_boundary_hook(ctl)
    wd = SLOWatchdog(0.010, window_s=60.0, min_samples=3, sustain=2,
                     telemetry_dir=tdir, xprof_steps=2, metrics=m)
    log.add_boundary_hook(wd.boundary_hook())
    monkeypatch.setenv("HARP_FAULT", "slow@epoch=1:ms=40")
    monkeypatch.setenv("HARP_PROCESS_ID", "0")
    cfg = km.KMeansConfig(8, 16, iterations=10)
    pts = rng.normal(size=(64, 16)).astype(np.float32)
    model = km.KMeans(session, cfg)
    p, c = model.prepare(pts, pts[:8].copy())
    model.fit_checkpointed(p, c, Checkpointer(str(tmp_path / "ckpt")),
                           save_every=1)
    monkeypatch.delenv("HARP_FAULT")
    telemetry.disable()           # closes hooks (any open xprof window)
    assert wd.incidents == 1, (wd.incidents, wd.window_stats())
    rec = _read_jsonl(os.path.join(tdir, "slo_incidents.jsonl"))[0]
    assert rec["p99_s"] >= 0.040              # the fault's per-boundary drag
    assert rec["straggler_report"]["suspects"] == [0]
    assert set(rec["triggered"]) >= {"xprof_request", "metrics_snapshot",
                                     "straggler_report_attached"}
    # the controller consumed the trigger and wrote a per-rank trace dir
    trace_dir = os.path.join(tdir, "xprof", "rank0")
    assert os.path.isdir(trace_dir) and os.listdir(trace_dir)


def test_serving_worker_feeds_watchdog_and_burns_on_slow_dispatch(
        session, rng, tmp_path):
    """The serving leg: every reply feeds (request age, ok) into the
    worker's watchdog; a dispatch dragged past the p99 target burns it."""
    m = Metrics()
    (workers, make_client), ep = _two_worker_gang(
        session, rng, metrics=m,
        slo_p99_s=0.005,
        slo_kw={"window_s": 60.0, "min_samples": 3, "sustain": 1,
                "eval_interval_s": 0.0, "telemetry_dir": str(tmp_path)})
    # drag the topk dispatch past the target deterministically
    orig = ep.dispatch

    def slow_dispatch(batch):
        time.sleep(0.02)
        return orig(batch)

    ep.dispatch = slow_dispatch
    client = make_client()
    try:
        for i in range(6):
            client.request(OP_TOPK, "topk", int(i), timeout=30.0)
    finally:
        client.close()
        for w in workers:
            w.close()
    wd = workers[1].slo           # worker 1 owns topk
    assert wd is not None and wd.incidents == 1
    assert (tmp_path / "slo_incidents.jsonl").exists()


# --------------------------------------------------------------------------- #
# Batcher observability satellites
# --------------------------------------------------------------------------- #

class _BlockingEndpoint:
    name = "fake"
    op = "classify"
    bucket_sizes = (4,)
    max_batch = 4

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()

    def bucket_for(self, n):
        return 4

    def validate_query(self, op, data):
        return None

    def dispatch(self, batch):
        self.entered.set()
        self.release.wait(10.0)
        return list(range(len(batch)))


def _msg(i, deadline_ts=None, ts=None):
    return {"kind": protocol.REQUEST, "id": f"t-{i}", "op": "classify",
            "model": "fake", "data": float(i),
            "reply_to": (9, "127.0.0.1", 1),
            "ts": time.time() if ts is None else ts,
            "deadline_ts": deadline_ts}


def test_batcher_pre_dispatch_queue_depth_and_high_watermark():
    ep = _BlockingEndpoint()
    m = Metrics()
    replies = []
    b = MicroBatcher(ep, lambda msg, ok, **kw: replies.append((msg, ok)),
                     metrics=m, max_wait_s=0.001)
    try:
        b.submit(_msg(0))
        assert ep.entered.wait(5.0)           # first dispatch is in flight
        for i in range(1, 7):                 # queue builds BEHIND it
            b.submit(_msg(i))
        assert m.gauges["serve.queue_depth.fake"] == 6.0
        assert m.gauges["serve.queue_high_watermark.fake"] == 6.0
        assert b.queue_high_watermark == 6
        # depth 5 and 6 exceeded max_batch=4: overload was visible twice
        assert m.counters["serve.queue_overfull.fake"] == 2
    finally:
        ep.release.set()
        b.drain_and_stop()
    # the watermark survives the drain (a past overload stays visible)
    assert m.gauges["serve.queue_high_watermark.fake"] == 6.0
    assert m.gauges["serve.queue_depth.fake"] <= 6.0


def test_deadline_exceeded_reply_carries_age_and_miss():
    class _Instant(_BlockingEndpoint):
        def __init__(self):
            super().__init__()
            self.release.set()

    ep = _Instant()
    m = Metrics()
    replies = []
    lock = threading.Lock()

    def reply(msg, ok, result=None, error=None, **kw):
        with lock:
            replies.append({"id": msg["id"], "ok": ok, "error": error})

    b = MicroBatcher(ep, reply, metrics=m, max_wait_s=0.001)
    try:
        now = time.time()
        b.submit(_msg(0, deadline_ts=now - 0.5, ts=now - 0.7))
        deadline = time.time() + 5.0
        while not replies and time.time() < deadline:
            time.sleep(0.005)
    finally:
        b.drain_and_stop()
    assert replies and not replies[0]["ok"]
    err = replies[0]["error"]
    assert err.startswith(protocol.ERR_DEADLINE)
    # the measured age and the miss margin ride the error, so a client can
    # tune its deadline vs the coalescing window from the reply alone
    assert "request age" in err and "missed deadline by" in err
    assert "max_wait_s" in err
    age = float(err.split("request age ")[1].split(" ms")[0])
    miss = float(err.split("missed deadline by ")[1].split(" ms")[0])
    assert age == pytest.approx(700, abs=250)
    assert miss == pytest.approx(500, abs=250)
    assert m.counters["serve.deadline_expired.fake"] == 1


# --------------------------------------------------------------------------- #
# Load-generator row: observability keys
# --------------------------------------------------------------------------- #

@pytest.mark.large
def test_serving_load_row_reconciles_spans_and_counts_expiry(session,
                                                             tmp_path):
    from harp_tpu.benchmark import serving_load

    telemetry.configure(str(tmp_path), interval=1)
    row = serving_load.measure(session, requests_per_mix=90, num_clients=3,
                               mixes={"mixed": 0.5}, trace_sample=2)
    telemetry.disable()
    assert row["mixes"]["mixed"]["errors"] == 0
    assert row["mixes"]["mixed"]["deadline_expired"] == 0
    sb = row["stage_breakdown"]
    assert set(sb) == {"total"} | set(spans.STAGES)
    rec = row["reconciliation"]
    assert rec["spans"] == sb["total"]["count"] > 0
    # stage durations partition each span: means reconcile tightly, p50s
    # within the stated 25% band
    assert rec["mean_ratio"] == pytest.approx(1.0, abs=0.02)
    assert rec["p50_ratio"] == pytest.approx(1.0, abs=0.25)
    skew = row["lookup_skew"]
    assert skew["total"] > 0 and len(skew["counts"]) == 8
    # the spans flowed THROUGH telemetry: kind:"span" events in the JSONL
    events = _read_jsonl(tmp_path / "rank0" / "steps.jsonl")
    assert sum(e.get("kind") == "span" for e in events) == rec["spans"]


@pytest.mark.large
def test_serving_load_counts_deadline_expiry_per_mix(session, tmp_path):
    from harp_tpu.benchmark import serving_load

    row = serving_load.measure(session, requests_per_mix=24, num_clients=3,
                               mixes={"mixed": 0.5}, trace_sample=0,
                               deadline_s=-0.001)    # born expired
    mixed = row["mixes"]["mixed"]
    assert mixed["requests"] == 0                    # all expired
    assert mixed["deadline_expired"] == mixed["errors"] > 0
    # the expiry error carries the tuning detail (batcher satellite)
    assert any("missed deadline by" in e for e in mixed["error_sample"])
