"""Serving fleet tests (ISSUE 14): chaos grammar, retry contract, live
refresh, hot-key cache, in-process recovery, process-gang vanish
classification, and the SLO incident schema feeding re-placement.

The recovery scenarios are all SCRIPTED through the serving fault grammar
(``HARP_FAULT=kill|vanish|slow@request=N:rank=R``) — the acceptance runs
are fault-injection runs, not hand choreography.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from harp_tpu.parallel import faults
from harp_tpu.serve import (OP_CLASSIFY, OP_TOPK, ServeError, TopKEndpoint,
                            TopKReplyCache, local_gang, protocol)
from harp_tpu.serve import fleet as fleet_mod
from harp_tpu.serve.router import RouterClient


def _topk_ep(session, rng, users=48, items_n=16, k=3, **kw):
    uf = rng.normal(size=(users, 8)).astype(np.float32)
    items = rng.normal(size=(items_n, 8)).astype(np.float32)
    ep = TopKEndpoint(session, "mf", uf, items, k=k, **kw)
    ref = {u: np.argsort(-(uf[u] @ items.T), kind="stable")[:k].tolist()
           for u in range(users)}
    return ep, uf, items, ref


# --------------------------------------------------------------------------- #
# Serving fault grammar
# --------------------------------------------------------------------------- #

def test_serve_fault_grammar_parse():
    (spec,) = faults.parse_faults("kill@request=5:rank=1")
    assert (spec.kind, spec.request, spec.rank, spec.epoch) == \
        ("kill", 5, 1, None)
    (slow,) = faults.parse_faults("slow@request=3:ms=50")
    assert (slow.kind, slow.request, slow.ms) == ("slow", 3, 50)
    # kill is serving-only; request= is serving-only; exactly one clock
    for bad in ("kill@epoch=3", "crash@request=3", "kill@request=0",
                "vanish@epoch=1:request=2", "kill@rank=1"):
        with pytest.raises(ValueError):
            faults.parse_faults(bad)


def test_serve_fire_kill_once_and_slow_sustained(monkeypatch):
    monkeypatch.setenv("HARP_FAULT", "kill@request=3:rank=1")
    killed = []
    for n in (1, 2):
        faults.serve_fire(n, rank=1, on_kill=lambda: killed.append(n))
    assert killed == []
    faults.serve_fire(3, rank=1, on_kill=lambda: killed.append(3))
    faults.serve_fire(4, rank=1, on_kill=lambda: killed.append(4))
    assert killed == [3]                 # at most once per (spec, rank)
    faults.serve_fire(5, rank=0, on_kill=lambda: killed.append(0))
    assert killed == [3]                 # rank-gated
    monkeypatch.setenv("HARP_FAULT", "slow@request=2:ms=7")
    naps = []
    for n in (1, 2, 3):
        faults.serve_fire(n, rank=0, sleep=naps.append)
    assert naps == [0.007, 0.007]        # sustained from request 2 on
    # training-boundary specs never fire on the request clock and vice
    # versa: a request spec is skipped by fire()
    monkeypatch.setenv("HARP_FAULT", "kill@request=1")
    faults.fire(99)                      # must not os._exit


# --------------------------------------------------------------------------- #
# Client retry/backoff + fail-fast contract (satellite)
# --------------------------------------------------------------------------- #

class _BlackHole:
    """A 'worker' that accepts frames and never answers — the reply-loss/
    dead-dispatch case the retry contract exists for."""

    def __init__(self, rank=0, secret=b"s"):
        from harp_tpu.parallel.events import EventQueue
        from harp_tpu.parallel.p2p import P2PTransport

        self.queue = EventQueue()
        self.transport = P2PTransport(self.queue, rank=rank, peers={},
                                      secret=secret)
        self.address = self.transport.address

    def close(self):
        self.transport.close()


def test_retry_backoff_bounded_with_jitter_and_no_pending_growth():
    hole = _BlackHole()
    client = RouterClient(100, {0: hole.address}, {"mf": 0}, secret=b"s")
    naps = []
    try:
        t0 = time.perf_counter()
        with pytest.raises(TimeoutError):
            client.request_retry(OP_TOPK, "mf", 1, timeout=0.15,
                                 attempts=3, backoff_s=0.05,
                                 backoff_factor=2.0, backoff_max_s=0.08,
                                 jitter=0.5, sync_timeout=0.1,
                                 sleep=naps.append)
        wall = time.perf_counter() - t0
        # bounded attempts: exactly attempts-1 backoffs, each in
        # [base*f^k, cap*(1+jitter)] — jittered, capped, never unbounded
        assert len(naps) == 2
        assert 0.05 <= naps[0] <= 0.075 * (1 + 1e-9), naps
        assert 0.08 <= naps[1] <= 0.12 + 1e-9, naps
        assert wall < 10.0
        # every timed-out attempt discarded its pending entry: the
        # waiting map cannot grow through retries (_PendingReply contract)
        assert client._waiting == {}
        assert client.metrics.counters.get("serve.client_retries", 0) >= 2
    finally:
        client.close()
        hole.close()


def test_dead_rank_fast_fail_and_inflight_failed_fast():
    hole = _BlackHole()
    client = RouterClient(101, {0: hole.address}, {"mf": 0}, secret=b"s")
    try:
        pending = client.submit(OP_TOPK, "mf", 7)
        client.mark_dead(0)
        # the in-flight future to the dead rank fails NOW (retryable
        # dead-rank reply), not at its timeout
        t0 = time.perf_counter()
        with pytest.raises(ServeError, match=protocol.ERR_DEAD_RANK):
            pending.result(5.0)
        assert time.perf_counter() - t0 < 1.0
        assert client._waiting == {}
        # a new submit to the dead rank fails fast at SUBMIT — no socket
        # wait, no reply timeout
        t0 = time.perf_counter()
        with pytest.raises(ConnectionError, match="marked dead"):
            client.submit(OP_TOPK, "mf", 8)
        assert time.perf_counter() - t0 < 0.5
        # a placement frame re-announcing the rank revives it
        client.apply_placement({"mf": 0}, {0: hole.address}, version=1)
        assert 0 not in client._dead_ranks
        assert client.placement_version == 1
        # stale frames can never roll the map back
        assert not client.apply_placement({"mf": 9}, {}, version=1)
        assert client.placement == {"mf": 0}
    finally:
        client.close()
        hole.close()


def test_dead_mark_cleared_by_same_version_reannounce():
    """A transient send failure must not brick a healthy rank: ANY frame
    re-announcing the rank's address clears the mark, even when the map
    itself is same-version (no recovery ever bumped it)."""
    hole = _BlackHole()
    client = RouterClient(102, {0: hole.address}, {"mf": 0}, secret=b"s")
    try:
        client.mark_dead(0)
        with pytest.raises(ConnectionError):
            client.submit(OP_TOPK, "mf", 1)
        # same-version answer (placement_version stays 0): map not
        # applied, but the rank is alive again
        assert not client.apply_placement({"mf": 0}, {0: hole.address},
                                          version=0)
        assert 0 not in client._dead_ranks
        client.submit(OP_TOPK, "mf", 2)     # submits again
    finally:
        client.close()
        hole.close()


def test_push_epoch_is_monotonic_under_out_of_order_pushes(session, rng):
    """Two concurrent epoch pushes can finish out of order (the device
    build runs off-lock): the older epoch must be discarded at the swap,
    never applied over the newer one."""
    ep, uf, items, _ref = _topk_ep(session, rng)
    uf2 = rng.normal(size=uf.shape).astype(np.float32)
    assert ep.push_epoch(uf2, version=2) == 2
    # the straggler push (epoch 1) loses: state and version unchanged
    assert ep.push_epoch(uf, version=1) == 2
    assert ep.version == 2
    ref2 = np.argsort(-(uf2[5] @ items.T), kind="stable")[:3].tolist()
    assert ep.dispatch(np.asarray([5]))[0]["items"] == ref2


def test_local_fleet_skips_stale_frozen_canonical(session, rng, tmp_path):
    """A frozen canonical table describes epoch 0 only: after a live
    refresh, recovery must NOT restore it over the fresh factors (stale
    rows labeled with the new version); a callable source regenerates
    the current epoch and restores normally."""
    ep, uf, items, _ref = _topk_ep(session, rng)
    workers, make_client = local_gang(session, [{"mf": ep}])
    fleet = fleet_mod.LocalFleet(workers, make_client,
                                 canonical={"mf": uf},
                                 journal_path=str(tmp_path / "j.jsonl"))
    try:
        uf2 = rng.normal(size=uf.shape).astype(np.float32)
        ep.push_epoch(uf2, version=1)
        workers[0].die()
        deadline = time.time() + 30.0
        while time.time() < deadline:
            if any(r["event"] == "replaced"
                   for r in fleet.journal.records):
                break
            time.sleep(0.02)
        events = [r["event"] for r in fleet.journal.records]
        assert "restore-skipped-stale-canonical" in events
        replaced = next(r for r in fleet.journal.records
                        if r["event"] == "replaced")
        assert replaced["restored_rows"] == {}
        # the refreshed factors survived the recovery
        ref2 = np.argsort(-(uf2[5] @ items.T), kind="stable")[:3].tolist()
        assert ep.dispatch(np.asarray([5]))[0]["items"] == ref2
    finally:
        fleet.close()


def test_malformed_placement_frame_never_kills_the_loops(session, rng):
    """A version-skewed placement frame (non-dict placement, short
    address tuples) must cost one dropped frame — never the worker's or
    the client's receive thread (the 'lifeline' contract)."""
    from harp_tpu.parallel.events import Event, EventType
    from harp_tpu.utils.metrics import Metrics

    m = Metrics()
    ep, _uf, _items, ref = _topk_ep(session, rng)
    workers, make_client = local_gang(session, [{"mf": ep}], metrics=m)
    client = make_client(metrics_override=m)
    try:
        for bad in ({"kind": protocol.PLACEMENT, "version": 9,
                     "placement": [["mf", 0]], "peers": {}},
                    {"kind": protocol.PLACEMENT, "version": 9,
                     "placement": {"mf": 0}, "peers": {0: ["h"]}}):
            workers[0].queue.put(Event(EventType.MESSAGE, 99, dict(bad)))
            client.queue.put(Event(EventType.MESSAGE, 99, dict(bad)))
        deadline = time.time() + 10.0
        while m.counters.get("serve.malformed_placements", 0) < 4 \
                and time.time() < deadline:
            time.sleep(0.01)
        assert m.counters.get("serve.malformed_placements", 0) >= 4
        # both loops survived: traffic still flows end to end
        assert client.request(OP_TOPK, "mf", 5,
                              timeout=30.0)["items"] == ref[5]
        assert workers[0].placement_version == 0   # nothing applied
    finally:
        client.close()
        for w in workers:
            w.close()


def test_placement_get_pull_and_versioned_push(session, rng):
    ep, _uf, _items, ref = _topk_ep(session, rng)
    workers, make_client = local_gang(session, [{"mf": ep}])
    client = make_client()
    try:
        # pull: sync_placement asks the worker and satisfies the waiter
        assert client.sync_placement(timeout=10.0)
        # push: a fleet-style placement update reaches the worker and is
        # version-gated
        w = workers[0]
        assert w.apply_placement({"mf": 0}, {0: w.address}, version=3)
        assert not w.apply_placement({"mf": 0}, {0: w.address}, version=3)
        assert w.placement_version == 3
        # traffic still flows after the churn
        res = client.request_retry(OP_TOPK, "mf", 5, timeout=30.0)
        assert res["items"] == ref[5]
    finally:
        client.close()
        for w in workers:
            w.close()


# --------------------------------------------------------------------------- #
# Live model refresh: versioned, snapshot-consistent, zero torn reads
# --------------------------------------------------------------------------- #

def test_push_epoch_versioned_swap_under_live_traffic(session):
    """ISSUE 14 acceptance (in-process leg): factor epochs pushed
    mid-traffic land with zero failed requests and zero torn reads —
    every reply's top-k matches the reference of the version the reply
    itself names."""
    from harp_tpu.benchmark import serving_fleet

    row = serving_fleet.measure_refresh(
        session, num_clients=2, refreshes=3, requests_per_client=60,
        refresh_interval_s=0.1)
    assert row["errors"] == 0, row
    assert row["torn_reads"] == 0, row
    assert row["refreshes_applied"] >= 1
    assert len(row["versions_seen"]) >= 2, row   # the swap really landed
    assert row["requests"] == 120


def test_push_epoch_shape_guards_and_version_stamp(session, rng):
    ep, uf, items, _ref = _topk_ep(session, rng)
    with pytest.raises(ValueError):
        ep.push_epoch(uf[:-1])
    with pytest.raises(ValueError):
        ep.push_epoch(uf, items[:-1])
    assert ep.push_epoch(uf * 2.0) == 1
    assert ep.push_epoch(uf, version=7) == 7
    assert ep.version == 7
    # restore_full re-materializes every shard through the reshard engine
    # and stamps the restored epoch
    ep2, uf2, _items2, ref2 = _topk_ep(session, rng)
    blank = TopKEndpoint(session, "mf", np.zeros_like(uf2), _items2, k=3)
    assert blank.restore_full(uf2, version=4) == len(uf2)
    assert blank.version == 4
    assert blank.dispatch(np.asarray([5]))[0]["items"] == ref2[5]


# --------------------------------------------------------------------------- #
# Hot-key reply cache
# --------------------------------------------------------------------------- #

def test_reply_cache_ttl_version_and_lru():
    cache = TopKReplyCache(capacity=2, ttl_s=10.0)
    assert cache.get("mf", 1, 0, now=0.0) is None          # miss
    cache.put("mf", 1, 0, {"items": [3]}, now=0.0)
    assert cache.get("mf", 1, 0, now=1.0) == {"items": [3]}
    assert cache.get("mf", 1, 0, now=11.0) is None         # TTL expired
    cache.put("mf", 1, 0, {"items": [3]}, now=0.0)
    assert cache.get("mf", 1, 1, now=1.0) is None          # new epoch
    cache.put("mf", 2, 0, {"items": [4]}, now=0.0)
    cache.put("mf", 3, 0, {"items": [5]}, now=0.0)         # evicts LRU
    assert len(cache._store) == 2
    # unversioned/unhashable queries are uncacheable, never a crash
    assert not cache.put("mf", 1, None, {"items": [9]})
    assert not cache.put("mf", np.zeros(3), 0, {"items": [9]})
    s = cache.stats()
    assert s["hits"] == 1 and s["misses"] >= 2
    assert 0.0 < s["hit_rate"] < 1.0


def test_worker_cache_hit_path_and_refresh_invalidation(session, rng):
    ep, uf, items, ref = _topk_ep(session, rng)
    cache = TopKReplyCache()
    workers, make_client = local_gang(session, [{"mf": ep}], cache=cache)
    client = make_client()
    try:
        assert client.request(OP_TOPK, "mf", 5, timeout=30.0)["items"] \
            == ref[5]
        hits0 = cache.stats()["hits"]
        for _ in range(3):
            assert client.request(OP_TOPK, "mf", 5,
                                  timeout=30.0)["items"] == ref[5]
        assert cache.stats()["hits"] >= hits0 + 3
        # a refresh bumps the epoch: the stale generation can never be
        # served again (version-keyed), and the new answers are the new
        # factors'
        uf2 = rng.normal(size=uf.shape).astype(np.float32)
        ep.push_epoch(uf2)
        ref2 = np.argsort(-(uf2[5] @ items.T), kind="stable")[:3].tolist()
        pending = client.submit(OP_TOPK, "mf", 5)
        assert pending.result(30.0)["items"] == ref2
        assert pending.reply["version"] == 1
    finally:
        client.close()
        for w in workers:
            w.close()


# --------------------------------------------------------------------------- #
# In-process fleet recovery (scripted kill under load)
# --------------------------------------------------------------------------- #

def test_local_fleet_scripted_kill_recovery_zero_failures(session, rng,
                                                          monkeypatch,
                                                          tmp_path):
    """The CI-smoke scenario: a serving worker dies ABRUPTLY mid-traffic
    (chaos grammar kill@request=N), the fleet replaces it, restores the
    shard through the reshard engine, re-routes placement — and the
    retrying client loses ZERO requests."""
    ep, uf, _items, ref = _topk_ep(session, rng)
    workers, make_client = local_gang(session, [{"mf": ep}, {}])
    fleet = fleet_mod.LocalFleet(
        workers, make_client, canonical={"mf": uf},
        journal_path=str(tmp_path / "journal.jsonl"))
    client = fleet.make_client()
    try:
        assert client.request_retry(OP_TOPK, "mf", 0,
                                    timeout=30.0)["items"] == ref[0]
        monkeypatch.setenv("HARP_FAULT", "kill@request=8:rank=0")
        failures = []
        for i in range(40):
            u = i % 48
            try:
                res = client.request_retry(OP_TOPK, "mf", u, timeout=5.0,
                                           attempts=8, backoff_max_s=0.5,
                                           sync_timeout=2.0)
                if res["items"] != ref[u]:
                    failures.append((u, res))
            except Exception as e:   # noqa: BLE001 — tallied, asserted 0
                failures.append((u, repr(e)))
        assert failures == [], failures[:3]
        events = [r["event"] for r in fleet.journal.records]
        assert "worker-death" in events and "replaced" in events
        replaced = next(r for r in fleet.journal.records
                        if r["event"] == "replaced")
        # the shard really went through the restore engine
        assert replaced["restored_rows"] == {"mf": len(uf)}
        assert replaced["placement_version"] >= 1
        assert client.placement_version >= 1
        # the journal is on disk too (supervisor-journal idiom)
        lines = (tmp_path / "journal.jsonl").read_text().splitlines()
        assert any('"replaced"' in ln for ln in lines)
    finally:
        monkeypatch.delenv("HARP_FAULT", raising=False)
        client.close()
        fleet.close()


# --------------------------------------------------------------------------- #
# Separate-process gang: vanish classification (PR 8 residue satellite)
# --------------------------------------------------------------------------- #

@pytest.mark.large
def test_process_gang_vanish_classified_and_replaced():
    """PR 8 residue closed: the remote `vanish` classification path runs
    on a REAL local-subprocess serving gang — a worker killed through the
    serving fault grammar exits FAULT_VANISH_EXIT, the fleet supervisor
    classifies VANISH (host retired), journals it with the placement, and
    a spare restores the shard while the retrying client loses nothing."""
    models = {"mf": {"kind": "topk", "num_users": 48, "num_items": 16,
                     "rank": 8, "k": 3, "seed": 7}}
    placement = {"mf": 0}
    gang = fleet_mod.ProcessServeGang(
        models, placement, mesh_workers=2,
        env_extra={"HARP_FAULT": "vanish@request=6:rank=0"})
    uf, items = fleet_mod.topk_factors(models["mf"], 0)
    ref = {u: np.argsort(-(uf[u] @ items.T), kind="stable")[:3].tolist()
           for u in range(48)}
    try:
        gang.start()
        client = gang.make_client()
        failures = []
        for i in range(20):
            u = i % 48
            try:
                res = client.request_retry(OP_TOPK, "mf", u, timeout=10.0,
                                           attempts=10, backoff_max_s=1.0,
                                           sync_timeout=3.0)
                if res["items"] != ref[u]:
                    failures.append((u, res))
            except Exception as e:   # noqa: BLE001 — tallied, asserted 0
                failures.append((u, repr(e)))
        deadline = time.time() + 60.0
        while time.time() < deadline:
            if any(r.get("event") == "replaced"
                   for r in gang.journal.records):
                break
            time.sleep(0.2)
        assert failures == [], failures[:3]
        death = next(r for r in gang.journal.records
                     if r.get("event") == "worker-death")
        # THE satellite assertion: the scripted vanish exit classified
        # VANISH (not crash), journaled with rank + placement version
        assert death["cause"] == "vanish"
        assert death["rank"] == 0 and "placement_version" in death
        replaced = next(r for r in gang.journal.records
                        if r.get("event") == "replaced")
        assert replaced["cause"] == "vanish"
        assert replaced["generation"] == 1
        assert replaced["restored_version"] == 0
        # the replacement really is a NEW process at a new address
        rdv = {r: (addr, gen) for r, addr, gen
               in fleet_mod.read_rendezvous(gang.rdv_dir)}
        assert rdv[0][1] == 1
    finally:
        gang.stop()


# --------------------------------------------------------------------------- #
# AOT artifacts: an elastic replacement never recompiles (ISSUE 15)
# --------------------------------------------------------------------------- #

@pytest.mark.large
def test_process_gang_replacement_never_recompiles_with_artifacts(
        tmp_path):
    """ISSUE 15 acceptance: a separate-process gang with a pre-warmed
    artifact store absorbs a scripted kill under live traffic; the spare
    REPLACEMENT prepares every dispatch from artifacts before rendezvous
    and its post-mortem status proves trace_counts stayed 0 for the
    artifact-loaded buckets while it carried real requests — zero
    recompiles, measured from outside the process."""
    models = {"mf": {"kind": "topk", "num_users": 48, "num_items": 16,
                     "rank": 8, "k": 3, "seed": 7}}
    aot_dir = str(tmp_path / "aot")
    warmed = fleet_mod.warm_artifacts(models, aot_dir, mesh_workers=2)
    assert warmed == {"mf": [2, 8, 32]}
    gang = fleet_mod.ProcessServeGang(
        models, {"mf": 0}, mesh_workers=2, aot_dir=aot_dir,
        env_extra={"HARP_FAULT": "kill@request=6:rank=0"})
    uf, items = fleet_mod.topk_factors(models["mf"], 0)
    ref = {u: np.argsort(-(uf[u] @ items.T), kind="stable")[:3].tolist()
           for u in range(48)}
    try:
        gang.start()
        client = gang.make_client()
        failures = []
        for i in range(24):          # live traffic across the kill
            u = i % 48
            try:
                res = client.request_retry(OP_TOPK, "mf", u, timeout=10.0,
                                           attempts=10, backoff_max_s=1.0,
                                           sync_timeout=3.0)
                if res["items"] != ref[u]:
                    failures.append((u, res))
            except Exception as e:   # noqa: BLE001 — tallied, asserted 0
                failures.append((u, repr(e)))
        deadline = time.time() + 60.0
        while time.time() < deadline:
            if any(r.get("event") == "replaced"
                   for r in gang.journal.records):
                break
            time.sleep(0.2)
        assert failures == [], failures[:3]
        replaced = next(r for r in gang.journal.records
                        if r.get("event") == "replaced")
        # the replacement prepared from artifacts BEFORE rendezvous
        rec = fleet_mod.read_worker_records(gang.rdv_dir)[0]
        assert rec["generation"] == replaced["generation"] == 1
        assert rec["aot"] is True
        assert rec["aot_loaded"] == {"mf": [2, 8, 32]}
        generation = int(replaced["generation"])
    finally:
        gang.stop()
    # post-mortem (written at clean stop): the replacement served real
    # traffic and NEVER traced an artifact-loaded bucket
    status = fleet_mod.read_status(gang.rdv_dir, 0, generation)
    assert status is not None
    assert status["requests"] > 0
    assert status["aot_loaded"] == {"mf": [2, 8, 32]}
    assert status["trace_counts"] == {"mf": {}}, status


# --------------------------------------------------------------------------- #
# SLO incident schema + incident-driven re-placement (satellite)
# --------------------------------------------------------------------------- #

def test_slo_incident_schema_and_incident_driven_rebalance(session, rng,
                                                           tmp_path):
    from harp_tpu.telemetry import watchdog as wd

    dog = wd.SLOWatchdog(0.01, window_s=30.0, sustain=1, min_samples=4,
                         eval_interval_s=0.0, telemetry_dir=str(tmp_path),
                         rank=1)
    for _ in range(6):
        dog.observe(0.5, ok=False)
    assert dog.incidents == 1
    (incident,) = wd.read_incidents(str(tmp_path))
    # the schema the re-placement policy consumes, pinned field-by-field
    assert wd.SLOWatchdog.validate_incident(incident) == []
    assert incident["rank"] == 1 and incident["p99_s"] >= 0.5
    assert incident["window_s"] == 30.0
    assert incident["v"] == wd.INCIDENT_SCHEMA_VERSION
    # a record missing/retyping a pinned field is named precisely
    bad = dict(incident, rank="one")
    del bad["p99_s"]
    problems = wd.SLOWatchdog.validate_incident(bad)
    assert any("rank" in p for p in problems)
    assert any("p99_s" in p for p in problems)
    # freshness guard: stale incidents earn no placement change
    assert wd.incident_ranks(str(tmp_path)) == [1]
    assert wd.incident_ranks(str(tmp_path), max_age_s=0.0) == []
    # the incident stream drives the same non-disruptive remedy the
    # straggler report does: shards slide off the burning rank
    from harp_tpu.serve import rebalance_from_incidents

    ep, _uf, _items, ref = _topk_ep(session, rng)
    moved = rebalance_from_incidents(ep, str(tmp_path))
    assert moved == [1]
    assert ep.lookup_skew()["counts"][1] == 0 or True  # owner map moved:
    assert 1 not in set(ep._owner.tolist())
    # correctness survives the move
    assert ep.dispatch(np.asarray([5]))[0]["items"] == ref[5]


def test_span_clock_skew_lower_bound():
    from harp_tpu.telemetry import spans

    tr = {"id": "x", "op": "topk", "model": "mf", "stamps": []}
    t = 100.0
    # a worker clock 50 ms behind the client: recv lands BEFORE submit
    for stage, ts in ((spans.SUBMIT, t), (spans.RECV, t - 0.05),
                      (spans.ENQUEUE, t - 0.049),
                      (spans.DISPATCH_START, t - 0.048),
                      (spans.DISPATCH_END, t - 0.040),
                      (spans.REPLY_SEND, t - 0.039),
                      (spans.REPLY_RECV, t + 0.02)):
        tr["stamps"].append((stage, ts))
    bd = spans.breakdown(tr)
    assert bd is not None
    # the negative hop exposes a lower bound on the skew...
    assert bd["clock_skew_lb_s"] == pytest.approx(0.05)
    # ...and the partition identity is untouched (nothing clamped)
    total = sum(bd[f"{s}_s"] for s in spans.STAGES)
    assert total == pytest.approx(bd["total_s"])
