"""The multi-process gang test — L3 bootstrap actually executed.

Reference parity: collective/Driver.java:93 + depl/Depl.java:36 launched one JVM
per worker over ssh and ran each collective's standalone main() as the
integration suite; MapCollectiveContainerLauncherImpl.java:294-331 provided the
rendezvous. Here the parent spawns 2 REAL OS processes, each with 4 virtual CPU
devices; they rendezvous through the jax.distributed coordinator (the YARN-AM
replacement) and run the full smoke routine in harp_tpu/parallel/mp_smoke.py:
cross-process collectives, one K-means iteration, the multi-process event
branches, session.barrier(), and a clean shutdown.

This intentionally runs OUTSIDE the in-process 8-device mesh the rest of the
suite uses: it is the only test that executes distributed.initialize/shutdown,
the events MESSAGE/COLLECTIVE multihost paths, and barrier()'s multihost branch.
"""

import os

from harp_tpu.parallel import mp_smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_gang_runs_collectives_and_kmeans():
    outs = mp_smoke.spawn_gang(num_processes=2, devices_per_process=4,
                               repo_root=REPO)
    assert len(outs) == 2


def test_nodes_file_launcher_runs_the_gang(tmp_path):
    """The depl/ nodes-file launcher: parse the reference's format (#rack
    headers + hostnames — the test_nodes fixture shape), launch one process
    per node with the gang env, and run the full smoke routine."""
    from harp_tpu.parallel import launch

    nodes_file = tmp_path / "nodes"
    nodes_file.write_text("#0\nlocalhost\n#1\n127.0.0.1\n")
    nodes = launch.parse_nodes_file(str(nodes_file))
    assert [n.rack for n in nodes] == [0, 1]
    assert len(nodes) == 2

    old = os.getcwd()
    os.chdir(REPO)
    try:
        # the real entry point's --smoke branch (Driver standalone-test mode)
        rc = launch.main([str(nodes_file), "--smoke"])
    finally:
        os.chdir(old)
    assert rc == 0


def test_three_process_gang():
    """A wider gang (3 procs × 2 devices): the ring schedules, KV rendezvous
    namespaces, and session event plane must not be 2-process artifacts.
    Generous timeout: three members compile concurrently on ONE shared host
    core, so member skew is minutes, not seconds."""
    outs = mp_smoke.spawn_gang(num_processes=3, devices_per_process=2,
                               timeout=600.0, repo_root=REPO)
    assert len(outs) == 3


def test_gang_launcher_runs_cli_training(tmp_path):
    """End-to-end depl parity: the nodes-file launcher runs a REAL training
    command, the run.py subcommand joins the gang (distributed.initialize
    reads the launcher's HARP_* env), and ONE distributed K-means trains
    over the gang's global mesh — not N independent copies."""
    import sys

    from harp_tpu.parallel import launch

    work = tmp_path / "km"
    cmd = [sys.executable, "-m", "harp_tpu.run", "kmeans", "--cpu-mesh",
           "--num-workers", "2", "--num-points", "512", "--num-centroids",
           "4", "--dim", "8", "--iterations", "4", "--work-dir", str(work),
           "--save-every", "2"]
    nodes = [launch.Node("localhost", 0) for _ in range(2)]
    results = launch.launch(nodes, cmd, timeout=420.0, cwd=REPO)
    for rc, out in results:
        assert rc == 0, out[-2000:]
        # the session spans the gang: 2 members x 2 virtual devices
        assert "workers=4" in out, out[-500:]
    # master (process 0) wrote the model and the checkpoints ONCE (gang
    # members skip writes — the shared-work-dir contract)
    assert (work / "centroids.csv").exists()
    assert (work / "ckpt").is_dir()
    # second launch: the checkpoint already covers every iteration — every
    # member resumes cleanly instead of re-training or tearing the dir
    results = launch.launch(nodes, cmd, timeout=420.0, cwd=REPO)
    for rc, out in results:
        assert rc == 0, out[-2000:]
        assert "fully resumed" in out, out[-500:]
