"""The multi-process gang test — L3 bootstrap actually executed.

Reference parity: collective/Driver.java:93 + depl/Depl.java:36 launched one JVM
per worker over ssh and ran each collective's standalone main() as the
integration suite; MapCollectiveContainerLauncherImpl.java:294-331 provided the
rendezvous. Here the parent spawns 2 REAL OS processes, each with 4 virtual CPU
devices; they rendezvous through the jax.distributed coordinator (the YARN-AM
replacement) and run the full smoke routine in harp_tpu/parallel/mp_smoke.py:
cross-process collectives, one K-means iteration, the multi-process event
branches, session.barrier(), and a clean shutdown.

This intentionally runs OUTSIDE the in-process 8-device mesh the rest of the
suite uses: it is the only test that executes distributed.initialize/shutdown,
the events MESSAGE/COLLECTIVE multihost paths, and barrier()'s multihost branch.
"""

import os

from harp_tpu.parallel import mp_smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_gang_runs_collectives_and_kmeans():
    outs = mp_smoke.spawn_gang(num_processes=2, devices_per_process=4,
                               repo_root=REPO)
    assert len(outs) == 2
