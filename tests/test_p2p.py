"""P2P event transport tests (parallel/p2p.py — SyncClient/Server residual).

In-process pairs with explicit peer maps; the REAL 2-process gang exercises
the KV-store rendezvous path in mp_smoke (tests/test_multiprocess.py).
"""

import numpy as np
import pytest

from harp_tpu.parallel.events import EventClient, EventQueue, EventType
from harp_tpu.parallel.p2p import P2PTransport


def _pair():
    q0, q1 = EventQueue(), EventQueue()
    t0 = P2PTransport(q0, rank=0, peers={})
    t1 = P2PTransport(q1, rank=1, peers={0: t0.address})
    t0._peers[1] = t1.address
    return q0, q1, t0, t1


def test_p2p_bidirectional_and_ordering():
    q0, q1, t0, t1 = _pair()
    try:
        for i in range(50):
            t0.send(1, {"i": i})
        t1.send(0, "reply")
        # TCP per-connection ordering: the 50 messages arrive in send order
        for i in range(50):
            ev = q1.wait(timeout=30.0)
            assert ev is not None and ev.type is EventType.MESSAGE
            assert ev.source == 0 and ev.payload == {"i": i}
        ev = q0.wait(timeout=30.0)
        assert ev is not None and ev.source == 1 and ev.payload == "reply"
        assert len(q0) == 0 and len(q1) == 0
    finally:
        t0.close()
        t1.close()


def test_p2p_large_payload_and_self_send():
    q0, q1, t0, t1 = _pair()
    try:
        blob = np.arange(1 << 18, dtype=np.int64)      # 2 MB, framed in one go
        t0.send(1, blob)
        ev = q1.wait(timeout=30.0)
        np.testing.assert_array_equal(ev.payload, blob)
        t0.send(0, "loopback")                          # self-send: no socket
        ev = q0.wait(timeout=5.0)
        assert ev.payload == "loopback" and ev.source == 0
    finally:
        t0.close()
        t1.close()


def test_p2p_unknown_dest_and_closed():
    q = EventQueue()
    t = P2PTransport(q, rank=0, peers={})
    with pytest.raises(KeyError):
        t.send(7, "nope")
    t.close()
    with pytest.raises(ConnectionError):
        t.send(0, "after-close")


def test_event_client_uses_transport():
    q0, q1, t0, t1 = _pair()
    try:
        c0 = EventClient(q0, worker_id=0, transport=t0)
        c0.send_message(dest=1, payload="via-transport")
        ev = q1.wait(timeout=30.0)
        assert ev is not None and ev.payload == "via-transport"
        # legacy gang-wide call pattern: a non-source caller is a no-op
        c1 = EventClient(q1, worker_id=1, transport=t1)
        c1.send_message(dest=0, payload="not-mine", source=0)
        assert q0.get() is None
    finally:
        t0.close()
        t1.close()


def test_p2p_reconnects_after_peer_restart():
    """ConnPool parity: a dead pooled connection is dropped and the send
    retried on a fresh one."""
    q0, q1a = EventQueue(), EventQueue()
    t0 = P2PTransport(q0, rank=0, peers={})
    t1a = P2PTransport(q1a, rank=1, peers={0: t0.address})
    t0._peers[1] = t1a.address
    t0.send(1, "first")
    assert q1a.wait(timeout=30.0).payload == "first"
    t1a.close()
    # peer restarts (new ephemeral port); t0's pooled conn is now stale — the
    # readability probe must detect the FIN and the retry path reconnect
    q1b = EventQueue()
    t1b = P2PTransport(q1b, rank=1, peers={0: t0.address})
    t0._peers[1] = t1b.address
    import time

    time.sleep(0.2)            # let the FIN reach t0's pooled socket
    try:
        t0.send(1, "second")
        ev = q1b.wait(timeout=30.0)
        assert ev is not None and ev.payload == "second"
    finally:
        t0.close()
        t1b.close()


def test_p2p_binds_loopback_without_gang():
    """Coordinator-less explicit-peer transports must not listen on all
    interfaces (advisor r3: an open unauthenticated pickle port is ACE)."""
    q = EventQueue()
    t = P2PTransport(q, rank=0, peers={})
    try:
        assert t.address[0] == "127.0.0.1"
        assert t._server.getsockname()[0] == "127.0.0.1"
    finally:
        t.close()


def test_p2p_hmac_handshake_accepts_and_rejects():
    """Authenticated pair delivers; a wrong-secret client and a raw socket
    that sends frames without answering the challenge are both rejected
    before any frame is unpickled."""
    import socket as sk
    import pickle
    import struct

    q0, q1 = EventQueue(), EventQueue()
    t0 = P2PTransport(q0, rank=0, peers={}, secret=b"gang-secret")
    t1 = P2PTransport(q1, rank=1, peers={0: t0.address}, secret=b"gang-secret")
    t0._peers[1] = t1.address
    try:
        t1.send(0, {"auth": True})
        ev = q0.wait(timeout=30.0)
        assert ev is not None and ev.payload == {"auth": True}

        # wrong secret: the server withholds its handshake ack, so the send
        # DETERMINISTICALLY raises after retries — never silent frame loss
        q_bad = EventQueue()
        t_bad = P2PTransport(q_bad, rank=2, peers={0: t0.address},
                             secret=b"wrong", retries=2, retry_sleep_s=0.05,
                             connect_timeout_s=2.0)
        with pytest.raises(ConnectionError):
            t_bad.send(0, "evil")
        t_bad.close()

        # mixed-auth misconfiguration (ADVICE r4): a PLAIN client against
        # this authenticated server must fail FAST with the mode-mismatch
        # error, not hang until connect_timeout waiting on frames/MACs
        import time as _time

        q_plain = EventQueue()
        t_plain = P2PTransport(q_plain, rank=3, peers={0: t0.address},
                               secret=None, retries=1,
                               connect_timeout_s=30.0)
        t_start = _time.perf_counter()
        with pytest.raises(ConnectionError, match="auth-mode mismatch"):
            t_plain.send(0, "plain-into-auth")
        assert _time.perf_counter() - t_start < 5.0   # fast, not timeout
        t_plain.close()

        # raw unauthenticated frame: never reaches the queue
        body = pickle.dumps((9, "raw-evil"))
        with sk.create_connection(t0.address, timeout=5.0) as raw:
            raw.sendall(struct.pack(">Q", len(body)) + body)
        import time

        time.sleep(0.5)
        assert q0.get() is None
    finally:
        t0.close()
        t1.close()


def test_p2p_concurrent_sends_do_not_interleave():
    """Frames from concurrent senders to one dest must never interleave on
    the pooled connection (per-dest send lock)."""
    import threading

    q0, q1, t0, t1 = _pair()
    try:
        blob = bytes(256 * 1024)            # larger than a socket buffer
        n_threads, per_thread = 4, 8

        def sender(tid):
            for i in range(per_thread):
                t0.send(1, {"tid": tid, "i": i, "blob": blob})

        threads = [threading.Thread(target=sender, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        seen = set()
        for _ in range(n_threads * per_thread):
            ev = q1.wait(timeout=30.0)
            assert ev is not None and len(ev.payload["blob"]) == len(blob)
            seen.add((ev.payload["tid"], ev.payload["i"]))
        assert len(seen) == n_threads * per_thread
    finally:
        t0.close()
        t1.close()
