"""Fused ring DMA engine tests (ISSUE 9).

The engine's whole contract is "moves bytes, never rounds them": on the
8-worker CPU mesh every fused schedule must be BITWISE the ppermute
schedule (the TPU kernels share the same semantics — the driver's on-chip
ring_dma_overlap bench run exercises those). Plus the budget-gate contract:
fused hops trace as the tagged ``fused_dma`` kind, and a fused target
silently reverting to bare ppermute fails JL201/JL203.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.collectives import lax_ops, rotation, table_ops
from harp_tpu.ops import ring_dma

W = 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- engine primitives ------------------------------------------------------


@pytest.mark.parametrize("shift", [1, 2, -1])
def test_fused_hop_matches_rotate_bitwise(session, rng, shift):
    x = rng.standard_normal((W, 5, 3)).astype(np.float32)
    fused = session.run(lambda a: ring_dma.hop(a, shift), session.scatter(x),
                        in_specs=(session.shard(),),
                        out_specs=session.shard())
    ref = session.run(lambda a: lax_ops.rotate(a, shift), session.scatter(x),
                      in_specs=(session.shard(),), out_specs=session.shard())
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(ref))


def test_fused_hop_is_exact_for_int_leaves(session):
    x = np.arange(W * 4, dtype=np.int32).reshape(W, 4)
    fused = session.run(lambda a: ring_dma.hop(a, 1), session.scatter(x),
                        in_specs=(session.shard(),),
                        out_specs=session.shard())
    np.testing.assert_array_equal(np.asarray(fused), np.roll(x, 1, axis=0))


def test_ring_allgather_matches_all_gather_bitwise(session, rng):
    x = rng.standard_normal((W * 2, 3)).astype(np.float32)
    fused = session.run(lambda a: ring_dma.ring_allgather(a)[None],
                        session.scatter(x), in_specs=(session.shard(),),
                        out_specs=session.replicate())
    ref = session.run(
        lambda a: jax.lax.all_gather(a, "workers", tiled=True)[None],
        session.scatter(x), in_specs=(session.shard(),),
        out_specs=session.replicate())
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(ref))


def test_lax_ops_allgather_fused_tiled_and_untiled(session, rng):
    x = rng.standard_normal((W * 2, 3)).astype(np.float32)
    for tiled in (True, False):
        fused = session.run(
            lambda a: lax_ops.allgather(a, tiled=tiled, fused=True)[None],
            session.scatter(x), in_specs=(session.shard(),),
            out_specs=session.replicate())
        ref = session.run(
            lambda a: lax_ops.allgather(a, tiled=tiled)[None],
            session.scatter(x), in_specs=(session.shard(),),
            out_specs=session.replicate())
        np.testing.assert_array_equal(np.asarray(fused), np.asarray(ref))


def test_table_allgather_fused_with_partitioner(session, rng):
    from harp_tpu.combiner import SUM
    from harp_tpu.partitioner import ModuloPartitioner
    from harp_tpu.table import Dist, Table

    data = rng.standard_normal((W, 4)).astype(np.float32)
    part = ModuloPartitioner(W, W)

    def gather(fused):
        def f(x):
            t = Table(x, SUM, Dist.SHARDED, W, W, "t")
            return table_ops.allgather(t, part, fused=fused).data[None]

        return session.run(f, session.scatter(data),
                           in_specs=(session.shard(),),
                           out_specs=session.replicate())

    np.testing.assert_array_equal(np.asarray(gather(True)),
                                  np.asarray(gather(False)))


# -- rotation schedules -----------------------------------------------------


def test_rotate_scan_fused_bitwise_mixed_tree(session, rng):
    """Float leaves ride the engine, int leaves the lax path — the fused
    trajectory (blocks, carry) must equal the unfused one bitwise."""
    f = rng.standard_normal((W, 4)).astype(np.float32)
    i = np.arange(W, dtype=np.int32).reshape(W, 1)

    def body(c, blk, t):
        bf, bi = blk
        return c + jnp.sum(bf) + jnp.sum(bi), (bf * 1.001 + 0.1, bi + 1)

    def run(fused):
        def fn(bf, bi):
            c, (of, oi) = rotation.rotate_scan(
                body, jnp.zeros(()), (bf, bi), W, fused_dma=fused)
            return c[None], of, oi

        return session.spmd(fn, in_specs=(session.shard(),) * 2,
                            out_specs=(session.shard(),) * 3)(f, i)

    for a, b in zip(run(False), run(True)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipelined_rotation_fused_bitwise(session, rng):
    a = rng.standard_normal((W, 3)).astype(np.float32)
    b = rng.standard_normal((W, 3)).astype(np.float32)

    def body(c, blk, t):
        return c + jnp.sum(blk), blk + 0.5

    def run(fused):
        def fn(ba, bb):
            c, sa, sb = rotation.pipelined_rotation(
                body, jnp.zeros(()), ba, bb, 2 * W, fused_dma=fused)
            return c[None], sa, sb

        return session.spmd(fn, in_specs=(session.shard(),) * 2,
                            out_specs=(session.shard(),) * 3)(a, b)

    for x, y in zip(run(False), run(True)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_rotate_scan_ef_state_threads_through(session, rng):
    """ef_state in → updated ef_state out, and re-feeding it continues the
    EF chain (the LDA epoch-carry contract)."""
    from harp_tpu.collectives import quantize

    comm = quantize.CommConfig(quant="int8")
    x = rng.standard_normal((W, 256)).astype(np.float32)

    def body(c, blk, t):
        return c, blk

    def fn(bx):
        res = rotation.ef_zero(bx)
        _, out1, res1 = rotation.rotate_scan(body, jnp.zeros(()), bx, W,
                                             comm=comm, ef_state=res)
        _, out2, res2 = rotation.rotate_scan(body, jnp.zeros(()), out1, W,
                                             comm=comm, ef_state=res1)
        return out2, res1, res2

    out2, res1, res2 = session.spmd(
        fn, in_specs=(session.shard(),),
        out_specs=(session.shard(),) * 3)(x)
    # residuals are live (nonzero) and shaped like the block
    assert np.asarray(res1).shape == x.shape
    assert np.abs(np.asarray(res1)).max() > 0
    # after 2 full EF rings the block tracks the exact one within codec tol
    np.testing.assert_allclose(np.asarray(out2), x, atol=0.2)


# -- ring attention ---------------------------------------------------------


@pytest.mark.parametrize("l_local,causal,flash", [
    (8, True, False),          # aligned, XLA hop
    (8, False, False),
    (7, True, True),           # PRIME local length through the flash kernel
    (16, False, True),         # aligned through the flash kernel
])
def test_ring_attention_fused_parity(session, rng, l_local, causal, flash):
    from harp_tpu.parallel import ring_attention as ra

    h, dh = 4, 8
    l_full = W * l_local
    q = rng.standard_normal((l_full, h, dh)).astype(np.float32)
    k = rng.standard_normal((l_full, h, dh)).astype(np.float32)
    v = rng.standard_normal((l_full, h, dh)).astype(np.float32)
    ref = np.stack([np.asarray(ra.reference_attention(
        q[:, i], k[:, i], v[:, i], causal)) for i in range(h)], axis=1)
    outs = {}
    for fused in (False, True):
        out = session.run(
            lambda a, b, c: ra.ring_attention_mha(
                a, b, c, causal, use_flash=flash, interpret=flash,
                fused_dma=fused),
            session.scatter(jnp.asarray(q)), session.scatter(jnp.asarray(k)),
            session.scatter(jnp.asarray(v)),
            in_specs=(session.shard(),) * 3, out_specs=session.shard())
        outs[fused] = np.asarray(out)
        np.testing.assert_allclose(outs[fused], ref, rtol=2e-4, atol=2e-5)
    # and the two transports agree bitwise with each other
    np.testing.assert_array_equal(outs[False], outs[True])


def test_flash_ring_hop_rejects_bad_modes():
    from harp_tpu.ops import pallas_kernels as pk

    x = jnp.zeros((16, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="return_stats"):
        pk.flash_attention_pallas(x, x, x, ring_hop=True)
    with pytest.raises(ValueError, match="interpret"):
        pk.flash_attention_pallas(x, x, x, ring_hop=True,
                                  return_stats=True, interpret=True)


# -- model-level fused parity ----------------------------------------------


def test_sgd_mf_fused_bitwise(session, rng):
    from harp_tpu.models import sgd_mf

    n = 400
    rows = rng.integers(0, 64, size=n)
    cols = rng.integers(0, 48, size=n)
    vals = rng.normal(size=n).astype(np.float32)
    for ns in (1, 2):
        outs = []
        for fused in (False, True):
            m = sgd_mf.SGDMF(session, sgd_mf.SGDMFConfig(
                rank=8, epochs=3, minibatches_per_hop=2, num_slices=ns,
                fused_dma=fused))
            outs.append(m.fit(rows, cols, vals, 64, 48))
        for a, b in zip(outs[0], outs[1]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_lda_fused_bitwise(session, rng):
    from harp_tpu.models import lda

    docs = rng.integers(0, 96, size=(16, 12))
    for ns in (1, 2):
        outs = []
        for fused in (False, True):
            m = lda.LDA(session, lda.LDAConfig(
                num_topics=4, vocab=96, epochs=3, num_model_slices=ns,
                fused_dma=fused))
            outs.append(m.fit(docs, seed=0))
        for a, b in zip(outs[0], outs[1]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_lda_quant_wt_convergence_parity_cvb0(session, rng):
    """The satellite quantized wt-block rotation: CVB0 is deterministic, so
    the f32-vs-quantized ll delta is PURE wire quantization error. The
    whole (vpb, K) count block rides int8 with EF in the epoch carry —
    tolerance is accordingly looser than the topic-total-only quant test
    (tiny tier-1 blocks quantize coarsely), and the chain must still
    IMPROVE like the f32 one."""
    from harp_tpu.models import lda

    docs = rng.integers(0, 96, size=(16, 12))
    base = lda.LDA(session, lda.LDAConfig(num_topics=4, vocab=96, epochs=4,
                                          method="cvb0"))
    _, _, ll0 = base.fit(docs, seed=0)
    ll0 = np.asarray(ll0)
    for codec in ("int8", "bf16"):
        for ns in (1, 2):
            m = lda.LDA(session, lda.LDAConfig(
                num_topics=4, vocab=96, epochs=4, method="cvb0",
                quant=codec, quant_wt=True, num_model_slices=ns))
            _, _, ll = m.fit(docs, seed=0)
            ll = np.asarray(ll)
            # trajectory parity: pinned at 20% relative (measured r10:
            # 1-13% across codecs/slice counts at this tier-1 shape — the
            # (12, 4) tier-1 wt blocks quantize coarsely; bigger blocks
            # only shrink the relative error)
            np.testing.assert_allclose(ll, ll0, rtol=0.2)


def test_lda_quant_wt_requires_quant(session):
    from harp_tpu.models import lda

    with pytest.raises(ValueError, match="quant_wt"):
        lda.LDA(session, lda.LDAConfig(num_topics=4, vocab=96,
                                       quant_wt=True))


# -- budget gate: fused targets pin their bytes -----------------------------


def test_fused_hop_name_contract():
    from tools.jaxlint import checkers_jaxpr

    assert checkers_jaxpr.FUSED_HOP_PREFIX == ring_dma.FUSED_HOP_NAME


def test_fused_trace_targets_pin_fused_dma_bytes(session):
    from tools.jaxlint import checkers_jaxpr

    counts, dtype_bad, nbytes = checkers_jaxpr.trace_target("lda_cgs_fused")
    assert dtype_bad == []
    # the wt hop is booked as fused_dma, NOT ppermute...
    assert counts.get("fused_dma", 0) >= 1
    assert counts.get("ppermute", 0) == 0
    # ...and moves exactly the bytes the unfused twin's ppermute moved
    counts0, _, nbytes0 = checkers_jaxpr.trace_target("lda_cgs")
    assert nbytes["fused_dma"] == nbytes0["ppermute"]
    assert sum(nbytes.values()) == sum(nbytes0.values())
    # the committed manifest carries the explicit fused row
    with open(os.path.join(REPO, checkers_jaxpr.BUDGET_FILE)) as f:
        manifest = json.load(f)
    row = manifest["targets"]["lda_cgs_fused"]
    assert row["fused_dma_bytes_per_step"] == nbytes["fused_dma"] > 0
    # quantized-wt satellite: its rotation wire sits well below the f32 one
    quant_row = manifest["targets"]["lda_cgs_quantwt_int8"]
    assert quant_row["bytes_per_step"] < row["bytes_per_step"]


def test_fused_revert_to_ppermute_fails_budget_gate():
    """ISSUE 9 acceptance: a fused target silently reverting to ppermute
    (the transport swap with identical totals) must fail the gate."""
    from tools.jaxlint import checkers_jaxpr

    with open(os.path.join(REPO, checkers_jaxpr.BUDGET_FILE)) as f:
        manifest = json.load(f)
    row = manifest["targets"]["lda_cgs_fused"]
    counts = dict(row["collectives"])
    nbytes = dict(row["bytes_by_kind"])
    # simulate the revert: the fused hop becomes a bare ppermute — same
    # bytes, same total, different kind
    counts["ppermute"] = counts.pop("fused_dma")
    nbytes["ppermute"] = nbytes.pop("fused_dma")
    traced = {"lda_cgs_fused": (counts, [], nbytes)}
    findings = checkers_jaxpr.check_budget(REPO, traced)
    mine = [f for f in findings if f.func == "lda_cgs_fused"]
    assert any(f.code == "JL201" for f in mine), mine   # kind drift
    assert any(f.code == "JL203" for f in mine), mine   # byte drift
    # and a manifest row LACKING the fused field while the trace moves
    # fused bytes is itself a finding
    legacy = {k: v for k, v in row.items()
              if k != "fused_dma_bytes_per_step"}
    import copy
    doctored = copy.deepcopy(manifest)
    doctored["targets"]["lda_cgs_fused"] = legacy
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        os.makedirs(os.path.join(td, "tools"))
        with open(os.path.join(td, checkers_jaxpr.BUDGET_FILE), "w") as f:
            json.dump(doctored, f)
        traced_ok = {"lda_cgs_fused": (dict(row["collectives"]), [],
                                       dict(row["bytes_by_kind"]))}
        findings = checkers_jaxpr.check_budget(td, traced_ok)
        assert any(f.code == "JL203"
                   and "fused_dma_bytes_per_step" in f.message
                   for f in findings if f.func == "lda_cgs_fused")


# -- bench row schemas ------------------------------------------------------


def test_ring_overlap_row_schema(session):
    from harp_tpu.benchmark import ring_overlap

    row = ring_overlap.measure(l_local=8, heads=2, dh=4, reps=1,
                               use_flash=False)
    for key in ("workers", "unfused_s", "no_rotation_s", "fused_s",
                "hop_share", "fused_speedup", "fused_hidden_fraction"):
        assert key in row, key
    assert row["workers"] == W
    assert 0.0 <= row["fused_hidden_fraction"] <= 1.0


def test_lda_overlap_fused_row_schema(session):
    from harp_tpu.benchmark import lda_overlap

    row = lda_overlap.measure(num_docs=16, vocab=96, num_topics=4,
                              doc_len=8, epochs=2, reps=1, fused=True)
    for key in ("single_s", "no_rotation_s", "two_slice_s",
                "fused_single_s", "fused_two_slice_s", "fused_speedup",
                "fused_hidden_fraction"):
        assert key in row, key
    assert 0.0 <= row["fused_hidden_fraction"] <= 1.0


def test_bench_local_carries_null_ring_dma_rows():
    with open(os.path.join(REPO, "BENCH_local.json")) as f:
        rec = json.load(f)
    assert "ring_dma_overlap" in rec
    assert "als_stage_budget" in rec
    if rec["ring_dma_overlap"] is None:
        assert "ring_dma_overlap" in rec["bench_schema_note_r10"]
    if rec["als_stage_budget"] is None:
        assert "als_stage_budget" in rec["bench_schema_note_r10"]


def test_bench_ring_dma_group_registered():
    import bench

    assert "ring_dma_overlap" in bench.ROW_GROUPS


# -- ALS stage-budget ablation ---------------------------------------------


def test_als_ablate_solve_is_identity_through_solve(session):
    from harp_tpu.models import als as als_mod

    cfg = als_mod.ALSConfig(rank=4, ablate_solve=True)
    a = jnp.stack([jnp.eye(4) * 2.0] * 3)
    b = jnp.ones((3, 4))
    out = als_mod._spd_solve(a, b, cfg)
    # identity pass-through (a real solve would return 0.5s)
    np.testing.assert_allclose(np.asarray(out), np.ones((3, 4)))
    # and the ablated model still runs end-to-end (wrong but finite)
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 32, size=200)
    cols = rng.integers(0, 24, size=200)
    vals = np.abs(rng.normal(size=200)).astype(np.float32)
    m = als_mod.ALS(session, als_mod.ALSConfig(
        rank=4, iterations=2, implicit=True, ablate_solve=True))
    _, _, rmse = m.fit(rows, cols, vals, 32, 24)
    assert np.all(np.isfinite(np.asarray(rmse)))
