"""Smoke tests for the examples/ launchers (reference: ml/java examples/ +
per-algorithm *Launcher classes run by contrib/test_scripts)."""

import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ,
           XLA_FLAGS="--xla_force_host_platform_device_count=8",
           JAX_PLATFORMS="cpu")


def test_collectives_tour_runs():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "collectives_tour.py")],
        env=ENV, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "allreduce" in out.stdout and "rotate" in out.stdout


def test_kmeans_launcher_cli(tmp_path):
    work = str(tmp_path / "km")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "kmeans_launcher.py"),
         "--cpu-mesh", "1000", "10", "20", "8", "5", work],
        env=ENV, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    cen = np.loadtxt(os.path.join(work, "centroids.csv"), delimiter=",")
    assert cen.shape == (10, 20)
    assert "cost:" in out.stdout


def _run_cmd(args, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "harp_tpu.run"] + args + ["--cpu-mesh",
                                                         "--num-workers", "8"],
        env=ENV, cwd=REPO, capture_output=True, text=True, timeout=timeout)


def test_run_kmeans_cli(tmp_path):
    out = _run_cmd(["kmeans", "--num-points", "1024", "--num-centroids", "10",
                    "--dim", "16", "--iterations", "4",
                    "--work-dir", str(tmp_path)])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "iters/s" in out.stdout and "cost" in out.stdout
    assert np.loadtxt(os.path.join(str(tmp_path), "centroids.csv"),
                      delimiter=",").shape == (10, 16)


def test_run_sgd_mf_cli_with_checkpointing(tmp_path):
    args = ["sgd_mf", "--num-users", "128", "--num-items", "96", "--density",
            "0.2", "--rank", "8", "--epochs", "6", "--save-every", "2",
            "--work-dir", str(tmp_path)]
    out = _run_cmd(args)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "M samples/s" in out.stdout
    # checkpoints written; a re-run resumes (no epochs left to run)
    ckpts = os.listdir(os.path.join(str(tmp_path), "ckpt"))
    assert any(c.startswith("step_") for c in ckpts)
    out2 = _run_cmd(args)
    assert out2.returncode == 0, out2.stderr[-2000:]


def test_run_lda_cli():
    out = _run_cmd(["lda", "--num-docs", "64", "--vocab", "48",
                    "--num-topics", "4", "--doc-len", "16", "--epochs", "3"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "M tokens/s" in out.stdout and "ll" in out.stdout


def test_run_pca_cli():
    out = _run_cmd(["pca", "--num-points", "1024", "--dim", "16",
                    "--iterations", "2"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "fits/s" in out.stdout and "eigenvalue" in out.stdout


def test_run_nn_cli():
    out = _run_cmd(["nn", "--num-points", "512", "--dim", "8",
                    "--epochs", "3", "--num-classes", "2"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "train acc" in out.stdout


def test_run_sgd_mf_cli_adaptive():
    out = _run_cmd(["sgd_mf", "--num-users", "128", "--num-items", "96",
                    "--density", "0.2", "--rank", "8", "--epochs", "6",
                    "--adaptive"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "tuned budget:" in out.stdout and "M samples/s" in out.stdout
