"""Smoke tests for the examples/ launchers (reference: ml/java examples/ +
per-algorithm *Launcher classes run by contrib/test_scripts)."""

import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ,
           XLA_FLAGS="--xla_force_host_platform_device_count=8",
           JAX_PLATFORMS="cpu")


def test_collectives_tour_runs():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "collectives_tour.py")],
        env=ENV, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "allreduce" in out.stdout and "rotate" in out.stdout


def test_analytics_tour_runs():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "analytics_tour.py")],
        env=ENV, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "ANALYTICS TOUR OK" in out.stdout


def test_kmeans_launcher_cli(tmp_path):
    work = str(tmp_path / "km")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "kmeans_launcher.py"),
         "--cpu-mesh", "1000", "10", "20", "8", "5", work],
        env=ENV, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    cen = np.loadtxt(os.path.join(work, "centroids.csv"), delimiter=",")
    assert cen.shape == (10, 20)
    assert "cost:" in out.stdout


def _run_cmd(args, timeout=300):
    return subprocess.run(
        [sys.executable, "-m", "harp_tpu.run"] + args + ["--cpu-mesh",
                                                         "--num-workers", "8"],
        env=ENV, cwd=REPO, capture_output=True, text=True, timeout=timeout)


def test_run_kmeans_cli(tmp_path):
    out = _run_cmd(["kmeans", "--num-points", "1024", "--num-centroids", "10",
                    "--dim", "16", "--iterations", "4",
                    "--work-dir", str(tmp_path)])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "iters/s" in out.stdout and "cost" in out.stdout
    assert np.loadtxt(os.path.join(str(tmp_path), "centroids.csv"),
                      delimiter=",").shape == (10, 16)


def test_run_sgd_mf_cli_with_checkpointing(tmp_path):
    args = ["sgd_mf", "--num-users", "128", "--num-items", "96", "--density",
            "0.2", "--rank", "8", "--epochs", "6", "--save-every", "2",
            "--work-dir", str(tmp_path)]
    out = _run_cmd(args)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "M samples/s" in out.stdout
    # checkpoints written; a re-run resumes (no epochs left to run)
    ckpts = os.listdir(os.path.join(str(tmp_path), "ckpt"))
    assert any(c.startswith("step_") for c in ckpts)
    out2 = _run_cmd(args)
    assert out2.returncode == 0, out2.stderr[-2000:]


def test_run_lda_cli():
    out = _run_cmd(["lda", "--num-docs", "64", "--vocab", "48",
                    "--num-topics", "4", "--doc-len", "16", "--epochs", "3"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "M tokens/s" in out.stdout and "ll" in out.stdout


def test_run_pca_cli():
    out = _run_cmd(["pca", "--num-points", "1024", "--dim", "16",
                    "--iterations", "2"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "fits/s" in out.stdout and "eigenvalue" in out.stdout


def test_run_nn_cli():
    out = _run_cmd(["nn", "--num-points", "512", "--dim", "8",
                    "--epochs", "3", "--num-classes", "2"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "train acc" in out.stdout


def test_run_sgd_mf_cli_adaptive():
    out = _run_cmd(["sgd_mf", "--num-users", "128", "--num-items", "96",
                    "--density", "0.2", "--rank", "8", "--epochs", "6",
                    "--adaptive"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "tuned budget:" in out.stdout and "M samples/s" in out.stdout


# --- round-3 launcher surface: one smoke per remaining family (VERDICT #3) -- #

import pytest


@pytest.mark.parametrize("args,expect", [
    (["als", "--num-users", "256", "--num-items", "192", "--density", "0.05",
      "--rank", "8", "--iterations", "3"], "iters/s"),
    (["ccd", "--num-users", "128", "--num-items", "96", "--density", "0.1",
      "--rank", "4", "--outer-iterations", "3"], "sweeps/s"),
    (["mds", "--num-points", "64", "--dim", "2", "--iterations", "5"],
     "stress"),
    (["pagerank", "--num-vertices", "512", "--num-edges", "2048",
      "--iterations", "5"], "delta"),
    (["subgraph", "--num-vertices", "64", "--num-edges", "256",
      "--template-size", "3", "--trials", "2"], "estimate"),
    (["subgraph", "--num-vertices", "48", "--num-edges", "128",
      "--template", "0-1,1-2,1-3", "--trials", "2"], "estimate"),
    (["svm", "--num-points", "512", "--dim", "8", "--iterations", "20"],
     "train acc"),
    (["forest", "--num-points", "512", "--dim", "8", "--depth", "3",
      "--num-trees", "2"], "train acc"),
    (["boosting", "--kind", "ada", "--num-points", "512", "--dim", "8",
      "--rounds", "4"], "train acc"),
    (["solver", "--kind", "lbfgs", "--num-points", "512", "--dim", "8",
      "--iterations", "10"], "mse"),
    (["stats", "--op", "qr", "--num-points", "512", "--dim", "16"],
     "||QR-X||"),
    (["stats", "--op", "quantiles", "--num-points", "512", "--dim", "8"],
     "quartiles"),
    (["linear", "--num-points", "512", "--dim", "8", "--l2", "0.1"],
     "mse"),
    (["classifiers", "--kind", "mlr", "--num-points", "512", "--dim", "8",
      "--num-classes", "3"], "train acc"),
    (["classifiers", "--kind", "knn", "--num-points", "512", "--dim", "8",
      "--num-classes", "2"], "train acc"),
    (["classifiers", "--kind", "em", "--num-points", "512", "--dim", "4",
      "--num-classes", "2"], "ll"),
    (["apriori", "--num-transactions", "512", "--num-items", "16"],
     "frequent itemsets"),
    (["sgxsimu", "--num-points", "2048", "--num-centroids", "8", "--dim",
      "16", "--iterations", "4", "--page-swap", "--enclave-per-thd-mb", "1",
      "--simulate"], "modeled slowdown"),
])
def test_run_family_cli(args, expect):
    out = _run_cmd(args)
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    assert expect in out.stdout, out.stdout


def test_gang_tour_example():
    """The round-3 distributed-runtime tour: gang launch -> distributed CLI
    training with checkpoints -> full resume -> fail-stop."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "gang_tour.py")],
        env=ENV, capture_output=True, text=True, timeout=700)
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    assert "gang tour OK" in out.stdout
