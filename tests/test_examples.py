"""Smoke tests for the examples/ launchers (reference: ml/java examples/ +
per-algorithm *Launcher classes run by contrib/test_scripts)."""

import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ,
           XLA_FLAGS="--xla_force_host_platform_device_count=8",
           JAX_PLATFORMS="cpu")


def test_collectives_tour_runs():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "collectives_tour.py")],
        env=ENV, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "allreduce" in out.stdout and "rotate" in out.stdout


def test_kmeans_launcher_cli(tmp_path):
    work = str(tmp_path / "km")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "kmeans_launcher.py"),
         "--cpu-mesh", "1000", "10", "20", "8", "5", work],
        env=ENV, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    cen = np.loadtxt(os.path.join(work, "centroids.csv"), delimiter=",")
    assert cen.shape == (10, 20)
    assert "cost:" in out.stdout
