"""Test harness: a deterministic 8-worker virtual mesh on CPU.

This replaces the reference's integration harness (one JVM per worker launched over
ssh by collective/Driver.java:93): every multi-worker behavior is tested in a single
process on an 8-device virtual CPU mesh, exactly how the driver validates the
multi-chip path.
"""

import os

# Must run before jax initializes a backend.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The image's sitecustomize force-selects the axon TPU backend via
# jax.config.update("jax_platforms", ...), which overrides the env var —
# override it back before any backend initializes.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


@pytest.fixture(scope="session")
def session():
    from harp_tpu.session import HarpSession

    assert len(jax.devices()) == 8, "virtual device mesh not active"
    return HarpSession(num_workers=8)


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "large: larger-scale behavior tests (~1 min total); "
        "deselect with -m 'not large'")
    config.addinivalue_line(
        "markers", "slow: multi-process gang relaunch tests (minutes); "
        "excluded from tier-1 (-m 'not slow')")
