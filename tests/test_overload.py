"""Overload-resilience tests (ISSUE 16): admission control + load
shedding at the batcher, the deadline-vs-shed exactly-one-reply contract,
duplicate-reply idempotence at the client, the per-rank circuit breaker,
and ``request_retry`` honoring a shed reply's ``retry_after_s``.
"""

import threading
import time

import numpy as np
import pytest

from harp_tpu.parallel.events import Event, EventType
from harp_tpu.serve import OP_TOPK, MicroBatcher, TopKReplyCache, protocol
from harp_tpu.serve.router import RouterClient, _PendingReply, local_gang
from harp_tpu.utils.metrics import Metrics


class _FakeEndpoint:
    name = "fake"
    op = "classify"
    bucket_sizes = (4, 8)
    max_batch = 8

    def __init__(self):
        self.batches = []

    def bucket_for(self, n):
        for b in self.bucket_sizes:
            if n <= b:
                return b
        raise ValueError(n)

    def validate_query(self, op, data):
        return None if op == self.op else f"op {op!r} mismatch"

    def dispatch(self, batch):
        self.batches.append(len(batch))
        return list(range(len(batch)))


def _collecting_reply():
    replies = []
    lock = threading.Lock()

    def reply(msg, ok, result=None, error=None, batch=None, bucket=None,
              **kw):
        with lock:
            replies.append({"id": msg["id"], "ok": ok, "result": result,
                            "error": error, "batch": batch,
                            "bucket": bucket, **kw})
    return replies, reply


def _msg(i, deadline_ts=None, priority=0):
    return {"kind": protocol.REQUEST, "id": f"t-{i}", "op": "classify",
            "model": "fake", "data": float(i),
            "reply_to": (9, "127.0.0.1", 1), "ts": time.time(),
            "deadline_ts": deadline_ts, "priority": priority}


# --------------------------------------------------------------------------- #
# Admission control: bounded queue, retryable shed, brownout priorities
# --------------------------------------------------------------------------- #

def test_queue_bound_sheds_with_retryable_reply_and_retry_after():
    ep = _FakeEndpoint()
    replies, reply = _collecting_reply()
    m = Metrics()
    # window >> test budget: nothing dispatches, the queue only grows
    b = MicroBatcher(ep, reply, max_wait_s=10.0, max_queue=2, metrics=m)
    try:
        assert b.submit(_msg(0))
        assert b.submit(_msg(1))
        assert b.submit(_msg(2))          # True: HANDLED (shed reply sent)
        shed = [r for r in replies if not r["ok"]]
        assert len(shed) == 1 and shed[0]["id"] == "t-2"
        assert shed[0]["error"].startswith(protocol.ERR_OVERLOADED)
        # the reply tells the client how long the backlog needs: with no
        # dispatch observed yet the EWMA falls back to max_wait_s —
        # ceil(2/8) windows x 10 s + one coalescing window = 20 s
        assert shed[0]["retry_after_s"] == pytest.approx(20.0)
        assert m.counters["serve.shed.fake"] == 1
        assert m.gauges["serve.shedding.fake"] == 1
        assert "serve.brownout_shed.fake" not in m.counters
    finally:
        b.kill()


def test_brownout_sheds_only_droppable_priorities():
    ep = _FakeEndpoint()
    replies, reply = _collecting_reply()
    m = Metrics()
    burning = {"on": True}
    b = MicroBatcher(ep, reply, max_wait_s=10.0, metrics=m,
                     brownout_fn=lambda: burning["on"],
                     brownout_min_priority=1)
    try:
        assert b.submit(_msg(0, priority=0))     # droppable: shed
        assert b.submit(_msg(1, priority=1))     # declared precious: kept
        burning["on"] = False
        assert b.submit(_msg(2, priority=0))     # healthy again: kept
        shed = [r for r in replies if not r["ok"]]
        assert [r["id"] for r in shed] == ["t-0"]
        assert shed[0]["error"].startswith(protocol.ERR_OVERLOADED)
        assert "brownout" in shed[0]["error"]
        assert m.counters["serve.shed.fake"] == 1
        assert m.counters["serve.brownout_shed.fake"] == 1
        assert b.pending() == 2
        # the accept path clears the shedding gauge — operators see the
        # brownout END, not a latched alarm
        assert m.gauges["serve.shedding.fake"] == 0
    finally:
        b.kill()


def test_deadline_beats_shed_with_exactly_one_reply():
    """A request that is BOTH past its deadline AND facing a full queue
    gets exactly one reply, and it is deadline-exceeded — shedding an
    already-dead request as 'retryable' would invite a pointless
    resubmit (ISSUE 16 satellite)."""
    ep = _FakeEndpoint()
    replies, reply = _collecting_reply()
    m = Metrics()
    b = MicroBatcher(ep, reply, max_wait_s=10.0, max_queue=1, metrics=m)
    try:
        assert b.submit(_msg(0))                 # fills the queue
        assert b.submit(_msg(1, deadline_ts=time.time() - 1.0))
        mine = [r for r in replies if r["id"] == "t-1"]
        assert len(mine) == 1                    # exactly ONE reply
        assert mine[0]["ok"] is False
        assert mine[0]["error"].startswith(protocol.ERR_DEADLINE)
        assert "retry_after_s" not in mine[0]
        assert m.counters["serve.deadline_expired.fake"] == 1
        assert "serve.shed.fake" not in m.counters
    finally:
        b.kill()


def test_retry_after_tracks_observed_dispatch_wall():
    """Once dispatches have been observed, retry_after_s is backlog x the
    EWMA dispatch wall — the server's own drain estimate, not a constant."""
    ep = _FakeEndpoint()
    replies, reply = _collecting_reply()
    b = MicroBatcher(ep, reply, max_wait_s=0.005, max_batch=4, max_queue=4)
    try:
        b.submit(_msg(0))
        deadline = time.time() + 5.0
        while not replies and time.time() < deadline:
            time.sleep(0.005)
        assert replies and replies[0]["ok"]      # one dispatch observed
        with b._cv:
            ewma = b._dispatch_ewma
        assert ewma is not None and ewma > 0.0
        with b._cv:
            assert b._retry_after_locked(8) == \
                pytest.approx(2 * ewma + b.max_wait_s)
    finally:
        b.drain_and_stop()


# --------------------------------------------------------------------------- #
# Client: duplicate-reply idempotence (the netdup seam, satellite S2)
# --------------------------------------------------------------------------- #

def test_duplicate_reply_is_dropped_counted_and_never_corrupts():
    m = Metrics()
    client = RouterClient(100, {}, {}, metrics=m)
    try:
        pending = _PendingReply()
        with client._lock:
            client._waiting["rid-1"] = (0, pending)
        reply = {"kind": protocol.REPLY, "id": "rid-1", "ok": True,
                 "result": 42}
        # the netdup'd wire delivers the same reply frame twice: the first
        # copy resolves the future, the second finds no waiting id
        client.queue.put(Event(EventType.MESSAGE, 0, dict(reply)))
        client.queue.put(Event(EventType.MESSAGE, 0, dict(reply)))
        assert pending.result(5.0) == 42
        deadline = time.time() + 5.0
        while (m.counters.get("serve.client.orphan_replies", 0) < 1
               and time.time() < deadline):
            time.sleep(0.005)
        assert m.counters["serve.client.orphan_replies"] == 1
        assert client._waiting == {}             # nothing left behind
        assert pending.reply["result"] == 42     # first copy untouched
    finally:
        client.close()


# --------------------------------------------------------------------------- #
# Circuit breaker: open / fail-fast / half-open probe / close
# --------------------------------------------------------------------------- #

def test_breaker_opens_probes_and_closes():
    m = Metrics()
    client = RouterClient(100, {}, {"mf": 0}, metrics=m,
                          breaker_threshold=2, breaker_cooldown_s=0.05)
    try:
        assert client.breaker_state(0) == "closed"
        client._breaker_failure(0)
        assert client.breaker_state(0) == "closed"     # under threshold
        client._breaker_failure(0)
        assert client.breaker_state(0) == "open"
        assert m.counters["serve.client.breaker_open"] == 1
        # open: submits fail fast without dialing
        with pytest.raises(ConnectionError, match="circuit open"):
            client._breaker_admit(0)
        assert m.counters["serve.client.breaker_fastfail"] == 1
        time.sleep(0.06)
        # after the cooldown the FIRST caller is the single half-open
        # probe; a second concurrent caller still fails fast
        client._breaker_admit(0)
        assert client.breaker_state(0) == "half-open"
        with pytest.raises(ConnectionError):
            client._breaker_admit(0)
        # failed probe: re-open, cooldown re-armed
        client._breaker_failure(0)
        assert client.breaker_state(0) == "open"
        assert m.counters["serve.client.breaker_open"] == 2
        time.sleep(0.06)
        client._breaker_admit(0)
        client._breaker_success(0)                     # probe answered
        assert client.breaker_state(0) == "closed"
        assert m.counters["serve.client.breaker_closed"] == 1
        # other ranks were never affected
        assert client.breaker_state(1) == "closed"
    finally:
        client.close()


def test_breaker_opens_from_real_connect_failures_and_placement_resets():
    """Real transport leg: consecutive connection-refused sends open the
    circuit (fast-fail, nothing dialed), and a placement frame
    re-announcing the rank resets its breaker — the supervisor vouches
    for the new address."""
    import socket

    # a port that refuses connections: bind, then close without listening
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_addr = s.getsockname()
    s.close()
    m = Metrics()
    client = RouterClient(100, {0: dead_addr}, {"mf": 0}, metrics=m,
                          breaker_threshold=2, breaker_cooldown_s=60.0)
    try:
        for _ in range(2):
            with pytest.raises(ConnectionError):
                client.submit(OP_TOPK, "mf", 1)
        assert client.breaker_state(0) == "open"
        t0 = time.perf_counter()
        with pytest.raises(ConnectionError, match="circuit open"):
            client.submit(OP_TOPK, "mf", 1)
        # fail-fast means no dial: the open-circuit path never pays the
        # transport's connect/retry budget
        assert time.perf_counter() - t0 < 0.5
        assert m.counters["serve.client.breaker_fastfail"] >= 1
        client.apply_placement({"mf": 0}, {0: dead_addr}, version=1)
        assert client.breaker_state(0) == "closed"
    finally:
        client.close()


# --------------------------------------------------------------------------- #
# request_retry: overloaded is transient, retry_after_s honored, no resync
# --------------------------------------------------------------------------- #

def _overloaded_error(retry_after_s):
    err = protocol.ServeError(
        f"{protocol.ERR_OVERLOADED}: queue shed at depth 3")
    err.reply = {"ok": False, "retry_after_s": retry_after_s}
    return err


class _ScriptedPending:
    def __init__(self, outcome):
        self._outcome = outcome

    def result(self, timeout=None):
        if isinstance(self._outcome, Exception):
            raise self._outcome
        return self._outcome


def test_request_retry_honors_retry_after_without_placement_resync():
    m = Metrics()
    client = RouterClient(100, {}, {"mf": 0}, metrics=m)
    outcomes = [_overloaded_error(0.4), _overloaded_error(99.0), "answer"]
    submits, naps, resyncs = [], [], []

    def fake_submit(op, model, data, *, dest=None, priority=0):
        submits.append(priority)
        return _ScriptedPending(outcomes[len(submits) - 1])

    client.submit = fake_submit
    client.sync_placement = lambda timeout=5.0: resyncs.append(timeout)
    try:
        res = client.request_retry(OP_TOPK, "mf", 1, attempts=5,
                                   backoff_s=0.001, backoff_max_s=0.002,
                                   jitter=0.0, priority=2,
                                   retry_after_cap_s=0.5,
                                   sleep=naps.append)
        assert res == "answer"
        assert submits == [2, 2, 2]              # priority rides through
        # backoff honored the server's drain estimate (0.4 > the
        # exponential schedule), and the cap defanged the corrupt 99 s
        assert naps[0] == pytest.approx(0.4)
        assert naps[1] == pytest.approx(0.5)
        assert resyncs == []                     # the map did not change
        assert m.counters["serve.client_overloaded"] == 2
    finally:
        client.close()


def test_request_retry_overloaded_exhausts_budget_loudly():
    client = RouterClient(100, {}, {"mf": 0}, metrics=Metrics())
    client.submit = lambda *a, **kw: _ScriptedPending(_overloaded_error(0.01))
    client.sync_placement = lambda timeout=5.0: True
    try:
        with pytest.raises(protocol.ServeError, match="overloaded"):
            client.request_retry(OP_TOPK, "mf", 1, attempts=3,
                                 backoff_s=0.001, jitter=0.0,
                                 sleep=lambda s: None)
    finally:
        client.close()


# --------------------------------------------------------------------------- #
# Worker path: cache hits are served even while the batcher browns out
# --------------------------------------------------------------------------- #

class _FakeBurningSLO:
    burning = True

    def is_burning(self):
        return True

    def observe(self, *a, **kw):
        pass

    def close(self):
        pass


def test_cache_hits_served_during_brownout(session, rng):
    from harp_tpu.serve import TopKEndpoint

    uf = rng.normal(size=(16, 4)).astype(np.float32)
    items = rng.normal(size=(8, 4)).astype(np.float32)
    ep = TopKEndpoint(session, "mf", uf, items, k=2)
    cache = TopKReplyCache()
    m = Metrics()
    workers, make_client = local_gang(session, [{"mf": ep}], cache=cache,
                                      metrics=m, brownout_min_priority=1)
    client = make_client()
    try:
        ref = np.argsort(-(uf[3] @ items.T), kind="stable")[:2].tolist()
        # warm the cache while healthy
        assert client.request(OP_TOPK, "mf", 3, timeout=30.0)["items"] \
            == ref
        # arm a sustained brownout: every sub-priority-1 request is shed
        workers[0].slo = _FakeBurningSLO()
        with pytest.raises(protocol.ServeError, match="overloaded"):
            client.request(OP_TOPK, "mf", 5, timeout=30.0)
        # ...but the hot key still answers from the cache — brownout sheds
        # WORK, not hits (cache sits before admission in the worker)
        assert client.request(OP_TOPK, "mf", 3, timeout=30.0)["items"] \
            == ref
        # and declared-precious traffic is never browned out
        assert client.request(OP_TOPK, "mf", 5, timeout=30.0,
                              priority=1)["items"] == \
            np.argsort(-(uf[5] @ items.T), kind="stable")[:2].tolist()
        assert m.counters["serve.brownout_shed.mf"] == 1
    finally:
        client.close()
        for w in workers:
            w.close()
