"""K-means end-to-end: every comm variant must match the numpy Lloyd reference.

Reference test-strategy parity: contrib/test_scripts/km.sh ran the real job on
synthetic data; here we additionally assert trajectory-exact agreement with numpy
(the reference could only eyeball convergence).
"""

import numpy as np
import pytest

from harp_tpu.io import datagen
from harp_tpu.models import kmeans as km

K, D, N, ITERS = 10, 100, 1000, 10  # BASELINE config[0] / README.md:158-160


@pytest.fixture(scope="module")
def data():
    pts = datagen.dense_points(N, D, seed=7, num_clusters=K)
    cen0 = datagen.initial_centroids(pts, K, seed=3)
    return pts, cen0


@pytest.fixture(scope="module")
def reference(data):
    pts, cen0 = data
    return km.numpy_reference(pts.astype(np.float64), cen0.astype(np.float64), ITERS)


@pytest.mark.parametrize("comm", km.COMM_VARIANTS)
def test_variant_matches_numpy(session, data, reference, comm):
    pts, cen0 = data
    model = km.KMeans(session, km.KMeansConfig(K, D, ITERS, comm))
    cen, costs = model.fit(pts, cen0)
    np.testing.assert_allclose(np.asarray(cen), reference, rtol=1e-3, atol=1e-4)
    # cost must be non-increasing (Lloyd guarantee)
    c = np.asarray(costs)
    assert np.all(np.diff(c) <= 1e-2 * np.abs(c[:-1]) + 1e-3), c


def test_variants_agree_exactly(session, data):
    """All comm patterns compute the same sums → identical trajectories."""
    pts, cen0 = data
    outs = {}
    for comm in ("regroupallgather", "allreduce", "bcastreduce", "pushpull",
                 "rotation"):
        model = km.KMeans(session, km.KMeansConfig(K, D, ITERS, comm))
        cen, _ = model.fit(pts, cen0)
        outs[comm] = np.asarray(cen)
    base = outs["regroupallgather"]
    for comm, cen in outs.items():
        np.testing.assert_allclose(cen, base, rtol=1e-5, atol=1e-6, err_msg=comm)


@pytest.mark.parametrize("k", [11, 3, 13])
def test_rotation_with_misaligned_padding(session, k):
    """Regression: K not aligned to the padded block size used to produce NaN
    distances (inf-coordinate padding) poisoning blocks that mix real+pad rows."""
    pts = __import__("harp_tpu.io.datagen", fromlist=["datagen"]).dense_points(
        400, 16, seed=11, num_clusters=k)
    from harp_tpu.io import datagen
    cen0 = datagen.initial_centroids(pts, k, seed=5)
    model = km.KMeans(session, km.KMeansConfig(k, 16, 6, "rotation"))
    cen, _ = model.fit(pts, cen0)
    ref = km.numpy_reference(pts.astype(np.float64), cen0.astype(np.float64), 6)
    np.testing.assert_allclose(np.asarray(cen), ref, rtol=1e-3, atol=1e-4)


def test_bad_point_count_raises(session, data):
    pts, cen0 = data
    model = km.KMeans(session, km.KMeansConfig(K, D, 2))
    with pytest.raises(ValueError, match="divide over"):
        model.fit(pts[:999], cen0)


def test_bad_comm_variant(session):
    with pytest.raises(ValueError, match="comm must be"):
        km.KMeans(session, km.KMeansConfig(comm="telepathy"))


def test_kmeans_fit_checkpointed_resume_equivalence(session, tmp_path):
    from harp_tpu.utils.checkpoint import Checkpointer

    pts = datagen.dense_points(160, 8, seed=0, num_clusters=4)
    cen0 = datagen.initial_centroids(pts, 4, seed=1)
    model = km.KMeans(session, km.KMeansConfig(4, 8, iterations=6))
    pts_dev, cen_dev = model.prepare(pts, cen0)
    cen_full, costs_full = model.fit_prepared(pts_dev, cen_dev)

    # uninterrupted checkpointed run is bitwise the full-scan trajectory
    ck1 = Checkpointer(str(tmp_path / "a"), use_orbax=False)
    cen_c, costs_c, start = model.fit_checkpointed(pts_dev, cen_dev, ck1,
                                                   save_every=2)
    assert start == 0
    np.testing.assert_array_equal(np.asarray(cen_full), np.asarray(cen_c))
    np.testing.assert_array_equal(np.asarray(costs_full), costs_c)

    # interrupt after 4 of 6 iterations; the resumed run completes bitwise
    ck2 = Checkpointer(str(tmp_path / "b"), use_orbax=False)
    model.fit_checkpointed(pts_dev, cen_dev, ck2, save_every=2, iterations=4)
    cen_r, costs_r, start_r = model.fit_checkpointed(pts_dev, cen_dev, ck2,
                                                     save_every=2)
    assert start_r == 4 and len(costs_r) == 2
    np.testing.assert_array_equal(np.asarray(cen_full), np.asarray(cen_r))
    np.testing.assert_array_equal(np.asarray(costs_full)[4:], costs_r)


# --- sgxsimu (experimental/kmeans/sgxsimu parity) -------------------------- #

def test_sgxsimu_cost_model_buckets():
    from harp_tpu.models.sgxsimu import (SGXCostConstants, SGXSimuConfig,
                                         model_kmeans_overheads)

    c = SGXCostConstants()
    cfg = SGXSimuConfig(threads_per_worker=2)
    m = model_kmeans_overheads(n_points=8192, dim=16, k=8, workers=4,
                               iterations=10, cfg=cfg)
    # buckets are PER WORKER (reference mappers sleep their own overheads
    # concurrently): creation per thread + attestation pairings
    # C(2,2->1)+(W-1)*thr, no gang-wide multiplier
    creation = 2 * c.ms(c.creation_enclave_fix
                        + 96 * 1024 * c.creation_enclave_kb)
    pairings = 1 + 3 * 2
    attest = c.ms(pairings * c.local_attestation)
    assert abs(m["init_ms"] - (creation + attest)) < 1e-9
    # comm: 2 collectives * (Ocall + Ecall*(W-1) + cen_kb * per_kb)
    cen_kb = 8 * 17 * 8 / 1024
    per_coll = c.ms(c.ocall + c.ecall * 3) + c.ms(cen_kb * c.cross_enclave_per_kb)
    assert abs(m["comm_ms_per_iter"] - 2 * per_coll) < 1e-9
    assert m["comp_swap_ms_per_iter"] == 0.0          # opt-in term
    assert m["total_overhead_ms"] == (
        m["init_ms"] + 10 * m["overhead_ms_per_iter"])
    assert m["gang_total_overhead_ms"] == 4 * m["total_overhead_ms"]


def test_sgxsimu_page_swap_activates_below_working_set():
    from harp_tpu.models.sgxsimu import SGXSimuConfig, model_kmeans_overheads

    big = model_kmeans_overheads(65536, 64, 16, 4, 5,
                                 SGXSimuConfig(include_page_swap=True,
                                               enclave_per_thd_mb=1))
    roomy = model_kmeans_overheads(65536, 64, 16, 4, 5,
                                   SGXSimuConfig(include_page_swap=True,
                                                 enclave_per_thd_mb=96))
    assert big["comp_swap_ms_per_iter"] > 0.0
    assert roomy["comp_swap_ms_per_iter"] == 0.0


def test_sgxsimu_fit_matches_plain_kmeans(session):
    from harp_tpu.models.sgxsimu import SGXSimuKMeans

    pts = datagen.dense_points(1024, 8, seed=0, num_clusters=4)
    cen0 = datagen.initial_centroids(pts, 4, seed=1)
    cfg = km.KMeansConfig(4, 8, iterations=5)
    cen_plain, costs_plain = km.KMeans(session, cfg).fit(pts, cen0)
    cen_sgx, costs_sgx, rep = SGXSimuKMeans(session, cfg).fit(pts, cen0)
    np.testing.assert_array_equal(np.asarray(cen_plain), cen_sgx)
    np.testing.assert_array_equal(np.asarray(costs_plain), costs_sgx)
    assert rep["modeled_slowdown"] > 1.0
    assert rep["init_ms"] > 0 and rep["comm_ms_per_iter"] > 0
    # simulate=True runs per-iteration compiled chunks with sleeps between;
    # Lloyd chunking is bitwise the full scan, so results are unchanged
    cen_sim, costs_sim, rep_sim = SGXSimuKMeans(session, cfg).fit(
        pts, cen0, simulate=True)
    np.testing.assert_array_equal(cen_sim, cen_sgx)
    np.testing.assert_array_equal(costs_sim, costs_sgx)
    assert rep_sim["simulated_ms_per_iter"] >= rep_sim["clean_ms_per_iter"]
