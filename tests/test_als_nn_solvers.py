"""ALS / mini-batch NN / optimization-solver tests (daal_als, daal_nn,
daal_optimization_solvers parity)."""

import numpy as np
import pytest

from harp_tpu.io import datagen
from harp_tpu.models import als, nn, solvers


def test_explicit_als_converges(session):
    rows, cols, vals = datagen.sparse_ratings(80, 64, rank=4, density=0.3,
                                              seed=7, noise=0.01)
    cfg = als.ALSConfig(rank=8, lam=0.05, iterations=8, implicit=False)
    u, v, rmse = als.ALS(session, cfg).fit(rows, cols, vals, 80, 64)
    assert rmse[-1] < 0.12
    assert rmse[-1] < 0.5 * rmse[0]
    pred = np.einsum("ij,ij->i", u[rows], v[cols])
    assert np.sqrt(np.mean((vals - pred) ** 2)) < 0.12


def test_als_zipf_bounded_padding_and_converges(session):
    """VERDICT #4: power-law rows must not blow up the CSR padding — capped
    chunks bound it, and convergence matches the uniform case's quality."""
    rows, cols, vals = datagen.zipf_ratings(
        num_users=256, num_items=192, rank=4, alpha=1.3, density=0.08, seed=9,
        noise=0.01)
    cfg = als.ALSConfig(rank=8, lam=0.05, iterations=8, implicit=False,
                        layout="sparse")     # this test is ABOUT the chunks
    model = als.ALS(session, cfg)
    u, v, rmse = model.fit(rows, cols, vals, 256, 192)
    assert model.last_layout_stats["overhead"] <= 4.0
    assert rmse[-1] < 0.5 * rmse[0]
    pred = np.einsum("ij,ij->i", u[rows], v[cols])
    assert np.sqrt(np.mean((vals - pred) ** 2)) < 0.15
    # the round-1 all-rows-to-max layout on the same data, for contrast
    m = max(np.bincount(rows).max(), np.bincount(cols).max())
    round1 = 256 * m / max(len(vals), 1)
    assert model.last_layout_stats["overhead"] < round1


def test_implicit_als_ranks_observed_higher(session):
    rng = np.random.default_rng(3)
    # block structure: users 0-39 consume items 0-31, users 40-79 items 32-63
    rows, cols = [], []
    for u_ in range(80):
        items = rng.choice(32, size=10, replace=False) + (32 if u_ >= 40 else 0)
        rows += [u_] * 10
        cols += list(items)
    rows = np.array(rows, np.int32)
    cols = np.array(cols, np.int32)
    vals = np.ones(len(rows), np.float32)
    cfg = als.ALSConfig(rank=6, lam=0.1, alpha=20.0, iterations=6,
                        implicit=True)
    u, v, _ = als.ALS(session, cfg).fit(rows, cols, vals, 80, 64)
    scores = u @ v.T
    in_block = scores[:40, :32].mean()
    out_block = scores[:40, 32:].mean()
    assert in_block > out_block + 0.2


def test_implicit_als_rejects_negative_values(session):
    # Hu-Koren confidence needs nonnegative counts; a negative value at high
    # alpha makes the normal equations indefinite → NaN factors (bench r3)
    rows = np.array([0, 1, 2], np.int32)
    cols = np.array([0, 1, 2], np.int32)
    vals = np.array([1.0, -0.5, 1.0], np.float32)
    cfg = als.ALSConfig(rank=4, iterations=1, implicit=True)
    with pytest.raises(ValueError, match="nonnegative interaction"):
        als.ALS(session, cfg).prepare(rows, cols, vals, 8, 8)


def test_als_prepare_fit_prepared_matches_fit(session):
    rng = np.random.default_rng(5)
    n = 64
    rows = rng.integers(0, n, 400).astype(np.int32)
    cols = rng.integers(0, n, 400).astype(np.int32)
    vals = np.abs(rng.normal(size=400)).astype(np.float32)
    cfg = als.ALSConfig(rank=4, lam=0.1, alpha=10.0, iterations=3,
                        implicit=True)
    m = als.ALS(session, cfg)
    u1, v1, r1 = m.fit(rows, cols, vals, n, n, seed=2)
    u2, v2, r2 = m.fit_prepared(m.prepare(rows, cols, vals, n, n, seed=2))
    np.testing.assert_array_equal(u1, u2)
    np.testing.assert_array_equal(v1, v2)
    np.testing.assert_array_equal(r1, r2)


def test_mlp_classifier(session):
    x, y = datagen.classification_data(640, 10, 3, seed=15)
    cfg = nn.NNConfig(layers=(32,), num_classes=3, lr=0.2, batch_size=20,
                      epochs=30)
    model = nn.MLPClassifier(session, cfg)
    losses = model.fit(x, y)
    assert losses[-1] < 0.5 * losses[0]
    assert (model.predict(x) == y).mean() > 0.9


@pytest.mark.parametrize("kind,iters", [
    ("sgd", 200), ("sgd_minibatch", 200), ("sgd_momentum", 120),
    ("adagrad", 300), ("lbfgs", 40),
])
def test_solvers_minimize_mse(session, kind, iters):
    rng = np.random.default_rng(1)
    x = rng.standard_normal((160, 8)).astype(np.float32)
    beta = rng.standard_normal(8).astype(np.float32)
    y = x @ beta
    lr = {"lbfgs": 0.5, "adagrad": 1.0}.get(kind, 0.1)
    cfg = solvers.SolverConfig(lr=lr, iterations=iters, batch_size=10)
    theta, losses = solvers.Solver(session, kind, cfg).minimize(
        solvers.mse_objective, x, y, np.zeros(8, np.float32))
    assert losses[-1] < 1e-2, (kind, losses[-5:])
    np.testing.assert_allclose(theta, beta, atol=0.1)


def test_lbfgs_beats_sgd_on_iterations(session):
    rng = np.random.default_rng(2)
    x = rng.standard_normal((160, 12)).astype(np.float32)
    # ill-conditioned quadratic: scale columns
    x *= np.logspace(0, 2, 12, dtype=np.float32)
    beta = rng.standard_normal(12).astype(np.float32)
    y = x @ beta
    cfg_l = solvers.SolverConfig(lr=1.0, iterations=60)
    _, loss_l = solvers.Solver(session, "lbfgs", cfg_l).minimize(
        solvers.mse_objective, x, y, np.zeros(12, np.float32))
    cfg_s = solvers.SolverConfig(lr=1e-5, iterations=60)
    _, loss_s = solvers.Solver(session, "sgd", cfg_s).minimize(
        solvers.mse_objective, x, y, np.zeros(12, np.float32))
    assert loss_l[-1] < loss_s[-1]


def test_als_dense_sparse_layout_parity(session):
    """The dense NaN-encoded GEMM layout converges to the same quality as
    the capped-chunk sparse layout in both modes (bf16 planes with f32
    accumulation — the dense SGD-MF precision contract)."""
    import dataclasses as _dc

    rows, cols, vals = datagen.sparse_ratings(128, 96, rank=6, density=0.08,
                                              seed=11, noise=0.01)
    for implicit in (False, True):
        v_in = np.abs(vals) if implicit else vals
        finals = {}
        for layout in ("sparse", "dense"):
            cfg = als.ALSConfig(rank=12, lam=0.1, alpha=20.0, iterations=8,
                                implicit=implicit, layout=layout)
            m = als.ALS(session, cfg)
            _, _, rmse = m.fit(rows, cols, v_in, 128, 96, seed=0)
            finals[layout] = float(rmse[-1])
        if implicit:
            stats = m.last_layout_stats
            assert stats["layout"] == "dense"
            # BOTH layouts dedupe keep-first in prepare (sgd_mf contract) and
            # report the count — identical training sets by construction
            n_unique = len({(int(r), int(c)) for r, c in zip(rows, cols)})
            assert stats["duplicates_dropped"] == len(rows) - n_unique
        assert abs(finals["dense"] - finals["sparse"]) < 0.05 * max(
            abs(finals["sparse"]), 0.02), (implicit, finals)


def test_als_auto_layout_threshold(session):
    """auto picks dense when this worker's plane shards fit dense_max_bytes,
    sparse when they do not; the budget is per-worker, so a wider mesh keeps
    dense available at sizes whose GLOBAL planes exceed it."""
    import dataclasses as _dc

    cfg = als.ALSConfig(rank=4, iterations=1)
    m = als.ALS(session, cfg)
    assert m._pick_layout(64, 64) == "dense"
    tight = als.ALS(session, _dc.replace(cfg, dense_max_bytes=1024))
    assert tight._pick_layout(64, 64) == "sparse"
    # per-worker budgeting: global planes for 4096² are 64 MiB > an 8 MiB
    # budget, but an 8-worker mesh's per-worker share (8 MiB) just fits
    w = session.num_workers
    per_worker = (4096 // w) * 4096 * 2 * 2
    roomy = als.ALS(session, _dc.replace(cfg, dense_max_bytes=per_worker))
    assert roomy._pick_layout(4096, 4096) == "dense"
    assert als.ALS(session, _dc.replace(
        cfg, dense_max_bytes=per_worker - 1))._pick_layout(4096, 4096) == \
        "sparse"
