"""Online serving tests (harp_tpu/serve/ — ISSUE 10).

Covers the endpoint dispatches (parity vs the models' own predict), the
one-compile-per-(model, batch-bucket) retrace contract, the 2-worker local
gang end-to-end under concurrent mixed traffic (the acceptance test), the
graceful-shutdown drain/reject contract, the micro-batcher's deadline/size
bounds, the jaxlint serve trace-target pins (a collective sneaking into
the classify dispatch fails the budget gate), and the load-generator row
schema.
"""

import os
import threading
import time

import numpy as np
import pytest

from harp_tpu.serve import (OP_CLASSIFY, OP_TOPK, MicroBatcher, ServeError,
                            TopKEndpoint, classify_from_forest,
                            classify_from_linear_svm,
                            classify_from_multiclass_svm, classify_from_nn,
                            local_gang)
from harp_tpu.serve import protocol, router

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _nn_model(session, dim=12, classes=3, seed=0):
    from harp_tpu.models import nn

    model = nn.MLPClassifier(session, nn.NNConfig(layers=(8,),
                                                  num_classes=classes))
    model.params = nn.init_params((dim, 8, classes), seed=seed)
    return model


# --------------------------------------------------------------------------- #
# Endpoint parity vs the models' own predict
# --------------------------------------------------------------------------- #

def test_classify_endpoint_parity_nn_linear_svm_forest(session, rng):
    from harp_tpu.models import forest, svm

    x = rng.normal(size=(11, 12)).astype(np.float32)

    nn_model = _nn_model(session)
    ep = classify_from_nn(session, nn_model)
    assert ep.dispatch(x) == nn_model.predict(x).tolist()

    lsvm = svm.LinearSVM(session)
    lsvm.w = rng.normal(size=12).astype(np.float32)
    lsvm.b = 0.25
    ep_svm = classify_from_linear_svm(session, lsvm)
    assert ep_svm.dispatch(x) == lsvm.predict(x).tolist()

    fx, fy = rng.normal(size=(64, 5)).astype(np.float32), \
        rng.integers(0, 2, size=64).astype(np.int32)
    rf = forest.RandomForest(session, forest.TreeConfig(
        depth=3, num_bins=8, num_classes=2, num_trees=2)).fit(fx, fy)
    ep_rf = classify_from_forest(session, rf)
    # device binning + walk must reproduce the host-numpy predict exactly
    assert ep_rf.dispatch(fx[:9]) == rf.predict(fx[:9]).tolist()


def test_classify_endpoint_parity_multiclass_svm(session, rng):
    from harp_tpu.io import datagen
    from harp_tpu.models import svm

    x, y = datagen.classification_data(64, 4, 3, seed=5)
    mc = svm.MultiClassSVM(session, svm.KernelSVMConfig(
        kernel="rbf", iterations=5, power_iters=2)).fit(x, y)
    ep = classify_from_multiclass_svm(session, mc)
    got = ep.dispatch(x[:10])
    assert got == mc.predict(x[:10]).tolist()


def test_topk_matches_numpy_and_unknown_ids(session, rng):
    uf = rng.normal(size=(48, 4)).astype(np.float32)
    items = rng.normal(size=(16, 4)).astype(np.float32)
    ep = TopKEndpoint(session, "mf", uf, items, k=3)
    rows = ep.dispatch(np.asarray([7, 11, 46, 10_000]))
    for qi, row in zip((7, 11, 46), rows):
        ref = np.argsort(-(uf[qi] @ items.T), kind="stable")[:3]
        assert row["found"] and row["items"] == ref.tolist(), (qi, row)
        np.testing.assert_allclose(row["scores"],
                                   (uf[qi] @ items.T)[ref], rtol=1e-5)
    # an id nobody owns comes back found=False, never a crash
    assert rows[3] == {"found": False, "items": [], "scores": []}


def test_topk_custom_user_ids_and_validation(session, rng):
    uf = rng.normal(size=(6, 4)).astype(np.float32)
    items = rng.normal(size=(8, 4)).astype(np.float32)
    ids = np.asarray([3, 100, 205, 1007, 40009, 123456])
    ep = TopKEndpoint(session, "mf", uf, items, k=2, user_ids=ids)
    row = ep.dispatch(np.asarray([40009]))[0]
    ref = np.argsort(-(uf[4] @ items.T), kind="stable")[:2]
    assert row["items"] == ref.tolist()
    with pytest.raises(ValueError):
        TopKEndpoint(session, "mf", uf, items, user_ids=ids[:3])
    with pytest.raises(ValueError):
        TopKEndpoint(session, "mf", uf[:, :2], items)


# --------------------------------------------------------------------------- #
# Retrace contract: one compile per (model, batch-bucket)
# --------------------------------------------------------------------------- #

def test_one_compile_per_model_bucket(session, rng):
    model = _nn_model(session)
    ep = classify_from_nn(session, model, bucket_sizes=(8, 32))
    for n in (1, 3, 8, 5, 2):            # all land in bucket 8
        ep.dispatch(rng.normal(size=(n, 12)).astype(np.float32))
    assert ep.trace_counts == {8: 1}, ep.trace_counts
    for n in (20, 32, 9):                # all land in bucket 32
        ep.dispatch(rng.normal(size=(n, 12)).astype(np.float32))
    assert ep.trace_counts == {8: 1, 32: 1}, ep.trace_counts
    with pytest.raises(ValueError):
        ep.dispatch(rng.normal(size=(33, 12)).astype(np.float32))


def test_bucket_sizes_must_split_over_mesh(session):
    model = _nn_model(session)
    with pytest.raises(ValueError):
        classify_from_nn(session, model, bucket_sizes=(7,))
    ep = classify_from_nn(session, model, bucket_sizes=(16,))
    assert ep.bucket_sizes == (16,) and ep.max_batch == 16


# --------------------------------------------------------------------------- #
# 2-worker local gang, concurrent mixed traffic (acceptance)
# --------------------------------------------------------------------------- #

def test_local_gang_concurrent_topk_classify_e2e(session, rng):
    """ISSUE 10 acceptance: a 2-worker local gang serves concurrent top-k +
    classify end-to-end with exactly one compile per (model, batch-bucket),
    including the forwarding leg (a request landing on a non-owning worker
    reaches the owner and the reply still travels owner -> client)."""
    nn_model = _nn_model(session)
    ep_c = classify_from_nn(session, nn_model, name="nn")
    uf = rng.normal(size=(48, 4)).astype(np.float32)
    items = rng.normal(size=(16, 4)).astype(np.float32)
    ep_t = TopKEndpoint(session, "mf", uf, items, k=3)
    x_pool = rng.normal(size=(32, 12)).astype(np.float32)
    ref_labels = nn_model.predict(x_pool)
    ref_top = {u: np.argsort(-(uf[u] @ items.T), kind="stable")[:3].tolist()
               for u in range(48)}

    workers, make_client = local_gang(session, [{"nn": ep_c}, {"mf": ep_t}])
    clients = [make_client() for _ in range(3)]
    failures = []

    def drive(ci, client):
        local_rng = np.random.default_rng(100 + ci)
        for i in range(30):
            try:
                if i % 2 == 0:
                    u = int(local_rng.integers(0, 48))
                    # client 0 misroutes every top-k to worker 0 — the
                    # forwarding leg carries it to the owner (worker 1)
                    dest = 0 if ci == 0 else None
                    res = client.request(OP_TOPK, "mf", u, dest=dest,
                                         timeout=60.0)
                    if res["items"] != ref_top[u]:
                        failures.append((ci, i, "topk", u, res))
                else:
                    j = int(local_rng.integers(0, len(x_pool)))
                    lab = client.request(OP_CLASSIFY, "nn", x_pool[j],
                                         timeout=60.0)
                    if lab != int(ref_labels[j]):
                        failures.append((ci, i, "classify", j, lab))
            except Exception as e:       # collected, asserted below
                failures.append((ci, i, type(e).__name__, str(e)))
    try:
        threads = [threading.Thread(target=drive, args=(ci, c))
                   for ci, c in enumerate(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120.0)
        assert failures == [], failures[:5]
        # exactly one compile per (model, bucket): 3 closed-loop clients
        # coalesce into batches <= 3, i.e. only the smallest bucket
        assert ep_c.trace_counts == {ep_c.bucket_sizes[0]: 1}
        assert ep_t.trace_counts == {ep_t.bucket_sizes[0]: 1}
        # the forwarding leg really ran (client 0 sent all top-k to rank 0)
        assert workers[0].metrics.counters.get("serve.forwarded", 0) >= 1
    finally:
        for c in clients:
            c.close()
        for w in workers:
            w.close()


# --------------------------------------------------------------------------- #
# Graceful shutdown: drain in-flight, reject new, no orphan threads
# --------------------------------------------------------------------------- #

def test_graceful_shutdown_drains_and_rejects(session, rng):
    nn_model = _nn_model(session)
    ep = classify_from_nn(session, nn_model, name="nn")
    # a long coalescing window keeps submissions in-flight deterministically
    workers, make_client = local_gang(session, [{"nn": ep}],
                                      max_wait_s=5.0)
    worker = workers[0]
    client = make_client()
    x = rng.normal(size=(12,)).astype(np.float32)
    try:
        pending = [client.submit(OP_CLASSIFY, "nn", x) for _ in range(3)]
        deadline = time.time() + 10.0
        while worker.batchers["nn"].pending() < 3:
            assert time.time() < deadline, "requests never reached batcher"
            time.sleep(0.005)
        worker.begin_drain()
        # new requests get the clean shutting-down reply
        with pytest.raises(ServeError, match=protocol.ERR_SHUTTING_DOWN):
            client.request(OP_CLASSIFY, "nn", x, timeout=30.0)
        # close() drains: the 3 in-flight requests are SERVED, not dropped
        worker.close()
        expect = int(nn_model.predict(x[None])[0])
        assert [p.result(30.0) for p in pending] == [expect] * 3
    finally:
        client.close()
        worker.close()                  # idempotent
    leftovers = [t.name for t in threading.enumerate()
                 if t.name.startswith(("harp-serve-worker",
                                       "harp-serve-batcher",
                                       "harp-serve-client"))]
    assert leftovers == [], leftovers


def test_atexit_close_contract(session, rng):
    """The PR 7 atexit-close contract extended to serve hooks: live
    workers/clients register and the exit hook closes them all."""
    nn_model = _nn_model(session)
    ep = classify_from_nn(session, nn_model, name="nn")
    workers, make_client = local_gang(session, [{"nn": ep}])
    client = make_client()
    assert workers[0] in router._LIVE and client in router._LIVE
    router._close_at_exit()
    assert workers[0]._closed and client._closed
    assert workers[0] not in router._LIVE and client not in router._LIVE
    router._close_at_exit()             # idempotent on an empty set


def test_unknown_model_is_a_clean_error(session, rng):
    nn_model = _nn_model(session)
    ep = classify_from_nn(session, nn_model, name="nn")
    workers, make_client = local_gang(session, [{"nn": ep}])
    client = make_client()
    try:
        with pytest.raises(ServeError, match=protocol.ERR_UNKNOWN_MODEL):
            client.request(OP_CLASSIFY, "no-such-model",
                           rng.normal(size=(12,)).astype(np.float32),
                           timeout=30.0)
    finally:
        client.close()
        workers[0].close()


# --------------------------------------------------------------------------- #
# Micro-batcher bounds (deterministic, fake endpoint — no mesh involved)
# --------------------------------------------------------------------------- #

class _FakeEndpoint:
    name = "fake"
    op = "classify"
    bucket_sizes = (4, 8)
    max_batch = 8

    def __init__(self):
        self.batches = []

    def bucket_for(self, n):
        for b in self.bucket_sizes:
            if n <= b:
                return b
        raise ValueError(n)

    def validate_query(self, op, data):
        return None if op == self.op else f"op {op!r} mismatch"

    def dispatch(self, batch):
        self.batches.append(len(batch))
        return list(range(len(batch)))


def _collecting_reply():
    replies = []
    lock = threading.Lock()

    def reply(msg, ok, result=None, error=None, batch=None, bucket=None):
        with lock:
            replies.append({"id": msg["id"], "ok": ok, "result": result,
                            "error": error, "batch": batch,
                            "bucket": bucket})
    return replies, reply


def _msg(i, deadline_ts=None):
    return {"kind": protocol.REQUEST, "id": f"t-{i}", "op": "classify",
            "model": "fake", "data": float(i),
            "reply_to": (9, "127.0.0.1", 1), "ts": time.time(),
            "deadline_ts": deadline_ts}


def test_batcher_size_bound_closes_full_batch_immediately():
    ep = _FakeEndpoint()
    replies, reply = _collecting_reply()
    b = MicroBatcher(ep, reply, max_wait_s=10.0)     # window >> test budget
    try:
        t0 = time.perf_counter()
        for i in range(8):
            assert b.submit(_msg(i))
        deadline = time.time() + 5.0
        while len(replies) < 8 and time.time() < deadline:
            time.sleep(0.005)
        # a full bucket dispatches on SIZE, long before the 10 s window
        assert time.perf_counter() - t0 < 5.0
        assert len(replies) == 8 and all(r["ok"] for r in replies)
        assert ep.batches == [8]
        assert {r["bucket"] for r in replies} == {8}
    finally:
        b.drain_and_stop()


def test_batcher_deadline_bound_serves_single_request():
    ep = _FakeEndpoint()
    replies, reply = _collecting_reply()
    b = MicroBatcher(ep, reply, max_wait_s=0.02)
    try:
        b.submit(_msg(0))
        deadline = time.time() + 5.0
        while not replies and time.time() < deadline:
            time.sleep(0.005)
        # an underfull batch closes max_wait_s after its oldest request
        assert replies and replies[0]["ok"] and replies[0]["batch"] == 1
        assert ep.batches == [1]
    finally:
        b.drain_and_stop()


def test_batcher_rejects_mismatched_request_not_its_batchmates():
    """One stale-placement/malformed request in a coalesced batch costs
    exactly that request a clean error — the batch-mates still dispatch."""
    ep = _FakeEndpoint()
    replies, reply = _collecting_reply()
    b = MicroBatcher(ep, reply, max_wait_s=10.0)
    bad = _msg(0)
    bad["op"] = "topk"                   # wrong op for this endpoint
    b.submit(bad)
    for i in range(1, 4):
        b.submit(_msg(i))
    b.drain_and_stop()
    by_id = {r["id"]: r for r in replies}
    assert by_id["t-0"]["ok"] is False
    assert "mismatch" in by_id["t-0"]["error"]
    assert all(by_id[f"t-{i}"]["ok"] for i in range(1, 4))
    assert ep.batches == [3]             # mates dispatched without the bad one


def test_reply_rank_collision_is_dropped_and_waiting_map_bounded(session,
                                                                 rng):
    """A client claiming a serving worker's rank must not hijack the gang's
    forwarding routes: the reply is dropped (counted), the client times
    out, and the timed-out entry leaves the client's waiting map."""
    from harp_tpu.serve.router import RouterClient
    from harp_tpu.utils.metrics import Metrics

    nn_model = _nn_model(session)
    ep = classify_from_nn(session, nn_model, name="nn")
    uf = rng.normal(size=(16, 4)).astype(np.float32)
    items = rng.normal(size=(8, 4)).astype(np.float32)
    ep_t = TopKEndpoint(session, "mf", uf, items, k=2)
    m = Metrics()
    workers, make_client = local_gang(session, [{"nn": ep}, {"mf": ep_t}],
                                      metrics=m)
    # the client claims WORKER 1's rank and talks to worker 0: worker 0
    # must not let the reply_to overwrite its forwarding route to worker 1
    bad_client = RouterClient(1, {0: workers[0].address}, {"nn": 0},
                              secret=b"harp-serve-local", metrics=m)
    try:
        pending = bad_client.submit(
            OP_CLASSIFY, "nn", rng.normal(size=(12,)).astype(np.float32))
        with pytest.raises(TimeoutError):
            pending.result(2.0)
        # the dispatch (first compile of this endpoint's bucket) may outlive
        # the client-side timeout — the dropped-reply counter ticks when
        # the batch is served, so poll for it
        deadline = time.time() + 30.0
        while (m.counters.get("serve.reply_rank_collisions", 0) < 1
               and time.time() < deadline):
            time.sleep(0.02)
        assert m.counters.get("serve.reply_rank_collisions", 0) >= 1
        # the timed-out entry was discarded — a resident client cannot
        # grow its waiting map through lost replies
        assert bad_client._waiting == {}
        # worker 0's route to worker 1 survived: a well-behaved client's
        # top-k request STILL forwards 0 -> 1 and comes back correct
        good = make_client()
        try:
            res = good.request(OP_TOPK, "mf", 5, dest=0, timeout=30.0)
            ref = np.argsort(-(uf[5] @ items.T), kind="stable")[:2]
            assert res["items"] == ref.tolist(), res
            x = rng.normal(size=(12,)).astype(np.float32)
            assert good.request(OP_CLASSIFY, "nn", x, timeout=30.0) == \
                int(nn_model.predict(x[None])[0])
        finally:
            good.close()
    finally:
        bad_client.close()
        for w in workers:
            w.close()


def test_batcher_expired_deadline_and_drain():
    ep = _FakeEndpoint()
    replies, reply = _collecting_reply()
    b = MicroBatcher(ep, reply, max_wait_s=10.0)
    b.submit(_msg(0, deadline_ts=time.time() - 1.0))   # already expired
    b.submit(_msg(1))
    b.drain_and_stop()                   # in-flight batch drains on stop
    assert not b.submit(_msg(2))         # refused once stopping
    by_id = {r["id"]: r for r in replies}
    assert by_id["t-0"]["ok"] is False
    assert protocol.ERR_DEADLINE in by_id["t-0"]["error"]
    assert by_id["t-1"]["ok"] is True
    assert ep.batches == [1]             # only the live request dispatched


# --------------------------------------------------------------------------- #
# jaxlint serve trace targets: zero-collective dispatch is a pinned contract
# --------------------------------------------------------------------------- #

def test_serve_trace_targets_pinned(session):
    import json

    from tools.jaxlint import checkers_jaxpr

    with open(os.path.join(REPO, checkers_jaxpr.BUDGET_FILE)) as f:
        manifest = json.load(f)["targets"]
    # the classify dispatch is pinned at ZERO collectives, zero bytes
    assert manifest["serve_classify_nn"]["collectives"] == {}
    assert manifest["serve_classify_nn"]["bytes_per_step"] == 0
    # the top-k dispatch is pinned at exactly the keyval lookup's routing:
    # bucket_route payload + mask all_to_alls, route_back all_to_all, and
    # the 4-byte route-overflow psum
    assert manifest["serve_topk_mf"]["collectives"] == {
        "all_to_all": 3, "psum": 1}
    assert manifest["serve_topk_mf"]["bytes_by_kind"]["psum"] == 4
    # live traces match the pins (the real JL201/JL203 gate re-checks this
    # over all targets in test_jaxlint; here we pin the serve rows' KINDS)
    counts, dtype_bad, nbytes = checkers_jaxpr.trace_target(
        "serve_classify_nn")
    assert counts == {} and dtype_bad == [] and nbytes == {}
    counts_t, _, nbytes_t = checkers_jaxpr.trace_target("serve_topk_mf")
    assert counts_t == manifest["serve_topk_mf"]["collectives"]
    assert sum(nbytes_t.values()) == \
        manifest["serve_topk_mf"]["bytes_per_step"]


def test_collective_in_classify_dispatch_fails_budget_gate():
    """ISSUE 10 acceptance: an in-dispatch collective fails jaxlint — a
    psum appearing in the (pinned-zero) classify dispatch is JL201 drift."""
    from tools.jaxlint import checkers_jaxpr

    doctored = {"serve_classify_nn": ({"psum": 1}, [], {"psum": 128})}
    findings = checkers_jaxpr.check_budget(REPO, doctored)
    hits = [f for f in findings if f.code == "JL201"
            and f.func == "serve_classify_nn" and "drift" in f.message]
    assert hits, findings
    assert "psum: traced 1 vs pinned 0" in hits[0].message


# --------------------------------------------------------------------------- #
# Load generator row schema (bench.py --only serving)
# --------------------------------------------------------------------------- #

def test_serving_load_row_schema(session):
    from harp_tpu.benchmark import serving_load

    row = serving_load.measure(session, requests_per_mix=24, num_clients=2)
    assert set(row["mixes"]) == {"topk_heavy", "classify_heavy", "mixed"}
    for mix, r in row["mixes"].items():
        assert r["errors"] == 0, (mix, r)
        assert r["requests"] > 0 and r["qps"] > 0
        assert 0 < r["p50_ms"] <= r["p99_ms"], (mix, r)
    # the batching stats prove the retrace contract held under load:
    # every bucket that was touched (warmup reaches each bucket a
    # num_clients closed loop can fill) compiled exactly once
    for name, occ in row["batching"].items():
        assert occ["trace_counts"] and all(
            v == 1 for v in occ["trace_counts"].values()), (name, occ)
    assert row["device"] in ("cpu", "tpu")
    if row["device"] != "tpu":
        assert "re-measures" in row["note"]
