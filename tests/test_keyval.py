"""Typed KV-table tests (reference: keyval/ Key2ValKVTable + typed variants
with per-value combiners — Int2Int/Long2Double family)."""

import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu import combiner as cb
from harp_tpu import keyval as kv

W = 8


def test_kv_merge_combines_like_a_dict(rng):
    store = kv.kv_empty(64, val_shape=(), val_dtype=jnp.float32)
    keys = rng.integers(0, 40, 100).astype(np.int32)
    vals = rng.normal(size=100).astype(np.float32)
    store, ovf = kv.kv_merge(store, jnp.asarray(keys), jnp.asarray(vals))
    assert int(ovf) == 0
    ref = {}
    for k_, v_ in zip(keys, vals):
        ref[int(k_)] = ref.get(int(k_), 0.0) + float(v_)
    assert int(store.count) == len(ref)
    got_v, got_f = kv.kv_lookup(store, jnp.arange(40))
    for k_ in range(40):
        if k_ in ref:
            assert bool(got_f[k_])
            np.testing.assert_allclose(float(got_v[k_]), ref[k_], rtol=1e-5)
        else:
            assert not bool(got_f[k_])

    # second merge combines with existing entries (add-with-combiner)
    store, ovf = kv.kv_merge(store, jnp.asarray(keys[:10]),
                             jnp.asarray(vals[:10]))
    got_v, _ = kv.kv_lookup(store, jnp.asarray(keys[:10]))
    for i in range(10):
        expect = ref[int(keys[i])] + sum(
            float(vals[j]) for j in range(10) if keys[j] == keys[i])
        np.testing.assert_allclose(float(got_v[i]), expect, rtol=1e-5)


def test_kv_merge_max_min_and_masks(rng):
    for comb, npop in ((cb.MAX, np.maximum), (cb.MIN, np.minimum)):
        store = kv.kv_empty(32, val_dtype=jnp.float32)
        keys = np.array([3, 7, 3, 7, 3], np.int32)
        vals = np.array([1.0, -2.0, 5.0, -8.0, 2.0], np.float32)
        mask = np.array([True, True, True, True, False])
        store, _ = kv.kv_merge(store, jnp.asarray(keys), jnp.asarray(vals),
                               comb, mask=jnp.asarray(mask))
        got, found = kv.kv_lookup(store, jnp.asarray([3, 7, 9]), default=-1.0)
        assert float(got[0]) == npop.reduce([1.0, 5.0])
        assert float(got[1]) == npop.reduce([-2.0, -8.0])
        assert float(got[2]) == -1.0 and not bool(found[2])


def test_kv_merge_overflow_counted():
    store = kv.kv_empty(4, val_dtype=jnp.float32)
    keys = jnp.arange(10, dtype=jnp.int32)
    vals = jnp.ones(10, jnp.float32)
    store, ovf = kv.kv_merge(store, keys, vals)
    assert int(ovf) == 6                      # largest 6 keys dropped
    got, found = kv.kv_lookup(store, jnp.arange(10))
    assert bool(np.all(np.asarray(found[:4])))
    assert not bool(np.any(np.asarray(found[4:])))


def test_kv_vector_values(rng):
    store = kv.kv_empty(16, val_shape=(3,), val_dtype=jnp.float32)
    keys = np.array([5, 5, 2], np.int32)
    vals = rng.normal(size=(3, 3)).astype(np.float32)
    store, _ = kv.kv_merge(store, jnp.asarray(keys), jnp.asarray(vals))
    got, _ = kv.kv_lookup(store, jnp.asarray([5, 2]))
    np.testing.assert_allclose(np.asarray(got[0]), vals[0] + vals[1],
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got[1]), vals[2], rtol=1e-5)


def test_distributed_kv_update_and_lookup(session, rng):
    n_local = 16
    keys = rng.integers(0, 200, size=(W, n_local)).astype(np.int32)
    vals = rng.normal(size=(W, n_local)).astype(np.float32)

    def prog(k, v, q):
        table = kv.DistributedKV(kv.kv_empty(128, val_dtype=jnp.float32))
        table, r_ovf, s_ovf = table.update(k[0], v[0], route_cap=n_local)
        out, found = table.lookup(q[0], default=0.0, route_cap=64)
        return out[None], found[None], r_ovf, s_ovf

    queries = np.broadcast_to(np.arange(64, dtype=np.int32), (W, 64)).copy()
    out, found, r_ovf, s_ovf = session.spmd(
        prog,
        in_specs=(session.shard(), session.shard(), session.shard()),
        out_specs=(session.shard(), session.shard(), session.replicate(),
                   session.replicate()))(keys, vals, queries)
    assert int(r_ovf) == 0 and int(s_ovf) == 0
    ref = {}
    for k_, v_ in zip(keys.reshape(-1), vals.reshape(-1)):
        ref[int(k_)] = ref.get(int(k_), 0.0) + float(v_)
    out = np.asarray(out)
    found = np.asarray(found)
    for w in range(W):
        for q in range(64):
            if q in ref:
                assert found[w, q], (w, q)
                np.testing.assert_allclose(out[w, q], ref[q], rtol=1e-4)
            else:
                assert not found[w, q]


def test_distributed_kv_lookup_under_capacity_pressure(session, rng):
    """Capacity-dropped queries must come back (default, False) and the
    surviving answers must land on the RIGHT records (route_back restores
    original order for both values and flags)."""
    # every query targets owner 0, so route_cap=2 drops most queries
    keys = np.zeros((W, 8), np.int32)          # key 0 → owner 0
    keys[:, 1] = 8                             # also owner 0 (8 % 8 == 0)
    vals = np.ones((W, 8), np.float32)
    queries = np.zeros((W, 6), np.int32)
    queries[:, 0] = 8                          # known key
    queries[:, 1] = 16                         # absent key (owner 0)

    def prog(k, v, q):
        table = kv.DistributedKV(kv.kv_empty(64, val_dtype=jnp.float32))
        table, _, _ = table.update(k[0], v[0], route_cap=64)
        out, found = table.lookup(q[0], default=-5.0, route_cap=2)
        return out[None], found[None]

    out, found = session.spmd(
        prog, in_specs=(session.shard(),) * 3,
        out_specs=(session.shard(), session.shard()))(keys, vals, queries)
    out, found = np.asarray(out), np.asarray(found)
    for w in range(W):
        # exactly 2 queries per worker survived the route_cap
        assert found[w].sum() <= 2
        # the first surviving query is the known key with the right value
        assert found[w, 0] and out[w, 0] == W * 1.0
        # absent key that survived routing reports not-found with default
        assert not found[w, 1] and out[w, 1] == -5.0
        # dropped queries come back (default, False) — never stale values
        assert np.all(out[w][~found[w]] == -5.0)


def test_distributed_kv_masked_padding_consumes_no_capacity(session):
    """Padding rows (mask=False) must not occupy worker-0 route slots."""
    n_local = 16
    keys = np.full((W, n_local), 7, np.int32)   # real key 7 (owner 7)
    mask = np.zeros((W, n_local), bool)
    mask[:, 0] = True                           # one real record per worker
    vals = np.ones((W, n_local), np.float32)

    def prog(k, v, m):
        table = kv.DistributedKV(kv.kv_empty(16, val_dtype=jnp.float32))
        # capacity 1: fits the single real record iff padding is excluded
        table, r_ovf, s_ovf = table.update(k[0], v[0], route_cap=1,
                                           mask=m[0])
        out, found = table.lookup(jnp.asarray([7], jnp.int32))
        return out[None], found[None], r_ovf, s_ovf

    out, found, r_ovf, s_ovf = session.spmd(
        prog, in_specs=(session.shard(),) * 3,
        out_specs=(session.shard(), session.shard(), session.replicate(),
                   session.replicate()))(keys, vals, mask)
    assert int(r_ovf) == 0 and int(s_ovf) == 0
    assert np.all(np.asarray(found))
    np.testing.assert_allclose(np.asarray(out).reshape(-1), W * 1.0)


def test_distributed_kv_reports_store_overflow(session, rng):
    keys = rng.integers(0, 1000, size=(W, 32)).astype(np.int32)
    vals = np.ones((W, 32), np.float32)

    def prog(k, v):
        table = kv.DistributedKV(kv.kv_empty(8, val_dtype=jnp.float32))
        table, r_ovf, s_ovf = table.update(k[0], v[0], route_cap=64)
        return r_ovf, s_ovf

    _, s_ovf = session.spmd(
        prog, in_specs=(session.shard(), session.shard()),
        out_specs=(session.replicate(), session.replicate()))(keys, vals)
    assert int(s_ovf) > 0     # 1000 keys over 8 workers x 8 slots must spill


# --------------------------------------------------------------------------- #
# 64-bit key space (Long2DoubleKVTable parity — VERDICT r2 #7)
# --------------------------------------------------------------------------- #

def test_split_join_keys64_roundtrip(rng):
    keys = rng.integers(0, 1 << 61, 1000).astype(np.int64)
    keys[:4] = [0, 1, (1 << 31), (1 << 40) + 12345]   # straddle int32
    hi, lo = kv.split_keys64(keys)
    assert hi.dtype == np.int32 and lo.dtype == np.int32
    np.testing.assert_array_equal(kv.join_keys64(hi, lo), keys)
    with pytest.raises(ValueError, match="64-bit keys"):
        kv.split_keys64(np.array([-1]))
    with pytest.raises(ValueError, match="64-bit keys"):
        kv.split_keys64(np.array([kv._KEY64_MAX]))


def test_kv64_merge_lookup_like_a_dict(rng):
    # keys deliberately beyond 2^31, including pairs equal in hi but not lo
    base = np.int64(1) << 40
    keys = base + rng.integers(0, 50, 200).astype(np.int64)
    keys[::7] += np.int64(1) << 35               # distinct hi values
    vals = rng.normal(size=200).astype(np.float32)
    hi, lo = kv.split_keys64(keys)
    store = kv.kv64_empty(128)
    store, ovf = kv.kv64_merge(store, jnp.asarray(hi), jnp.asarray(lo),
                               jnp.asarray(vals))
    assert int(ovf) == 0
    ref = {}
    for k_, v_ in zip(keys, vals):
        ref[int(k_)] = ref.get(int(k_), 0.0) + float(v_)
    assert int(store.count) == len(ref)
    # store is lexicographically sorted and round-trips to sorted int64 keys
    live = np.asarray(store.hi) != kv.EMPTY
    got_keys = kv.join_keys64(np.asarray(store.hi)[live],
                              np.asarray(store.lo)[live])
    np.testing.assert_array_equal(got_keys, np.sort(list(ref)))
    q_keys = np.array(sorted(ref)[:64] + [123, base - 1], np.int64)
    q_hi, q_lo = kv.split_keys64(q_keys)
    got_v, got_f = kv.kv64_lookup(store, jnp.asarray(q_hi), jnp.asarray(q_lo))
    for i, k_ in enumerate(q_keys):
        if int(k_) in ref:
            assert bool(got_f[i]), k_
            np.testing.assert_allclose(float(got_v[i]), ref[int(k_)],
                                       rtol=1e-4)
        else:
            assert not bool(got_f[i])


def test_kv64_overflow_counted(rng):
    keys = (np.int64(1) << 45) + np.arange(50, dtype=np.int64)
    hi, lo = kv.split_keys64(keys)
    store = kv.kv64_empty(32)
    store, ovf = kv.kv64_merge(store, jnp.asarray(hi), jnp.asarray(lo),
                               jnp.ones(50, np.float32))
    assert int(ovf) == 50 - 32
    assert int(store.count) == 32
    # the SMALLEST keys survive (largest dropped, deterministically)
    live = np.asarray(store.hi) != kv.EMPTY
    got = kv.join_keys64(np.asarray(store.hi)[live],
                         np.asarray(store.lo)[live])
    np.testing.assert_array_equal(got, keys[:32])


def test_distributed_kv64_update_and_lookup(session, rng):
    n_local = 16
    base = np.int64(1) << 50
    keys = base + rng.integers(0, 100, size=(W, n_local)).astype(np.int64)
    vals = rng.normal(size=(W, n_local)).astype(np.float32)
    hi, lo = kv.split_keys64(keys)
    q_keys = base + np.arange(64, dtype=np.int64)
    q_hi, q_lo = kv.split_keys64(np.broadcast_to(q_keys, (W, 64)).copy())

    def prog(h, l, v, qh, ql):
        table = kv.DistributedKV64(kv.kv64_empty(128))
        table, r_ovf, s_ovf = table.update(h[0], l[0], v[0],
                                           route_cap=2 * n_local)
        out, found = table.lookup(qh[0], ql[0], default=0.0, route_cap=64)
        return out[None], found[None], r_ovf, s_ovf

    out, found, r_ovf, s_ovf = session.spmd(
        prog, in_specs=(session.shard(),) * 5,
        out_specs=(session.shard(), session.shard(), session.replicate(),
                   session.replicate()))(hi, lo, vals, q_hi, q_lo)
    assert int(r_ovf) == 0 and int(s_ovf) == 0
    ref = {}
    for k_, v_ in zip(keys.reshape(-1), vals.reshape(-1)):
        ref[int(k_)] = ref.get(int(k_), 0.0) + float(v_)
    out = np.asarray(out)
    found = np.asarray(found)
    for w in range(W):
        for i, k_ in enumerate(q_keys):
            if int(k_) in ref:
                assert found[w, i], (w, i)
                np.testing.assert_allclose(out[w, i], ref[int(k_)],
                                           rtol=1e-4)
            else:
                assert not found[w, i]
