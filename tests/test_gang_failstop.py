"""Gang fail-stop: the launcher kills survivors the moment a member dies,
and the per-member watchdog turns a device hang into that death.

Reference parity: Harp's master logged "Slaves may fail" after the 1800 s
DATA_MAX_WAIT_TIME and the job died (Communication.java:82); workers were
never re-executed (SURVEY §5). Here the same fail-stop contract is enforced
in seconds: parallel.launch polls every member and kills the gang on the
first non-zero exit; parallel.failure.start_gang_watchdog exits a member
whose device misses a heartbeat so the launcher can do so.
"""

import sys
import time

import pytest

from harp_tpu.parallel import failure, launch


def _nodes(n):
    return [launch.Node("localhost", 0) for _ in range(n)]


def test_launch_fail_stop_kills_survivors():
    # member 0 crashes quickly; member 1 would sleep for 120 s (a stand-in
    # for "blocked in the jax.distributed rendezvous"). The launcher must
    # return long before any timeout, having killed member 1.
    cmd = [sys.executable, "-c",
           "import os, sys, time\n"
           "if os.environ['HARP_PROCESS_ID'] == '0':\n"
           "    time.sleep(0.2); sys.exit(3)\n"
           "time.sleep(120)"]
    t0 = time.monotonic()
    results = launch.launch(_nodes(2), cmd, timeout=60.0)
    elapsed = time.monotonic() - t0
    assert elapsed < 30.0, f"fail-stop took {elapsed:.1f}s"
    assert results[0][0] == 3
    assert results[1][0] != 0            # killed, not completed


def test_launch_drains_large_stdout_without_stall():
    # a member writing far beyond the ~64 KB PIPE buffer must not stall the
    # gang (advisor r2: serial reaping let an unreaped member block on write)
    cmd = [sys.executable, "-c",
           "import sys\n"
           "sys.stdout.write('x' * (1 << 20))\n"
           "sys.stdout.write('\\nDONE\\n')"]
    results = launch.launch(_nodes(2), cmd, timeout=60.0)
    for rc, out in results:
        assert rc == 0
        assert out.endswith("DONE\n") and len(out) > (1 << 20)


def test_gang_elastic_restart_resumes_bitwise(tmp_path):
    """The recovery half of fail-stop (VERDICT r4 item 6 — SURVEY §5's
    designated upgrade over the reference's rerun-from-iteration-0): kill
    one gang member MID-fit_checkpointed, relaunch the gang on the same
    work dir, and the resumed run's final model is BITWISE identical to an
    uninterrupted run. The kill triggers the launcher's fail-stop (the
    survivor is killed too), and the atomic checkpoint rename guarantees
    the work dir only ever shows complete checkpoints."""
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def km_cmd(work):
        return [sys.executable, "-m", "harp_tpu.run", "kmeans", "--cpu-mesh",
                "--num-workers", "2", "--num-points", "512",
                "--num-centroids", "4", "--dim", "8", "--iterations", "8",
                "--work-dir", str(work), "--save-every", "2"]

    # uninterrupted reference run
    work_a = tmp_path / "a"
    results = launch.launch(_nodes(2), km_cmd(work_a), timeout=420.0,
                            cwd=repo)
    assert all(rc == 0 for rc, _ in results), results
    ref = (work_a / "centroids.csv").read_bytes()

    # interrupted run: member 1 exits DETERMINISTICALLY at its second
    # checkpoint-boundary save() call (step 4 of 8) — mid-run by
    # construction, no polling race; the launcher's fail-stop then kills
    # member 0, which cannot progress anyway (chunk 5-6's collectives need
    # the dead member)
    work_b = tmp_path / "b"
    killer = [sys.executable, "-c",
              "import os, sys, runpy\n"
              "if os.environ.get('HARP_PROCESS_ID') == '1':\n"
              "    from harp_tpu.utils import checkpoint as ck\n"
              "    orig = ck.Checkpointer.save\n"
              "    calls = {'n': 0}\n"
              "    def save_then_die(self, step, state, **kw):\n"
              "        r = orig(self, step, state, **kw)\n"
              "        calls['n'] += 1\n"
              "        if calls['n'] == 2:\n"
              "            os._exit(9)\n"
              "        return r\n"
              "    ck.Checkpointer.save = save_then_die\n"
              "sys.argv = ['harp_tpu.run'] + sys.argv[1:]\n"
              "runpy.run_module('harp_tpu.run', run_name='__main__')\n",
              ] + km_cmd(work_b)[3:]
    results = launch.launch(_nodes(2), killer, timeout=420.0, cwd=repo)
    rcs = sorted(rc for rc, _ in results)
    assert 9 in rcs, results                 # the killed member
    assert not (work_b / "centroids.csv").exists()   # died mid-run
    kept = sorted(p.name for p in (work_b / "ckpt").iterdir()
                  if p.name.startswith("step_"))
    assert kept, "no checkpoint survived the kill"

    # elastic restart: same command, same work dir — resumes from the
    # newest checkpoint and completes
    results = launch.launch(_nodes(2), km_cmd(work_b), timeout=420.0,
                            cwd=repo)
    assert all(rc == 0 for rc, _ in results), results
    assert (work_b / "centroids.csv").read_bytes() == ref


def test_launch_timeout_kills_gang():
    cmd = [sys.executable, "-c", "import time; time.sleep(120)"]
    t0 = time.monotonic()
    with pytest.raises(Exception):       # subprocess.TimeoutExpired
        launch.launch(_nodes(2), cmd, timeout=2.0)
    assert time.monotonic() - t0 < 30.0


def test_watchdog_injected_probe_failure():
    hits = []
    wd = failure.Watchdog(interval_s=0.02, timeout_s=0.1,
                          on_failure=lambda: hits.append(1),
                          probe=lambda t: False)
    wd.start()
    deadline = time.monotonic() + 5.0
    while not wd.failed and time.monotonic() < deadline:
        time.sleep(0.02)
    wd.stop()
    assert wd.failed and hits == [1]
    with pytest.raises(failure.WorkerFailure):
        wd.ok()


def test_gang_watchdog_chain_device_hang_fails_the_gang():
    # the full chain: member 0's device "hangs" (probe stubbed to fail) →
    # gang watchdog exits the process with GANG_WATCHDOG_EXIT → the
    # launcher's poll loop kills member 1, which was sleeping toward 120 s
    cmd = [sys.executable, "-c",
           "import os, time\n"
           "from harp_tpu.parallel import failure\n"
           "if os.environ['HARP_PROCESS_ID'] == '0':\n"
           "    failure.probe_devices = lambda t: False\n"
           "    failure.start_gang_watchdog(interval_s=0.1, timeout_s=0.1)\n"
           "time.sleep(120)"]
    t0 = time.monotonic()
    results = launch.launch(_nodes(2), cmd, timeout=60.0)
    elapsed = time.monotonic() - t0
    assert elapsed < 30.0, f"watchdog fail-stop took {elapsed:.1f}s"
    assert results[0][0] == failure.GANG_WATCHDOG_EXIT
    assert results[1][0] != 0


def test_gang_watchdog_env_disable(monkeypatch):
    monkeypatch.setenv("HARP_WATCHDOG", "0")
    assert failure.start_gang_watchdog() is None


def test_first_failure_lowest_rank_within_one_poll_interval():
    """The launch.py:52-57 contract, previously documented but untested:
    when SEVERAL members die within one poll interval, first_failure blames
    the LOWEST rank — even if a higher rank died first in wall time. Ranks
    exit in reverse order (rank 2 first) well inside a single long poll
    interval, so one sweep observes all three dead and must pick rank 0."""
    cmd = [sys.executable, "-c",
           "import os, sys, time\n"
           "rank = int(os.environ['HARP_PROCESS_ID'])\n"
           "time.sleep(1.0 - 0.3 * rank)\n"    # rank 2 dies FIRST
           "sys.exit(10 + rank)"]
    results = launch.launch(_nodes(3), cmd, timeout=60.0, poll_interval=3.0)
    assert results.first_failure == (0, 10)
    # every member's own exit code is still reported faithfully
    assert [rc for rc, _ in results] == [10, 11, 12]
