"""jaxlint (tools/jaxlint) — tier-1.

Three layers, mirroring tests/test_check_claims.py's contract style:

* fixture snippets with KNOWN violations assert the exact finding codes
  each checker raises (and that the clean twin of each snippet is silent);
* the repo itself must lint clean (this is the tier-1 wiring — a new
  violation anywhere in harp_tpu/ fails the suite, so DOTS_PASSED captures
  the lint exactly like the scatter lint it absorbed);
* the allowlist contract: justifications are mandatory, stale entries fail;
* the jaxpr engine: traced collective budgets must match the committed
  tools/collective_budget.json, and drift is detected loudly.
"""

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.jaxlint import checkers_jaxpr  # noqa: E402
from tools.jaxlint import checkers_ast as ca  # noqa: E402
from tools.jaxlint.allowlist import ALLOWLIST  # noqa: E402
from tools.jaxlint.core import (Finding, apply_allowlist,  # noqa: E402
                                run_ast_checkers, validate_allowlist)


def _run(checker, src, rel="harp_tpu/models/fake.py"):
    return checker(ast.parse(src), rel, src)


def _codes(findings):
    return [f.code for f in findings]


# -- JL101 collective-divergence -------------------------------------------

def test_collective_in_rank_branch_is_flagged():
    src = (
        "def step(x):\n"
        "    wid = lax_ops.worker_id()\n"
        "    if wid == 0:\n"
        "        x = jax.lax.psum(x, 'workers')\n"
        "    return x\n")
    got = _run(ca.check_collective_divergence, src)
    assert _codes(got) == ["JL101"]
    assert got[0].func == "step" and "psum" in got[0].message


def test_collective_divergence_nested_and_else_branch():
    src = (
        "def step(x):\n"
        "    if jax.process_index() != 0:\n"
        "        y = 1\n"
        "    else:\n"
        "        for _ in range(3):\n"
        "            x = lax_ops.allgather(x)\n"
        "    return x\n")
    assert _codes(_run(ca.check_collective_divergence, src)) == ["JL101"]


def test_masked_contribution_idiom_is_clean():
    # the lax_ops.broadcast shape: EVERY worker calls the collective, the
    # rank condition only masks the contribution — no divergence
    src = (
        "def bcast(x, root):\n"
        "    mask = jax.lax.axis_index('workers') == root\n"
        "    return jax.lax.psum(jnp.where(mask, x, 0.0), 'workers')\n")
    assert _run(ca.check_collective_divergence, src) == []
    # rank-conditional HOST work (no collective inside) is also fine
    src2 = (
        "def save(x):\n"
        "    if jax.process_index() == 0:\n"
        "        np.savetxt('out.csv', x)\n")
    assert _run(ca.check_collective_divergence, src2) == []


# -- JL102 axis-name --------------------------------------------------------

def test_unknown_axis_literal_is_flagged():
    src = (
        "def step(x):\n"
        "    return jax.lax.psum(x, axis_name='worker')\n")   # typo'd axis
    got = _run(ca.check_axis_name, src)
    assert _codes(got) == ["JL102"] and "'worker'" in got[0].message


def test_declared_or_canonical_axes_are_clean():
    src = (
        "MY_AXIS = 'ring'\n"
        "def step(x, mesh):\n"
        "    a = jax.lax.psum(x, 'workers')\n"        # canonical
        "    b = jax.lax.all_gather(x, 'ring')\n"     # declared above
        "    c = lax_ops.allreduce(x, axis_name=WORKERS)\n"  # constant ref
        "    return a, b, c\n")
    assert _run(ca.check_axis_name, src) == []


# -- JL103 retrace-hazard ---------------------------------------------------

def test_immediately_invoked_jit_is_flagged():
    src = (
        "def fit(sess, x):\n"
        "    return sess.spmd(lambda a: a + 1, in_specs=s, out_specs=s)(x)\n")
    got = _run(ca.check_retrace_hazard, src)
    assert _codes(got) == ["JL103"] and "one expression" in got[0].message


def test_jit_in_loop_without_cache_guard_is_flagged():
    src = (
        "def fit(sess, xs):\n"
        "    for x in xs:\n"
        "        f = jax.jit(step)\n"
        "        f(x)\n")
    assert _codes(_run(ca.check_retrace_hazard, src)) == ["JL103"]
    # the repo's cache idiom is clean: the wrapper is STORED in a container
    guarded = (
        "def fit(self, sess, xs):\n"
        "    for x in xs:\n"
        "        if x.shape not in self._fns:\n"
        "            self._fns[x.shape] = jax.jit(step)\n"
        "        self._fns[x.shape](x)\n")
    assert _run(ca.check_retrace_hazard, guarded) == []
    # an unrelated `not in` membership test is NOT a cache: a plain-name
    # bind inside it still rebuilds the wrapper every iteration
    skip_filter = (
        "def fit(sess, xs):\n"
        "    for x in xs:\n"
        "        if x.tag not in SKIP:\n"
        "            f = jax.jit(step)\n"
        "            f(x)\n")
    assert _codes(_run(ca.check_retrace_hazard, skip_filter)) == ["JL103"]


def test_jitted_mutable_default_and_global_are_flagged():
    src = (
        "@jax.jit\n"
        "def step(x, opts={}):\n"
        "    return x\n")
    assert _codes(_run(ca.check_retrace_hazard, src)) == ["JL103"]
    src2 = (
        "@partial(jax.jit, static_argnums=(1,))\n"
        "def step(x, n):\n"
        "    global _SCALE\n"
        "    return x * _SCALE\n")
    assert _codes(_run(ca.check_retrace_hazard, src2)) == ["JL103"]
    # plain decorated function with hashable defaults is clean
    assert _run(ca.check_retrace_hazard,
                "@jax.jit\ndef step(x, n=3):\n    return x * n\n") == []


# -- JL104 host-sync-hot-loop ----------------------------------------------

def test_host_sync_inside_fit_loop_is_flagged():
    src = (
        "def fit(self, xs):\n"
        "    costs = []\n"
        "    for x in xs:\n"
        "        c = self._step(x)\n"
        "        costs.append(np.asarray(c).tolist())\n"
        "        c.block_until_ready()\n"
        "        n = c.item()\n"
        "    return costs\n")
    got = _run(ca.check_host_sync, src)
    assert _codes(got) == ["JL104"] * 3


def test_host_sync_outside_loop_or_fit_is_clean():
    # after the loop: one sync per fit is fine
    src = ("def fit(self, xs):\n"
           "    for x in xs:\n"
           "        c = self._step(x)\n"
           "    return np.asarray(c)\n")
    assert _run(ca.check_host_sync, src) == []
    # not a fit/train path: loaders may asarray per file
    src2 = ("def load(paths):\n"
            "    return [np.asarray(read(p)) for p in paths]\n")
    assert _run(ca.check_host_sync, src2) == []
    # timing.py is the sanctioned sync site
    src3 = ("def fit_timed(self, xs):\n"
            "    for x in xs:\n"
            "        self._step(x).block_until_ready()\n")
    assert ca.check_host_sync(ast.parse(src3),
                              "harp_tpu/benchmark/timing.py", src3) == []


# -- JL105 broad-except -----------------------------------------------------

def test_broad_except_variants_are_flagged():
    src = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except (ValueError, BaseException):\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except:\n"
        "        pass\n")
    assert _codes(_run(ca.check_broad_except, src)) == ["JL105"] * 3
    assert _run(ca.check_broad_except,
                "def f():\n"
                "    try:\n"
                "        import scipy\n"
                "    except ImportError:\n"
                "        scipy = None\n") == []


# -- JL106 scatter (folded lint_scatter) ------------------------------------

def test_scatter_in_hot_tree_flagged_and_cold_tree_exempt():
    src = "def hot(x, i, v):\n    return x.at[i].add(v)\n"
    assert _codes(_run(ca.check_scatter, src,
                       "harp_tpu/models/fake.py")) == ["JL106"]
    assert _codes(_run(ca.check_scatter, src,
                       "harp_tpu/ops/fake.py")) == ["JL106"]
    # gathers and non-hot trees don't trip
    assert _run(ca.check_scatter, "def f(x, i):\n    return x[i]\n",
                "harp_tpu/models/fake.py") == []
    assert _run(ca.check_scatter, src, "harp_tpu/parallel/fake.py") == []


# -- allowlist contract -----------------------------------------------------

def test_allowlist_suppresses_and_staleness_fails():
    f = Finding("JL105", "broad-except", "harp_tpu/models/fake.py", 3,
                "f", "msg")
    ok = {("harp_tpu/models/fake.py", "f", "JL105"):
          "a justification long enough to satisfy the schema"}
    active, stale = apply_allowlist([f], ok)
    assert active == [] and stale == []
    # same entry with no matching finding -> stale, loudly
    active, stale = apply_allowlist([], ok)
    assert active == [] and len(stale) == 1 and "prune" in stale[0]


def test_allowlist_requires_real_justifications():
    assert validate_allowlist(
        {("a.py", "f", "JL105"): "ok"}) != []            # too short
    assert validate_allowlist({("a.py", "f"): "x" * 40}) != []   # bad key
    assert validate_allowlist(
        {("a.py", "f", "JL105"): "cold prepare-side layout, runs once"}
    ) == []


def test_committed_allowlist_is_schema_valid_and_live():
    assert validate_allowlist(ALLOWLIST) == []
    raw = run_ast_checkers(REPO, ca.ast_checkers_for_repo(REPO))
    _active, stale = apply_allowlist(raw, ALLOWLIST)
    assert stale == [], "\n".join(stale)


# -- the repo itself lints clean (tier-1 wiring) ----------------------------

def test_repo_is_clean_under_all_ast_checkers():
    raw = run_ast_checkers(REPO, ca.ast_checkers_for_repo(REPO))
    active, _stale = apply_allowlist(raw, ALLOWLIST)
    assert active == [], "\n".join(str(f) for f in active)


# -- jaxpr engine: collective budget + dtype policy -------------------------

def test_traced_budgets_match_committed_manifest(session):
    # `session` fixture guarantees the 8-device mesh is up; trace_all then
    # reuses the already-initialized backend
    traced = checkers_jaxpr.trace_all()
    findings = checkers_jaxpr.check_budget(REPO, traced)
    assert findings == [], "\n".join(str(f) for f in findings)
    # the manifest's collective KINDS are the comm contract: the flagship
    # regroupallgather variant must stay reduce_scatter+all_gather (+ the
    # cost psum), not degrade to, e.g., a pair of psums
    counts, dtype_bad, nbytes = traced["kmeans_regroupallgather"]
    assert counts == {"psum": 1, "reduce_scatter": 1, "all_gather": 1}
    assert dtype_bad == []
    # the byte contract: every target carries per-kind operand bytes, and
    # the quantized twins sit well below their f32 programs — a quantized
    # path silently reverting to f32 moves these and fails JL203
    f32_bytes = sum(traced["kmeans_allreduce"][2].values())
    int8_bytes = sum(traced["kmeans_allreduce_int8"][2].values())
    assert 0 < int8_bytes < f32_bytes / 2, (int8_bytes, f32_bytes)
    assert sum(traced["sgd_mf_dense_int8"][2].values()) < sum(
        traced["sgd_mf_dense"][2].values())
    # the quantized SERVING wire (ISSUE 17): same route/route-back shape
    # (3 all_to_all + 1 psum), strictly fewer bytes than the f32 dispatch
    # — an endpoint silently reverting to f32 payloads fails JL203 here
    serve_counts, _, serve_f32 = traced["serve_topk_mf"]
    serve_counts_i8, _, serve_i8 = traced["serve_topk_mf_int8"]
    assert serve_counts_i8 == serve_counts
    assert 0 < sum(serve_i8.values()) < sum(serve_f32.values())
    assert sum(nbytes.values()) > 0


def test_budget_drift_and_stale_rows_are_loud():
    traced = {"kmeans_regroupallgather": ({"psum": 5}, [], {"psum": 20})}
    findings = checkers_jaxpr.check_budget(REPO, traced)
    msgs = "\n".join(f.message for f in findings)
    # count drift on the one traced target...
    assert any(f.code == "JL201" and "drift" in f.message
               and f.func == "kmeans_regroupallgather" for f in findings)
    assert "traced 5 vs pinned 1" in msgs
    # ...and every other committed row reports as stale/unmatched
    assert any("matches no trace target" in f.message for f in findings)


def test_byte_budget_drift_is_loud_at_same_counts():
    # JL203's reason to exist: SAME collective counts, different operand
    # bytes (the silently-dropped-quantization signature) must fail even
    # though JL201 sees no drift
    import json

    with open(os.path.join(REPO, checkers_jaxpr.BUDGET_FILE)) as f:
        manifest = json.load(f)
    row = manifest["targets"]["kmeans_allreduce"]
    counts = dict(row["collectives"])
    widened = {k: 4 * v for k, v in row["bytes_by_kind"].items()}
    traced = {"kmeans_allreduce": (counts, [], widened)}
    findings = checkers_jaxpr.check_budget(REPO, traced)
    assert not any(f.code == "JL201" and f.func == "kmeans_allreduce"
                   for f in findings)
    hits = [f for f in findings
            if f.code == "JL203" and f.func == "kmeans_allreduce"]
    assert hits and "byte-budget drift" in hits[0].message
    # a manifest row lacking bytes_per_step is itself a finding
    clean = {"kmeans_allreduce": (counts, [],
                                  dict(row["bytes_by_kind"]))}
    assert not any(f.func == "kmeans_allreduce"
                   for f in checkers_jaxpr.check_budget(REPO, clean))


def test_dtype_policy_reports_bf16_accumulation():
    import jax
    import jax.numpy as jnp

    def bad(a, b):
        return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())))

    x = jnp.zeros((4, 4), jnp.bfloat16)
    closed = jax.make_jaxpr(bad)(x, x)
    counts, dtype_bad = {}, []
    checkers_jaxpr._walk(closed.jaxpr, counts, dtype_bad, {})
    assert any("bf16" in m for m in dtype_bad)

    def good(a, b):
        return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    counts, dtype_bad = {}, []
    checkers_jaxpr._walk(jax.make_jaxpr(good)(x, x).jaxpr, counts, dtype_bad,
                         {})
    assert dtype_bad == []


# -- JL3xx concurrency engine (ISSUE 13 tentpole) ---------------------------

from tools.jaxlint.checkers_threads import check_concurrency  # noqa: E402

_HOST_REL = "harp_tpu/serve/fake.py"


def _runc(src, rel=_HOST_REL):
    return check_concurrency(ast.parse(src), rel, src)


def test_jl301_doctored_unguarded_shared_write_fails_loudly():
    # the acceptance fixture: a receive-loop thread writes state the main
    # thread reads, no lock anywhere — the PR 10-12 hand-review bug class
    src = (
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._thread = threading.Thread(target=self._loop,\n"
        "                                        daemon=True)\n"
        "    def _loop(self):\n"
        "        self.state = 'running'\n"
        "    def poke(self):\n"
        "        return self.state\n")
    got = _runc(src)
    assert _codes(got) == ["JL301"]
    assert got[0].func == "_loop" and "self.state" in got[0].message
    assert "thread:_loop" in got[0].message


def test_jl301_guarded_write_twin_is_clean():
    src = (
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._thread = threading.Thread(target=self._loop,\n"
        "                                        daemon=True)\n"
        "    def _loop(self):\n"
        "        with self._lock:\n"
        "            self.state = 'running'\n"
        "    def poke(self):\n"
        "        with self._lock:\n"
        "            return self.state\n")
    assert _runc(src) == []
    # an Event signal instead of a bare flag is also clean (sync
    # primitives manage their own safety)
    src2 = (
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._draining = threading.Event()\n"
        "        self._thread = threading.Thread(target=self._loop,\n"
        "                                        daemon=True)\n"
        "    def _loop(self):\n"
        "        if self._draining.is_set():\n"
        "            return\n"
        "    def begin_drain(self):\n"
        "        self._draining.set()\n")
    assert _runc(src2) == []


def test_jl301_only_fires_in_host_trees():
    src = (
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._thread = threading.Thread(target=self._loop,\n"
        "                                        daemon=True)\n"
        "    def _loop(self):\n"
        "        self.state = 1\n"
        "    def poke(self):\n"
        "        return self.state\n")
    assert _runc(src, "harp_tpu/models/fake.py") == []
    assert _codes(_runc(src, "harp_tpu/telemetry/fake.py")) == ["JL301"]


def test_jl302_unsynchronized_rmw_and_check_then_act_are_flagged():
    src = (
        "class Counter:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "        self._d = {}\n"
        "        self._thread = threading.Thread(target=self._loop,\n"
        "                                        daemon=True)\n"
        "    def _loop(self):\n"
        "        self._n += 1\n"
        "        if 'k' in self._d:\n"
        "            self._d.pop('k')\n"
        "    def read(self):\n"
        "        return self._n, self._d.get('k')\n")
    got = _runc(src)
    assert _codes(got) == ["JL302", "JL302"]
    assert "read-modify-write" in got[0].message
    assert "check-then-act" in got[1].message


def test_jl302_guarded_rmw_twin_is_clean():
    src = (
        "class Counter:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n"
        "        self._d = {}\n"
        "        self._thread = threading.Thread(target=self._loop,\n"
        "                                        daemon=True)\n"
        "    def _loop(self):\n"
        "        with self._lock:\n"
        "            self._n += 1\n"
        "            if 'k' in self._d:\n"
        "                self._d.pop('k')\n"
        "    def read(self):\n"
        "        with self._lock:\n"
        "            return self._n, self._d.get('k')\n")
    assert _runc(src) == []


def test_jl303_doctored_lock_order_inversion_fails_loudly():
    src = (
        "class AB:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n")
    got = _runc(src)
    assert _codes(got) == ["JL303"]
    assert "deadlock" in got[0].message.lower()
    assert "_a" in got[0].message and "_b" in got[0].message


def test_jl303_cross_method_inversion_via_call_under_lock():
    # one() holds _a and CALLS a method that takes _b; two() nests b -> a
    src = (
        "class AB:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            self.take_b()\n"
        "    def take_b(self):\n"
        "        with self._b:\n"
        "            pass\n"
        "    def two(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n")
    assert _codes(_runc(src)) == ["JL303"]


def test_jl303_consistent_order_twin_is_clean():
    src = (
        "class AB:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n")
    assert _runc(src) == []


def test_jl304_unjoined_non_daemon_thread_is_flagged():
    src = (
        "class Spawner:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._loop)\n"
        "        self._t.start()\n"
        "    def _loop(self):\n"
        "        pass\n")
    got = _runc(src)
    assert _codes(got) == ["JL304"] and "self._t" in got[0].message
    # module-level function variant
    src2 = (
        "def fire_and_forget(fn):\n"
        "    t = threading.Thread(target=fn)\n"
        "    t.start()\n")
    assert _codes(_runc(src2)) == ["JL304"]


def test_jl304_joined_or_daemon_twins_are_clean():
    joined = (
        "class Spawner:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._loop)\n"
        "        self._t.start()\n"
        "    def _loop(self):\n"
        "        pass\n"
        "    def close(self):\n"
        "        self._t.join(5.0)\n")
    assert _runc(joined) == []
    daemon = (
        "class Spawner:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._loop, daemon=True)\n"
        "        self._t.start()\n"
        "    def _loop(self):\n"
        "        pass\n")
    assert _runc(daemon) == []
    # local thread joined in the same function
    local = (
        "def run_and_wait(fn):\n"
        "    t = threading.Thread(target=fn)\n"
        "    t.start()\n"
        "    t.join()\n")
    assert _runc(local) == []


def test_jl3xx_callback_protocol_flags_hook_state():
    # __call__ is the hook/callback protocol: registered by one thread,
    # invoked by another — public attrs written there are the class's
    # cross-thread read surface (the GangCollector/exporter race)
    src = (
        "class Hook:\n"
        "    def __call__(self, i, log):\n"
        "        self.last = i\n")
    got = _runc(src, "harp_tpu/telemetry/fake.py")
    assert _codes(got) == ["JL301"]
    # a lock-guarded publish is the clean twin
    src2 = (
        "class Hook:\n"
        "    def __init__(self):\n"
        "        self._publish_lock = threading.Lock()\n"
        "    def __call__(self, i, log):\n"
        "        with self._publish_lock:\n"
        "            self._last = i\n"
        "    @property\n"
        "    def last(self):\n"
        "        with self._publish_lock:\n"
        "            return self._last\n")
    assert _runc(src2, "harp_tpu/telemetry/fake.py") == []


def test_jl3xx_rides_the_allowlist_and_staleness_contract():
    # suppression and the staleness guarantee extend to JL3xx unchanged
    f = Finding("JL301", "unguarded-shared-write", _HOST_REL, 7, "_loop",
                "msg")
    ok = {(_HOST_REL, "_loop", "JL301"):
          "sticky single-writer flag, GIL-atomic store, reader tolerates "
          "one-interval staleness"}
    active, stale = apply_allowlist([f], ok)
    assert active == [] and stale == []
    active, stale = apply_allowlist([], ok)
    assert active == [] and len(stale) == 1 and "prune" in stale[0]


def test_repo_host_plane_is_clean_under_concurrency_checker():
    # the tentpole's acceptance: the checker runs clean on the repo, with
    # every pre-existing real finding fixed or individually justified
    raw = run_ast_checkers(REPO, [check_concurrency])
    active, _stale = apply_allowlist(raw, ALLOWLIST)
    assert active == [], "\n".join(str(f) for f in active)
    # ... and the justified exemptions are LIVE findings, not blanket
    # passes: the raw run still sees the allowlisted sites
    raw_keys = {f.key for f in raw}
    for key in [k for k in ALLOWLIST if k[2].startswith("JL30")]:
        assert key in raw_keys, f"stale JL3xx allowlist entry {key}"


# -- gang-mode collective budgets (ISSUE 13 tentpole, part 2) ---------------

import copy  # noqa: E402
import json  # noqa: E402


def _gang_manifest_rows():
    with open(os.path.join(REPO, checkers_jaxpr.BUDGET_FILE)) as f:
        return json.load(f)["gang_targets"]


def _as_traced(rows):
    return {name: dict(row, _dtype_bad=[]) for name, row in rows.items()}


def test_gang_manifest_pins_three_plus_targets_with_link_split():
    rows = _gang_manifest_rows()
    assert len(rows) >= 3, sorted(rows)
    for name, row in rows.items():
        assert row["processes"] >= 2, name
        assert row["processes"] * row["devices_per_process"] == 8, name
        assert row["per_process_shard_shapes"], name
        # the link split partitions bytes_by_kind exactly, per kind
        for kind, b in row["bytes_by_kind"].items():
            dcn = row["bytes_by_link"]["dcn"][kind]
            ici = row["bytes_by_link"]["ici"][kind]
            assert dcn + ici == b, (name, kind)
            assert dcn > 0, (name, kind)   # a 2-process gang always
            #                                crosses the DCN
        assert row["dcn_bytes_per_step"] == sum(
            row["bytes_by_link"]["dcn"].values()), name
    # manifest rows self-check clean against themselves
    assert checkers_jaxpr.check_gang_budget(REPO, _as_traced(rows)) == []


def test_gang_doctored_dcn_byte_count_fails_jl203():
    # the acceptance criterion: doctoring a DCN byte count fails JL203
    rows = _as_traced(_gang_manifest_rows())
    name = sorted(rows)[0]
    row = copy.deepcopy(rows[name])
    kind = sorted(row["bytes_by_link"]["dcn"])[0]
    row["bytes_by_link"]["dcn"][kind] += 4096
    row["dcn_bytes_per_step"] += 4096
    doctored = dict(rows, **{name: row})
    findings = checkers_jaxpr.check_gang_budget(REPO, doctored)
    hits = [f for f in findings if f.code == "JL203" and f.func == name]
    assert hits and "DCN" in hits[0].message, findings
    assert not any(f.code == "JL201" and f.func == name for f in findings)


def test_gang_doctored_shard_shape_fails_jl201():
    rows = _as_traced(_gang_manifest_rows())
    name = sorted(rows)[0]
    row = copy.deepcopy(rows[name])
    row["per_process_shard_shapes"][0][0] *= 2
    findings = checkers_jaxpr.check_gang_budget(
        REPO, dict(rows, **{name: row}))
    hits = [f for f in findings if f.code == "JL201" and f.func == name]
    assert hits and "shard shapes" in hits[0].message, findings


def test_gang_missing_and_stale_rows_are_loud():
    rows = _as_traced(_gang_manifest_rows())
    # a gang target with no manifest row
    extra = dict(rows)
    extra["gang2x4_new_workload"] = copy.deepcopy(
        rows[sorted(rows)[0]])
    findings = checkers_jaxpr.check_gang_budget(REPO, extra)
    assert any(f.code == "JL201" and "no manifest row" in f.message
               for f in findings)
    # a manifest row whose target vanished
    short = dict(rows)
    dropped = sorted(short)[0]
    del short[dropped]
    findings = checkers_jaxpr.check_gang_budget(REPO, short)
    assert any(f.code == "JL201" and f.func == dropped
               and "stale" in f.message for f in findings)


def test_split_bytes_by_link_edge_model():
    split = checkers_jaxpr.split_bytes_by_link
    # ring kinds: P of W edges cross the DCN -> 2/8 here
    out = split({"ppermute": 800}, world=8, processes=2,
                devices_per_process=4, link_class="dcn")
    assert out["dcn"]["ppermute"] == 200
    assert out["ici"]["ppermute"] == 600
    # all_to_all: W-D of W-1 peers are remote -> 4/7
    out = split({"all_to_all": 700}, world=8, processes=2,
                devices_per_process=4, link_class="dcn")
    assert out["dcn"]["all_to_all"] == 400
    assert out["ici"]["all_to_all"] == 300
    # floor split still sums exactly on odd byte counts
    out = split({"ppermute": 101}, world=8, processes=2,
                devices_per_process=4, link_class="dcn")
    assert out["dcn"]["ppermute"] + out["ici"]["ppermute"] == 101
    # a single-pod gang (workers axis hinted ici) books everything as ICI
    out = split({"ppermute": 800}, world=8, processes=2,
                devices_per_process=4, link_class="ici")
    assert out["dcn"]["ppermute"] == 0 and out["ici"]["ppermute"] == 800


def test_gang_traced_budgets_match_committed_manifest(session):
    # the end-to-end gate: retracing the gang registry on the live mesh
    # reproduces the committed rows exactly (any drift is loud)
    gang = checkers_jaxpr.trace_gang_all()
    findings = checkers_jaxpr.check_gang_budget(REPO, gang)
    assert findings == [], "\n".join(str(f) for f in findings)
    assert len(gang) >= 3
    for name, row in gang.items():
        assert row["_dtype_bad"] == [], name


# -- --json machine-readable output (ISSUE 13 satellite) --------------------


def test_json_output_one_finding_per_line(tmp_path, capsys):
    pkg = tmp_path / "harp_tpu" / "serve"
    pkg.mkdir(parents=True)
    (pkg / "racy.py").write_text(
        "import threading\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._thread = threading.Thread(target=self._loop,\n"
        "                                        daemon=True)\n"
        "    def _loop(self):\n"
        "        self.state = 1\n"
        "    def poke(self):\n"
        "        return self.state\n")
    from tools.jaxlint.__main__ import main as jaxlint_main

    rc = jaxlint_main([str(tmp_path), "--ast-only", "--json"])
    out = capsys.readouterr().out
    assert rc == 1
    lines = [json.loads(line) for line in out.strip().splitlines()]
    assert lines, out
    for rec in lines:
        assert {"file", "line", "code", "checker", "func", "message",
                "allowlisted"} <= set(rec), rec
    jl301 = [r for r in lines if r["code"] == "JL301"]
    assert jl301 and jl301[0]["file"] == "harp_tpu/serve/racy.py"
    assert jl301[0]["line"] == 7 and jl301[0]["func"] == "_loop"
    assert jl301[0]["allowlisted"] is False
    # human-mode summary lines must NOT pollute the JSONL stream
    assert not any(line.startswith(("ast engine", "jaxlint"))
                   for line in out.strip().splitlines())


def test_json_stale_allowlist_records_ride_the_jsonl_stream(tmp_path,
                                                            capsys):
    (tmp_path / "harp_tpu").mkdir()
    (tmp_path / "harp_tpu" / "clean.py").write_text("X = 1\n")
    from tools.jaxlint.__main__ import main as jaxlint_main

    rc = jaxlint_main([str(tmp_path), "--ast-only", "--json"])
    out = capsys.readouterr().out
    # the fixture tree itself is clean, but the committed allowlist is
    # stale against it — staleness must surface as machine-readable
    # records on the same stream (and keep the nonzero exit), never as
    # human prose polluting the JSONL
    lines = [json.loads(line) for line in out.strip().splitlines()]
    assert lines and all(rec["code"] == "stale-allowlist" for rec in lines)
    assert rc == 1  # stale entries are findings by contract


def test_json_deferred_callback_write_is_not_guard_shadowed():
    # a closure DEFINED under a lock executes later without it: its
    # unguarded write must still fire (the guard state does not leak in)
    src = (
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._thread = threading.Thread(target=self._loop,\n"
        "                                        daemon=True)\n"
        "    def make_cb(self):\n"
        "        with self._lock:\n"
        "            def cb():\n"
        "                self.state = 1\n"
        "            self._cb = cb\n"
        "    def _loop(self):\n"
        "        self.state = 2\n"
        "    def poke(self):\n"
        "        return self.state\n")
    got = _runc(src)
    assert sorted((f.func, f.code) for f in got) == [
        ("_loop", "JL301"), ("make_cb", "JL301")], got


def test_jl301_nested_fn_thread_target_makes_method_a_root():
    # a Thread targeting a function NESTED in a method: the closure's
    # unguarded cross-thread write must fire (the enclosing method hosts
    # the thread domain)
    src = (
        "class Worker:\n"
        "    def start(self):\n"
        "        def loop():\n"
        "            self.state = 1\n"
        "        threading.Thread(target=loop, daemon=True).start()\n"
        "    def poke(self):\n"
        "        return self.state\n")
    got = _runc(src)
    assert [(f.func, f.code) for f in got] == [("start", "JL301")], got


# -- JL4xx static memory engine (ISSUE 19) ----------------------------------

import numpy as np  # noqa: E402

from harp_tpu.aot import static_memory  # noqa: E402
from tools.jaxlint import checkers_memory  # noqa: E402


def _memory_manifest_rows():
    with open(os.path.join(REPO, checkers_memory.BUDGET_FILE)) as f:
        return json.load(f)["memory"]


def test_memory_manifest_pins_twelve_plus_targets():
    rows = _memory_manifest_rows()
    assert len(rows) >= 12, sorted(rows)
    for name, row in rows.items():
        assert set(checkers_memory.MEMORY_FIELDS) <= set(row), name
        assert row["resident_arg_bytes"] > 0, name
        assert row["peak_live_bytes"] >= row["resident_arg_bytes"], name
        assert row["transient_peak_ratio"] == round(
            row["peak_live_bytes"] / row["resident_arg_bytes"],
            static_memory.RATIO_DIGITS), name
        # every committed program sits under the JL404 absolute guard
        assert (row["transient_peak_ratio"]
                < checkers_memory.TRANSIENT_BLOWUP_RATIO), name
    # both serving dispatches are pinned, and the int8 resident footprint
    # sits strictly below the f32 twin's — the quantized mode's memory
    # story, now a static number the mall can plan on
    assert (rows["serve_topk_mf_int8"]["resident_arg_bytes"]
            < rows["serve_topk_mf"]["resident_arg_bytes"])
    assert "serve_classify_nn" in rows
    assert any(name.startswith("gang2x4_") for name in rows), sorted(rows)
    # manifest rows self-check clean against themselves
    assert checkers_memory.check_memory_budget(REPO, dict(rows)) == []


def test_memory_doctored_peak_row_fails_jl401():
    # the acceptance criterion: doctoring a peak_live_bytes row fails
    # JL401 loudly, and ONLY for the doctored target
    rows = _memory_manifest_rows()
    doctored = copy.deepcopy(rows)
    doctored["serve_topk_mf"]["peak_live_bytes"] += 4096
    findings = checkers_memory.check_memory_budget(REPO, doctored)
    hits = [f for f in findings
            if f.code == "JL401" and f.func == "serve_topk_mf"]
    assert hits and "drift" in hits[0].message, findings
    assert "peak_live_bytes" in hits[0].message
    assert all(f.func == "serve_topk_mf" for f in findings), findings


def test_memory_missing_stale_and_absent_section_are_loud(tmp_path):
    rows = _memory_manifest_rows()
    # a traced target with no manifest row
    extra = copy.deepcopy(rows)
    extra["serve_new_workload"] = dict(extra[sorted(extra)[0]])
    findings = checkers_memory.check_memory_budget(REPO, extra)
    assert any(f.code == "JL401" and "no memory row" in f.message
               for f in findings)
    # a manifest row whose target vanished
    short = copy.deepcopy(rows)
    dropped = sorted(short)[0]
    del short[dropped]
    findings = checkers_memory.check_memory_budget(REPO, short)
    assert any(f.code == "JL401" and f.func == dropped
               and "stale" in f.message for f in findings)
    # a manifest missing the whole memory section (pre-r20 checkout)
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "collective_budget.json").write_text(
        json.dumps({"targets": {}}))
    findings = checkers_memory.check_memory_budget(str(tmp_path), rows)
    assert [f.code for f in findings] == ["JL401"], findings
    assert "no memory section" in findings[0].message


def test_jl402_dropped_donation_fixture_and_honored_twin(session):
    import jax

    x = np.ones(8, np.float32)
    # f32 input donated, scalar output: no output aval matches, XLA
    # drops the donation silently — JL402's reason to exist
    dropped = jax.make_jaxpr(
        lambda v: jax.jit(lambda y: y.sum(), donate_argnums=(0,))(v))(x)
    findings = checkers_memory.donation_findings(dropped, "fixture")
    assert [f.code for f in findings] == ["JL402"], findings
    assert "aliases NO output" in findings[0].message
    assert findings[0].func == "fixture"
    # the clean twin: same donation, but the output aval matches — the
    # donation is honored, nothing fires
    honored = jax.make_jaxpr(
        lambda v: jax.jit(lambda y: y + 1, donate_argnums=(0,))(v))(x)
    assert checkers_memory.donation_findings(honored, "fixture") == []


def test_jl403_constant_bloat_fixture_and_small_const_twin(session):
    import jax

    big = np.ones((128, 128), np.float32)      # 64 KiB: at the threshold
    bloated = jax.make_jaxpr(lambda v: v[:128, :128] + big)(
        np.ones((256, 256), np.float32))
    findings = checkers_memory.const_findings(bloated, "fixture")
    assert [f.code for f in findings] == ["JL403"], findings
    assert "65536 B" in findings[0].message
    # the clean twin: a tiny closed-over constant rides below threshold
    small = np.ones((4,), np.float32)
    lean = jax.make_jaxpr(lambda v: v + small)(np.ones(4, np.float32))
    assert checkers_memory.const_findings(lean, "fixture") == []


def test_jl404_broadcast_blowup_fixture_and_calm_twin(session):
    import jax
    import jax.numpy as jnp

    x = np.ones(8, np.float32)
    # 32 B of arguments materializing a 128 KiB broadcast: the static
    # signature of an accidental full gather/broadcast
    blown = jax.make_jaxpr(
        lambda v: jnp.broadcast_to(v, (4096, 8)).sum())(x)
    findings = checkers_memory.transient_findings(blown, "fixture")
    assert [f.code for f in findings] == ["JL404"], findings
    assert "4097.0x" in findings[0].message
    calm = jax.make_jaxpr(lambda v: v * 2.0)(x)
    assert checkers_memory.transient_findings(calm, "fixture") == []


def test_memory_traced_rows_match_committed_manifest(session):
    # the end-to-end gate: re-analyzing every traced program reproduces
    # the committed memory rows exactly, and the repo's own programs
    # carry no JL402/403/404 hazards (every donation aliases, no captured
    # constants above threshold, no transient blowup)
    mem = checkers_memory.trace_memory_all()
    findings = checkers_memory.check_memory_budget(REPO, mem)
    assert findings == [], "\n".join(str(f) for f in findings)
    assert len(mem) >= 12
    assert checkers_memory.check_memory_hazards() == []


def test_static_resident_bytes_cross_checks_endpoint_gauge(session):
    # the mall-planning contract: the static resident estimate equals the
    # endpoint's runtime resident-state gauge plus the placed query
    # buffer (the only dispatch argument that is not resident state) —
    # for BOTH endpoint families, and both match the committed rows
    # (the manifest is traced at these exact tier-1 shapes)
    import jax

    from harp_tpu.models import nn
    from harp_tpu.serve import endpoints as serve_ep

    rows = _memory_manifest_rows()
    rng = np.random.default_rng(0)
    uf = rng.normal(size=(64, 8)).astype(np.float32)
    items = rng.normal(size=(32, 8)).astype(np.float32)
    ep = serve_ep.TopKEndpoint(session, "mf", uf, items, k=4)
    ids = rng.integers(0, 64, size=ep.bucket_sizes[0])
    fn, args, _n, _bucket = ep.prepared(ids)
    row = static_memory.memory_row(jax.make_jaxpr(fn)(*args))
    assert row["resident_arg_bytes"] == (
        ep.resident_bytes() + int(args[-1].nbytes))
    assert row == rows["serve_topk_mf"]

    model = nn.MLPClassifier(session, nn.NNConfig(layers=(8,),
                                                  num_classes=3))
    model.params = nn.init_params((12, 8, 3), seed=0)
    cep = serve_ep.classify_from_nn(session, model, name="nn")
    x = rng.normal(size=(cep.bucket_sizes[0], 12)).astype(np.float32)
    cfn, cargs, _cn, _cbucket = cep.prepared(x)
    crow = static_memory.memory_row(jax.make_jaxpr(cfn)(*cargs))
    assert crow["resident_arg_bytes"] == (
        cep.resident_bytes() + int(cargs[-1].nbytes))
    assert crow == rows["serve_classify_nn"]


def test_memory_only_flag_runs_exactly_one_engine(session, capsys):
    from tools.jaxlint.__main__ import main as jaxlint_main

    rc = jaxlint_main(["--memory-only"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "memory engine:" in out
    for banner in ("ast engine", "jaxpr engine", "gang engine",
                   "artifact engine"):
        assert banner not in out, out


def test_memory_doctored_manifest_fails_jl401_in_json_stream(
        session, tmp_path, capsys):
    # end to end through the CLI: a doctored peak in a copied manifest
    # surfaces as a machine-readable JL401 record on the JSONL stream
    # with the full record schema, and the exit goes nonzero
    (tmp_path / "tools").mkdir()
    with open(os.path.join(REPO, checkers_memory.BUDGET_FILE)) as f:
        doc = json.load(f)
    doc["memory"]["serve_topk_mf"]["peak_live_bytes"] += 4096
    (tmp_path / "tools" / "collective_budget.json").write_text(
        json.dumps(doc))
    from tools.jaxlint.__main__ import main as jaxlint_main

    rc = jaxlint_main([str(tmp_path), "--memory-only", "--json"])
    out = capsys.readouterr().out
    assert rc == 1
    lines = [json.loads(line) for line in out.strip().splitlines()]
    hits = [r for r in lines if r["code"] == "JL401"]
    assert hits and hits[0]["func"] == "serve_topk_mf", out
    assert hits[0]["allowlisted"] is False
    assert "drift" in hits[0]["message"]
    assert {"file", "line", "code", "checker", "func", "message",
            "allowlisted"} <= set(hits[0])


# -- JL5xx lowered-HLO engine (ISSUE 20) -------------------------------------

import pytest  # noqa: E402

from harp_tpu.aot import hlo_audit  # noqa: E402
from tools.jaxlint import checkers_hlo  # noqa: E402
from tools.jaxlint.core import split_allowlist  # noqa: E402


def _hlo_section():
    with open(os.path.join(REPO, checkers_hlo.BUDGET_FILE)) as f:
        return json.load(f)["hlo"]


def _write_budget(tmp_path, doc):
    (tmp_path / "tools").mkdir(exist_ok=True)
    (tmp_path / "tools" / "collective_budget.json").write_text(
        json.dumps(doc))


# a minimal post-SPMD module in the shapes the parser consumes: a tuple-
# result async all-reduce pair (books ONCE, at the -start), a while loop,
# and per-device entry parameters
_HLO_FIXTURE = """\
HloModule fixture_spmd

%body (p: (s32[], f32[8,2])) -> (s32[], f32[8,2]) {
  %p = (s32[], f32[8,2]{1,0}) parameter(0)
  %ars = (f32[8,2]{1,0}, f32[8,2]{1,0}) all-reduce-start(f32[8,2]{1,0} %x), to_apply=%add
  %ard = (f32[8,2]{1,0}, f32[8,2]{1,0}) all-reduce-done((f32[8,2]{1,0}, f32[8,2]{1,0}) %ars)
  ROOT %t = (s32[], f32[8,2]{1,0}) tuple(s32[] %i, f32[8,2]{1,0} %y)
}

ENTRY %main.9_spmd (param.1: f32[8,2], param.0: s32[]) -> (s32[], f32[8,2]) {
  %param.0 = s32[] parameter(1)
  %param.1 = f32[8,2]{1,0} parameter(0)
  %init = (s32[], f32[8,2]{1,0}) tuple(s32[] %param.0, f32[8,2]{1,0} %param.1)
  ROOT %w = (s32[], f32[8,2]{1,0}) while((s32[], f32[8,2]{1,0}) %init), condition=%cond, body=%body
}
"""


def test_hlo_parser_shapes_collectives_and_while():
    shapes = hlo_audit.parse_shapes("(f32[8,2]{1,0}, s32[], token[])")
    assert [str(s) for s in shapes] == ["f32[8,2]", "s32[]"]
    assert hlo_audit.shape_bytes("(f32[8,2]{1,0}, s32[])") == 64 + 4
    assert hlo_audit.shape_bytes("bf16[4,4]") == 32
    stats = hlo_audit.collective_stats(_HLO_FIXTURE)
    # the -start books the op once; the -done is the same transfer
    assert stats == {"all-reduce": {"count": 1, "bytes": 128,
                                    "shapes": ["f32[8,2]+f32[8,2]"]}}
    assert hlo_audit.while_count(_HLO_FIXTURE) == 1
    row = hlo_audit.hlo_row(_HLO_FIXTURE)
    assert row["collectives"] == {"all-reduce": 1}
    assert row["collective_bytes_total"] == 128
    assert row["while_count"] == 1
    assert row["instruction_count"] == 7
    # entry params surface per-DEVICE blocks, not argument order
    assert sorted(str(s) for s in
                  hlo_audit.entry_param_shapes(_HLO_FIXTURE)) == \
        ["f32[8,2]", "s32[]"]


def test_jl501_injected_compiler_allgather_and_clean_twin():
    # the acceptance fixture: a compiler-side all-gather injected into a
    # module whose trace only showed a psum fails JL501 loudly, naming
    # the op, shape, and inferred cause
    doctored = (
        "HloModule fixture_spmd\n\n"
        "ENTRY %main.1_spmd (param.0: f32[8,16]) -> f32[64,16] {\n"
        "  %param.0 = f32[8,16]{1,0} parameter(0)\n"
        "  %ar = f32[8,16]{1,0} all-reduce(f32[8,16]{1,0} %param.0)\n"
        "  ROOT %ag = f32[64,16]{1,0} all-gather(f32[8,16]{1,0} %ar), "
        "dimensions={0}\n"
        "}\n")
    findings = checkers_hlo.inserted_findings_from(
        doctored, {"psum": 1}, "fixture")
    assert [f.code for f in findings] == ["JL501"], findings
    msg = findings[0].message
    assert "all-gather" in msg and "f32[64,16]" in msg
    assert "full-broadcast" in msg          # the inferred cause family
    assert findings[0].func == "fixture"
    # clean twin 1: the SAME module when the trace owned the gather
    assert checkers_hlo.inserted_findings_from(
        doctored, {"psum": 1, "all_gather": 1}, "fixture") == []
    # clean twin 2: drop the injected op — a psum-only module is clean
    clean = doctored.replace(
        "  ROOT %ag = f32[64,16]{1,0} all-gather(f32[8,16]{1,0} %ar), "
        "dimensions={0}\n", "")
    assert checkers_hlo.inserted_findings_from(
        clean, {"psum": 1}, "fixture") == []


class _FakeSharded:
    """A placed-array stand-in: shape/dtype/sharding is all the audit
    reads off a leaf."""

    class _S:
        def __init__(self, shard):
            self._shard = shard

        def shard_shape(self, _global_shape):
            return self._shard

    def __init__(self, shape, shard):
        self.shape = shape
        self.dtype = np.dtype("float32")
        self.sharding = self._S(shard)


def test_jl503_replicated_where_sharded_and_clean_twin():
    args = (_FakeSharded((64, 16), (8, 16)),)
    # doctored: the partitioner compiled the declared-sharded operand at
    # its GLOBAL shape — the silent full-replication signature
    doctored = (
        "ENTRY %main.1_spmd (param.0: f32[64,16]) -> f32[64,16] {\n"
        "  %param.0 = f32[64,16]{1,0} parameter(0)\n"
        "}\n")
    findings = checkers_hlo.replicated_findings_from(doctored, args, "fx")
    assert [f.code for f in findings] == ["JL503"], findings
    assert "REPLICATED" in findings[0].message
    assert "f32[64,16]" in findings[0].message
    assert "f32[8,16]" in findings[0].message        # the declared block
    # clean twin: compiled at the declared per-device block
    clean = doctored.replace("f32[64,16]", "f32[8,16]")
    assert checkers_hlo.replicated_findings_from(clean, args, "fx") == []
    # conservative twin: a const-folded (dropped) param is NOT a finding
    folded = "ENTRY %main.1_spmd () -> f32[] {\n}\n"
    assert checkers_hlo.replicated_findings_from(folded, args, "fx") == []


def test_hlo_manifest_pins_all_targets_and_dispatch_matrix():
    from tools.jaxlint import trace_targets

    section = _hlo_section()
    rows = section["targets"]
    expected = set(trace_targets.TARGETS) | set(trace_targets.GANG_TARGETS)
    assert set(rows) == expected, sorted(expected ^ set(rows))
    for name, row in rows.items():
        assert set(checkers_hlo.HLO_FIELDS) <= set(row), name
        assert row["instruction_count"] > 0, name
        assert set(row["collectives"]) == set(row["collective_bytes"]), name
        assert row["collective_bytes_total"] == sum(
            row["collective_bytes"].values()), name
        assert set(row["collectives"]) <= set(
            hlo_audit.HLO_COLLECTIVE_OPS), name
    # the quantized serving dispatch moves FEWER compiled collective
    # bytes than its f32 twin at the same op count — the int8 wire story,
    # now a compiled-layer number
    assert (rows["serve_topk_mf_int8"]["collectives"]
            == rows["serve_topk_mf"]["collectives"])
    assert (rows["serve_topk_mf_int8"]["collective_bytes_total"]
            < rows["serve_topk_mf"]["collective_bytes_total"])
    # the device-kind matrix: cpu is always pinned, with all 6 serving
    # dispatches; mf routes stay collective, nn dispatches stay local
    matrix = section["device_kinds"]["cpu"]
    assert set(matrix) == {f"serve/{m}/b{b}" for m in ("mf", "nn")
                           for b in (8, 32, 128)}
    for name, row in matrix.items():
        if name.startswith("serve/mf/"):
            assert row["collectives"].get("all-to-all", 0) >= 1, name
        else:
            assert row["collectives"] == {}, name
    # the committed section self-checks clean
    assert checkers_hlo.check_hlo_budget(REPO, dict(rows),
                                         dict(matrix)) == []


def test_jl502_doctored_missing_stale_and_env_rows_are_loud(tmp_path):
    with open(os.path.join(REPO, checkers_hlo.BUDGET_FILE)) as f:
        doc = json.load(f)
    rows = doc["hlo"]["targets"]
    matrix = doc["hlo"]["device_kinds"]["cpu"]

    # the acceptance criterion: a doctored compiled row fails JL502
    # loudly, and ONLY for the doctored target
    doctored = copy.deepcopy(doc)
    doctored["hlo"]["targets"]["kmeans_allreduce"][
        "instruction_count"] += 7
    _write_budget(tmp_path, doctored)
    findings = checkers_hlo.check_hlo_budget(str(tmp_path), dict(rows),
                                             dict(matrix))
    assert [(f.code, f.func) for f in findings] == \
        [("JL502", "kmeans_allreduce")], findings
    assert "drift" in findings[0].message
    assert "instruction_count" in findings[0].message

    # a lowered target with no pinned row / a row whose target vanished
    extra = dict(rows)
    extra["new_workload"] = dict(rows["kmeans_allreduce"])
    _write_budget(tmp_path, doc)
    findings = checkers_hlo.check_hlo_budget(str(tmp_path), extra,
                                             dict(matrix))
    assert any(f.code == "JL502" and "no hlo row" in f.message
               for f in findings)
    short = dict(rows)
    del short["kmeans_allreduce"]
    findings = checkers_hlo.check_hlo_budget(str(tmp_path), short,
                                             dict(matrix))
    assert any(f.code == "JL502" and f.func == "kmeans_allreduce"
               and "stale" in f.message for f in findings)

    # a manifest missing the whole hlo section (pre-r21 checkout)
    _write_budget(tmp_path, {"targets": {}})
    findings = checkers_hlo.check_hlo_budget(str(tmp_path), dict(rows),
                                             dict(matrix))
    assert [f.code for f in findings] == ["JL502"], findings
    assert "no hlo section" in findings[0].message

    # a different jax version re-pins with ONE finding, not N drifts
    repinned = copy.deepcopy(doc)
    repinned["hlo"]["lowered_with_jax"] = "0.0.1"
    repinned["hlo"]["targets"]["kmeans_allreduce"][
        "instruction_count"] += 7
    _write_budget(tmp_path, repinned)
    findings = checkers_hlo.check_hlo_budget(str(tmp_path), dict(rows),
                                             dict(matrix))
    assert len(findings) == 1 and "re-pin" in findings[0].message, findings


def test_jl504_doctored_device_kind_rows_are_loud(tmp_path):
    with open(os.path.join(REPO, checkers_hlo.BUDGET_FILE)) as f:
        doc = json.load(f)
    rows = doc["hlo"]["targets"]
    matrix = doc["hlo"]["device_kinds"]["cpu"]

    # the acceptance criterion: a doctored device-kind row fails JL504
    # loudly, naming the dispatch and the kind
    doctored = copy.deepcopy(doc)
    doctored["hlo"]["device_kinds"]["cpu"]["serve/mf/b8"][
        "collective_bytes_total"] += 64
    _write_budget(tmp_path, doctored)
    findings = checkers_hlo.check_hlo_budget(str(tmp_path), dict(rows),
                                             dict(matrix))
    assert [(f.code, f.func) for f in findings] == \
        [("JL504", "serve/mf/b8")], findings
    assert "'cpu'" in findings[0].message
    assert "kind-dependent" in findings[0].message

    # a missing matrix for the RUNNING kind is loud
    missing = copy.deepcopy(doc)
    del missing["hlo"]["device_kinds"]["cpu"]
    _write_budget(tmp_path, missing)
    findings = checkers_hlo.check_hlo_budget(str(tmp_path), dict(rows),
                                             dict(matrix))
    assert [f.code for f in findings] == ["JL504"], findings
    assert "no pinned serving-dispatch row matrix" in findings[0].message

    # stale dispatch row under the running kind
    stale = copy.deepcopy(doc)
    stale["hlo"]["device_kinds"]["cpu"]["serve/mf/b999"] = \
        dict(matrix["serve/mf/b8"])
    _write_budget(tmp_path, stale)
    findings = checkers_hlo.check_hlo_budget(str(tmp_path), dict(rows),
                                             dict(matrix))
    assert any(f.code == "JL504" and f.func == "serve/mf/b999"
               and "stale" in f.message for f in findings)

    # a pinned kind this process cannot reach is CARRIED, never stale:
    # the TPU matrix a TPU run pinned must survive a cpu-only check
    foreign = copy.deepcopy(doc)
    foreign["hlo"]["device_kinds"]["TPU v99"] = {
        "serve/mf/b8": dict(matrix["serve/mf/b8"])}
    _write_budget(tmp_path, foreign)
    assert checkers_hlo.check_hlo_budget(str(tmp_path), dict(rows),
                                         dict(matrix)) == []


def test_hlo_allowlist_pool_split_regression():
    # one allowlist, one pool per engine: JL4xx -> memory, JL5xx -> hlo,
    # everything else -> ast; disjoint and exhaustive
    fake = {
        ("a.py", "f", "JL101"): "x" * 20,
        ("tools/collective_budget.json", "t", "JL402"): "y" * 20,
        ("tools/collective_budget.json", "t2", "JL501"): "z" * 20,
        ("tools/collective_budget.json", "t3", "JL503"): "w" * 20,
    }
    pools = split_allowlist(fake)
    assert set(pools) == {"ast", "memory", "hlo"}
    assert set(pools["ast"]) == {("a.py", "f", "JL101")}
    assert set(pools["memory"]) == {
        ("tools/collective_budget.json", "t", "JL402")}
    assert set(pools["hlo"]) == {
        ("tools/collective_budget.json", "t2", "JL501"),
        ("tools/collective_budget.json", "t3", "JL503")}
    merged = {}
    for p in pools.values():
        assert not set(merged) & set(p)          # disjoint
        merged.update(p)
    assert merged == fake                        # exhaustive

    # the regression this split exists for: a JL5xx entry must NOT reach
    # an AST-pool pass — there it matches no finding and would report
    # stale, failing every non-hlo stage of CI
    ast_findings = [Finding("JL101", "c", "a.py", 1, "f", "m")]
    active, stale = apply_allowlist(ast_findings, pools["ast"])
    assert active == [] and stale == []
    # ...and in ITS pool it suppresses the matching finding
    hlo_finding = Finding("JL501", "inserted-collective",
                          "tools/collective_budget.json", 1, "t2", "m")
    active, stale = apply_allowlist([hlo_finding], pools["hlo"])
    assert active == []                       # suppressed in its own pool
    assert len(stale) == 1 and "t3" in stale[0]   # unmatched JL503 entry
    # the committed allowlist partitions cleanly too
    committed = split_allowlist(ALLOWLIST)
    committed_merged = {}
    for p in committed.values():
        committed_merged.update(p)
    assert committed_merged == dict(ALLOWLIST)


def test_hlo_relowered_rows_match_committed_manifest(session):
    # the end-to-end gate: re-lowering every traced program (and the 6
    # serving dispatches on this backend) reproduces the committed hlo
    # section exactly, and the repo's own programs carry no JL501/JL503
    # hazards (no compiler-inserted collective kinds, no silently
    # replicated operands)
    rows = checkers_hlo.trace_hlo_all()
    kind_rows = checkers_hlo.serving_dispatch_rows()
    findings = checkers_hlo.check_hlo_budget(REPO, rows, kind_rows)
    assert findings == [], "\n".join(str(f) for f in findings)
    assert len(rows) >= 32
    assert len(kind_rows) == 6
    assert checkers_hlo.check_hlo_hazards() == []


def test_hlo_build_section_carries_unreachable_kinds(session, tmp_path):
    # --update-budget on a cpu-only host must not DROP a TPU matrix a
    # TPU run pinned: build_hlo_section refreshes the running kind and
    # carries every other kind forward verbatim
    with open(os.path.join(REPO, checkers_hlo.BUDGET_FILE)) as f:
        doc = json.load(f)
    foreign_row = {"serve/mf/b8":
                   dict(doc["hlo"]["device_kinds"]["cpu"]["serve/mf/b8"])}
    doctored = copy.deepcopy(doc)
    doctored["hlo"]["device_kinds"]["TPU v99"] = foreign_row
    _write_budget(tmp_path, doctored)
    section = checkers_hlo.build_hlo_section(str(tmp_path))
    assert section["device_kinds"]["TPU v99"] == foreign_row
    assert set(section["device_kinds"]["cpu"]) == \
        set(doc["hlo"]["device_kinds"]["cpu"])
    assert section["targets"] == doc["hlo"]["targets"]


def test_hlo_only_flag_runs_exactly_one_engine(session, capsys):
    from tools.jaxlint.__main__ import main as jaxlint_main

    rc = jaxlint_main(["--hlo-only"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "hlo engine:" in out
    for banner in ("ast engine", "jaxpr engine", "gang engine",
                   "memory engine", "artifact engine"):
        assert banner not in out, out
    # exactly-one-engine contract: combining selectors is a usage error
    with pytest.raises(SystemExit):
        jaxlint_main(["--hlo-only", "--memory-only"])
    capsys.readouterr()
    with pytest.raises(SystemExit):
        jaxlint_main(["--hlo-only", "--update-budget"])
    capsys.readouterr()


def test_hlo_doctored_manifest_fails_jl502_in_json_stream(
        session, tmp_path, capsys):
    # end to end through the CLI: a doctored compiled-collective row in a
    # copied manifest surfaces as a machine-readable JL502 record on the
    # JSONL stream with the full record schema, and the exit goes nonzero
    with open(os.path.join(REPO, checkers_hlo.BUDGET_FILE)) as f:
        doc = json.load(f)
    doc["hlo"]["targets"]["serve_topk_mf"]["collective_bytes"][
        "all-to-all"] += 64
    _write_budget(tmp_path, doc)
    from tools.jaxlint.__main__ import main as jaxlint_main

    rc = jaxlint_main([str(tmp_path), "--hlo-only", "--json"])
    out = capsys.readouterr().out
    assert rc == 1
    lines = [json.loads(line) for line in out.strip().splitlines()]
    hits = [r for r in lines if r["code"] == "JL502"]
    assert hits and hits[0]["func"] == "serve_topk_mf", out
    assert hits[0]["allowlisted"] is False
    assert "drift" in hits[0]["message"]
    assert {"file", "line", "code", "checker", "func", "message",
            "allowlisted"} <= set(hits[0])


def test_bench_list_groups_matches_only_validator():
    # the satellite contract: --list-groups prints EXACTLY the names the
    # --only validator accepts, one per line
    import subprocess

    import bench

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--list-groups"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert out.returncode == 0, out.stderr
    assert out.stdout.split() == list(bench.ROW_GROUPS)
