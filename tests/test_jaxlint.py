"""jaxlint (tools/jaxlint) — tier-1.

Three layers, mirroring tests/test_check_claims.py's contract style:

* fixture snippets with KNOWN violations assert the exact finding codes
  each checker raises (and that the clean twin of each snippet is silent);
* the repo itself must lint clean (this is the tier-1 wiring — a new
  violation anywhere in harp_tpu/ fails the suite, so DOTS_PASSED captures
  the lint exactly like the scatter lint it absorbed);
* the allowlist contract: justifications are mandatory, stale entries fail;
* the jaxpr engine: traced collective budgets must match the committed
  tools/collective_budget.json, and drift is detected loudly.
"""

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.jaxlint import checkers_jaxpr  # noqa: E402
from tools.jaxlint import checkers_ast as ca  # noqa: E402
from tools.jaxlint.allowlist import ALLOWLIST  # noqa: E402
from tools.jaxlint.core import (Finding, apply_allowlist,  # noqa: E402
                                run_ast_checkers, validate_allowlist)


def _run(checker, src, rel="harp_tpu/models/fake.py"):
    return checker(ast.parse(src), rel, src)


def _codes(findings):
    return [f.code for f in findings]


# -- JL101 collective-divergence -------------------------------------------

def test_collective_in_rank_branch_is_flagged():
    src = (
        "def step(x):\n"
        "    wid = lax_ops.worker_id()\n"
        "    if wid == 0:\n"
        "        x = jax.lax.psum(x, 'workers')\n"
        "    return x\n")
    got = _run(ca.check_collective_divergence, src)
    assert _codes(got) == ["JL101"]
    assert got[0].func == "step" and "psum" in got[0].message


def test_collective_divergence_nested_and_else_branch():
    src = (
        "def step(x):\n"
        "    if jax.process_index() != 0:\n"
        "        y = 1\n"
        "    else:\n"
        "        for _ in range(3):\n"
        "            x = lax_ops.allgather(x)\n"
        "    return x\n")
    assert _codes(_run(ca.check_collective_divergence, src)) == ["JL101"]


def test_masked_contribution_idiom_is_clean():
    # the lax_ops.broadcast shape: EVERY worker calls the collective, the
    # rank condition only masks the contribution — no divergence
    src = (
        "def bcast(x, root):\n"
        "    mask = jax.lax.axis_index('workers') == root\n"
        "    return jax.lax.psum(jnp.where(mask, x, 0.0), 'workers')\n")
    assert _run(ca.check_collective_divergence, src) == []
    # rank-conditional HOST work (no collective inside) is also fine
    src2 = (
        "def save(x):\n"
        "    if jax.process_index() == 0:\n"
        "        np.savetxt('out.csv', x)\n")
    assert _run(ca.check_collective_divergence, src2) == []


# -- JL102 axis-name --------------------------------------------------------

def test_unknown_axis_literal_is_flagged():
    src = (
        "def step(x):\n"
        "    return jax.lax.psum(x, axis_name='worker')\n")   # typo'd axis
    got = _run(ca.check_axis_name, src)
    assert _codes(got) == ["JL102"] and "'worker'" in got[0].message


def test_declared_or_canonical_axes_are_clean():
    src = (
        "MY_AXIS = 'ring'\n"
        "def step(x, mesh):\n"
        "    a = jax.lax.psum(x, 'workers')\n"        # canonical
        "    b = jax.lax.all_gather(x, 'ring')\n"     # declared above
        "    c = lax_ops.allreduce(x, axis_name=WORKERS)\n"  # constant ref
        "    return a, b, c\n")
    assert _run(ca.check_axis_name, src) == []


# -- JL103 retrace-hazard ---------------------------------------------------

def test_immediately_invoked_jit_is_flagged():
    src = (
        "def fit(sess, x):\n"
        "    return sess.spmd(lambda a: a + 1, in_specs=s, out_specs=s)(x)\n")
    got = _run(ca.check_retrace_hazard, src)
    assert _codes(got) == ["JL103"] and "one expression" in got[0].message


def test_jit_in_loop_without_cache_guard_is_flagged():
    src = (
        "def fit(sess, xs):\n"
        "    for x in xs:\n"
        "        f = jax.jit(step)\n"
        "        f(x)\n")
    assert _codes(_run(ca.check_retrace_hazard, src)) == ["JL103"]
    # the repo's cache idiom is clean: the wrapper is STORED in a container
    guarded = (
        "def fit(self, sess, xs):\n"
        "    for x in xs:\n"
        "        if x.shape not in self._fns:\n"
        "            self._fns[x.shape] = jax.jit(step)\n"
        "        self._fns[x.shape](x)\n")
    assert _run(ca.check_retrace_hazard, guarded) == []
    # an unrelated `not in` membership test is NOT a cache: a plain-name
    # bind inside it still rebuilds the wrapper every iteration
    skip_filter = (
        "def fit(sess, xs):\n"
        "    for x in xs:\n"
        "        if x.tag not in SKIP:\n"
        "            f = jax.jit(step)\n"
        "            f(x)\n")
    assert _codes(_run(ca.check_retrace_hazard, skip_filter)) == ["JL103"]


def test_jitted_mutable_default_and_global_are_flagged():
    src = (
        "@jax.jit\n"
        "def step(x, opts={}):\n"
        "    return x\n")
    assert _codes(_run(ca.check_retrace_hazard, src)) == ["JL103"]
    src2 = (
        "@partial(jax.jit, static_argnums=(1,))\n"
        "def step(x, n):\n"
        "    global _SCALE\n"
        "    return x * _SCALE\n")
    assert _codes(_run(ca.check_retrace_hazard, src2)) == ["JL103"]
    # plain decorated function with hashable defaults is clean
    assert _run(ca.check_retrace_hazard,
                "@jax.jit\ndef step(x, n=3):\n    return x * n\n") == []


# -- JL104 host-sync-hot-loop ----------------------------------------------

def test_host_sync_inside_fit_loop_is_flagged():
    src = (
        "def fit(self, xs):\n"
        "    costs = []\n"
        "    for x in xs:\n"
        "        c = self._step(x)\n"
        "        costs.append(np.asarray(c).tolist())\n"
        "        c.block_until_ready()\n"
        "        n = c.item()\n"
        "    return costs\n")
    got = _run(ca.check_host_sync, src)
    assert _codes(got) == ["JL104"] * 3


def test_host_sync_outside_loop_or_fit_is_clean():
    # after the loop: one sync per fit is fine
    src = ("def fit(self, xs):\n"
           "    for x in xs:\n"
           "        c = self._step(x)\n"
           "    return np.asarray(c)\n")
    assert _run(ca.check_host_sync, src) == []
    # not a fit/train path: loaders may asarray per file
    src2 = ("def load(paths):\n"
            "    return [np.asarray(read(p)) for p in paths]\n")
    assert _run(ca.check_host_sync, src2) == []
    # timing.py is the sanctioned sync site
    src3 = ("def fit_timed(self, xs):\n"
            "    for x in xs:\n"
            "        self._step(x).block_until_ready()\n")
    assert ca.check_host_sync(ast.parse(src3),
                              "harp_tpu/benchmark/timing.py", src3) == []


# -- JL105 broad-except -----------------------------------------------------

def test_broad_except_variants_are_flagged():
    src = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except (ValueError, BaseException):\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except:\n"
        "        pass\n")
    assert _codes(_run(ca.check_broad_except, src)) == ["JL105"] * 3
    assert _run(ca.check_broad_except,
                "def f():\n"
                "    try:\n"
                "        import scipy\n"
                "    except ImportError:\n"
                "        scipy = None\n") == []


# -- JL106 scatter (folded lint_scatter) ------------------------------------

def test_scatter_in_hot_tree_flagged_and_cold_tree_exempt():
    src = "def hot(x, i, v):\n    return x.at[i].add(v)\n"
    assert _codes(_run(ca.check_scatter, src,
                       "harp_tpu/models/fake.py")) == ["JL106"]
    assert _codes(_run(ca.check_scatter, src,
                       "harp_tpu/ops/fake.py")) == ["JL106"]
    # gathers and non-hot trees don't trip
    assert _run(ca.check_scatter, "def f(x, i):\n    return x[i]\n",
                "harp_tpu/models/fake.py") == []
    assert _run(ca.check_scatter, src, "harp_tpu/parallel/fake.py") == []


# -- allowlist contract -----------------------------------------------------

def test_allowlist_suppresses_and_staleness_fails():
    f = Finding("JL105", "broad-except", "harp_tpu/models/fake.py", 3,
                "f", "msg")
    ok = {("harp_tpu/models/fake.py", "f", "JL105"):
          "a justification long enough to satisfy the schema"}
    active, stale = apply_allowlist([f], ok)
    assert active == [] and stale == []
    # same entry with no matching finding -> stale, loudly
    active, stale = apply_allowlist([], ok)
    assert active == [] and len(stale) == 1 and "prune" in stale[0]


def test_allowlist_requires_real_justifications():
    assert validate_allowlist(
        {("a.py", "f", "JL105"): "ok"}) != []            # too short
    assert validate_allowlist({("a.py", "f"): "x" * 40}) != []   # bad key
    assert validate_allowlist(
        {("a.py", "f", "JL105"): "cold prepare-side layout, runs once"}
    ) == []


def test_committed_allowlist_is_schema_valid_and_live():
    assert validate_allowlist(ALLOWLIST) == []
    raw = run_ast_checkers(REPO, ca.ast_checkers_for_repo(REPO))
    _active, stale = apply_allowlist(raw, ALLOWLIST)
    assert stale == [], "\n".join(stale)


# -- the repo itself lints clean (tier-1 wiring) ----------------------------

def test_repo_is_clean_under_all_ast_checkers():
    raw = run_ast_checkers(REPO, ca.ast_checkers_for_repo(REPO))
    active, _stale = apply_allowlist(raw, ALLOWLIST)
    assert active == [], "\n".join(str(f) for f in active)


# -- jaxpr engine: collective budget + dtype policy -------------------------

def test_traced_budgets_match_committed_manifest(session):
    # `session` fixture guarantees the 8-device mesh is up; trace_all then
    # reuses the already-initialized backend
    traced = checkers_jaxpr.trace_all()
    findings = checkers_jaxpr.check_budget(REPO, traced)
    assert findings == [], "\n".join(str(f) for f in findings)
    # the manifest's collective KINDS are the comm contract: the flagship
    # regroupallgather variant must stay reduce_scatter+all_gather (+ the
    # cost psum), not degrade to, e.g., a pair of psums
    counts, dtype_bad, nbytes = traced["kmeans_regroupallgather"]
    assert counts == {"psum": 1, "reduce_scatter": 1, "all_gather": 1}
    assert dtype_bad == []
    # the byte contract: every target carries per-kind operand bytes, and
    # the quantized twins sit well below their f32 programs — a quantized
    # path silently reverting to f32 moves these and fails JL203
    f32_bytes = sum(traced["kmeans_allreduce"][2].values())
    int8_bytes = sum(traced["kmeans_allreduce_int8"][2].values())
    assert 0 < int8_bytes < f32_bytes / 2, (int8_bytes, f32_bytes)
    assert sum(traced["sgd_mf_dense_int8"][2].values()) < sum(
        traced["sgd_mf_dense"][2].values())
    assert sum(nbytes.values()) > 0


def test_budget_drift_and_stale_rows_are_loud():
    traced = {"kmeans_regroupallgather": ({"psum": 5}, [], {"psum": 20})}
    findings = checkers_jaxpr.check_budget(REPO, traced)
    msgs = "\n".join(f.message for f in findings)
    # count drift on the one traced target...
    assert any(f.code == "JL201" and "drift" in f.message
               and f.func == "kmeans_regroupallgather" for f in findings)
    assert "traced 5 vs pinned 1" in msgs
    # ...and every other committed row reports as stale/unmatched
    assert any("matches no trace target" in f.message for f in findings)


def test_byte_budget_drift_is_loud_at_same_counts():
    # JL203's reason to exist: SAME collective counts, different operand
    # bytes (the silently-dropped-quantization signature) must fail even
    # though JL201 sees no drift
    import json

    with open(os.path.join(REPO, checkers_jaxpr.BUDGET_FILE)) as f:
        manifest = json.load(f)
    row = manifest["targets"]["kmeans_allreduce"]
    counts = dict(row["collectives"])
    widened = {k: 4 * v for k, v in row["bytes_by_kind"].items()}
    traced = {"kmeans_allreduce": (counts, [], widened)}
    findings = checkers_jaxpr.check_budget(REPO, traced)
    assert not any(f.code == "JL201" and f.func == "kmeans_allreduce"
                   for f in findings)
    hits = [f for f in findings
            if f.code == "JL203" and f.func == "kmeans_allreduce"]
    assert hits and "byte-budget drift" in hits[0].message
    # a manifest row lacking bytes_per_step is itself a finding
    clean = {"kmeans_allreduce": (counts, [],
                                  dict(row["bytes_by_kind"]))}
    assert not any(f.func == "kmeans_allreduce"
                   for f in checkers_jaxpr.check_budget(REPO, clean))


def test_dtype_policy_reports_bf16_accumulation():
    import jax
    import jax.numpy as jnp

    def bad(a, b):
        return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())))

    x = jnp.zeros((4, 4), jnp.bfloat16)
    closed = jax.make_jaxpr(bad)(x, x)
    counts, dtype_bad = {}, []
    checkers_jaxpr._walk(closed.jaxpr, counts, dtype_bad, {})
    assert any("bf16" in m for m in dtype_bad)

    def good(a, b):
        return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    counts, dtype_bad = {}, []
    checkers_jaxpr._walk(jax.make_jaxpr(good)(x, x).jaxpr, counts, dtype_bad,
                         {})
    assert dtype_bad == []
