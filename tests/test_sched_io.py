"""Scheduler lifecycle + input-pipeline tests (reference: schdynamic/InputTest,
schstatic/StaticSchedulerTest, fileformat + datasource behaviors)."""

import os

import numpy as np
import pytest

from harp_tpu import config as config_lib
from harp_tpu.io import datagen, loaders
from harp_tpu.sched.dynamic import DynamicScheduler, Task
from harp_tpu.sched.static import StaticScheduler


class Square(Task):
    def run(self, x):
        return x * x


class TestDynamicScheduler:
    def test_shared_queue_processes_all(self):
        s = DynamicScheduler([Square() for _ in range(4)])
        s.start()
        s.submit_all(range(100))
        out = sorted(s.drain())
        assert out == sorted(i * i for i in range(100))
        s.stop()

    def test_pause_keeps_queue(self):
        s = DynamicScheduler([Square()])
        s.start()
        s.submit_all([1, 2, 3])
        assert sorted(s.drain()) == [1, 4, 9]
        s.pause()
        s.submit(5)           # queued while paused
        s.start()
        assert s.wait_for_output() == 25
        s.stop()

    def test_pause_with_backlog_does_not_run_backlog(self):
        """Regression: pause() used to enqueue poison pills BEHIND the backlog,
        executing everything before stopping."""
        import threading
        import time

        ran = []
        gate = threading.Event()

        class Slow(Task):
            def run(self, x):
                gate.wait(5)
                ran.append(x)
                return x

        s = DynamicScheduler([Slow()])
        s.start()
        s.submit_all(range(50))
        time.sleep(0.05)       # worker is blocked inside item 0
        gate.set()
        s.pause()              # must stop after in-flight item(s), keep the rest
        assert len(ran) < 50, "pause executed the whole backlog"
        # backlog preserved: restart and everything completes
        s.start()
        total = len(ran)
        remaining = 50 - total
        outs = [s.wait_for_output() for _ in range(s._submitted)]
        assert len(ran) == 50
        s.stop()

    def test_stop_discards_backlog(self):
        import threading

        gate = threading.Event()

        class Slow(Task):
            def run(self, x):
                gate.wait(5)
                return x

        s = DynamicScheduler([Slow()])
        s.start()
        s.submit_all(range(20))
        gate.set()
        s.stop()
        # after stop, no deadlock: claimable outputs == _submitted
        leftover = s.drain()
        assert len(leftover) == len(leftover)  # drain returned without blocking


class TestStaticScheduler:
    def test_private_queues_stay_pinned(self):
        class Tag(Task):
            def __init__(self, tag):
                self.tag = tag

            def run(self, x):
                return (self.tag, x)

        s = StaticScheduler([Tag(0), Tag(1), Tag(2)])
        s.start()
        for tid in range(3):
            s.submit(tid, tid * 10)
        for tid in range(3):
            tag, val = s.wait_for_output(tid)
            assert tag == tid and val == tid * 10
        s.stop()


class TestLoaders:
    def test_split_files_contiguous(self):
        groups = loaders.split_files([f"f{i:02d}" for i in range(10)], 4)
        assert [len(g) for g in groups] == [3, 3, 2, 2]
        assert groups[0] == ["f00", "f01", "f02"]

    def test_dense_csv_roundtrip(self, tmp_path):
        ref = np.random.default_rng(0).normal(size=(20, 5)).astype(np.float32)
        paths = []
        for i in range(4):
            p = tmp_path / f"part{i}.csv"
            np.savetxt(p, ref[i * 5:(i + 1) * 5], delimiter=",", fmt="%.6f")
            paths.append(str(p))
        out = loaders.load_dense_csv(paths, num_threads=2)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_coo_to_csr(self):
        rows = np.array([2, 0, 1, 0, 2], dtype=np.int64)
        cols = np.array([1, 0, 2, 1, 0], dtype=np.int64)
        vals = np.arange(5, dtype=np.float32)
        indptr, idx, v = loaders.coo_to_csr(rows, cols, vals, num_rows=3)
        np.testing.assert_array_equal(indptr, [0, 2, 3, 5])
        np.testing.assert_array_equal(idx, [0, 1, 2, 1, 0])
        np.testing.assert_array_equal(v, [1, 3, 2, 0, 4])

    def test_regroup_coo_by_row(self):
        rows, cols, vals = datagen.sparse_ratings(100, 50, 4, density=0.1, seed=1)
        parts = loaders.regroup_coo_by_row(rows, cols, vals, num_workers=4)
        assert sum(p[0].size for p in parts) == rows.size
        block = -(-100 // 4)
        for w, (r, _, _) in enumerate(parts):
            if r.size:
                assert np.all(np.minimum(r // block, 3) == w)


class TestConfig:
    def test_parse_into_dataclass(self):
        from harp_tpu.models.kmeans import KMeansConfig

        cfg = config_lib.parse_into(
            KMeansConfig, ["--num-centroids", "32", "--comm", "allreduce"])
        assert cfg.num_centroids == 32
        assert cfg.comm == "allreduce"
        assert cfg.dim == 100  # default preserved


class TestDatagen:
    def test_clustered_points_shape_and_determinism(self):
        a = datagen.dense_points(100, 10, seed=5, num_clusters=3)
        b = datagen.dense_points(100, 10, seed=5, num_clusters=3)
        np.testing.assert_array_equal(a, b)
        assert a.shape == (100, 10) and a.dtype == np.float32

    def test_sparse_ratings_low_rank(self):
        r, c, v = datagen.sparse_ratings(50, 40, 8, density=0.2, seed=2)
        assert r.size == int(50 * 40 * 0.2)
        assert r.max() < 50 and c.max() < 40


def test_scheduler_propagates_task_errors():
    """A failing task must fail the CALLER, not hang drain() forever
    (code-review r3: the monitor thread used to die without producing its
    output slot)."""
    from harp_tpu.sched.dynamic import DynamicScheduler, Task

    class Boom(Task):
        def run(self, item):
            if item == 2:
                raise RuntimeError("task 2 exploded")
            return item * 10

    sched = DynamicScheduler([Boom(), Boom()])
    sched.start()
    sched.submit_all([1, 2, 3])
    with pytest.raises(RuntimeError, match="exploded"):
        sched.drain()
    sched.stop()


def test_load_coo_missing_file_raises_not_hangs(tmp_path):
    import os

    from harp_tpu.io import loaders

    good = os.path.join(str(tmp_path), "good.coo")
    with open(good, "w") as f:
        f.write("0 1 2.5\n")
    with pytest.raises(Exception):
        loaders.load_coo([good, os.path.join(str(tmp_path), "missing.coo")])


def test_load_coo_duplicate_paths_keep_both(tmp_path):
    import os

    from harp_tpu.io import loaders

    p = os.path.join(str(tmp_path), "a.coo")
    with open(p, "w") as f:
        f.write("0 1 2.0\n1 2 3.0\n")
    rows, cols, vals = loaders.load_coo([p, p])
    assert rows.tolist() == [0, 1, 0, 1]
    assert vals.tolist() == [2.0, 3.0, 2.0, 3.0]


def test_coo_to_csr_validates_and_fixes_dtype():
    from harp_tpu.io import loaders

    rows = np.array([0, -1], np.int64)
    with pytest.raises(ValueError, match="row ids"):
        loaders.coo_to_csr(rows, np.zeros(2, np.int64),
                           np.ones(2, np.float64), num_rows=2)
    # f64 values come back f32 on BOTH paths (build-independent dtype)
    ip, ix, v = loaders.coo_to_csr(np.array([1, 0]), np.array([3, 4]),
                                   np.array([1.5, 2.5], np.float64))
    assert v.dtype == np.float32
    assert ip.tolist() == [0, 1, 2] and ix.tolist() == [4, 3]


# --------------------------------------------------------------------------- #
# Remote-store seam (the HDFS role — HarpDAALDataSource.java:64 via fsspec)
# --------------------------------------------------------------------------- #


def test_loaders_over_memory_urls(session):
    """e2e over an object-store filesystem: write part-files to memory://,
    list the directory, load dense CSV + COO through the reader pool, and
    feed a model — the reference's HDFS-directory-of-parts idiom."""
    import fsspec

    from harp_tpu.io import loaders
    from harp_tpu.models import kmeans as km

    rng = np.random.default_rng(5)
    fs = fsspec.filesystem("memory")
    parts = []
    all_rows = []
    for i in range(3):
        block = rng.standard_normal((16, 4)).astype(np.float32)
        all_rows.append(block)
        path = f"memory://harp_io_test/part-{i:03d}.csv"
        with fsspec.open(path, "w") as f:
            for row in block:
                f.write(",".join(f"{v:.6f}" for v in row) + "\n")
        parts.append(path)
    try:
        listed = loaders.list_files("memory://harp_io_test/")
        # fsspec canonicalizes memory:// paths as rooted (memory:///x)
        assert [p.rsplit("/", 1)[-1] for p in listed] == \
            [p.rsplit("/", 1)[-1] for p in sorted(parts)], listed
        dense = loaders.load_dense_csv(listed)
        np.testing.assert_allclose(dense, np.concatenate(all_rows),
                                   atol=1e-5)   # %.6f write precision
        # split across workers then fit — the full ingest → train path
        groups = loaders.split_files(listed, 3)
        assert [len(g) for g in groups] == [1, 1, 1]
        cen, costs = km.KMeans(session, km.KMeansConfig(
            num_centroids=2, dim=4, iterations=3)).fit(dense, dense[:2])
        assert np.isfinite(np.asarray(costs)).all()

        coo_path = "memory://harp_io_test/coo-000.txt"
        with fsspec.open(coo_path, "w") as f:
            f.write("0 1 2.5\n1 0 1.5\n")
        r, c, v = loaders.load_coo([coo_path])
        assert r.tolist() == [0, 1] and c.tolist() == [1, 0]
        np.testing.assert_allclose(v, [2.5, 1.5])
    finally:
        fs.rm("/harp_io_test", recursive=True)


def test_list_files_local_dir_and_glob(tmp_path):
    from harp_tpu.io import loaders

    for name in ("b.csv", "a.csv", "c.txt"):
        (tmp_path / name).write_text("1,2\n")
    got = loaders.list_files(str(tmp_path))
    assert [os.path.basename(p) for p in got] == ["a.csv", "b.csv", "c.txt"]
    got = loaders.list_files(str(tmp_path / "*.csv"))
    assert [os.path.basename(p) for p in got] == ["a.csv", "b.csv"]
