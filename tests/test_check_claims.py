"""Claims honesty check (tools/check_claims.py) — tier-1.

VERDICT r5 #8: README/PERF headline throughput numbers must sit inside the
latest committed BENCH record's bands (the "≥6×" vs 5.22/5.44 drift class).
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_claims  # noqa: E402


def test_repo_claims_match_committed_bench_record():
    assert check_claims.check(REPO) == []
    assert check_claims.main([REPO]) == 0


def test_parse_value_suffixes():
    assert check_claims.parse_value("1397") == 1397.0
    assert check_claims.parse_value("1.11M") == 1.11e6
    assert check_claims.parse_value("3.05B") == 3.05e9
    assert check_claims.parse_value("3.05G") == 3.05e9
    assert check_claims.parse_value("67.2M") == 67.2e6
    assert check_claims.parse_value("fast") is None


def test_drifted_claim_fails():
    claim = check_claims.Claim("x", "DOC.md", r"rate is (\S+) tokens/s",
                               ("row", "rate"))
    bench = {"row": {"rate": 100.0}}
    assert check_claims.check_claim(claim, "rate is 103 tokens/s",
                                    bench) is None
    v = check_claims.check_claim(claim, "rate is 150 tokens/s", bench)
    assert v and "out of" not in v and "150" in v     # drift is named
    # ±10% band is relative to the RECORDED value
    assert check_claims.check_claim(claim, "rate is 111 tokens/s",
                                    bench) is not None


def test_stale_entry_and_null_record_fail():
    claim = check_claims.Claim("x", "DOC.md", r"rate is (\S+) tokens/s",
                               ("row", "rate"))
    # reworded prose: the pattern no longer matches → loud
    v = check_claims.check_claim(claim, "throughput: 103 tokens/s", {})
    assert v and "not found" in v
    # null bench value (e.g. a pending on-chip row): a numeric claim on an
    # unmeasured row must fail
    v = check_claims.check_claim(claim, "rate is 103 tokens/s",
                                 {"row": {"rate": None}})
    assert v and "unmeasured" in v
