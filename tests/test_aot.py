"""AOT dispatch artifacts (ISSUE 15): store round-trip, the invalidation
matrix, endpoint load parity + the never-recompile contract, manifest
drift detection, the per-model coalescing deadline satellite, and the
persistent compile-cache wiring."""

import json
import os
import threading
import time

import numpy as np
import pytest

from harp_tpu.aot import serve_artifacts
from harp_tpu.aot.store import (FMT_EXPORT, ArtifactKey, ArtifactStore,
                                canonical_program_text, layout_of)
from harp_tpu.serve.endpoints import TopKEndpoint, classify_from_nn
from harp_tpu.utils.metrics import Metrics


def _metrics_store(tmp_path, sub="store"):
    m = Metrics()
    return m, ArtifactStore(str(tmp_path / sub), metrics=m)


def _topk(session, _rng=None, name="mf", buckets=(8,), k=3, seed=0):
    # self-seeded so a donor/twin pair built back to back holds the SAME
    # factor tables (parity asserts compare their dispatches)
    rng = np.random.default_rng(seed)
    uf = rng.normal(size=(48, 6)).astype(np.float32)
    items = rng.normal(size=(24, 6)).astype(np.float32)
    return TopKEndpoint(session, name, uf, items, k=k,
                        bucket_sizes=buckets), uf, items


def _nn_endpoint(session, name="nn", buckets=(8,)):
    from harp_tpu.models import nn

    model = nn.MLPClassifier(session, nn.NNConfig(layers=(8,),
                                                  num_classes=3))
    model.params = nn.init_params((12, 8, 3), seed=0)
    return classify_from_nn(session, model, name=name,
                            bucket_sizes=buckets)


# --------------------------------------------------------------------------- #
# Store round-trip
# --------------------------------------------------------------------------- #

def test_store_roundtrip_parity_and_hit_metric(session, rng, tmp_path):
    import jax.numpy as jnp

    m, store = _metrics_store(tmp_path)
    fn = session.spmd(lambda x: jnp.tanh(x) * 2.0,
                      in_specs=(session.shard(),),
                      out_specs=session.shard())
    x = session.scatter(rng.normal(size=(16, 4)).astype(np.float32))
    key = ArtifactKey(name="t/roundtrip", world=session.num_workers,
                      layout=layout_of((x,)), model_hash="h")
    meta = store.export_and_put(key, fn, (x,))
    assert meta["format"] == FMT_EXPORT and meta["content_hash"]
    hit = store.load(key)
    assert hit is not None
    loaded, meta2 = hit
    assert meta2["content_hash"] == meta["content_hash"]
    np.testing.assert_array_equal(np.asarray(loaded(x)),
                                  np.asarray(fn(x)))
    counters = m.snapshot()["counters"]
    assert counters["aot.store.hit"] == 1
    assert counters["aot.store.put"] == 1


def test_canonical_text_strips_locations():
    text = ('#loc1 = loc("/tmp/x.py":3:0)\n'
            'module @jit_f {\n'
            '  %0 = stablehlo.add %a, %b : tensor<f32> loc(#loc1)\n'
            '  %1 = stablehlo.abs %0 : tensor<f32> loc(unknown)\n'
            '}\n')
    canon = canonical_program_text(text)
    assert "loc(" not in canon
    assert "stablehlo.add" in canon and "stablehlo.abs" in canon


# --------------------------------------------------------------------------- #
# Invalidation matrix: every stale axis rejects LOUDLY and falls back
# --------------------------------------------------------------------------- #

def _doctor_meta(store, name, **fields):
    path = store._paths(name)[0]
    with open(path) as f:
        meta = json.load(f)
    meta.update(fields)
    with open(path, "w") as f:
        json.dump(meta, f)


@pytest.mark.parametrize("axis,doctor", [
    ("jax_version", {"jax_version": "0.0.1"}),
    ("device_kind", {"device_kind": "TPU v99"}),
    ("world", {"world": 4096}),
    ("layout", {"layout": "doctored-layout"}),
])
def test_invalidation_matrix_meta_axes(session, rng, tmp_path, axis,
                                       doctor):
    m, store = _metrics_store(tmp_path, sub=axis)
    ep, _uf, _items = _topk(session, rng)
    serve_artifacts.export_endpoint(store, ep, model_hash="h")
    name = serve_artifacts.dispatch_name("mf", 8)
    _doctor_meta(store, name, **doctor)
    twin, _, _ = _topk(session, rng)
    loaded = serve_artifacts.load_endpoint(store, twin, model_hash="h",
                                           warm=False)
    assert loaded == []              # rejected, not served
    counters = m.snapshot()["counters"]
    assert counters[f"aot.store.miss_{axis}"] == 1, counters
    # ...and the fallback COMPILES, correctly (the loud path never
    # degrades service)
    ids = np.array([1, 7, 40])
    assert twin.dispatch(ids) == ep.dispatch(ids)
    assert twin.trace_counts == {8: 1}
    assert twin.aot_loaded == set()


def test_invalidation_model_hash_absent_and_corrupt(session, rng,
                                                    tmp_path):
    m, store = _metrics_store(tmp_path)
    ep, _, _ = _topk(session, rng)
    name = serve_artifacts.dispatch_name("mf", 8)
    # absent: empty store
    twin, _, _ = _topk(session, rng)
    assert serve_artifacts.load_endpoint(store, twin, warm=False) == []
    assert m.snapshot()["counters"]["aot.store.miss_absent"] == 1
    # model hash: exported under one model identity, loaded under another
    serve_artifacts.export_endpoint(store, ep, model_hash="model-A")
    assert serve_artifacts.load_endpoint(store, twin, model_hash="model-B",
                                         warm=False) == []
    assert m.snapshot()["counters"]["aot.store.miss_model_hash"] == 1
    # corrupt payload: bytes no longer match the meta's sha
    with open(store._paths(name)[1], "r+b") as f:
        f.write(b"garbage")
    assert serve_artifacts.load_endpoint(store, twin, model_hash="model-A",
                                         warm=False) == []
    assert m.snapshot()["counters"]["aot.store.miss_corrupt"] == 1


# --------------------------------------------------------------------------- #
# Endpoint load: parity, zero traces, loud displacement, rebalance reset
# --------------------------------------------------------------------------- #

def test_endpoint_load_zero_trace_and_parity(session, rng, tmp_path):
    m, store = _metrics_store(tmp_path)
    donor, _, _ = _topk(session, rng, buckets=(8, 16))
    serve_artifacts.export_endpoint(store, donor, model_hash="h")
    twin, _, _ = _topk(session, rng, buckets=(8, 16))
    loaded = serve_artifacts.load_endpoint(store, twin, model_hash="h")
    assert loaded == [8, 16]
    assert twin.aot_loaded == {8, 16}
    for n in (3, 12):                # both buckets, real traffic
        ids = rng.integers(0, 48, size=n)
        assert twin.dispatch(ids) == donor.dispatch(ids)
    # THE contract: artifact-loaded buckets never traced in this process
    assert twin.trace_counts == {}
    assert m.snapshot()["counters"]["aot.store.hit"] == 2


def test_classify_endpoint_load_parity(session, rng, tmp_path):
    _m, store = _metrics_store(tmp_path)
    donor = _nn_endpoint(session)
    serve_artifacts.export_endpoint(store, donor, model_hash="h")
    twin = _nn_endpoint(session)
    assert serve_artifacts.load_endpoint(store, twin,
                                         model_hash="h") == [8]
    x = rng.normal(size=(5, 12)).astype(np.float32)
    assert twin.dispatch(x) == donor.dispatch(x)
    assert twin.trace_counts == {}


def test_displaced_artifact_install_fails_loud(session, rng, tmp_path):
    _m, store = _metrics_store(tmp_path)
    donor, _, _ = _topk(session, rng)
    serve_artifacts.export_endpoint(store, donor, model_hash="h")
    twin, _, _ = _topk(session, rng)
    serve_artifacts.load_endpoint(store, twin, model_hash="h", warm=False)
    # simulate a displacement bug: the installed fn vanishes while the
    # loaded mark stays — the rebuild must NOT silently recompile
    twin._fns.pop(8)
    with pytest.raises(RuntimeError, match="never recompile"):
        twin.dispatch(np.array([1]))


def test_rebalance_clears_loaded_marks_and_recompiles(session, rng,
                                                      tmp_path):
    _m, store = _metrics_store(tmp_path)
    donor, uf, _items = _topk(session, rng)
    serve_artifacts.export_endpoint(store, donor, model_hash="h")
    twin, _, _ = _topk(session, rng)
    serve_artifacts.load_endpoint(store, twin, model_hash="h", warm=False)
    assert twin.aot_loaded == {8}
    twin.rebalance(1)                # owner-routed layout: NEW program
    assert twin.aot_loaded == set()
    ids = np.array([2, 9, 33])
    assert twin.dispatch(ids) == donor.dispatch(ids)
    assert twin.trace_counts == {8: 1}    # the lazy rebuild may trace


# --------------------------------------------------------------------------- #
# Manifest: clean against the committed pin, drift is a finding
# --------------------------------------------------------------------------- #

def test_manifest_diff_logic(tmp_path, monkeypatch):
    from harp_tpu.aot import manifest

    rows = {"serve/x/b8": {"content_hash": "a" * 64,
                           "format": "jax_export", "payload_bytes": 10}}
    monkeypatch.setattr(manifest, "build_rows", lambda workdir: dict(rows))
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "tools"), exist_ok=True)
    manifest.write(root, dict(rows))
    assert manifest.check(root, str(tmp_path / "w")) == []
    # hash drift = a finding naming the target
    doctored = {"serve/x/b8": dict(rows["serve/x/b8"],
                                   content_hash="b" * 64)}
    manifest.write(root, doctored)
    findings = manifest.check(root, str(tmp_path / "w"))
    assert len(findings) == 1 and "serve/x/b8" in findings[0] \
        and "drifted" in findings[0]
    # stale pinned row + unpinned fresh target
    manifest.write(root, {"gone/row": rows["serve/x/b8"]})
    findings = manifest.check(root, str(tmp_path / "w"))
    assert any("not pinned" in f for f in findings)
    assert any("stale" in f for f in findings)
    # environment mismatch: ONE re-pin finding, no bogus per-row noise
    manifest.write(root, dict(rows))
    path = manifest.manifest_path(root)
    with open(path) as f:
        doc = json.load(f)
    doc["jax_version"] = "9.9.9"
    with open(path, "w") as f:
        json.dump(doc, f)
    findings = manifest.check(root, str(tmp_path / "w"))
    assert len(findings) == 1 and "re-pin" in findings[0]


@pytest.mark.large
def test_committed_manifest_matches_fresh_export(tmp_path):
    """The real gate: the committed tools/artifact_manifest.json must
    match a fresh in-process export of the registry (the jaxlint
    --artifacts-only stage, run as a test so tier-1 catches drift even
    when CI stages are skipped)."""
    from harp_tpu.aot import manifest

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = manifest.check(root, str(tmp_path / "w"))
    assert findings == [], "\n".join(findings)


# --------------------------------------------------------------------------- #
# Satellites: per-model max_wait_s + suggestion, compile cache
# --------------------------------------------------------------------------- #

def test_suggest_max_wait_from_span_table():
    from harp_tpu.serve.batcher import suggest_max_wait_s
    from harp_tpu.telemetry import spans

    m = Metrics()
    assert suggest_max_wait_s(m, "mf") is None      # no samples: keep cfg
    for wait in (0.001, 0.002, 0.004):
        bd = {"total_s": wait + 0.001, "submit_hop_s": 0.0005,
              "route_s": 0.0, "coalesce_s": wait, "dispatch_s": 0.0004,
              "reply_build_s": 0.0, "reply_hop_s": 0.0001,
              "forwarded": False, "model": "mf"}
        spans.observe_span(bd, m)
    got = suggest_max_wait_s(m, "mf", headroom=1.0)
    assert got == pytest.approx(0.004)              # p90 of the coalesce
    # clamped at both ends
    assert suggest_max_wait_s(m, "mf", headroom=100.0) == 0.05
    assert suggest_max_wait_s(m, "mf", headroom=1e-6) == 0.0002


def test_two_models_one_worker_honor_different_deadlines(session, rng):
    """ISSUE 15 satellite acceptance: two models on ONE worker with
    per-model max_wait_s overrides — a lone request to the slow-coalesce
    model waits ~its deadline, the fast model replies well before it."""
    from harp_tpu.serve import OP_CLASSIFY, local_gang

    slow, fast = 0.25, 0.002
    eps = {"a": _nn_endpoint(session, name="a"),
           "b": _nn_endpoint(session, name="b")}
    workers, make_client = local_gang(
        session, [eps], max_wait_s=fast,
        max_wait_overrides={"a": slow})
    client = make_client()
    try:
        x = rng.normal(size=(12,)).astype(np.float32)
        for model in ("a", "b"):     # compile both buckets first
            client.request(OP_CLASSIFY, model, x, timeout=60.0)
        t0 = time.perf_counter()
        client.request(OP_CLASSIFY, "b", x, timeout=30.0)
        dt_fast = time.perf_counter() - t0
        t0 = time.perf_counter()
        client.request(OP_CLASSIFY, "a", x, timeout=30.0)
        dt_slow = time.perf_counter() - t0
    finally:
        client.close()
        for w in workers:
            w.close()
    assert workers[0].batchers["a"].max_wait_s == slow
    assert workers[0].batchers["b"].max_wait_s == fast
    # the slow model's lone request waits out its own window; the fast
    # one must not inherit it (generous margins — CI boxes wobble)
    assert dt_slow >= slow * 0.8, dt_slow
    assert dt_fast < slow * 0.5, dt_fast


def test_compile_cache_dir_populates(session, rng, tmp_path):
    """ServeWorker(compile_cache_dir=) wires jax's persistent cache: a
    dispatch writes cache entries into the directory."""
    import jax

    from harp_tpu.serve import OP_CLASSIFY, local_gang

    cache_dir = str(tmp_path / "cc")
    prev = jax.config.jax_compilation_cache_dir
    workers, make_client = local_gang(
        session, [{"cc": _nn_endpoint(session, name="cc")}],
        compile_cache_dir=cache_dir)
    client = make_client()
    try:
        x = rng.normal(size=(12,)).astype(np.float32)
        client.request(OP_CLASSIFY, "cc", x, timeout=60.0)
        assert os.listdir(cache_dir), "no persistent-cache entries written"
    finally:
        client.close()
        for w in workers:
            w.close()
        # the cache config is process-global: restore AND re-latch so the
        # rest of the suite compiles exactly as before this test
        jax.config.update("jax_compilation_cache_dir", prev)
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()


def test_worker_aot_store_loads_before_serving(session, rng, tmp_path):
    """local_gang(aot_dir=): the worker ctor installs store hits — the
    endpoint serves loaded programs from its very first request
    (trace_counts stays empty) and reports what it loaded."""
    from harp_tpu.serve import OP_TOPK, local_gang

    _m, store = _metrics_store(tmp_path)
    donor, _, _ = _topk(session, rng, buckets=(8,))
    serve_artifacts.export_endpoint(store, donor)
    twin, _, _ = _topk(session, rng, buckets=(8,))
    workers, make_client = local_gang(session, [{"mf": twin}],
                                      aot_dir=store.root)
    client = make_client()
    try:
        assert workers[0].aot_loaded == {"mf": [8]}
        res = client.request(OP_TOPK, "mf", 7, timeout=60.0)
        assert res["items"] == donor.dispatch(np.array([7]))[0]["items"]
        assert twin.trace_counts == {}
    finally:
        client.close()
        for w in workers:
            w.close()


# --------------------------------------------------------------------------- #
# Static memory rows in artifact meta (ISSUE 19): metadata, never a key axis
# --------------------------------------------------------------------------- #

def test_export_records_static_memory_row_in_meta(session, rng, tmp_path):
    from harp_tpu.aot.store import KEY_AXES

    _m, store = _metrics_store(tmp_path)
    ep, _uf, _items = _topk(session, rng)
    metas = serve_artifacts.export_endpoint(store, ep, model_hash="h")
    assert metas, metas
    for meta in metas.values():
        mem = meta["memory"]
        assert mem["resident_arg_bytes"] > 0
        assert mem["peak_live_bytes"] >= mem["resident_arg_bytes"]
        assert mem["transient_peak_ratio"] > 1.0
    # the row is placement METADATA: the key matrix is unchanged, so a
    # memory field can never turn a load into a (or mask a real) miss
    assert KEY_AXES == ("jax_version", "device_kind", "world", "quant",
                        "layout", "model_hash")
    assert not any(axis in ("memory", "resident_arg_bytes",
                            "peak_live_bytes", "transient_peak_ratio")
                   for axis in KEY_AXES)


def test_memory_row_mismatch_or_absence_never_misses(session, rng,
                                                     tmp_path):
    # a doctored (or stripped — pre-r20 store) memory row must NOT reject
    # the artifact: only KEY_AXES decide hit vs miss
    m, store = _metrics_store(tmp_path)
    ep, _uf, _items = _topk(session, rng)
    serve_artifacts.export_endpoint(store, ep, model_hash="h")
    name = serve_artifacts.dispatch_name("mf", 8)
    _doctor_meta(store, name,
                 memory={"resident_arg_bytes": 1, "peak_live_bytes": 2,
                         "transient_peak_ratio": 2.0})
    twin, _, _ = _topk(session, rng)
    loaded = serve_artifacts.load_endpoint(store, twin, model_hash="h",
                                           warm=False)
    assert loaded == [8], loaded
    # strip the row entirely: still a hit
    path = store._paths(name)[0]
    with open(path) as f:
        meta = json.load(f)
    del meta["memory"]
    with open(path, "w") as f:
        json.dump(meta, f)
    twin2, _, _ = _topk(session, rng)
    loaded = serve_artifacts.load_endpoint(store, twin2, model_hash="h",
                                           warm=False)
    assert loaded == [8], loaded
    assert m.snapshot()["counters"]["aot.store.hit"] == 2


def test_aot_ls_prints_resident_and_peak_bytes(session, rng, tmp_path,
                                               capsys):
    from harp_tpu.run import run_aot

    _m, store = _metrics_store(tmp_path)
    ep, _uf, _items = _topk(session, rng)
    metas = serve_artifacts.export_endpoint(store, ep, model_hash="h")
    # one artifact with a pre-r20 (row-less) meta: the listing degrades
    # to placeholders instead of crashing
    name = serve_artifacts.dispatch_name("mf", 8)
    path = store._paths(name)[0]
    with open(path) as f:
        meta = json.load(f)
    stripped = dict(meta)
    del stripped["memory"]
    alt = str(tmp_path / "store2")
    store2 = ArtifactStore(alt)
    os.makedirs(os.path.dirname(store2._paths(name)[0]), exist_ok=True)
    with open(store2._paths(name)[0], "w") as f:
        json.dump(stripped, f)
    with open(store._paths(name)[1], "rb") as f:
        payload = f.read()
    with open(store2._paths(name)[1], "wb") as f:
        f.write(payload)

    assert run_aot(["ls", "--aot-dir", str(tmp_path / "store")]) == 0
    out = capsys.readouterr().out
    mem = metas[8]["memory"]
    assert f"res={mem['resident_arg_bytes']:>8d} B" in out
    assert f"peak={mem['peak_live_bytes']:>8d} B" in out

    assert run_aot(["ls", "--aot-dir", alt]) == 0
    out = capsys.readouterr().out
    assert "res=       ? B peak=       ? B" in out


# --------------------------------------------------------------------------- #
# Compiled-HLO rows in artifact meta (ISSUE 20): metadata, never a key axis
# --------------------------------------------------------------------------- #

def test_export_records_hlo_row_in_meta(session, rng, tmp_path):
    from harp_tpu.aot.store import KEY_AXES

    _m, store = _metrics_store(tmp_path)
    ep, _uf, _items = _topk(session, rng)
    metas = serve_artifacts.export_endpoint(store, ep, model_hash="h")
    assert metas, metas
    for meta in metas.values():
        hlo = meta["hlo"]
        assert hlo["instruction_count"] > 0
        assert set(hlo["collectives"]) == set(hlo["collective_bytes"])
        assert hlo["collective_bytes_total"] == sum(
            hlo["collective_bytes"].values())
        assert hlo["while_count"] >= 0
        # the top-k dispatch routes through the keyval all_to_alls — the
        # compiled row must show the partitioner kept them collective
        assert hlo["collectives"].get("all-to-all", 0) >= 1, hlo
    # the row is fleet-tooling METADATA: the key matrix is unchanged, so
    # an hlo field can never turn a load into a (or mask a real) miss
    assert KEY_AXES == ("jax_version", "device_kind", "world", "quant",
                        "layout", "model_hash")
    assert "hlo" not in KEY_AXES


def test_hlo_row_mismatch_or_absence_never_misses(session, rng, tmp_path):
    # a doctored (or stripped — pre-r21 store) hlo row must NOT reject
    # the artifact: only KEY_AXES decide hit vs miss
    m, store = _metrics_store(tmp_path)
    ep, _uf, _items = _topk(session, rng)
    serve_artifacts.export_endpoint(store, ep, model_hash="h")
    name = serve_artifacts.dispatch_name("mf", 8)
    _doctor_meta(store, name,
                 hlo={"collectives": {"all-gather": 99},
                      "collective_bytes": {"all-gather": 1},
                      "collective_bytes_total": 1,
                      "instruction_count": 1, "while_count": 0})
    twin, _, _ = _topk(session, rng)
    loaded = serve_artifacts.load_endpoint(store, twin, model_hash="h",
                                           warm=False)
    assert loaded == [8], loaded
    # strip the row entirely: still a hit
    path = store._paths(name)[0]
    with open(path) as f:
        meta = json.load(f)
    del meta["hlo"]
    with open(path, "w") as f:
        json.dump(meta, f)
    twin2, _, _ = _topk(session, rng)
    loaded = serve_artifacts.load_endpoint(store, twin2, model_hash="h",
                                           warm=False)
    assert loaded == [8], loaded
    assert m.snapshot()["counters"]["aot.store.hit"] == 2


def test_aot_ls_json_rows_are_machine_readable(session, rng, tmp_path,
                                               capsys):
    # `aot ls --json`: one JSON object per artifact with the key axes,
    # the r20 res/peak columns, and the r21 hlo row — and a pre-r20/r21
    # meta serializes those fields as null instead of crashing or
    # dropping the key
    from harp_tpu.run import run_aot

    _m, store = _metrics_store(tmp_path)
    ep, _uf, _items = _topk(session, rng)
    metas = serve_artifacts.export_endpoint(store, ep, model_hash="h")
    name = serve_artifacts.dispatch_name("mf", 8)
    path = store._paths(name)[0]
    with open(path) as f:
        meta = json.load(f)
    stripped = {k: v for k, v in meta.items()
                if k not in ("memory", "hlo")}
    alt = str(tmp_path / "store2")
    store2 = ArtifactStore(alt)
    os.makedirs(os.path.dirname(store2._paths(name)[0]), exist_ok=True)
    with open(store2._paths(name)[0], "w") as f:
        json.dump(stripped, f)
    with open(store._paths(name)[1], "rb") as f:
        payload = f.read()
    with open(store2._paths(name)[1], "wb") as f:
        f.write(payload)

    assert run_aot(["ls", "--aot-dir", str(tmp_path / "store"),
                    "--json"]) == 0
    rows = [json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()]
    assert len(rows) == len(metas)
    row = next(r for r in rows if r["name"] == name)
    assert row["resident_arg_bytes"] == \
        metas[8]["memory"]["resident_arg_bytes"]
    assert row["peak_live_bytes"] == metas[8]["memory"]["peak_live_bytes"]
    assert row["hlo"] == metas[8]["hlo"]
    assert row["world"] == session.num_workers
    assert row["content_hash"] == metas[8]["content_hash"]

    assert run_aot(["ls", "--aot-dir", alt, "--json"]) == 0
    rows = [json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()]
    assert rows[0]["hlo"] is None
    assert rows[0]["resident_arg_bytes"] is None
    assert rows[0]["peak_live_bytes"] is None
