"""Quantized serving path (ISSUE 17).

Covers the packed-row int8 codec (host encode -> device decode, the
all-zero-row corner), top-k answer parity vs the f32 endpoint at the
recsys bench shapes under BOTH scoring policies, classify label parity
with int8 resident params, the unknown-id and reshard-engine contracts
under int8 state, the pinned int8 dispatch-wire budget (a doctored f32
revert fails JL203), quant as a cache-key and AOT-key axis (stale-mode
hits / silent installs are impossible), the resident-bytes gauge, and the
compact reply wire (request-side negotiation, idempotent client decode,
old clients keep plain f32).
"""

import json
import os

import numpy as np
import pytest

from harp_tpu.collectives import quantize
from harp_tpu.serve import (OP_CLASSIFY, OP_TOPK, TopKEndpoint,
                            classify_from_nn, local_gang)
from harp_tpu.serve import protocol
from harp_tpu.serve.cache import TopKReplyCache
from harp_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _nn_model(session, dim=12, classes=3, seed=0):
    from harp_tpu.models import nn

    model = nn.MLPClassifier(session, nn.NNConfig(layers=(8,),
                                                  num_classes=classes))
    model.params = nn.init_params((dim, 8, classes), seed=seed)
    return model


def _factors(rng, users=64, items=32, rank=8):
    uf = rng.normal(size=(users, rank)).astype(np.float32)
    it = rng.normal(size=(items, rank)).astype(np.float32)
    return uf, it


def _overlap(a, b):
    k = max(len(a), len(b))
    return len(set(a) & set(b)) / k if k else 1.0


# --------------------------------------------------------------------------- #
# Packed-row codec
# --------------------------------------------------------------------------- #

def test_packed_row_codec_roundtrip_and_zero_row(session, rng):
    import jax.numpy as jnp

    rows = rng.normal(size=(17, 8)).astype(np.float32) * 3.0
    rows[5] = 0.0                       # the all-zero corner
    packed = quantize.encode_rows_np(rows)
    assert packed.dtype == np.int8
    assert packed.shape == (17, quantize.packed_row_width(8))
    q, scales = quantize.decode_rows(jnp.asarray(packed))
    deq = np.asarray(q, np.float32) * np.asarray(scales)[:, None]
    # per-row absmax scaling: error bounded by scale/2 = max|row|/254
    bound = np.abs(rows).max(axis=1, keepdims=True) / 254.0 + 1e-7
    assert (np.abs(deq - rows) <= bound).all()
    # the zero row decodes to EXACT +0.0 (its scale is 0.0, q * 0 = +0.0)
    assert np.asarray(scales)[5] == 0.0
    np.testing.assert_array_equal(deq[5], np.zeros(8, np.float32))
    # dequantize_rows is the fused device twin of (decode, multiply)
    fused = np.asarray(quantize.dequantize_rows(jnp.asarray(packed)))
    np.testing.assert_allclose(fused, deq, rtol=0, atol=0)


# --------------------------------------------------------------------------- #
# Top-k parity at the recsys bench shapes, both scoring policies
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("quant_score", ["int8_direct", "dequant"])
def test_topk_int8_overlap_at_bench_shapes(session, rng, quant_score):
    uf, items = _factors(rng, users=512, items=256, rank=8)
    k = 10
    ep32 = TopKEndpoint(session, f"mf32-{quant_score}", uf, items, k=k)
    ep8 = TopKEndpoint(session, f"mf8-{quant_score}", uf, items, k=k,
                       quant="int8", quant_score=quant_score)
    ids = rng.choice(512, size=64, replace=False)
    r32 = ep32.dispatch(ids)
    r8 = ep8.dispatch(ids)
    overlaps = [_overlap(a["items"], b["items"]) for a, b in zip(r32, r8)]
    assert float(np.mean(overlaps)) >= 0.95, (quant_score, overlaps)
    # int8 shrinks the resident store (the >= 3x bar is asserted at the
    # bench's rank-64 shapes below — at rank 8 the +4 B/row scale and the
    # id/count side-structures dilute the table term)
    assert ep32.resident_bytes() / ep8.resident_bytes() >= 2.0


def test_topk_int8_resident_reduction_at_rank64(session, rng):
    # the bench-row acceptance shape term: at rank 64 the packed row is
    # 68 int8 bytes vs 256 f32 bytes, so the endpoint footprint drops
    # >= 3x even with the id/count side-structures included
    uf, items = _factors(rng, users=64, items=32, rank=64)
    ep32 = TopKEndpoint(session, "mf32r64", uf, items, k=5)
    ep8 = TopKEndpoint(session, "mf8r64", uf, items, k=5, quant="int8")
    assert ep32.resident_bytes() / ep8.resident_bytes() >= 3.0
    assert [r["items"] for r in ep32.dispatch(np.arange(8))] == [
        r["items"] for r in ep8.dispatch(np.arange(8))]


def test_topk_int8_unknown_id_and_bad_quant(session, rng):
    uf, items = _factors(rng)
    ep = TopKEndpoint(session, "mf8u", uf, items, k=3, quant="int8")
    rows = ep.dispatch(np.array([1, 999]))
    assert rows[0]["found"] is True and len(rows[0]["items"]) == 3
    assert rows[1] == {"found": False, "items": [], "scores": []}
    with pytest.raises(ValueError, match="quant"):
        TopKEndpoint(session, "bad", uf, items, k=3, quant="int4")
    with pytest.raises(ValueError, match="quant_score"):
        TopKEndpoint(session, "bad2", uf, items, k=3, quant="int8",
                     quant_score="magic")


# --------------------------------------------------------------------------- #
# Classify parity with int8 resident params
# --------------------------------------------------------------------------- #

def test_classify_int8_label_parity(session, rng):
    nn_model = _nn_model(session)
    ep32 = classify_from_nn(session, nn_model, name="nnq32")
    ep8 = classify_from_nn(session, nn_model, name="nnq8", quant="int8")
    x = rng.normal(size=(48, 12)).astype(np.float32)
    got32, got8 = ep32.dispatch(x), ep8.dispatch(x)
    agree = np.mean(np.asarray(got32) == np.asarray(got8))
    assert agree >= 0.95, (agree, got32, got8)
    assert ep32.resident_bytes() / ep8.resident_bytes() >= 3.0


# --------------------------------------------------------------------------- #
# Reshard engine under int8 state (packed rows ride the same moves)
# --------------------------------------------------------------------------- #

def test_int8_restore_shard_and_rebalance_keep_answers(session, rng):
    uf, items = _factors(rng)
    ep = TopKEndpoint(session, "mf8rs", uf, items, k=4, quant="int8")
    ids = np.arange(0, 64, 3)
    baseline = ep.dispatch(ids[:8])
    # wipe rank 2's shard, restore it through the reshard engine
    keys_d, vals_d, counts_d, items_d = ep._state[:4]
    wiped = np.asarray(vals_d).copy()
    wiped[2] = 0
    ep._state = (keys_d, ep.session.scatter(wiped), counts_d, items_d)
    assert ep.dispatch(ids[:8]) != baseline
    n = ep.restore_shard(2, uf)
    assert n == int(np.sum(np.arange(64) % 8 == 2))
    assert ep.dispatch(ids[:8]) == baseline
    # rebalance away from rank 1: same answers, unknown ids still clean
    info = ep.rebalance(1)
    assert info["owners"][1] == 0
    assert ep.dispatch(ids[:8]) == baseline
    assert ep.dispatch(np.array([999]))[0]["found"] is False


def test_int8_push_epoch_swaps_answers(session, rng):
    uf, items = _factors(rng)
    ep = TopKEndpoint(session, "mf8pe", uf, items, k=3, quant="int8")
    before = ep.dispatch(np.arange(8))
    rng2 = np.random.default_rng(99)
    uf2 = rng2.normal(size=uf.shape).astype(np.float32) * 2.0
    it2 = rng2.normal(size=items.shape).astype(np.float32) * 2.0
    ep.push_epoch(uf2, it2, version=1)
    after = ep.dispatch(np.arange(8))
    assert after != before
    # the swapped epoch answers match a fresh int8 endpoint on uf2/it2
    fresh = TopKEndpoint(session, "mf8pe2", uf2, it2, k=3, quant="int8")
    assert [r["items"] for r in after] == [
        r["items"] for r in fresh.dispatch(np.arange(8))]


# --------------------------------------------------------------------------- #
# The pinned int8 wire: strictly below f32, doctored revert is loud
# --------------------------------------------------------------------------- #

def test_int8_budget_row_pinned_below_f32():
    from tools.jaxlint import checkers_jaxpr

    with open(os.path.join(REPO, checkers_jaxpr.BUDGET_FILE)) as f:
        manifest = json.load(f)
    f32 = manifest["targets"]["serve_topk_mf"]
    i8 = manifest["targets"]["serve_topk_mf_int8"]
    assert i8["collectives"] == f32["collectives"]
    assert 0 < i8["bytes_per_step"] < f32["bytes_per_step"]


def test_doctored_f32_revert_fails_jl203():
    # the silent-revert signature: the int8 target tracing at the f32
    # row's bytes — same counts, wider wire — must fail JL203
    from tools.jaxlint import checkers_jaxpr

    with open(os.path.join(REPO, checkers_jaxpr.BUDGET_FILE)) as f:
        manifest = json.load(f)
    f32 = manifest["targets"]["serve_topk_mf"]
    i8 = manifest["targets"]["serve_topk_mf_int8"]
    doctored = {"serve_topk_mf_int8": (
        dict(i8["collectives"]), [], dict(f32["bytes_by_kind"]))}
    findings = checkers_jaxpr.check_budget(REPO, doctored)
    hits = [f for f in findings if f.code == "JL203"
            and f.func == "serve_topk_mf_int8"]
    assert hits, findings
    # the honest bytes pass the same gate
    clean = {"serve_topk_mf_int8": (
        dict(i8["collectives"]), [], dict(i8["bytes_by_kind"]))}
    assert not any(f.func == "serve_topk_mf_int8"
                   for f in checkers_jaxpr.check_budget(REPO, clean))


# --------------------------------------------------------------------------- #
# Quant as a key axis: reply cache and AOT store
# --------------------------------------------------------------------------- #

def test_cache_keys_on_quant_mode():
    cache = TopKReplyCache(metrics=Metrics())
    cache.put("mf", 7, 0, {"items": [1]}, quant=None)        # f32 fill
    assert cache.get("mf", 7, 0, quant=None) == {"items": [1]}
    # the int8 twin at the SAME epoch can never see the f32 entry...
    assert cache.get("mf", 7, 0, quant="int8") is None
    # ...and an int8 fill flips latest, retiring the f32 mode's entries
    cache.put("mf", 7, 0, {"items": [2]}, quant="int8")
    assert cache.get_latest("mf", 7) == ({"items": [2]}, 0)


def test_aot_f32_artifact_is_loud_miss_for_int8_endpoint(session, rng,
                                                         tmp_path):
    from harp_tpu.aot import serve_artifacts
    from harp_tpu.aot.store import ArtifactStore

    m = Metrics()
    store = ArtifactStore(str(tmp_path / "store"), metrics=m)
    uf, items = _factors(rng, users=48, items=24, rank=6)
    donor = TopKEndpoint(session, "mfq", uf, items, k=3, bucket_sizes=(8,))
    serve_artifacts.export_endpoint(store, donor, model_hash="h")
    twin = TopKEndpoint(session, "mfq", uf, items, k=3, bucket_sizes=(8,),
                        quant="int8")
    loaded = serve_artifacts.load_endpoint(store, twin, model_hash="h",
                                           warm=False)
    # NEVER a silent install: the f32-keyed artifact misses on the quant
    # axis and the miss is metered
    assert loaded == []
    assert m.snapshot()["counters"].get("aot.store.miss_quant", 0) >= 1
    # the int8 endpoint's own export round-trips for its int8 twin
    serve_artifacts.export_endpoint(store, twin, model_hash="h")
    twin2 = TopKEndpoint(session, "mfq", uf, items, k=3, bucket_sizes=(8,),
                         quant="int8")
    assert serve_artifacts.load_endpoint(store, twin2, model_hash="h",
                                         warm=False) == [8]


# --------------------------------------------------------------------------- #
# Resident-bytes gauge
# --------------------------------------------------------------------------- #

def test_resident_bytes_gauge_exported(session, rng):
    m = Metrics()
    uf, items = _factors(rng)
    ep = TopKEndpoint(session, "mfg", uf, items, k=3, quant="int8",
                      metrics=m)
    gauges = m.snapshot()["gauges"]
    assert gauges["serve.resident_bytes.mfg"] == ep.resident_bytes()
    # the gauge tracks epoch swaps (re-published, same packed footprint)
    ep.push_epoch(uf * 2.0, items, version=1)
    assert (m.snapshot()["gauges"]["serve.resident_bytes.mfg"]
            == ep.resident_bytes())


# --------------------------------------------------------------------------- #
# Compact reply wire
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("enc", ["f16", "int8"])
def test_reply_encode_decode_roundtrip(enc):
    result = {"found": True, "items": [3, 1, 2],
              "scores": [1.5, -0.25, 0.125]}
    wire = protocol.encode_result(result, enc)
    assert "scores" not in wire and wire["scores_enc"]["dtype"] == enc
    assert wire["items"] == [3, 1, 2]
    back = protocol.decode_result(wire)
    tol = 1e-3 if enc == "f16" else 1.5 / 127.0
    np.testing.assert_allclose(back["scores"], result["scores"], atol=tol)
    # idempotent on both shapes: plain results and already-decoded ones
    assert protocol.decode_result(back) == back
    assert protocol.decode_result(result) == result
    assert protocol.decode_result(None) is None
    # non-score results (classify labels) pass through untouched
    assert protocol.encode_result(2, enc) == 2
    # empty scores encode to an empty payload and decode back
    empty = protocol.decode_result(protocol.encode_result(
        {"found": False, "items": [], "scores": []}, enc))
    assert empty["scores"] == []


def test_choose_enc_negotiation():
    assert protocol.choose_enc(None) is None
    assert protocol.choose_enc(()) is None
    assert protocol.choose_enc(("f16",)) == "f16"
    assert protocol.choose_enc(("int8", "f16")) == "int8"
    # unknown-first degrades to the first mode this worker supports
    assert protocol.choose_enc(("zstd9", "f16")) == "f16"
    assert protocol.choose_enc(("zstd9",)) is None
    assert protocol.choose_enc(7) is None
    with pytest.raises(ValueError, match="accept_enc"):
        protocol.make_request("r0", OP_TOPK, "mf", 1, (0, "h", 1),
                              accept_enc=("zstd9",))


def test_gang_encoded_replies_old_and_new_clients(session, rng):
    """End to end through the quantized gang: a new client (accept_enc)
    receives encoded scores and decodes them transparently; an old client
    (no accept_enc) keeps receiving plain f32 — same answers."""
    uf, items = _factors(rng)
    ep = TopKEndpoint(session, "mfe", uf, items, k=3, quant="int8")
    m = Metrics()
    workers, make_client = local_gang(session, [{"mfe": ep}], metrics=m,
                                      accept_enc=("f16",))
    new_c = make_client()
    try:
        res_new = new_c.request(OP_TOPK, "mfe", 5, timeout=60.0)
        assert res_new["found"] is True and len(res_new["scores"]) == 3
        assert all(isinstance(s, float) for s in res_new["scores"])
        # the worker really did encode (the counter is the proof — the
        # client-side decode makes the payload shape invisible up here)
        assert m.snapshot()["counters"].get(
            "serve.reply_encoded.f16", 0) >= 1
    finally:
        new_c.close()
        for w in workers:
            w.close()
    # old-client path: a fresh f32-contract gang on the same endpoint
    # state answers with IDENTICAL items and compatible scores
    ep2 = TopKEndpoint(session, "mfe2", uf, items, k=3, quant="int8")
    workers2, make_client2 = local_gang(session, [{"mfe2": ep2}])
    old = make_client2()
    try:
        res_old = old.request(OP_TOPK, "mfe2", 5, timeout=60.0)
        assert res_old["items"] == res_new["items"]
        np.testing.assert_allclose(res_old["scores"], res_new["scores"],
                                   atol=1e-2)
    finally:
        old.close()
        for w in workers2:
            w.close()
