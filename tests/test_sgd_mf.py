"""SGD-MF convergence tests (reference: sgd/SGDCollectiveMapper + BASELINE SGD-MF).

Statistical-parity strategy per SURVEY §7: the reference's async Hogwild updates are
only statistically specified, so we assert monotone-ish RMSE descent and recovery of
a low-rank signal, not a bitwise trajectory.
"""

import dataclasses

import pytest

import numpy as np

from harp_tpu.io import datagen
from harp_tpu.models import sgd_mf


def test_sgd_mf_converges(session):
    rows, cols, vals = datagen.sparse_ratings(
        num_users=96, num_items=80, rank=4, density=0.25, seed=3, noise=0.01)
    cfg = sgd_mf.SGDMFConfig(rank=8, lam=0.01, lr=0.08, epochs=20,
                             minibatches_per_hop=4)
    model = sgd_mf.SGDMF(session, cfg)
    w_f, h_f, rmse = model.fit(rows, cols, vals, 96, 80)

    assert rmse.shape == (cfg.epochs,)
    # pre-update streaming RMSE of the first epoch reflects the random init
    assert rmse[0] > 0.2
    # strong descent over training
    assert rmse[-1] < 0.25 * rmse[0]
    # final factors actually reconstruct the ratings
    final = sgd_mf.numpy_rmse(w_f, h_f, rows, cols, vals)
    assert final < 0.12


def test_sgd_mf_rmse_monitor_matches_factors(session):
    rows, cols, vals = datagen.sparse_ratings(
        num_users=64, num_items=64, rank=3, density=0.3, seed=11, noise=0.0)
    cfg = sgd_mf.SGDMFConfig(rank=6, lam=0.0, lr=0.05, epochs=12,
                             minibatches_per_hop=2)
    w_f, h_f, rmse = sgd_mf.SGDMF(session, cfg).fit(rows, cols, vals, 64, 64)
    # reported streaming RMSE (pre-update) should upper-bound the post-training
    # reconstruction error of the same epoch's end state
    final = sgd_mf.numpy_rmse(w_f, h_f, rows, cols, vals)
    assert final <= rmse[-1] * 1.5 + 1e-3
    assert np.all(np.isfinite(rmse))


def test_bucketize_covers_all_entries():
    rng = np.random.default_rng(0)
    nnz = 500
    rows = rng.integers(0, 40, nnz).astype(np.int32)
    cols = rng.integers(0, 30, nnz).astype(np.int32)
    vals = rng.standard_normal(nnz).astype(np.float32)
    r, c, v, m, rpw, cpb = sgd_mf.bucketize(rows, cols, vals, 8, 40, 30, 4)
    assert int(m.sum()) == nnz
    np.testing.assert_allclose(v[m > 0].sum(), vals.sum(), rtol=1e-4)
    # localized indices stay inside their blocks
    assert r.max() < rpw and c.max() < cpb
    # bucket length divisible by minibatch count
    assert r.shape[2] % 4 == 0


def test_serpentine_assign_balances_and_fits_capacity():
    rng = np.random.default_rng(7)
    counts = (rng.zipf(1.4, size=1000) * 3).astype(np.int64)
    bins, slots = sgd_mf.serpentine_assign(counts, 8)
    cap = -(-1000 // 8)
    assert slots.max() < cap
    # every bin holds ceil/floor ids
    sizes = np.bincount(bins, minlength=8)
    assert sizes.max() - sizes.min() <= 1
    # loads near-balanced (LPT-style bound: one heaviest id + an average share)
    loads = np.bincount(bins, weights=counts, minlength=8)
    assert loads.max() <= counts.max() + 2.0 * counts.sum() / 8
    # (bin, slot) is injective
    assert len(np.unique(bins.astype(np.int64) * cap + slots)) == 1000


def test_sparse_layout_bounds_padding_on_zipf_data(session):
    """VERDICT #4: power-law data must not blow up bucket padding."""
    rows, cols, vals = datagen.zipf_ratings(
        num_users=512, num_items=512, rank=4, alpha=1.2, density=0.05, seed=2)
    cfg = sgd_mf.SGDMFConfig(rank=8, lam=0.01, lr=0.05, epochs=8,
                             minibatches_per_hop=2, layout="sparse")
    model = sgd_mf.SGDMF(session, cfg)
    state = model.prepare(rows, cols, vals, 512, 512)
    assert model.last_layout_stats["overhead"] <= 4.0
    # and convergence is unchanged by the balanced remap
    w_f, h_f, rmse = model.fit_prepared(state)
    assert rmse[-1] < 0.6 * rmse[0]
    assert np.isfinite(sgd_mf.numpy_rmse(w_f, h_f, rows, cols, vals))

    # the round-1 contiguous layout on the same data, for contrast
    plain = sgd_mf.SGDMF(session, dataclasses.replace(cfg, balance=False))
    plain.prepare(rows, cols, vals, 512, 512)
    assert (model.last_layout_stats["overhead"]
            <= plain.last_layout_stats["overhead"] + 1e-9)


def test_dense_and_sparse_layouts_agree(session):
    """The masked dense-stripe path is the same SGD math as the sparse
    bucket path — both must recover the low-rank signal on identical data."""
    rows, cols, vals = datagen.sparse_ratings(
        num_users=96, num_items=80, rank=4, density=0.25, seed=3, noise=0.01)
    # dedupe so both layouts see the exact same entry set
    keys = rows.astype(np.int64) * 80 + cols
    _, first = np.unique(keys, return_index=True)
    rows, cols, vals = rows[first], cols[first], vals[first]
    finals = {}
    for layout in ("sparse", "dense"):
        cfg = sgd_mf.SGDMFConfig(rank=8, lam=0.01, lr=0.08, epochs=20,
                                 minibatches_per_hop=4, layout=layout)
        w_f, h_f, rmse = sgd_mf.SGDMF(session, cfg).fit(
            rows, cols, vals, 96, 80)
        finals[layout] = sgd_mf.numpy_rmse(w_f, h_f, rows, cols, vals)
        assert rmse[-1] < 0.3 * rmse[0], layout
    assert abs(finals["dense"] - finals["sparse"]) < 0.06


def test_hop_budget_tuner_policy():
    """adjustMiniBatch analog: sweeps once, then settles on the largest budget
    within slack of the fastest; EWMA tracks drift."""
    t = sgd_mf.HopBudgetTuner([1, 2, 4, 8], slack=0.2)
    # sweep order is ascending candidates
    sweep = [t.next_budget() for _ in range(4)]
    for nmb, sec in zip([1, 2, 4, 8], [1.0, 1.0, 1.1, 2.0]):
        assert t.next_budget() == nmb
        t.record(nmb, sec)
    assert sweep[0] == 1
    # 4 is within 20% of the best (1.0) -> pick the LARGEST qualifying budget
    assert t.chosen == 4
    assert t.next_budget() == 4
    # drift: budget 4 becomes slow; EWMA pushes choice down
    for _ in range(12):
        t.record(4, 3.0)
    assert t.chosen == 2


@pytest.mark.parametrize("layout", ["dense", "sparse"])
def test_fit_adaptive_converges_and_tunes(session, layout):
    rows, cols, vals = datagen.sparse_ratings(
        num_users=96, num_items=80, rank=4, density=0.25, seed=3, noise=0.01)
    cfg = sgd_mf.SGDMFConfig(rank=8, lam=0.01, lr=0.08, epochs=16,
                             minibatches_per_hop=4, layout=layout)
    model = sgd_mf.SGDMF(session, cfg)
    state = model.prepare(rows, cols, vals, 96, 80)
    w_f, h_f, rmse, tuner = model.fit_adaptive(state)
    assert rmse.shape == (16,)
    # every candidate was measured during the sweep, then a choice stuck
    assert set(tuner.times) == {1, 2, 4}
    assert tuner.chosen in (1, 2, 4)
    # convergence unhurt by the tuning epochs
    assert rmse[-1] < 0.3 * rmse[0]
    assert sgd_mf.numpy_rmse(w_f, h_f, rows, cols, vals) < 0.15


@pytest.mark.parametrize("layout", ["dense", "sparse"])
def test_fit_checkpointed_resume_matches_uninterrupted(session, tmp_path,
                                                       layout):
    """VERDICT #10: interrupt + resume mid-training reproduces the
    uninterrupted run exactly (training is deterministic given data+factors
    at the per-epoch program granularity)."""
    from harp_tpu.utils.checkpoint import Checkpointer

    rows, cols, vals = datagen.sparse_ratings(
        num_users=96, num_items=80, rank=4, density=0.25, seed=3, noise=0.01)
    cfg = sgd_mf.SGDMFConfig(rank=8, lam=0.01, lr=0.08, epochs=6,
                             minibatches_per_hop=4, layout=layout)
    model = sgd_mf.SGDMF(session, cfg)
    state = model.prepare(rows, cols, vals, 96, 80)

    # uninterrupted
    w_a, h_a, rmse_a, start_a = model.fit_checkpointed(
        state, Checkpointer(str(tmp_path / "a")), save_every=2)
    assert start_a == 0 and rmse_a.shape == (6,)

    # interrupted after 3 epochs, then resumed to completion
    ckpt_b = Checkpointer(str(tmp_path / "b"))
    model.fit_checkpointed(state, ckpt_b, epochs=3, save_every=1)
    w_b, h_b, rmse_b, start_b = model.fit_checkpointed(state, ckpt_b,
                                                       save_every=1)
    assert start_b == 3 and rmse_b.shape == (3,)
    np.testing.assert_array_equal(w_a, w_b)
    np.testing.assert_array_equal(h_a, h_b)
    np.testing.assert_array_equal(rmse_a[3:], rmse_b)

    # a fully-resumed call (nothing left to do) returns the final state
    w_c, h_c, rmse_c, start_c = model.fit_checkpointed(state, ckpt_b,
                                                       save_every=1)
    assert start_c == 6 and rmse_c.shape == (0,)
    np.testing.assert_array_equal(w_c, w_a)


@pytest.mark.parametrize("layout", ["dense", "sparse"])
def test_sgd_mf_two_slice_pipeline_converges(session, layout):
    """numModelSlices=2 parity: double-buffered rotation (dymoro pipeline)
    converges like the single-slice schedule — on BOTH data layouts."""
    rows, cols, vals = datagen.sparse_ratings(
        num_users=96, num_items=80, rank=4, density=0.25, seed=3, noise=0.01)
    cfg = sgd_mf.SGDMFConfig(rank=8, lam=0.01, lr=0.08, epochs=20,
                             minibatches_per_hop=4, num_slices=2,
                             layout=layout)
    w_f, h_f, rmse = sgd_mf.SGDMF(session, cfg).fit(rows, cols, vals, 96, 80)
    assert rmse[-1] < 0.25 * rmse[0]
    assert sgd_mf.numpy_rmse(w_f, h_f, rows, cols, vals) < 0.12


def test_sgd_mf_two_slice_covers_every_rating(session):
    """Every rating is visited exactly once per epoch (streaming count)."""
    rows, cols, vals = datagen.sparse_ratings(64, 64, 3, 0.3, seed=1)
    cfg = sgd_mf.SGDMFConfig(rank=4, epochs=1, minibatches_per_hop=2,
                             num_slices=2)
    model = sgd_mf.SGDMF(session, cfg)
    state = model.prepare(rows, cols, vals, 64, 64)
    # cnt accumulated in the epoch equals nnz -> rmse is finite and well-formed
    _, _, rmse = model.fit_prepared(state)
    assert np.all(np.isfinite(rmse))
    # direct check: bucket masks cover all ratings exactly once
    _, _, _, mask, _, _ = sgd_mf.bucketize(rows, cols, vals, 8, 64, 64, 2,
                                           num_col_blocks=16)
    assert int(mask.sum()) == len(vals)


def test_nan_ratings_rejected_and_auto_dense_respects_int32_guard(session):
    """NaN is the dense missing-entry sentinel: NaN input values raise; and
    auto layout never picks a dense slab the int32 scatter could not index."""
    rows = np.array([0, 1], np.int32)
    cols = np.array([0, 1], np.int32)
    vals = np.array([1.0, np.nan], np.float32)
    m = sgd_mf.SGDMF(session, sgd_mf.SGDMFConfig(rank=4, epochs=1))
    with pytest.raises(ValueError, match="NaN"):
        m.prepare(rows, cols, vals, 8, 8)

    # a geometry whose slab would exceed 2^31 elements must auto-pick sparse
    # even under an unlimited byte budget
    big = sgd_mf.SGDMF(session, sgd_mf.SGDMFConfig(
        rank=4, epochs=1, dense_max_bytes=1 << 62))
    assert big._choose_layout(200_000, 200_000) == "sparse"
    assert big._choose_layout(512, 512) == "dense"


def test_dense_mf_hop_pallas_matches_xla_stripes():
    """The fused pallas hop (interpret mode on CPU) is bit-comparable to the
    XLA stripe loop in models/sgd_mf._build_dense."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.ops import pallas_kernels as pk

    rng = np.random.default_rng(0)
    NMB, S, CPB, K = 2, 16, 256, 8
    RPW = NMB * S
    LR, LAM = 0.05, 0.01
    v = rng.random((RPW, CPB)).astype(np.float32)
    v[rng.random((RPW, CPB)) < 0.9] = np.nan
    vb = jnp.asarray(v, jnp.bfloat16)
    w0 = jnp.asarray(rng.random((RPW, K)), jnp.float32)
    h0 = jnp.asarray(rng.random((CPB, K)), jnp.float32)
    rc = jnp.asarray(rng.integers(1, 5, RPW), jnp.float32)
    cc = jnp.asarray(rng.integers(1, 5, (NMB, CPB)), jnp.float32)
    bf = jnp.bfloat16

    def stripe(state, xs):
        hb, sse = state
        w_s, v_s, rc_s, cc_s = xs
        hb_b = hb.astype(bf)
        pred = jax.lax.dot_general(w_s.astype(bf), hb_b,
                                   (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32)
        g = jnp.where(jnp.isnan(v_s), jnp.asarray(0.0),
                      v_s.astype(jnp.float32) - pred).astype(bf)
        dw = jax.lax.dot_general(g, hb_b, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        dh = jax.lax.dot_general(g, w_s.astype(bf), (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        w_s = w_s + LR * (dw - LAM * rc_s[:, None] * w_s)
        hb = hb + LR * (dh - LAM * cc_s[:, None] * hb)
        sse = sse + jnp.sum(g.astype(jnp.float32) ** 2)
        return (hb, sse), w_s

    (h_ref, sse_ref), w_ref = jax.lax.scan(
        stripe, (h0, jnp.zeros(())),
        (w0.reshape(NMB, S, K), vb.reshape(NMB, S, CPB),
         rc.reshape(NMB, S), cc))
    w_t, h_t, sse_pl = pk.dense_mf_hop_pallas(
        vb, w0.T, h0.T, rc.reshape(NMB, S), cc, LR, LAM, col_tile=128,
        interpret=True)
    np.testing.assert_allclose(np.asarray(w_ref.reshape(RPW, K)),
                               np.asarray(w_t.T), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_ref), np.asarray(h_t.T),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(sse_ref), float(sse_pl), rtol=1e-4)
