"""Regression/classification family tests (daal_linreg/ridge/naive/svm/knn +
contrib/mlr parity) — all checked against plain-numpy references."""

import numpy as np
import pytest

from harp_tpu.io import datagen
from harp_tpu.models import knn, linear, logistic, naive_bayes, svm


def test_linear_regression_recovers_beta(session):
    x, y, beta = datagen.regression_data(256, 10, num_targets=2, seed=5,
                                         noise=0.001)
    model = linear.LinearRegression(session).fit(x, y)
    np.testing.assert_allclose(model.beta, beta, atol=0.01)
    pred = model.predict(x)
    assert np.sqrt(np.mean((pred - y) ** 2)) < 0.01


def test_ridge_matches_numpy_closed_form(session):
    rng = np.random.default_rng(2)
    x = rng.standard_normal((128, 6)).astype(np.float32)
    y = rng.standard_normal((128, 1)).astype(np.float32)
    lam = 2.5
    model = linear.RidgeRegression(session, l2=lam, fit_intercept=False).fit(x, y)
    ref = np.linalg.solve(x.T @ x + lam * np.eye(6), x.T @ y)
    np.testing.assert_allclose(model.beta, ref, atol=1e-3)


def test_linear_regression_intercept(session):
    rng = np.random.default_rng(8)
    x = rng.standard_normal((160, 4)).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5, 3.0], np.float32) + 7.0)[:, None]
    model = linear.LinearRegression(session).fit(x, y)
    np.testing.assert_allclose(model.intercept, [7.0], atol=1e-2)


def test_multinomial_nb(session):
    rng = np.random.default_rng(4)
    # class c has elevated counts in feature block c
    n, d, c = 240, 12, 3
    y = rng.integers(0, c, n).astype(np.int32)
    x = rng.poisson(1.0, (n, d)).astype(np.float32)
    for ci in range(c):
        x[y == ci, ci * 4:(ci + 1) * 4] += rng.poisson(6.0, ((y == ci).sum(), 4))
    model = naive_bayes.MultinomialNB(session, num_classes=c).fit(x, y)
    acc = (model.predict(x) == y).mean()
    assert acc > 0.9


def test_gaussian_nb(session):
    x, y = datagen.classification_data(320, 8, 3, seed=6)
    # shift class means apart so GNB is applicable
    for c in range(3):
        x[y == c] += 3.0 * c
    model = naive_bayes.GaussianNB(session, num_classes=3).fit(x, y)
    assert (model.predict(x) == y).mean() > 0.9


def test_mlr_converges(session):
    x, y = datagen.classification_data(400, 10, 4, seed=9)
    cfg = logistic.MLRConfig(num_classes=4, lr=0.5, l2=1e-4, iterations=150)
    model = logistic.MLR(session, cfg)
    losses = model.fit(x, y)
    assert losses[-1] < 0.5 * losses[0]
    assert (model.predict(x) == y).mean() > 0.9


def test_linear_svm(session):
    rng = np.random.default_rng(12)
    n = 320
    w_true = np.array([1.5, -2.0, 0.7, 0.0, 1.0], np.float32)
    x = rng.standard_normal((n, 5)).astype(np.float32)
    y = (x @ w_true + 0.3 > 0).astype(np.int32)
    model = svm.LinearSVM(session, svm.SVMConfig(c=10.0, lr=0.05,
                                                 iterations=300))
    objs = model.fit(x, y)
    assert objs[-1] < objs[0]
    assert (model.predict(x) == y).mean() > 0.95


def test_knn(session):
    x, y = datagen.classification_data(400, 6, 3, seed=20)
    for c in range(3):
        x[y == c] += 4.0 * c          # separable clusters
    model = knn.KNNClassifier(session, k=5, num_classes=3).fit(x, y)
    queries = x[:40]
    pred = model.predict(queries)
    assert (pred == y[:40]).mean() > 0.95
    dists, labels = model.kneighbors(queries)
    assert dists.shape == (40, 5) and labels.shape == (40, 5)
    # nearest neighbor of a training point is itself (distance ~0)
    assert np.allclose(dists[:, 0], 0.0, atol=1e-3)
