"""Regression/classification family tests (daal_linreg/ridge/naive/svm/knn +
contrib/mlr parity) — all checked against plain-numpy references."""

import numpy as np
import pytest

from harp_tpu.io import datagen
from harp_tpu.models import knn, linear, logistic, naive_bayes, svm


def test_linear_regression_recovers_beta(session):
    x, y, beta = datagen.regression_data(256, 10, num_targets=2, seed=5,
                                         noise=0.001)
    model = linear.LinearRegression(session).fit(x, y)
    np.testing.assert_allclose(model.beta, beta, atol=0.01)
    pred = model.predict(x)
    assert np.sqrt(np.mean((pred - y) ** 2)) < 0.01


def test_ridge_matches_numpy_closed_form(session):
    rng = np.random.default_rng(2)
    x = rng.standard_normal((128, 6)).astype(np.float32)
    y = rng.standard_normal((128, 1)).astype(np.float32)
    lam = 2.5
    model = linear.RidgeRegression(session, l2=lam, fit_intercept=False).fit(x, y)
    ref = np.linalg.solve(x.T @ x + lam * np.eye(6), x.T @ y)
    np.testing.assert_allclose(model.beta, ref, atol=1e-3)


def test_linear_regression_intercept(session):
    rng = np.random.default_rng(8)
    x = rng.standard_normal((160, 4)).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5, 3.0], np.float32) + 7.0)[:, None]
    model = linear.LinearRegression(session).fit(x, y)
    np.testing.assert_allclose(model.intercept, [7.0], atol=1e-2)


def test_multinomial_nb(session):
    rng = np.random.default_rng(4)
    # class c has elevated counts in feature block c
    n, d, c = 240, 12, 3
    y = rng.integers(0, c, n).astype(np.int32)
    x = rng.poisson(1.0, (n, d)).astype(np.float32)
    for ci in range(c):
        x[y == ci, ci * 4:(ci + 1) * 4] += rng.poisson(6.0, ((y == ci).sum(), 4))
    model = naive_bayes.MultinomialNB(session, num_classes=c).fit(x, y)
    acc = (model.predict(x) == y).mean()
    assert acc > 0.9


def test_gaussian_nb(session):
    x, y = datagen.classification_data(320, 8, 3, seed=6)
    # shift class means apart so GNB is applicable
    for c in range(3):
        x[y == c] += 3.0 * c
    model = naive_bayes.GaussianNB(session, num_classes=3).fit(x, y)
    assert (model.predict(x) == y).mean() > 0.9


def test_mlr_converges(session):
    x, y = datagen.classification_data(400, 10, 4, seed=9)
    cfg = logistic.MLRConfig(num_classes=4, lr=0.5, l2=1e-4, iterations=150)
    model = logistic.MLR(session, cfg)
    losses = model.fit(x, y)
    assert losses[-1] < 0.5 * losses[0]
    assert (model.predict(x) == y).mean() > 0.9


def test_linear_svm(session):
    rng = np.random.default_rng(12)
    n = 320
    w_true = np.array([1.5, -2.0, 0.7, 0.0, 1.0], np.float32)
    x = rng.standard_normal((n, 5)).astype(np.float32)
    y = (x @ w_true + 0.3 > 0).astype(np.int32)
    model = svm.LinearSVM(session, svm.SVMConfig(c=10.0, lr=0.05,
                                                 iterations=300))
    objs = model.fit(x, y)
    assert objs[-1] < objs[0]
    assert (model.predict(x) == y).mean() > 0.95


def test_kernel_svm_rbf_beats_linear_on_circles(session):
    """VERDICT r3 item 3's done-bar: a non-linearly-separable 2D dataset
    (concentric circles) where the LINEAR machine fails and the RBF kernel
    machine succeeds."""
    rng = np.random.default_rng(5)
    n = 256
    theta = rng.uniform(0, 2 * np.pi, n)
    radius = np.where(np.arange(n) % 2 == 0, 1.0, 3.0)
    y = (np.arange(n) % 2 == 0).astype(np.int32)   # inner circle = class 1
    x = (radius[:, None] * np.c_[np.cos(theta), np.sin(theta)]
         + 0.1 * rng.standard_normal((n, 2))).astype(np.float32)

    lin = svm.KernelSVM(session, svm.KernelSVMConfig(
        kernel="linear", c=10.0, iterations=300))
    lin.fit(x, y)
    acc_lin = (lin.predict(x) == y).mean()

    rbf = svm.KernelSVM(session, svm.KernelSVMConfig(
        kernel="rbf", sigma=1.0, c=10.0, iterations=300))
    duals = rbf.fit(x, y)
    acc_rbf = (rbf.predict(x) == y).mean()

    assert acc_lin < 0.7, acc_lin            # linear genuinely fails
    assert acc_rbf > 0.97, acc_rbf           # rbf separates the circles
    # exact dual objective at each iterate: monotone non-decreasing up to
    # f32 summation noise (projected gradient ascent with eta = 1/lambda_max)
    assert np.all(np.diff(duals) >= -1e-5 * np.maximum(np.abs(duals[:-1]), 1.0))
    assert rbf.sv_x is not None and len(rbf.sv_x) > 0


def test_kernel_svm_binary_agrees_with_margin(session):
    """On a separable problem the dual machine reaches the training labels
    and puts its support vectors near the margin."""
    rng = np.random.default_rng(8)
    n = 192
    x = rng.standard_normal((n, 4)).astype(np.float32)
    w_true = np.array([2.0, -1.0, 0.5, 1.5], np.float32)
    y = (x @ w_true > 0).astype(np.int32)
    m = svm.KernelSVM(session, svm.KernelSVMConfig(
        kernel="rbf", sigma=2.0, c=10.0, iterations=400))
    m.fit(x, y)
    assert (m.predict(x) == y).mean() > 0.97


def test_kernel_svm_early_stop_fires_on_recorded_config(session):
    """The RECORDED early-stop config (svm.EARLY_STOP_RECORDED_CONFIG — the
    VERDICT r5 leftover: no committed record showed the stop actually
    firing) must trigger well inside its budget, and the stopped model must
    match the full-budget run (predictions + converged dual)."""
    x, y = svm.early_stop_recorded_problem()
    cfg = dict(svm.EARLY_STOP_RECORDED_CONFIG)
    full = svm.KernelSVM(session, svm.KernelSVMConfig(
        **{**cfg, "early_stop_tol": 0.0}))
    duals_full = full.fit(x, y)
    es = svm.KernelSVM(session, svm.KernelSVMConfig(**cfg))
    duals = es.fit(x, y)
    # fires: strictly inside the budget (measured ~700 of 2000)
    assert es.n_iter_ < cfg["iterations"], es.n_iter_
    assert es.n_iter_ < 1500, es.n_iter_
    # parity: same predictions, and the stopped dual is within 0.5% of the
    # fully-converged one (measured 0.2%; the criterion bounds the tail's
    # per-step progress at 1e-5, so the residual gap is a few tenths of %)
    assert (es.predict(x) == full.predict(x)).mean() > 0.99
    np.testing.assert_allclose(duals[-1], duals_full[-1], rtol=5e-3)
    # plateau backfill keeps the fixed-shape trace monotone
    assert np.all(np.diff(duals) >= -1e-5 * np.maximum(np.abs(duals[:-1]),
                                                       1.0))


def test_kernel_svm_device_prediction_matches_numpy_oracle(session):
    """decision_function runs on device (_decision_jit); the host numpy
    kernel (_gram_np) is the oracle it must match."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal((96, 4)).astype(np.float32)
    y = (x[:, 1] + x[:, 2] > 0).astype(np.int32)
    m = svm.KernelSVM(session, svm.KernelSVMConfig(
        kernel="rbf", sigma=1.5, c=5.0, iterations=200))
    m.fit(x, y)
    z = rng.standard_normal((17, 4)).astype(np.float32)
    got = m.decision_function(z)
    want = (svm._gram_np(m.config, z, m.sv_x) + 1.0) @ m.sv_coef
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_multiclass_svm_one_vs_one(session):
    """DAAL MultiClassDenseBatch parity: one-vs-one vote over kernel
    machines classifies 3 Gaussian blobs (non-axis-aligned)."""
    x, y = datagen.classification_data(360, 5, 3, seed=31)
    for c in range(3):
        x[y == c, c % 5] += 5.0
    m = svm.MultiClassSVM(session, svm.KernelSVMConfig(
        kernel="rbf", sigma=2.0, c=10.0, iterations=300))
    m.fit(x, y)
    pred = m.predict(x)
    assert set(np.unique(pred)) <= set(np.unique(y))
    assert (pred == y).mean() > 0.95
    # one machine per class pair
    assert len(m._machines) == 3


def test_knn(session):
    x, y = datagen.classification_data(400, 6, 3, seed=20)
    for c in range(3):
        x[y == c] += 4.0 * c          # separable clusters
    model = knn.KNNClassifier(session, k=5, num_classes=3).fit(x, y)
    queries = x[:40]
    pred = model.predict(queries)
    assert (pred == y[:40]).mean() > 0.95
    dists, labels = model.kneighbors(queries)
    assert dists.shape == (40, 5) and labels.shape == (40, 5)
    # nearest neighbor of a training point is itself (distance ~0)
    assert np.allclose(dists[:, 0], 0.0, atol=1e-3)
