"""World-size-agnostic checkpoint resume (collectives.repartition).

The supervisor's shrink-relaunch (parallel.supervisor, on_suspect) restores
a checkpoint written by W workers into a W' != W gang. These tests pin the
restore-time resharding contract on the virtual CPU mesh: replicated leaves
(K-means centroids) transfer exactly; sharded leaves (SGD-MF factor tables,
the LDA chain) gather-and-resplit through the saved (bin, slot) / token-key
maps — a pure-resume round trip is EXACT in canonical id order, and a
resumed-then-continued run converges like an uninterrupted W' run.

All re-partitioning is host-side numpy at restore time: no step program
changes, so the jaxlint collective budgets (JL201/JL203) are untouched —
tools/jaxlint's pinned traces are the regression gate for that.
"""

import numpy as np
import pytest

from harp_tpu.collectives import repartition as rep
from harp_tpu.io import datagen
from harp_tpu.session import HarpSession
from harp_tpu.utils import checkpoint as ckpt_lib
from harp_tpu.utils.checkpoint import Checkpointer


@pytest.fixture(scope="module")
def sess8():
    return HarpSession(num_workers=8)


@pytest.fixture(scope="module")
def sess4():
    return HarpSession(num_workers=4)


# --------------------------------------------------------------------------- #
# primitives
# --------------------------------------------------------------------------- #

def test_permute_roundtrip_is_identity(rng):
    from harp_tpu.models.sgd_mf import serpentine_assign

    n, bins, rpb = 37, 4, 10
    counts = rng.integers(1, 50, n)
    assign = serpentine_assign(counts, bins)
    canon = rng.standard_normal((n, 3)).astype(np.float32)
    fill = np.full((bins * rpb, 3), np.nan, np.float32)
    permuted = rep.permute_rows(canon, assign[0], assign[1], rpb, fill)
    back = rep.unpermute_rows(permuted, assign[0], assign[1], rpb, n)
    np.testing.assert_array_equal(back, canon)


def test_repartition_factor_across_bin_counts(rng):
    from harp_tpu.models.sgd_mf import identity_assign, serpentine_assign

    n = 29
    counts = rng.integers(1, 9, n)
    old_assign, old_rpb = serpentine_assign(counts, 8), 4
    new_assign, new_rpb = identity_assign(n, 4), 8
    canon = rng.standard_normal((n, 2)).astype(np.float32)
    saved = rep.permute_rows(canon, old_assign[0], old_assign[1], old_rpb,
                             np.zeros((8 * old_rpb, 2), np.float32))
    moved = rep.repartition_factor(saved, old_assign, old_rpb, new_assign,
                                   new_rpb, n,
                                   np.zeros((4 * new_rpb, 2), np.float32))
    back = rep.unpermute_rows(moved, new_assign[0], new_assign[1], new_rpb, n)
    np.testing.assert_array_equal(back, canon)


def test_rematch_tokens_matches_by_doc_vocab(rng):
    docs = np.array([0, 0, 0, 1, 1])
    vocab = np.array([5, 5, 2, 2, 7])
    payload = np.array([10, 11, 12, 13, 14])
    order = rng.permutation(5)
    out = rep.rematch_tokens(docs, vocab, payload, docs[order], vocab[order])
    # same-(doc, vocab) duplicates may swap (exchangeable) — here all keys
    # with duplicates carry distinct payloads only within (0, 5)
    assert sorted(out.tolist()) == sorted(payload.tolist())
    for d, v in {(0, 2), (1, 2), (1, 7)}:
        mask_new = (docs[order] == d) & (vocab[order] == v)
        mask_old = (docs == d) & (vocab == v)
        assert set(out[mask_new]) == set(payload[mask_old])


def test_rematch_tokens_rejects_foreign_corpus():
    with pytest.raises(ValueError, match="different data"):
        rep.rematch_tokens(np.array([0]), np.array([1]), np.array([9]),
                           np.array([0]), np.array([2]))


# --------------------------------------------------------------------------- #
# checkpoint meta plumbing
# --------------------------------------------------------------------------- #

def test_state_meta_roundtrips_through_manifest(tmp_path):
    state = {"a": np.ones((3, 2), np.float32), "b": np.zeros(5, np.int32)}
    meta = ckpt_lib.state_meta(state, model="demo", world=8)
    ck = Checkpointer(str(tmp_path), use_orbax=False)
    ck.save(1, state, meta=meta)
    step, restored, got = ck.restore_latest_valid(
        like={k: np.zeros_like(v) for k, v in state.items()},
        return_meta=True)
    assert step == 1 and got["world"] == 8 and got["model"] == "demo"
    like = ckpt_lib.meta_like(got)
    assert like["a"].shape == (3, 2) and like["b"].dtype == np.int32
    np.testing.assert_array_equal(restored["a"], state["a"])


def test_like_from_meta_resolves_per_step(tmp_path):
    # steps written at DIFFERENT world sizes: the template must follow each
    # candidate step's own meta (a corrupt newest step falls back to a step
    # of another shape)
    from harp_tpu.parallel import faults

    ck = Checkpointer(str(tmp_path), use_orbax=False, keep=5)
    s1 = {"w": np.full((8, 2), 1.0, np.float32)}
    s2 = {"w": np.full((4, 2), 2.0, np.float32)}
    ck.save(1, s1, meta=ckpt_lib.state_meta(s1, world=8))
    ck.save(2, s2, meta=ckpt_lib.state_meta(s2, world=4))
    faults.corrupt_latest(str(tmp_path))
    step, state, meta = ck.restore_latest_valid(
        like_from_meta=lambda m: ckpt_lib.meta_like(m), return_meta=True)
    assert step == 1 and meta["world"] == 8
    assert np.shape(state["w"]) == (8, 2)


# --------------------------------------------------------------------------- #
# kmeans: replicated leaves restore exactly across world sizes
# --------------------------------------------------------------------------- #

def test_kmeans_w8_checkpoint_resumes_into_w4(tmp_path, sess8, sess4):
    from harp_tpu.models import kmeans as km

    pts = datagen.dense_points(256, 8, seed=0, num_clusters=4)
    cen0 = datagen.initial_centroids(pts, 4, seed=1)
    cfg = km.KMeansConfig(4, 8, iterations=6)

    m8 = km.KMeans(sess8, cfg)
    ck = Checkpointer(str(tmp_path / "ck"))
    m8.fit_checkpointed(*m8.prepare(pts, cen0), ck, save_every=1,
                        iterations=3)

    # shrink resume: W=8 checkpoint at iteration 3 finishes under W=4
    m4 = km.KMeans(sess4, cfg)
    ck_b = Checkpointer(str(tmp_path / "ck"))
    cen_res, costs_res, start = m4.fit_checkpointed(
        *m4.prepare(pts, cen0), ck_b, save_every=1)
    assert start == 3 and len(costs_res) == 3

    # convergence parity vs an uninterrupted W=4 run: Lloyd only reorders
    # the allreduce sum across worker counts, so the trajectories agree to
    # float tolerance
    m4c = km.KMeans(sess4, cfg)
    ck_c = Checkpointer(str(tmp_path / "clean"))
    cen_clean, costs_clean, _ = m4c.fit_checkpointed(
        *m4c.prepare(pts, cen0), ck_c, save_every=1)
    np.testing.assert_allclose(np.asarray(cen_res), np.asarray(cen_clean),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(costs_res[-1], costs_clean[-1], rtol=1e-4)


# --------------------------------------------------------------------------- #
# sgd_mf: sharded factors gather-and-resplit through the saved id maps
# --------------------------------------------------------------------------- #

def _ratings():
    return datagen.sparse_ratings(64, 64, rank=4, density=0.25, seed=3)


def test_sgd_mf_w8_state_restores_exactly_into_w4(tmp_path, sess8, sess4):
    # pure resume (no further epochs): the canonical (id-ordered) factors a
    # W=4 resume finalizes must be BITWISE the ones W=8 checkpointed. Note
    # 64 rows block to 8x8 AND 4x16 — the factor shapes collide across
    # worlds, so only the manifest world metadata can route this correctly.
    from harp_tpu.models import sgd_mf

    rows, cols, vals = _ratings()
    cfg = sgd_mf.SGDMFConfig(rank=4, epochs=2, layout="sparse",
                             minibatches_per_hop=2)
    m8 = sgd_mf.SGDMF(sess8, cfg)
    st8 = m8.prepare(rows, cols, vals, 64, 64, seed=0)
    ck = Checkpointer(str(tmp_path / "ck"))
    w_a, h_a, rmse_a, start_a = m8.fit_checkpointed(st8, ck, save_every=1)
    assert start_a == 0 and len(rmse_a) == 2

    m4 = sgd_mf.SGDMF(sess4, cfg)
    st4 = m4.prepare(rows, cols, vals, 64, 64, seed=0)
    ck_b = Checkpointer(str(tmp_path / "ck"))
    w_b, h_b, rmse_b, start_b = m4.fit_checkpointed(st4, ck_b, save_every=1)
    assert start_b == 2 and len(rmse_b) == 0
    np.testing.assert_array_equal(w_b, w_a)
    np.testing.assert_array_equal(h_b, h_a)


def test_sgd_mf_w8_checkpoint_continues_converging_at_w4(tmp_path, sess8,
                                                         sess4):
    from harp_tpu.models import sgd_mf

    rows, cols, vals = _ratings()
    cfg = sgd_mf.SGDMFConfig(rank=4, epochs=6, layout="sparse",
                             minibatches_per_hop=2)
    m8 = sgd_mf.SGDMF(sess8, cfg)
    st8 = m8.prepare(rows, cols, vals, 64, 64, seed=0)
    ck = Checkpointer(str(tmp_path / "ck"))
    m8.fit_checkpointed(st8, ck, epochs=2, save_every=1)

    m4 = sgd_mf.SGDMF(sess4, cfg)
    st4 = m4.prepare(rows, cols, vals, 64, 64, seed=0)
    ck_b = Checkpointer(str(tmp_path / "ck"))
    _, _, rmse_res, start = m4.fit_checkpointed(st4, ck_b, save_every=1)
    assert start == 2 and len(rmse_res) == 4
    assert rmse_res[-1] <= rmse_res[0] + 1e-6     # still descending at W=4

    m4c = sgd_mf.SGDMF(sess4, cfg)
    st4c = m4c.prepare(rows, cols, vals, 64, 64, seed=0)
    ck_c = Checkpointer(str(tmp_path / "clean"))
    _, _, rmse_clean, _ = m4c.fit_checkpointed(st4c, ck_c, save_every=1)
    # convergence parity: the shrink-resumed run lands where a clean W=4
    # run lands (trajectories differ — different blocking — but quality
    # must not)
    assert abs(float(rmse_res[-1]) - float(rmse_clean[-1])) < 0.05, \
        (rmse_res, rmse_clean)


# --------------------------------------------------------------------------- #
# lda: chain state re-matches tokens by (doc, vocab) key
# --------------------------------------------------------------------------- #

def test_lda_w8_chain_restores_exactly_into_w4(tmp_path, sess8, sess4):
    from harp_tpu.models import lda

    docs = datagen.lda_corpus(16, 32, 4, 12, seed=5)
    cfg = lda.LDAConfig(num_topics=4, vocab=32, epochs=2)
    m8 = lda.LDA(sess8, cfg)
    ck = Checkpointer(str(tmp_path / "ck"))
    dt_a, wt_a, ll_a, _ = m8.fit_checkpointed(m8.prepare(docs, seed=0), ck,
                                              save_every=1)

    m4 = lda.LDA(sess4, cfg)
    ck_b = Checkpointer(str(tmp_path / "ck"))
    dt_b, wt_b, ll_b, start = m4.fit_checkpointed(m4.prepare(docs, seed=0),
                                                  ck_b, save_every=1)
    assert start == 2 and len(ll_b) == 0
    # doc-topic and word-topic COUNTS are invariant under the only freedom
    # the re-match has (same-word-same-doc occurrence order) — exact
    np.testing.assert_array_equal(np.asarray(dt_b), np.asarray(dt_a))
    np.testing.assert_array_equal(np.asarray(wt_b), np.asarray(wt_a))


def test_lda_w8_checkpoint_continues_at_w4(tmp_path, sess8, sess4):
    from harp_tpu.models import lda

    docs = datagen.lda_corpus(16, 32, 4, 12, seed=5)
    cfg = lda.LDAConfig(num_topics=4, vocab=32, epochs=4)
    m8 = lda.LDA(sess8, cfg)
    ck = Checkpointer(str(tmp_path / "ck"))
    m8.fit_checkpointed(m8.prepare(docs, seed=0), ck, save_every=1, epochs=2)

    m4 = lda.LDA(sess4, cfg)
    ck_b = Checkpointer(str(tmp_path / "ck"))
    dt, wt, ll, start = m4.fit_checkpointed(m4.prepare(docs, seed=0), ck_b,
                                            save_every=1)
    assert start == 2 and len(ll) == 2
    assert np.all(np.isfinite(ll))
    assert dt.shape == (16, 4) and wt.shape == (32, 4)
    # the restored chain must carry exactly the corpus's token mass
    np.testing.assert_allclose(np.asarray(wt).sum(), docs.size, rtol=1e-6)


def test_sgd_mf_legacy_metaless_checkpoint_still_resumes(tmp_path, sess8):
    # a pre-elastic checkpoint holds only {w, h} and no manifest meta: the
    # SAME-world resume must keep working (restored through the legacy
    # template), not die on a leaf-count mismatch against the new 6-leaf
    # state
    from harp_tpu.models import sgd_mf

    rows, cols, vals = _ratings()
    cfg = sgd_mf.SGDMFConfig(rank=4, epochs=2, layout="sparse",
                             minibatches_per_hop=2)
    m = sgd_mf.SGDMF(sess8, cfg)
    st = m.prepare(rows, cols, vals, 64, 64, seed=0)
    ck = Checkpointer(str(tmp_path / "full"), use_orbax=False)
    w_a, h_a, _, _ = m.fit_checkpointed(st, ck, save_every=1)
    _, saved, _ = ck.restore_latest_valid(
        like_from_meta=lambda meta: ckpt_lib.meta_like(meta),
        return_meta=True)

    legacy = Checkpointer(str(tmp_path / "legacy"), use_orbax=False)
    legacy.save(2, {"w": saved["w"], "h": saved["h"]})      # no meta
    m2 = sgd_mf.SGDMF(sess8, cfg)
    st2 = m2.prepare(rows, cols, vals, 64, 64, seed=0)
    w_b, h_b, rmse_b, start = m2.fit_checkpointed(
        st2, Checkpointer(str(tmp_path / "legacy"), use_orbax=False),
        save_every=1)
    assert start == 2 and len(rmse_b) == 0
    np.testing.assert_array_equal(w_b, w_a)
    np.testing.assert_array_equal(h_b, h_a)


def test_lda_legacy_metaless_checkpoint_still_resumes(tmp_path, sess8):
    from harp_tpu.models import lda

    docs = datagen.lda_corpus(16, 32, 4, 12, seed=5)
    cfg = lda.LDAConfig(num_topics=4, vocab=32, epochs=2)
    m = lda.LDA(sess8, cfg)
    ck = Checkpointer(str(tmp_path / "full"), use_orbax=False)
    dt_a, wt_a, _, _ = m.fit_checkpointed(m.prepare(docs, seed=0), ck,
                                          save_every=1)
    _, saved, _ = ck.restore_latest_valid(
        like_from_meta=lambda meta: ckpt_lib.meta_like(meta),
        return_meta=True)

    legacy = Checkpointer(str(tmp_path / "legacy"), use_orbax=False)
    legacy.save(2, {"z": saved["z"], "wt": saved["wt"]})    # no meta
    m2 = lda.LDA(sess8, cfg)
    dt_b, wt_b, ll_b, start = m2.fit_checkpointed(
        m2.prepare(docs, seed=0),
        Checkpointer(str(tmp_path / "legacy"), use_orbax=False),
        save_every=1)
    assert start == 2 and len(ll_b) == 0
    np.testing.assert_array_equal(np.asarray(dt_b), np.asarray(dt_a))
    np.testing.assert_array_equal(np.asarray(wt_b), np.asarray(wt_a))


def test_wrong_model_work_dir_raises_clearly(tmp_path, sess8):
    # the restore template follows the SAVED shapes, so the old leaf-count
    # guard can't catch a wrong-model dir anymore — the recorded model name
    # must (an LDA resume pointed at an sgd_mf work dir used to die with a
    # raw KeyError)
    from harp_tpu.models import lda, sgd_mf

    rows, cols, vals = _ratings()
    cfg_mf = sgd_mf.SGDMFConfig(rank=4, epochs=1, layout="sparse",
                                minibatches_per_hop=2)
    m = sgd_mf.SGDMF(sess8, cfg_mf)
    st = m.prepare(rows, cols, vals, 64, 64, seed=0)
    ck = Checkpointer(str(tmp_path / "ck"), use_orbax=False)
    m.fit_checkpointed(st, ck, save_every=1)

    docs = datagen.lda_corpus(16, 32, 4, 12, seed=5)
    m_lda = lda.LDA(sess8, lda.LDAConfig(num_topics=4, vocab=32, epochs=2))
    with pytest.raises(ValueError, match="written by model 'sgd_mf'"):
        m_lda.fit_checkpointed(
            m_lda.prepare(docs, seed=0),
            Checkpointer(str(tmp_path / "ck"), use_orbax=False),
            save_every=1)
