"""Wire fault grammar tests (ISSUE 16): parse-time loudness for the net
kinds plus REAL-socket injection at the p2p frame boundary — the frames
cross an actual loopback connection and the receiver's decode guard, not
a mocked transport.
"""

import pytest

from harp_tpu.parallel import faults
from harp_tpu.parallel.events import EventQueue
from harp_tpu.parallel.p2p import P2PTransport


def _pair():
    q0, q1 = EventQueue(), EventQueue()
    t0 = P2PTransport(q0, rank=0, peers={})
    t1 = P2PTransport(q1, rank=1, peers={0: t0.address})
    t0._peers[1] = t1.address
    return q0, q1, t0, t1


# --------------------------------------------------------------------------- #
# Grammar: the wire kinds parse, and meaningless qualifiers fail LOUDLY
# --------------------------------------------------------------------------- #

def test_net_grammar_parses_every_wire_kind():
    (drop,) = faults.parse_faults("netdrop@request=3")
    assert (drop.kind, drop.request, drop.rank) == ("netdrop", 3, None)
    (delay,) = faults.parse_faults("netdelay@request=1:ms=5:rank=2")
    assert (delay.kind, delay.ms, delay.rank) == ("netdelay", 5, 2)
    (part,) = faults.parse_faults("netpart@request=1:rank=0:peer=1")
    assert (part.kind, part.peer) == ("netpart", 1)
    specs = faults.parse_faults("netdup@request=2,netcorrupt@request=4")
    assert [s.kind for s in specs] == ["netdup", "netcorrupt"]


def test_net_grammar_rejects_meaningless_qualifiers():
    for bad in (
        "netdrop@epoch=3",               # wire kinds ride the frame clock
        "netcorrupt@request=1:ms=5",     # ms= is slow/netdelay only
        "netpart@request=1",             # a directed cut NEEDS peer=
        "kill@request=1:peer=0",         # peer= is netpart only
        "netdrop@request=0",             # frame clock is 1-based
        "netdelay@epoch=2:ms=5",         # even the sustained kinds
        "netdup@request=1:epoch=1",      # never both clocks
    ):
        with pytest.raises(ValueError):
            faults.parse_faults(bad)


def test_net_grammar_rank_bounds_use_the_serving_world(monkeypatch):
    # request-clock specs live in the SERVING gang's rank space: a rank
    # the serving world cannot hold is a scripting bug, rejected at parse
    with pytest.raises(ValueError, match="serving world"):
        faults.parse_faults("netdrop@request=1:rank=5", serve_world_size=2)
    with pytest.raises(ValueError, match="serving world"):
        faults.parse_faults("netpart@request=1:peer=3", serve_world_size=2)
    assert faults.parse_faults("netdrop@request=1:rank=1",
                               serve_world_size=2)
    # the fleet spawner exports the width; parse reads it from the env
    monkeypatch.setenv("HARP_SERVE_WORLD", "2")
    with pytest.raises(ValueError, match="serving world"):
        faults.parse_faults("netdup@request=1:rank=3")
    # epoch-clock specs still bound against the TRAINING world
    assert faults.parse_faults("crash@epoch=1:rank=5", world_size=8,
                               serve_world_size=2)
    # a spec disarmed by attempt gating is exempt (post-shrink relaunch
    # keeps the env that killed the old top rank)
    assert faults.parse_faults("netdrop@request=1:rank=9:attempt=1",
                               serve_world_size=2)


def test_net_fire_one_shot_per_rank_and_delay_sustained(monkeypatch):
    monkeypatch.setenv("HARP_FAULT", "netdrop@request=5")
    assert faults.net_fire(4, rank=0, dest=1) == []
    assert faults.net_fire(5, rank=0, dest=1) == ["drop"]
    assert faults.net_fire(6, rank=0, dest=1) == []     # once per (spec,
    assert faults.net_fire(9, rank=1, dest=0) == ["drop"]   # rank)
    monkeypatch.setenv("HARP_FAULT", "netdelay@request=2:ms=9")
    naps = []
    for n in (1, 2, 3):
        assert faults.net_fire(n, rank=0, dest=1, sleep=naps.append) == []
    assert naps == [0.009, 0.009]        # sustained from frame 2 on


# --------------------------------------------------------------------------- #
# Real sockets: the transport applies the actions at its frame boundary
# --------------------------------------------------------------------------- #

def test_netdrop_eats_exactly_one_frame(monkeypatch):
    q0, q1, t0, t1 = _pair()
    monkeypatch.setenv("HARP_FAULT", "netdrop@request=2:rank=0")
    try:
        for i in range(3):
            t0.send(1, {"i": i})
        # frame 2 vanished on the wire; the sender saw a clean send and
        # the connection carried frame 3 as if nothing happened
        got = [q1.wait(timeout=30.0).payload["i"] for _ in range(2)]
        assert got == [0, 2]
        assert len(q1) == 0
    finally:
        monkeypatch.delenv("HARP_FAULT")
        t0.close()
        t1.close()


def test_netdup_delivers_the_frame_twice(monkeypatch):
    q0, q1, t0, t1 = _pair()
    monkeypatch.setenv("HARP_FAULT", "netdup@request=1:rank=0")
    try:
        t0.send(1, "hello")
        assert q1.wait(timeout=30.0).payload == "hello"
        assert q1.wait(timeout=30.0).payload == "hello"   # the retransmit
        t0.send(1, "after")                               # one-shot: clean
        assert q1.wait(timeout=30.0).payload == "after"
        assert len(q1) == 0
    finally:
        monkeypatch.delenv("HARP_FAULT")
        t0.close()
        t1.close()


def test_netcorrupt_dropped_by_decode_guard_connection_survives(monkeypatch):
    q0, q1, t0, t1 = _pair()
    monkeypatch.setenv("HARP_FAULT", "netcorrupt@request=1:rank=0")
    try:
        t0.send(1, "garbled-on-the-wire")
        # the length prefix stayed true, so the receiver consumed exactly
        # one frame of garbage, dropped it, and kept the connection: the
        # NEXT frame arrives on the same socket
        t0.send(1, "clean")
        ev = q1.wait(timeout=30.0)
        assert ev is not None and ev.payload == "clean"
        assert len(q1) == 0
    finally:
        monkeypatch.delenv("HARP_FAULT")
        t0.close()
        t1.close()


def test_netpart_is_directed_and_sustained(monkeypatch):
    q0, q1, t0, t1 = _pair()
    monkeypatch.setenv("HARP_FAULT", "netpart@request=1:rank=0:peer=1")
    try:
        # rank 0 cannot reach 1 — the same ConnectionError a dead NIC
        # produces, raised before the socket is touched, every time
        for _ in range(2):
            with pytest.raises(ConnectionError):
                t0.send(1, "cut")
        # ...but the cut is DIRECTED: 1 -> 0 still flows
        t1.send(0, "reverse-ok")
        ev = q0.wait(timeout=30.0)
        assert ev is not None and ev.payload == "reverse-ok"
        assert len(q1) == 0
    finally:
        monkeypatch.delenv("HARP_FAULT")
        t0.close()
        t1.close()
