"""Quantized collectives: codec round trips, error feedback, wire-format
correctness, and convergence parity for EVERY quantized model path vs its
f32 twin on the 8-worker mesh (ISSUE 6 acceptance).

Tolerances are pinned per codec: int8 quantizes to ~1/254 of each
256-element block's amax, bf16 to ~2^-8 relative — and error feedback keeps
the per-step error from compounding across a trajectory, which is what the
full-trajectory parity tests below actually exercise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu import combiner as cb
from harp_tpu.collectives import lax_ops, quantize, rotation
from harp_tpu.parallel import mesh as mesh_lib

W = 8


# -- codec round trips -------------------------------------------------------

@pytest.mark.parametrize("n", [256, 512, 300, 97, 1, 7])  # aligned/padded/prime
@pytest.mark.parametrize("codec", ["int8", "bf16"])
def test_codec_round_trip_error_bounds(rng, n, codec):
    comm = quantize.CommConfig(quant=codec)
    x = (10.0 * rng.standard_normal(n)).astype(np.float32)
    block = quantize._block_for(n, comm)
    payload, scale, n_out = quantize.encode_flat(jnp.asarray(x), comm, block)
    out = np.asarray(quantize.decode_flat(payload, scale, n_out, comm))
    assert out.shape == x.shape
    if codec == "int8":
        # error <= half a quantization step of the block's amax scale
        bound = np.abs(x).max() / 127.0 * 0.5 + 1e-6
    else:
        bound = np.abs(x) * 2.0 ** -8 + 1e-6   # bf16 ~8-bit mantissa
    assert np.all(np.abs(out - x) <= bound), np.abs(out - x).max()


def test_codec_zero_block_is_exact():
    comm = quantize.CommConfig(quant="int8")
    x = jnp.zeros((64,), jnp.float32)
    payload, scale, n = quantize.encode_flat(x, comm, 32)
    np.testing.assert_array_equal(
        np.asarray(quantize.decode_flat(payload, scale, n, comm)), 0.0)


def test_comm_config_validation():
    with pytest.raises(ValueError, match="quant"):
        quantize.CommConfig(quant="fp4")
    with pytest.raises(ValueError, match="block"):
        quantize.CommConfig(quant="int8", block=0)
    assert not quantize.CommConfig().active
    assert quantize.CommConfig(quant="bf16").active


def test_wire_bytes_per_element():
    assert quantize.wire_bytes_per_element(None) == 4.0
    assert quantize.wire_bytes_per_element(
        quantize.CommConfig(quant="bf16")) == 2.0
    int8 = quantize.wire_bytes_per_element(
        quantize.CommConfig(quant="int8"), 1024)
    assert 1.0 < int8 < 1.1          # payload + amortized per-block scale


# -- quantized collective semantics vs f32 ----------------------------------

@pytest.mark.parametrize("codec", ["int8", "bf16"])
def test_quantized_allreduce_matches_f32_within_codec_tol(session, rng,
                                                          codec):
    comm = quantize.CommConfig(quant=codec)
    contribs = rng.normal(size=(W, 37, 5)).astype(np.float32)

    def f(c):
        return lax_ops.allreduce(c[0], cb.SUM, comm=comm)[None]

    out = session.spmd(f, in_specs=(session.shard(),),
                       out_specs=session.replicate())(contribs[:, None])
    ref = contribs.sum(0)
    tol = 0.1 if codec == "int8" else 0.05
    assert np.abs(np.asarray(out)[0] - ref).max() < tol


@pytest.mark.parametrize("codec", ["int8", "bf16"])
def test_quantized_reduce_scatter_and_allgather(session, rng, codec):
    comm = quantize.CommConfig(quant=codec)
    contribs = rng.normal(size=(W, 16, 3)).astype(np.float32)

    def f(c):
        return lax_ops.reduce_scatter(c[0], cb.SUM, comm=comm)

    out = session.spmd(f, in_specs=(session.shard(),),
                       out_specs=session.shard())(contribs)
    ref = contribs.sum(0)
    assert np.abs(np.asarray(out).reshape(16, 3) - ref).max() < 0.1

    blocks = rng.normal(size=(W, 4)).astype(np.float32)

    def g(c):
        return lax_ops.allgather(c, comm=comm)[None]

    out2 = session.spmd(g, in_specs=(session.shard(),),
                        out_specs=session.replicate())(blocks)
    assert np.abs(np.asarray(out2)[0].reshape(W, 4) - blocks).max() < 0.05


@pytest.mark.parametrize("codec", ["int8", "bf16"])
def test_quantized_rotate(session, rng, codec):
    comm = quantize.CommConfig(quant=codec)
    blocks = rng.normal(size=(W, 6)).astype(np.float32)

    def f(c):
        return lax_ops.rotate(c, 1, comm=comm)

    out = session.spmd(f, in_specs=(session.shard(),),
                       out_specs=session.shard())(blocks)
    assert np.abs(np.asarray(out) - np.roll(blocks, 1, axis=0)).max() < 0.05


def test_quantized_requires_sum_or_avg(session):
    comm = quantize.CommConfig(quant="int8")
    with pytest.raises(ValueError, match="SUM/AVG"):
        def f(c):
            return lax_ops.allreduce(c[0], cb.MAX, comm=comm)[None]
        session.spmd(f, in_specs=(session.shard(),),
                     out_specs=session.replicate())(np.ones((W, 1, 4),
                                                            np.float32))


def test_avg_combiner_divides_once(session):
    comm = quantize.CommConfig(quant="bf16")
    contribs = np.full((W, 8), 2.0, np.float32)

    def f(c):
        return lax_ops.allreduce(c[0], cb.AVG, comm=comm)[None]

    out = session.spmd(f, in_specs=(session.shard(),),
                       out_specs=session.replicate())(contribs[:, None])
    np.testing.assert_allclose(np.asarray(out)[0], 2.0, atol=0.05)


# -- error feedback ----------------------------------------------------------

def test_error_feedback_averages_out_quantization_error(session, rng):
    """The EF property: repeating a quantized allreduce of the SAME input
    with the residual carried makes the time-average of the outputs
    converge to the true sum — without EF the bias persists every round."""
    comm = quantize.CommConfig(quant="int8", block=16)
    x = (10.0 * rng.standard_normal((W, 33))).astype(np.float32)

    def ef_loop(c):
        xl = c[0]

        def body(carry, _):
            res, acc = carry
            out, res = lax_ops.allreduce(xl, cb.SUM, comm=comm, residual=res)
            return (res, acc + out), None

        (_, acc), _ = jax.lax.scan(
            body, (jnp.zeros_like(xl), jnp.zeros_like(xl)), None, length=40)
        return (acc / 40)[None]

    out = session.spmd(ef_loop, in_specs=(session.shard(),),
                       out_specs=session.replicate())(x[:, None])

    def single(c):
        return lax_ops.allreduce(c[0], cb.SUM, comm=comm)[None]

    one = session.spmd(single, in_specs=(session.shard(),),
                       out_specs=session.replicate())(x[:, None])
    ref = x.sum(0)
    err_avg = np.abs(np.asarray(out)[0] - ref).max()
    err_one = np.abs(np.asarray(one)[0] - ref).max()
    assert err_avg < err_one / 3, (err_avg, err_one)


def test_f32_path_with_residual_is_exact_and_uniform(session, rng):
    # comm=None + residual: call sites stay uniform, math stays exact
    x = rng.normal(size=(W, 5)).astype(np.float32)

    def f(c):
        out, res = lax_ops.allreduce(c[0], cb.SUM, residual=jnp.zeros_like(
            c[0]))
        return (out + 0 * res)[None]

    out = session.spmd(f, in_specs=(session.shard(),),
                       out_specs=session.replicate())(x[:, None])
    np.testing.assert_allclose(np.asarray(out).reshape(-1), x.sum(0),
                               rtol=1e-6)


def test_quantized_rotate_scan_returns_blocks_near_home(session, rng):
    comm = quantize.CommConfig(quant="int8")
    blocks = rng.normal(size=(W, 6)).astype(np.float32)

    def body(c, blk, t):
        return c, blk

    def f(b):
        _, out = rotation.rotate_scan(body, jnp.zeros(()), b, W, comm=comm)
        return out

    out = session.spmd(f, in_specs=(session.shard(),),
                       out_specs=session.shard())(blocks)
    # W lossy hops; EF bounds the drift to a few quantization steps
    assert np.abs(np.asarray(out) - blocks).max() < 0.1


def test_quantized_rotation_passes_integer_leaves_exact(session, rng):
    comm = quantize.CommConfig(quant="int8")
    ids = np.arange(W, dtype=np.int32).reshape(W, 1)
    vals = rng.normal(size=(W, 3)).astype(np.float32)

    def body(c, blk, t):
        return c, blk

    def f(i, v):
        _, (oi, ov) = rotation.rotate_scan(body, jnp.zeros(()), (i, v), W,
                                           comm=comm)
        return oi, ov

    oi, ov = session.spmd(f, in_specs=(session.shard(), session.shard()),
                          out_specs=(session.shard(), session.shard()))(
        ids, vals)
    np.testing.assert_array_equal(np.asarray(oi), ids)  # ints: bit-exact
    assert np.abs(np.asarray(ov) - vals).max() < 0.1


# -- link-class topology hints ----------------------------------------------

def test_chunks_for_link():
    assert rotation.chunks_for_link(10 << 20, "ici") == 1
    assert rotation.chunks_for_link(100, "dcn") == 1
    assert rotation.chunks_for_link(3 << 20, "dcn") == 3
    assert rotation.chunks_for_link(1 << 30, "dcn") == rotation.MAX_DCN_CHUNKS


def test_axis_link_class_registry():
    assert mesh_lib.axis_link_class("workers") == "ici"
    mesh_lib.set_axis_link_class("workers", "dcn")
    try:
        assert mesh_lib.axis_link_class("workers") == "dcn"
    finally:
        mesh_lib.set_axis_link_class("workers", "ici")
    with pytest.raises(ValueError, match="link_class"):
        mesh_lib.set_axis_link_class("workers", "ethernet")


def test_chunked_rotate_matches_monolithic(session, rng):
    x = rng.normal(size=(W, 24)).astype(np.float32)

    def f(b):
        return lax_ops.rotate(b, 1, num_chunks=3)

    out = session.spmd(f, in_specs=(session.shard(),),
                       out_specs=session.shard())(x)
    np.testing.assert_array_equal(np.asarray(out), np.roll(x, 1, axis=0))


def test_dcn_link_class_chunks_the_rotation_hop(session):
    """A DCN-hinted axis splits rotate_scan's hop into multiple ppermutes
    (traced, not executed — the jaxpr is the contract)."""
    rows = (3 * rotation.DCN_CHUNK_BYTES) // 4 // 16  # ~3 MiB of f32

    def body(c, blk, t):
        return c, blk

    def run(link):
        def f(b):
            _, out = rotation.rotate_scan(body, jnp.zeros(()), b, 1,
                                          link_class=link)
            return out
        prog = session.spmd(f, in_specs=(session.shard(),),
                            out_specs=session.shard())
        text = str(jax.make_jaxpr(prog)(
            jnp.zeros((W * rows, 16), jnp.float32)))
        return text.count("ppermute")

    assert run("ici") == 1
    assert run("dcn") == 3


# -- rotate_map bijection validation (satellite fix) -------------------------

def test_rotate_map_valid_bijection_still_works(session, rng):
    x = rng.normal(size=(W, 3)).astype(np.float32)
    mapping = {i: (i + 3) % W for i in range(W)}

    def f(b):
        return lax_ops.rotate_map(b, mapping)

    out = session.spmd(f, in_specs=(session.shard(),),
                       out_specs=session.shard())(x)
    np.testing.assert_array_equal(np.asarray(out), np.roll(x, 3, axis=0))


@pytest.mark.parametrize("mapping,hint", [
    ({0: 1, 1: 0}, "sources missing"),              # partial map
    ({i: 0 for i in range(W)}, "destinations missing"),  # many-to-one
    ({i: i + 1 for i in range(W)}, "out-of-range"),  # dest W is not a worker
])
def test_rotate_map_rejects_non_bijections(session, mapping, hint):
    def f(b):
        return lax_ops.rotate_map(b, mapping)

    with pytest.raises(ValueError, match=hint):
        session.spmd(f, in_specs=(session.shard(),),
                     out_specs=session.shard())(np.ones((W, 2), np.float32))


# -- convergence parity: every quantized model path vs f32 -------------------

@pytest.mark.parametrize("variant", ["allreduce", "regroupallgather",
                                     "pushpull", "rotation"])
def test_kmeans_quantized_parity_full_trajectory(session, rng, variant):
    from harp_tpu.io import datagen
    from harp_tpu.models import kmeans as km

    # well-separated clusters: near-tie assignments (which a lossy wire is
    # ALLOWED to flip — same epsilon class as the documented lane_pad /
    # bf16 flips) would make max-abs centroid comparison meaningless noise
    pts = datagen.dense_points(64, 16, seed=12, num_clusters=8)
    cen0 = datagen.initial_centroids(pts, 8, seed=13)
    base = km.KMeans(session, km.KMeansConfig(8, 16, iterations=5,
                                              comm=variant))
    c0, cost0 = base.fit(pts, cen0)
    c0, cost0 = np.asarray(c0), np.asarray(cost0)
    for codec, cen_tol, cost_tol in (("int8", 0.2, 1e-2),
                                     ("bf16", 0.05, 1e-3)):
        m = km.KMeans(session, km.KMeansConfig(8, 16, iterations=5,
                                               comm=variant, quant=codec))
        c, cost = m.fit(pts, cen0)
        assert np.abs(np.asarray(c) - c0).max() < cen_tol, (variant, codec)
        # the whole COST TRAJECTORY stays within tolerance (2%: early
        # iterations see the largest relative wire error), and the
        # converged tail within the per-codec bound (int8's final cost
        # keeps ~0.6% of un-fed-back last-step error; bf16 ~0.02%)
        np.testing.assert_allclose(np.asarray(cost), cost0, rtol=2e-2)
        np.testing.assert_allclose(np.asarray(cost)[-1], cost0[-1],
                                   rtol=cost_tol)


def test_kmeans_rejects_quantized_bcastreduce(session):
    from harp_tpu.models import kmeans as km

    with pytest.raises(ValueError, match="bcastreduce"):
        km.KMeans(session, km.KMeansConfig(8, 16, comm="bcastreduce",
                                           quant="int8"))


@pytest.mark.parametrize("num_slices", [1, 2])
def test_sgd_mf_quantized_rotation_parity(session, rng, num_slices):
    from harp_tpu.models import sgd_mf

    n = 400
    rows = rng.integers(0, 64, size=n)
    cols = rng.integers(0, 48, size=n)
    vals = rng.normal(size=n).astype(np.float32)
    base = sgd_mf.SGDMF(session, sgd_mf.SGDMFConfig(
        rank=8, epochs=4, minibatches_per_hop=2, num_slices=num_slices))
    _, _, r0 = base.fit(rows, cols, vals, 64, 48)
    for codec in ("int8", "bf16"):
        m = sgd_mf.SGDMF(session, sgd_mf.SGDMFConfig(
            rank=8, epochs=4, minibatches_per_hop=2, num_slices=num_slices,
            quant=codec))
        _, _, r = m.fit(rows, cols, vals, 64, 48)
        # rmse trajectory parity: quantized H-blocks with EF track the f32
        # run to well under the rmse's own scale
        np.testing.assert_allclose(r, r0, atol=0.02)


def test_lda_quantized_allreduce_parity_cvb0(session, rng):
    """CVB0 is deterministic mean-field, so f32-vs-quantized differences
    are PURE wire quantization error — no CGS chain-divergence noise."""
    from harp_tpu.models import lda

    docs = rng.integers(0, 96, size=(16, 12))
    base = lda.LDA(session, lda.LDAConfig(num_topics=4, vocab=96, epochs=4,
                                          method="cvb0"))
    _, _, ll0 = base.fit(docs, seed=0)
    for codec in ("int8", "bf16"):
        m = lda.LDA(session, lda.LDAConfig(num_topics=4, vocab=96, epochs=4,
                                           method="cvb0", quant=codec))
        _, _, ll = m.fit(docs, seed=0)
        np.testing.assert_allclose(ll, ll0, rtol=1e-3)
