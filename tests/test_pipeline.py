"""Streaming ingestion engine (io/pipeline, ISSUE 18): bounded-queue
backpressure, chunk-order determinism, stream-fed K-means bitwise parity,
object-store part-files, and the distributed COO→CSR regroup against the
host-shuffle oracle."""

import os
import time

import numpy as np
import pytest


def _write_parts(tmp_path, sizes, d=6, seed=7):
    rng = np.random.default_rng(seed)
    blocks, paths = [], []
    for i, n in enumerate(sizes):
        block = rng.standard_normal((n, d)).astype(np.float32)
        path = tmp_path / f"part-{i:03d}"
        np.savetxt(path, block, fmt="%.6f", delimiter=",")
        # reparse so expectations carry the exact %.6f round-trip values
        blocks.append(np.loadtxt(path, delimiter=",",
                                 dtype=np.float32, ndmin=2))
        paths.append(str(path))
    return paths, np.concatenate(blocks)


# --------------------------------------------------------------------------- #
# Reader-pool backpressure (DynamicScheduler out_capacity)
# --------------------------------------------------------------------------- #


def test_scheduler_bounded_output_backpressures_and_delivers():
    from harp_tpu.sched.dynamic import DynamicScheduler, Task

    class _Echo(Task):
        def run(self, item):
            return item

    sched = DynamicScheduler([_Echo() for _ in range(4)], out_capacity=2)
    sched.start()
    try:
        sched.submit_all(range(32))
        time.sleep(0.3)
        # producers are instant: without the bound all 32 results would be
        # resident by now; the bounded queue holds the pool at <= capacity
        assert sched._out.maxsize == 2
        assert sched._out.qsize() <= 2
        got = sorted(sched.wait_for_output() for _ in range(32))
        assert got == list(range(32))
    finally:
        sched.stop()


def test_scheduler_stop_with_full_output_queue_does_not_deadlock():
    from harp_tpu.sched.dynamic import DynamicScheduler, Task

    class _Echo(Task):
        def run(self, item):
            return item

    sched = DynamicScheduler([_Echo() for _ in range(2)], out_capacity=1)
    sched.start()
    sched.submit_all(range(16))
    time.sleep(0.2)        # workers now blocked publishing into the bound
    t0 = time.perf_counter()
    sched.stop()           # must drain-and-join, not hang on the full queue
    assert time.perf_counter() - t0 < 10.0


def test_stream_loader_backpressure_bound(tmp_path):
    from harp_tpu.io import pipeline as pl

    paths, _ = _write_parts(tmp_path, [40] * 8)
    loader = pl.StreamLoader(paths, chunk_rows=16, num_threads=4,
                             queue_depth=2)
    it = iter(loader)
    next(it)
    time.sleep(0.3)        # consumer stalls; the pool may NOT run ahead
    assert loader._sched._out.qsize() <= 2
    for _ in it:           # drain: every row still arrives, in order
        pass


# --------------------------------------------------------------------------- #
# Chunk determinism + counting pass
# --------------------------------------------------------------------------- #


def test_chunk_stream_deterministic_across_thread_counts(tmp_path):
    from harp_tpu.io import pipeline as pl

    sizes = [37, 5, 64, 1, 23]          # ragged on purpose
    paths, whole = _write_parts(tmp_path, sizes)

    def snapshot(**kw):
        chunks = list(pl.StreamLoader(paths, chunk_rows=32, **kw))
        return [(c.index, c.offset, c.rows, c.data.copy()) for c in chunks]

    ref = snapshot(serial=True)
    for kw in ({"num_threads": 1}, {"num_threads": 4},
               {"num_threads": 4, "queue_depth": 1}):
        got = snapshot(**kw)
        assert [(g[0], g[1], g[2]) for g in got] == \
            [(r[0], r[1], r[2]) for r in ref]
        for g, r in zip(got, ref):
            np.testing.assert_array_equal(g[3], r[3])
    # fixed budget shape everywhere, zero-padded tail, exact coverage
    total = sum(sizes)
    assert all(r[3].shape == (32, whole.shape[1]) for r in ref)
    assert sum(r[2] for r in ref) == total
    flat = np.concatenate([r[3][:r[2]] for r in ref])
    np.testing.assert_array_equal(flat, whole)
    tail = ref[-1]
    assert not tail[3][tail[2]:].any()          # tail padding is zeros


def test_count_pass_totals(tmp_path):
    from harp_tpu.io import native_bridge, pipeline as pl

    if not native_bridge.available():
        pytest.skip("native parser not built")
    paths, whole = _write_parts(tmp_path, [10, 3, 9])
    loader = pl.StreamLoader(paths, chunk_rows=8)
    assert loader.total_rows == len(whole)
    assert loader.num_cols == whole.shape[1]
    assert loader.metrics.timing("ingest.count")["count"] == 1


def test_stream_over_memory_urls():
    """Object-store part-files ride the same pool (fsspec read timed as
    ingest.read, no native fast path, no counting pass)."""
    import fsspec

    from harp_tpu.io import loaders, pipeline as pl

    fs = fsspec.filesystem("memory")
    rng = np.random.default_rng(11)
    blocks = []
    try:
        for i in range(3):
            block = rng.standard_normal((12, 4)).astype(np.float32)
            blocks.append(block)
            with fsspec.open(f"memory://harp_pl_test/part-{i:02d}", "w") as f:
                for row in block:
                    f.write(",".join(f"{v:.6f}" for v in row) + "\n")
        paths = loaders.list_files("memory://harp_pl_test/")
        loader = pl.StreamLoader(paths, chunk_rows=10, num_threads=2)
        assert loader.total_rows is None        # no native count over URLs
        chunks = list(loader)
        flat = np.concatenate([c.data[:c.rows] for c in chunks])
        np.testing.assert_allclose(flat, np.concatenate(blocks), atol=1e-5)
        assert loader.metrics.timing("ingest.read")["count"] == 3
    finally:
        fs.rm("/harp_pl_test", recursive=True)


# --------------------------------------------------------------------------- #
# Stream-fed K-means: bitwise parity with the in-memory fit
# --------------------------------------------------------------------------- #


def test_fit_from_stream_bitwise_equals_in_memory(session, tmp_path):
    from harp_tpu.io import loaders, pipeline as pl
    from harp_tpu.models import kmeans as km

    paths, whole = _write_parts(tmp_path, [50, 17, 30], d=5)
    pts = loaders.truncate_to_workers(whole, session.num_workers)
    cen0 = whole[:4].copy()
    model = km.KMeans(session, km.KMeansConfig(
        num_centroids=4, dim=5, iterations=3))
    ref_cen, ref_costs = model.fit(pts, cen0)

    for wrap in (lambda ld: ld,
                 lambda ld: pl.DevicePrefetcher(ld, session.replicate_put)):
        loader = pl.StreamLoader(paths, chunk_rows=24, num_threads=3)
        cen, costs = model.fit_from_stream(wrap(loader), cen0, len(pts))
        np.testing.assert_array_equal(np.asarray(cen), np.asarray(ref_cen))
        np.testing.assert_array_equal(np.asarray(costs),
                                      np.asarray(ref_costs))


def test_fit_stream_minibatch_converges(session, tmp_path):
    from harp_tpu.io import pipeline as pl
    from harp_tpu.models import kmeans as km

    paths, whole = _write_parts(tmp_path, [64, 64], d=4, seed=3)
    model = km.KMeans(session, km.KMeansConfig(
        num_centroids=3, dim=4, iterations=1))
    cen, costs = model.fit_stream_minibatch(
        pl.StreamLoader(paths, chunk_rows=32), whole[:3])
    assert cen.shape == (3, 4) and np.isfinite(cen).all()
    assert costs.shape == (4,) and np.isfinite(costs).all()


def test_prefetcher_propagates_producer_error(session):
    from harp_tpu.io import pipeline as pl

    def boom():
        yield pl.Chunk(0, 0, 4, np.zeros((4, 2), np.float32), 32)
        raise RuntimeError("parse exploded")

    pre = pl.DevicePrefetcher(boom(), session.replicate_put)
    next(pre)
    with pytest.raises(RuntimeError, match="parse exploded"):
        for _ in pre:
            pass


def test_assemble_stream_validates_shape(session):
    from harp_tpu.io import pipeline as pl

    with pytest.raises(ValueError, match="multiple"):
        pl.assemble_stream(session, [], session.num_workers + 1, 8)


# --------------------------------------------------------------------------- #
# Distributed COO -> CSR
# --------------------------------------------------------------------------- #


def test_pack_unpack_coo_roundtrip(rng):
    from harp_tpu.io import pipeline as pl

    rows = rng.integers(0, 2 ** 40, 100)
    cols = rng.integers(0, 2 ** 40, 100)
    vals = rng.standard_normal(100).astype(np.float32)
    r, c, v = pl.unpack_coo(pl.pack_coo(rows, cols, vals))
    np.testing.assert_array_equal(r, rows)
    np.testing.assert_array_equal(c, cols)
    np.testing.assert_array_equal(v, vals)


def test_regroup_coo_device_matches_host_oracle(session, rng):
    from harp_tpu.io import pipeline as pl

    w = session.num_workers
    num_rows, nnz = 101, 4000           # ragged last block on purpose
    rows = rng.integers(0, num_rows, nnz).astype(np.int64)
    cols = rng.integers(0, 57, nnz).astype(np.int64)
    vals = rng.standard_normal(nnz).astype(np.float32)
    got = pl.regroup_coo_device(session, rows, cols, vals,
                                num_rows=num_rows)
    block = -(-num_rows // w)
    owner = np.minimum(rows // block, w - 1)
    assert len(got) == w
    for wi in range(w):
        m = owner == wi                 # host oracle: same order, nnz for nnz
        np.testing.assert_array_equal(got[wi][0], rows[m])
        np.testing.assert_array_equal(got[wi][1], cols[m])
        np.testing.assert_array_equal(got[wi][2], vals[m])


def test_regroup_coo_device_empty(session):
    from harp_tpu.io import pipeline as pl

    got = pl.regroup_coo_device(
        session, np.empty(0, np.int64), np.empty(0, np.int64),
        np.empty(0, np.float32))
    assert len(got) == session.num_workers
    assert all(len(r) == 0 for r, _, _ in got)


def test_coo_to_csr_distributed_matches_per_block_oracle(session, rng):
    from harp_tpu.io import loaders, pipeline as pl

    w = session.num_workers
    num_rows, nnz = 96, 3000
    rows = rng.integers(0, num_rows, nnz).astype(np.int64)
    cols = rng.integers(0, 33, nnz).astype(np.int64)
    vals = rng.standard_normal(nnz).astype(np.float32)
    got = pl.coo_to_csr_distributed(session, rows, cols, vals,
                                    num_rows=num_rows)
    block = num_rows // w
    for wi in range(w):
        m = (rows >= wi * block) & (rows < (wi + 1) * block)
        ip, ix, v = loaders.coo_to_csr(rows[m] - wi * block, cols[m],
                                       vals[m], num_rows=block)
        np.testing.assert_array_equal(got[wi][0], ip)
        np.testing.assert_array_equal(got[wi][1], ix)
        np.testing.assert_array_equal(got[wi][2], v)


def test_coo_to_csr_numpy_fallback_uses_bincount(monkeypatch):
    from harp_tpu.io import loaders, native_bridge

    rows = np.array([3, 0, 3, 1, 0], np.int64)
    cols = np.array([1, 2, 0, 4, 3], np.int64)
    vals = np.array([1, 2, 3, 4, 5], np.float32)
    expect = loaders.coo_to_csr(rows, cols, vals, num_rows=5)
    monkeypatch.setattr(native_bridge, "coo_to_csr",
                        lambda *a, **k: None)
    ip, ix, v = loaders.coo_to_csr(rows, cols, vals, num_rows=5)
    np.testing.assert_array_equal(ip, expect[0])
    np.testing.assert_array_equal(ix, expect[1])
    np.testing.assert_array_equal(v, expect[2])
    assert ip.tolist() == [0, 2, 3, 3, 5, 5]
    assert ix.tolist() == [2, 3, 4, 1, 0]      # stable within each row


# --------------------------------------------------------------------------- #
# Budget manifest: the pinned regroup schedule must stay bounded
# --------------------------------------------------------------------------- #


def test_ingest_regroup_budget_drift_is_loud():
    """JL203 teeth for the new target: the regroup silently degrading to a
    full-gather-sized transfer (same collective counts, 4x the bytes) must
    fail the budget check even though JL201 sees no count drift."""
    import json

    from tools.jaxlint import checkers_jaxpr

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, checkers_jaxpr.BUDGET_FILE)) as f:
        manifest = json.load(f)
    row = manifest["targets"]["ingest_coo_regroup"]
    assert row["bytes_per_step"] == 480     # 8 peers x 3 records x 20 B
    counts = dict(row["collectives"])
    widened = {k: 4 * v for k, v in row["bytes_by_kind"].items()}
    findings = checkers_jaxpr.check_budget(
        repo, {"ingest_coo_regroup": (counts, [], widened)})
    mine = [f for f in findings if f.func == "ingest_coo_regroup"]
    assert not any(f.code == "JL201" for f in mine)
    hits = [f for f in mine if f.code == "JL203"]
    assert hits and "byte-budget drift" in hits[0].message
    clean = {"ingest_coo_regroup": (counts, [], dict(row["bytes_by_kind"]))}
    assert not any(f.func == "ingest_coo_regroup"
                   for f in checkers_jaxpr.check_budget(repo, clean))


def test_bench_ingest_row_schema():
    """The committed --only ingest row carries the acceptance fields (run
    when BENCH_local.json has the group — tier-1 asserts schema, not
    numbers)."""
    import json

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "BENCH_local.json")
    if not os.path.exists(path):
        pytest.skip("no committed bench record")
    with open(path) as f:
        detail = json.load(f)
    row = detail.get("ingest")
    if not isinstance(row, dict) or "error" in row:
        pytest.skip("no committed ingest row")
    for key in ("stream_load_mb_per_sec", "serialized_wall_s",
                "overlapped_wall_s", "overlap_efficiency", "overlap_gate",
                "overlap_note", "e2e_stream_fit_wall_s", "stages",
                "regroup"):
        assert key in row, key
    assert row["overlap_gate"] in ("on", "skipped")
    if row["overlap_gate"] == "skipped":
        assert row["overlap_pass"] is None
    else:
        assert isinstance(row["overlap_pass"], bool)
    assert {"nnz", "wall_s", "wire_bytes", "rounds"} <= set(row["regroup"])
    assert row["stages"].get("parse") or row["stages"].get("read")
