"""Dense analytics suite vs numpy references (daal_cov/pca/mom/qr/svd/... parity)."""

import numpy as np
import pytest

from harp_tpu.models import stats


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(17)
    x = rng.standard_normal((160, 12)).astype(np.float32)
    x = x @ rng.standard_normal((12, 12)).astype(np.float32)  # correlated cols
    return x


def test_covariance(session, data):
    cov, mean = stats.Covariance(session).compute(data)
    np.testing.assert_allclose(mean, data.mean(0), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(cov, np.cov(data, rowvar=False), rtol=1e-3,
                               atol=1e-3)


def test_moments(session, data):
    m = stats.LowOrderMoments(session).compute(data)
    assert m.count == data.shape[0]
    np.testing.assert_allclose(m.mean, data.mean(0), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(m.variance, data.var(0, ddof=1), rtol=1e-3,
                               atol=1e-3)
    np.testing.assert_allclose(m.minimum, data.min(0), rtol=1e-6)
    np.testing.assert_allclose(m.maximum, data.max(0), rtol=1e-6)


def test_pca_matches_numpy_eigh(session, data):
    w, comps, mean = stats.PCA(session).fit(data)
    corr = np.corrcoef(data, rowvar=False)
    w_ref = np.sort(np.linalg.eigvalsh(corr))[::-1]
    np.testing.assert_allclose(w, w_ref, rtol=1e-3, atol=1e-3)
    # components are orthonormal rows
    np.testing.assert_allclose(comps @ comps.T, np.eye(comps.shape[0]),
                               atol=1e-3)


def test_pca_fit_repeated_matches_fit(session, data):
    # the bench path: N fits inside one compiled program (lax.scan) must
    # produce exactly the same result as one host-level fit call
    model = stats.PCA(session)
    w1, c1, m1 = model.fit(data)
    w2, c2, m2 = model.fit_repeated(data, 3)
    np.testing.assert_allclose(w1, w2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(m1, m2, rtol=1e-5, atol=1e-5)
    # eigenvector sign is arbitrary per column; compare up to sign
    np.testing.assert_allclose(np.abs(c1), np.abs(c2), rtol=1e-3, atol=1e-3)


def test_zscore_and_minmax(session, data):
    z = stats.ZScore(session).transform(data)
    np.testing.assert_allclose(z.mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(z.std(0, ddof=1), 1.0, atol=1e-3)
    mm = stats.MinMax(session, 0.0, 1.0).transform(data)
    np.testing.assert_allclose(mm.min(0), 0.0, atol=1e-6)
    np.testing.assert_allclose(mm.max(0), 1.0, atol=1e-6)


def test_qr_reconstructs(session, data):
    q, r = stats.QR(session).compute(data)
    np.testing.assert_allclose(q @ r, data, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(q.T @ q, np.eye(q.shape[1]), atol=1e-3)
    assert np.all(np.diag(r) >= 0)   # sign-normalized
    assert np.allclose(r, np.triu(r), atol=1e-5)


def test_svd_matches_numpy(session, data):
    u, s, vt = stats.SVD(session).compute(data)
    s_ref = np.linalg.svd(data, compute_uv=False)
    np.testing.assert_allclose(s, s_ref, rtol=1e-3)
    np.testing.assert_allclose(u @ np.diag(s) @ vt, data, rtol=1e-3, atol=1e-3)


def test_cholesky(session, data):
    l = stats.Cholesky(session).compute(data)
    np.testing.assert_allclose(l @ l.T, data.T @ data, rtol=1e-2, atol=1e-1)


def test_quantiles_and_sort(session, data):
    # includes the extremes: q=0 reads worker 0's first row, q=1 the last
    # worker's last row — the owner-boundary cases of the distributed
    # order-statistic pick
    qs = [0.0, 0.1, 0.5, 0.9, 1.0]
    q = stats.Quantiles(session).compute(data, qs)
    np.testing.assert_allclose(q, np.quantile(data, qs, axis=0), rtol=1e-4,
                               atol=1e-4)
    # the distributed odd-even block sort assembles to the full column sort
    s = stats.Sorting(session).compute(data)
    np.testing.assert_allclose(s, np.sort(data, axis=0), rtol=1e-6)


def test_outliers(session):
    rng = np.random.default_rng(3)
    x = rng.standard_normal((120, 4)).astype(np.float32)
    x[5] = 40.0   # blatant outlier
    flags = stats.OutlierDetection(session, threshold=4.0).compute(x)
    assert flags[5] == 1
    assert flags.sum() <= 3


def test_kernel_functions(session):
    import jax.numpy as jnp
    from harp_tpu.ops import kernels
    rng = np.random.default_rng(1)
    x = rng.standard_normal((10, 4)).astype(np.float32)
    z = rng.standard_normal((6, 4)).astype(np.float32)
    lin = np.asarray(kernels.linear_kernel(jnp.asarray(x), jnp.asarray(z)))
    np.testing.assert_allclose(lin, x @ z.T, rtol=1e-5)
    rbf = np.asarray(kernels.rbf_kernel(jnp.asarray(x), jnp.asarray(z), 2.0))
    d = ((x[:, None] - z[None]) ** 2).sum(-1)
    np.testing.assert_allclose(rbf, np.exp(-d / 8.0), rtol=1e-4, atol=1e-5)
    poly = np.asarray(kernels.polynomial_kernel(jnp.asarray(x), jnp.asarray(z),
                                                1.0, 1.0, 2))
    np.testing.assert_allclose(poly, (x @ z.T + 1.0) ** 2, rtol=1e-4)


def test_knn_k_guard(session):
    from harp_tpu.models import knn as knn_mod
    x = np.zeros((16, 3), np.float32)
    y = np.zeros((16,), np.int32)
    model = knn_mod.KNNClassifier(session, k=5)
    try:
        model.fit(x, y)
        raise AssertionError("expected ValueError")
    except ValueError as e:
        assert "rows per worker" in str(e)


# --------------------------------------------------------------------------- #
# CSR analytics variants (daal_kmeans/allreducecsr, daal_cov/csrdistri,
# daal_pca/corcsrdistr) + PCA method="svd" (daal_pca/svddensedistr)
# --------------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def sparse_coo():
    """A sparsified dataset: ~10% density, 192 rows x 24 cols — generated by
    the SAME helper the CLI uses (io.datagen.sparse_points)."""
    from harp_tpu.io import datagen

    n, d = 192, 24
    rows, cols, vals = datagen.sparse_points(n, d, 0.1, seed=23)
    dense = np.zeros((n, d), np.float32)
    dense[rows, cols] = vals
    return rows, cols, vals, dense


def test_sparse_kmeans_matches_dense(session):
    """Well-separated sparse clusters (disjoint column groups): the sparse
    and dense E-steps must produce the same trajectory. Random near-tied
    data would flip argmins on summation-order noise — separation makes the
    comparison meaningful."""
    from harp_tpu.models import kmeans as km
    from harp_tpu.models import sparse

    rng = np.random.default_rng(3)
    n, d, k, gcols = 192, 24, 4, 6
    dense = np.zeros((n, d), np.float32)
    rows_l, cols_l, vals_l = [], [], []
    for i in range(n):
        g = i % k
        cset = g * gcols + rng.choice(gcols, 3, replace=False)
        v = (5.0 + 0.5 * rng.standard_normal(3)).astype(np.float32)
        dense[i, cset] = v
        rows_l += [i] * 3
        cols_l += cset.tolist()
        vals_l += v.tolist()
    rows = np.asarray(rows_l, np.int64)
    cols = np.asarray(cols_l, np.int64)
    vals = np.asarray(vals_l, np.float32)
    cen0 = dense[:k].copy()
    dcfg = km.KMeansConfig(num_centroids=k, dim=d, iterations=6,
                           comm="allreduce")
    dcen, dcost = km.KMeans(session, dcfg).fit(dense, cen0)
    scfg = sparse.SparseKMeansConfig(num_centroids=k, dim=d, iterations=6)
    scen, scost = sparse.SparseKMeans(session, scfg).fit(
        rows, cols, vals, n, cen0)
    np.testing.assert_allclose(scen, np.asarray(dcen), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(scost, np.asarray(dcost), rtol=1e-4)


def test_sparse_kmeans_phantom_row_padding(session):
    """A row count NOT divisible by the workers: internal phantom rows must
    not perturb counts or cost (numpy-oracle comparison on separated
    clusters, 189 % 8 != 0)."""
    from harp_tpu.models import sparse
    from harp_tpu.models.kmeans import numpy_reference

    rng = np.random.default_rng(9)
    n, d, k = 189, 16, 3
    dense = np.zeros((n, d), np.float32)
    rows_l, cols_l, vals_l = [], [], []
    for i in range(n):
        g = i % k
        c = g * 5 + rng.choice(5, 2, replace=False)
        v = (4.0 + 0.3 * rng.standard_normal(2)).astype(np.float32)
        dense[i, c] = v
        rows_l += [i, i]
        cols_l += c.tolist()
        vals_l += v.tolist()
    cen0 = dense[:k].copy()
    scfg = sparse.SparseKMeansConfig(num_centroids=k, dim=d, iterations=4)
    scen, _ = sparse.SparseKMeans(session, scfg).fit(
        np.asarray(rows_l, np.int64), np.asarray(cols_l, np.int64),
        np.asarray(vals_l, np.float32), n, cen0)
    ref = numpy_reference(dense, cen0.copy(), 4)
    np.testing.assert_allclose(scen, ref, rtol=1e-3, atol=1e-3)


def test_csr_covariance_and_pca_match_dense(session, sparse_coo):
    from harp_tpu.models import sparse

    rows, cols, vals, dense = sparse_coo
    n, d = dense.shape
    cov, mean = sparse.CSRCovariance(session).compute(rows, cols, vals, n, d)
    np.testing.assert_allclose(mean, dense.mean(0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(cov, np.cov(dense, rowvar=False), rtol=1e-3,
                               atol=1e-4)
    w, comps, _ = sparse.CSRPCA(session).fit(rows, cols, vals, n, d)
    wd, compsd, _ = stats.PCA(session).fit(dense)
    np.testing.assert_allclose(w, wd, rtol=1e-3, atol=1e-4)
    # eigenvectors match up to sign
    dots = np.abs(np.sum(comps * compsd, axis=1))
    np.testing.assert_allclose(dots[:5], 1.0, atol=1e-2)


def test_pca_svd_method_matches_correlation(session, data):
    """daal_pca/svddensedistr parity: the svd method's eigenvalues equal the
    correlation method's (z-score + TSQR-SVD route)."""
    w_cor, comps_cor, mean_cor = stats.PCA(session, method="cor").fit(data)
    w_svd, comps_svd, mean_svd = stats.PCA(session, method="svd").fit(data)
    np.testing.assert_allclose(w_svd, w_cor, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(mean_svd, mean_cor, rtol=1e-5, atol=1e-5)
    dots = np.abs(np.sum(comps_svd * comps_cor, axis=1))
    np.testing.assert_allclose(dots[:6], 1.0, atol=1e-2)
    with pytest.raises(ValueError):
        stats.PCA(session, method="eig")


def test_sparse_kmeans_strategies_agree(session, sparse_coo):
    """densify (MXU tiles) and gather (nnz-proportional) E-steps produce the
    same stats on the same shard — one iteration, no argmin compounding."""
    import jax.numpy as jnp

    from harp_tpu.models import sparse

    rows, cols, vals, dense = sparse_coo
    n, d = dense.shape
    idx, val, mask, real = sparse.csr_worker_layout(rows, cols, vals, n, 1)
    x_sq = (val * val * mask).sum(axis=1).astype(np.float32)
    cen = dense[:5].copy() + 0.01
    out = {}
    for strat in ("densify", "gather"):
        stats, cost = sparse.sparse_kmeans_stats(
            jnp.asarray(idx), jnp.asarray(val), jnp.asarray(mask),
            jnp.asarray(real), jnp.asarray(x_sq), jnp.asarray(cen), strat)
        out[strat] = (np.asarray(stats), float(cost))
    np.testing.assert_allclose(out["densify"][0], out["gather"][0],
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(out["densify"][1], out["gather"][1],
                               rtol=1e-4)
    with pytest.raises(ValueError):
        sparse.sparse_kmeans_stats(
            jnp.asarray(idx), jnp.asarray(val), jnp.asarray(mask),
            jnp.asarray(real), jnp.asarray(x_sq), jnp.asarray(cen), "csr")
