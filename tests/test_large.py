"""Larger-scale tests (marked ``large``) — behavior at sizes where padding,
memory, and collective-layout decisions matter, not just math (VERDICT r1:
"toy-scale tests verify math, not behavior at size").

These run in the default suite (~1 min total on the 8-worker CPU mesh); use
``-m "not large"`` to skip them for a quick loop.
"""

import numpy as np
import pytest

from harp_tpu.io import datagen

pytestmark = pytest.mark.large


def test_sgd_mf_sparse_zipf_at_scale(session):
    """~350k Zipf ratings, sparse layout: padding bound holds and training
    moves at a scale where a bad layout would OOM-blow the buckets."""
    from harp_tpu.models import sgd_mf

    rows, cols, vals = datagen.zipf_ratings(
        num_users=8192, num_items=8192, rank=8, alpha=1.15, density=0.005,
        seed=1, noise=0.01)
    assert len(vals) > 250_000
    cfg = sgd_mf.SGDMFConfig(rank=16, lam=0.01, lr=0.05, epochs=2,
                             minibatches_per_hop=4, layout="sparse")
    model = sgd_mf.SGDMF(session, cfg)
    state = model.prepare(rows, cols, vals, 8192, 8192)
    assert model.last_layout_stats["overhead"] <= 4.0
    w, h, rmse = model.fit_prepared(state)
    assert np.isfinite(rmse).all() and rmse[-1] < rmse[0]


def test_als_zipf_at_scale(session):
    from harp_tpu.models import als

    rows, cols, vals = datagen.zipf_ratings(
        num_users=4096, num_items=4096, rank=8, alpha=1.2, density=0.01,
        seed=2, noise=0.01)
    cfg = als.ALSConfig(rank=16, lam=0.05, iterations=3, implicit=False,
                        layout="sparse")     # this test is ABOUT the chunks
    model = als.ALS(session, cfg)
    u, v, rmse = model.fit(rows, cols, vals, 4096, 4096)
    assert model.last_layout_stats["overhead"] <= 4.0
    assert rmse[-1] < rmse[0]


def test_lda_at_scale(session):
    """512 docs x 128 tokens, vocab 2048: block padding stays bounded and the
    reference likelihood improves."""
    from harp_tpu.models import lda

    rng = np.random.default_rng(3)
    v = 2048
    p = np.arange(1, v + 1, dtype=np.float64) ** -1.1
    docs = rng.choice(v, size=(512, 128), p=p / p.sum()).astype(np.int32)
    cfg = lda.LDAConfig(num_topics=16, vocab=v, alpha=0.1, beta=0.01,
                        epochs=2)
    model = lda.LDA(session, cfg)
    _, wt, ll = model.fit(docs, seed=0)
    assert model.last_layout_stats["overhead"] <= 4.0
    assert np.isfinite(ll).all() and ll[-1] > ll[0]
    host = lda.reference_log_likelihood(wt, cfg.beta, cfg.vocab)
    np.testing.assert_allclose(ll[-1], host, rtol=1e-3)


def test_group_by_key_sharded_100k_records(session, rng):
    """1e5 records through the owner-partitioned shuffle: O(N/W) buckets
    suffice and the combined result matches a host reduction."""
    from harp_tpu import combiner as cb
    from harp_tpu.collectives import table_ops

    n_local, num_keys = 12_800, 4096
    keys = rng.integers(0, num_keys, size=(8, n_local)).astype(np.int32)
    vals = rng.normal(size=(8, n_local)).astype(np.float32)

    def f(k, v):
        out, ovf = table_ops.group_by_key_sharded(
            k[0], v[0], num_keys=num_keys, combiner=cb.SUM,
            capacity=2 * n_local // 8 + 256)
        return out, ovf

    out, ovf = session.spmd(
        f, in_specs=(session.shard(), session.shard()),
        out_specs=(session.replicate(), session.replicate()))(keys, vals)
    assert int(ovf) == 0
    ref = np.zeros(num_keys, np.float32)
    np.add.at(ref, keys.reshape(-1), vals.reshape(-1))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)


def test_distributed_kv_20k_keys(session, rng):
    """20k distinct keys through DistributedKV: store capacity sizing and
    routed lookup at a size where per-worker fan-out matters."""
    import jax.numpy as jnp

    from harp_tpu import keyval as kv

    n_local = 8192
    keys = rng.integers(0, 20_000, size=(8, n_local)).astype(np.int32)
    vals = np.ones((8, n_local), np.float32)

    def prog(k, v):
        t = kv.DistributedKV(kv.kv_empty(4096, val_dtype=jnp.float32))
        t, r_ovf, s_ovf = t.update(k[0], v[0], route_cap=2 * n_local // 8 + 256)
        probe = jnp.arange(1000, dtype=jnp.int32)
        out, found = t.lookup(probe, route_cap=512)
        return out[None], found[None], r_ovf, s_ovf

    out, found, r_ovf, s_ovf = session.spmd(
        prog, in_specs=(session.shard(), session.shard()),
        out_specs=(session.shard(), session.shard(), session.replicate(),
                   session.replicate()))(keys, vals)
    assert int(r_ovf) == 0 and int(s_ovf) == 0
    counts = np.bincount(keys.reshape(-1), minlength=20_000)
    out, found = np.asarray(out), np.asarray(found)
    for q in range(0, 1000, 97):
        for w in range(8):
            if counts[q]:
                assert found[w, q] and out[w, q] == counts[q]
            else:
                assert not found[w, q]
