"""utils.tracing coverage (ISSUE 7 satellite — the module had zero tests).

CPU-backend smoke: the jax profiler runs fine on the virtual CPU mesh, so
trace capture, timeline annotation, and the device-memory profile are all
exercised for real (file artifacts asserted, not just "didn't raise")."""

import os

import jax.numpy as jnp

from harp_tpu.utils import tracing


def _files_under(root):
    return [os.path.join(r, f) for r, _, fs in os.walk(root) for f in fs]


def test_trace_produces_a_trace_directory(tmp_path):
    d = str(tmp_path / "trace")
    with tracing.trace(d):
        jnp.square(jnp.arange(128.0)).block_until_ready()
    found = _files_under(d)
    # the profiler writes plugins/profile/<ts>/*.xplane.pb (+ a trace json)
    assert found, f"no trace artifacts under {d}"
    assert any(f.endswith(".xplane.pb") for f in found), found


def test_trace_closes_on_exception(tmp_path):
    # the finally must stop the trace — a second capture would otherwise
    # die with "profiler already started"
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    try:
        with tracing.trace(d1):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    with tracing.trace(d2):
        jnp.ones(8).block_until_ready()
    assert _files_under(d2)


def test_split_start_stop_spans_host_boundaries(tmp_path):
    # the xprof-window form: open at one loop boundary, close at a later one
    d = str(tmp_path / "window")
    tracing.start_trace(d)
    for _ in range(3):
        jnp.sum(jnp.arange(32.0)).block_until_ready()
    tracing.stop_trace()
    assert _files_under(d)


def test_annotate_wraps_a_host_span(tmp_path):
    d = str(tmp_path / "trace")
    with tracing.trace(d):
        with tracing.annotate("harp-test-span"):
            jnp.sum(jnp.ones(16)).block_until_ready()
    assert _files_under(d)


def test_device_memory_profile_writes_a_file(tmp_path):
    p = str(tmp_path / "mem.pprof")
    jnp.ones(1024).block_until_ready()
    tracing.device_memory_profile(p)
    assert os.path.isfile(p) and os.path.getsize(p) > 0
