"""Reference-format dataset ingestion, end to end (VERDICT r2 #10).

The reference shipped per-algorithm datasets its launchers consumed
(/root/reference/datasets/): MovieLens-format COO ratings for daal_als/sgd
(``user item rating`` lines, one file per split — movielens-train/x*),
dense CSV row blocks for daal_kmeans (densedistri/kmeans_dense_*.csv,
HarpDAALDataSource.loadDenseCSV). These tests write synthetic fixtures in
those EXACT on-disk formats, then drive the full pipeline a reference user
would: split files → loaders (native mmap parser when built, numpy
fallback) → regroup → prepare → fit, asserting convergence.
"""

import os

import numpy as np
import pytest

from harp_tpu.io import datagen, loaders
from harp_tpu.models import kmeans as km
from harp_tpu.models import sgd_mf

W = 8


@pytest.fixture(scope="module")
def movielens_dir(tmp_path_factory):
    """A MovieLens-format ratings directory: 4 split files of
    ``user item rating`` lines (the reference's movielens-train/x00* shape),
    generated from a rank-4 ground-truth model so training can provably fit
    it."""
    root = tmp_path_factory.mktemp("movielens")
    rows, cols, vals = datagen.sparse_ratings(256, 192, rank=4,
                                              density=0.08, seed=11)
    order = np.random.default_rng(0).permutation(len(rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    splits = np.array_split(np.arange(len(rows)), 4)
    for i, idx in enumerate(splits):
        with open(os.path.join(root, f"x{i:05d}"), "w") as f:
            for r, c, v in zip(rows[idx], cols[idx], vals[idx]):
                f.write(f"{r} {c} {v:.6f}\n")
    return str(root), (rows, cols, vals)


def test_movielens_files_to_sgd_mf_convergence(session, movielens_dir):
    root, (rows0, cols0, vals0) = movielens_dir
    paths = sorted(os.path.join(root, p) for p in os.listdir(root))
    assert len(paths) == 4
    # reference flow: split files across workers, load each split, regroup
    per_worker = loaders.split_files(paths, 4)
    assert all(chunk for chunk in per_worker)
    rows, cols, vals = loaders.load_coo(paths)
    assert len(rows) == len(rows0)
    # loaded triples match what was written (order-insensitive)
    key = lambda r, c: np.asarray(r) * 192 + np.asarray(c)
    np.testing.assert_array_equal(np.sort(key(rows, cols)),
                                  np.sort(key(rows0, cols0)))
    groups = loaders.regroup_coo_by_row(rows, cols, vals, W)
    assert sum(len(g[0]) for g in groups) == len(rows)

    cfg = sgd_mf.SGDMFConfig(rank=8, lam=0.01, lr=0.1, epochs=30,
                             minibatches_per_hop=4)
    model = sgd_mf.SGDMF(session, cfg)
    _, _, rmse = model.fit(rows.astype(np.int64), cols.astype(np.int64),
                           vals.astype(np.float32), 256, 192, seed=0)
    assert rmse[-1] < 0.5 * rmse[0], rmse


def test_movielens_files_to_coo_csr(movielens_dir):
    root, _ = movielens_dir
    paths = sorted(os.path.join(root, p) for p in os.listdir(root))
    rows, cols, vals = loaders.load_coo(paths)
    indptr, indices, values = loaders.coo_to_csr(rows, cols, vals,
                                                 num_rows=256)
    assert indptr[-1] == len(rows)
    # CSR row slices hold exactly that row's entries
    r = int(rows[0])
    sl = slice(indptr[r], indptr[r + 1])
    assert (np.sort(indices[sl])
            == np.sort(cols[rows == r])).all()


def test_kmeans_dense_csv_blocks_to_fit(session, tmp_path):
    # densedistri format: one dense CSV per mapper (kmeans_dense_<i>.csv)
    pts = datagen.dense_points(512, 12, seed=4, num_clusters=5)
    paths = []
    for i, block in enumerate(np.array_split(pts, 4)):
        p = str(tmp_path / f"kmeans_dense_{i + 1}.csv")
        np.savetxt(p, block, delimiter=",", fmt="%.6f")
        paths.append(p)
    loaded = loaders.load_dense_csv(paths)
    np.testing.assert_allclose(loaded, pts, rtol=1e-5, atol=1e-5)
    cen0 = datagen.initial_centroids(loaded, 5, seed=1)
    model = km.KMeans(session, km.KMeansConfig(5, 12, iterations=10))
    _, costs = model.fit(loaded, cen0)
    costs = np.asarray(costs)
    assert costs[-1] < costs[0]


def test_shipped_fixture_datasets_load(session):
    """The committed datasets/ fixtures (reference parity:
    /root/reference/datasets ships per-algorithm canonical inputs) load
    through the same file flags the CLI exposes, and metadata files
    (_README — the Hadoop hidden-file convention) are skipped."""
    import harp_tpu

    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(harp_tpu.__file__))), "datasets")
    files = loaders.list_files(os.path.join(root, "kmeans"))
    assert len(files) == 4 and all("part-" in f for f in files)

    pts = loaders.load_dense_csv(files)
    assert pts.shape == (512, 16)

    docs = loaders.load_corpus(os.path.join(root, "lda"))
    assert docs.shape == (128, 32) and docs.min() >= 0

    x, y = loaders.load_labeled_csv(os.path.join(root, "svm"))
    assert x.shape == (256, 8) and set(np.unique(y)) == {0, 1}

    rows, cols, vals = loaders.load_coo(
        loaders.list_files(os.path.join(root, "sgd_mf")))
    assert len(rows) == len(cols) == len(vals) > 1000

    # a fixture-driven fit end to end (the CLI's --points-file path)
    cen0 = datagen.initial_centroids(pts, 8, seed=1)
    model = km.KMeans(session, km.KMeansConfig(8, 16, iterations=8))
    _, costs = model.fit(pts, cen0)
    costs = np.asarray(costs)
    assert costs[-1] < costs[0]
