"""Aux subsystems (checkpoint/metrics/events/failure), collective micro-bench,
pallas kernel (interpret mode), and sequence parallelism tests."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.benchmark import collectives as bench
from harp_tpu.ops import distance, pallas_kernels
from harp_tpu.parallel import events, failure, ring_attention
from harp_tpu.utils import checkpoint, metrics


def test_checkpointer_roundtrip(tmp_path):
    ck = checkpoint.Checkpointer(str(tmp_path), keep=2)
    state = {"w": np.arange(6.0).reshape(2, 3), "step": np.asarray(3)}
    for s in (1, 2, 3):
        ck.save(s, state)
    assert ck.steps() == [2, 3]            # keep=2 pruned step 1
    out = ck.restore_latest(like=state)
    np.testing.assert_allclose(np.asarray(out["w"]), state["w"])
    assert ck.latest_step() == 3


def test_checkpointer_async_save_roundtrip(tmp_path):
    """async_save overlaps the disk write; wait()/restore join it and the
    result is identical to a synchronous save. Resume via fit_checkpointed
    works across sync and async writers."""
    from harp_tpu.utils.checkpoint import Checkpointer

    state = {"w": np.arange(12.0).reshape(3, 4), "step": np.int32(7)}
    sync = Checkpointer(str(tmp_path / "sync"))
    sync.save(3, state)
    asy = Checkpointer(str(tmp_path / "async"), async_save=True)
    asy.save(3, state)
    asy.wait()
    got_s = sync.restore(3, like=state)
    got_a = asy.restore(3, like=state)
    np.testing.assert_array_equal(got_s["w"], got_a["w"])
    assert got_a["step"] == 7
    # steps() joins the in-flight write, so a save followed immediately by
    # steps() always sees the new checkpoint
    asy.save(4, state)
    assert asy.steps()[-1] == 4


def test_prune_spares_live_foreign_tmp_dir(tmp_path):
    """_prune must not delete a concurrently LIVE writer's tmp dir (ADVICE
    r5): a fresh foreign-pid ``*.tmp-*`` dir survives every prune; only one
    past the staleness threshold (a fail-stop orphan) is reaped."""
    ck = checkpoint.Checkpointer(str(tmp_path), keep=1, use_orbax=False)
    fresh = tmp_path / "step_000000000099.tmp-99999"   # foreign pid, live
    fresh.mkdir()
    (fresh / "payload.npz").write_bytes(b"in-flight")
    stale = tmp_path / "step_000000000098.tmp-88888"   # fail-stop orphan
    stale.mkdir()
    old = time.time() - 2 * checkpoint.STALE_TMP_SECONDS
    os.utime(stale, (old, old))
    state = {"a": np.ones(2)}
    ck.save(1, state)
    ck.save(2, state)                                  # both prune
    assert fresh.exists(), "live writer's tmp dir was deleted by prune"
    assert not stale.exists(), "stale orphan tmp dir survived prune"
    assert ck.steps() == [2]


def test_checkpointer_numpy_fallback(tmp_path):
    ck = checkpoint.Checkpointer(str(tmp_path), use_orbax=False)
    state = {"a": np.ones(4), "b": np.zeros((2, 2))}
    ck.save(7, state)
    out = ck.restore(7, like=state)
    np.testing.assert_allclose(out["a"], state["a"])
    assert ck.restore_latest(like=state) is not None


def test_metrics_registry():
    m = metrics.Metrics()
    m.count("iters", 3)
    m.gauge("loss", 0.5)
    with m.timer("phase"):
        time.sleep(0.01)
    snap = m.snapshot()
    assert snap["counters"]["iters"] == 3
    assert snap["gauges"]["loss"] == 0.5
    assert snap["timers"]["phase"]["count"] == 1
    assert snap["timers"]["phase"]["total_s"] >= 0.01
    m.log_summary()   # must not raise


def test_event_queue():
    q = events.EventQueue()
    client = events.EventClient(q, worker_id=0)
    client.send_local({"x": 1})
    client.send_collective("sync-point")
    client.send_message(0, "to-self")
    client.send_message(3, "dropped")     # single-process, not for us
    got = [q.get(), q.get(), q.get()]
    assert got[0].type is events.EventType.LOCAL
    assert got[1].type is events.EventType.COLLECTIVE
    assert got[2].payload == "to-self"
    assert q.get() is None
    assert q.wait(timeout=0.05) is None


def test_failure_watchdog():
    assert failure.probe_devices(timeout_s=30.0)
    with failure.Watchdog(interval_s=0.05, timeout_s=30.0) as wd:
        time.sleep(0.15)
        wd.ok()                            # healthy devices: no raise
    wd2 = failure.Watchdog()
    wd2.failed = True
    with pytest.raises(failure.WorkerFailure):
        wd2.ok()


def test_bench_collectives_smoke(session):
    results = bench.bench_collectives(session, sizes_kb=[4], loops=3,
                                      ops=["allreduce", "rotate"])
    assert len(results) == 2
    for r in results:
        assert r.seconds > 0 and r.us_per_op > 0
    table = bench.format_table(results)
    assert "allreduce" in table and "busbw GB/s" in table
    # renamed fields say what they mean (ADVICE r5): the PER-WORKER payload
    # (total array bytes / W) and NCCL-busbw bandwidth, with the convention
    # note available to ship inside emitted records
    w2 = session.num_workers ** 2
    rows = max(w2, (4 * 1024 // 4) // 128 // w2 * w2)
    assert results[0].payload_bytes_per_worker == \
        rows * 128 * 4 // session.num_workers
    assert results[0].busbw_gbps > 0
    assert "busbw" in bench.CONVENTION_NOTE


def test_pallas_kmeans_kernel_interpret_matches_xla():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((256, 16)), jnp.float32)
    c = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    sums_ref, counts_ref, cost_ref = distance.partial_sums_counts(x, c)
    sums, counts, cost = pallas_kernels.kmeans_stats_pallas(
        x, c, block_n=64, interpret=True)
    np.testing.assert_allclose(np.asarray(sums), np.asarray(sums_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(counts), np.asarray(counts_ref),
                               rtol=1e-6)
    np.testing.assert_allclose(float(cost), float(cost_ref), rtol=1e-4)
    # bf16 point storage: compare like-for-like against the XLA path fed
    # the SAME bf16 points (f32-vs-bf16 comparisons flip near-tie
    # assignments and move whole rows between cluster sums)
    x16 = x.astype(jnp.bfloat16)
    s_ref16, c_ref16, cost_ref16 = distance.partial_sums_counts(
        x16, c, compute_dtype=jnp.bfloat16)   # bf16 cross term, like pallas
    sums16, counts16, cost16 = pallas_kernels.kmeans_stats_pallas(
        x16, c, block_n=64, interpret=True)
    assert float(jnp.sum(counts16)) == x.shape[0]
    np.testing.assert_allclose(np.asarray(counts16), np.asarray(c_ref16),
                               atol=1)
    np.testing.assert_allclose(np.asarray(sums16), np.asarray(s_ref16),
                               rtol=2e-2, atol=0.2)
    np.testing.assert_allclose(float(cost16), float(cost_ref16), rtol=2e-2)


def test_pallas_spd_solve_interpret_matches_scipy():
    """The lane-vectorized batched Cholesky solve (interpret mode) matches
    jax.scipy's exact SPD solve, including K/N shapes that need padding."""
    rng = np.random.default_rng(7)
    for n, k in [(256, 16), (300, 10)]:       # (aligned, needs K+N padding)
        g = rng.standard_normal((n, k, k)).astype(np.float32)
        a = g @ np.transpose(g, (0, 2, 1)) + 0.1 * np.eye(k, dtype=np.float32)
        b = rng.standard_normal((n, k)).astype(np.float32)
        want = jax.scipy.linalg.solve(jnp.asarray(a), jnp.asarray(b)[..., None],
                                      assume_a="pos")[..., 0]
        got = pallas_kernels.spd_solve_pallas(jnp.asarray(a), jnp.asarray(b),
                                              tile_b=128, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)


def test_als_pallas_solver_matches_cholesky():
    """ALS solver='pallas' through the REAL _spd_solve dispatch (off-TPU the
    explicit request runs the kernel in interpret mode) agrees with the
    exact cholesky path on the regularized ALS normal equations."""
    from harp_tpu.models.als import ALSConfig, _spd_solve

    rng = np.random.default_rng(11)
    k = 8
    v = rng.standard_normal((64, k)).astype(np.float32)
    a = np.einsum("ek,el->kl", v, v) + 0.5 * np.eye(k, dtype=np.float32)
    a = np.broadcast_to(a, (32, k, k)).copy()
    b = rng.standard_normal((32, k)).astype(np.float32)
    exact = _spd_solve(jnp.asarray(a), jnp.asarray(b),
                       ALSConfig(rank=k, solver="cholesky"))
    fast = _spd_solve(jnp.asarray(a), jnp.asarray(b),
                      ALSConfig(rank=k, solver="pallas"))
    np.testing.assert_allclose(np.asarray(fast), np.asarray(exact),
                               rtol=2e-3, atol=2e-3)


def test_ring_attention_matches_reference(session):
    rng = np.random.default_rng(5)
    l, d, dv = 64, 16, 16
    q = rng.standard_normal((l, d)).astype(np.float32)
    k = rng.standard_normal((l, d)).astype(np.float32)
    v = rng.standard_normal((l, dv)).astype(np.float32)

    for causal in (False, True):
        out = session.run(
            lambda a, b, c: ring_attention.ring_attention(a, b, c, causal),
            session.scatter(jnp.asarray(q)), session.scatter(jnp.asarray(k)),
            session.scatter(jnp.asarray(v)),
            in_specs=(session.shard(),) * 3, out_specs=session.shard())
        ref = ring_attention.reference_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)


def test_ring_attention_mha_matches_ulysses_and_reference(session):
    """The two SP layouts compute the SAME attention: multi-head ring vs
    Ulysses vs the replicated per-head reference."""
    rng = np.random.default_rng(13)
    l, h, dh = 64, 8, 8
    q = rng.standard_normal((l, h, dh)).astype(np.float32)
    k = rng.standard_normal((l, h, dh)).astype(np.float32)
    v = rng.standard_normal((l, h, dh)).astype(np.float32)
    ring = session.run(
        lambda a, b, c: ring_attention.ring_attention_mha(a, b, c, True),
        session.scatter(jnp.asarray(q)), session.scatter(jnp.asarray(k)),
        session.scatter(jnp.asarray(v)),
        in_specs=(session.shard(),) * 3, out_specs=session.shard())
    uly = session.run(
        lambda a, b, c: ring_attention.ulysses_attention(a, b, c, h, True),
        session.scatter(jnp.asarray(q)), session.scatter(jnp.asarray(k)),
        session.scatter(jnp.asarray(v)),
        in_specs=(session.shard(),) * 3, out_specs=session.shard())
    np.testing.assert_allclose(np.asarray(ring), np.asarray(uly),
                               rtol=2e-3, atol=2e-3)
    ref = np.stack([
        np.asarray(ring_attention.reference_attention(
            jnp.asarray(q[:, i]), jnp.asarray(k[:, i]), jnp.asarray(v[:, i]),
            True)) for i in range(h)], axis=1)
    np.testing.assert_allclose(np.asarray(ring), ref, rtol=2e-3, atol=2e-3)


def test_blocked_attention_matches_reference_all_block_sizes():
    """The streamed-KV inner attention (what ulysses now runs) is exact for
    every block size, causal and not — including blocks that split the
    causal boundary."""
    rng = np.random.default_rng(13)
    for l in (48, 47):           # 47: prime length exercises the KV padding
        h, d = 2, 8
        q = jnp.asarray(rng.standard_normal((l, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((l, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((l, h, d)), jnp.float32)
        for causal in (False, True):
            ref = jax.vmap(
                lambda qh, kh, vh: ring_attention.reference_attention(
                    qh, kh, vh, causal), in_axes=1, out_axes=1)(q, k, v)
            for blk in (5, 16, 48, 512):
                got = ring_attention.blocked_attention(q, k, v, causal,
                                                       kv_block=blk)
                np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                           rtol=2e-4, atol=2e-5)


def test_ulysses_attention_matches_reference(session):
    rng = np.random.default_rng(9)
    l, h, dh = 64, 8, 8
    q = rng.standard_normal((l, h, dh)).astype(np.float32)
    k = rng.standard_normal((l, h, dh)).astype(np.float32)
    v = rng.standard_normal((l, h, dh)).astype(np.float32)
    out = session.run(
        lambda a, b, c: ring_attention.ulysses_attention(a, b, c, h, True),
        session.scatter(jnp.asarray(q)), session.scatter(jnp.asarray(k)),
        session.scatter(jnp.asarray(v)),
        in_specs=(session.shard(),) * 3, out_specs=session.shard())
    # per-head reference
    ref = np.stack([
        np.asarray(ring_attention.reference_attention(
            jnp.asarray(q[:, i]), jnp.asarray(k[:, i]), jnp.asarray(v[:, i]),
            True)) for i in range(h)], axis=1)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3, atol=2e-3)


def test_session_event_api_single_process(session):
    """CollectiveMapper getEvent/waitEvent/sendEvent parity on HarpSession
    (single-process: local delivery, no transport)."""
    from harp_tpu.parallel.events import EventType

    assert session.get_event() is None
    session.send_event({"k": 1})                 # collective → local queue
    ev = session.get_event()
    assert ev is not None and ev.type is EventType.COLLECTIVE
    assert ev.payload == {"k": 1}
    session.send_event("mine", dest=0)           # dest == self
    ev = session.wait_event(timeout=5.0)
    assert ev is not None and ev.payload == "mine"
    import pytest as _pt

    with _pt.raises(ValueError, match="process rank"):
        session.send_event("not-mine", dest=3)   # rank out of range: loud
    session.close_events()
    assert session.get_event() is None           # closed plane: pure peek


def test_flash_attention_interpret_matches_reference():
    """The pallas flash kernel (interpret mode) is exact vs the replicated
    reference, causal and not, across tilings including multi-block grids,
    RAGGED lengths (prime L — padded keys masked inside the kernel,
    VERDICT r4 #10) and Dv != Dh value heads. r7: every pack-eligible shape
    (even H, Dh/Dv <= 64) also runs the two-heads-per-128-lane packed
    layout, which must be bit-for-par with the unpacked one."""
    rng = np.random.default_rng(21)
    for l, h, dh, dv, causal in [(64, 2, 16, 16, False),
                                 (64, 2, 16, 16, True),
                                 (96, 1, 8, 8, True),
                                 (61, 2, 16, 16, False),   # prime L
                                 (97, 1, 8, 8, True),      # prime L, causal
                                 (64, 2, 16, 24, True),    # Dv != Dh
                                 (127, 4, 64, 64, True)]:  # prime L, Dh=64
        q = jnp.asarray(rng.standard_normal((l, h, dh)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((l, h, dh)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((l, h, dv)), jnp.float32)
        ref = jax.vmap(lambda a, b, c: ring_attention.reference_attention(
            a, b, c, causal), in_axes=1, out_axes=1)(q, k, v)
        packs = [False]
        if h % 2 == 0 and dh <= 64 and dv <= 64:
            packs.append(True)
        for hp in packs:
            got = pallas_kernels.flash_attention_pallas(
                q, k, v, causal, bq=32, bk=32, interpret=True, head_pack=hp)
            assert got.shape == (l, h, dv)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)


def test_flash_attention_interpret_bf16():
    """bf16 q/k/v through the kernel (both layouts) tracks the f32
    reference within bf16 mantissa tolerance — the second dtype of the
    existing kernel test matrix (the K-means kernel tests bf16 the same
    way), at an aligned AND a prime (ragged-padding) length."""
    rng = np.random.default_rng(23)
    for l in (64, 61):
        h, dh = 2, 32
        q = jnp.asarray(rng.standard_normal((l, h, dh)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((l, h, dh)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((l, h, dh)), jnp.bfloat16)
        ref = jax.vmap(lambda a, b, c: ring_attention.reference_attention(
            a.astype(jnp.float32), b.astype(jnp.float32),
            c.astype(jnp.float32), True), in_axes=1, out_axes=1)(q, k, v)
        for hp in (False, True):
            got = pallas_kernels.flash_attention_pallas(
                q, k, v, True, bq=32, bk=32, interpret=True, head_pack=hp)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=3e-2, atol=3e-2)


def test_flash_causal_grid_is_blocksparse():
    """The causal grid NEVER fetches a fully-masked KV block: the
    scalar-prefetch layout arrays ARE the kernel's index map, so asserting
    on them is asserting what the DMA engine is steered to. The trapezoid
    visits ~L(L+bk)/2 worth of KV positions, not L²."""
    layout = pallas_kernels._flash_grid_layout
    # bench shape: L=16384, bq=256, bk=512 — 64 q tiles x 32 kv blocks
    n_q, n_kv, bq, bk = 64, 32, 256, 512
    iq_of, j_of = layout(n_q, n_kv, bq, bk, causal=True)
    # 1) no dead blocks: every visited pair has its smallest key position
    #    <= its largest query position
    assert np.all(j_of * bk <= (iq_of + 1) * bq - 1)
    # 2) no live block is missed and none visits twice: per q tile exactly
    #    ceil(((iq+1)*bq)/bk) blocks, each once
    for iq in range(n_q):
        js = np.sort(j_of[iq_of == iq])
        m = min(n_kv, -(-((iq + 1) * bq) // bk))
        assert js.tolist() == list(range(m))
    # 3) the r5 grid visited n_q*n_kv = 2048 blocks; the trapezoid visits
    #    1056 — the DMA traffic the pl.when predication could not remove
    assert len(iq_of) == 1056 < 0.55 * n_q * n_kv
    # 4) with bq == bk the visited KV positions are EXACTLY L(L+bk)/2
    l = 4096
    b = 256
    iq_sq, j_sq = layout(l // b, l // b, b, b, causal=True)
    assert len(iq_sq) * b * b == l * (l + b) // 2
    # non-causal stays the full rectangle
    iq_r, j_r = layout(4, 3, 32, 32, causal=False)
    assert len(iq_r) == 12 and j_r.max() == 2


def test_flash_stats_compose_ring_hops():
    """return_stats exposes the streaming-softmax pieces so ring hops can
    merge flash-kernel partial results: a diagonal-causal hop over the own
    block merged with a full hop over an earlier block equals the causal
    reference — the exact composition ring_attention_mha runs."""
    rng = np.random.default_rng(29)
    l, h, dh = 64, 4, 16
    lq = l // 2
    q = jnp.asarray(rng.standard_normal((l, h, dh)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((l, h, dh)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((l, h, dh)), jnp.float32)
    ref = jax.vmap(lambda a, b, c: ring_attention.reference_attention(
        a, b, c, True), in_axes=1, out_axes=1)(q, k, v)
    q1 = q[lq:]                         # "worker 1"'s query rows
    o0, m0, d0 = pallas_kernels.flash_attention_pallas(
        q1, k[lq:], v[lq:], causal=True, bq=16, bk=16, interpret=True,
        return_stats=True)              # hop 0: own (diagonal) block
    o1, m1, d1 = pallas_kernels.flash_attention_pallas(
        q1, k[:lq], v[:lq], causal=False, bq=16, bk=16, interpret=True,
        return_stats=True)              # hop 1: fully-live earlier block
    valid = jnp.ones(m0.shape, bool)
    _, num, den = ring_attention._softmax_merge(
        m0, o0 * d0[..., None], d0, m1, o1 * d1[..., None], d1, valid)
    out = num / jnp.maximum(den, 1e-30)[..., None]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref[lq:]),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_mha_flash_hops_match_reference(session):
    """The full ring schedule with flash-kernel hops (interpret mode inside
    shard_map) matches the replicated reference — the TPU dispatch path,
    exercised end to end on the 8-worker CPU mesh."""
    rng = np.random.default_rng(31)
    l, h, dh = 64, 4, 16
    q = rng.standard_normal((l, h, dh)).astype(np.float32)
    k = rng.standard_normal((l, h, dh)).astype(np.float32)
    v = rng.standard_normal((l, h, dh)).astype(np.float32)
    for causal in (True, False):
        ref = np.stack([
            np.asarray(ring_attention.reference_attention(
                jnp.asarray(q[:, i]), jnp.asarray(k[:, i]),
                jnp.asarray(v[:, i]), causal)) for i in range(h)], axis=1)
        out = session.run(
            lambda a, b, c: ring_attention.ring_attention_mha(
                a, b, c, causal, use_flash=True, interpret=True),
            session.scatter(jnp.asarray(q)), session.scatter(jnp.asarray(k)),
            session.scatter(jnp.asarray(v)),
            in_specs=(session.shard(),) * 3, out_specs=session.shard())
        np.testing.assert_allclose(np.asarray(out), ref,
                                   rtol=2e-3, atol=2e-3)
