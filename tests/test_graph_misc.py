"""PageRank / MDS / EM / quality / boosting / trees / apriori / subgraph tests
(contrib simplepagerank, wdamds, daal_em, daal_quality_metrics, daal_{stump,
adaboost,logitboost,brownboost}, daal_dtree/dforest, daal_ar, sahad parity)."""

import numpy as np
import pytest

from harp_tpu.io import datagen
from harp_tpu.models import (assoc, boosting, em, forest, mds, pagerank,
                             quality, subgraph)


def _ring_edges(n):
    src = np.arange(n, dtype=np.int64)
    dst = (src + 1) % n
    return src, dst


def test_pagerank_uniform_on_ring(session):
    n = 24
    src, dst = _ring_edges(n)
    pr = pagerank.PageRank(session, pagerank.PageRankConfig(iterations=30))
    ranks, deltas = pr.run(src, dst, n)
    np.testing.assert_allclose(ranks, 1.0 / n, atol=1e-4)
    assert deltas[-1] < 1e-5


def test_pagerank_matches_numpy_power_iteration(session):
    rng = np.random.default_rng(7)
    n, m = 40, 200
    src = rng.integers(0, n, m)
    dst = rng.integers(0, n, m)
    cfg = pagerank.PageRankConfig(damping=0.85, iterations=50)
    ranks, _ = pagerank.PageRank(session, cfg).run(src, dst, n)
    # numpy reference with same dangling handling
    deg = np.bincount(src, minlength=n).astype(np.float64)
    r = np.full(n, 1.0 / n)
    for _ in range(50):
        contrib = np.zeros(n)
        np.add.at(contrib, dst, r[src] / deg[src])
        dangling = r[deg == 0].sum()
        r = (1 - 0.85) / n + 0.85 * (contrib + dangling / n)
    np.testing.assert_allclose(ranks, r, atol=1e-4)
    np.testing.assert_allclose(ranks.sum(), 1.0, atol=1e-3)


def test_mds_recovers_geometry(session):
    rng = np.random.default_rng(4)
    pts = rng.standard_normal((48, 2)).astype(np.float32)
    d = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1)).astype(np.float32)
    model = mds.WDAMDS(session, mds.MDSConfig(dim=2, iterations=80))
    x, stress = model.fit(d, seed=1)
    assert stress[-1] < 0.05 * stress[0]
    # embedded distances match target distances (up to rigid motion)
    d_emb = np.sqrt(((x[:, None] - x[None]) ** 2).sum(-1))
    assert np.abs(d_emb - d).mean() < 0.1 * d.mean()


def test_wda_mds_weighted_cg_matches_numpy_oracle(session):
    """The distributed weighted V CG solve (WDAMDSMapper.java:585 parity)
    matches a single-host SMACOF-with-CG oracle on NON-uniform weights —
    the case where the old uniform V+=I/n simplification was a genuinely
    different algorithm."""
    rng = np.random.default_rng(11)
    n = 48
    pts = rng.standard_normal((n, 2)).astype(np.float32)
    d = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1)).astype(np.float32)
    w = rng.uniform(0.2, 3.0, (n, n)).astype(np.float32)
    w = (w + w.T) / 2.0                     # symmetric, strongly non-uniform
    cfg = mds.MDSConfig(dim=2, iterations=25, cg_iters=20)
    x, stress = mds.WDAMDS(session, cfg).fit(d, weights=w, seed=1)
    # oracle with the identical init and the identical truncated CG
    x0 = np.random.default_rng(1).standard_normal((n, 2)).astype(np.float32)
    x0 -= x0.mean(axis=0)
    x_ref, s_ref = mds.numpy_wda_smacof(d, w, x0, cfg.iterations,
                                        cfg.cg_iters)
    np.testing.assert_allclose(stress, s_ref, rtol=1e-3)
    np.testing.assert_allclose(x, x_ref, rtol=1e-2, atol=1e-2)
    # and the weighted fit still embeds the geometry
    d_emb = np.sqrt(((x[:, None] - x[None]) ** 2).sum(-1))
    assert np.abs(d_emb - d).mean() < 0.15 * d.mean()


def test_mds_matmuls_request_highest_precision(session):
    """Regression guard for a REAL-CHIP-only failure the CPU suite cannot
    reproduce: TPU's default f32 matmul truncates operands to bf16, which
    sign-flips the CG's pᵀVp at convergence scale and sent the embedding to
    overflow (stress NaN at iteration 1 on hardware, round 5). The three
    SMACOF matmuls (V matvec, B(X)·X, pairwise distances) must pin
    Precision.HIGHEST — assert it survives in the traced jaxpr."""
    from harp_tpu.models.mds import MDSConfig, _smacof

    n = 16
    cfg = MDSConfig(dim=2, iterations=1)
    prog = session.spmd(
        lambda d, wt, x0: _smacof(d, wt, x0, n, cfg),
        in_specs=(session.shard(), session.shard(), session.replicate()),
        out_specs=(session.replicate(), session.replicate()))
    text = prog.lower(np.zeros((n, n), np.float32),
                      np.zeros((n, n), np.float32),
                      np.zeros((n, 2), np.float32)).as_text()
    dots = [ln for ln in text.splitlines() if "dot_general" in ln]
    assert dots, "no dot_general in the SMACOF program?"
    low = [ln for ln in dots if "HIGHEST" not in ln]
    assert not low, f"SMACOF matmuls without HIGHEST precision: {low}"


def test_em_gmm_recovers_components(session):
    rng = np.random.default_rng(9)
    centers = np.array([[0, 0], [6, 0], [0, 6]], np.float32)
    x = np.concatenate([
        c + rng.standard_normal((80, 2)).astype(np.float32) for c in centers])
    rng.shuffle(x)
    model = em.EMGMM(session, em.EMConfig(num_components=3, iterations=40))
    pi, mean, cov, ll = model.fit(x, seed=3)
    assert ll[-1] > ll[0]
    np.testing.assert_allclose(sorted(pi), [1 / 3] * 3, atol=0.08)
    # every true center has a recovered mean nearby
    for c in centers:
        assert np.min(np.linalg.norm(mean - c, axis=1)) < 0.6


def test_quality_metrics(session):
    rng = np.random.default_rng(2)
    y = rng.integers(0, 3, 240).astype(np.int32)
    pred = y.copy()
    flip = rng.random(240) < 0.2
    pred[flip] = (pred[flip] + 1) % 3
    qm = quality.QualityMetrics(session)
    out = qm.classification(y, pred, 3)
    assert abs(out["accuracy"] - (y == pred).mean()) < 1e-5
    assert out["confusion"].sum() == 240
    # AUC: separable scores → ~1; random scores → ~0.5
    yb = rng.integers(0, 2, 240).astype(np.int32)
    assert qm.auc(yb, yb + 0.1 * rng.random(240).astype(np.float32)) > 0.99
    reg = qm.regression(np.arange(240, dtype=np.float32),
                        np.arange(240, dtype=np.float32) + 1.0)
    assert abs(reg["rmse"] - 1.0) < 1e-4 and reg["r2"] > 0.99


@pytest.fixture(scope="module")
def clf_data():
    rng = np.random.default_rng(11)
    n = 320
    x = rng.standard_normal((n, 6)).astype(np.float32)
    y = ((x[:, 0] + 0.5 * x[:, 1] - 0.3 * x[:, 2]) > 0).astype(np.int32)
    return x, y


def test_stump_and_adaboost(session, clf_data):
    x, y = clf_data
    stump = boosting.DecisionStump(session).fit(x, y)
    acc_stump = (stump.predict(x) == y).mean()
    assert acc_stump > 0.65
    ada = boosting.AdaBoost(session, boosting.BoostConfig(rounds=30)).fit(x, y)
    acc_ada = (ada.predict(x) == y).mean()
    assert acc_ada > acc_stump
    assert acc_ada > 0.85


def test_logitboost_and_brownboost(session, clf_data):
    x, y = clf_data
    lb = boosting.LogitBoost(session, boosting.BoostConfig(rounds=30)).fit(x, y)
    assert (lb.predict(x) == y).mean() > 0.85
    bb = boosting.BrownBoost(session, boosting.BoostConfig(rounds=30)).fit(x, y)
    assert (bb.predict(x) == y).mean() > 0.8


def test_decision_tree_and_forest(session):
    rng = np.random.default_rng(21)
    n = 400
    x = rng.standard_normal((n, 5)).astype(np.float32)
    # axis-aligned XOR-ish target: tree-friendly, linear-unfriendly
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.int32)
    tree = forest.DecisionTree(session, forest.TreeConfig(depth=3, num_bins=16,
                                                          num_classes=2))
    tree.fit(x, y)
    assert (tree.predict(x) == y).mean() > 0.9
    rf = forest.RandomForest(session, forest.TreeConfig(
        depth=3, num_bins=16, num_classes=2, num_trees=8,
        feature_fraction=0.8))
    rf.fit(x, y, seed=1)
    assert (rf.predict(x) == y).mean() > 0.9


def test_apriori(session):
    rng = np.random.default_rng(5)
    n, d = 240, 8
    tx = (rng.random((n, d)) < 0.15).astype(np.float32)
    # plant a strong pattern: items 0,1 co-occur in 40% of transactions
    planted = rng.random(n) < 0.4
    tx[planted, 0] = 1.0
    tx[planted, 1] = 1.0
    model = assoc.Apriori(session, assoc.AprioriConfig(
        min_support=0.2, min_confidence=0.6, max_size=3))
    model.fit(tx)
    assert (0,) in model.itemsets and (0, 1) in model.itemsets
    assert abs(model.itemsets[(0, 1)] - tx[:, [0, 1]].all(1).mean()) < 1e-6
    assert any(set(a) | set(c) == {0, 1} for a, c, _, _ in model.rules)


def test_subgraph_edge_count_exact_expectation(session):
    # k=2 template: "paths" of 2 vertices = edges; per-trial estimates are
    # exactly the edge count (every 2-coloring counts each edge with p=1/2,
    # unbiased correction 1/p = 2) up to coloring noise — mean over trials
    rng = np.random.default_rng(6)
    n, m = 32, 80
    src = rng.integers(0, n, m)
    dst = (src + 1 + rng.integers(0, n - 1, m)) % n
    cfg = subgraph.SubgraphConfig(template_size=2, trials=64)
    est, trials = subgraph.SubgraphCounter(session, cfg).count_paths(
        src, dst, n, seed=2)
    assert abs(est - m) < 0.25 * m


def test_subgraph_k4_three_paths(session):
    # K4: number of simple 3-vertex paths = 3 * C(4,3) = 12
    edges = [(0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3)]
    src = np.array([e[0] for e in edges])
    dst = np.array([e[1] for e in edges])
    cfg = subgraph.SubgraphConfig(template_size=3, trials=96)
    est, _ = subgraph.SubgraphCounter(session, cfg).count_paths(src, dst, 4,
                                                                seed=7)
    assert abs(est - 12.0) < 6.0


def test_tree_template_automorphisms():
    t = subgraph.TreeTemplate
    assert t([(0, 1)]).automorphisms() == 2                       # edge
    assert t([(0, 1), (1, 2)]).automorphisms() == 2               # path-3
    assert t([(0, 1), (1, 2), (2, 3), (3, 4)]).automorphisms() == 2  # u5-1
    assert t([(0, 1), (0, 2), (0, 3), (0, 4)]).automorphisms() == 24  # star-5
    # spider S(2,1,1): center 1, legs 2-3 / 0 / 4 — the two single leaves swap
    assert t([(0, 1), (1, 2), (2, 3), (1, 4)]).automorphisms() == 2
    # the 7-vertex identity tree (legs of lengths 1,2,3) has aut = 1
    assert t([(0, 1), (0, 2), (2, 3), (0, 4), (4, 5),
              (5, 6)]).automorphisms() == 1
    with pytest.raises(ValueError):
        t([(0, 1), (0, 1)])                                       # dup edge
    with pytest.raises(ValueError):
        t([(0, 1), (2, 3)])                                       # forest


def test_tree_templates_match_brute_force(session):
    """VERDICT #3: general tree templates (u5-1 path, u5-2 spider, star,
    caterpillar) agree with exact backtracking counts on random graphs."""
    rng = np.random.default_rng(11)
    n, m = 24, 60
    src = rng.integers(0, n, m)
    dst = (src + 1 + rng.integers(0, n - 1, m)) % n
    templates = {
        "u3-star": [(0, 1), (0, 2), (0, 3)],
        "u5-1-path": [(0, 1), (1, 2), (2, 3), (3, 4)],
        "u5-star": [(0, 1), (0, 2), (0, 3), (0, 4)],
        "u5-2-spider": [(0, 1), (1, 2), (2, 3), (1, 4)],
    }
    counter = subgraph.SubgraphCounter(
        session, subgraph.SubgraphConfig(trials=160))
    for name, edges in templates.items():
        exact = subgraph.brute_force_tree_count(edges, src, dst, n)
        est, trials = counter.count_template(edges, src, dst, n, seed=5)
        assert exact > 0, name
        assert abs(est - exact) < 0.3 * exact + 2.0, (
            f"{name}: est {est} vs exact {exact}")


def test_general_tree_dp_reproduces_path_counts(session):
    """The path case through the general DP matches exact path counts (the
    pre-rewrite behavior was verified against the same oracle)."""
    rng = np.random.default_rng(3)
    n, m = 20, 40
    src = rng.integers(0, n, m)
    dst = (src + 1 + rng.integers(0, n - 1, m)) % n
    path4 = [(0, 1), (1, 2), (2, 3)]
    exact = subgraph.brute_force_tree_count(path4, src, dst, n)
    cfg = subgraph.SubgraphConfig(template_size=4, trials=160)
    est, _ = subgraph.SubgraphCounter(session, cfg).count_paths(
        src, dst, n, seed=9)
    assert abs(est - exact) < 0.3 * exact + 2.0


def test_template_file_format_roundtrip(tmp_path, session):
    """The reference's .template format (u5-2: vertex count, edge count,
    edges) parses and counts — datasets/daal_subgraph/templates parity."""
    from harp_tpu.models import subgraph

    p = tmp_path / "u5-2.template"
    p.write_text("5\n4\n0 1\n0 2\n0 3\n3 4\n")
    edges = subgraph.load_template_file(str(p))
    assert edges == [(0, 1), (0, 2), (0, 3), (3, 4)]
    t = subgraph.TreeTemplate(edges)
    assert t.k == 5
    bad = tmp_path / "bad.template"
    bad.write_text("3\n2\n0 1\n")          # declares 2 edges, carries 1
    import pytest

    with pytest.raises(ValueError, match="declares"):
        subgraph.load_template_file(str(bad))
    oob = tmp_path / "oob.template"
    oob.write_text("3\n2\n0 1\n1 5\n")     # vertex 5 outside [0, 3)
    with pytest.raises(ValueError, match="outside"):
        subgraph.load_template_file(str(oob))
