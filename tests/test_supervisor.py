"""Elastic gang supervisor: deterministic fault injection, classify/backoff/
journal policy, verified-checkpoint resume.

Reference parity (SURVEY §5): the reference's failure handling ENDED at
detection — "Slaves may fail" (Communication.java:82) and the job died, with
workers never re-executed. These tests cover the recovery half the reference
never had: scripted member death (parallel.faults) → gang fail-stop → the
supervisor (parallel.supervisor) relaunches from the newest checksum-verified
checkpoint → the finished model is bitwise what an uninterrupted run produces.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from harp_tpu.parallel import failure, faults, launch, supervisor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _nodes(n):
    return [launch.Node("localhost", 0) for _ in range(n)]


def _journal(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


# --------------------------------------------------------------------------- #
# fault grammar + firing semantics
# --------------------------------------------------------------------------- #

def test_fault_grammar_roundtrip():
    specs = faults.parse_faults(
        "crash@epoch=3:rank=1, hang@epoch=2, "
        "ckpt-corrupt@epoch=4:rank=0:attempt=1")
    assert specs == [
        faults.FaultSpec("crash", 3, 1, 0),
        faults.FaultSpec("hang", 2, None, 0),
        faults.FaultSpec("ckpt-corrupt", 4, 0, 1),
    ]


@pytest.mark.parametrize("bad", [
    "explode@epoch=1",            # unknown kind
    "crash@rank=1",               # missing epoch
    "crash epoch=1",              # no @
    "crash@epoch=three",          # non-integer
    "crash@epoch=1:node=2",       # unknown key
])
def test_fault_grammar_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_faults(bad)


def test_fault_bad_spec_raises_on_every_boundary(monkeypatch):
    # a malformed plan must fail EVERY fire(), not just the first — a caught
    # first error must not install a stale/empty plan that silently disarms
    # the scripted fault
    monkeypatch.setenv("HARP_FAULT", "crash@epoch=oops")
    with pytest.raises(ValueError):
        faults.fire(1)
    with pytest.raises(ValueError):
        faults.fire(2)


def test_fault_fire_rank_and_attempt_gating(monkeypatch):
    fired = []
    monkeypatch.setattr(faults, "_execute",
                        lambda spec, ckpt: fired.append(spec.kind))
    monkeypatch.setenv("HARP_FAULT", "crash@epoch=3:rank=1")
    monkeypatch.setenv("HARP_PROCESS_ID", "0")
    faults.fire(5)
    assert fired == []                       # wrong rank never fires
    monkeypatch.setenv("HARP_PROCESS_ID", "1")
    faults.fire(2)
    assert fired == []                       # epoch not reached yet
    monkeypatch.setenv("HARP_GANG_ATTEMPT", "1")
    faults.fire(3)
    assert fired == []                       # relaunched attempt: disarmed
    monkeypatch.setenv("HARP_GANG_ATTEMPT", "0")
    faults.fire(3)
    faults.fire(4)
    assert fired == ["crash"]                # fires exactly once


def test_fault_crash_kills_a_real_process(tmp_path):
    # end-to-end through a subprocess (faults must not need jax): the hook
    # at an "iteration boundary" exits with the scripted code
    proc = subprocess.run(
        [sys.executable, "-c",
         "from harp_tpu.parallel import faults\n"
         "for epoch in range(1, 6):\n"
         "    faults.fire(epoch)\n"
         "print('survived')"],
        env={**os.environ, "HARP_FAULT": "crash@epoch=3:rank=0"},
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert proc.returncode == faults.FAULT_CRASH_EXIT
    assert "survived" not in proc.stdout


def test_fault_ckpt_corrupt_targets_newest_step(tmp_path):
    from harp_tpu.utils.checkpoint import Checkpointer, latest_valid_step

    ck = Checkpointer(str(tmp_path), use_orbax=False, keep=5)
    for s in (1, 2):
        ck.save(s, {"w": np.full((3, 3), float(s))})
    assert faults.corrupt_latest(str(tmp_path)).endswith(
        os.path.join("step_000000000002", "arrays.npz"))
    assert latest_valid_step(str(tmp_path)) == 1


# --------------------------------------------------------------------------- #
# launcher: first-failure attribution + partial output on timeout (satellite)
# --------------------------------------------------------------------------- #

def test_launch_reports_first_failing_member():
    cmd = [sys.executable, "-c",
           "import os, sys, time\n"
           "if os.environ['HARP_PROCESS_ID'] == '1':\n"
           "    time.sleep(0.2); sys.exit(7)\n"
           "time.sleep(120)"]
    results = launch.launch(_nodes(3), cmd, timeout=60.0)
    assert not results.ok
    assert results.first_failure == (1, 7)
    assert results.first_failed_rank == 1 and results.first_failed_rc == 7
    # survivors were killed, but are NOT blamed
    assert results[0][0] != 0 and results[2][0] != 0


def test_launch_clean_gang_has_no_first_failure():
    results = launch.launch(_nodes(2), [sys.executable, "-c", "print('hi')"],
                            timeout=60.0)
    assert results.ok and results.first_failure is None


def test_launch_timeout_carries_partial_output():
    cmd = [sys.executable, "-c",
           "import os, sys, time\n"
           "print('rank', os.environ['HARP_PROCESS_ID'], 'starting',"
           " flush=True)\n"
           "time.sleep(120)"]
    with pytest.raises(subprocess.TimeoutExpired) as ei:
        launch.launch(_nodes(2), cmd, timeout=3.0)
    outs = ei.value.member_outputs
    assert len(outs) == 2
    assert "rank 0 starting" in outs[0] and "rank 1 starting" in outs[1]
    assert "rank 0 starting" in ei.value.output


# --------------------------------------------------------------------------- #
# supervisor policy: classify, backoff, budget, suspect node, journal
# --------------------------------------------------------------------------- #

def test_classify_watchdog_vs_crash():
    crash = launch.GangResult([(0, ""), (9, "")], first_failure=(1, 9))
    wd = launch.GangResult([(98, ""), (-9, "")], first_failure=(0, 98))
    clean = launch.GangResult([(0, ""), (0, "")])
    assert supervisor.classify(crash)[0] is supervisor.FailureClass.CRASH
    assert supervisor.classify(wd) == (supervisor.FailureClass.WATCHDOG, 0, 98)
    assert supervisor.classify(clean)[0] is supervisor.FailureClass.CLEAN


def test_policy_backoff_is_exponential_and_capped():
    pol = supervisor.RestartPolicy(backoff_base_s=1.0, backoff_factor=2.0,
                                   backoff_max_s=5.0)
    assert [pol.backoff(i) for i in range(4)] == [1.0, 2.0, 4.0, 5.0]


def test_supervise_budget_exhausted_keeps_journal(tmp_path):
    journal_path = str(tmp_path / "journal.jsonl")
    sleeps = []
    out = supervisor.supervise(
        _nodes(2),
        [sys.executable, "-c",
         "import os, sys, time\n"
         "if os.environ['HARP_PROCESS_ID'] == '0':\n"
         "    sys.exit(7)\n"
         "time.sleep(120)"],
        policy=supervisor.RestartPolicy(max_restarts=2),
        timeout=60.0, journal_path=journal_path, sleep=sleeps.append)
    assert not out.ok and out.gave_up == "budget" and out.attempts == 3
    assert sleeps == [1.0, 2.0]               # exponential schedule honored
    records = _journal(journal_path)
    restarts = [r for r in records if r["event"] == "restart"]
    assert len(restarts) == 2
    assert all(r["cause"] == "crash" and r["first_rank"] == 0
               and r["first_rc"] == 7 for r in restarts)
    assert records[-1]["event"] == "give-up"


def test_supervise_recovers_from_transient_crash(tmp_path):
    # the member keys on HARP_GANG_ATTEMPT exactly like the fault layer:
    # dead on attempt 0, clean on the relaunch
    from harp_tpu.utils.metrics import Metrics

    m = Metrics()
    out = supervisor.supervise(
        _nodes(2),
        [sys.executable, "-c",
         "import os, sys\n"
         "sys.exit(5 if os.environ['HARP_GANG_ATTEMPT'] == '0' else 0)"],
        policy=supervisor.RestartPolicy(max_restarts=2),
        timeout=60.0, metrics=m, sleep=lambda s: None)
    assert out.ok and out.attempts == 2
    assert m.counters["supervisor.restarts"] == 1
    assert m.counters["supervisor.recoveries"] == 1
    assert [r["event"] for r in out.journal] == ["restart", "success"]


def test_supervise_marks_repeat_watchdog_node_suspect(tmp_path):
    # rank 1 exits with the watchdog code on EVERY attempt: after
    # watchdog_suspect_after deaths the supervisor stops burning budget
    journal_path = str(tmp_path / "journal.jsonl")
    out = supervisor.supervise(
        _nodes(2),
        [sys.executable, "-c",
         "import os, sys, time\n"
         "if os.environ['HARP_PROCESS_ID'] == '1':\n"
         "    sys.exit(98)\n"
         "time.sleep(120)"],
        policy=supervisor.RestartPolicy(max_restarts=10,
                                        watchdog_suspect_after=2),
        timeout=60.0, journal_path=journal_path, sleep=lambda s: None)
    assert not out.ok and out.gave_up == "suspect-node"
    assert out.attempts == 2                  # not 11: aborted early
    records = _journal(journal_path)
    assert records[-1]["event"] == "abort-suspect"
    assert records[-1]["first_rank"] == 1
    assert records[-1]["host"] == "localhost"


def test_supervise_aborts_on_non_retryable_exit(tmp_path):
    # argparse usage errors (rc=2) fail identically every attempt: the
    # supervisor must not burn the budget relaunching them
    out = supervisor.supervise(
        _nodes(2), [sys.executable, "-c", "import sys; sys.exit(2)"],
        policy=supervisor.RestartPolicy(max_restarts=5),
        timeout=60.0, sleep=lambda s: None)
    assert not out.ok and out.gave_up == "non-retryable"
    assert out.attempts == 1                  # no relaunch at all
    assert out.journal[-1]["event"] == "abort-non-retryable"


def test_supervise_classifies_gang_timeout(tmp_path):
    out = supervisor.supervise(
        _nodes(2), [sys.executable, "-c", "import time; time.sleep(120)"],
        policy=supervisor.RestartPolicy(max_restarts=1),
        timeout=2.0, sleep=lambda s: None)
    assert not out.ok and out.gave_up == "budget"
    restarts = [r for r in out.journal if r["event"] == "restart"]
    assert restarts and restarts[0]["cause"] == "timeout"
    assert restarts[0]["timed_out"] is True


# --------------------------------------------------------------------------- #
# checkpoint integrity: manifest checksums + clear structural errors
# --------------------------------------------------------------------------- #

def test_corrupt_latest_checkpoint_falls_back_to_previous(tmp_path):
    from harp_tpu.utils import checkpoint as ck

    c = ck.Checkpointer(str(tmp_path), use_orbax=False, keep=5)
    like = {"w": np.zeros((4, 2)), "b": np.zeros(3)}
    for s in (1, 2, 3):
        c.save(s, {"w": np.full((4, 2), float(s)), "b": np.arange(3.) * s})
    faults.corrupt_latest(str(tmp_path))
    assert c.steps() == [1, 2, 3]             # the dir still lists it...
    assert c.valid_steps() == [1, 2]          # ...but it no longer verifies
    restored = c.restore_latest(like=like)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.full((4, 2), 2.0))
    assert ck.latest_valid_step(str(tmp_path)) == 2


def test_corrupt_orbax_checkpoint_falls_back(tmp_path):
    # the DEFAULT checkpoint format (run.py's) must carry the same manifest
    # guarantee as the numpy fallback: corrupt newest payload -> skipped
    from harp_tpu.utils import checkpoint as ck

    if ck._orbax() is None:
        pytest.skip("orbax not installed")
    c = ck.Checkpointer(str(tmp_path))
    assert c.use_orbax
    like = {"w": np.zeros((8, 4))}
    for s in (1, 2):
        c.save(s, {"w": np.full((8, 4), float(s))})
    assert c.valid_steps() == [1, 2]
    damaged = faults.corrupt_latest(str(tmp_path))
    assert damaged is not None and "step_000000000002" in damaged
    assert not damaged.endswith("manifest.json")
    assert c.valid_steps() == [1]
    restored = c.restore_latest(like=like)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.full((8, 4), 1.0))
    assert ck.latest_valid_step(str(tmp_path)) == 1
    # the supervisor's journaling scan (deep=False) must not pay an orbax
    # re-load per step: an orbax dir's existence counts as complete (the
    # child re-verifies deeply), while npz payloads still CRC-check
    assert ck.latest_valid_step(str(tmp_path), deep=False) == 2


def test_shallow_scan_still_crc_checks_npz(tmp_path):
    # the gang wire format is npz — the supervisor's deep=False journal scan
    # keeps full CRC verification there (cheap, numpy-only), so the journaled
    # resumed_step matches what the relaunched gang actually resumes from
    from harp_tpu.utils import checkpoint as ck

    c = ck.Checkpointer(str(tmp_path), use_orbax=False, keep=5)
    for s in (1, 2):
        c.save(s, {"w": np.full((4, 4), float(s))})
    faults.corrupt_latest(str(tmp_path))
    assert ck.latest_valid_step(str(tmp_path), deep=False) == 1


def test_truncated_npz_fails_verification(tmp_path):
    from harp_tpu.utils import checkpoint as ck

    c = ck.Checkpointer(str(tmp_path), use_orbax=False)
    c.save(1, {"w": np.ones((64, 64))})
    npz = tmp_path / "step_000000000001" / "arrays.npz"
    npz.write_bytes(npz.read_bytes()[:100])   # torn write
    assert not c.verify(1)
    assert c.valid_steps() == []
    assert c.restore_latest(like={"w": np.zeros((64, 64))}) is None


def test_restore_numpy_leaf_count_mismatch_is_clear(tmp_path):
    from harp_tpu.utils.checkpoint import Checkpointer

    c = Checkpointer(str(tmp_path), use_orbax=False)
    c.save(1, {"w": np.ones(2), "b": np.zeros(2)})
    with pytest.raises(ValueError, match="2 arrays.*3 leaves"):
        c.restore(1, like={"w": np.zeros(2), "b": np.zeros(2),
                           "extra": np.zeros(1)})


@pytest.mark.parametrize("use_orbax", [False, True])
def test_restore_latest_valid_mismatch_raises_not_skips(tmp_path, use_orbax):
    # a state-shape mismatch must raise the clear error, NOT be classified
    # as corruption and skipped (which would silently retrain from scratch
    # and eventually prune the old checkpoints)
    from harp_tpu.utils import checkpoint as ck

    if use_orbax and ck._orbax() is None:
        pytest.skip("orbax not installed")
    c = ck.Checkpointer(str(tmp_path), use_orbax=use_orbax)
    c.save(1, {"w": np.ones(2), "b": np.zeros(2)})
    with pytest.raises(ValueError, match="2 arrays.*3 leaves"):
        c.restore_latest_valid(like={"w": np.zeros(2), "b": np.zeros(2),
                                     "extra": np.zeros(1)})


# --------------------------------------------------------------------------- #
# failure-detection satellites: probe hygiene + watchdog without handler
# --------------------------------------------------------------------------- #

def test_probe_threads_are_named_and_capped(monkeypatch):
    import jax

    # a device_put that "hangs" long past the probe deadline (the returned
    # None then errors in the probe thread, which just marks it poisoned)
    monkeypatch.setattr(jax, "device_put", lambda *a, **k: time.sleep(2.0))
    monkeypatch.setattr(failure, "_orphan_probes", set())
    t0 = time.monotonic()
    for _ in range(failure.MAX_ORPHAN_PROBES):
        assert failure.probe_devices(timeout_s=0.01) is False
    names = [t.name for t in threading.enumerate()
             if t.name.startswith("harp-probe-")]
    assert len(names) == failure.MAX_ORPHAN_PROBES
    # cap reached: fails fast with NO new thread
    assert failure.probe_devices(timeout_s=10.0) is False
    assert time.monotonic() - t0 < 5.0
    assert len([t for t in threading.enumerate()
                if t.name.startswith("harp-probe-")]) == \
        failure.MAX_ORPHAN_PROBES


def test_watchdog_keeps_probing_when_no_handler():
    calls = []

    def probe(timeout_s):
        calls.append(1)
        return False

    wd = failure.Watchdog(interval_s=0.01, timeout_s=0.01, on_failure=None,
                          probe=probe)
    wd.start()
    deadline = time.monotonic() + 5.0
    while len(calls) < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert wd._thread.is_alive()              # did not silently stop
    wd.stop()
    assert len(calls) >= 3 and wd.failed
    with pytest.raises(failure.WorkerFailure):
        wd.ok()


def test_watchdog_handler_path_still_stops():
    hits = []
    wd = failure.Watchdog(interval_s=0.01, timeout_s=0.01,
                          on_failure=lambda: hits.append(1),
                          probe=lambda t: False)
    wd.start()
    deadline = time.monotonic() + 5.0
    while not hits and time.monotonic() < deadline:
        time.sleep(0.01)
    wd.stop()
    assert hits == [1]                        # fired once, then stopped


# --------------------------------------------------------------------------- #
# end-to-end: scripted fault -> supervised relaunch -> verified bitwise resume
# --------------------------------------------------------------------------- #

def _km_cmd(work, iterations=4, extra=()):
    return [sys.executable, "-m", "harp_tpu.run", "kmeans", "--cpu-mesh",
            "--num-workers", "1", "--num-points", "64", "--num-centroids",
            "2", "--dim", "4", "--iterations", str(iterations),
            "--work-dir", str(work), "--save-every", "1", *extra]


def test_selfsupervised_fault_run_smoke(tmp_path):
    """Tier-1 smoke: single-process job, scripted crash at epoch 3, one
    supervised relaunch, final model bitwise-equal to an unfaulted run."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("HARP_FAULT", None)
    ref = subprocess.run(_km_cmd(tmp_path / "ref"), env=env, cwd=REPO,
                         capture_output=True, text=True, timeout=300)
    assert ref.returncode == 0, ref.stdout + ref.stderr

    work = tmp_path / "faulted"
    proc = subprocess.run(
        _km_cmd(work, extra=["--max-restarts", "2"]),
        env={**env, "HARP_FAULT": "crash@epoch=3:rank=0"}, cwd=REPO,
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert (work / "centroids.csv").read_bytes() == \
        (tmp_path / "ref" / "centroids.csv").read_bytes()
    restarts = [r for r in _journal(work / "restart_journal.jsonl")
                if r["event"] == "restart"]
    assert len(restarts) == 1
    assert restarts[0]["first_rank"] == 0
    assert restarts[0]["first_rc"] == faults.FAULT_CRASH_EXIT
    assert restarts[0]["resumed_step"] == 2   # crash BEFORE epoch 3 ran
    metrics = json.load(open(work / "supervisor_metrics.json"))
    assert metrics["counters"]["supervisor.recoveries"] == 1


def test_selfsupervised_usage_error_exits_2(tmp_path):
    # a usage error is non-retryable AND its exit code must survive
    # supervision (scripts distinguish rc 2 from job failure rc 1)
    proc = subprocess.run(
        [sys.executable, "-m", "harp_tpu.run", "kmeans",
         "--max-restarts", "2", "--bogus-flag"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2


@pytest.mark.slow
def test_gang_supervisor_acceptance_bitwise(tmp_path):
    """The ISSUE acceptance scenario: HARP_FAULT=crash@epoch=3:rank=1 on a
    2-process gang kmeans job, --save-every 1 --max-restarts 2 — completes
    via ONE supervisor relaunch, centroids bitwise-equal to the unfaulted
    gang run, journal records the failing rank and resumed step."""
    def gang_km(work):
        return [sys.executable, "-m", "harp_tpu.run", "kmeans", "--cpu-mesh",
                "--num-workers", "2", "--num-points", "512",
                "--num-centroids", "4", "--dim", "8", "--iterations", "8",
                "--work-dir", str(work), "--save-every", "1"]

    ref_work = tmp_path / "ref"
    results = launch.launch(_nodes(2), gang_km(ref_work), timeout=420.0,
                            cwd=REPO)
    assert results.ok, list(results)

    work = tmp_path / "faulted"
    env_backup = os.environ.get("HARP_FAULT")
    os.environ["HARP_FAULT"] = "crash@epoch=3:rank=1"
    try:
        out = supervisor.supervise(
            _nodes(2), gang_km(work),
            policy=supervisor.RestartPolicy(max_restarts=2),
            timeout=420.0, cwd=REPO,
            checkpoint_dir=str(work / "ckpt"),
            journal_path=str(work / "restart_journal.jsonl"))
    finally:
        if env_backup is None:
            os.environ.pop("HARP_FAULT", None)
        else:
            os.environ["HARP_FAULT"] = env_backup
    assert out.ok and out.attempts == 2
    assert (work / "centroids.csv").read_bytes() == \
        (ref_work / "centroids.csv").read_bytes()
    restarts = [r for r in _journal(work / "restart_journal.jsonl")
                if r["event"] == "restart"]
    assert len(restarts) == 1
    assert restarts[0]["first_rank"] == 1
    assert restarts[0]["first_rc"] == faults.FAULT_CRASH_EXIT
    assert restarts[0]["resumed_step"] == 2


@pytest.mark.slow
def test_gang_supervisor_corrupt_checkpoint_resume(tmp_path):
    """Corrupt-then-crash plan: epoch 2's checkpoint is damaged before the
    crash, so the relaunch resumes from step 1 (manifest fallback) and still
    finishes bitwise-identical."""
    def gang_km(work):
        return [sys.executable, "-m", "harp_tpu.run", "kmeans", "--cpu-mesh",
                "--num-workers", "2", "--num-points", "256",
                "--num-centroids", "4", "--dim", "8", "--iterations", "6",
                "--work-dir", str(work), "--save-every", "1"]

    ref_work = tmp_path / "ref"
    assert launch.launch(_nodes(2), gang_km(ref_work), timeout=420.0,
                         cwd=REPO).ok

    work = tmp_path / "faulted"
    env_backup = os.environ.get("HARP_FAULT")
    os.environ["HARP_FAULT"] = \
        "ckpt-corrupt@epoch=2:rank=0,crash@epoch=3:rank=1"
    try:
        out = supervisor.supervise(
            _nodes(2), gang_km(work),
            policy=supervisor.RestartPolicy(max_restarts=2),
            timeout=420.0, cwd=REPO,
            checkpoint_dir=str(work / "ckpt"),
            journal_path=str(work / "restart_journal.jsonl"))
    finally:
        if env_backup is None:
            os.environ.pop("HARP_FAULT", None)
        else:
            os.environ["HARP_FAULT"] = env_backup
    assert out.ok
    assert (work / "centroids.csv").read_bytes() == \
        (ref_work / "centroids.csv").read_bytes()
    restarts = [r for r in _journal(work / "restart_journal.jsonl")
                if r["event"] == "restart"]
    assert len(restarts) == 1
    assert restarts[0]["resumed_step"] == 1   # step 2 was corrupt


# --------------------------------------------------------------------------- #
# elastic re-placement: spare pools, vanish classification, shrink-relaunch
# --------------------------------------------------------------------------- #

def _vanish_cmd(rank="1", then="sys.exit(0)"):
    """Gang member: scripted vanish of `rank` on attempt 0, `then` after."""
    return [sys.executable, "-c",
            "import os, sys\n"
            "if os.environ['HARP_GANG_ATTEMPT'] == '0' and "
            f"os.environ['HARP_PROCESS_ID'] == '{rank}':\n"
            "    sys.exit(86)\n"
            + then]


def test_parse_nodes_file_spare_section(tmp_path):
    nodes_file = tmp_path / "nodes"
    nodes_file.write_text("#0\nhostA\nhostB\n#spare\nspare1\n#1\nspare2\n")
    members, spares = launch.parse_nodes_file_with_spares(str(nodes_file))
    assert [n.host for n in members] == ["hostA", "hostB"]
    assert [(n.host, n.rack) for n in spares] == [("spare1", 0),
                                                 ("spare2", 1)]
    # the members-only parser stays back-compatible
    assert launch.parse_nodes_file(str(nodes_file)) == members


def test_ssh_option_construction():
    opts = launch.ssh_options(connect_timeout=7)
    assert opts == ["-o", "BatchMode=yes", "-o", "ConnectTimeout=7",
                    "-o", "ConnectionAttempts=1"]
    # sub-second timeouts still produce a valid (>= 1 s) ssh option
    assert "ConnectTimeout=1" in launch.ssh_options(connect_timeout=0.2)


def test_remote_spawn_uses_bounded_ssh_options(monkeypatch):
    captured = {}

    def fake_popen(argv, **kwargs):
        captured["argv"] = argv

        class P:
            stdout = None
        return P()

    monkeypatch.setattr(launch.subprocess, "Popen", fake_popen)
    launch._spawn(launch.Node("far-host", 0), {"HARP_PROCESS_ID": "0"},
                  ["echo", "hi"])
    argv = captured["argv"]
    assert argv[:2] == ["ssh", "-tt"]
    assert argv[2:8] == launch.ssh_options()
    assert argv[8] == "far-host"


def test_probe_host_bounded_retry():
    calls = []

    def runner(argv, **kwargs):
        calls.append(argv)

        class P:
            returncode = 255
        return P()

    assert launch.probe_host("localhost") is True         # no ssh at all
    assert launch.probe_host("far-host", connect_timeout=1, attempts=2,
                             runner=runner) is False
    assert len(calls) == 2                                # bounded retry
    assert all("ConnectTimeout=1" in " ".join(a) for a in calls)

    def runner_ok(argv, **kwargs):
        class P:
            returncode = 0
        return P()

    assert launch.probe_host("far-host", runner=runner_ok) is True


def test_fault_vanish_kind_parses_and_fires(tmp_path):
    specs = faults.parse_faults("vanish@epoch=2:rank=1", world_size=4)
    assert specs == [faults.FaultSpec("vanish", 2, 1, 0)]
    proc = subprocess.run(
        [sys.executable, "-c",
         "from harp_tpu.parallel import faults\n"
         "for epoch in range(1, 4):\n"
         "    faults.fire(epoch)\n"],
        env={**os.environ, "HARP_FAULT": "vanish@epoch=2:rank=0"},
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert proc.returncode == faults.FAULT_VANISH_EXIT


def test_fault_rank_out_of_range_rejected_loudly():
    with pytest.raises(ValueError, match=r"rank=5 is out of range for "
                                         r"world size 4 \(valid ranks "
                                         r"0\.\.3\)"):
        faults.parse_faults("crash@epoch=1:rank=5", world_size=4)
    with pytest.raises(ValueError, match="rank=-1"):
        faults.parse_faults("crash@epoch=1:rank=-1")
    # world size flows in from the gang env too (fires on every boundary)
    env_backup = dict(os.environ)
    os.environ["HARP_NUM_PROCESSES"] = "2"
    os.environ["HARP_FAULT"] = "crash@epoch=1:rank=3"
    try:
        with pytest.raises(ValueError, match="out of range"):
            faults.fire(1)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


def test_supervise_vanish_replaces_with_spare(tmp_path):
    journal_path = str(tmp_path / "journal.jsonl")
    out = supervisor.supervise(
        _nodes(2), _vanish_cmd(),
        policy=supervisor.RestartPolicy(max_restarts=2, on_suspect="replace"),
        spares=[launch.Node("127.0.0.1", 0)],
        timeout=60.0, journal_path=journal_path, sleep=lambda s: None)
    assert out.ok and out.attempts == 2
    restarts = [r for r in _journal(journal_path) if r["event"] == "restart"]
    assert len(restarts) == 1
    r = restarts[0]
    assert r["cause"] == "vanish" and r["first_rc"] == 86
    # the placement-map schema the journal contract pins
    assert r["placement"] == {"action": "replace", "rank": 1,
                              "reason": "vanish", "old_host": "localhost",
                              "new_host": "127.0.0.1"}
    assert r["hosts"] == ["localhost", "127.0.0.1"] and r["world"] == 2


def test_supervise_unreachable_spare_falls_back_to_shrink(tmp_path):
    journal_path = str(tmp_path / "journal.jsonl")
    out = supervisor.supervise(
        _nodes(2), _vanish_cmd(),
        policy=supervisor.RestartPolicy(max_restarts=2, on_suspect="replace"),
        spares=[launch.Node("dead-spare", 0)],
        probe=lambda host: host != "dead-spare",
        timeout=60.0, journal_path=journal_path, sleep=lambda s: None)
    assert out.ok and out.attempts == 2
    records = _journal(journal_path)
    assert [r["event"] for r in records] == ["spare-unreachable", "restart",
                                            "success"]
    assert records[0]["host"] == "dead-spare"
    r = records[1]
    assert r["placement"]["action"] == "shrink"
    assert r["placement"]["rank"] == 1 and r["world"] == 1


def test_supervise_shrink_relaunches_one_smaller(tmp_path):
    # the relaunched gang must really be one member smaller: the surviving
    # member asserts HARP_NUM_PROCESSES shrank from 3 to 2
    cmd = [sys.executable, "-c",
           "import os, sys, time\n"
           "if os.environ['HARP_GANG_ATTEMPT'] == '0':\n"
           "    if os.environ['HARP_PROCESS_ID'] == '2':\n"
           "        sys.exit(86)\n"
           "    time.sleep(120)\n"      # survivors: killed by fail-stop
           "sys.exit(0 if os.environ['HARP_NUM_PROCESSES'] == '2' else 17)"]
    out = supervisor.supervise(
        _nodes(3), cmd,
        policy=supervisor.RestartPolicy(max_restarts=2, on_suspect="shrink"),
        timeout=60.0, sleep=lambda s: None)
    assert out.ok and out.attempts == 2
    restart = next(r for r in out.journal if r["event"] == "restart")
    assert restart["placement"]["action"] == "shrink"
    assert restart["world"] == 2 and len(restart["hosts"]) == 2


def test_supervise_vanish_with_abort_policy_keeps_shape(tmp_path):
    # default-compatible: on_suspect="abort" relaunches a vanished member at
    # the SAME shape (fail-stop + journal, the PR 1 behavior) — the cause
    # still reads vanish so operators see what happened
    out = supervisor.supervise(
        _nodes(2), _vanish_cmd(),
        policy=supervisor.RestartPolicy(max_restarts=2),
        timeout=60.0, sleep=lambda s: None)
    assert out.ok and out.attempts == 2
    restart = next(r for r in out.journal if r["event"] == "restart")
    assert restart["cause"] == "vanish"
    assert restart["placement"] is None and restart["world"] == 2


def test_supervise_watchdog_suspect_replaced_not_aborted(tmp_path):
    # rank 1 watchdog-dies on attempts 0 and 1 (suspect after 2); with a
    # spare pool the supervisor swaps the node instead of aborting; the
    # member only survives once re-placed (attempt 2)
    cmd = [sys.executable, "-c",
           "import os, sys\n"
           "if os.environ['HARP_PROCESS_ID'] == '1' and "
           "int(os.environ['HARP_GANG_ATTEMPT']) < 2:\n"
           "    sys.exit(98)\n"
           "sys.exit(0)"]
    out = supervisor.supervise(
        _nodes(2), cmd,
        policy=supervisor.RestartPolicy(max_restarts=3, on_suspect="replace",
                                        watchdog_suspect_after=2),
        spares=[launch.Node("127.0.0.1", 0)],
        timeout=60.0, sleep=lambda s: None)
    assert out.ok and out.attempts == 3
    placements = [r["placement"] for r in out.journal
                  if r["event"] == "restart" and r["placement"]]
    assert len(placements) == 1
    assert placements[0]["action"] == "replace"
    assert placements[0]["reason"] == "watchdog"


def test_supervise_drop_stragglers_on_sustained_bsp_suspect(tmp_path):
    # the gang keeps crashing while the telemetry straggler report names
    # rank 1 in bsp_suspects: after straggler_strikes consecutive failures
    # the member is dropped (no spares -> shrink), and the next attempt
    # succeeds
    tele = tmp_path / "tele"
    tele.mkdir()
    (tele / "straggler_report.json").write_text(json.dumps(
        {"suspects": [], "bsp_suspects": [1], "gang_median_p50_s": 0.5,
         "num_ranks": 2, "ts": time.time() + 1e6}))   # stays fresh per attempt
    cmd = [sys.executable, "-c",
           "import os, sys\n"
           "sys.exit(7 if os.environ['HARP_NUM_PROCESSES'] == '2' else 0)"]
    out = supervisor.supervise(
        _nodes(2), cmd,
        policy=supervisor.RestartPolicy(max_restarts=4,
                                        drop_stragglers=True,
                                        straggler_strikes=2),
        telemetry_dir=str(tele),
        timeout=60.0, journal_path=str(tmp_path / "j.jsonl"),
        sleep=lambda s: None)
    assert out.ok and out.attempts == 3
    placements = [r["placement"] for r in out.journal
                  if r["event"] == "restart" and r.get("placement")]
    assert len(placements) == 1
    assert placements[0] == {"action": "shrink", "rank": 1,
                             "reason": "straggler", "old_host": "localhost",
                             "new_host": None}


def test_supervise_single_member_cannot_shrink(tmp_path):
    cmd = [sys.executable, "-c", "import sys; sys.exit(86)"]
    out = supervisor.supervise(
        _nodes(1), cmd,
        policy=supervisor.RestartPolicy(max_restarts=3, on_suspect="shrink"),
        timeout=60.0, sleep=lambda s: None)
    assert not out.ok and out.gave_up == "no-members"
    assert out.journal[-1]["event"] == "abort-no-members"


def test_supervise_rejects_unknown_on_suspect():
    with pytest.raises(ValueError, match="on_suspect"):
        supervisor.supervise(
            _nodes(1), [sys.executable, "-c", "pass"],
            policy=supervisor.RestartPolicy(on_suspect="bogus"),
            timeout=10.0, sleep=lambda s: None)


def test_supervise_stale_straggler_report_never_evicts(tmp_path):
    # a report published BEFORE this attempt started (ts in the past) is
    # attached to the journal as context but earns no eviction strikes — a
    # dead gang's evidence must not drop a member of the relaunched one
    tele = tmp_path / "tele"
    tele.mkdir()
    (tele / "straggler_report.json").write_text(json.dumps(
        {"suspects": [], "bsp_suspects": [1], "gang_median_p50_s": 0.5,
         "num_ranks": 2, "ts": 0.0}))
    out = supervisor.supervise(
        _nodes(2), [sys.executable, "-c", "import sys; sys.exit(7)"],
        policy=supervisor.RestartPolicy(max_restarts=2,
                                        drop_stragglers=True,
                                        straggler_strikes=2),
        telemetry_dir=str(tele),
        timeout=60.0, sleep=lambda s: None)
    assert not out.ok and out.gave_up == "budget"
    restarts = [r for r in out.journal if r["event"] == "restart"]
    assert all(r["placement"] is None for r in restarts)       # no eviction
    assert restarts[0]["straggler"]["bsp_suspects"] == [1]     # but journaled


def test_fault_rank_validation_exempts_disarmed_specs():
    # after a shrink-relaunch the spec that vanished the old top rank is
    # still in the environment: on attempt 1 of the now-1-member gang it is
    # DISARMED (attempt gating), so the range check must not brick the
    # relaunch — while a spec armed for THIS attempt still fails loudly
    env_backup = dict(os.environ)
    os.environ.update({"HARP_NUM_PROCESSES": "1", "HARP_GANG_ATTEMPT": "1",
                       "HARP_FAULT": "vanish@epoch=3:rank=1"})
    try:
        faults.fire(3)                       # disarmed: parses, never fires
        os.environ["HARP_FAULT"] = "vanish@epoch=3:rank=1:attempt=1"
        with pytest.raises(ValueError, match="out of range"):
            faults.fire(3)
    finally:
        os.environ.clear()
        os.environ.update(env_backup)


def test_straggler_strikes_reset_across_intervening_watchdog(tmp_path):
    # the CONSECUTIVE contract survives a vanish/watchdog failure in the
    # middle: attempt 0 names rank 1 (strike 1), attempt 1 is a watchdog
    # death with NO fresh report naming it — the strike must reset, so the
    # budget runs out with rank 1 never evicted
    import time as _time

    tele = tmp_path / "tele"
    tele.mkdir()
    report = {"suspects": [], "bsp_suspects": [1], "gang_median_p50_s": 0.5,
              "num_ranks": 2, "ts": _time.time() + 1e6}
    (tele / "straggler_report.json").write_text(json.dumps(report))
    attempts = {"n": -1}

    def attempt_and_flip_report(*a, **k):
        attempts["n"] += 1
        if attempts["n"] == 1:
            # intervening watchdog death; report no longer names rank 1
            (tele / "straggler_report.json").write_text(json.dumps(
                {**report, "bsp_suspects": []}))
            return launch.GangResult([(98, ""), (0, "")],
                                     first_failure=(0, 98))
        (tele / "straggler_report.json").write_text(json.dumps(
            {**report, "bsp_suspects": [1]}))
        return launch.GangResult([(7, ""), (0, "")], first_failure=(0, 7))

    out = supervisor._supervise(
        attempt_and_flip_report, _nodes(2),
        policy=supervisor.RestartPolicy(max_restarts=3,
                                        drop_stragglers=True,
                                        straggler_strikes=2,
                                        watchdog_suspect_after=5),
        checkpoint_dir=None, journal_path=None, metrics=None,
        metrics_path=None, sleep=lambda s: None, echo=False,
        telemetry_dir=str(tele))
    assert not out.ok and out.gave_up == "budget"
    restarts = [r for r in out.journal if r["event"] == "restart"]
    # named on attempts 0 and 2 but NOT consecutively: never dropped
    assert all(r["placement"] is None for r in restarts), restarts
