"""Lane-packing + one-hot-GEMM scatter engine (ops/lane_pack) parity tests.

Shape coverage mirrors the spd_solve pattern (aligned / needs-padding /
prime): the engine must be exact at lane-aligned shapes, shapes whose token
count needs chunk padding, and prime widths that defeat every divisor
heuristic. The gemm_scatter 'exact_pm1' policy is BITWISE-checked against
``segment_sum`` — 0/1 one-hots and ±1/0 deltas are bf16-representable and
integer sums are exact in the f32 accumulator regardless of reduction order,
which is the whole exactness argument the LDA count write rests on.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.io import datagen
from harp_tpu.models import kmeans as km
from harp_tpu.models import lda, sparse
from harp_tpu.ops import distance, lane_pack, pallas_kernels


# --------------------------------------------------------------------------- #
# gemm_scatter
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("t,width,k,chunk", [
    (256, 128, 32, 64),     # lane-aligned, chunk divides
    (300, 96, 10, 77),      # needs chunk padding (the spd K=10/N=300 shape)
    (997, 13, 7, None),     # prime token count AND prime width
])
def test_gemm_scatter_bitwise_matches_segment_sum(rng, t, width, k, chunk):
    ids = jnp.asarray(rng.integers(0, width, t), jnp.int32)
    delta = jnp.asarray(rng.integers(-1, 2, (t, k)), jnp.float32)  # ±1/0
    got = lane_pack.gemm_scatter(ids, delta, width, chunk=chunk)
    want = jax.ops.segment_sum(delta, ids, num_segments=width)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("b,t,width", [(4, 256, 128), (3, 301, 128),
                                       (5, 97, 11)])
def test_gemm_scatter_batched_matches_per_slice(rng, b, t, width):
    """The batched form (one batched GEMM per chunk — the vocab-sub-block
    LDA scatter) is bitwise the per-slice unbatched scatter."""
    ids = jnp.asarray(rng.integers(0, width, (b, t)), jnp.int32)
    delta = jnp.asarray(rng.integers(-1, 2, (b, t, 6)), jnp.float32)
    got = lane_pack.gemm_scatter(ids, delta, width, chunk=64)
    assert got.shape == (b, width, 6)
    for i in range(b):
        want = lane_pack.gemm_scatter(ids[i], delta[i], width, chunk=64)
        np.testing.assert_array_equal(np.asarray(got[i]), np.asarray(want))


def test_gemm_scatter_f32_policy_for_real_valued_deltas(rng):
    """policy='f32' (the densify/CVB0 route): arbitrary real deltas, f32
    one-hot GEMM — per-cell sums agree with segment_sum to float tolerance
    (the two reduce in different orders)."""
    ids = jnp.asarray(rng.integers(0, 40, 500), jnp.int32)
    delta = jnp.asarray(rng.standard_normal((500, 5)), jnp.float32)
    got = lane_pack.gemm_scatter(ids, delta, 40, policy="f32")
    want = jax.ops.segment_sum(delta, ids, num_segments=40)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gemm_scatter_policy_checks(rng):
    ids = jnp.asarray(rng.integers(0, 8, 16), jnp.int32)
    ok = jnp.ones((16, 2), jnp.float32)
    with pytest.raises(TypeError, match="exact_pm1"):
        # an int delta cannot have been produced under the ±1/0 f32/bf16
        # contract (f64 would be the other offender, but x64-off silently
        # downcasts it before the check can see it)
        lane_pack.gemm_scatter(ids, ok.astype(jnp.int32), 8)
    with pytest.raises(ValueError, match="policy"):
        lane_pack.gemm_scatter(ids, ok, 8, policy="fast_and_wrong")
    with pytest.raises(ValueError, match="trailing K"):
        lane_pack.gemm_scatter(ids, jnp.ones((16,), jnp.float32), 8)
    with pytest.raises(ValueError, match="token axes"):
        lane_pack.gemm_scatter(ids, jnp.ones((15, 2), jnp.float32), 8)


# --------------------------------------------------------------------------- #
# densify_rows
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("b,m,width", [(64, 8, 128), (300, 10, 96),
                                       (31, 7, 13)])
def test_densify_rows_matches_numpy(rng, b, m, width):
    idx = rng.integers(0, width, (b, m))
    vals = rng.standard_normal((b, m)).astype(np.float32)
    want = np.zeros((b, width), np.float32)
    np.add.at(want, (np.arange(b)[:, None], idx), vals)
    got = lane_pack.densify_rows(jnp.asarray(idx), jnp.asarray(vals), width)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6, atol=1e-6)


# --------------------------------------------------------------------------- #
# padding helpers
# --------------------------------------------------------------------------- #

def test_round_up_and_lane_target():
    assert lane_pack.round_up(100, 128) == 128
    assert lane_pack.round_up(128, 128) == 128
    assert lane_pack.round_up(129, 128) == 256
    assert lane_pack.round_up(0, 8) == 8          # never zero-sized
    # lane multiple that still splits over W workers
    assert lane_pack.lane_target(100, divisor=8) == 128
    assert lane_pack.lane_target(100, divisor=3) == 384   # lcm(128, 3)
    assert lane_pack.lane_target(129, divisor=8) == 256
    with pytest.raises(ValueError):
        lane_pack.round_up(4, 0)
    with pytest.raises(ValueError):
        lane_pack.lane_target(4, divisor=-1)


def test_pad_rows_cols_and_mask(rng):
    a = jnp.asarray(rng.standard_normal((10, 100)), jnp.float32)
    p = lane_pack.pad_rows(a, 16)
    assert p.shape == (16, 100) and np.all(np.asarray(p[10:]) == 0)
    assert lane_pack.pad_rows(a, 10) is a          # no-op, no copy
    q = lane_pack.pad_cols(a, 128)
    assert q.shape == (10, 128) and np.all(np.asarray(q[:, 100:]) == 0)
    assert lane_pack.pad_cols(a, 100) is a
    with pytest.raises(ValueError):
        lane_pack.pad_rows(a, 9)
    s = lane_pack.mask_phantom_cols(a, 60)
    assert np.all(np.isinf(np.asarray(s)[:, 60:]))
    np.testing.assert_array_equal(np.asarray(s)[:, :60], np.asarray(a)[:, :60])
    assert lane_pack.mask_phantom_cols(a, 100) is a


def test_scatter_chunk_budget_and_divisors():
    # divisor near the budget is preferred (no per-call pad concat)
    assert 1000 % lane_pack.scatter_chunk(1000, 64) == 0
    # large prime token count: falls back to the budget size
    c = lane_pack.scatter_chunk(1000003, 8192)
    assert c == (64 * 1024 * 1024) // (2 * 8192)
    # batch multiplies the transient: chunk shrinks accordingly (prime
    # token count so the divisor preference cannot kick in)
    assert (lane_pack.scatter_chunk(1000003, 128, batch=64)
            == (64 * 1024 * 1024) // (2 * 128 * 64))
    # ... and with a composite count, a nearby divisor wins instead
    assert 10**9 % lane_pack.scatter_chunk(10**9, 128, batch=64) == 0
    assert lane_pack.scatter_chunk(0, 128) == 1


def test_sub_block_split():
    slots = jnp.asarray([0, 127, 128, 300], jnp.int32)
    sub, within = lane_pack.sub_block_split(slots)
    np.testing.assert_array_equal(np.asarray(sub), [0, 0, 1, 2])
    np.testing.assert_array_equal(np.asarray(within), [0, 127, 0, 44])


# --------------------------------------------------------------------------- #
# call-site parity: the engine IS the implementation behind all three users
# --------------------------------------------------------------------------- #

def test_lda_subblock_ns1_is_bitwise_the_flat_layout(session):
    """vocab_sub_block == vpb (NS=1): identical token layout and chunk, so
    the batched engine path must reproduce the flat gemm_scatter trajectory
    BITWISE — the engine-vs-inline equivalence proof at the model level."""
    docs = datagen.lda_corpus(num_docs=64, vocab=96, num_topics=4,
                              doc_len=24, seed=6)
    cfg = lda.LDAConfig(num_topics=4, vocab=96, epochs=6,
                        wt_access="gemm_scatter")
    base = lda.LDA(session, cfg).fit(docs, seed=3)
    sub = lda.LDA(session, dataclasses.replace(
        cfg, vocab_sub_block=12)).fit(docs, seed=3)   # vpb = 96/8 = 12
    for a, b in zip(base, sub):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_lda_subblock_multi_sub_converges_and_conserves_counts(session):
    """NS > 1 re-orders tokens (different draws, statistically equivalent
    chain): counts stay exactly conserved and the likelihood improves."""
    docs = datagen.lda_corpus(num_docs=64, vocab=96, num_topics=4,
                              doc_len=24, seed=6)
    model = lda.LDA(session, lda.LDAConfig(
        num_topics=4, vocab=96, epochs=15, wt_access="gemm_scatter",
        vocab_sub_block=4))                           # vpb=12 -> NS=3
    dt, wt, ll = model.fit(docs, seed=3)
    assert model.last_layout_stats["sub_blocks_per_block"] == 3
    assert np.isclose(dt.sum(), docs.size, atol=1e-2)
    assert np.isclose(wt.sum(), docs.size, atol=1e-2)
    assert np.all(np.isfinite(ll)) and ll[-1] > ll[0]


def test_lda_subblock_config_validation(session):
    with pytest.raises(ValueError, match="vocab_sub_block"):
        lda.LDA(session, lda.LDAConfig(method="cvb0", vocab_sub_block=128))
    with pytest.raises(ValueError, match="vocab_sub_block"):
        lda.LDA(session, lda.LDAConfig(wt_access="gather",
                                       vocab_sub_block=128))


def test_kmeans_lane_pad_matches_unpadded_trajectory(session):
    """128-lane padding (phantom centroids masked, zero feature columns) is
    a layout change, not a math change: same trajectory as lane_pad=False
    and as the numpy reference."""
    pts = datagen.dense_points(1000, 100, seed=7, num_clusters=10)
    cen0 = datagen.initial_centroids(pts, 10, seed=3)
    outs = {}
    for lp in (True, False):
        cfg = km.KMeansConfig(10, 100, 8, "regroupallgather", lane_pad=lp)
        cen, costs = km.KMeans(session, cfg).fit(pts, cen0)
        assert cen.shape == (10, 100)
        outs[lp] = (np.asarray(cen), np.asarray(costs))
    np.testing.assert_allclose(outs[True][0], outs[False][0],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(outs[True][1], outs[False][1], rtol=1e-5)
    ref = km.numpy_reference(pts.astype(np.float64),
                             cen0.astype(np.float64), 8)
    np.testing.assert_allclose(outs[True][0], ref, rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("comm", km.COMM_VARIANTS)
def test_kmeans_lane_pad_all_variants_agree(session, comm):
    """Cross-variant bit-identity survives lane padding (every variant pads
    the same way, phantoms average to zero everywhere)."""
    pts = datagen.dense_points(400, 17, seed=11, num_clusters=5)
    cen0 = datagen.initial_centroids(pts, 5, seed=5)
    cfg = km.KMeansConfig(5, 17, 5, comm, lane_pad=True)
    cen, _ = km.KMeans(session, cfg).fit(pts, cen0)
    base_cfg = km.KMeansConfig(5, 17, 5, "regroupallgather", lane_pad=True)
    base, _ = km.KMeans(session, base_cfg).fit(pts, cen0)
    np.testing.assert_allclose(np.asarray(cen), np.asarray(base),
                               rtol=1e-5, atol=1e-6, err_msg=comm)


def test_partial_sums_counts_valid_k_masks_phantoms(rng):
    """The E-step with a lane-padded centroid table (+ valid_k) returns the
    unpadded stats exactly, phantom rows all-zero."""
    x = jnp.asarray(rng.standard_normal((256, 100)), jnp.float32)
    c = jnp.asarray(rng.standard_normal((10, 100)), jnp.float32)
    s_ref, n_ref, cost_ref = distance.partial_sums_counts(x, c)
    # phantom rows are ZERO — without masking they'd WIN points (score 0
    # beats positive scores), which is exactly what valid_k prevents
    c_pad = lane_pack.pad_rows(c, 128)
    s, n, cost = distance.partial_sums_counts(x, c_pad, valid_k=10)
    # counts are exact integers; sums agree to float tolerance (the wider
    # output lets XLA re-tile the N-reduction — ulp-level differences)
    np.testing.assert_allclose(np.asarray(s[:10]), np.asarray(s_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(n[:10]), np.asarray(n_ref))
    assert np.all(np.asarray(s[10:]) == 0) and np.all(np.asarray(n[10:]) == 0)
    np.testing.assert_allclose(float(cost), float(cost_ref), rtol=1e-6)
    # feature padding is an exact no-op
    x_pad = lane_pack.pad_cols(x, 128)
    c_pad2 = lane_pack.pad_cols(c_pad, 128)
    s2, n2, cost2 = distance.partial_sums_counts(x_pad, c_pad2, valid_k=10)
    np.testing.assert_allclose(np.asarray(s2[:10, :100]), np.asarray(s_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(n2[:10]), np.asarray(n_ref))
    assert np.all(np.asarray(s2[:, 100:]) == 0)


def test_pallas_kmeans_kernel_valid_k_interpret(rng):
    """The fused pallas E-step masks lane-padding phantoms in-kernel
    (interpret mode; zero phantom rows would otherwise capture points —
    the old 1e6-fill is gone, masking is scale-independent)."""
    x = jnp.asarray(rng.standard_normal((128, 16)), jnp.float32)
    c = jnp.asarray(rng.standard_normal((6, 16)), jnp.float32)
    s_ref, n_ref, cost_ref = distance.partial_sums_counts(x, c)
    c_pad = lane_pack.pad_rows(c, 16)
    sums, counts, cost = pallas_kernels.kmeans_stats_pallas(
        x, c_pad, block_n=32, interpret=True, valid_k=6)
    np.testing.assert_allclose(np.asarray(sums[:6]), np.asarray(s_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(counts[:6]), np.asarray(n_ref),
                               rtol=1e-6)
    assert np.all(np.asarray(counts[6:]) == 0)
    np.testing.assert_allclose(float(cost), float(cost_ref), rtol=1e-4)


def test_sparse_kmeans_densify_rides_engine(session, rng):
    """CSR K-means 'densify' (now on lane_pack.densify_rows) still matches
    the dense trajectory on the equivalent matrix."""
    n, d, kk = 96, 24, 4
    dense = (rng.random((n, d)) * (rng.random((n, d)) < 0.3)).astype(
        np.float32)
    rows, cols = np.nonzero(dense)
    vals = dense[rows, cols]
    cen0 = dense[:kk].copy()
    model = sparse.SparseKMeans(session, sparse.SparseKMeansConfig(
        kk, d, 5, strategy="densify"))
    cen_sp, _ = model.fit(rows, cols, vals, n, cen0)
    ref = km.numpy_reference(dense.astype(np.float64),
                             cen0.astype(np.float64), 5)
    np.testing.assert_allclose(cen_sp, ref, rtol=1e-3, atol=1e-4)
