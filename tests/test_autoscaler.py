"""Autoscaler tests (ISSUE 16 tentpole layer 4): fleet scale_up/scale_down
mechanics through the versioned-placement push, the controller's
hysteresis/cooldown policy against a scripted fleet, and a compact live
ramp where the worker count follows the load up AND back down.
"""

import threading
import time

import numpy as np
import pytest

from harp_tpu.serve import fleet as fleet_mod
from harp_tpu.serve.autoscaler import Autoscaler
from harp_tpu.serve.router import local_gang
from harp_tpu.utils.metrics import Metrics

OP_TOPK = "topk"


def _specs(n, users=24, items=12, rank=4, k=3):
    return {f"m{i}": {"kind": "topk", "num_users": users,
                      "num_items": items, "rank": rank, "k": k, "seed": i}
            for i in range(n)}


def _gang_and_fleet(session, n_models=2, metrics=None, **gang_kw):
    specs = _specs(n_models)
    eps = {name: fleet_mod.build_endpoint(session, name, sp)
           for name, sp in specs.items()}
    workers, mk = local_gang(session, [eps], max_wait_s=0.005,
                             client_rank_base=1000, metrics=metrics,
                             **gang_kw)

    def builder(name, version):
        return fleet_mod.build_endpoint(session, name, specs[name],
                                        version=version, restore=True)

    fleet = fleet_mod.LocalFleet(workers, mk, endpoint_builder=builder,
                                 metrics=metrics)
    refs = {}
    for name, sp in specs.items():
        uf, vf = fleet_mod.topk_factors(sp, 0)
        refs[name] = fleet_mod.topk_reference(uf, vf, sp["k"])
    return fleet, specs, refs


# --------------------------------------------------------------------------- #
# Fleet mechanics: the moves land through the versioned-placement push
# --------------------------------------------------------------------------- #

def test_fleet_scale_up_and_down_through_versioned_placement(session):
    m = Metrics()
    fleet, specs, refs = _gang_and_fleet(session, n_models=2, metrics=m)
    client = fleet.make_client()
    try:
        assert client.request_retry(OP_TOPK, "m1", 4,
                                    timeout=30.0)["items"] == refs["m1"][4]
        w = fleet.scale_up(["m1"])
        assert fleet.worker_count() == 2
        assert fleet.placement["m1"] == w.rank != fleet.placement["m0"]
        # the move is journaled with the placement version it pushed and
        # the fresh endpoint's trace ledger (0 at install — nothing ran)
        up = next(r for r in fleet.journal.records
                  if r["event"] == "scale-up")
        assert up["models"] == ["m1"] and up["placement_version"] >= 1
        assert up["trace_counts"] == {"m1": 0}
        assert m.counters["fleet.scale_ups"] == 1
        assert m.gauges["fleet.workers"] == 2
        # existing AND fresh clients serve correct answers off the new map
        for u in (0, 7):
            assert client.request_retry(OP_TOPK, "m1", u,
                                        timeout=30.0)["items"] == \
                refs["m1"][u]
        fresh = fleet.make_client()
        try:
            assert fresh.request_retry(OP_TOPK, "m1", 2,
                                       timeout=30.0)["items"] == \
                refs["m1"][2]
        finally:
            fresh.close()
        # ...and back down: the victim's models re-home onto a survivor
        moved = fleet.scale_down(w.rank)
        assert fleet.worker_count() == 1
        assert moved == {"m1": fleet.placement["m1"]}
        assert fleet.placement["m1"] != w.rank
        down = next(r for r in fleet.journal.records
                    if r["event"] == "scale-down")
        assert down["rank"] == w.rank
        assert m.counters["fleet.scale_downs"] == 1
        for name in specs:
            assert client.request_retry(OP_TOPK, name, 5,
                                        timeout=30.0)["items"] == \
                refs[name][5]
    finally:
        client.close()
        fleet.close()


def test_fleet_scale_up_warms_from_aot_store(session, tmp_path):
    # the elastic worker must LOAD its dispatches, not compile them: the
    # store is keyed by spec hash (warm_artifacts' convention), so the
    # fleet forwards aot_model_hashes to the minted ServeWorker — without
    # them every load would silently miss into a warm-compile
    from harp_tpu.aot import serve_artifacts

    specs = _specs(2)
    aot_dir = str(tmp_path / "store")
    fleet_mod.warm_artifacts(specs, aot_dir, session=session)
    eps = {name: fleet_mod.build_endpoint(session, name, sp)
           for name, sp in specs.items()}
    m = Metrics()
    workers, mk = local_gang(session, [eps], max_wait_s=0.005,
                             client_rank_base=1000, metrics=m)

    def builder(name, version):
        return fleet_mod.build_endpoint(session, name, specs[name],
                                        version=version, restore=True)

    fleet = fleet_mod.LocalFleet(
        workers, mk, endpoint_builder=builder, metrics=m, aot_dir=aot_dir,
        aot_model_hashes={name: serve_artifacts.model_hash_from_spec(sp)
                          for name, sp in specs.items()})
    client = fleet.make_client()
    try:
        fleet.scale_up(["m1"])
        up = next(r for r in fleet.journal.records
                  if r["event"] == "scale-up")
        # every bucket loaded, zero traces — the never-recompile contract
        # extended to the demand-driven elastic path
        assert up["trace_counts"] == {"m1": 0}
        assert up["aot_loaded"]["m1"] >= 1
        uf, vf = fleet_mod.topk_factors(specs["m1"], 0)
        ref = fleet_mod.topk_reference(uf, vf, specs["m1"]["k"])
        for u in (0, 9):
            assert client.request_retry(OP_TOPK, "m1", u,
                                        timeout=30.0)["items"] == ref[u]
        # served off the loaded executables: still untraced
        new_w = fleet._workers[max(fleet._workers)]
        assert sum(new_w.endpoints["m1"].trace_counts.values()) == 0
    finally:
        client.close()
        fleet.close()


def test_fleet_scale_requires_builder(session):
    specs = _specs(1)
    eps = {"m0": fleet_mod.build_endpoint(session, "m0", specs["m0"])}
    workers, mk = local_gang(session, [eps], client_rank_base=1000)
    fleet = fleet_mod.LocalFleet(workers, mk)    # no endpoint_builder
    try:
        with pytest.raises(RuntimeError, match="endpoint_builder"):
            fleet.scale_up(["m0"])
    finally:
        fleet.close()


# --------------------------------------------------------------------------- #
# Policy: hysteresis streaks, cooldown, LIFO victim, journaled skips
# --------------------------------------------------------------------------- #

class _FakeWorker:
    def __init__(self, rank):
        self.rank = rank


class _FakeFleet:
    """A scripted fleet: moves mutate the placement instantly, so the
    controller's decisions are observable without sockets or a mesh."""

    def __init__(self, placement):
        self.metrics = Metrics()
        self.placement = dict(placement)
        self.records = []
        self.up_calls, self.down_calls = [], []
        self._next = max(placement.values(), default=-1) + 1

    def worker_count(self):
        return len(set(self.placement.values())) or 1

    def workers(self):
        return [_FakeWorker(r) for r in sorted(set(self.placement.values()))]

    def _journal(self, rec):
        self.records.append(rec)

    def scale_up(self, models):
        rank, self._next = self._next, self._next + 1
        for name in models:
            self.placement[name] = rank
        self.up_calls.append(list(models))
        return _FakeWorker(rank)

    def scale_down(self, rank):
        survivors = sorted(set(self.placement.values()) - {rank})
        moved = {}
        for name, r in self.placement.items():
            if r == rank:
                self.placement[name] = moved[name] = survivors[0]
        self.down_calls.append(rank)
        return moved


def _idle_controller(fleet, **kw):
    """A controller whose own thread effectively never ticks — the test
    drives _tick() by hand for deterministic decisions."""
    kw.setdefault("poll_interval_s", 3600.0)
    kw.setdefault("cooldown_s", 0.0)
    return Autoscaler(fleet, **kw)


def test_policy_up_streak_hysteresis_and_cooldown():
    fleet = _FakeFleet({"a": 0, "b": 0})
    asc = _idle_controller(fleet, up_streak=2, cooldown_s=10.0,
                           max_workers=4)
    try:
        fleet.metrics.gauge("serve.queue_depth.a", 9.0)
        fleet.metrics.gauge("serve.queue_depth.b", 3.0)
        asc._tick()                              # streak 1: no move yet
        assert fleet.up_calls == []
        asc._tick()                              # streak 2: move, hottest
        assert fleet.up_calls == [["a"]]         # model leaves the donor
        acts = [r["action"] for r in asc.trajectory()]
        assert acts == ["scale-up"]
        # cooldown: still overloaded, but the fresh worker gets its grace
        asc._tick()
        asc._tick()
        assert fleet.up_calls == [["a"]]
        # one noisy healthy poll RESETS the streak (hysteresis)
        asc2 = _idle_controller(_FakeFleet({"a": 0, "b": 0}), up_streak=2)
        try:
            asc2.fleet.metrics.gauge("serve.queue_depth.a", 9.0)
            asc2._tick()
            asc2.fleet.metrics.gauge("serve.queue_depth.a", 0.0)
            asc2._tick()                         # signal broke: reset
            asc2.fleet.metrics.gauge("serve.queue_depth.a", 9.0)
            asc2._tick()                         # streak back to 1 only
            assert asc2.fleet.up_calls == []
        finally:
            asc2.close()
    finally:
        asc.close()


def test_policy_shed_delta_and_burning_are_overload_signals():
    fleet = _FakeFleet({"a": 0, "b": 0, "c": 0})
    asc = _idle_controller(fleet, up_streak=1, max_workers=4)
    try:
        asc._tick()                              # baseline counters
        fleet.metrics.count("serve.shed.a", 5)
        asc._tick()                              # shed delta > 0: overload
        assert fleet.up_calls == [["a"]]
        asc._tick()                              # delta back to 0: no move
        assert len(fleet.up_calls) == 1
        fleet.metrics.gauge("slo.burning", 1.0)
        asc._tick()                              # burn state: overload
        assert len(fleet.up_calls) == 2          # b/c still share a donor
    finally:
        asc.close()


def test_policy_down_lifo_min_workers_and_skip_up():
    fleet = _FakeFleet({"a": 0, "b": 1, "c": 2})
    asc = _idle_controller(fleet, down_streak=2, min_workers=1,
                           up_streak=1, max_workers=4)
    try:
        # idle (no depth gauges, no sheds, no burn): two polls shrink,
        # and the victim is the HIGHEST rank (LIFO unwind)
        asc._tick()
        asc._tick()
        assert fleet.down_calls == [2]
        asc._tick()
        asc._tick()
        assert fleet.down_calls == [2, 1]
        # min_workers floor: never below
        for _ in range(4):
            asc._tick()
        assert fleet.down_calls == [2, 1]
    finally:
        asc.close()
    # overload with NO multi-model donor: journaled skip, no move
    lone = _FakeFleet({"a": 0, "b": 1})
    asc2 = _idle_controller(lone, up_streak=1, max_workers=4)
    try:
        lone.metrics.gauge("serve.queue_depth.a", 50.0)
        asc2._tick()
        assert lone.up_calls == []
        assert [r["action"] for r in asc2.trajectory()][-1] == "skip-up"
    finally:
        asc2.close()


def test_policy_never_strips_donor_bare():
    fleet = _FakeFleet({"a": 0, "b": 0, "c": 0})
    asc = _idle_controller(fleet, up_streak=1, models_per_move=5,
                           max_workers=4)
    try:
        fleet.metrics.gauge("serve.queue_depth.a", 9.0)
        fleet.metrics.gauge("serve.queue_depth.b", 8.0)
        fleet.metrics.gauge("serve.queue_depth.c", 1.0)
        asc._tick()
        # asked for 5, donor owns 3: at most 2 move (hottest first), the
        # donor keeps one — a bare donor would just invert the imbalance
        assert fleet.up_calls == [["a", "b"]]
    finally:
        asc.close()


def test_controller_survives_a_failing_move():
    class _Exploding(_FakeFleet):
        def scale_up(self, models):
            raise RuntimeError("builder exploded")

    fleet = _Exploding({"a": 0, "b": 0})
    asc = Autoscaler(fleet, poll_interval_s=0.01, up_streak=1,
                     cooldown_s=0.0, max_workers=4)
    try:
        fleet.metrics.gauge("serve.queue_depth.a", 9.0)
        deadline = time.time() + 5.0
        while (fleet.metrics.counters.get("fleet.autoscale.errors", 0) < 1
               and time.time() < deadline):
            time.sleep(0.01)
        # the loop journaled the error and KEPT RUNNING
        assert fleet.metrics.counters["fleet.autoscale.errors"] >= 1
        assert any(r["action"] == "error" for r in asc.trajectory())
        assert asc._thread.is_alive()
    finally:
        asc.close()


# --------------------------------------------------------------------------- #
# Live ramp: the worker count follows the load up AND back down
# --------------------------------------------------------------------------- #

def test_autoscaler_follows_a_live_ramp_up_and_down(session):
    m = Metrics()
    fleet, specs, refs = _gang_and_fleet(session, n_models=3, metrics=m,
                                         max_queue=64)
    stop = threading.Event()
    failures, served = [], [0]

    def load(tid):
        c = fleet.make_client()
        rng = np.random.default_rng(tid)
        while not stop.is_set():
            name = f"m{rng.integers(0, 3)}"
            u = int(rng.integers(0, 24))
            try:
                r = c.request_retry(OP_TOPK, name, u, timeout=10.0,
                                    attempts=8, backoff_max_s=0.5)
                if r["items"] != refs[name][u]:
                    failures.append((name, u, "wrong", r["items"]))
                served[0] += 1
            except Exception as e:  # noqa: BLE001 — the tally IS the gate
                failures.append((name, u, repr(e)))
        c.close()

    asc = Autoscaler(fleet, metrics=m, poll_interval_s=0.05, up_depth=4.0,
                     down_depth=0.5, up_streak=2, down_streak=10,
                     cooldown_s=0.5, max_workers=3, models_per_move=1)
    threads = [threading.Thread(target=load, args=(i,)) for i in range(10)]
    for t in threads:
        t.start()
    try:
        peak, t0 = 1, time.monotonic()
        while time.monotonic() - t0 < 30.0:
            peak = max(peak, fleet.worker_count())
            if peak >= 2:
                break
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(30.0)
        assert peak >= 2, \
            f"never scaled up under the ramp ({asc.trajectory()})"
        # ramp subsided: the controller unwinds to one worker
        t1 = time.monotonic()
        while time.monotonic() - t1 < 30.0 and fleet.worker_count() > 1:
            time.sleep(0.1)
        deadline = time.time() + 10.0
        while (not any(r["action"] == "scale-down"
                       for r in asc.trajectory())
               and time.time() < deadline):
            time.sleep(0.05)
        assert fleet.worker_count() == 1, asc.trajectory()
        assert not failures, failures[:5]
        assert served[0] > 30
        acts = [r["action"] for r in asc.trajectory()]
        assert "scale-up" in acts and "scale-down" in acts
    finally:
        stop.set()
        asc.close()
        fleet.close()
