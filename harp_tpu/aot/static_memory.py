"""Static memory estimator — liveness analysis over traced jaxprs.

The model-mall planning input (ISSUE 19): ``serve.resident_bytes`` is a
runtime gauge, and donation is a comment-level promise jax silently drops
on any aliasing mismatch. This module computes, from a ``jax.make_jaxpr``
trace alone (no execution, no compile), the numbers a multi-tenant mall
must reason about BEFORE placing a program:

* ``resident_arg_bytes`` — the input footprint: every program argument and
  closed-over constant, summed over abstract values. For a serving
  dispatch this is exactly ``Endpoint.resident_bytes()`` plus the placed
  query buffer (tier-1 cross-checks the two).
* ``peak_live_bytes`` — the liveness peak: each variable is live from its
  defining equation to its last use (program inputs from equation 0,
  program outputs to the end), and the peak is the largest byte sum of any
  equation's live set, recursively including sub-jaxpr interiors (scan /
  while / cond / pjit bodies contribute ``max(0, sub peak − sub args)`` on
  top of the enclosing live set — branches of one cond never coexist, so
  subprograms take a max, not a sum).
* ``transient_peak_ratio`` — ``peak / resident``, the static twin of the
  reshard engine's chunk budget: an accidental full-gather/broadcast
  materialization shows up as this ratio exploding long before it OOMs on
  real HBM.

This is a static MODEL, not an XLA allocator simulation: XLA may fuse away
intermediates the model charges, and buffer assignment may hold inputs the
model retires early. What matters for the gate is that the model is
deterministic for a given jaxpr — the pinned rows move exactly when the
traced program moves, which is the same contract the collective-budget
rows already enforce for wire bytes.

The donation audit rides the same trace: a ``pjit`` equation's
``donated_invars`` mark buffers the caller promised to XLA, but XLA only
honors a donation whose aval (shape + dtype) matches an output's — an
unmatched donation is SILENTLY dropped (jax emits only a warning), and the
"reused" buffer quietly doubles. :func:`dropped_donations` reproduces the
lowering's greedy aval match and returns every donation that cannot alias
any output.

Used by the AOT store (per-artifact memory rows in the meta — metadata,
never a key axis) and by ``tools/jaxlint/checkers_memory.py`` (the JL4xx
engine that pins the rows in ``tools/collective_budget.json``).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, NamedTuple, Tuple

RATIO_DIGITS = 4     # manifest rows round the ratio so exact-equality
#                      drift checks are stable across float printers


def aval_bytes(aval) -> int:
    """Bytes of one abstract value (0 for tokens/opaque avals)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for s in shape:
        n *= int(s)
    return n * dtype.itemsize


def _var_bytes(var) -> int:
    return aval_bytes(getattr(var, "aval", None))


def _subjaxprs(eqn) -> Iterator:
    """Raw sub-jaxprs of one equation (ClosedJaxpr params unwrap to their
    inner jaxpr; consts are handled by the caller via ``_sub_consts``)."""
    for v in eqn.params.values():
        items = v if isinstance(v, (list, tuple)) else [v]
        for item in items:
            if hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr
            elif hasattr(item, "eqns") and not hasattr(item, "jaxpr"):
                yield item


def _sub_closed(eqn) -> Iterator[Tuple[object, list]]:
    """(jaxpr, consts) pairs for one equation's sub-programs."""
    for v in eqn.params.values():
        items = v if isinstance(v, (list, tuple)) else [v]
        for item in items:
            if hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr, list(getattr(item, "consts", []))
            elif hasattr(item, "eqns") and not hasattr(item, "jaxpr"):
                yield item, []


class Liveness(NamedTuple):
    peak_live_bytes: int
    resident_arg_bytes: int
    peak_eqn_index: int          # -1 when the peak IS the argument set
    peak_eqn_primitive: str      # "" when peak_eqn_index == -1


def analyze_liveness(jaxpr) -> Liveness:
    """Liveness over one (raw) jaxpr — module docstring's model."""
    invars = list(jaxpr.constvars) + list(jaxpr.invars)
    resident = sum(_var_bytes(v) for v in invars)
    n = len(jaxpr.eqns)
    # last use per var: program outputs live to the end; a defined-but-
    # unused result is still materialized AT its defining equation
    last: Dict[object, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if hasattr(v, "aval") and not hasattr(v, "val"):
                last[v] = i
    for v in jaxpr.outvars:
        if hasattr(v, "aval") and not hasattr(v, "val"):
            last[v] = n
    spans: List[Tuple[int, int, int]] = []     # (start, end, bytes)
    for v in invars:
        spans.append((0, last.get(v, -1), _var_bytes(v)))
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            spans.append((i, max(last.get(v, i), i), _var_bytes(v)))
    # prefix-sum the live bytes per equation index
    delta = [0] * (n + 2)
    for start, end, b in spans:
        if end < start or b == 0:
            continue
        delta[start] += b
        delta[end + 1] -= b
    live = [0] * max(n, 1)
    acc = 0
    for i in range(n):
        acc += delta[i]
        live[i] = acc
    peak, peak_i, peak_prim = resident, -1, ""
    for i, eqn in enumerate(jaxpr.eqns):
        extra = 0
        for sub in _subjaxprs(eqn):
            sub_res = analyze_liveness(sub)
            sub_args = sum(_var_bytes(v) for v in
                           list(sub.constvars) + list(sub.invars))
            # interior headroom beyond what the enclosing live set already
            # charges for the operands; max across subs — cond branches /
            # while phases never coexist
            extra = max(extra, max(0, sub_res.peak_live_bytes - sub_args))
        if live[i] + extra > peak:
            peak, peak_i = live[i] + extra, i
            peak_prim = eqn.primitive.name
    return Liveness(peak, resident, peak_i, peak_prim)


def memory_row(closed) -> dict:
    """The manifest/artifact row for one ``ClosedJaxpr``: resident bytes,
    peak live bytes, and the rounded transient ratio."""
    res = analyze_liveness(closed.jaxpr)
    # closed-over consts are resident too — they are baked into the
    # program's HBM footprint exactly like arguments (for a make_jaxpr
    # trace they surface as constvars, already counted; top-level consts
    # carried on the ClosedJaxpr are the same vars, so nothing is added
    # twice — constvars and consts are index-aligned)
    peak = res.peak_live_bytes
    resident = res.resident_arg_bytes
    ratio = round(peak / resident, RATIO_DIGITS) if resident else 0.0
    return {
        "resident_arg_bytes": resident,
        "peak_live_bytes": peak,
        "transient_peak_ratio": ratio,
    }


class CapturedConst(NamedTuple):
    nbytes: int
    shape: Tuple[int, ...]
    dtype: str


def captured_consts(closed) -> List[CapturedConst]:
    """Every closed-over constant baked into the traced program,
    recursively (top-level ClosedJaxpr consts plus inner pjit/closed-call
    consts) — the JL403 surface: each one is duplicated HBM per program
    AND a retrace hazard (a new closure constant is a new program)."""
    out: List[CapturedConst] = []

    def note(consts):
        for c in consts:
            b = int(getattr(c, "nbytes", 0) or 0)
            if b:
                out.append(CapturedConst(
                    b, tuple(int(s) for s in getattr(c, "shape", ())),
                    str(getattr(c, "dtype", ""))))

    def walk(jaxpr, consts):
        note(consts)
        for eqn in jaxpr.eqns:
            for sub, sub_consts in _sub_closed(eqn):
                walk(sub, sub_consts)

    walk(closed.jaxpr, list(closed.consts))
    return out


class DroppedDonation(NamedTuple):
    jit_name: str        # the pjit's `name` param (the traced fn's name)
    aval: str            # the donated-but-unaliasable buffer's aval
    nbytes: int


def dropped_donations(closed) -> List[DroppedDonation]:
    """Donated buffers that cannot alias ANY output (module docstring):
    walks every pjit equation, greedily matches each output aval
    (shape + dtype, in output order — the lowering's own matching) against
    the still-unclaimed donated inputs, and returns the leftovers. A
    non-empty result means XLA drops those donations with only a warning:
    the caller believes the buffer is reused; it is actually doubled."""
    out: List[DroppedDonation] = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pjit":
                don = eqn.params.get("donated_invars") or ()
                if any(don):
                    unmatched = [v.aval for v, d in zip(eqn.invars, don)
                                 if d]
                    for o in eqn.outvars:
                        oa = o.aval
                        for di in unmatched:
                            if (di.shape == oa.shape
                                    and di.dtype == oa.dtype):
                                unmatched.remove(di)
                                break
                    name = str(eqn.params.get("name", "<jit>"))
                    for u in unmatched:
                        out.append(DroppedDonation(
                            name, str(u), aval_bytes(u)))
            for sub in _subjaxprs(eqn):
                walk(sub)

    walk(closed.jaxpr)
    return out
