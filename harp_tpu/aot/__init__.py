"""AOT dispatch artifacts — compiled programs as first-class, shippable
files (ISSUE 15, the ROADMAP "AOT dispatch artifacts" item).

Harp's execution model is long-running resident workers; PR 14 made ours
an elastic fleet — and made the cost of a COLD resident visible: the
committed recovery blip is dominated by spare jax start + first-dispatch
compile. This package takes the SNIPPETS.md eval_shape→compiled-resident-fn
pattern to its conclusion, the way DrJAX (arXiv:2403.07128) treats
staged-out programs as reusable first-class artifacts rather than
per-process compile events:

* :mod:`~harp_tpu.aot.store` — the artifact store: every resident serving
  dispatch (and any step program) is exported ONCE via ``jax.export``
  (serialized-executable bytes where export is unsupported) and written
  keyed by (name, world, layout, jax version, device kind, model hash).
  A later process LOADS instead of compiling; every key-axis mismatch is
  a LOUD, metered miss (``aot.store.miss_<reason>``) that falls back to
  the compile path — a stale artifact can never be served silently.
* :mod:`~harp_tpu.aot.serve_artifacts` — the serving glue: export every
  (model, bucket) resident dispatch of an endpoint; install store hits
  into a fresh endpoint's compiled-fn cache so the replacement worker
  never traces (``trace_counts`` stays 0 for artifact-loaded buckets —
  asserted, not hoped), and optionally WARM each loaded bucket before the
  worker rendezvouses.
* :mod:`~harp_tpu.aot.manifest` — the pinned compiled-program manifest
  (``tools/artifact_manifest.json``): content hashes of the registry's
  exported programs, checked by jaxlint the way collective budgets are —
  a silently changed compiled program is a CI finding;
  ``--update-artifacts`` regenerates.
* :mod:`~harp_tpu.aot.cache` — jax's persistent compilation cache wired
  as a one-call helper (``--compile-cache-dir`` on every run.py
  subcommand, ``ServeWorker(compile_cache_dir=)``): distinct from and
  composable with the export path — export kills the TRACE, the compile
  cache kills the XLA compile of whatever still lowers.
"""

from __future__ import annotations

from harp_tpu.aot.cache import enable_compile_cache
from harp_tpu.aot.store import (ArtifactKey, ArtifactStore, device_kind,
                                layout_of)

__all__ = [
    "ArtifactKey", "ArtifactStore", "device_kind", "enable_compile_cache",
    "layout_of",
]
