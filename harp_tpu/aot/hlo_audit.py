"""Post-SPMD lowered-HLO audit — what the compiler actually emits.

Every byte contract the repo enforces (the JL2xx collective budgets, the
JL4xx memory rows) is pinned at the **jaxpr** level: `jax.make_jaxpr`
records the collectives the PROGRAM asked for. But the XLA SPMD
partitioner is free to insert all-gathers, reshards, and full replication
*after* tracing — EQuARX (arXiv:2506.17615) shows the real wire behavior
of XLA collectives is decided exactly at this layer. A program whose
jaxpr is budget-clean can still compile into one that all-gathers a whole
factor table per step, and nothing in the traced contract would notice.

This module closes that gap statically (ISSUE 20): it lowers an
already-traced program through ``jax.jit(...).lower(...).compile()`` —
compilation only, **no execution** — and parses the post-partitioning
optimized HLO module text for

* **compiler-emitted collectives** (``all-gather`` / ``all-reduce`` /
  ``collective-permute`` / ``all-to-all`` / ``reduce-scatter``): counts,
  result-shape bytes, and the shapes themselves, per op kind;
* **cost-row scalars**: total instruction count and while-body count —
  the coarse "did the compiled program grow an op / a loop" signal the
  artifact-manifest hash flags without explaining;
* **entry-parameter shapes**: the per-device blocks the partitioner
  actually compiled each input to — an operand DECLARED sharded that
  compiles at its GLOBAL shape was silently replicated (the static
  signature of a full broadcast).

Conventions: HLO collective bytes are the op's RESULT shape bytes (what
the op materializes — for all-reduce/collective-permute/all-to-all this
equals the operand payload; for all-gather it is the gathered result, for
reduce-scatter the scattered one). This deliberately differs from the
jaxpr engine's operand-bytes convention: the two sections pin different
layers and are never diffed number-for-number — JL501 diffs *kinds*, and
JL502 pins the compiled rows against themselves over time.

Used by ``tools/jaxlint/checkers_hlo.py`` (the JL5xx engine) and by the
AOT store (per-artifact ``hlo`` meta rows — metadata, never a key axis,
exactly like the r20 ``memory`` rows).
"""

from __future__ import annotations

import re
from typing import Dict, List, NamedTuple, Optional, Tuple

# the HLO ops that move bytes between devices post-partitioning. The
# -start/-done async split (TPU) books the op once, at its -start.
HLO_COLLECTIVE_OPS = (
    "all-gather", "all-reduce", "all-to-all", "collective-permute",
    "reduce-scatter", "collective-broadcast",
)

# jaxpr collective primitive -> the HLO op kinds it legitimately lowers
# to. An HLO collective kind in the compiled module with NO traced jaxpr
# primitive mapping to it is COMPILER-INSERTED (JL501): the partitioner
# added communication the traced contract never showed.
JAXPR_TO_HLO: Dict[str, Tuple[str, ...]] = {
    # deliberately sharp: a psum maps to all-reduce ONLY. A backend that
    # decomposes it into reduce-scatter + all-gather changed the wire
    # pattern, and that is exactly what JL501 exists to surface — every
    # committed target compiles its psums to plain all-reduce (verified
    # over both registries), so the sharp mapping costs nothing here and
    # catches the decomposition the day a backend introduces it.
    "psum": ("all-reduce",),
    "pmin": ("all-reduce",),
    "pmax": ("all-reduce",),
    "all_gather": ("all-gather",),
    "all_to_all": ("all-to-all",),
    "reduce_scatter": ("reduce-scatter",),
    "psum_scatter": ("reduce-scatter",),
    "ppermute": ("collective-permute",),
    "pshuffle": ("collective-permute",),
    "pbroadcast": ("collective-broadcast", "all-gather"),
    "pgather": ("all-gather",),
    # fused ring-DMA hops: on the CPU tracing mesh the engine lowers them
    # through lax_ops.rotate (ops/ring_dma fallback), i.e. ppermute
    "fused_dma": ("collective-permute",),
}

# why would the partitioner insert this op kind? The inferred cause a
# JL501 finding carries — the three GSPMD insertion families.
INSERTED_CAUSE = {
    "all-gather": "a sharded operand was resharded to REPLICATED (the "
                  "silent full-broadcast signature — GSPMD gathers the "
                  "whole array onto every device)",
    "all-reduce": "partial-sum completion: an unreduced partial result "
                  "crossed a sharding boundary and the partitioner "
                  "finished the reduction itself",
    "collective-permute": "a resharding between mismatched shardings "
                          "(shard rotation / halo exchange inserted by "
                          "the partitioner)",
    "all-to-all": "a sharded-axis transpose resharding (the partitioned "
                  "dim moved to a different axis)",
    "reduce-scatter": "a reduce+reshard combination the partitioner "
                      "fused in place of the traced pattern",
    "collective-broadcast": "a single-device value was broadcast to the "
                            "full mesh by the partitioner",
}

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "s4": 1, "u4": 1, "f8e4m3fn": 1, "f8e5m2": 1,
}

# numpy/jax dtype name -> HLO dtype token (for matching declared arg
# shardings against compiled entry parameters)
_NP_TO_HLO = {
    "float32": "f32", "float64": "f64", "bfloat16": "bf16",
    "float16": "f16", "int32": "s32", "int64": "s64", "int16": "s16",
    "int8": "s8", "uint8": "u8", "uint16": "u16", "uint32": "u32",
    "uint64": "u64", "bool": "pred", "complex64": "c64",
    "complex128": "c128",
}

# one HLO instruction line: `  %name.1 = <shape> op-name(...)` — shape is
# a typed array (`f32[8,2]{1,0}`) or a tuple of them
_SHAPE_RE = r"(?:\([^)]*\)|[\w\[\],]+(?:\{[^}]*\})?)"
_INSTR_RE = re.compile(
    r"^\s+(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(" + _SHAPE_RE + r")\s+"
    r"([\w\-]+)\(", re.MULTILINE)
_ARRAY_SHAPE_RE = re.compile(r"([a-z]\w*)\[([\d,]*)\]")


class HloShape(NamedTuple):
    dtype: str                  # HLO dtype token ("f32", "s32", ...)
    dims: Tuple[int, ...]

    @property
    def nbytes(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n * _DTYPE_BYTES.get(self.dtype, 0)

    def __str__(self) -> str:
        return f"{self.dtype}[{','.join(str(d) for d in self.dims)}]"


def parse_shapes(text: str) -> List[HloShape]:
    """Every array shape in one HLO type string (a tuple type yields each
    element; tokens and opaque types yield nothing)."""
    out = []
    for m in _ARRAY_SHAPE_RE.finditer(text):
        dtype, dims = m.group(1), m.group(2)
        if dtype not in _DTYPE_BYTES:
            continue              # token[] / opaque[] carry no bytes
        out.append(HloShape(
            dtype, tuple(int(d) for d in dims.split(",") if d)))
    return out


def shape_bytes(text: str) -> int:
    return sum(s.nbytes for s in parse_shapes(text))


def iter_instructions(hlo_text: str):
    """(result-type text, op name) for every instruction in the module,
    async ``-start``/``-done`` pairs normalized: the ``-start`` books the
    op under its base name, the ``-done`` is skipped (one transfer, one
    count)."""
    for m in _INSTR_RE.finditer(hlo_text):
        shape_txt, op = m.group(1), m.group(2)
        if op.endswith("-done"):
            continue
        if op.endswith("-start"):
            op = op[: -len("-start")]
        yield shape_txt, op


def collective_stats(hlo_text: str) -> Dict[str, dict]:
    """``{op: {"count", "bytes", "shapes"}}`` over the compiled module —
    bytes are result-shape bytes (module docstring's convention)."""
    out: Dict[str, dict] = {}
    for shape_txt, op in iter_instructions(hlo_text):
        if op not in HLO_COLLECTIVE_OPS:
            continue
        row = out.setdefault(op, {"count": 0, "bytes": 0, "shapes": []})
        row["count"] += 1
        row["bytes"] += shape_bytes(shape_txt)
        row["shapes"].append(
            "+".join(str(s) for s in parse_shapes(shape_txt)) or "()")
    return out


def instruction_count(hlo_text: str) -> int:
    return sum(1 for _ in iter_instructions(hlo_text))


def while_count(hlo_text: str) -> int:
    return sum(1 for _shape, op in iter_instructions(hlo_text)
               if op == "while")


def hlo_row(hlo_text: str) -> dict:
    """The pinned manifest/artifact row for one compiled module: per-kind
    collective counts and bytes, total collective bytes, instruction
    count, while-body count (JL502's contract — exact equality, like the
    jaxpr byte rows)."""
    stats = collective_stats(hlo_text)
    return {
        "collectives": {op: s["count"] for op, s in sorted(stats.items())},
        "collective_bytes": {op: s["bytes"]
                             for op, s in sorted(stats.items())},
        "collective_bytes_total": sum(s["bytes"] for s in stats.values()),
        "instruction_count": instruction_count(hlo_text),
        "while_count": while_count(hlo_text),
    }


# -- lowering ---------------------------------------------------------------


def lower_closed(closed, args):
    """Compile one already-traced ``ClosedJaxpr`` at its placed args —
    the post-SPMD module for a program the trace cache already holds.
    Compilation only: nothing executes, no output buffer is ever
    materialized."""
    import jax

    # jaxpr_as_fun takes the FLAT invars; the cached args are the original
    # pytrees (make_jaxpr flattened them in tree-leaf order)
    flat = jax.tree_util.tree_leaves(args)
    fn = jax.core.jaxpr_as_fun(closed)
    return jax.jit(fn).lower(*flat).compile()


def compiled_text(compiled) -> str:
    return compiled.as_text()


def lower_fn_text(fn, args) -> str:
    """Post-SPMD module text for a live callable (the AOT export path:
    the endpoint's compiled dispatch is already a jit)."""
    import jax

    lowered = (fn.lower(*args) if hasattr(fn, "lower")
               else jax.jit(fn).lower(*args))
    return lowered.compile().as_text()


def hlo_row_for(fn, args) -> dict:
    """``hlo_row`` of a live callable — the per-artifact meta row the AOT
    store records (metadata, never a key axis)."""
    return hlo_row(lower_fn_text(fn, args))


# -- JL501: compiler-inserted collectives -----------------------------------


class InsertedCollective(NamedTuple):
    op: str                     # HLO op kind
    count: int
    bytes: int
    shapes: Tuple[str, ...]
    cause: str                  # inferred GSPMD insertion family


def expected_hlo_kinds(jaxpr_counts: Dict[str, int]) -> set:
    """The HLO collective kinds the traced jaxpr accounts for."""
    kinds = set()
    for prim, n in jaxpr_counts.items():
        if n:
            kinds.update(JAXPR_TO_HLO.get(prim, ()))
    return kinds


def inserted_collectives(hlo_text: str, jaxpr_counts: Dict[str, int],
                         ) -> List[InsertedCollective]:
    """Compiled collective kinds the traced program never asked for —
    each one is communication the SPMD partitioner inserted after
    tracing, invisible to every jaxpr-level budget (JL501)."""
    allowed = expected_hlo_kinds(jaxpr_counts)
    out = []
    for op, s in sorted(collective_stats(hlo_text).items()):
        if op in allowed:
            continue
        out.append(InsertedCollective(
            op, s["count"], s["bytes"], tuple(s["shapes"][:4]),
            INSERTED_CAUSE.get(op, "unmapped compiler-side insertion")))
    return out


# -- JL503: sharding-propagation audit --------------------------------------


_ENTRY_RE = re.compile(r"^ENTRY\s+%?[\w.\-]+\s*\((.*?)\)\s*->",
                       re.MULTILINE | re.DOTALL)
_PARAM_RE = re.compile(r"[\w.\-]+:\s*([a-z]\w*\[[\d,]*\](?:\{[^}]*\})?)")


def entry_param_shapes(hlo_text: str) -> List[HloShape]:
    """The compiled entry computation's parameter shapes — per-DEVICE
    blocks after partitioning (what each device actually holds)."""
    m = _ENTRY_RE.search(hlo_text)
    if m is None:
        return []
    return [s for p in _PARAM_RE.finditer(m.group(1))
            for s in parse_shapes(p.group(1))]


class ReplicatedOperand(NamedTuple):
    dtype: str
    global_shape: Tuple[int, ...]
    declared_shard: Tuple[int, ...]
    nbytes: int                 # the global (replicated) footprint


def declared_shard_shapes(args) -> List[Tuple[str, Tuple[int, ...],
                                              Tuple[int, ...]]]:
    """``(hlo dtype, global shape, declared per-device shard shape)`` for
    every placed argument leaf (host arrays count as replicated)."""
    import jax

    out = []
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        shape = tuple(int(s) for s in shape)
        hlo_dt = _NP_TO_HLO.get(str(dtype), str(dtype))
        sharding = getattr(leaf, "sharding", None)
        shard = shape
        if sharding is not None:
            try:
                shard = tuple(int(s) for s in sharding.shard_shape(shape))
            except (TypeError, ValueError):
                shard = shape
        out.append((hlo_dt, shape, shard))
    return out


def replicated_where_sharded(hlo_text: str, args,
                             ) -> List[ReplicatedOperand]:
    """Operands DECLARED sharded that the partitioner compiled at their
    GLOBAL shape (JL503): the entry parameter carries the full array on
    every device — a silent full replication that multiplies the operand's
    HBM footprint by the mesh width and usually rides an inserted
    all-gather on the wire.

    Matching is by (dtype, shape) MULTISET, not position — the compiled
    entry's parameter order is not the argument order. A declared shard
    shape missing from the compiled parameters while the same operand's
    GLOBAL shape shows up in the surplus is the replication signature;
    any other mismatch (a const-folded parameter the compiler dropped) is
    conservatively ignored."""
    from collections import Counter

    declared = declared_shard_shapes(args)
    got = Counter((s.dtype, s.dims) for s in entry_param_shapes(hlo_text))
    expect = Counter((dt, shard) for dt, _g, shard in declared)
    missing = expect - got
    surplus = got - expect
    out = []
    for dt, gshape, shard in declared:
        if shard == gshape:
            continue                       # declared replicated: fine
        if missing.get((dt, shard), 0) <= 0:
            continue                       # compiled at its shard shape
        if surplus.get((dt, gshape), 0) <= 0:
            continue                       # dropped/reshaped, not gathered
        missing[(dt, shard)] -= 1
        surplus[(dt, gshape)] -= 1
        n = 1
        for d in gshape:
            n *= d
        out.append(ReplicatedOperand(
            dt, gshape, shard, n * _DTYPE_BYTES.get(dt, 0)))
    return out
