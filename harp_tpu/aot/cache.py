"""jax persistent compilation cache — one-call wiring (ISSUE 15 satellite).

Distinct from and composable with the export-artifact path: an export
artifact kills the TRACE (the Python body never runs on load), but its
shipped StableHLO still XLA-compiles once per process; the persistent
compilation cache turns that compile — and every other compile the process
performs, artifact-backed or not — into a disk load. A fleet pointing every
worker's ``--compile-cache-dir`` at shared storage pays each distinct
program's compile exactly once, fleet-wide.

jax gates cache writes on minimum compile time / entry size by default
(tuned for large programs); serving dispatches at tier-1 shapes compile in
milliseconds, so :func:`enable_compile_cache` zeroes both floors — the
point here is cold-start latency, not disk economy.
"""

from __future__ import annotations

import logging
from typing import Optional

LOG = logging.getLogger("harp_tpu.aot")

_enabled_dir: Optional[str] = None


def enable_compile_cache(directory: Optional[str]) -> bool:
    """Point jax's persistent compilation cache at ``directory`` (created
    if missing). Returns whether the cache is active. ``None``/empty is a
    no-op returning False — every CLI flag funnels through here, unset
    included. Idempotent; a second call with a DIFFERENT directory
    re-points the cache (jax re-reads the config per compile)."""
    global _enabled_dir
    if not directory:
        return False
    import os

    import jax

    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    # zero the write floors: serving dispatches are small and fast to
    # compile — exactly the programs a cold start pays for one by one
    for knob, value in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, value)
        except AttributeError:
            # an older/newer jax without this knob: the cache still works
            # at its default floor — log once, keep going
            LOG.info("compile cache: config %s unavailable on jax %s",
                     knob, jax.__version__)
    # jax latches its cache decision at the FIRST compile of the process
    # (sticky _cache_initialized/_cache_checked flags): a process that
    # already compiled anything before this call — a serving worker
    # enabling the cache at ctor time inside a long-lived controller —
    # would silently keep the cache off without this reset
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except (ImportError, AttributeError):
        LOG.info("compile cache: reset_cache unavailable on jax %s — "
                 "cache activates only if nothing compiled yet",
                 jax.__version__)
    _enabled_dir = directory
    return True


def active_dir() -> Optional[str]:
    """The directory the cache was last enabled at (None = never)."""
    return _enabled_dir
