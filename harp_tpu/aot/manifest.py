"""Pinned compiled-program manifest — ``tools/artifact_manifest.json``.

The collective-budget idea applied to whole programs (ISSUE 15): the
budget manifest pins what a step program COMMUNICATES; this manifest pins
what the exported program IS. Every registry target below is exported at
tier-1 shapes on the 8-worker virtual CPU mesh and content-hashed over its
lowered StableHLO module text (deterministic per jax version/platform —
verified cross-process). jaxlint checks the hashes the way it checks byte
budgets: a silently changed compiled program — a dispatch gaining an op, a
sharding drift, an optimization barrier appearing — is a CI finding naming
the target, and ``--update-artifacts`` regenerates the manifest so the
change is COMMITTED deliberately, diff-reviewed like a budget row.

Registry: the serving dispatches of the fleet's deterministic tier-1
models (every bucket of the top-k and classify endpoints — the exact
programs ``aot warm`` ships and a spare loads) plus two model STEP
programs (K-means regroupallgather, SGD-MF dense rotation) exported
through the same store path — the "step programs as artifacts" half of the
tentpole, pinned at the same shapes the budget manifest traces.

The manifest also records the jax version / device kind / world it was
pinned under; a checker running anywhere else reports ONE clear re-pin
finding instead of N bogus hash drifts.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Tuple

from harp_tpu.aot import serve_artifacts
from harp_tpu.aot.store import ArtifactStore, layout_of

MANIFEST_REL = os.path.join("tools", "artifact_manifest.json")
NUM_WORKERS = 8                # the tier-1 virtual mesh (conftest/jaxlint)

# the fleet-shaped deterministic serving models (same tier-1 shapes the
# serving_fleet bench and chaos smoke run): spec IS model identity
SERVE_MODELS: Dict[str, dict] = {
    "mf": {"kind": "topk", "num_users": 64, "num_items": 32, "rank": 8,
           "k": 3, "seed": 7},
    "nn": {"kind": "classify_nn", "dim": 12, "classes": 3, "layers": [8],
           "seed": 1},
}


def _session():
    from harp_tpu.session import HarpSession

    return HarpSession(num_workers=NUM_WORKERS)


def _rng():
    import numpy as np

    return np.random.default_rng(0)


def _step_kmeans() -> Tuple[Callable, tuple]:
    from harp_tpu.models import kmeans as km

    sess = _session()
    model = km.KMeans(sess, km.KMeansConfig(8, 16, iterations=2,
                                            comm="regroupallgather"))
    pts = _rng().normal(size=(64, 16)).astype("float32")
    p, c = model.prepare(pts, pts[:8].copy())
    return model._fit, (p, c)


def _step_sgd_mf() -> Tuple[Callable, tuple]:
    from harp_tpu.models import sgd_mf

    sess = _session()
    cfg = sgd_mf.SGDMFConfig(rank=8, lam=0.01, lr=0.1, epochs=2,
                             minibatches_per_hop=2)
    model = sgd_mf.SGDMF(sess, cfg)
    rng = _rng()
    n = 400
    rows = rng.integers(0, 64, size=n)
    cols = rng.integers(0, 48, size=n)
    vals = rng.normal(size=n).astype("float32")
    layout, data, w0, h0, meta = model.prepare(rows, cols, vals, 64, 48)
    key = model._program(layout, cfg.minibatches_per_hop, cfg.epochs,
                         meta[6])
    return model._compiled[key], (*data, w0, h0)

STEP_PROGRAMS: Dict[str, Callable] = {
    "step/kmeans_regroupallgather": _step_kmeans,
    "step/sgd_mf_dense": _step_sgd_mf,
}


def manifest_path(root: str) -> str:
    return os.path.join(root, MANIFEST_REL)


def export_registry(store: ArtifactStore) -> Dict[str, dict]:
    """Export every registry target into ``store``; returns
    ``{name: meta}`` — the rows the manifest pins. Serving endpoints are
    built from their deterministic specs (every bucket exported); step
    programs export their prepared compiled fn + placed args."""
    from harp_tpu.serve import fleet as fleet_mod

    out: Dict[str, dict] = {}
    sess = _session()
    for model, mspec in SERVE_MODELS.items():
        ep = fleet_mod.build_endpoint(sess, model, mspec)
        metas = serve_artifacts.export_endpoint(
            store, ep,
            model_hash=serve_artifacts.model_hash_from_spec(mspec))
        for bucket, meta in metas.items():
            out[serve_artifacts.dispatch_name(model, bucket)] = meta
    for name, build in STEP_PROGRAMS.items():
        fn, args = build()
        from harp_tpu.aot.store import ArtifactKey

        key = ArtifactKey(name=name, world=NUM_WORKERS,
                          layout=layout_of(args),
                          model_hash=serve_artifacts.model_hash_from_spec(
                              {"step": name}))
        out[name] = store.export_and_put(key, fn, args)
    return out


def build_rows(workdir: str) -> Dict[str, dict]:
    """Export the registry into ``workdir`` and distill the manifest rows
    (content hash + format + size per target)."""
    metas = export_registry(ArtifactStore(workdir))
    return {name: {"content_hash": m["content_hash"],
                   "format": m["format"],
                   "payload_bytes": m["payload_bytes"]}
            for name, m in sorted(metas.items())}


def write(root: str, rows: Dict[str, dict]) -> str:
    from harp_tpu.aot.store import device_kind, jax_version

    path = manifest_path(root)
    with open(path, "w") as f:
        json.dump({
            "_comment": "Pinned compiled-program hashes (harp_tpu/aot/"
                        "manifest.py registry, tier-1 shapes, 8-worker "
                        "virtual mesh). content_hash = sha256 of the "
                        "exported StableHLO module text. Checked by "
                        "jaxlint; regenerate DELIBERATELY with "
                        "`python -m tools.jaxlint --update-artifacts`.",
            "jax_version": jax_version(),
            "device_kind": device_kind(),
            "world": NUM_WORKERS,
            "artifacts": rows,
        }, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def update(root: str, workdir: str) -> str:
    return write(root, build_rows(workdir))


def check(root: str, workdir: str) -> List[str]:
    """Re-export the registry and diff against the committed manifest.
    Returns finding strings (empty = clean): hash drift (the compiled
    program changed — commit it deliberately via --update-artifacts),
    unpinned target (registry grew without re-pinning), stale manifest row
    (registry shrank), or an environment mismatch (ONE re-pin finding)."""
    from harp_tpu.aot.store import device_kind, jax_version

    path = manifest_path(root)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except OSError:
        return [f"artifact manifest missing at {path} — run "
                f"`python -m tools.jaxlint --update-artifacts`"]
    env = {"jax_version": jax_version(), "device_kind": device_kind(),
           "world": NUM_WORKERS}
    for axis, running in env.items():
        pinned = manifest.get(axis)
        if pinned != running:
            return [
                f"artifact manifest was pinned under {axis}={pinned!r} "
                f"but this environment runs {running!r} — exported "
                f"programs are environment-specific; re-pin with "
                f"--update-artifacts on the CI environment"]
    rows = build_rows(workdir)
    pinned_rows = manifest.get("artifacts", {})
    findings = []
    for name, row in rows.items():
        pin = pinned_rows.get(name)
        if pin is None:
            findings.append(
                f"artifact target {name!r} is not pinned in the manifest "
                f"— new registry targets must be committed "
                f"(--update-artifacts)")
        elif pin.get("content_hash") != row["content_hash"]:
            findings.append(
                f"artifact {name!r} compiled-program hash drifted: "
                f"manifest pins {pin.get('content_hash', '')[:12]}…, "
                f"freshly exported program hashes "
                f"{row['content_hash'][:12]}… — the resident program "
                f"CHANGED; commit it deliberately (--update-artifacts) "
                f"or find the regression")
    for name in pinned_rows:
        if name not in rows:
            findings.append(
                f"manifest pins {name!r} but the registry no longer "
                f"exports it — stale row; --update-artifacts")
    return findings
