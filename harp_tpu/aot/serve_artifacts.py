"""Serving-plane glue: export / load / warm resident dispatches.

The serving analog of the store (ISSUE 15 tentpole): every
(model, bucket) resident dispatch an :class:`~harp_tpu.serve.endpoints.
Endpoint` holds is one exportable program. ``aot warm`` (run.py) calls
:func:`export_endpoint` offline; a starting worker calls
:func:`load_endpoint` — fresh store hits are INSTALLED into the endpoint's
compiled-fn cache (``Endpoint.install_compiled``), so the first dispatch
replays shipped StableHLO instead of tracing: ``trace_counts`` stays 0 for
every loaded bucket, and the endpoint's never-recompile assertion keeps it
that way under live traffic.

``warm=True`` additionally dispatches each loaded bucket once on an EMPTY
placed query before returning — the XLA compile of the shipped module (and
anything the persistent compilation cache serves) happens BEFORE the worker
rendezvouses, so an elastic replacement's first real request pays a warm
dispatch, nothing else.

Artifact identity: the store key's ``layout`` axis is derived from the
actual dispatch signature (``Endpoint.dispatch_args``), so any resident
reshape — a rebalance's owner-routed layout, a different bucket set, a
re-sharded state arg — is automatically a different artifact. The
``model_hash`` axis carries the model identity: fleet workers pass
:func:`model_hash_from_spec` (the deterministic spec IS the model);
spec-less endpoints default to a structural hash of the endpoint itself.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Optional

from harp_tpu.aot.store import ArtifactKey, ArtifactStore, layout_of


def dispatch_name(model: str, bucket: int, *,
                  owner_routed: bool = False) -> str:
    """Store name of one (model, bucket) dispatch. The owner-routed
    (post-rebalance) program is a different artifact by name AND layout —
    the suffix keeps the store listing readable."""
    return f"serve/{model}/b{bucket}" + ("-routed" if owner_routed else "")


def model_hash_from_spec(mspec: dict) -> str:
    """Content hash of a deterministic fleet model spec — every process
    that regenerates the model from the same spec shares the hash, so the
    initial worker's artifacts serve every later spare. A changed spec
    (new shape, new seed, new kind) is a changed model: miss_model_hash."""
    return hashlib.sha256(
        json.dumps(mspec, sort_keys=True).encode()).hexdigest()


def endpoint_model_hash(ep) -> str:
    """Structural fallback hash for endpoints built without a spec: the
    endpoint class, name, bucket set, and its model-shape attributes.
    Coarser than a spec hash (two different factor TABLES of the same
    shape share it — the layout axis still matches, and factor values are
    state, not program), which is exactly right: the artifact is the
    PROGRAM."""
    ident = {"class": type(ep).__name__, "name": ep.name,
             "buckets": list(ep.bucket_sizes)}
    for attr in ("k", "num_items", "_dim", "dim"):
        v = getattr(ep, attr, None)
        if isinstance(v, (int, float)):
            ident[attr] = v
    return hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()).hexdigest()


def _key(ep, bucket: int, args, model_hash: Optional[str]) -> ArtifactKey:
    return ArtifactKey(
        name=dispatch_name(ep.name, bucket,
                           owner_routed=getattr(ep, "_owner_routed", False)),
        world=ep.session.num_workers,
        layout=layout_of(args),
        model_hash=model_hash or endpoint_model_hash(ep),
        # the quant axis (ISSUE 17): an f32-keyed artifact is a LOUD
        # metered miss_quant for an int8 endpoint, never a silent install
        quant=getattr(ep, "quant", None) or "f32")


def export_endpoint(store: ArtifactStore, ep, *,
                    model_hash: Optional[str] = None,
                    buckets=None) -> Dict[int, dict]:
    """Export every bucket's resident dispatch into the store (the
    offline ``aot warm`` path — this TRACES each bucket in the exporting
    process, which is the whole point: the trace happens here, once, not
    in every cold worker). Returns ``{bucket: meta}``."""
    import jax

    from harp_tpu.aot import hlo_audit, static_memory

    out = {}
    for bucket in (ep.bucket_sizes if buckets is None else buckets):
        fn = ep.compiled(bucket)
        args = ep.dispatch_args(bucket)
        # the static memory row rides along as placement metadata (never
        # a key axis): the mall reads resident/peak bytes off the meta
        # without deserializing the program. The compiled-HLO cost row
        # (ISSUE 20) rides the same way — what the partitioner actually
        # emits for this dispatch, readable without deserializing
        mem = static_memory.memory_row(jax.make_jaxpr(fn)(*args))
        hlo = hlo_audit.hlo_row_for(fn, args)
        out[bucket] = store.export_and_put(
            _key(ep, bucket, args, model_hash), fn, args, memory=mem,
            hlo=hlo)
    return out


def load_endpoint(store: ArtifactStore, ep, *,
                  model_hash: Optional[str] = None, warm: bool = True,
                  warm_missing: bool = False) -> List[int]:
    """Install every fresh store hit into the endpoint; returns the loaded
    buckets (sorted). Misses fall back to the lazy compile path untouched
    — unless ``warm_missing``, which builds and warms the missed buckets
    NOW (tracing them — the spare path's "never serve cold" completion:
    with a populated store nothing misses and nothing traces; with a stale
    one, the compile still lands before rendezvous instead of under
    traffic)."""
    import jax

    # collective warm dispatches (top-k) must not overlap other collective
    # programs on the shared mesh — same gate the live dispatch path holds
    from contextlib import nullcontext

    from harp_tpu.serve.endpoints import _COLLECTIVE_GATE
    gate = (_COLLECTIVE_GATE if getattr(ep, "collective_dispatch", False)
            else nullcontext())
    loaded = []
    try:
        args0 = ep.dispatch_args(ep.bucket_sizes[0])
    except (NotImplementedError, ValueError) as e:
        # an endpoint that cannot describe its own dispatch signature (a
        # ClassifyEndpoint built without dim=, a custom subclass without
        # _dummy_batch) keeps the lazy compile path it always had — a
        # worker that served fine without AOT must still start WITH it;
        # the skip is metered and logged like a store miss
        store.metrics.count("aot.store.skip_unfingerprintable")
        import logging

        logging.getLogger("harp_tpu.aot").warning(
            "endpoint %r cannot build its dispatch signature (%s) — "
            "AOT load skipped, lazy compile path kept", ep.name, e)
        return loaded
    for bucket in ep.bucket_sizes:
        args = (args0 if bucket == ep.bucket_sizes[0]
                else ep.dispatch_args(bucket))
        hit = store.load(_key(ep, bucket, args, model_hash))
        if hit is None:
            if warm_missing:
                with gate:
                    jax.block_until_ready(ep.compiled(bucket)(
                        *ep.dispatch_args(bucket)))
            continue
        fn, _meta = hit
        ep.install_compiled(bucket, fn)
        loaded.append(bucket)
        if warm:
            # one empty-query dispatch: the shipped module's XLA compile
            # (or compile-cache load) happens here, pre-rendezvous; the
            # dummy args are rebuilt because the loaded jit holds no
            # donation contract but the compile-path twin above does
            with gate:
                jax.block_until_ready(fn(*ep.dispatch_args(bucket)))
    return loaded
