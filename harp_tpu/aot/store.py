"""Artifact store — serialized compiled programs keyed by everything that
could invalidate them.

One artifact = one exported program (a resident serving dispatch, a model
step program) written as two files under the store root::

    <root>/<name>.json      # the key + content hash + format (the meta)
    <root>/<name>.bin       # the serialized program bytes (the payload)

``name`` may contain ``/`` (e.g. ``serve/mf/b8``) — artifacts nest in
subdirectories. Writes are tmp+rename atomic (the rendezvous-file idiom),
so a concurrent reader can never see a torn artifact.

The KEY is the invalidation matrix (ISSUE 15 satellite): an artifact is
only served when every axis matches the loading process —

* ``jax_version``  — StableHLO/runtime compatibility is jax's contract
  per version; a mismatched load is rejected (``miss_jax_version``);
* ``device_kind``  — a program exported for one accelerator generation
  must not run on another (``miss_device_kind``);
* ``world``        — the mesh width baked into the program
  (``miss_world``);
* ``quant``        — the resident quant mode the program was exported
  under (``"f32"`` or ``"int8"`` — ISSUE 17): an int8 artifact must never
  warm an f32 endpoint or vice versa (``miss_quant``). Checked BEFORE
  layout so a pure quant flip names itself instead of surfacing as the
  layout drift its dtype shift also causes;
* ``layout``       — the full abstract signature: shape/dtype/sharding of
  every argument, :func:`layout_of` (``miss_layout``);
* ``model_hash``   — the model identity the program serves; the caller's
  content hash of the model spec/structure (``miss_model_hash``).

Every miss is LOUD: a warning log naming the axis and both values, and an
``aot.store.miss_<reason>`` metric — then the caller falls back to the
compile path. A hit counts ``aot.store.hit``. Nothing in this module can
make a worker serve a stale program silently.

Formats: ``jax_export`` (primary — ``jax.export`` serialized StableHLO;
portable across processes, still XLA-compiles at load, which the
persistent compilation cache then absorbs) and ``pickled_executable``
(fallback where export is unsupported on the running jax —
``jax.experimental.serialize_executable``: zero compile at load but
pinned to the exact device topology). :meth:`ArtifactStore.export_fn`
picks automatically; the meta records which.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

LOG = logging.getLogger("harp_tpu.aot")

META_VERSION = 1
FMT_EXPORT = "jax_export"
FMT_PICKLED = "pickled_executable"

# the key axes checked at load, in check order: the FIRST mismatching axis
# names the miss (a stale artifact usually fails several; one clear reason
# beats four)
KEY_AXES = ("jax_version", "device_kind", "world", "quant", "layout",
            "model_hash")


def jax_version() -> str:
    import jax

    return jax.__version__


def device_kind() -> str:
    """The accelerator generation the running backend exposes (e.g.
    ``TPU v5e`` / ``cpu``) — programs are compiled FOR a device kind."""
    import jax

    return str(jax.devices()[0].device_kind)


def layout_of(args) -> str:
    """Fingerprint of an argument pytree's abstract signature: treedef
    plus shape, dtype, and sharding spec per leaf — ANY layout drift (a
    resized bucket, a re-sharded state arg, an owner-map arg appearing
    after a rebalance, a restructured parameter tree) changes this string
    and invalidates the artifact."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = [str(treedef)]
    for a in leaves:
        spec = getattr(getattr(a, "sharding", None), "spec", None)
        parts.append(f"{tuple(a.shape)}:{a.dtype}:{spec}")
    return ";".join(parts)


# MLIR debug info is NOT part of the program: loc() records carry source
# file paths, line numbers, and per-process location-counter ids, all of
# which shift with import order, trace count, and checkout path while the
# ops stay identical. The content hash must pin the PROGRAM, so the
# canonical text drops every loc record before hashing (verified: the
# same registry exported from different entry points differs ONLY in loc
# lines).
_LOC_DEF = re.compile(r"^#loc\d* = loc\(.*\)$\n?", re.MULTILINE)
_LOC_REF = re.compile(r" loc\((?:#loc\d*|unknown|\".*?\"(?:\(.*?\))?)\)")


def canonical_program_text(mlir_text: str) -> str:
    """The location-stripped module text whose sha256 is the artifact
    content hash — deterministic for a given program + jax version +
    platform, regardless of which process traced it."""
    return _LOC_REF.sub("", _LOC_DEF.sub("", mlir_text))


@dataclass(frozen=True)
class ArtifactKey:
    """Everything that must match for a stored program to be servable."""

    name: str                   # e.g. "serve/mf/b8" or "step/kmeans"
    world: int                  # mesh width the program was exported at
    layout: str                 # layout_of(args) at export time
    model_hash: str             # caller's model-identity content hash
    jax_version: str = field(default_factory=jax_version)
    device_kind: str = field(default_factory=device_kind)
    quant: str = "f32"          # resident quant mode ("f32" | "int8")


def _check_name(name: str) -> str:
    # names become paths under the store root; keep them rooted there
    if not name or name.startswith(("/", ".")) or ".." in name.split("/"):
        raise ValueError(f"artifact name must be a relative path without "
                         f"'..' segments; got {name!r}")
    return name


class ArtifactStore:
    """File-backed store of exported programs (module docstring)."""

    def __init__(self, root: str, metrics=None):
        if metrics is None:
            from harp_tpu.utils.metrics import DEFAULT as metrics
        self.root = root
        self.metrics = metrics

    # -- paths --------------------------------------------------------------

    def _paths(self, name: str) -> Tuple[str, str]:
        base = os.path.join(self.root, _check_name(name))
        return base + ".json", base + ".bin"

    def list(self) -> List[dict]:
        """Every artifact's meta (sorted by name); unreadable/torn metas
        are skipped — listing must survive any seam."""
        metas = []
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in sorted(files):
                if not fn.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(dirpath, fn)) as f:
                        metas.append(json.load(f))
                except (OSError, ValueError):
                    continue
        return sorted(metas, key=lambda m: m.get("name", ""))

    # -- export (build the payload from a live compiled fn) -----------------

    def export_fn(self, fn: Callable, args) -> Tuple[bytes, str, str]:
        """Serialize a jitted ``fn`` at ``args``'s abstract signature →
        ``(payload, content_hash, format)``. ``args`` may be concrete
        arrays or ShapeDtypeStructs (shape/dtype/sharding is all that is
        read). The content hash is over the lowered StableHLO module text
        — deterministic for a given jax version/platform, which is what
        lets the manifest pin it across processes."""
        try:
            from jax import export as jax_export
        except ImportError:          # pragma: no cover — this jax has it
            jax_export = None
        if jax_export is not None:
            exported = jax_export.export(fn)(*args)
            content_hash = hashlib.sha256(canonical_program_text(
                exported.mlir_module()).encode()).hexdigest()
            return exported.serialize(), content_hash, FMT_EXPORT
        # serialized-bytes fallback: pickle the compiled executable
        # (topology-pinned; the key's device_kind/world axes gate it)
        from jax.experimental import serialize_executable as sx

        lowered = fn.lower(*args)
        content_hash = hashlib.sha256(canonical_program_text(
            lowered.as_text()).encode()).hexdigest()
        payload, _, _ = sx.serialize(lowered.compile())
        return bytes(payload), content_hash, FMT_PICKLED

    def load_fn(self, payload: bytes, fmt: str) -> Callable:
        """Deserialize a payload back into a dispatchable callable. The
        ``jax_export`` format re-enters through ``jax.jit`` (one XLA
        compile of the shipped StableHLO — no TRACE, so a loaded
        endpoint's ``trace_counts`` stays 0; the persistent compilation
        cache absorbs the compile); ``pickled_executable`` is the
        already-compiled executable."""
        import jax

        if fmt == FMT_EXPORT:
            from jax import export as jax_export

            exported = jax_export.deserialize(bytearray(payload))
            return jax.jit(exported.call)
        if fmt == FMT_PICKLED:
            from jax.experimental import serialize_executable as sx

            compiled = sx.deserialize_and_load(payload)
            return compiled
        raise ValueError(f"unknown artifact format {fmt!r}")

    # -- put/load -----------------------------------------------------------

    def put(self, key: ArtifactKey, payload: bytes, content_hash: str,
            fmt: str, memory: Optional[dict] = None,
            hlo: Optional[dict] = None) -> dict:
        """Write one artifact atomically; returns the meta written.

        ``memory`` is the program's static memory row
        (``harp_tpu.aot.static_memory.memory_row``:
        resident_arg_bytes / peak_live_bytes / transient_peak_ratio) and
        ``hlo`` its compiled-HLO cost row
        (``harp_tpu.aot.hlo_audit.hlo_row``: compiler-emitted collective
        counts/bytes, instruction count, while count) — both recorded as
        METADATA (placement planning / fleet tooling), never a key axis:
        a differing or absent row must not turn a load into a miss
        (``load_meta`` checks only ``KEY_AXES``)."""
        meta_path, bin_path = self._paths(key.name)
        os.makedirs(os.path.dirname(meta_path) or ".", exist_ok=True)
        meta = {"v": META_VERSION, **asdict(key),
                "content_hash": content_hash, "format": fmt,
                "payload_bytes": len(payload),
                "payload_sha256": hashlib.sha256(payload).hexdigest()}
        if memory is not None:
            meta["memory"] = dict(memory)
        if hlo is not None:
            meta["hlo"] = dict(hlo)
        tmp = bin_path + f".tmp-{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, bin_path)
        tmp = meta_path + f".tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(tmp, meta_path)
        self.metrics.count("aot.store.put")
        return meta

    def export_and_put(self, key: ArtifactKey, fn: Callable, args,
                       memory: Optional[dict] = None,
                       hlo: Optional[dict] = None) -> dict:
        payload, content_hash, fmt = self.export_fn(fn, args)
        return self.put(key, payload, content_hash, fmt, memory=memory,
                        hlo=hlo)

    def _miss(self, key: ArtifactKey, reason: str, detail: str) -> None:
        # LOUD by contract: the metric names the axis, the log names both
        # values — a fleet quietly recompiling everything is an incident
        # in the making, and this is its first signal
        self.metrics.count(f"aot.store.miss_{reason}")
        LOG.warning("aot artifact %r rejected (%s): %s — falling back to "
                    "compile", key.name, reason, detail)

    def load_meta(self, key: ArtifactKey) -> Optional[dict]:
        """The meta for ``key`` IF every key axis matches; None (with the
        metered miss) otherwise."""
        meta_path, _bin_path = self._paths(key.name)
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except OSError:
            self._miss(key, "absent", f"no artifact at {meta_path}")
            return None
        except ValueError:
            self._miss(key, "corrupt", f"unparseable meta at {meta_path}")
            return None
        want = asdict(key)
        for axis in KEY_AXES:
            if meta.get(axis) != want[axis]:
                self._miss(key, axis,
                           f"artifact has {axis}={meta.get(axis)!r}, this "
                           f"process needs {want[axis]!r}")
                return None
        return meta

    def load(self, key: ArtifactKey) -> Optional[Tuple[Callable, dict]]:
        """``(callable, meta)`` for a fresh hit; None on ANY mismatch or
        unreadable payload (all metered — the caller compiles instead)."""
        meta = self.load_meta(key)
        if meta is None:
            return None
        _meta_path, bin_path = self._paths(key.name)
        try:
            with open(bin_path, "rb") as f:
                payload = f.read()
        except OSError as e:
            self._miss(key, "corrupt", f"payload unreadable: {e}")
            return None
        if hashlib.sha256(payload).hexdigest() != meta.get("payload_sha256"):
            self._miss(key, "corrupt", "payload bytes do not match meta "
                                       "(torn or tampered)")
            return None
        try:
            fn = self.load_fn(payload, meta["format"])
        except Exception as e:  # noqa: BLE001 — deserialize failures of a
            #   stale/foreign payload must degrade to compile, never crash
            #   a starting worker; the miss is metered and logged
            self._miss(key, "corrupt", f"deserialize failed: {e!r}")
            return None
        self.metrics.count("aot.store.hit")
        return fn, meta

    # -- summary ------------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        """Hit/miss counters (this process, this registry)."""
        snap = self.metrics.snapshot().get("counters", {})
        return {k: v for k, v in snap.items() if k.startswith("aot.store.")}
