"""harp-tpu: a TPU-native distributed ML framework with the capabilities of Harp.

Harp (Indiana University) plugged MPI-style collectives into Hadoop MapReduce for
iterative distributed ML on Xeon clusters (Java + Intel DAAL native kernels). This
framework re-expresses those capabilities idiomatically for TPU:

* Harp's Table/Partition data model  → :mod:`harp_tpu.table` (dense sharded arrays
  with distribution states) on a ``jax.sharding.Mesh``.
* Harp's TCP collective runtime      → :mod:`harp_tpu.collectives` (XLA collectives
  over ICI/DCN inside shard_map).
* ``CollectiveMapper`` / HarpSession → :class:`harp_tpu.session.HarpSession`.
* dymoro model rotation              → :mod:`harp_tpu.collectives.rotation`.
* Intel DAAL kernels                 → :mod:`harp_tpu.ops` (jnp + pallas) and
  :mod:`harp_tpu.models` (the algorithm library).
* keyval/ typed KV tables            → :mod:`harp_tpu.keyval` (sorted dense
  stores + :class:`harp_tpu.keyval.DistributedKV`).
* YARN gang scheduling               → :mod:`harp_tpu.parallel.distributed`
  (+ :mod:`harp_tpu.parallel.launch` nodes-file launcher).
* per-algorithm CLI launchers        → ``python -m harp_tpu.run <algo>``.

See SURVEY.md at the repo root for the full reference analysis and mapping;
MIGRATION.md for the Harp-user cookbook; PERF.md for measured performance.
"""

from harp_tpu import combiner
from harp_tpu import keyval
from harp_tpu import partitioner
from harp_tpu.combiner import AVG, MAX, MIN, MINUS, MULTIPLY, SUM, Combiner, Op
from harp_tpu.parallel.mesh import MODEL, WORKERS, force_host_devices, make_mesh
from harp_tpu.session import HarpSession
from harp_tpu.table import Dist, Table

__version__ = "0.1.0"

__all__ = [
    "AVG", "MAX", "MIN", "MINUS", "MULTIPLY", "SUM",
    "Combiner", "Op", "Dist", "Table", "HarpSession",
    "WORKERS", "MODEL", "force_host_devices", "make_mesh",
    "combiner", "keyval", "partitioner",
]
