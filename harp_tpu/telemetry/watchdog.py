"""SLO watchdog — a rolling-window evaluator that acts on its own signal.

PR 7 built the diagnostics (straggler reports, on-demand xprof windows)
but left the trigger to an operator: somebody had to notice a latency
regression and drop the trigger file. This module closes that loop. A
:class:`SLOWatchdog` consumes a latency/error stream — serving request
ages (``ServeWorker`` feeds it at every reply) or training chunk-boundary
walls (the :meth:`boundary_hook` adapter) — over a rolling window and,
when the SLO burns SUSTAINED (rolling p99 above target, or the error
fraction past the budget, for ``sustain`` consecutive evaluations), it
fires the existing PR 7 machinery exactly once per burn window:

* **xprof window** — writes the operator trigger file
  (``<dir>/xprof_request.json``) next to the telemetry output. Every rank
  polling that directory (the :class:`~harp_tpu.telemetry.xprof.
  XprofController` boundary hook) opens a profiler window at its next
  boundary — the alignment-safe gang-wide arm path PR 7 built for exactly
  this kind of out-of-band trigger.
* **straggler snapshot** — dumps the LOCAL ``Metrics.snapshot()`` as
  ``slo_snapshot_rank<r>_<n>.json`` and attaches the latest PUBLISHED
  straggler report (the GangCollector's cadence output) to the incident.
  Deliberately non-collective: a watchdog fires when ITS rank sees burn,
  and a collective gather from an unaligned boundary would deadlock the
  gang — the same reasoning that keeps xprof window start/stop local.
* **incident journal** — appends one JSON line to
  ``<dir>/slo_incidents.jsonl`` (the supervisor-journal idiom): observed
  p99 vs target, error fraction vs budget, window occupancy, and what was
  triggered. ``slo.incidents`` counts, ``slo.burning`` gauges the live
  state.

"Exactly once per burn window": the watchdog is a two-state machine
(ok ⇄ burning). Entering *burning* fires; staying in it does not; an
evaluation that sees the SLO met returns to *ok* and re-arms. A sustained
fault (the ``slow@`` grammar) therefore produces ONE incident, not one
per reply — and a second burn after recovery produces a second.

Evaluation is amortized: ``observe`` is deque appends; the window is only
evaluated every ``eval_interval_s`` (or when a hook forces it at a chunk
boundary), so the reply path pays no percentile sort per request.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Deque, Optional, Tuple

DEFAULT_WINDOW_S = 30.0
DEFAULT_BUDGET = 0.1          # tolerated error fraction over the window
DEFAULT_SUSTAIN = 2           # consecutive burning evaluations before firing
DEFAULT_MIN_SAMPLES = 20
INCIDENTS_NAME = "slo_incidents.jsonl"
TRIGGER_NAME = "xprof_request.json"     # xprof.XprofController's file path

# The machine-readable incident contract (ISSUE 14 satellite): every
# journaled slo-burn record carries AT LEAST these fields, typed as noted —
# the serving re-placement policy consumes rank/p99_s/window_s directly
# (serve.endpoints.rebalance_from_incidents), so the schema is pinned by a
# test, not by convention. Extending the record is fine; dropping or
# retyping one of these is a consumer-breaking change.
INCIDENT_SCHEMA_VERSION = 1
INCIDENT_REQUIRED_FIELDS = {
    "v": int,                  # INCIDENT_SCHEMA_VERSION
    "kind": str,               # "slo-burn"
    "ts": (int, float),        # wall clock at fire time
    "rank": int,               # the rank whose watchdog burned
    "incident": int,           # per-watchdog incident ordinal (1-based)
    "p99_s": (int, float),     # observed rolling p99 at fire time
    "p99_target_s": (int, float),
    "error_fraction": (int, float),
    "error_budget": (int, float),
    "window_s": (int, float),  # the rolling-window width evaluated
    "samples": int,            # window occupancy at fire time
    "triggered": list,         # which PR 7 machinery fired
}


class SLOWatchdog:
    """Rolling p99-target + error-budget evaluator (module docstring).

    ``p99_target_s`` is the SLO; ``telemetry_dir`` is where the trigger
    file, snapshots, and incident journal land (None = evaluate and
    count, trigger nothing — tests and dry runs). ``xprof_steps`` sizes
    the profiler window the incident arms.
    """

    def __init__(self, p99_target_s: float, *,
                 window_s: float = DEFAULT_WINDOW_S,
                 error_budget: float = DEFAULT_BUDGET,
                 sustain: int = DEFAULT_SUSTAIN,
                 min_samples: int = DEFAULT_MIN_SAMPLES,
                 eval_interval_s: Optional[float] = None,
                 telemetry_dir: Optional[str] = None,
                 xprof_steps: int = 8, rank: Optional[int] = None,
                 metrics=None, on_burn=None):
        if metrics is None:
            from harp_tpu.utils.metrics import DEFAULT as metrics
        if p99_target_s <= 0:
            raise ValueError(f"p99_target_s must be positive, got "
                             f"{p99_target_s}")
        self.p99_target_s = float(p99_target_s)
        self.window_s = float(window_s)
        self.error_budget = float(error_budget)
        self.sustain = max(1, int(sustain))
        self.min_samples = max(1, int(min_samples))
        self.eval_interval_s = (window_s / 4.0 if eval_interval_s is None
                                else float(eval_interval_s))
        self.telemetry_dir = telemetry_dir
        self.xprof_steps = int(xprof_steps)
        self.rank = (int(os.environ.get("HARP_PROCESS_ID", "0"))
                     if rank is None else rank)
        self.metrics = metrics
        self.on_burn = on_burn
        self.incidents = 0
        self.burning = False
        self._burn_streak = 0
        self._last_eval = 0.0
        # (ts, latency_s, ok) — pruned to window_s on every evaluation.
        # The lock covers every window/state access: a ServeWorker feeds
        # observe() from its receive thread AND every MicroBatcher thread,
        # and an unguarded evaluate() iterating the deque mid-append would
        # raise (and _safe_reply would eat the reply it rode in on)
        self._lock = threading.Lock()
        self._window: Deque[Tuple[float, float, bool]] = collections.deque()

    # -- stream input -------------------------------------------------------

    def observe(self, latency_s: float, *, ok: bool = True,
                now: Optional[float] = None) -> None:
        """One request/step outcome. Cheap (deque append + a cadence check
        under the lock); the window only gets sorted when an evaluation is
        due. Thread-safe — any reply/boundary thread may call it."""
        now = time.time() if now is None else now
        with self._lock:
            self._window.append((now, float(latency_s), bool(ok)))
            self._evaluate_locked(now=now)

    def is_burning(self) -> bool:
        """Thread-safe read of the live burn state — the serving brownout
        arm (ISSUE 16): every MicroBatcher admission decision polls this,
        so it takes the lock rather than racing the bare attribute the
        evaluator writes under it."""
        with self._lock:
            return self.burning

    # -- evaluation ---------------------------------------------------------

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        w = self._window
        while w and w[0][0] < cutoff:
            w.popleft()

    def window_stats(self, now: Optional[float] = None) -> dict:
        """Rolling p99 + error fraction over the live window (nearest-rank
        over the actual samples — the window is already bounded by time,
        no reservoir needed). Thread-safe."""
        with self._lock:
            return self._window_stats_locked(
                time.time() if now is None else now)

    def _window_stats_locked(self, now: float) -> dict:
        self._prune(now)
        lats = sorted(v for _t, v, _ok in self._window)
        n = len(lats)
        errors = sum(1 for _t, _v, ok in self._window if not ok)
        p99 = lats[min(n - 1, max(0, -(-99 * n // 100) - 1))] if n else None
        return {"samples": n, "p99_s": p99,
                "error_fraction": (errors / n) if n else 0.0}

    def evaluate(self, now: Optional[float] = None,
                 force: bool = False) -> Optional[dict]:
        """Run one evaluation if the cadence is due (or ``force``).
        Returns the incident record when this evaluation FIRED, else
        None. Thread-safe."""
        with self._lock:
            return self._evaluate_locked(now=now, force=force)

    def _evaluate_locked(self, now: Optional[float] = None,
                         force: bool = False) -> Optional[dict]:
        now = time.time() if now is None else now
        if not force and now - self._last_eval < self.eval_interval_s:
            return None
        self._last_eval = now
        stats = self._window_stats_locked(now)
        burn = (stats["samples"] >= self.min_samples
                and (stats["p99_s"] > self.p99_target_s
                     or stats["error_fraction"] > self.error_budget))
        if not burn:
            self._burn_streak = 0
            if self.burning:
                self.burning = False
                self.metrics.gauge("slo.burning", 0.0)
            return None
        self._burn_streak += 1
        if self.burning or self._burn_streak < self.sustain:
            return None
        self.burning = True          # entering the burn window: fire ONCE
        self.metrics.gauge("slo.burning", 1.0)
        return self._fire(now, stats)

    # -- actions ------------------------------------------------------------

    def _fire(self, now: float, stats: dict) -> dict:
        self.incidents += 1
        self.metrics.count("slo.incidents")
        incident = {
            "v": 1, "kind": "slo-burn", "ts": round(now, 3),
            "rank": self.rank, "incident": self.incidents,
            "p99_s": stats["p99_s"], "p99_target_s": self.p99_target_s,
            "error_fraction": round(stats["error_fraction"], 4),
            "error_budget": self.error_budget,
            "window_s": self.window_s, "samples": stats["samples"],
            "triggered": [],
        }
        if self.telemetry_dir:
            incident["triggered"] = self._trigger_pr7_machinery(incident)
            self._journal(incident)
        if self.on_burn is not None:
            self.on_burn(incident)
        return incident

    def _trigger_pr7_machinery(self, incident: dict) -> list:
        from harp_tpu.telemetry.gang import read_straggler_report

        triggered = []
        d = self.telemetry_dir
        os.makedirs(d, exist_ok=True)
        trigger = os.path.join(d, TRIGGER_NAME)
        try:
            # atomic write: every rank's XprofController polls this file by
            # (mtime, size) token — a torn write must not half-arm the gang
            tmp = trigger + f".tmp-{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"steps": self.xprof_steps,
                           "reason": f"slo-burn #{self.incidents} "
                                     f"rank {self.rank}"}, f)
            os.replace(tmp, trigger)
            triggered.append("xprof_request")
        except OSError as e:
            incident["xprof_error"] = str(e)
        snap_path = os.path.join(
            d, f"slo_snapshot_rank{self.rank}_{self.incidents}.json")
        try:
            self.metrics.dump(snap_path)
            triggered.append("metrics_snapshot")
            incident["snapshot"] = os.path.basename(snap_path)
        except OSError as e:
            incident["snapshot_error"] = str(e)
        report = read_straggler_report(d)
        if report is not None:
            incident["straggler_report"] = {
                "ts": report.get("ts"),
                "suspects": report.get("suspects"),
                "bsp_suspects": report.get("bsp_suspects")}
            triggered.append("straggler_report_attached")
        return triggered

    def _journal(self, incident: dict) -> None:
        path = os.path.join(self.telemetry_dir, INCIDENTS_NAME)
        try:
            with open(path, "a") as f:
                f.write(json.dumps(incident) + "\n")
        except OSError as e:
            incident["journal_error"] = str(e)

    # -- incident-stream readers (the re-placement consumer surface) --------

    @staticmethod
    def validate_incident(incident: dict) -> list:
        """Schema-check one incident record against
        :data:`INCIDENT_REQUIRED_FIELDS`; returns the list of violations
        (empty = conformant). The journal writer and the re-placement
        consumer share this one definition, so they cannot drift apart
        silently."""
        problems = []
        for field, types in INCIDENT_REQUIRED_FIELDS.items():
            if field not in incident:
                problems.append(f"missing field {field!r}")
            elif incident[field] is not None \
                    and not isinstance(incident[field], types):
                problems.append(
                    f"field {field!r} is {type(incident[field]).__name__}, "
                    f"want {types}")
        return problems

    # -- training-gang adapter ----------------------------------------------

    def boundary_hook(self):
        """A StepLog boundary hook feeding the watchdog the INTER-BOUNDARY
        wall — the time between consecutive chunk boundaries. That is the
        honest training-side SLO signal: it covers the compiled chunk, the
        checkpoint save, AND any host-side drag the chunk-internal step
        timer cannot see (the ``slow@`` fault grammar injects its sleep at
        the iteration boundary, OUTSIDE the timed chunk — a per-step-timer
        feed would be blind to exactly the fault class this watchdog
        exists to catch). The p99 target is therefore per chunk boundary
        when the watchdog rides a training gang, and per request when it
        rides the serving reply path."""
        watchdog = self
        prev = [None]

        def hook(_boundary_index: int, log) -> None:
            now_pc = time.perf_counter()
            if prev[0] is not None:
                with watchdog._lock:
                    watchdog._window.append(
                        (time.time(), now_pc - prev[0], True))
                    watchdog._evaluate_locked(force=True)
            prev[0] = now_pc

        hook.close = lambda: None
        return hook


def read_incidents(telemetry_dir: str,
                   max_age_s: Optional[float] = None) -> list:
    """Parse the SLO incident journal (``slo_incidents.jsonl``) — every
    watchdog in the gang appends to the same file, so this is the GANG's
    incident stream, in append order. A torn/undecodable line is skipped
    (the journal is append-only under concurrent writers; a reader must
    survive the seam), and ``max_age_s`` drops records older than the
    bound — a dead gang's stale incidents earn no placement change, the
    same trust rule the straggler-report readers apply."""
    path = os.path.join(telemetry_dir, INCIDENTS_NAME)
    out = []
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return out
    now = time.time()
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not isinstance(rec, dict) or rec.get("kind") != "slo-burn":
            continue
        if max_age_s is not None:
            ts = rec.get("ts")
            if not isinstance(ts, (int, float)) or now - ts > max_age_s:
                continue
        out.append(rec)
    return out


def incident_ranks(telemetry_dir: str, world: Optional[int] = None,
                   max_age_s: Optional[float] = 600.0) -> list:
    """Ranks the fresh SLO incident stream names — the serving analog of
    :func:`harp_tpu.parallel.supervisor.straggler_ranks`, and the feed the
    ISSUE 14 re-placement path consumes (``rank``/``p99_s``/``window_s``
    are schema-pinned, INCIDENT_REQUIRED_FIELDS). Bounded to ``world``
    when given; sorted, deduplicated."""
    ranks = set()
    for rec in read_incidents(telemetry_dir, max_age_s=max_age_s):
        r = rec.get("rank")
        if isinstance(r, int) and (world is None or 0 <= r < world):
            ranks.add(r)
    return sorted(ranks)
