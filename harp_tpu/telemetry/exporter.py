"""Live metrics export — a per-worker pull endpoint over the existing
``Metrics``/``TimerReservoir`` surface.

Until now the only ways OUT of the metrics registry were the JSONL step
events, the straggler report file, and ``Metrics.dump()`` at end of job —
nothing an operator (or a scrape-based monitoring stack) could poll on a
LIVE gang. :class:`MetricsExporter` is that endpoint: a stdlib
``http.server`` thread (no new dependencies — the container pins its
environment) bound to loopback by default, serving

* ``GET /metrics`` — Prometheus text exposition: every counter as a
  ``counter``, every gauge as a ``gauge``, every bounded timer as a
  ``summary`` (``{quantile="0.5|0.9|0.99"}`` off the reservoir plus exact
  ``_count``/``_sum``). Names are sanitized (``serve.queue_depth.topk`` →
  ``harp_serve_queue_depth_topk``) — the serving counters, batcher queue
  depth, and ``telemetry.events_dropped`` all ride through unchanged.
* ``GET /snapshot`` — the raw ``Metrics.snapshot()`` JSON plus
  ``{rank, ts}`` (the exact dict the straggler exchange broadcasts, so a
  scraper and the gang detector read ONE schema).
* ``GET /gang`` — the gang-aggregated view when a source is wired
  (``gang=`` callable returning ``{rank: snapshot}`` — run.py passes the
  :class:`~harp_tpu.telemetry.gang.GangCollector`'s last exchange, which
  already rides the events control plane; 404 when absent): per-rank
  snapshots plus an :func:`aggregate_snapshots` roll-up (counters summed,
  timer counts/totals summed, worst-rank percentiles — percentiles do not
  merge exactly, so the roll-up reports the honest worst case and keeps
  the per-rank rows for anything finer).

``Metrics.snapshot()`` is registry-lock-consistent (jaxlint v3 made the
registry thread-safe), so a scrape sees one coherent point-in-time view
even while workers mutate. The exporter binds port 0 (ephemeral) unless
told otherwise, serves from a daemon thread, and registers an atexit close
so an abandoned gang never leaks the listening socket.
"""

from __future__ import annotations

import atexit
import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
PREFIX = "harp_"
QUANTILES = ((0.5, "0.5"), (0.9, "0.9"), (0.99, "0.99"))


def _sanitize(name: str) -> str:
    return PREFIX + _NAME_RE.sub("_", name)


def prometheus_text(snapshot: Dict) -> str:
    """Render one ``Metrics.snapshot()`` dict as Prometheus text
    exposition (pure function — the schema test and the handler share
    it)."""
    lines = []
    for name in sorted(snapshot.get("counters", {})):
        mname = _sanitize(name)
        lines.append(f"# TYPE {mname} counter")
        lines.append(f"{mname} {snapshot['counters'][name]:g}")
    for name in sorted(snapshot.get("gauges", {})):
        mname = _sanitize(name)
        lines.append(f"# TYPE {mname} gauge")
        lines.append(f"{mname} {snapshot['gauges'][name]:g}")
    for name in sorted(snapshot.get("timers", {})):
        t = snapshot["timers"][name]
        if not t:
            continue
        mname = _sanitize(name) + "_seconds"
        lines.append(f"# TYPE {mname} summary")
        for q, label in QUANTILES:
            key = f"p{int(q * 100)}_s"
            if key in t:
                lines.append(
                    f'{mname}{{quantile="{label}"}} {t[key]:g}')
        lines.append(f"{mname}_count {t.get('count', 0):g}")
        lines.append(f"{mname}_sum {t.get('total_s', 0.0):g}")
    return "\n".join(lines) + "\n"


def aggregate_snapshots(per_rank: Dict[int, dict]) -> Dict:
    """Roll ``{rank: snapshot}`` up into one gang view: counters summed,
    timers summed where sums are exact (count/total) and WORST-rank where
    they are not (p50/p99 — reservoir percentiles do not merge; the gang's
    slowest rank is the honest aggregate for an SLO eye). Gauges keep only
    a per-rank map (a summed gauge is meaningless)."""
    counters: Dict[str, float] = {}
    timers: Dict[str, dict] = {}
    for _rank, snap in sorted(per_rank.items()):
        for name, v in snap.get("counters", {}).items():
            counters[name] = counters.get(name, 0.0) + v
        for name, t in snap.get("timers", {}).items():
            if not t:
                continue
            row = timers.setdefault(name, {"count": 0, "total_s": 0.0,
                                           "worst_p50_s": 0.0,
                                           "worst_p99_s": 0.0})
            row["count"] += t.get("count", 0)
            row["total_s"] += t.get("total_s", 0.0)
            row["worst_p50_s"] = max(row["worst_p50_s"],
                                     t.get("p50_s") or 0.0)
            row["worst_p99_s"] = max(row["worst_p99_s"],
                                     t.get("p99_s") or 0.0)
    for row in timers.values():
        if row["count"]:
            row["mean_s"] = row["total_s"] / row["count"]
    return {"num_ranks": len(per_rank), "counters": counters,
            "timers": timers,
            "gauges_by_rank": {r: s.get("gauges", {})
                               for r, s in sorted(per_rank.items())}}


class MetricsExporter:
    """Pull exporter for one process's metrics registry (module
    docstring). ``port=0`` binds an ephemeral port (read it back from
    ``self.port``); ``gang`` optionally supplies the ``/gang`` view."""

    def __init__(self, metrics=None, *, host: str = "127.0.0.1",
                 port: int = 0, rank: Optional[int] = None,
                 gang: Optional[Callable[[], Optional[Dict[int, dict]]]]
                 = None):
        if metrics is None:
            from harp_tpu.utils.metrics import DEFAULT as metrics
        import os

        self.metrics = metrics
        self.rank = (int(os.environ.get("HARP_PROCESS_ID", "0"))
                     if rank is None else rank)
        self.gang = gang
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):        # scrapes must not spam stderr
                pass

            def do_GET(self):
                try:
                    body, ctype = exporter._render(self.path)
                except (KeyError, TypeError, ValueError,
                        RuntimeError) as e:
                    # a malformed registry entry costs one scrape a 500,
                    # never the serving thread (snapshot() itself is
                    # registry-lock-consistent since jaxlint v3; this is
                    # defense against custom gang= sources and schema
                    # surprises)
                    self.send_error(500, str(e))
                    return
                if body is None:
                    self.send_error(404, "unknown path (serve /metrics, "
                                         "/snapshot, /gang)")
                    return
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"harp-metrics-exporter-{self.port}")
        self._thread.start()
        # close() races itself: atexit fires on the main thread while an
        # owner (ServeWorker.close, a test teardown) may be closing from
        # another — the lock makes the idempotence check-then-act atomic
        # so shutdown() runs exactly once (JL302's check-then-act class)
        self._close_lock = threading.Lock()
        self._closed = False
        atexit.register(self.close)

    @property
    def address(self):
        return (self.host, self.port)

    def _render(self, path: str):
        path = path.split("?", 1)[0]
        if path == "/metrics":
            return prometheus_text(self.metrics.snapshot()), \
                "text/plain; version=0.0.4"
        if path == "/snapshot":
            snap = self.metrics.snapshot()
            snap["rank"] = self.rank
            snap["ts"] = round(time.time(), 3)
            return json.dumps(snap), "application/json"
        if path == "/gang":
            per_rank = self.gang() if self.gang is not None else None
            if not per_rank:
                return None, ""
            return json.dumps(
                {"aggregated": aggregate_snapshots(per_rank),
                 "ranks": {str(r): s for r, s in sorted(per_rank.items())},
                 "ts": round(time.time(), 3)}), "application/json"
        return None, ""

    def close(self) -> None:
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(5.0)
        atexit.unregister(self.close)

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
