"""Gang aggregation + straggler detection over the events control plane.

The elastic supervisor's watchdog-suspect policy (``parallel.supervisor``)
can only classify a member AFTER it dies; a straggling-but-alive rank
(thermal throttling, a sick ICI link, a noisy neighbor on its host) silently
stretches every bulk-synchronous step to the slowest member's pace. This
module gives the gang the signal the reference never had: every rank's
``Metrics.snapshot()`` — per-step p50/p90/p99 from the bounded timer
reservoirs — exchanged over the existing authenticated events control plane
(``events.send_collective``; P2P-backed sessions use the same API), and a
straggler report: suspect = sustained p50 step time > ``k`` × the gang
median. The report is written as JSON next to the telemetry JSONL so the
supervisor (and an operator) can consume it without joining the gang.

All exchange functions are COLLECTIVE host operations — every rank must call
them at the same chunk boundary (the SPMD host loops guarantee this; the
count-based telemetry interval keeps cadence aligned). Single-process
sessions degrade to a local snapshot, so every code path runs in tier-1.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
from typing import Dict, List, Optional

SNAPSHOT_TAG = "harp.telemetry.snapshot"
REPORT_NAME = "straggler_report.json"
REPORT_VERSION = 1

# suspect threshold: sustained p50 step time > k x gang median
DEFAULT_K = 2.0
# a rank must have this many step samples before its p50 is trusted —
# a single cold-start step must not flag a healthy rank
DEFAULT_MIN_SAMPLES = 3
# ... and must exceed the median by an absolute floor too: on a gang whose
# steps are all microseconds, 2 us vs a 1 us median clears any ratio k but
# drags nothing — a straggler must cost real wall time
DEFAULT_MIN_GAP_S = 1e-3


def gather_snapshots(session, metrics=None) -> Dict[int, dict]:
    """Exchange per-rank metric snapshots; every rank returns the full map.

    COLLECTIVE: all processes must call together. W tiny broadcasts (one per
    source rank) on the host control plane — never inside a step program.
    Unrelated events already queued are re-enqueued, not lost (the event
    queue makes no ordering promise; see ``HarpSession.send_event``).
    """
    import jax

    if metrics is None:
        from harp_tpu.utils.metrics import DEFAULT as metrics
    local = metrics.snapshot()
    n = jax.process_count()
    if n == 1:
        return {int(os.environ.get("HARP_PROCESS_ID", "0")): local}
    for src in range(n):
        session.send_event((SNAPSHOT_TAG, src, local), source=src)
    snaps: Dict[int, dict] = {}
    requeue = []
    while len(snaps) < n:
        ev = session.get_event()
        if ev is None:
            break               # queue drained early: report what arrived
        payload = ev.payload
        if (isinstance(payload, tuple) and len(payload) == 3
                and payload[0] == SNAPSHOT_TAG):
            snaps[int(payload[1])] = payload[2]
        else:
            requeue.append(ev)
    queue = session.open_events()[0]
    for ev in requeue:
        queue.put(ev)
    return snaps


def _step_timing(snapshot: dict, timer_prefix: str) -> Optional[dict]:
    """The rank's step timer: the ``timer_prefix``-matching timer with the
    most samples (a rank running several models reports its busiest loop)."""
    timers = snapshot.get("timers", {})
    best = None
    for name, t in timers.items():
        if name.startswith(timer_prefix) and t.get("count", 0):
            if best is None or t["count"] > best["count"]:
                best = t
    return best


def straggler_report(per_rank: Dict[int, dict], *,
                     timer_prefix: str = "telemetry.step",
                     k: float = DEFAULT_K,
                     min_samples: int = DEFAULT_MIN_SAMPLES,
                     min_gap_s: float = DEFAULT_MIN_GAP_S) -> dict:
    """Pure detection over exchanged snapshots (unit-testable without a gang).

    Two complementary signals, because the same straggler leaves opposite
    timer signatures depending on the loop shape:

    * ``suspects`` — p50 > k × gang median: a SELF-PACED host loop (each
      rank times its own work, no collective inside the timed region — the
      serving path, data loading, per-rank host work) where the straggler's
      own timer inflates.
    * ``bsp_suspects`` — p50 × k < gang median: a BULK-SYNCHRONOUS fit loop
      (the timed region is a compiled chunk whose first collective makes
      every healthy rank wait for the straggler), where the drag lands in
      the VICTIMS' timers and the straggler is the one rank NOT waiting —
      measured on the 3-member gang drive: victims p50 ≈ 131 ms, the
      scripted slow rank 15 ms. Only meaningful when the step timers wrap
      gang-synchronized dispatches; the run.py gang CLI's chunk loops do.

    Ranks with fewer than ``min_samples`` step samples are listed but
    excluded from the median and both suspect lists — cold ranks are
    unknown, not slow. With fewer than 2 measurable ranks there is no gang
    median and no suspects (a 1-rank "gang" cannot straggle relative to
    itself). Both signals keep the ``min_gap_s`` absolute floor so
    microsecond jitter never flags.
    """
    ranks: Dict[int, dict] = {}
    p50s: List[float] = []
    for rank, snap in sorted(per_rank.items()):
        t = _step_timing(snap, timer_prefix)
        row = {"count": int(t["count"]) if t else 0,
               "p50_s": t.get("p50_s") if t else None,
               "p99_s": t.get("p99_s") if t else None,
               "measurable": bool(t) and t.get("count", 0) >= min_samples}
        ranks[rank] = row
        if row["measurable"]:
            p50s.append(row["p50_s"])
    median = statistics.median(p50s) if len(p50s) >= 2 else None
    suspects, bsp_suspects = [], []
    if median is not None:
        suspects = [r for r, row in ranks.items()
                    if row["measurable"] and row["p50_s"] > k * median
                    and row["p50_s"] - median >= min_gap_s]
        bsp_suspects = [r for r, row in ranks.items()
                        if row["measurable"] and row["p50_s"] * k < median
                        and median - row["p50_s"] >= min_gap_s]
    return {"v": REPORT_VERSION, "ts": round(time.time(), 3), "k": k,
            "min_samples": min_samples, "min_gap_s": min_gap_s,
            "num_ranks": len(per_rank),
            "gang_median_p50_s": median, "ranks": ranks,
            "suspects": suspects, "bsp_suspects": bsp_suspects}


def write_straggler_report(directory: str, report: dict) -> str:
    """Persist one report as ``<dir>/straggler_report.json`` (atomic
    rename — the supervisor may read it mid-publish)."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, REPORT_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def publish_straggler_report(session, directory: str, *, metrics=None,
                             k: float = DEFAULT_K,
                             min_samples: int = DEFAULT_MIN_SAMPLES,
                             min_gap_s: float = DEFAULT_MIN_GAP_S,
                             snapshots: Optional[Dict[int, dict]] = None
                             ) -> dict:
    """Gather + detect + persist. COLLECTIVE (all ranks call) unless
    ``snapshots`` passes an already-gathered exchange (the GangCollector
    does — it keeps the map for the exporter's ``/gang`` view); every rank
    returns the same report, rank 0 writes ``<dir>/straggler_report.json``."""
    import jax

    snaps = (gather_snapshots(session, metrics=metrics)
             if snapshots is None else snapshots)
    report = straggler_report(snaps, k=k, min_samples=min_samples,
                              min_gap_s=min_gap_s)
    if metrics is None:
        from harp_tpu.utils.metrics import DEFAULT as metrics
    metrics.gauge("telemetry.straggler_suspects", len(report["suspects"]))
    if jax.process_index() == 0:
        write_straggler_report(directory, report)
    return report


def read_straggler_report(directory: Optional[str]) -> Optional[dict]:
    """The newest published report under a telemetry directory, or None
    (missing/torn file — the supervisor treats either as 'no signal')."""
    if not directory:
        return None
    path = os.path.join(directory, REPORT_NAME)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class GangCollector:
    """Boundary hook: publish the straggler report every ``every`` chunk
    boundaries (count-based so all ranks broadcast on the same boundary;
    install via ``StepLog.add_boundary_hook`` only when every rank runs the
    same host loop — the run.py gang CLI does)."""

    def __init__(self, session, directory: str, *, every: int = 1,
                 k: float = DEFAULT_K,
                 min_samples: int = DEFAULT_MIN_SAMPLES,
                 min_gap_s: float = DEFAULT_MIN_GAP_S):
        self.session = session
        self.directory = directory
        self.every = max(1, every)
        self.k = k
        self.min_samples = min_samples
        self.min_gap_s = min_gap_s
        # the most recent gathered {rank: snapshot} exchange and report —
        # WRITTEN on the training thread at boundary cadence, READ by the
        # metrics exporter's /gang scrape threads (telemetry.exporter wires
        # ``gang=collector.snapshots``). The lock makes each publish
        # atomic (the mid-publish torn read PR 12's hand review missed —
        # JL301); a consumer that needs the (snapshots, report) pair from
        # ONE exchange must read through ``last_exchange()`` — two
        # separate property reads can still straddle a publish.
        self._publish_lock = threading.Lock()
        self._last_report: Optional[dict] = None
        self._last_snapshots: Optional[Dict[int, dict]] = None

    @property
    def last_report(self) -> Optional[dict]:
        with self._publish_lock:
            return self._last_report

    @property
    def last_snapshots(self) -> Optional[Dict[int, dict]]:
        with self._publish_lock:
            return self._last_snapshots

    def last_exchange(self):
        """``(snapshots, report)`` from ONE publish, read under one lock
        hold — the pair-consistent accessor (separate property reads can
        interleave with a boundary publish)."""
        with self._publish_lock:
            return self._last_snapshots, self._last_report

    def snapshots(self) -> Optional[Dict[int, dict]]:
        """The exporter's ``gang=`` source (bound method, scrape-thread
        safe)."""
        return self.last_snapshots

    def __call__(self, boundary_index: int, log) -> None:
        if boundary_index % (self.every * log.interval) != 0:
            return
        from harp_tpu.telemetry.step_log import phase

        with phase("gang.straggler_publish"):
            snaps = gather_snapshots(self.session, metrics=log.metrics)
            report = publish_straggler_report(
                self.session, self.directory, metrics=log.metrics,
                k=self.k, min_samples=self.min_samples,
                min_gap_s=self.min_gap_s, snapshots=snaps)
            with self._publish_lock:
                self._last_snapshots = snaps
                self._last_report = report
