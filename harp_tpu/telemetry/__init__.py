"""Gang-wide telemetry — per-step structured events, comm-volume accounting,
straggler detection, and on-demand profiler windows.

The reference's only observability was log4j inline wall-clock per phase
(SURVEY §5: KMeansCollectiveMapper.java:190-195 per-iteration compute/merge/
aggregate ms). This package is that idiom grown into a subsystem, under one
hard constraint: **telemetry must never enter a jitted step program**. Every
hook lives at the host chunk boundaries where the training loops ALREADY
synchronize losses to the host (the ``fit_checkpointed`` chunk fetches, the
final ``np.asarray`` of a scanned fit) — jaxlint's JL104 host-sync check and
the JL201/JL203 collective-budget manifest are bitwise unchanged with
telemetry on, and ``tools/ci_checks.sh`` gates exactly that.

Layers:

* :mod:`~harp_tpu.telemetry.step_log` — per-step structured events into a
  bounded ring buffer, flushed as JSONL per rank. ``record_chunk`` is the one
  call the models make; it is a single ``None``-check when telemetry is off.
* :mod:`~harp_tpu.telemetry.comm_ledger` — wire-volume accounting priced off
  the pinned collective-budget manifest (``tools/collective_budget.json``):
  bytes/step, cumulative GB, achieved busbw as gauges, with quantized paths
  priced at their quantized ``bytes_per_step`` rows. No hot-path
  instrumentation — EQuARX-style measured wire bytes for free.
* :mod:`~harp_tpu.telemetry.gang` — rank 0 collects per-rank
  ``Metrics.snapshot()`` over the authenticated events control plane and
  publishes a straggler report (suspect = sustained p50 step time > k× the
  gang median) consumable by ``parallel.supervisor``.
* :mod:`~harp_tpu.telemetry.xprof` — an ``events.send_collective`` payload
  makes every rank capture a ``jax.profiler`` trace for the next N chunk
  boundaries into a per-rank directory: profile a slow gang without
  restarting it.

The serving observability plane (PR 12) extends the same contracts to the
request path:

* :mod:`~harp_tpu.telemetry.spans` — end-to-end request tracing: sampled
  request frames carry per-stage host-boundary stamps through the serve
  router/batcher; completed spans land as ``kind: "span"`` events in the
  same JSONL stream. Zero-drift gated like the rest of the package.
* :mod:`~harp_tpu.telemetry.exporter` — a per-worker stdlib-HTTP pull
  exporter: ``/metrics`` (Prometheus text), ``/snapshot`` (JSON), and the
  gang-aggregated ``/gang`` view off the events-control-plane exchange.
* :mod:`~harp_tpu.telemetry.watchdog` — an SLO watchdog over the span /
  step stream (rolling p99 target + error budget) that, on sustained
  burn, auto-arms an xprof window, dumps the straggler-format snapshot,
  and journals the incident — the PR 7 machinery triggered by its own
  signal instead of an operator.

Enable with ``harp_tpu.run ... --telemetry-dir DIR [--telemetry-interval N]``
or programmatically via :func:`configure`; the ``HARP_TELEMETRY_DIR`` /
``HARP_TELEMETRY_INTERVAL`` environment variables do the same for embedded
callers (gang members inherit them from the launcher environment).
"""

from __future__ import annotations

from harp_tpu.telemetry import spans
from harp_tpu.telemetry.comm_ledger import (CommLedger, ledger_for,
                                            load_manifest, manifest_target)
from harp_tpu.telemetry.exporter import (MetricsExporter,
                                         aggregate_snapshots,
                                         prometheus_text)
from harp_tpu.telemetry.gang import (gather_snapshots, publish_straggler_report,
                                     straggler_report)
from harp_tpu.telemetry.spans import record_span
from harp_tpu.telemetry.step_log import (StepLog, active, configure, disable,
                                         phase, record_chunk, record_timing)
from harp_tpu.telemetry.watchdog import SLOWatchdog
from harp_tpu.telemetry.xprof import XprofController, request_xprof

__all__ = [
    "CommLedger", "MetricsExporter", "SLOWatchdog", "StepLog",
    "XprofController", "active", "aggregate_snapshots", "configure",
    "disable", "gather_snapshots", "ledger_for", "load_manifest",
    "manifest_target", "phase", "prometheus_text",
    "publish_straggler_report", "record_chunk", "record_span",
    "record_timing", "request_xprof", "spans", "straggler_report",
]
