"""On-demand xprof windows — profile a slow gang without restarting it.

A control-plane event (``events.send_collective``; Harp's
CollectiveMapper.sendEvent:645 residual) arms every rank to capture a
``jax.profiler`` trace covering the next N chunk boundaries into a per-rank
directory. The request rides the SAME authenticated host control plane the
gang already synchronizes events over, and start/stop happen strictly at
chunk boundaries — the traced step programs are untouched (the profiler
observes them; it does not change them), so the collective-budget manifest
stays pinned with a window open.

Two trigger paths:

* **embedded** — any rank calls :func:`request_xprof` at a boundary. The
  request is a COLLECTIVE host event: every rank calls it together (the
  SPMD host loops make that free), only the source's payload is delivered.
* **operator** — the run.py CLI cannot inject a collective event from
  outside the gang (the event plane is authenticated and gang-internal), so
  the controller ALSO polls a trigger FILE at every boundary:
  ``<telemetry-dir>/xprof_request.json`` containing ``{"steps": N}``
  (optional ``"dir"``). Drop the file while the job runs and every rank
  opens a window at its next boundary. Window start/stop is purely LOCAL
  (no collective), so ranks reaching the boundary on either side of the
  file write simply open their windows one boundary apart — no alignment
  hazard. Each rank consumes a given file content once (mtime+size token);
  rewrite the file to re-arm.

The training side installs an :class:`XprofController` as a StepLog
boundary hook (``run.py`` does this when telemetry is enabled).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Optional

XPROF_TAG = "harp.telemetry.xprof"


def request_xprof(session, steps: int, directory: str, *,
                  source: int = 0) -> None:
    """Arm an N-boundary profiler window on every rank (COLLECTIVE: all
    ranks call together; the ``source`` rank's payload wins). The window
    opens at each rank's next chunk boundary."""
    session.send_event({"tag": XPROF_TAG, "steps": int(steps),
                        "dir": directory}, source=source)


class XprofController:
    """Boundary hook driving the per-rank profiler window.

    Polls the session event queue at every boundary; on an armed request,
    starts ``jax.profiler`` into ``<dir>/rank<r>/`` and stops it after the
    requested number of boundaries. Non-xprof events are re-enqueued
    untouched. One window at a time; a request arriving mid-window extends
    nothing and is dropped with a note (re-arm after the window closes).
    """

    def __init__(self, session, rank: Optional[int] = None,
                 trigger_path: Optional[str] = None,
                 default_dir: Optional[str] = None):
        self.session = session
        self.rank = (int(os.environ.get("HARP_PROCESS_ID", "0"))
                     if rank is None else rank)
        self.remaining = 0
        self.trace_dir: Optional[str] = None
        self.trigger_path = trigger_path
        self.default_dir = default_dir
        self._consumed_token = None
        if trigger_path:
            # a trigger file left over from a PREVIOUS run must not open a
            # window at boundary 1 of this one: only writes after startup arm
            try:
                st = os.stat(trigger_path)
                self._consumed_token = (st.st_mtime_ns, st.st_size)
            except OSError:
                pass

    def _poll_request(self) -> Optional[dict]:
        requeue = []
        found = None
        while True:
            ev = self.session.get_event()
            if ev is None:
                break
            payload = ev.payload
            if (isinstance(payload, dict)
                    and payload.get("tag") == XPROF_TAG and found is None):
                found = payload
            else:
                requeue.append(ev)
        if requeue:
            queue = self.session.open_events()[0]
            for ev in requeue:
                queue.put(ev)
        if found is None:
            found = self._poll_trigger_file()
        return found

    def _poll_trigger_file(self) -> Optional[dict]:
        """The operator path: a JSON trigger file next to the telemetry
        output (module docstring). Malformed content is reported once per
        write, never fatal — a typo must not kill a training gang."""
        if not self.trigger_path:
            return None
        try:
            st = os.stat(self.trigger_path)
        except OSError:
            return None
        token = (st.st_mtime_ns, st.st_size)
        if token == self._consumed_token:
            return None
        self._consumed_token = token
        try:
            with open(self.trigger_path) as f:
                req = json.load(f)
            steps = int(req["steps"])
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"harp_tpu.telemetry: bad xprof trigger file "
                  f"{self.trigger_path}: {e}", file=sys.stderr, flush=True)
            return None
        out = req.get("dir") or self.default_dir
        if not out:
            print(f"harp_tpu.telemetry: xprof trigger file has no 'dir' and "
                  f"no default directory is configured",
                  file=sys.stderr, flush=True)
            return None
        return {"tag": XPROF_TAG, "steps": steps, "dir": out}

    def _start(self, req: dict) -> None:
        from harp_tpu.utils import tracing

        self.trace_dir = os.path.join(req["dir"], f"rank{self.rank}")
        os.makedirs(self.trace_dir, exist_ok=True)
        tracing.start_trace(self.trace_dir)
        self.remaining = max(1, int(req["steps"]))
        print(f"harp_tpu.telemetry: xprof window open (rank {self.rank}, "
              f"{self.remaining} boundaries) -> {self.trace_dir}",
              file=sys.stderr, flush=True)

    def _stop(self) -> None:
        from harp_tpu.utils import tracing

        tracing.stop_trace()
        print(f"harp_tpu.telemetry: xprof window closed (rank {self.rank}) "
              f"-> {self.trace_dir}", file=sys.stderr, flush=True)
        self.remaining = 0

    @property
    def tracing(self) -> bool:
        return self.remaining > 0

    def __call__(self, boundary_index: int, log=None) -> None:
        """Tick one chunk boundary (StepLog boundary-hook signature)."""
        if self.tracing:
            self.remaining -= 1
            if self.remaining == 0:
                self._stop()
        req = self._poll_request()
        if req is not None:
            if self.tracing:
                print("harp_tpu.telemetry: xprof request ignored — a window "
                      "is already open (re-arm after it closes)",
                      file=sys.stderr, flush=True)
            else:
                self._start(req)

    def close(self) -> None:
        """End-of-job safety: a window left open past the last boundary is
        closed so the trace file is complete."""
        if self.tracing:
            self._stop()
