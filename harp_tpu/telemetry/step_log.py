"""Per-step structured telemetry — bounded ring buffer, JSONL flush.

Design contract (the tentpole's hard constraint): the models call
:func:`record_chunk` ONLY at host chunk boundaries where the per-step losses
are ALREADY host-synced (the ``fit_checkpointed`` chunk fetch, the final
``np.asarray`` of a scanned fit). No call here ever touches a device array,
so no new D2H sync can enter a jitted step program — the traced step programs
the collective-budget manifest pins (JL201/JL203) are bitwise identical with
telemetry on or off, and when telemetry is DISABLED (the default) the whole
layer is one module-level ``None`` check per boundary.

Events are one JSON object per training step::

    {"v": 1, "model": "kmeans", "rank": 0, "step": 17, "loss": 81.2,
     "step_s": 0.0031, "chunk_steps": 4, "chunk_wall_s": 0.0124,
     "phase": "fit", "ts": 1723456789.2, ...}

``step_s`` is the chunk wall amortized over the chunk's steps when the chunk
ran several iterations inside one compiled program (the honest per-step figure
available without syncing inside the scan); a one-step chunk's ``step_s`` is
a real per-step measurement. Events land in a bounded ring (oldest dropped
first, drops counted) and flush as JSONL to ``<dir>/rank<r>/steps.jsonl`` at
boundary cadence — never inside a step.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

EVENT_VERSION = 1
DEFAULT_CAPACITY = 4096        # ring slots (events), not bytes
DEFAULT_INTERVAL = 16          # chunk boundaries between flushes/hook runs

ENV_DIR = "HARP_TELEMETRY_DIR"
ENV_INTERVAL = "HARP_TELEMETRY_INTERVAL"


def _rank() -> int:
    return int(os.environ.get("HARP_PROCESS_ID", "0"))


class StepLog:
    """Bounded per-rank step-event buffer with JSONL persistence.

    ``interval`` is counted in chunk BOUNDARIES, not seconds: in a gang every
    rank runs the same SPMD host loop, so a count-based cadence keeps the
    boundary hooks (gang snapshot exchange, xprof windows — both collective
    host operations) aligned across ranks, where a wall-clock cadence would
    let rank A broadcast while rank B still thinks it has 100 ms to go.
    """

    def __init__(self, directory: str, *, capacity: int = DEFAULT_CAPACITY,
                 interval: int = DEFAULT_INTERVAL,
                 rank: Optional[int] = None, metrics=None):
        if metrics is None:
            from harp_tpu.utils.metrics import DEFAULT as metrics
        self.directory = directory
        self.rank = _rank() if rank is None else rank
        self.interval = max(1, int(interval))
        self.metrics = metrics
        self.capacity = capacity
        self.dropped = 0
        self.boundaries = 0
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        # flush drains the ring with a check-then-popleft loop and appends
        # to the JSONL file: single-writer in the training loops, but the
        # serving plane's span recorder (telemetry.spans.record_span) runs
        # on every RouterClient receive thread — the lock makes the drain
        # and the file append atomic (emit stays lock-free: deque.append
        # is atomic under the GIL)
        self._flush_lock = threading.Lock()
        self._hooks: List[Callable[[int, "StepLog"], None]] = []
        self._rank_dir = os.path.join(directory, f"rank{self.rank}")
        os.makedirs(self._rank_dir, exist_ok=True)
        self.path = os.path.join(self._rank_dir, "steps.jsonl")

    # -- ring ---------------------------------------------------------------
    def emit(self, event: Dict) -> None:
        if len(self._ring) == self.capacity:
            # deque(maxlen) evicts silently; count the loss so a too-small
            # ring is visible in the metrics snapshot instead of silent
            self.dropped += 1
            self.metrics.count("telemetry.events_dropped")
        self._ring.append(event)

    def flush(self) -> int:
        """Drain the ring to the per-rank JSONL file; returns events
        written. Thread-safe (span recorders flush from client receive
        threads)."""
        with self._flush_lock:
            if not self._ring:
                return 0
            n = 0
            with open(self.path, "a") as f:
                while self._ring:
                    f.write(json.dumps(self._ring.popleft()) + "\n")
                    n += 1
        self.metrics.count("telemetry.events_flushed", n)
        return n

    # -- boundary hooks (gang aggregation, xprof windows) -------------------
    def add_boundary_hook(self, fn: Callable[[int, "StepLog"], None]) -> None:
        """Register ``fn(boundary_index, log)`` to run at EVERY chunk
        boundary (hooks gate themselves on cadence — the xprof window must
        tick per boundary while the gang gather runs every ``interval``)."""
        self._hooks.append(fn)

    def boundary(self) -> None:
        """One chunk boundary: run hooks, flush on the interval cadence."""
        self.boundaries += 1
        for fn in list(self._hooks):
            fn(self.boundaries, self)
        if self.boundaries % self.interval == 0 \
                or len(self._ring) >= self.capacity:
            self.flush()

    def close(self) -> None:
        """Flush and close boundary hooks that hold resources (an xprof
        window still open at the last boundary must stop its trace or the
        profile is never written — XprofController.close)."""
        for fn in self._hooks:
            closer = getattr(fn, "close", None)
            if closer is not None:
                closer()
        self.flush()


# -- module-level active log (the models' single None-check fast path) -------

_active: Optional[StepLog] = None
_env_checked = False
_atexit_installed = False


def _flush_at_exit() -> None:
    # the last chunk of a run usually lands below the flush cadence — a
    # process exiting must not lose the tail of its step log, and a
    # still-open xprof window must stop its trace (close() handles both)
    if _active is not None:
        _active.close()


def configure(directory: Optional[str] = None, *,
              interval: Optional[int] = None,
              capacity: int = DEFAULT_CAPACITY,
              rank: Optional[int] = None, metrics=None) -> Optional[StepLog]:
    """Install the process StepLog. ``directory=None`` reads
    ``HARP_TELEMETRY_DIR`` (still-unset means telemetry stays off). Returns
    the active log (or None). Reconfiguring replaces the log after flushing
    the old one."""
    global _active, _env_checked
    _env_checked = True
    if directory is None:
        directory = os.environ.get(ENV_DIR) or None
    if interval is None:
        interval = int(os.environ.get(ENV_INTERVAL, DEFAULT_INTERVAL))
    if _active is not None:
        _active.close()
        _active = None
    if directory:
        _active = StepLog(directory, capacity=capacity, interval=interval,
                          rank=rank, metrics=metrics)
        global _atexit_installed
        if not _atexit_installed:
            atexit.register(_flush_at_exit)
            _atexit_installed = True
    return _active


def disable() -> None:
    """Flush and turn telemetry off (tests; also ignores the env var until
    the next explicit :func:`configure`)."""
    global _active, _env_checked
    if _active is not None:
        _active.close()
    _active = None
    _env_checked = True


def active() -> Optional[StepLog]:
    """The process StepLog, auto-configured from the environment on first
    use (gang members inherit HARP_TELEMETRY_DIR from the launcher)."""
    global _env_checked
    if _active is None and not _env_checked:
        if os.environ.get(ENV_DIR):
            return configure()
        _env_checked = True
    return _active


# -- the one call the models make --------------------------------------------

def record_chunk(model: str, *, start: int,
                 losses: Optional[Sequence[float]] = None,
                 steps: Optional[int] = None,
                 wall_s: Optional[float] = None,
                 ledger=None, phase: str = "fit",
                 extra: Optional[Dict] = None) -> None:
    """Record one host chunk boundary: ``steps`` training steps beginning at
    0-based ``start``, with per-step ``losses`` that are ALREADY host-synced
    (never pass device arrays — convert at an existing D2H point or pass
    None), the chunk's measured ``wall_s``, and an optional
    :class:`~harp_tpu.telemetry.comm_ledger.CommLedger` to advance.

    No-op (one None check) when telemetry is off.
    """
    log = active()
    if log is None:
        return
    n = steps if steps is not None else (len(losses) if losses is not None
                                         else 1)
    if n <= 0:
        return
    step_s = (wall_s / n) if wall_s is not None else None
    if step_s is not None:
        # the straggler detector's signal: per-step wall into the bounded
        # timer reservoir (one sample per step so p50 weighs steps, not
        # chunks of different lengths)
        for _ in range(n):
            log.metrics.observe(f"telemetry.step.{model}", step_s)
    if ledger is not None:
        ledger.on_steps(n, wall_s=wall_s)
    ts = time.time()
    base = {"v": EVENT_VERSION, "model": model, "rank": log.rank,
            "phase": phase, "ts": round(ts, 3)}
    if extra:
        base.update(extra)
    if ledger is not None and ledger.bytes_per_step is not None:
        base["wire_bytes_per_step"] = ledger.bytes_per_step
        # "scaled": the model computed its payload ratio vs the traced shape
        # (exact); "traced_shape": fixed reference pricing, exact only at
        # tier-1 shapes (comm_ledger module docstring)
        base["wire_pricing"] = ("scaled" if getattr(ledger, "exact", False)
                                else "traced_shape")
    for i in range(n):
        ev = dict(base)
        ev["step"] = start + i
        ev["chunk_steps"] = n
        if wall_s is not None:
            ev["step_s"] = round(step_s, 9)
            ev["chunk_wall_s"] = round(wall_s, 6)
        if losses is not None and i < len(losses):
            ev["loss"] = float(losses[i])
        log.emit(ev)
    log.metrics.count(f"telemetry.steps.{model}", n)
    log.boundary()


def record_timing(name: str, *, timer: Optional[str] = None,
                  metrics=None, extra: Optional[Dict] = None) -> None:
    """Surface a bounded-timer percentile snapshot into ``steps.jsonl``.

    One event, ``kind: "timing"``, whose latency fields are EXACTLY
    ``utils.metrics.Metrics.timing()`` output (count/total_s/mean_s/last_s/
    p50_s/p90_s/p99_s) — the same schema the straggler report's per-rank
    rows carry, so serving-bench latency rows and straggler reports share
    one latency format instead of two drifting ones. ``timer`` names the
    reservoir to snapshot (default: ``name``); ``metrics`` overrides the
    registry (the serving load generator keeps per-mix registries so one
    mix's reservoir never dilutes the next). No-op when telemetry is off or
    the timer has no samples.
    """
    log = active()
    if log is None:
        return
    reg = metrics if metrics is not None else log.metrics
    t = reg.timing(timer or name)
    if not t:
        return
    ev = {"v": EVENT_VERSION, "kind": "timing", "name": name,
          "rank": log.rank, "ts": round(time.time(), 3)}
    ev.update(t)
    if extra:
        ev.update(extra)
    log.emit(ev)
    log.boundary()


@contextlib.contextmanager
def phase(name: str):
    """Host phase timer (checkpoint save, data load, gang gather): records
    into the bounded ``telemetry.phase.<name>`` timer when telemetry is on;
    a plain no-op otherwise."""
    log = active()
    if log is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        log.metrics.observe(f"telemetry.phase.{name}",
                            time.perf_counter() - t0)
