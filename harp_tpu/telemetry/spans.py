"""End-to-end request tracing for the serving plane — per-stage spans.

PR 10's serving path was a black box between client submit and reply: the
load generator measured end-to-end latency, the batcher gauged its own
dispatch wall, and nothing connected the two. This module makes every
sampled request carry a **trace**: a tiny dict riding the request frame
(the p2p transport pickles plain dicts — same reasoning as the protocol
frames) that every HOST boundary the request already crosses stamps with a
``(stage, wall-clock)`` pair:

========================  ====================================================
stage                     stamped by
========================  ====================================================
``submit``                ``RouterClient.submit`` (request leaves the client)
``recv``                  ``ServeWorker._handle`` (every worker that receives
                          the frame — twice when forwarded)
``forward``               the non-owning worker, before the forward send
``enqueue``               ``MicroBatcher.submit`` (accepted for coalescing)
``dispatch_start``        the batcher, immediately before the endpoint
                          dispatch (the resident compiled fn)
``dispatch_end``          the batcher, immediately after
``reply_send``            ``ServeWorker._reply`` (reply leaves the owner)
``reply_recv``            ``RouterClient._recv_loop`` (reply arrives)
========================  ====================================================

The reply carries the accumulated trace back, so the CLIENT holds the full
span and reconstructs the breakdown (:func:`breakdown`): the six stage
durations PARTITION the end-to-end latency exactly —

    total = submit_hop + route + coalesce + dispatch + reply_build
            + reply_hop

(``route`` covers receive→enqueue including the forward hop when the
request landed on a non-owning worker; ``forward_hop`` is additionally
reported on its own). Completed spans are observed into per-stage bounded
timers (``serve.span.<stage>``) and sampled into the PR 7 JSONL stream as
``kind: "span"`` events (:func:`record_span`) — same file, same versioned
schema, same bounded ring as the training step events.

**Zero-drift contract (the PR 7 contract extended to serving).** Every
stamp above sits in host router/batcher Python, around — never inside —
the resident jitted dispatch. The collective-budget manifest is
byte-identical with request tracing enabled; ``tools/ci_checks.sh`` stage 2
runs the jaxpr engine with BOTH ``HARP_TELEMETRY_DIR`` and
``HARP_TRACE_REQUESTS`` set and tier-1 keeps the serve-target version of
the check, so the contract is gated, not promised.

Sampling: a client samples every Nth request (``trace_sample=N`` on
:class:`~harp_tpu.serve.router.RouterClient`, or the
``HARP_TRACE_REQUESTS`` environment variable; ``1`` traces everything,
``0``/unset disables). An unsampled request carries no trace key and pays
one dict lookup per boundary.

Clocks: stamps are ``time.time()`` so a multi-host gang produces
comparable timelines; within one host the stage deltas are exact, across
hosts the two hop stages absorb any clock skew (documented — the fleet
item's NTP-bounded skew note rides there, not here).
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

TRACE_KEY = "trace"
ENV_SAMPLE = "HARP_TRACE_REQUESTS"

# stage names (the stamp vocabulary — breakdown() depends on these)
SUBMIT = "submit"
RECV = "recv"
FORWARD = "forward"
ENQUEUE = "enqueue"
DISPATCH_START = "dispatch_start"
DISPATCH_END = "dispatch_end"
REPLY_SEND = "reply_send"
REPLY_RECV = "reply_recv"

# the stages whose durations partition the end-to-end latency, in order
STAGES = ("submit_hop", "route", "coalesce", "dispatch", "reply_build",
          "reply_hop")

SPAN_VERSION = 1


def env_sample_interval() -> int:
    """The process-default sampling interval (0 = tracing off)."""
    try:
        return max(0, int(os.environ.get(ENV_SAMPLE, "0") or 0))
    except ValueError:
        return 0


def start_trace(msg: Dict, *, op: str, model: str) -> Dict:
    """Attach a fresh trace to an outgoing request frame and stamp
    ``submit``. The trace id IS the request id (already unique per client),
    so reply matching and span matching share one identity."""
    tr = {"id": msg["id"], "op": op, "model": model, "stamps": []}
    msg[TRACE_KEY] = tr
    stamp(msg, SUBMIT)
    return tr


def stamp(msg: Dict, stage: str) -> None:
    """Stamp one host boundary on a request/reply frame; a frame without a
    trace (unsampled — the common case) costs exactly this dict lookup."""
    tr = msg.get(TRACE_KEY)
    if tr is not None:
        tr["stamps"].append((stage, time.time()))


def stamp_trace(tr: Dict, stage: str) -> None:
    """Stamp a bare trace dict (the reply path holds the trace after the
    request frame is gone)."""
    tr["stamps"].append((stage, time.time()))


def _first(stamps: List, stage: str) -> Optional[float]:
    for s, ts in stamps:
        if s == stage:
            return ts
    return None


def _last(stamps: List, stage: str) -> Optional[float]:
    out = None
    for s, ts in stamps:
        if s == stage:
            out = ts
    return out


def breakdown(tr: Dict) -> Optional[Dict]:
    """Reconstruct the per-stage durations of a completed span.

    Returns ``None`` when the span is incomplete (a request rejected
    before the batcher — draining, unknown model, validation — never
    reaches the dispatch stamps; callers count those, they don't chart
    them). The six stage durations sum to ``total_s`` exactly: they are
    consecutive differences over one ordered stamp sequence.
    """
    stamps = tr.get("stamps", ())
    submit = _first(stamps, SUBMIT)
    recv_last = _last(stamps, RECV)
    enqueue = _first(stamps, ENQUEUE)
    d0 = _first(stamps, DISPATCH_START)
    d1 = _first(stamps, DISPATCH_END)
    rs = _first(stamps, REPLY_SEND)
    rr = _first(stamps, REPLY_RECV)
    if None in (submit, recv_last, enqueue, d0, d1, rs, rr):
        return None
    recv_first = _first(stamps, RECV)
    fwd = _first(stamps, FORWARD)
    out = {
        "trace_id": tr.get("id"),
        "op": tr.get("op"),
        "model": tr.get("model"),
        "forwarded": fwd is not None,
        "total_s": rr - submit,
        "submit_hop_s": recv_first - submit,
        "route_s": enqueue - recv_first,
        "coalesce_s": d0 - enqueue,
        "dispatch_s": d1 - d0,
        "reply_build_s": rs - d1,
        "reply_hop_s": rr - rs,
    }
    if fwd is not None:
        out["forward_hop_s"] = recv_last - fwd
    # multi-host clock-skew bound (ISSUE 14): stamps are wall-clock, so on
    # a cross-host hop the skew lands entirely in the two hop stages. A
    # NEGATIVE hop duration is impossible on a true timeline — its
    # magnitude is therefore a per-span LOWER BOUND on the client↔worker
    # clock offset, surfaced here (and counted by observe_span) so the
    # fleet can check its NTP story against live traffic instead of
    # trusting it. The partition identity is preserved (nothing is
    # clamped): total still equals the stage sum exactly.
    skew_lb = max(0.0, -out["submit_hop_s"], -out["reply_hop_s"])
    if skew_lb > 0.0:
        out["clock_skew_lb_s"] = skew_lb
    return out


def observe_span(bd: Dict, metrics) -> None:
    """Feed one breakdown into the bounded per-stage timers — the surface
    the serving bench's stage table and the SLO watchdog read. Names:
    ``serve.span.total`` plus ``serve.span.<stage>`` per partition stage."""
    metrics.observe("serve.span.total", bd["total_s"])
    for stage in STAGES:
        metrics.observe(f"serve.span.{stage}", bd[f"{stage}_s"])
    if bd.get("model"):
        # the one per-model stage split (ISSUE 15 satellite): coalesce is
        # the stage a per-model max_wait_s deadline governs, so the
        # suggest_max_wait_s helper needs it PER MODEL — one extra
        # reservoir per served model, nothing else fans out
        metrics.observe(f"serve.span.coalesce.{bd['model']}",
                        bd["coalesce_s"])
    metrics.count("serve.spans")
    if bd["forwarded"]:
        metrics.count("serve.spans_forwarded")
    if bd.get("clock_skew_lb_s"):
        # a cross-host span whose hop went negative: the gang's clocks are
        # at least this far apart — the fleet's NTP bound is violated when
        # this grows past it
        metrics.count("serve.spans_skewed")
        metrics.observe("serve.span.clock_skew_lb", bd["clock_skew_lb_s"])


def record_span(bd: Dict, *, extra: Optional[Dict] = None) -> None:
    """Emit one completed span into the PR 7 JSONL stream as a
    ``kind: "span"`` event (same versioned schema family, same bounded
    ring, ``DIR/rank<r>/steps.jsonl``). No-op when telemetry is off.

    Unlike ``record_chunk``/``record_timing`` this does NOT tick a StepLog
    boundary: boundaries run gang-collective hooks on a count cadence, and
    a serving client shares no cadence with a training loop — spans flush
    on the log's interval of recorded spans instead (plus the existing
    ring-capacity and atexit flushes).
    """
    from harp_tpu.telemetry import step_log

    log = step_log.active()
    if log is None:
        return
    ev = {"v": SPAN_VERSION, "kind": "span", "rank": log.rank,
          "ts": round(time.time(), 3)}
    for k, v in bd.items():
        ev[k] = round(v, 9) if isinstance(v, float) else v
    if extra:
        ev.update(extra)
    log.emit(ev)
    log.metrics.count("telemetry.spans")
    if log.metrics.counters["telemetry.spans"] % log.interval == 0:
        log.flush()
