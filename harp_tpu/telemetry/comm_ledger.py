"""Comm-volume accounting priced off the pinned collective-budget manifest.

EQuARX (arXiv:2506.17615) treats wire bytes as a first-class measured
quantity. This repo already PINS per-step collective operand bytes statically:
``tools/collective_budget.json`` (jaxlint JL203) records, for every traced
step program, the collective count/kind AND byte volume — including the
quantized twins, whose rows sit far below their f32 counterparts. Runtime
comm-volume telemetry therefore needs no hot-path instrumentation at all:
join the host step counter with the model's manifest row and multiply.

The manifest rows are traced at tier-1 shapes; a job at different shapes
passes ``scale`` = (its per-step collective payload elements) / (the traced
shape's) — for the stat-table workloads that ratio is exact for the dominant
payload (K-means: the padded ``(k_pad, d_pad+1)`` f32 table via
``KMeans.comm_scale``; the few-byte scalar-cost psum rides unscaled and is
noise). Models that do NOT compute a scale (lda/sgd_mf/als/nn today) get
TRACED-SHAPE pricing: the row is exact only at tier-1 shapes and otherwise a
fixed per-step reference volume, NOT the job's true bytes. That distinction
is machine-readable, not prose: ``exact=False`` ledgers publish
``comm.<target>.pricing_exact = 0`` and stamp every step event's pricing
field (step_log attaches ``wire_pricing: "traced_shape"``), so a dashboard
cannot mistake a reference counter for a measurement.

Gauges published into the metrics registry (visible in every
``Metrics.snapshot()`` the gang layer exchanges)::

    comm.<target>.wire_bytes_per_step    manifest-priced bytes per step
    comm.<target>.cumulative_gb          bytes_per_step x steps / 1e9
    comm.<target>.busbw_gbps             bytes moved / wall seconds (when the
                                         boundary passes wall_s)
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
MANIFEST_PATH = os.path.join(_REPO_ROOT, "tools", "collective_budget.json")

_manifest_cache: Dict[str, dict] = {}


def load_manifest(path: Optional[str] = None) -> dict:
    """The pinned budget manifest (cached per path); ``{}`` targets when the
    file is absent (an installed wheel without the tools tree) — the ledger
    then prices nothing rather than crashing training."""
    p = path or MANIFEST_PATH
    if p not in _manifest_cache:
        try:
            with open(p) as f:
                _manifest_cache[p] = json.load(f)
        except (OSError, json.JSONDecodeError):
            _manifest_cache[p] = {"targets": {}}
    return _manifest_cache[p]


def manifest_target(model: str, *, comm: Optional[str] = None,
                    quant: Optional[str] = None,
                    sub_block: bool = False,
                    manifest_path: Optional[str] = None) -> Optional[str]:
    """Resolve a model config to its budget-manifest row name.

    Quantized paths resolve to their quantized twin when the manifest pins
    one (``kmeans_allreduce_int8``), falling back to the f32 row otherwise
    (the counts still hold; the byte price is then an upper bound and the
    fallback is recorded by the caller's gauge name staying unsuffixed).
    Returns None when no row matches — the ledger stays inert.
    """
    targets = load_manifest(manifest_path).get("targets", {})
    base = {
        "kmeans": f"kmeans_{comm}" if comm else None,
        "lda": "lda_cgs_subblock128" if sub_block else "lda_cgs",
        "sgd_mf": "sgd_mf_dense",
        "als": "als_explicit",
        "nn": "nn_mlp",
        "pagerank": "pagerank",
    }.get(model)
    if base is None:
        return None
    if quant:
        suffixed = f"{base}_{quant}"
        if suffixed in targets:
            return suffixed
    return base if base in targets else None


class CommLedger:
    """Step-counter -> wire-volume join against one manifest row."""

    def __init__(self, target: Optional[str], *, scale: float = 1.0,
                 exact: bool = False,
                 manifest_path: Optional[str] = None, metrics=None):
        if metrics is None:
            from harp_tpu.utils.metrics import DEFAULT as metrics
        self.metrics = metrics
        self.target = target
        self.steps = 0
        self.wall_s = 0.0
        self.scale = scale
        # exact=True ONLY when the caller computed a real payload scale for
        # its shapes (KMeans.comm_scale); False = traced-shape reference
        # pricing, flagged in the gauges and step events
        self.exact = exact
        row = (load_manifest(manifest_path).get("targets", {}).get(target)
               if target else None)
        self.bytes_per_step: Optional[float] = (
            row["bytes_per_step"] * scale
            if row and "bytes_per_step" in row else None)
        self.bytes_by_kind: Dict[str, float] = (
            {k: v * scale for k, v in row.get("bytes_by_kind", {}).items()}
            if row else {})

    @property
    def cumulative_bytes(self) -> float:
        return (self.bytes_per_step or 0.0) * self.steps

    def on_steps(self, n: int, wall_s: Optional[float] = None) -> None:
        """Advance the counter by ``n`` steps (``wall_s``: the chunk's wall,
        for the achieved-busbw gauge). Inert when no manifest row matched."""
        if self.bytes_per_step is None or n <= 0:
            return
        self.steps += n
        if wall_s:
            self.wall_s += wall_s
        pfx = f"comm.{self.target}"
        self.metrics.gauge(f"{pfx}.pricing_exact", 1.0 if self.exact else 0.0)
        self.metrics.gauge(f"{pfx}.wire_bytes_per_step", self.bytes_per_step)
        self.metrics.gauge(f"{pfx}.cumulative_gb",
                           self.cumulative_bytes / 1e9)
        if self.wall_s > 0:
            self.metrics.gauge(f"{pfx}.busbw_gbps",
                               self.cumulative_bytes / self.wall_s / 1e9)

    def snapshot(self) -> dict:
        return {"target": self.target, "steps": self.steps,
                "scale": self.scale, "exact": self.exact,
                "bytes_per_step": self.bytes_per_step,
                "cumulative_bytes": self.cumulative_bytes,
                "bytes_by_kind": self.bytes_by_kind}


def ledger_for(model: str, *, comm: Optional[str] = None,
               quant: Optional[str] = None, sub_block: bool = False,
               scale: Optional[float] = None, exact: Optional[bool] = None,
               metrics=None) -> Optional[CommLedger]:
    """A ledger for the model's manifest row — or None when telemetry is off
    (so the models' fast path stays a single check) or no row matches.
    ``scale=None`` means the caller did not compute a payload scale: the row
    is traced-shape reference pricing and is flagged as such (class
    docstring). Passing a scale claims exact pricing UNLESS ``exact=False``
    overrides — a scale can be right for the payload shape but the traced
    collective operands also depend on e.g. the worker count (K-means at
    ``num_workers != 8`` passes its element ratio with ``exact=False``)."""
    from harp_tpu.telemetry import step_log

    log = step_log.active()
    if log is None:
        return None
    target = manifest_target(model, comm=comm, quant=quant,
                             sub_block=sub_block)
    if target is None:
        return None
    if exact is None:
        exact = scale is not None
    return CommLedger(target, scale=1.0 if scale is None else scale,
                      exact=exact,
                      metrics=metrics if metrics is not None else log.metrics)
