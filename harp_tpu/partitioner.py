"""Partitioners — map partition IDs to owning workers.

Reference parity: ``partition/Partitioner`` (partition/Partitioner.java:24, default
``partitionID % numWorkers``) and the per-algorithm custom partitioners.

TPU-native design: XLA collectives want *block* layouts — worker ``w`` owns the
contiguous slice ``[w*B, (w+1)*B)`` of the partition axis, because that is what
``psum_scatter``/``all_gather`` produce natively. So the canonical owner map here is
BLOCK, and MODULO (Harp's default) is expressed as BLOCK composed with a static
permutation of the partition axis. Arbitrary owner maps are supported the same way:
any assignment with equal per-worker counts is a permutation away from BLOCK; unequal
assignments are padded to the max count.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class Partitioner:
    """Maps each of ``num_partitions`` IDs to one of ``num_workers`` owners.

    ``permutation()`` returns the static index vector ``perm`` such that reordering
    the partition axis by ``perm`` puts every worker's partitions into one contiguous
    block (worker 0's block first). ``num_partitions`` must be a multiple of
    ``num_workers`` after padding (Table handles padding).
    """

    num_partitions: int
    num_workers: int

    def owner(self, pid: np.ndarray | int):
        raise NotImplementedError

    def permutation(self) -> np.ndarray:
        pids = np.arange(self.num_partitions)
        owners = np.asarray(self.owner(pids))
        counts = np.bincount(owners, minlength=self.num_workers)
        if counts.max() != counts.min():
            raise ValueError(
                "unequal partitions per worker "
                f"({counts.tolist()}); pad the table first"
            )
        # Stable sort by owner: block order, preserving ID order within a worker.
        return np.argsort(owners, kind="stable")

    def inverse_permutation(self) -> np.ndarray:
        perm = self.permutation()
        inv = np.empty_like(perm)
        inv[perm] = np.arange(len(perm))
        return inv

    @property
    def is_block(self) -> bool:
        return bool(np.all(self.permutation() == np.arange(self.num_partitions)))


@dataclasses.dataclass(frozen=True)
class BlockPartitioner(Partitioner):
    """Worker w owns contiguous block w — the XLA-native layout."""

    def owner(self, pid):
        block = self.num_partitions // self.num_workers
        return np.asarray(pid) // block


@dataclasses.dataclass(frozen=True)
class ModuloPartitioner(Partitioner):
    """Harp's default: owner = pid % num_workers (partition/Partitioner.java:24)."""

    def owner(self, pid):
        return np.asarray(pid) % self.num_workers


@dataclasses.dataclass(frozen=True)
class CustomPartitioner(Partitioner):
    """Explicit owner table (tuple so the dataclass stays hashable/static)."""

    owners: tuple = ()

    def owner(self, pid):
        return np.asarray(self.owners)[np.asarray(pid)]


def default_partitioner(num_partitions: int, num_workers: int) -> Partitioner:
    return BlockPartitioner(num_partitions, num_workers)
