"""Restore-time re-partitioning — world-size-agnostic checkpoint resume.

Elastic re-placement (parallel.supervisor) can relaunch a gang one member
smaller when a host vanishes and no spare is left, so a checkpoint written by
W workers must restore into a W' != W gang. That is the array-redistribution
problem of arXiv:2112.01075 (portable collective-based resharding) applied at
RESTORE time instead of in-program: the reference never faced it because its
failure story ended at "Slaves may fail" (Communication.java:82) — the job
died at the original shape or not at all.

Everything here is HOST-side numpy, run once between attempts, OUTSIDE every
compiled step program. The jaxlint collective budgets (JL201/JL203) therefore
stay bitwise: restore never traces, never adds a collective, never changes a
pinned step program — the resized gang's programs are simply the ones the new
world size always had.

**r12:** this module is no longer the default resume path — it is the PARITY
ORACLE and 1-worker fallback for :mod:`collectives.reshard`, the device-side
twin that moves the same rows between the same layouts ON the mesh in
chunk-bounded collective rounds (bitwise-equal by contract,
tests/test_reshard.py). Full-table host materialization is exactly what
production factor-table sizes cannot afford; keep new call sites on the
device engine unless they run where no mesh exists.

Two leaf families, mirroring the table partitioners next door (table_ops):

* **replicated** leaves (K-means centroids) re-partition EXACTLY — identity;
  every worker already holds the full array and the new world replicates it.
* **sharded** leaves gather-and-resplit: the checkpoint stores the permuted
  device layout PLUS its (bin, slot) id assignments
  (sgd_mf.serpentine_assign / identity_assign), so resume de-permutes to
  canonical id order with the SAVED maps and re-permutes with the NEW
  session's maps. Padded slots (ids no data references) take the new run's
  fresh init values — they are never read by training math and never
  contribute to a loss.

LDA's chain state needs one more tool: topic assignments live per TOKEN in a
blocked layout whose bucket order depends on the world size. Occurrences of
the same word in the same document are exchangeable in the collapsed-Gibbs
state (doc-topic, word-topic and topic-total counts are all invariant under
permuting them), so :func:`rematch_tokens` transfers per-token payloads
between layouts by matching on the (doc, vocab-id) key.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def unpermute_rows(permuted: np.ndarray, bins: np.ndarray, slots: np.ndarray,
                   rows_per_bin: int, n_valid: int) -> np.ndarray:
    """Permuted block layout ``(num_bins * rows_per_bin, ...)`` → canonical
    id order ``(n_valid, ...)``: ``canonical[i] = permuted[bins[i] *
    rows_per_bin + slots[i]]`` (the gather half of gather-and-resplit)."""
    permuted = np.asarray(permuted)
    idx = (np.asarray(bins[:n_valid], np.int64) * rows_per_bin
           + np.asarray(slots[:n_valid], np.int64))
    if len(idx) and (idx.min() < 0 or idx.max() >= permuted.shape[0]):
        raise ValueError(
            f"assignment maps address rows outside the saved layout "
            f"({permuted.shape[0]} rows, max index {idx.max()}) — the "
            f"checkpoint's maps do not describe this payload")
    return permuted[idx]


def permute_rows(canonical: np.ndarray, bins: np.ndarray, slots: np.ndarray,
                 rows_per_bin: int, fill: np.ndarray) -> np.ndarray:
    """Canonical ``(n, ...)`` id order → the permuted block layout of
    ``fill`` (the resplit half). ``fill`` supplies every padded slot — pass
    the new world's fresh init so ids no data references stay initialized
    exactly as an uninterrupted run at the new size would have them."""
    out = np.array(fill, copy=True)
    n = len(canonical)
    idx = (np.asarray(bins[:n], np.int64) * rows_per_bin
           + np.asarray(slots[:n], np.int64))
    if len(idx) and (idx.min() < 0 or idx.max() >= out.shape[0]):
        raise ValueError(
            f"assignment maps address rows outside the new layout "
            f"({out.shape[0]} rows, max index {idx.max()})")
    out[idx] = canonical
    return out


def repartition_factor(saved: np.ndarray,
                       old_assign: Tuple[np.ndarray, np.ndarray],
                       old_rows_per_bin: int,
                       new_assign: Tuple[np.ndarray, np.ndarray],
                       new_rows_per_bin: int,
                       n_valid: int, fill: np.ndarray) -> np.ndarray:
    """Move a row-sharded factor table between block layouts: de-permute
    with the layout it was SAVED under, re-permute with the layout the new
    world PREPARES — exact for every id the data references (sgd_mf W/H
    resume across a shrink/grow)."""
    canonical = unpermute_rows(saved, old_assign[0], old_assign[1],
                               old_rows_per_bin, n_valid)
    return permute_rows(canonical, new_assign[0], new_assign[1],
                        new_rows_per_bin, fill)


def rematch_tokens(old_doc: np.ndarray, old_vocab: np.ndarray,
                   old_payload: np.ndarray,
                   new_doc: np.ndarray, new_vocab: np.ndarray) -> np.ndarray:
    """Transfer per-token payloads between two blocked corpus layouts by
    matching tokens on the (doc, vocab-id) key.

    The k-th occurrence of word v in document d on the old side maps to the
    k-th occurrence on the new side (both sides order occurrences by their
    bucket scan order). Occurrences of the same (d, v) are exchangeable in
    the collapsed-Gibbs chain state — every count the sampler conditions on
    is invariant under permuting them — so the match is exact up to that
    symmetry. Raises when the token multisets disagree (resuming against a
    different corpus)."""
    old_order = np.lexsort((old_vocab, old_doc))
    new_order = np.lexsort((new_vocab, new_doc))
    if not (np.array_equal(np.asarray(old_doc)[old_order],
                           np.asarray(new_doc)[new_order])
            and np.array_equal(np.asarray(old_vocab)[old_order],
                               np.asarray(new_vocab)[new_order])):
        raise ValueError(
            "checkpoint token multiset does not match the prepared corpus "
            "— the resumed run was prepared on different data than the "
            "checkpoint was written from")
    out = np.empty((len(new_doc),) + np.asarray(old_payload).shape[1:],
                   np.asarray(old_payload).dtype)
    out[new_order] = np.asarray(old_payload)[old_order]
    return out
