"""Quantized collectives — wire-format compression for the bandwidth-bound hops.

EQuARX (PAPERS.md, arXiv:2506.17615) shows a quantized AllReduce inside XLA
recovers most of a real mesh's collective bandwidth at negligible accuracy
cost. XLA gives us no hook into its reduction stages, so the same two-stage
decomposition is expressed HERE, at the JAX level, out of primitives whose
wire dtype we control:

  * quantized reduce_scatter = ``all_to_all`` of int8/bf16 chunk payloads
    (+ per-block f32 scales for int8) and a LOCAL f32 dequant-sum — the
    accumulation never happens in the narrow dtype (the repo-wide
    lane_pack/JL202 policy: narrow operands, f32 sums);
  * quantized allgather   = ``all_gather`` of the re-quantized reduced
    chunk (+ scales);
  * quantized allreduce   = the two stages composed (the bandwidth-optimal
    decomposition ``table_ops.aggregate`` already documents for f32);
  * quantized rotate      = ``ppermute`` of the encoded block (+ scales).

Semantics are **dequantize-after-transport**: callers pass f32 and receive
f32 — the wire format changes, the math (f32 accumulation, same combiner)
does not. What DOES change is a bounded per-element quantization error; the
**error-feedback** helpers below carry the encode residual so that error is
re-applied to the next send instead of compounding (EF-SGD: the time-average
of the fed-back error vanishes). Residual state lives

  * in the scan carry of ``rotation.rotate_scan``/``pipelined_rotation``
    for rotation paths (one residual per sender — the standard EF-ring
    formulation), and
  * in model fit state for allreduce paths (KMeans/LDA carry it through
    their iteration scan).

int8 uses symmetric scale-per-block quantization (``CommConfig.block``
elements per f32 scale; blocks adapt down for small payloads so a (K,)
vector never pads to a full block). bf16 is a plain downcast — no scales,
half the bytes, ~8-bit mantissa.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from harp_tpu import combiner as combiner_lib
from harp_tpu import compat

QUANT_MODES = (None, "int8", "bf16")

# guards the scale division; an all-zero block quantizes to zeros exactly
_TINY = 1e-30


@dataclasses.dataclass(frozen=True)
class CommConfig:
    """Opt-in wire-format config threaded through the collective layer.

    ``quant=None`` (the default everywhere) keeps every path bit-identical
    to the pre-quantization f32 programs — the collective-budget manifest
    pins that. ``block`` is the int8 scale granularity in elements (ignored
    by bf16)."""

    quant: Optional[str] = None      # None | "int8" | "bf16"
    block: int = 256                 # elements per f32 scale (int8 only)

    def __post_init__(self):
        if self.quant not in QUANT_MODES:
            raise ValueError(
                f"quant must be one of {QUANT_MODES}, got {self.quant!r}")
        if self.block < 1:
            raise ValueError(f"block must be >= 1, got {self.block}")

    @property
    def active(self) -> bool:
        return self.quant is not None


# --------------------------------------------------------------------------- #
# Codecs: flat f32 vector <-> (payload, scales)
# --------------------------------------------------------------------------- #

def _block_for(n: int, comm: CommConfig, chunks: int = 1) -> int:
    """Effective scale-block size: adapt down so every chunk holds at least
    one whole block (a (K,) LDA delta must not pad to 256 elements)."""
    per_chunk = max(1, -(-n // chunks))
    return max(1, min(comm.block, per_chunk))


def encode_flat(flat: jax.Array, comm: CommConfig, block: int
                ) -> Tuple[jax.Array, Optional[jax.Array], int]:
    """Encode a flat f32 vector. Returns (payload, scales-or-None, n).

    int8 payload is (nb, block) with scales (nb,); bf16 payload is the
    padded flat vector itself (no scales). Padding is zeros — exact under
    both codecs, trimmed by :func:`decode_flat`."""
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    if comm.quant == "bf16":
        return flat.astype(jnp.bfloat16), None, n
    blocks = flat.reshape(-1, block)
    scale = jnp.max(jnp.abs(blocks), axis=1) / 127.0
    q = jnp.clip(jnp.round(blocks / jnp.maximum(scale, _TINY)[:, None]),
                 -127, 127).astype(jnp.int8)
    return q, scale, n


def decode_flat(payload: jax.Array, scale: Optional[jax.Array], n: int,
                comm: CommConfig) -> jax.Array:
    """Inverse of :func:`encode_flat` — back to a flat f32 vector of len n."""
    if comm.quant == "bf16":
        return payload.astype(jnp.float32)[:n]
    flat = (payload.astype(jnp.float32) * scale[:, None]).reshape(-1)
    return flat[:n]


def ef_encode_flat(flat: jax.Array, residual: jax.Array, comm: CommConfig,
                   block: int):
    """Error-feedback encode: compress (x + residual), return the payload
    plus the NEW residual (what the wire failed to carry this round)."""
    y = flat + residual
    payload, scale, n = encode_flat(y, comm, block)
    return payload, scale, n, y - decode_flat(payload, scale, n, comm)


# --------------------------------------------------------------------------- #
# Packed-row codec: f32 factor rows <-> self-describing int8 rows
# --------------------------------------------------------------------------- #
#
# The SERVING-path codec (ISSUE 17). A factor table row quantizes with one
# symmetric per-ROW scale (the row is the dot-product unit, so a per-row
# scale factors out of the score exactly), and the scale travels INSIDE the
# row as its last 4 bytes (the f32 bitcast to int8). The packed row is one
# homogeneous int8 vector, which is what makes it a drop-in KVStore value
# dtype: it rides `DistributedKV.lookup`'s route-back all_to_all, the
# reshard engine's restore/rebalance rounds, and `push_epoch`'s re-scatter
# with zero extra bookkeeping — the scale can never be separated from the
# row it describes. An all-zero row (a KVStore default / a reshard fill)
# decodes to exactly 0.0: the bitcast of four zero bytes is +0.0f.

ROW_SCALE_BYTES = 4          # one f32 scale, bitcast into the row's tail


def encode_rows_np(rows: np.ndarray) -> np.ndarray:
    """Host-side packed-row encode: f32 ``(..., r)`` -> int8 ``(..., r+4)``.

    Symmetric per-row int8 (``scale = max|row| / 127``), scale appended as
    its 4 raw bytes. Numpy's ``.view`` and the device-side
    ``lax.bitcast_convert_type`` both reinterpret native-endian memory, so
    the round trip is exact (pinned by tests/test_serve_quant.py)."""
    rows = np.asarray(rows, np.float32)
    scale = (np.max(np.abs(rows), axis=-1, keepdims=True)
             / 127.0).astype(np.float32)
    q = np.clip(np.rint(rows / np.maximum(scale, _TINY)),
                -127, 127).astype(np.int8)
    return np.concatenate([q, scale.view(np.int8)], axis=-1)


def decode_rows(packed: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Device-side packed-row split: int8 ``(..., r+4)`` ->
    (``(..., r)`` int8 quantized values, ``(...,)`` f32 per-row scales).
    The scale comes back by bitcast — no arithmetic, bit-exact."""
    q = packed[..., :-ROW_SCALE_BYTES]
    scale = jax.lax.bitcast_convert_type(
        packed[..., -ROW_SCALE_BYTES:], jnp.float32)
    return q, scale


def dequantize_rows(packed: jax.Array) -> jax.Array:
    """Device-side packed-row decode back to f32 ``(..., r)``."""
    q, scale = decode_rows(packed)
    return q.astype(jnp.float32) * scale[..., None]


def packed_row_width(r: int) -> int:
    """Trailing width of a packed int8 row for rank-``r`` factors."""
    return int(r) + ROW_SCALE_BYTES


# --------------------------------------------------------------------------- #
# Quantized axis collectives (call inside shard_map over axis_name)
# --------------------------------------------------------------------------- #

def _check_combiner(combiner, op: str) -> None:
    if combiner.op not in (combiner_lib.Op.SUM, combiner_lib.Op.AVG):
        raise ValueError(
            f"quantized {op} supports SUM/AVG combiners only (dequant-sum "
            f"is the transport-side math), got {combiner.op}")


def rotate_q(x: jax.Array, steps: int, axis_name: str,
             comm: CommConfig) -> jax.Array:
    """Quantized ring-shift: encode, ppermute the payload (+scales for
    int8), decode on arrival. One lossy encode per hop; error feedback for
    repeated hops lives in ``rotation.rotate_scan``'s carry."""
    n_ax = compat.axis_size(axis_name)
    perm = [(i, (i + steps) % n_ax) for i in range(n_ax)]
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    block = _block_for(flat.shape[0], comm)
    payload, scale, n = encode_flat(flat, comm, block)
    payload = jax.lax.ppermute(payload, axis_name, perm)
    if scale is not None:
        scale = jax.lax.ppermute(scale, axis_name, perm)
    return decode_flat(payload, scale, n, comm).reshape(shape).astype(x.dtype)


def allgather_q(x: jax.Array, axis_name: str, comm: CommConfig,
                tiled: bool = True) -> jax.Array:
    """Quantized allgather: each worker's block rides the wire encoded and
    is dequantized on arrival — every worker decodes the SAME payload, so
    the gathered result stays replicated-consistent."""
    w = compat.axis_size(axis_name)
    flat = x.reshape(-1).astype(jnp.float32)
    block = _block_for(flat.shape[0], comm)
    payload, scale, n = encode_flat(flat, comm, block)
    all_payload = jax.lax.all_gather(payload, axis_name)       # (W, ...)
    if scale is not None:
        all_scale = jax.lax.all_gather(scale, axis_name)       # (W, nb)
        flat_all = (all_payload.astype(jnp.float32)
                    * all_scale[..., None]).reshape(w, -1)[:, :n]
    else:
        flat_all = all_payload.astype(jnp.float32).reshape(w, -1)[:, :n]
    out = flat_all.reshape((w,) + x.shape).astype(x.dtype)
    if tiled:
        return out.reshape((w * x.shape[0],) + x.shape[1:])
    return out


def reduce_scatter_q(
    x: jax.Array,
    combiner: combiner_lib.Combiner,
    axis_name: str,
    comm: CommConfig,
    residual: Optional[jax.Array] = None,
):
    """Quantized reduce_scatter: worker w receives the f32-accumulated
    combination of every worker's chunk w. Chunks ride the wire encoded
    through ONE all_to_all (+ one for int8 scales); the sum runs in f32
    AFTER dequantization (per-source scales), never in the narrow dtype.

    ``residual`` (shaped like x, f32): error-feedback state — compress
    (x + residual) and return the new residual alongside the result."""
    _check_combiner(combiner, "reduce_scatter")
    w = compat.axis_size(axis_name)
    p = x.shape[0]
    if p % w:
        raise ValueError(f"leading dim {p} must divide over {w} workers")
    shape_out = (p // w,) + x.shape[1:]
    chunks = x.reshape((w, -1)).astype(jnp.float32)           # (W, E)
    e = chunks.shape[1]
    block = _block_for(e, comm)
    if residual is not None:
        res_chunks = residual.reshape((w, -1)).astype(jnp.float32)
        y = chunks + res_chunks
    else:
        y = chunks
    # encode each destination chunk (vmap keeps one (W, nb, block) payload)
    enc = jax.vmap(lambda c: encode_flat(c, comm, block)[:2])
    payload, scale = enc(y)
    n = e
    if residual is not None:
        if scale is not None:
            dec_all = (payload.astype(jnp.float32)
                       * scale[..., None]).reshape(w, -1)[:, :n]
        else:
            dec_all = payload.astype(jnp.float32).reshape(w, -1)[:, :n]
        new_res = (y - dec_all).reshape(residual.shape).astype(residual.dtype)
    payload = jax.lax.all_to_all(payload, axis_name, split_axis=0,
                                 concat_axis=0)               # (W, ...) from
    if scale is not None:
        scale = jax.lax.all_to_all(scale, axis_name, split_axis=0,
                                   concat_axis=0)
        flat_sum = jnp.sum(payload.astype(jnp.float32) * scale[..., None],
                           axis=0).reshape(-1)[:n]
    else:
        flat_sum = jnp.sum(payload.astype(jnp.float32), axis=0)[:n]
    if combiner.op is combiner_lib.Op.AVG:
        flat_sum = flat_sum / w
    out = flat_sum.reshape(shape_out).astype(x.dtype)
    if residual is not None:
        return out, new_res
    return out


def allreduce_q(
    x: jax.Array,
    combiner: combiner_lib.Combiner,
    axis_name: str,
    comm: CommConfig,
    residual: Optional[jax.Array] = None,
):
    """Quantized allreduce: quantized reduce_scatter + quantized allgather
    over the flattened payload — the EQuARX two-stage decomposition at the
    JAX level. Wire bytes ≈ f32 allreduce / 4 (int8 + scale overhead) or
    / 2 (bf16); the result is identical (replicated) on every worker.

    Error feedback covers BOTH stages when ``residual`` (shaped like x,
    f32) is passed: stage-1 encode errors land in the residual for every
    element, and this worker's stage-2 re-encode error is folded into its
    own chunk's slice — the residual lives entirely in x's domain."""
    _check_combiner(combiner, "allreduce")
    w = compat.axis_size(axis_name)
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    cpw = -(-n // w)                         # elements per worker chunk
    pad = w * cpw - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    stacked = flat.reshape(w, cpw)
    block = _block_for(cpw, comm)
    if residual is not None:
        res_flat = residual.reshape(-1).astype(jnp.float32)
        if pad:
            res_flat = jnp.concatenate(
                [res_flat, jnp.zeros((pad,), jnp.float32)])
        y = stacked + res_flat.reshape(w, cpw)
    else:
        y = stacked
    enc = jax.vmap(lambda c: encode_flat(c, comm, block)[:2])
    payload, scale = enc(y)
    if residual is not None:
        if scale is not None:
            dec_all = (payload.astype(jnp.float32)
                       * scale[..., None]).reshape(w, -1)[:, :cpw]
        else:
            dec_all = payload.astype(jnp.float32).reshape(w, -1)[:, :cpw]
        err1 = y - dec_all                                    # (W, cpw)
    payload = jax.lax.all_to_all(payload, axis_name, split_axis=0,
                                 concat_axis=0)
    if scale is not None:
        scale = jax.lax.all_to_all(scale, axis_name, split_axis=0,
                                   concat_axis=0)
        own = jnp.sum(payload.astype(jnp.float32) * scale[..., None],
                      axis=0).reshape(-1)[:cpw]
    else:
        own = jnp.sum(payload.astype(jnp.float32), axis=0).reshape(-1)[:cpw]
    if combiner.op is combiner_lib.Op.AVG:
        own = own / w
    # stage 2: re-encode the reduced chunk, allgather
    payload2, scale2, _ = encode_flat(own, comm, block)
    all_p2 = jax.lax.all_gather(payload2, axis_name)
    if scale2 is not None:
        all_s2 = jax.lax.all_gather(scale2, axis_name)
        full = (all_p2.astype(jnp.float32)
                * all_s2[..., None]).reshape(w, -1)[:, :cpw]
    else:
        full = all_p2.astype(jnp.float32).reshape(w, -1)[:, :cpw]
    out = full.reshape(-1)[:n].reshape(shape).astype(x.dtype)
    if residual is not None:
        err2 = own - decode_flat(payload2, scale2, cpw, comm)  # own chunk
        wid = jax.lax.axis_index(axis_name)
        err = err1.at[wid].add(err2)      # fold stage-2 error into own slice
        new_res = err.reshape(-1)[:n].reshape(residual.shape).astype(
            residual.dtype)
        return out, new_res
    return out


def zeros_residual(x) -> jax.Array:
    """Fresh f32 error-feedback state shaped like ``x`` (models put this in
    their fit carry; rotation puts it in the scan carry)."""
    return jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), x)


# --------------------------------------------------------------------------- #
# Wire accounting (bench + PERF stage math; jaxlint measures traced programs)
# --------------------------------------------------------------------------- #

def wire_bytes_per_element(comm: Optional[CommConfig], n: int = 0) -> float:
    """Bytes each payload element occupies on the wire: 4 (f32), 2 (bf16),
    or 1 + 4/block (int8 + amortized f32 scale, at the effective block for
    an n-element payload)."""
    if comm is None or not comm.active:
        return 4.0
    if comm.quant == "bf16":
        return 2.0
    block = _block_for(n or comm.block, comm)
    return 1.0 + 4.0 / block
