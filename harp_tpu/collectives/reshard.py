"""On-device live resharding — collective array redistribution.

The device-side counterpart of :mod:`collectives.repartition` (the host-numpy
gather-and-resplit PR 8 introduced for world-size-agnostic resume).  The host
path is correct but it is exactly the anti-pattern arXiv:2112.01075
("Memory-efficient array redistribution through portable collective
communication", PAPERS.md) exists to kill at production factor-table sizes:
every sharded leaf is materialized IN FULL on every host, permuted with fancy
indexing, and re-uploaded.  This module moves the rows between two block
layouts ON the mesh instead: the (old bin/slot → new bin/slot) permutation is
decomposed host-side into a bounded sequence of ``all_to_all`` / ``ppermute``
ROUNDS whose per-round payload never exceeds a configured ``chunk_bytes`` —
the paper's memory-efficient schedule: no worker ever materializes more than
one round's worth of foreign rows, vs the host path's full table.

Contract:

* **bitwise** — rows are copied verbatim (gather → collective → scatter, no
  arithmetic), so the device result is bit-identical to
  ``repartition.repartition_factor`` / ``rematch_tokens`` on the same maps.
  The numpy path stays as the parity oracle and the ``num_workers == 1``
  small-world fallback.
* **bounded** — every collective in the traced program carries at most
  ``chunk_bytes`` of row payload (the all_to_all operand for the default
  schedule, each ppermute for the ring schedule).  The jaxlint manifest pins
  the reshard step program (``reshard_factor_a2a`` / ``reshard_factor_ring``
  trace targets): a schedule that silently degrades to a full gather grows
  its per-round bytes and fails JL203 exactly like a quantized path
  reverting to f32.
* **composable** — the ring schedule rides ``lax_ops.rotate``, so the
  ``quant=`` wire codecs and the DCN link-class chunking
  (``rotation.chunks_for_link``) compose for cross-pod hops.  A quantized
  wire trades the bitwise contract for volume, exactly as it does for
  training hops — leave ``comm=None`` (the default) when resuming.

Index maps (``plan_moves``) are host-computed int32 arrays proportional to
the number of ROWS moved — they are the permutation's description, not its
payload (for a rank-64 f32 factor table they are ~1/32 of the leaf), and
they are the same (bin, slot) assignments the checkpoint already carries.

Layout vocabulary: a row-sharded leaf lives on the mesh in *device order* —
worker ``w`` holds ``local_rows`` consecutive rows of the flattened global
array.  A :class:`RowLayout` maps canonical ids into that order through the
model's (bin, slot) assignment plus the bin→(worker, base) placement
(1-slice: bin b on worker b at base 0; 2-slice: bin b on worker ``b % W`` at
base ``(b // W) * rows_per_bin`` — the worker-major half-slice stacking of
``sgd_mf._place_h0`` / LDA's 2-slice wt).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np

from harp_tpu.parallel.mesh import WORKERS

# One round of foreign rows per worker: 1 MiB by default — small enough that
# even a GB-scale table reshards in bounded memory, large enough that the
# round count stays in the hundreds (a v5e ICI link moves 1 MiB in ~10 us).
DEFAULT_CHUNK_BYTES = 1 << 20


def resolve_mode(mode: str, num_workers: int) -> str:
    """The ONE resume-reshard mode resolution every model shares
    (``SGDMFConfig.reshard`` / ``LDAConfig.reshard``): validates
    ``auto|device|ring|host`` and resolves ``auto`` to the device schedule
    on a multi-worker mesh, to the host oracle on a 1-worker mesh (the
    small-world fallback — nothing to redistribute over)."""
    if mode not in ("auto", "device", "ring", "host"):
        raise ValueError(f"reshard must be auto|device|ring|host, "
                         f"got {mode!r}")
    if mode == "auto":
        return "host" if num_workers == 1 else "device"
    return mode


# --------------------------------------------------------------------------- #
# Layouts
# --------------------------------------------------------------------------- #

@dataclasses.dataclass(frozen=True)
class RowLayout:
    """Where each canonical id's row lives on a ``num_workers`` mesh."""

    bins: np.ndarray          # (n_ids,) bin of canonical id i
    slots: np.ndarray         # (n_ids,) slot within the bin
    rows_per_bin: int
    num_bins: int
    bin_owner: np.ndarray     # (num_bins,) worker holding each bin
    bin_base: np.ndarray      # (num_bins,) local row offset of the bin
    local_rows: int           # device rows per worker

    @property
    def total_rows(self) -> int:
        return self.num_bins * self.rows_per_bin

    def device_positions(self, n_valid: int) -> np.ndarray:
        """Flat device-order position of each of the first ``n_valid`` ids:
        ``owner * local_rows + base + slot``."""
        b = np.asarray(self.bins[:n_valid], np.int64)
        s = np.asarray(self.slots[:n_valid], np.int64)
        if len(b) and (b.min() < 0 or b.max() >= self.num_bins
                       or s.min() < 0 or s.max() >= self.rows_per_bin):
            raise ValueError(
                f"assignment maps address (bin, slot) outside the layout "
                f"({self.num_bins} bins x {self.rows_per_bin} rows) — the "
                f"maps do not describe this layout")
        return (np.asarray(self.bin_owner, np.int64)[b] * self.local_rows
                + np.asarray(self.bin_base, np.int64)[b] + s)


def block_layout(assign: Tuple[np.ndarray, np.ndarray], rows_per_bin: int,
                 num_workers: int, num_slices: int = 1) -> RowLayout:
    """Layout of a (bin, slot)-assigned factor table.

    ``num_slices=1``: bin b lives whole on worker b (the W factor, 1-slice H,
    1-slice LDA wt).  ``num_slices=2``: bins are worker-major half-slices —
    bin b on worker ``b % W`` at base ``(b // W) * rows_per_bin`` (the
    ``_place_h0`` / 2-slice wt stacking)."""
    num_bins = num_slices * num_workers
    b = np.arange(num_bins)
    return RowLayout(
        bins=np.asarray(assign[0]), slots=np.asarray(assign[1]),
        rows_per_bin=int(rows_per_bin), num_bins=num_bins,
        bin_owner=(b % num_workers).astype(np.int64),
        bin_base=((b // num_workers) * rows_per_bin).astype(np.int64),
        local_rows=num_slices * int(rows_per_bin))


def contiguous_split(positions: np.ndarray, total_rows: int,
                     num_workers: int) -> Tuple[np.ndarray, np.ndarray, int]:
    """(worker, slot, padded_total) of flat positions under an even
    contiguous split over ``num_workers`` — how a flat host leaf (or a live
    device array) shards over the mesh."""
    per = -(-max(int(total_rows), 1) // num_workers)
    p = np.asarray(positions, np.int64)
    if len(p) and (p.min() < 0 or p.max() >= total_rows):
        raise ValueError(
            f"positions address rows outside the flat leaf "
            f"({total_rows} rows, max {p.max() if len(p) else 0})")
    return p // per, p % per, per * num_workers


# --------------------------------------------------------------------------- #
# Plans
# --------------------------------------------------------------------------- #

@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    """Host-computed move schedule: which local row each worker ships to
    each peer in each bounded round, and where received rows land."""

    num_workers: int
    schedule: str             # "alltoall" | "ring"
    chunk_rows: int           # rows per (peer, round) — the byte bound
    src_rows: int             # padded flat source rows (divides num_workers)
    dst_rows: int             # flat destination rows (divides num_workers)
    rounds: int               # alltoall rounds (ring: sum over shifts)
    # alltoall: (W, rounds, W, C) send local-slots / recv local-positions,
    # -1 = pad.  ring: per shift s in 0..W-1, (W, rounds_s, C) pairs; shift 0
    # is the local (no-wire) copy.
    send_idx: Optional[np.ndarray]
    recv_pos: Optional[np.ndarray]
    ring_rounds: Optional[Tuple[Tuple[np.ndarray, np.ndarray], ...]]
    moved_rows: int           # rows that cross a worker boundary
    local_rows_moved: int     # rows that stay on their worker
    row_bytes: int

    @property
    def bytes_moved(self) -> int:
        """Payload bytes that cross a worker boundary (the wire volume the
        bench rows report; the host path gathers ``src_rows * row_bytes`` to
        EVERY worker instead)."""
        return self.moved_rows * self.row_bytes


def plan_moves(src_pos: np.ndarray, dst_pos: np.ndarray, src_rows: int,
               dst_rows: int, num_workers: int, row_bytes: int,
               chunk_bytes: int = DEFAULT_CHUNK_BYTES,
               schedule: str = "alltoall") -> ReshardPlan:
    """Decompose a flat-position permutation into bounded collective rounds.

    ``src_pos[i]`` / ``dst_pos[i]`` are the flat device-order positions of
    moved row i in the source and destination leaves.  The source is (or is
    placed as) an even contiguous split of ``src_rows`` over the mesh; the
    destination layout's ``dst_rows`` must already divide the mesh."""
    if schedule not in ("alltoall", "ring"):
        raise ValueError(f"schedule must be alltoall|ring, got {schedule!r}")
    w = int(num_workers)
    src_pos = np.asarray(src_pos, np.int64)
    dst_pos = np.asarray(dst_pos, np.int64)
    if len(src_pos) != len(dst_pos):
        raise ValueError(f"{len(src_pos)} source positions vs "
                         f"{len(dst_pos)} destinations")
    sw, ss, src_pad = contiguous_split(src_pos, src_rows, w)
    if dst_rows % w:
        raise ValueError(f"destination rows {dst_rows} must divide the "
                         f"{w}-worker mesh")
    dst_local = dst_rows // w
    if len(dst_pos) and (dst_pos.min() < 0 or dst_pos.max() >= dst_rows):
        raise ValueError(
            f"destination positions address rows outside the new layout "
            f"({dst_rows} rows, max {dst_pos.max()})")
    dw, ds = dst_pos // dst_local, dst_pos % dst_local
    if len(dst_pos) != len(np.unique(dst_pos)):
        raise ValueError("destination positions collide — the new layout "
                         "maps two ids onto one row")
    row_bytes = max(int(row_bytes), 1)
    n = len(src_pos)
    cross = sw != dw
    if schedule == "alltoall":
        # foreign footprint per round = the all_to_all operand: W chunks of
        # C rows -> C = chunk_bytes / (W * row_bytes)
        chunk = max(1, int(chunk_bytes) // (w * row_bytes))
        pair = sw * w + dw
        order = np.argsort(pair, kind="stable")
        counts = np.bincount(pair, minlength=w * w)
        rounds = max(1, -(-int(counts.max(initial=0)) // chunk))
        starts = np.concatenate([[0], np.cumsum(counts)])
        rank = np.arange(n) - starts[pair[order]]
        r, c = np.divmod(rank, chunk)
        send = np.full((w, rounds, w, chunk), -1, np.int32)
        recv = np.full((w, rounds, w, chunk), -1, np.int32)
        send[sw[order], r, dw[order], c] = ss[order].astype(np.int32)
        recv[dw[order], r, sw[order], c] = ds[order].astype(np.int32)
        return ReshardPlan(w, schedule, chunk, src_pad, dst_rows, rounds,
                           send, recv, None, int(cross.sum()),
                           int(n - cross.sum()), row_bytes)
    # ring: one ppermute per shift, chunked into rounds of C rows each so a
    # single hop never carries more than chunk_bytes
    chunk = max(1, int(chunk_bytes) // row_bytes)
    shift = (dw - sw) % w
    per_shift = []
    total_rounds = 0
    for s in range(w):
        m = shift == s
        ssw, sss, sds = sw[m], ss[m], ds[m]
        counts = np.bincount(ssw, minlength=w)
        rounds_s = max(1, -(-int(counts.max(initial=0)) // chunk)) \
            if m.any() else 0
        if rounds_s == 0:
            per_shift.append((np.full((w, 0, chunk), -1, np.int32),
                              np.full((w, 0, chunk), -1, np.int32)))
            continue
        order = np.argsort(ssw, kind="stable")
        starts = np.concatenate([[0], np.cumsum(counts)])
        rank = np.arange(m.sum()) - starts[ssw[order]]
        r, c = np.divmod(rank, chunk)
        send = np.full((w, rounds_s, chunk), -1, np.int32)
        recv = np.full((w, rounds_s, chunk), -1, np.int32)
        send[ssw[order], r, c] = sss[order].astype(np.int32)
        # the receiver of shift s from sender ssw is (ssw + s) % w; entry c
        # of the sender's chunk lands at entry c on the receiver
        recv[(ssw[order] + s) % w, r, c] = sds[order].astype(np.int32)
        per_shift.append((send, recv))
        total_rounds += rounds_s
    return ReshardPlan(w, schedule, chunk, src_pad, dst_rows, total_rounds,
                       None, None, tuple(per_shift), int(cross.sum()),
                       int(n - cross.sum()), row_bytes)


def plan_factor_reshard(old: RowLayout, old_world: int, new: RowLayout,
                        num_workers: int, n_valid: int, row_bytes: int,
                        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                        schedule: str = "alltoall") -> ReshardPlan:
    """Plan moving a (bin, slot)-sharded factor table saved by an
    ``old_world`` gang onto this ``num_workers`` mesh's ``new`` layout.
    The saved flat leaf (old device order) is placed as a contiguous split;
    every id the data references moves to its new (bin, slot) row."""
    src_pos = old.device_positions(n_valid)
    dst_pos = new.device_positions(n_valid)
    return plan_moves(src_pos, dst_pos, old_world * old.local_rows,
                      num_workers * new.local_rows, num_workers, row_bytes,
                      chunk_bytes, schedule)


def plan_coo_regroup(rows: np.ndarray, num_rows: int, num_workers: int,
                     row_bytes: int = 20,
                     chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                     schedule: str = "alltoall"
                     ) -> Tuple[ReshardPlan, np.ndarray, int]:
    """Plan routing COO nonzeros to the worker owning their row block — the
    ingestion regroup (HarpDAALDataSource.regroupCOOList) as a bounded
    reshard instead of a whole-table host shuffle.

    Record i sits at flat source position i (parse order, contiguous split
    over the mesh); its destination is ``owner * capacity + rank`` where
    ``owner`` follows the SAME ceil-block ownership rule as the host oracle
    (``loaders.regroup_coo_by_row``) and ``rank`` is the record's order
    among its owner's records in GLOBAL parse order — so each worker's
    received slice is exactly the oracle's boolean-mask slice, nnz for nnz.

    ``row_bytes`` defaults to the packed (row i64, col i64, val f32) record:
    5 int32 lanes = 20 B (io/pipeline.pack_coo).  Returns
    ``(plan, per-worker counts, per-worker slot capacity)``.
    """
    rows = np.asarray(rows, np.int64)
    n = len(rows)
    w = int(num_workers)
    block = -(-max(int(num_rows), 1) // w)
    owner = np.minimum(rows // block, w - 1)
    counts = np.bincount(owner, minlength=w).astype(np.int64)
    cap = max(1, int(counts.max(initial=0)))
    starts = np.concatenate([[0], np.cumsum(counts)])
    order = np.argsort(owner, kind="stable")
    rank = np.empty(n, np.int64)
    rank[order] = np.arange(n) - starts[owner[order]]
    dst_pos = owner * cap + rank
    plan = plan_moves(np.arange(n, dtype=np.int64), dst_pos, max(n, 1),
                      w * cap, w, row_bytes, chunk_bytes, schedule)
    return plan, counts, cap


# --------------------------------------------------------------------------- #
# Device programs
# --------------------------------------------------------------------------- #

def _row_meta(shape: Sequence[int], local_rows: int) -> Tuple[int, ...]:
    """Per-row trailing shape of a flat leaf whose local block holds
    ``local_rows`` rows (validates divisibility of the local element count)."""
    elems = 1
    for s in shape:
        elems *= int(s)
    if local_rows <= 0 or elems % local_rows:
        raise ValueError(f"local block of {elems} elements does not hold "
                         f"{local_rows} rows")
    return (elems // local_rows,)


def prepare_reshard(session, src, plan: ReshardPlan, fill, *, comm=None,
                    link_class: Optional[str] = None):
    """Build the reshard step program and its placed arguments.

    ``src``: the saved leaf — a host ndarray in the OLD world's flat device
    order (padded + scattered contiguously here), or a LIVE device array
    already sharded over this mesh (rebalance / shard restore: zero host
    involvement).  ``fill``: the device array supplying every row the plan
    does not write (fresh init for padded slots, or the live table when only
    some rows move).  Returns ``(fn, args)``; ``fn(*args)`` yields the
    resharded leaf in ``fill``'s shape and sharding.  The device path NEVER
    gathers a sharded leaf to host — no ``np.asarray`` of a device array
    happens here or in the traced program.
    """
    import jax
    import jax.numpy as jnp

    from harp_tpu.collectives import lax_ops, rotation

    w = plan.num_workers
    if session.num_workers != w:
        raise ValueError(f"plan was made for {w} workers; session has "
                         f"{session.num_workers}")
    # per-worker local blocks reshape to (local_rows, row_elems): row
    # boundaries must survive the flatten, which they do for every layout
    # this module defines (rows are the trailing-contiguous unit)
    fill_shape = tuple(np.shape(fill))
    src_shape = tuple(np.shape(src))
    row_elems = _row_meta(fill_shape, plan.dst_rows)[0]
    src_row_elems = _row_meta(src_shape, plan.src_rows)[0] \
        if isinstance(src, jax.Array) else None
    if isinstance(src, jax.Array):
        if src_row_elems != row_elems:
            raise ValueError(
                f"source rows ({src_row_elems} elems) and destination rows "
                f"({row_elems} elems) disagree")
        src_dev = src
    else:
        # host leaf from the checkpoint: pad the flat device-order payload
        # to the contiguous split and scatter — the one H2D the resume pays
        # anyway; no device array is gathered back
        flat = np.asarray(src).reshape(-1, row_elems)
        if len(flat) > plan.src_rows:
            raise ValueError(f"saved leaf has {len(flat)} rows; plan "
                             f"expects at most {plan.src_rows}")
        if len(flat) < plan.src_rows:
            pad = np.zeros((plan.src_rows - len(flat), row_elems),
                           flat.dtype)
            flat = np.concatenate([flat, pad], axis=0)
        src_dev = session.scatter(flat)
    dst_local = plan.dst_rows // w
    src_local = plan.src_rows // w
    link = link_class

    def _local_rows_of(x, rows):
        return x.reshape((rows, row_elems))

    if plan.schedule == "alltoall":
        send = session.scatter(plan.send_idx)
        recv = session.scatter(plan.recv_pos)

        def prog(src_a, fill_a, send_a, recv_a):
            src_l = _local_rows_of(src_a, src_local)
            dst = _local_rows_of(fill_a, dst_local)
            trash = dst_local            # pads land on a discarded row
            dst = jnp.concatenate(
                [dst, jnp.zeros((1, row_elems), dst.dtype)], axis=0)

            def body(d, xs):
                si, rp = xs              # (W, C) each
                payload = src_l[jnp.maximum(si, 0).reshape(-1)]
                moved = lax_ops.all_to_all(payload)
                pos = jnp.where(rp.reshape(-1) >= 0, rp.reshape(-1), trash)
                return d.at[pos].set(moved), None

            dst, _ = jax.lax.scan(body, dst, (send_a[0], recv_a[0]))
            return dst[:dst_local].reshape(fill_a.shape)

        fn = session.spmd(prog, in_specs=(session.shard(),) * 4,
                          out_specs=session.shard())
        return fn, (src_dev, fill, send, recv)

    placed = [(session.scatter(s), session.scatter(r))
              for s, r in plan.ring_rounds]

    def prog(src_a, fill_a, *rounds_args):
        src_l = _local_rows_of(src_a, src_local)
        dst = _local_rows_of(fill_a, dst_local)
        trash = dst_local
        dst = jnp.concatenate(
            [dst, jnp.zeros((1, row_elems), dst.dtype)], axis=0)
        for s in range(w):
            send_a, recv_a = rounds_args[2 * s], rounds_args[2 * s + 1]
            if send_a.shape[1] == 0:
                continue

            def body(d, xs, s=s):
                si, rp = xs              # (C,) each
                payload = src_l[jnp.maximum(si, 0)]
                if s:
                    nb = payload.size * payload.dtype.itemsize
                    payload = lax_ops.rotate(
                        payload, s, comm=comm,
                        num_chunks=rotation.chunks_for_link(
                            nb, rotation._resolve_link(link, WORKERS)))
                pos = jnp.where(rp >= 0, rp, trash)
                return d.at[pos].set(payload), None

            dst, _ = jax.lax.scan(body, dst, (send_a[0], recv_a[0]))
        return dst[:dst_local].reshape(fill_a.shape)

    fn = session.spmd(prog,
                      in_specs=(session.shard(),) * (2 + 2 * len(placed)),
                      out_specs=session.shard())
    args = (src_dev, fill) + tuple(a for pair in placed for a in pair)
    return fn, args


def reshard(session, src, plan: ReshardPlan, fill, *, comm=None,
            link_class: Optional[str] = None):
    """Run the bounded-round device reshard; returns the new leaf (device
    array shaped and sharded like ``fill``).  One-shot per resume — the
    compile is the price of NOT gathering the table (see prepare_reshard
    for the no-host-gather contract)."""
    fn, args = prepare_reshard(session, src, plan, fill, comm=comm,
                               link_class=link_class)
    return fn(*args)


def reshard_factor(session, saved, old: RowLayout, old_world: int,
                   new: RowLayout, n_valid: int, fill, *,
                   chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                   schedule: str = "alltoall", comm=None,
                   link_class: Optional[str] = None):
    """Device twin of :func:`collectives.repartition.repartition_factor`:
    moves a (bin, slot)-sharded factor table from the layout it was SAVED
    under onto this session's layout, bitwise, in chunk-bounded rounds."""
    row_elems = _row_meta(np.shape(fill),
                          session.num_workers * new.local_rows)[0]
    row_bytes = row_elems * np.dtype(fill.dtype).itemsize
    plan = plan_factor_reshard(old, old_world, new, session.num_workers,
                               n_valid, row_bytes, chunk_bytes, schedule)
    return reshard(session, saved, plan, fill, comm=comm,
                   link_class=link_class)
