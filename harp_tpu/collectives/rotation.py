"""Model-rotation pipeline — the TPU-native dymoro.

Reference parity: Harp's **dy**namic **mo**del **ro**tation machinery
(harp-daal-interface dymoro/): ``Rotator`` (dymoro/Rotator.java:30-73) ran rotate ops
on a background StaticScheduler thread so communication overlapped compute, with the
model split into ``numModelSlices`` (=2 in SGD-MF, SGDCollectiveMapper.java:120-223)
— slice k computes while slice k-1 is in flight around the ring.

TPU-native: no background threads. The same schedule is expressed as a ``lax.scan``
whose dataflow makes the overlap visible to XLA: at micro-step t we issue the
``ppermute`` for the just-updated slice and compute on the slice that arrived at
t-1; the permute's result is not consumed until t+1, so XLA's async collective
scheduler overlaps it with the compute — the dymoro pipeline, minus the threads,
scheduled by the compiler onto ICI DMA engines.

The timer-bounded *dynamic* part of dymoro (Scheduler.java:85-160 randomly scheduled
(row, col) blocks until a wall-clock budget expired) is host-driven and
data-dependent — hostile to XLA. Per SURVEY §7 "hard parts", it is reformulated as a
**bounded-staleness fixed block schedule**: a fixed number of randomly-permuted block
updates per rotation hop (seeded, reproducible). Convergence-equivalent, not
step-equivalent; see models/sgd_mf.py.

Wire-format options (this layer owns the hot hops, so both live here):

* ``comm`` (quantize.CommConfig): int8/bf16 quantized hops with
  **error-feedback state carried in the scan carry** — each sender keeps the
  residual its last encode failed to carry and adds it to the next outgoing
  block (EF-ring: the time-average of the fed-back error vanishes). Only
  float32 leaves are quantized; integer/bool leaves ride the wire exact.
* ``link_class`` ("ici" | "dcn", default: the mesh-axis hint,
  ``parallel.mesh.axis_link_class``): a DCN hop splits its payload into
  ~``DCN_CHUNK_BYTES`` ppermute chunks so in-flight pieces pipeline over the
  slow link; an ICI hop stays one monolithic permute (the extra dispatches
  would only cost latency on a fabric that is already one hop wide).
* ``fused_dma`` (r10, ops/ring_dma.py): float-leaf payloads ride the fused
  in-kernel ``make_async_remote_copy`` hop instead of ``ppermute`` — on TPU
  the block moves producer-HBM → remote-HBM with no staging copies; off TPU
  the engine's tagged lax fallback keeps the schedule bitwise-identical and
  the jaxpr budget books the bytes as ``fused_dma``. Precedence: a
  quantized hop (``comm`` active) keeps the quantize path (the wire is
  already 2-4× smaller and needs its encode/decode programs), and a DCN
  hop keeps the chunked ppermute pipeline — ``fused_dma`` engages only on
  plain ICI hops, where it is exact.
* ``ef_state`` (r10): pass a residual tree (:func:`ef_zero`) to carry the
  quantization error-feedback state ACROSS calls — e.g. LDA threads the
  wt-block residual through its epoch scan so an epoch boundary never
  drops the pending error; the call then returns the updated state as an
  extra output.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, TypeVar

import jax
import jax.numpy as jnp

from harp_tpu.collectives import lax_ops, quantize
from harp_tpu.ops import ring_dma
from harp_tpu.parallel import mesh as mesh_lib
from harp_tpu.parallel.mesh import WORKERS

Carry = TypeVar("Carry")
Slice = Any  # pytree of arrays — one model slice's per-worker block

# DCN rotation hops pipeline in ~1 MiB pieces (big enough to amortize
# per-message overhead on a data-center link, small enough that several are
# in flight); capped at 8 chunks so tiny payloads don't shatter.
DCN_CHUNK_BYTES = 1 << 20
MAX_DCN_CHUNKS = 8


def chunks_for_link(nbytes: int, link_class: str) -> int:
    """ppermute chunk count for one rotation hop of ``nbytes`` payload."""
    if link_class == "dcn":
        return max(1, min(MAX_DCN_CHUNKS, -(-nbytes // DCN_CHUNK_BYTES)))
    return 1


def _resolve_link(link_class: Optional[str], axis_name: str) -> str:
    return (link_class if link_class is not None
            else mesh_lib.axis_link_class(axis_name))


def _leaf_bytes(x) -> int:
    return int(x.size) * x.dtype.itemsize


def _quantizable(leaf) -> bool:
    return jnp.issubdtype(leaf.dtype, jnp.floating)


def _ef_zero(block: Slice):
    """EF residual tree for a block: f32 zeros for float leaves, None-like
    zeros (unused) for non-float leaves so tree structures stay aligned."""
    return jax.tree.map(
        lambda a: jnp.zeros(a.shape, jnp.float32) if _quantizable(a)
        else jnp.zeros((), jnp.float32), block)


# public alias: models that thread EF state through their own scan carries
# (``ef_state=``) build the initial residual with this
ef_zero = _ef_zero


def _shift_block(block: Slice, res: Optional[Slice], shift: int,
                 axis_name: str, comm: Optional[quantize.CommConfig],
                 link_class: str, fused: bool = False):
    """One hop of the block pytree: quantized+EF when ``comm`` is active,
    chunked when the link class asks for it, fused ring DMA for float
    leaves when ``fused`` (plain ICI hops only — the caller resolves the
    precedence). Returns (block', res')."""
    if comm is None or not comm.active:
        def send(x):
            if fused and _quantizable(x):
                return ring_dma.hop(x, shift, axis_name)
            return lax_ops.rotate(
                x, shift, axis_name,
                num_chunks=chunks_for_link(_leaf_bytes(x), link_class))
        return jax.tree.map(send, block), res

    def send_ef(leaf, r):
        if not _quantizable(leaf):
            return lax_ops.rotate(leaf, shift, axis_name), r
        flat = leaf.reshape(-1).astype(jnp.float32)
        block_sz = quantize._block_for(flat.shape[0], comm)
        payload, scale, n, new_r = quantize.ef_encode_flat(
            flat, r.reshape(-1), comm, block_sz)
        n_ax = lax_ops.num_workers(axis_name)
        perm = [(i, (i + shift) % n_ax) for i in range(n_ax)]
        payload = jax.lax.ppermute(payload, axis_name, perm)
        if scale is not None:
            scale = jax.lax.ppermute(scale, axis_name, perm)
        out = quantize.decode_flat(payload, scale, n, comm).reshape(
            leaf.shape).astype(leaf.dtype)
        return out, new_r.reshape(r.shape)

    # flatten/unflatten instead of a tuple-leafed tree.map: block pytrees may
    # themselves contain tuples (kernel SVM rotates an (x, coef) pair)
    leaves_b, treedef = jax.tree.flatten(block)
    leaves_r = jax.tree.flatten(res)[0]
    sent = [send_ef(lb, lr) for lb, lr in zip(leaves_b, leaves_r)]
    new_block = jax.tree.unflatten(treedef, [s[0] for s in sent])
    new_res = jax.tree.unflatten(treedef, [s[1] for s in sent])
    return new_block, new_res


def rotate_scan(
    body: Callable[[Carry, Slice, jax.Array], Tuple[Carry, Slice]],
    carry: Carry,
    model_block: Slice,
    num_steps: int,
    axis_name: str = WORKERS,
    shift: int = 1,
    comm: Optional[quantize.CommConfig] = None,
    link_class: Optional[str] = None,
    fused_dma: bool = False,
    ef_state: Optional[Slice] = None,
):
    """Unpipelined rotation loop: compute on the block, then shift it.

    ``body(carry, block, step) -> (carry, updated_block)``. After ``num_steps`` =
    num_workers, every worker has seen (and updated) every model block once and each
    block is home again. This is Harp's plain ``rotate()`` loop
    (LocalGlobalSyncCollective.rotate:710 called per iteration).

    ``shift=0`` skips the permute entirely — a timing ablation that keeps the
    compute schedule but removes the collective (the block never moves, so the
    RESULT is wrong); used only to measure the rotation's share of hop time.

    ``comm``/``link_class``: wire-format options (module docstring). The EF
    residual rides in the scan carry; with ``comm`` active the returned
    block is the lossy-wire trajectory (convergence-equivalent, not
    bit-identical — models pin a parity tolerance vs the f32 run).

    ``fused_dma``/``ef_state``: module docstring. With ``ef_state`` passed
    the return is ``(carry, block, ef_state')``; otherwise the historical
    2-tuple.
    """
    link = _resolve_link(link_class, axis_name)
    quant = comm is not None and comm.active
    fused = fused_dma and not quant and link == "ici"
    res0 = (ef_state if ef_state is not None
            else _ef_zero(model_block) if quant else None)

    def step(state, t):
        c, blk, res = state
        c, blk = body(c, blk, t)
        if shift:
            blk, res = _shift_block(blk, res, shift, axis_name, comm, link,
                                    fused=fused)
        return (c, blk, res), None

    (carry, model_block, res), _ = jax.lax.scan(
        step, (carry, model_block, res0), jnp.arange(num_steps))
    if ef_state is not None:
        return carry, model_block, res
    return carry, model_block


def pipelined_rotation(
    body: Callable[[Carry, Slice, jax.Array], Tuple[Carry, Slice]],
    carry: Carry,
    slice_a: Slice,
    slice_b: Slice,
    num_micro_steps: int,
    axis_name: str = WORKERS,
    shift: int = 1,
    comm: Optional[quantize.CommConfig] = None,
    link_class: Optional[str] = None,
    fused_dma: bool = False,
    ef_state: Optional[Tuple[Slice, Slice]] = None,
):
    """Double-buffered rotation: compute on one slice while the other is in flight.

    The model is split into two slices (Harp: numModelSlices=2). Micro-step t:

      1. ``body`` updates the *resident* slice;
      2. its ``ppermute`` to the next worker is issued;
      3. the slice issued at t-1 becomes resident for t+1.

    For a full epoch (every slice block visits every worker once) use
    ``num_micro_steps = 2 * num_workers``; slices land back on their home workers.

    Returns (carry, slice_a', slice_b') with both slices at their original
    positions when num_micro_steps is a multiple of 2*num_workers.

    ``shift=0``: timing ablation, see :func:`rotate_scan` (slices still swap
    resident/inflight roles but never cross workers).

    ``comm``/``link_class``: wire-format options (module docstring). One EF
    residual per (sender, slice family): sends alternate the two slice
    families, so the residuals ride the same resident/inflight seat swap
    the slices do — slice A's encode error is re-sent with the next
    A-family send, never injected into B's coordinates (and slices of
    different shapes each keep a correctly-shaped residual).

    ``fused_dma``/``ef_state``: module docstring. ``ef_state`` is the
    ``(residual_a, residual_b)`` pair; when passed the return is
    ``(carry, slice_a', slice_b', ef_state')``.
    """
    link = _resolve_link(link_class, axis_name)
    quant = comm is not None and comm.active
    fused = fused_dma and not quant and link == "ici"
    if ef_state is not None:
        res_a0, res_b0 = ef_state
    else:
        res_a0 = _ef_zero(slice_a) if quant else None
        res_b0 = _ef_zero(slice_b) if quant else None

    def step(state, t):
        c, resident, inflight, res_res, res_inf = state
        c, updated = body(c, resident, t)
        outgoing = updated
        if shift:
            outgoing, res_res = _shift_block(updated, res_res, shift,
                                             axis_name, comm, link,
                                             fused=fused)
        # inflight was issued last step; it is resident for the next step. XLA sees
        # `outgoing` unused until step t+1 → overlaps the permute with t+1's compute.
        # The residuals swap seats in lockstep with their slices.
        return (c, inflight, outgoing, res_inf, res_res), None

    state = (carry, slice_a, slice_b, res_a0, res_b0)
    (carry, sa, sb, res_a, res_b), _ = jax.lax.scan(
        step, state, jnp.arange(num_micro_steps))
    if ef_state is not None:
        return carry, sa, sb, (res_a, res_b)
    return carry, sa, sb


class Rotator:
    """Convenience wrapper holding the rotation config (Harp: dymoro/Rotator).

    Harp's Rotator exposed getRotation(k)/rotate(k) imperative calls; here the
    equivalent is declarative — construct with the schedule shape, call
    :meth:`run` with the per-hop body. Kept as a class so algorithm code reads
    like the reference's. ``comm``/``link_class`` thread to the scan
    implementations (module docstring).
    """

    def __init__(self, num_workers: int, num_slices: int = 2,
                 axis_name: str = WORKERS,
                 comm: Optional[quantize.CommConfig] = None,
                 link_class: Optional[str] = None,
                 fused_dma: bool = False,
                 shift: int = 1):
        if num_slices not in (1, 2):
            raise ValueError("num_slices must be 1 (plain) or 2 (double-buffered)")
        self.num_workers = num_workers
        self.num_slices = num_slices
        self.axis_name = axis_name
        self.comm = comm
        self.link_class = link_class
        self.fused_dma = fused_dma
        # shift=0: the scan never permutes — either a timing ablation
        # (rotate_scan doc) or a body that performs the hop ITSELF (the
        # dense-MF in-kernel ring epilogue returns the already-hopped block)
        self.shift = shift

    def run(self, body, carry, slices, epochs: int = 1):
        """Run ``epochs`` full rotations. ``slices``: tuple of model slices
        (length == num_slices)."""
        if self.num_slices == 1:
            (slice_a,) = slices
            carry, out = rotate_scan(body, carry, slice_a,
                                     epochs * self.num_workers, self.axis_name,
                                     shift=self.shift, comm=self.comm,
                                     link_class=self.link_class,
                                     fused_dma=self.fused_dma)
            return carry, (out,)
        sa, sb = slices
        carry, sa, sb = pipelined_rotation(
            body, carry, sa, sb, epochs * 2 * self.num_workers, self.axis_name,
            shift=self.shift, comm=self.comm, link_class=self.link_class,
            fused_dma=self.fused_dma)
        return carry, (sa, sb)
