"""Model-rotation pipeline — the TPU-native dymoro.

Reference parity: Harp's **dy**namic **mo**del **ro**tation machinery
(harp-daal-interface dymoro/): ``Rotator`` (dymoro/Rotator.java:30-73) ran rotate ops
on a background StaticScheduler thread so communication overlapped compute, with the
model split into ``numModelSlices`` (=2 in SGD-MF, SGDCollectiveMapper.java:120-223)
— slice k computes while slice k-1 is in flight around the ring.

TPU-native: no background threads. The same schedule is expressed as a ``lax.scan``
whose dataflow makes the overlap visible to XLA: at micro-step t we issue the
``ppermute`` for the just-updated slice and compute on the slice that arrived at
t-1; the permute's result is not consumed until t+1, so XLA's async collective
scheduler overlaps it with the compute — the dymoro pipeline, minus the threads,
scheduled by the compiler onto ICI DMA engines.

The timer-bounded *dynamic* part of dymoro (Scheduler.java:85-160 randomly scheduled
(row, col) blocks until a wall-clock budget expired) is host-driven and
data-dependent — hostile to XLA. Per SURVEY §7 "hard parts", it is reformulated as a
**bounded-staleness fixed block schedule**: a fixed number of randomly-permuted block
updates per rotation hop (seeded, reproducible). Convergence-equivalent, not
step-equivalent; see models/sgd_mf.py.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple, TypeVar

import jax
import jax.numpy as jnp

from harp_tpu.collectives import lax_ops
from harp_tpu.parallel.mesh import WORKERS

Carry = TypeVar("Carry")
Slice = Any  # pytree of arrays — one model slice's per-worker block


def rotate_scan(
    body: Callable[[Carry, Slice, jax.Array], Tuple[Carry, Slice]],
    carry: Carry,
    model_block: Slice,
    num_steps: int,
    axis_name: str = WORKERS,
    shift: int = 1,
) -> Tuple[Carry, Slice]:
    """Unpipelined rotation loop: compute on the block, then shift it.

    ``body(carry, block, step) -> (carry, updated_block)``. After ``num_steps`` =
    num_workers, every worker has seen (and updated) every model block once and each
    block is home again. This is Harp's plain ``rotate()`` loop
    (LocalGlobalSyncCollective.rotate:710 called per iteration).

    ``shift=0`` skips the permute entirely — a timing ablation that keeps the
    compute schedule but removes the collective (the block never moves, so the
    RESULT is wrong); used only to measure the rotation's share of hop time.
    """

    def step(state, t):
        c, blk = state
        c, blk = body(c, blk, t)
        if shift:
            blk = jax.tree.map(lambda x: lax_ops.rotate(x, shift, axis_name),
                               blk)
        return (c, blk), None

    (carry, model_block), _ = jax.lax.scan(step, (carry, model_block),
                                           jnp.arange(num_steps))
    return carry, model_block


def pipelined_rotation(
    body: Callable[[Carry, Slice, jax.Array], Tuple[Carry, Slice]],
    carry: Carry,
    slice_a: Slice,
    slice_b: Slice,
    num_micro_steps: int,
    axis_name: str = WORKERS,
    shift: int = 1,
) -> Tuple[Carry, Slice, Slice]:
    """Double-buffered rotation: compute on one slice while the other is in flight.

    The model is split into two slices (Harp: numModelSlices=2). Micro-step t:

      1. ``body`` updates the *resident* slice;
      2. its ``ppermute`` to the next worker is issued;
      3. the slice issued at t-1 becomes resident for t+1.

    For a full epoch (every slice block visits every worker once) use
    ``num_micro_steps = 2 * num_workers``; slices land back on their home workers.

    Returns (carry, slice_a', slice_b') with both slices at their original
    positions when num_micro_steps is a multiple of 2*num_workers.

    ``shift=0``: timing ablation, see :func:`rotate_scan` (slices still swap
    resident/inflight roles but never cross workers).
    """

    def step(state, t):
        c, resident, inflight = state
        c, updated = body(c, resident, t)
        outgoing = updated
        if shift:
            outgoing = jax.tree.map(
                lambda x: lax_ops.rotate(x, shift, axis_name), updated)
        # inflight was issued last step; it is resident for the next step. XLA sees
        # `outgoing` unused until step t+1 → overlaps the permute with t+1's compute.
        return (c, inflight, outgoing), None

    state = (carry, slice_a, slice_b)
    (carry, sa, sb), _ = jax.lax.scan(step, state, jnp.arange(num_micro_steps))
    return carry, sa, sb


class Rotator:
    """Convenience wrapper holding the rotation config (Harp: dymoro/Rotator).

    Harp's Rotator exposed getRotation(k)/rotate(k) imperative calls; here the
    equivalent is declarative — construct with the schedule shape, call
    :meth:`run` with the per-hop body. Kept as a class so algorithm code reads
    like the reference's.
    """

    def __init__(self, num_workers: int, num_slices: int = 2,
                 axis_name: str = WORKERS):
        if num_slices not in (1, 2):
            raise ValueError("num_slices must be 1 (plain) or 2 (double-buffered)")
        self.num_workers = num_workers
        self.num_slices = num_slices
        self.axis_name = axis_name

    def run(self, body, carry, slices, epochs: int = 1):
        """Run ``epochs`` full rotations. ``slices``: tuple of model slices
        (length == num_slices)."""
        if self.num_slices == 1:
            (slice_a,) = slices
            carry, out = rotate_scan(body, carry, slice_a,
                                     epochs * self.num_workers, self.axis_name)
            return carry, (out,)
        sa, sb = slices
        carry, sa, sb = pipelined_rotation(
            body, carry, sa, sb, epochs * 2 * self.num_workers, self.axis_name)
        return carry, (sa, sb)
