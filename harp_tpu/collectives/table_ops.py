"""Table-level collectives — Harp's user-facing collective API, TPU-native.

Reference parity: the instance methods on ``CollectiveMapper``
(core/harp-hadoop/.../CollectiveMapper.java — broadcast:403, reduce:431,
allgather:455, allreduce:479, regroup:505, pull:538, push:573, rotate:606) and the
static classes in ``collective/``. Each op here is a distribution-state transition on
a :class:`harp_tpu.table.Table` (see table.py docstring for the state model) that
lowers to exactly one XLA collective.

These functions run INSIDE an SPMD program (shard_map over the ``workers`` axis) —
use :class:`harp_tpu.session.HarpSession` to enter one. Non-block partition→worker
maps are handled by a static permutation of the partition axis (harp_tpu.partitioner):
permute → block collective → (on gather) inverse-permute, so arbitrary Harp
partitioners cost one local gather, never extra network.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from harp_tpu import compat
from harp_tpu import combiner as combiner_lib
from harp_tpu import partitioner as partitioner_lib
from harp_tpu.collectives import lax_ops
from harp_tpu.parallel.mesh import WORKERS
from harp_tpu.table import Dist, Table


def _perm_apply(data: jax.Array, perm) -> jax.Array:
    import numpy as np

    if perm is None or bool(np.all(np.asarray(perm) == np.arange(len(perm)))):
        return data
    return jnp.take(data, jnp.asarray(perm), axis=0)


def allreduce(t: Table, axis_name: str = WORKERS, comm=None, residual=None):
    """LOCAL → REPLICATED: combine per-worker contributions partition-wise.

    Reference: AllreduceCollective.allreduce:150 / CollectiveMapper.allreduce:479.

    ``comm``/``residual``: opt-in quantized wire format + error-feedback
    state (collectives/quantize.py); with ``residual`` the return is
    ``(table, residual')``, same contract as :func:`regroup`.
    """
    _expect(t, Dist.LOCAL, "allreduce")
    if residual is not None:
        out, residual = lax_ops.allreduce(t.data, t.combiner, axis_name,
                                          comm=comm, residual=residual)
        return t.with_data(out, Dist.REPLICATED), residual
    out = lax_ops.allreduce(t.data, t.combiner, axis_name, comm=comm)
    return t.with_data(out, Dist.REPLICATED)


def reduce(t: Table, root: int = 0, axis_name: str = WORKERS) -> Table:
    """LOCAL → LOCAL: combined table on ``root``, identity elsewhere
    (ReduceCollective.reduce:150)."""
    _expect(t, Dist.LOCAL, "reduce")
    out = lax_ops.reduce(t.data, root, t.combiner, axis_name)
    return t.with_data(out, Dist.LOCAL)


def broadcast(t: Table, root: int = 0, axis_name: str = WORKERS) -> Table:
    """LOCAL@root → REPLICATED (BcastCollective.broadcast:338)."""
    out = lax_ops.broadcast(t.data, root, axis_name)
    return t.with_data(out, Dist.REPLICATED)


def regroup(
    t: Table,
    partitioner: Optional[partitioner_lib.Partitioner] = None,
    axis_name: str = WORKERS,
    comm=None,
    residual=None,
):
    """LOCAL → SHARDED: route each partition to its owner, combining contributions.

    Reference: RegroupCollective.regroupCombine:154 (partitioner → P2P dispatch →
    combine-on-arrival). Lowered to reduce_scatter (SUM/AVG) or all_to_all+combine.

    ``comm``/``residual``: opt-in quantized wire format + error-feedback
    state (collectives/quantize.py). With ``residual`` the return is
    ``(table, residual')`` — residuals live in the PRE-permutation partition
    order (t.data's), so the same partitioner must ride every call.
    """
    _expect(t, Dist.LOCAL, "regroup")
    perm = partitioner.permutation() if partitioner is not None else None
    data = _perm_apply(t.data, perm)
    res = _perm_apply(residual, perm) if residual is not None else None
    if res is not None:
        out, res = lax_ops.reduce_scatter(data, t.combiner, axis_name,
                                          comm=comm, residual=res)
        inv = (partitioner.inverse_permutation() if partitioner is not None
               else None)
        return t.with_data(out, Dist.SHARDED), _perm_apply(res, inv)
    out = lax_ops.reduce_scatter(data, t.combiner, axis_name, comm=comm)
    return t.with_data(out, Dist.SHARDED)


def allgather(
    t: Table,
    partitioner: Optional[partitioner_lib.Partitioner] = None,
    axis_name: str = WORKERS,
    comm=None,
    fused: bool = False,
) -> Table:
    """SHARDED → REPLICATED (AllgatherCollective.allgather:147, ring relay).

    ``partitioner`` must match the one used at regroup time so partition-ID order is
    restored after the gather. ``comm``: opt-in quantized wire format
    (stateless — the gathered result stays replicated-consistent).
    ``fused`` (r10): the reference's ring relay as W−1 fused in-kernel DMA
    hops (ops/ring_dma; bitwise ``all_gather``) — the Table-level face of
    the shared ring engine.
    """
    _expect(t, Dist.SHARDED, "allgather")
    full = lax_ops.allgather(t.data, axis_name, comm=comm, fused=fused)
    inv = partitioner.inverse_permutation() if partitioner is not None else None
    full = _perm_apply(full, inv)
    return t.with_data(full, Dist.REPLICATED)


def aggregate(
    t: Table,
    partitioner: Optional[partitioner_lib.Partitioner] = None,
    axis_name: str = WORKERS,
) -> Table:
    """LOCAL → REPLICATED via regroup+allgather (RegroupCollective.aggregate:268).

    On TPU this is exactly reduce_scatter + all_gather — the bandwidth-optimal
    allreduce decomposition — so ``aggregate`` and ``allreduce`` cost the same; Harp
    exposed both because its TCP implementations differed.
    """
    return allgather(regroup(t, partitioner, axis_name), partitioner, axis_name)


def rotate(t: Table, steps: int = 1, axis_name: str = WORKERS) -> Table:
    """SHARDED → SHARDED: ring-shift ownership by ``steps``
    (LocalGlobalSyncCollective.rotate:710 → ppermute over the ICI ring)."""
    _expect(t, Dist.SHARDED, "rotate")
    return t.with_data(lax_ops.rotate(t.data, steps, axis_name))


def rotate_with_map(t: Table, mapping: dict, axis_name: str = WORKERS) -> Table:
    """Rotate with an explicit worker→worker map (rotateGlobal:746)."""
    _expect(t, Dist.SHARDED, "rotate")
    return t.with_data(lax_ops.rotate_map(t.data, mapping, axis_name))


def push(
    local: Table,
    global_table: Table,
    partitioner: Optional[partitioner_lib.Partitioner] = None,
    axis_name: str = WORKERS,
    comm=None,
    residual=None,
):
    """Parameter-server push: combine LOCAL contributions into the persistent
    SHARDED global table (LocalGlobalSyncCollective.push:209).

    ``comm``/``residual``: quantize the regroup's wire format; with
    ``residual`` the return is ``(table, residual')`` (see :func:`regroup`).
    """
    _expect(local, Dist.LOCAL, "push")
    _expect(global_table, Dist.SHARDED, "push(global)")
    if residual is not None:
        delta, residual = regroup(local, partitioner, axis_name, comm=comm,
                                  residual=residual)
        merged = global_table.combiner.fn(global_table.data, delta.data)
        return global_table.with_data(merged), residual
    delta = regroup(local, partitioner, axis_name, comm=comm)
    merged = global_table.combiner.fn(global_table.data, delta.data)
    return global_table.with_data(merged)


def pull(
    global_table: Table,
    partitioner: Optional[partitioner_lib.Partitioner] = None,
    axis_name: str = WORKERS,
    comm=None,
    fused: bool = False,
) -> Table:
    """Parameter-server pull: SHARDED global → REPLICATED local copy
    (LocalGlobalSyncCollective.pull:185; the chain-bcast variant :228-295 is an XLA
    scheduling detail here). ``comm``: quantized wire format for the gather;
    ``fused``: the r10 ring-DMA relay (see :func:`allgather`)."""
    return allgather(global_table, partitioner, axis_name, comm=comm,
                     fused=fused)


def gather(t: Table, root: int = 0, axis_name: str = WORKERS) -> Table:
    """SHARDED → root holds the full table (Communication.gather:196)."""
    _expect(t, Dist.SHARDED, "gather")
    out = lax_ops.gather(t.data, root, axis_name)
    return t.with_data(out, Dist.LOCAL)


def join(
    dynamic: Table,
    static: Table,
    partitioner: Optional[partitioner_lib.Partitioner] = None,
    axis_name: str = WORKERS,
) -> Table:
    """Co-locate a dynamic table with a static one (GraphCollective.join:313).

    Harp routed the dynamic table's partitions to whichever worker held the
    matching static partition (vertex tables joining edge tables). Here the
    join is a regroup of the dynamic table; co-location holds ONLY when
    ``partitioner`` is the same one used to shard the static table (a Table
    does not carry its layout, so this contract is the caller's — pass None
    iff the static table uses the default block layout).
    """
    _expect(static, Dist.SHARDED, "join(static)")
    _expect(dynamic, Dist.LOCAL, "join(dynamic)")
    if dynamic.num_partitions != static.num_partitions:
        raise ValueError(
            f"join requires matching partition counts: dynamic has "
            f"{dynamic.num_partitions}, static has {static.num_partitions}")
    return regroup(dynamic, partitioner, axis_name)


def group_by_key(
    keys: jax.Array,
    values: jax.Array,
    num_keys: int,
    combiner: combiner_lib.Combiner = combiner_lib.SUM,
    axis_name: str = WORKERS,
) -> jax.Array:
    """GroupByKeyCollective:42 — shuffle KV pairs by key, combining equal keys.

    TPU-native: all_gather the (key, value) records, then a masked segment reduction
    into the dense key space. Returns the combined value per key, REPLICATED.
    ``num_keys`` must be static (the key-space size).
    """
    all_keys = lax_ops.allgather(keys, axis_name)
    all_vals = lax_ops.allgather(values, axis_name)
    if combiner.op in (combiner_lib.Op.SUM, combiner_lib.Op.AVG):
        out = jax.ops.segment_sum(all_vals, all_keys, num_segments=num_keys)
        if combiner.op is combiner_lib.Op.AVG:
            counts = jax.ops.segment_sum(jnp.ones_like(all_keys), all_keys,
                                         num_segments=num_keys)
            out = out / jnp.maximum(counts, 1).astype(out.dtype).reshape(
                (-1,) + (1,) * (out.ndim - 1))
        return out
    if combiner.op is combiner_lib.Op.MAX:
        return jax.ops.segment_max(all_vals, all_keys, num_segments=num_keys)
    if combiner.op is combiner_lib.Op.MIN:
        return jax.ops.segment_min(all_vals, all_keys, num_segments=num_keys)
    raise ValueError(f"group_by_key unsupported for {combiner.op}")


def default_route_capacity(n: int, num_workers: int) -> int:
    """Default per-destination bucket size: 2x a balanced share."""
    return max(1, 2 * -(-n // num_workers))


def bucket_route(dest: jax.Array, capacity: int, payloads,
                 valid: Optional[jax.Array] = None,
                 axis_name: str = WORKERS):
    """Fixed-capacity owner routing — the shared shuffle core.

    Routes each record (one row of every array in ``payloads``) to worker
    ``dest[i]`` through one ``all_to_all`` of static (W, capacity) buckets.
    ``valid=False`` rows and out-of-range destinations (``dest < 0`` or
    ``dest >= W``) are excluded without consuming capacity. Returns
    ``(routed, recv_mask, overflow, routing)``:
    ``routed`` mirrors ``payloads`` with shapes (W, capacity, ...);
    ``recv_mask`` marks filled slots; ``overflow`` is the psum'd count of
    VALID records dropped for capacity; ``routing`` feeds
    :func:`route_back`."""
    w = compat.axis_size(axis_name)
    n = dest.shape[0]
    # invalid records (valid=False or negative dest) route to a virtual
    # "drop" destination w so they never consume a real bucket's capacity;
    # dest >= w is likewise dropped by the ok mask below
    keep = dest >= 0
    if valid is not None:
        keep = keep & valid
    dest = jnp.where(keep, dest, w)
    order = jnp.argsort(dest, stable=True)
    d_s = dest[order]
    counts = jnp.bincount(d_s, length=w + 1)
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(n) - starts[d_s]
    ok = (pos < capacity) & (d_s < w)
    d_c = jnp.minimum(d_s, w - 1)
    pos_c = jnp.minimum(pos, capacity - 1)
    routed = []
    for p in payloads:
        p_s = p[order]
        okf = ok.astype(p_s.dtype).reshape((n,) + (1,) * (p_s.ndim - 1))
        # valid positions are unique → masked scatter-add == set; excluded
        # rows clamp to the last slot but add zeros
        buf = jnp.zeros((w, capacity) + p_s.shape[1:], p_s.dtype
                        ).at[d_c, pos_c].add(p_s * okf)
        routed.append(jax.lax.all_to_all(buf, axis_name, split_axis=0,
                                         concat_axis=0))
    buf_m = jnp.zeros((w, capacity), jnp.float32).at[d_c, pos_c].add(
        ok.astype(jnp.float32))
    recv_mask = jax.lax.all_to_all(buf_m, axis_name, split_axis=0,
                                   concat_axis=0)
    overflow = jax.lax.psum(jnp.sum((~ok) & (d_s < w)), axis_name)
    routing = (order, d_c, pos_c, ok, n)
    return routed, recv_mask, overflow, routing


def route_back(answers, routing, axis_name: str = WORKERS):
    """Return per-slot answers (W, capacity, ...) to the senders, restoring
    the original record order. Second output marks records whose answer
    actually made the round trip (False for capacity-dropped records)."""
    back = jax.lax.all_to_all(answers, axis_name, split_axis=0, concat_axis=0)
    order, d_c, pos_c, ok, n = routing
    picked = back[d_c, pos_c]
    inv = jnp.zeros_like(order).at[order].set(jnp.arange(n))
    return picked[inv], ok[inv]


def group_by_key_sharded(
    keys: jax.Array,
    values: jax.Array,
    num_keys: int,
    combiner: combiner_lib.Combiner = combiner_lib.SUM,
    capacity: int = 0,
    replicate_result: bool = True,
    axis_name: str = WORKERS,
) -> Tuple[jax.Array, jax.Array]:
    """Owner-partitioned KV shuffle — the scalable GroupByKeyCollective:42.

    Unlike :func:`group_by_key` (which all_gathers every record to every
    worker — O(N·W) memory), records are routed to their key's owner
    (``key // ceil(num_keys/W)``) through ONE ``all_to_all`` of fixed-capacity
    per-destination buckets, then segment-combined locally: per-worker
    footprint is O(N/W · capacity-slack + num_keys/W), matching the
    reference's point-to-point regroup dispatch.

    ``capacity`` is the per-destination bucket size (default ``2·ceil(n/W)``
    — 2× a balanced share). Records beyond a bucket's capacity are DROPPED
    and counted: the second return value is the global overflow count
    (callers must check it — shapes are static under jit, so overflow cannot
    raise device-side). Returns the combined values REPLICATED over workers
    (``replicate_result=False`` keeps only this worker's (ceil(num_keys/W),
    ...) key block).
    """
    w = compat.axis_size(axis_name)
    kpw = -(-num_keys // w)
    n = keys.shape[0]
    cap = capacity or default_route_capacity(n, w)
    dest = jnp.minimum(keys // kpw, w - 1)
    (rk, rv), rm, overflow, _ = bucket_route(dest, cap, (keys, values),
                                             axis_name=axis_name)
    wid = jax.lax.axis_index(axis_name)
    lk = (rk - wid * kpw).reshape(-1)
    lk = jnp.where(rm.reshape(-1) > 0, lk, kpw)     # invalid → drop segment
    rv = rv.reshape((-1,) + rv.shape[2:])
    rm_f = rm.reshape(-1).astype(rv.dtype).reshape(
        (-1,) + (1,) * (rv.ndim - 1))
    # invalid slots are already excluded: their segment id is redirected to
    # the kpw overflow row, which the [:kpw] slice drops
    if combiner.op in (combiner_lib.Op.SUM, combiner_lib.Op.AVG):
        out = jax.ops.segment_sum(rv, lk, num_segments=kpw + 1)[:kpw]
        if combiner.op is combiner_lib.Op.AVG:
            cnt = jax.ops.segment_sum(rm.reshape(-1), lk,
                                      num_segments=kpw + 1)[:kpw]
            out = out / jnp.maximum(cnt, 1.0).astype(out.dtype).reshape(
                (-1,) + (1,) * (out.ndim - 1))
    elif combiner.op in (combiner_lib.Op.MAX, combiner_lib.Op.MIN):
        seg = (jax.ops.segment_max if combiner.op is combiner_lib.Op.MAX
               else jax.ops.segment_min)
        out = seg(rv, lk, num_segments=kpw + 1)[:kpw]
    else:
        raise ValueError(f"group_by_key_sharded unsupported for {combiner.op}")
    if replicate_result:
        out = lax_ops.allgather(out, axis_name)[:num_keys]
    return out, overflow


def _expect(t: Table, dist: Dist, op: str) -> None:
    if t.dist is not dist:
        raise ValueError(
            f"{op} expects a {dist.value} table, got {t.dist.value} "
            f"(table {t.name!r}); see harp_tpu.table state model"
        )
