"""Array-level collective primitives, usable inside shard_map over the worker axis.

Reference parity: Harp's eight collectives in ``collective/`` (SURVEY §2.1). The
reference hand-implements comm algorithms over TCP — chain & MST broadcast
(BcastCollective.broadcast:338), recursive halving/doubling allreduce
(AllreduceCollective.allreduce:150-291), ring allgather (AllgatherCollective:155-213),
point-to-point regroup (RegroupCollective.regroupCombine:154), ring rotate
(LocalGlobalSyncCollective.rotate:710). On TPU the *algorithm choice* belongs to XLA:
each op here is a single named collective and XLA picks the ICI/DCN schedule
(bidirectional rings, etc.). What we keep from Harp is the vocabulary and semantics.

All functions take ``axis_name`` (default "workers") and must be called inside a
``shard_map``/``pmap`` context binding that axis.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from harp_tpu import compat
from harp_tpu import combiner as combiner_lib
from harp_tpu.collectives import quantize
from harp_tpu.parallel.mesh import WORKERS


def worker_id(axis_name: str = WORKERS) -> jax.Array:
    """This worker's ID inside the SPMD program (Harp: Workers.getSelfID)."""
    return jax.lax.axis_index(axis_name)


def num_workers(axis_name: str = WORKERS) -> int:
    return compat.axis_size(axis_name)


def barrier(axis_name: str = WORKERS) -> None:
    """Reference: Communication.barrier:61 (master counts workers then replies).

    Under SPMD a barrier is implicit — every collective synchronizes the axis. This
    exists for API parity and for forcing ordering in timing code; it lowers to a
    1-element psum that XLA cannot elide across.
    """
    jax.lax.psum(jnp.ones((), jnp.int32), axis_name)


def allreduce(
    x: jax.Array,
    combiner: combiner_lib.Combiner = combiner_lib.SUM,
    axis_name: str = WORKERS,
    comm: Optional[quantize.CommConfig] = None,
    residual: Optional[jax.Array] = None,
):
    """All workers end with the combined value.

    Reference: AllreduceCollective.allreduce:150 (recursive halving/doubling).

    ``comm`` (opt-in, quantize.CommConfig): int8/bf16 wire format via the
    two-stage quantized decomposition — dequantize-after-transport, f32
    accumulation (collectives/quantize.py). When ``residual`` is passed
    (error-feedback state shaped like x) the return is ``(out, residual')``
    — also on the f32 path, so call sites stay uniform."""
    if comm is not None and comm.active:
        return quantize.allreduce_q(x, combiner, axis_name, comm, residual)
    out = combiner.psum_like(x, axis_name)
    return (out, residual) if residual is not None else out


def reduce(
    x: jax.Array,
    root: int = 0,
    combiner: combiner_lib.Combiner = combiner_lib.SUM,
    axis_name: str = WORKERS,
) -> jax.Array:
    """Combined value lands on ``root``; other workers get the combiner identity.

    Reference: ReduceCollective.reduce:150. On ICI a rooted reduce costs the same as
    allreduce (the fabric is symmetric), so this is allreduce + mask — the mask keeps
    Harp's semantics observable (non-roots don't see the result).
    """
    full = combiner.psum_like(x, axis_name)
    mask = jax.lax.axis_index(axis_name) == root
    return jnp.where(mask, full, jnp.full_like(full, combiner.identity))


def broadcast(x: jax.Array, root: int = 0, axis_name: str = WORKERS) -> jax.Array:
    """Every worker ends with ``root``'s value.

    Reference: BcastCollective.broadcast:338 (chain or MST over TCP). Lowered as a
    masked psum, which XLA turns into an ICI broadcast tree.
    """
    mask = jax.lax.axis_index(axis_name) == root
    return jax.lax.psum(jnp.where(mask, x, jnp.zeros_like(x)), axis_name)


def allgather(x: jax.Array, axis_name: str = WORKERS, tiled: bool = True,
              comm: Optional[quantize.CommConfig] = None,
              fused: bool = False) -> jax.Array:
    """Concatenate every worker's block along axis 0 (ring allgather).

    Reference: AllgatherCollective.allgather:147 (send-to-next ring relay).
    ``comm``: opt-in quantized wire format (stateless — every worker decodes
    the same payload, so the gathered result stays replicated-consistent).

    ``fused`` (r10): run the reference's ring relay LITERALLY as W−1 fused
    in-kernel DMA hops (ops/ring_dma.ring_allgather — bitwise
    ``all_gather``, no per-hop staging copies; off TPU the engine's tagged
    fallback keeps the jaxpr budget honest). A quantized wire takes
    precedence (the codec needs its encode/decode programs around the
    transport)."""
    if comm is not None and comm.active:
        return quantize.allgather_q(x, axis_name, comm, tiled=tiled)
    if fused:
        from harp_tpu.ops import ring_dma  # local: ring_dma imports lax_ops

        if tiled:
            return ring_dma.ring_allgather(x, axis_name)
        return ring_dma.ring_allgather(x[None], axis_name)
    return jax.lax.all_gather(x, axis_name, tiled=tiled)


def gather(x: jax.Array, root: int = 0, axis_name: str = WORKERS,
           tiled: bool = True) -> jax.Array:
    """Root ends with all blocks; others get zeros (Communication.gather:196)."""
    full = jax.lax.all_gather(x, axis_name, tiled=tiled)
    mask = jax.lax.axis_index(axis_name) == root
    return jnp.where(mask, full, jnp.zeros_like(full))


def reduce_scatter(
    x: jax.Array,
    combiner: combiner_lib.Combiner = combiner_lib.SUM,
    axis_name: str = WORKERS,
    comm: Optional[quantize.CommConfig] = None,
    residual: Optional[jax.Array] = None,
):
    """Combine per-worker contributions and scatter blocks: worker w gets the
    combined block w of the partition axis.

    This is Harp's ``regroup`` with the block partitioner
    (RegroupCollective.regroupCombine:154: partitioner → P2P dispatch → combine on
    arrival). SUM/AVG lower to ``psum_scatter``; other algebras lower to
    ``all_to_all`` + a local combine (XLA has no reduce_scatter for max/min).

    ``comm``/``residual``: opt-in quantized wire format + error-feedback
    state, same contract as :func:`allreduce` (SUM/AVG only).
    """
    if comm is not None and comm.active:
        return quantize.reduce_scatter_q(x, combiner, axis_name, comm,
                                         residual)
    if residual is not None:
        out = reduce_scatter(x, combiner, axis_name)
        return out, residual
    n = compat.axis_size(axis_name)
    if combiner.op in (combiner_lib.Op.SUM, combiner_lib.Op.AVG):
        out = jax.lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
        if combiner.op is combiner_lib.Op.AVG:
            out = out / n
        return out
    # General algebra: exchange blocks, then combine the n contributions locally.
    block = x.shape[0] // n
    chunks = x.reshape((n, block) + x.shape[1:])
    # all_to_all: chunk j of worker i -> worker j's slot i.
    exchanged = jax.lax.all_to_all(chunks, axis_name, split_axis=0, concat_axis=0)
    return combiner.tree_combine(exchanged, axis=0)


def rotate(x: jax.Array, steps: int = 1, axis_name: str = WORKERS,
           comm: Optional[quantize.CommConfig] = None,
           num_chunks: int = 1) -> jax.Array:
    """Ring-shift this worker's block to ``(id + steps) % n`` — i.e. each worker
    receives the block previously held by ``id - steps``.

    Reference: LocalGlobalSyncCollective.rotate:710 (ring or custom rotateMap).
    Lowered to ``ppermute`` which maps 1:1 onto neighbor ICI links.

    ``comm``: opt-in quantized wire format (stateless; rotation loops carry
    error feedback in ``rotation.rotate_scan``'s carry instead).
    ``num_chunks`` > 1 splits the block into that many ppermutes along axis
    0 — DCN-hop pipelining (``rotation.chunks_for_link``): XLA's async
    collective scheduler overlaps in-flight chunks over a slow link, where
    one monolithic permute would serialize behind the first byte.
    """
    if comm is not None and comm.active:
        # chunking composes with quantization at the whole-block level: the
        # encode is one program either way, and a quantized DCN hop is
        # already 2-4x smaller than the chunking threshold assumes
        return quantize.rotate_q(x, steps, axis_name, comm)
    n = compat.axis_size(axis_name)
    perm = [(i, (i + steps) % n) for i in range(n)]
    if num_chunks > 1 and x.ndim and x.shape[0] > 1:
        parts = jnp.array_split(x, min(num_chunks, x.shape[0]), axis=0)
        return jnp.concatenate(
            [jax.lax.ppermute(p, axis_name, perm) for p in parts], axis=0)
    return jax.lax.ppermute(x, axis_name, perm)


def rotate_map(x: jax.Array, mapping: dict, axis_name: str = WORKERS) -> jax.Array:
    """Rotate with an explicit worker→worker map (Harp's rotateMap Int2IntMap,
    LocalGlobalSyncCollective.rotateGlobal:746).

    ``mapping`` must be a bijection over the whole axis: ``ppermute`` sends
    nothing for missing sources and delivers ZEROS to unnamed destinations,
    so a malformed map would silently drop shards — validate loudly instead.
    """
    n = compat.axis_size(axis_name)
    srcs, dsts = set(mapping.keys()), set(mapping.values())
    expect = set(range(n))
    if srcs != expect or dsts != expect:
        missing_src = sorted(expect - srcs)
        missing_dst = sorted(expect - dsts)
        bad = sorted((srcs | dsts) - expect)
        raise ValueError(
            f"rotate_map mapping must be a bijection over all {n} workers: "
            f"sources missing {missing_src}, destinations missing "
            f"{missing_dst}, out-of-range ids {bad} — a partial map would "
            f"silently replace the unnamed workers' shards with zeros")
    perm = sorted(mapping.items())
    return jax.lax.ppermute(x, axis_name, perm)


def all_to_all(x: jax.Array, axis_name: str = WORKERS) -> jax.Array:
    """Block transpose across workers: chunk j of worker i → slot i of worker j.

    The substrate for general regroup and for Ulysses-style sequence parallelism.
    ``x`` has shape (n*block, ...); result has the same shape.
    """
    n = compat.axis_size(axis_name)
    block = x.shape[0] // n
    chunks = x.reshape((n, block) + x.shape[1:])
    out = jax.lax.all_to_all(chunks, axis_name, split_axis=0, concat_axis=0)
    return out.reshape((n * block,) + x.shape[1:])


def send_recv(x: jax.Array, pairs: list[tuple[int, int]],
              axis_name: str = WORKERS) -> jax.Array:
    """Point-to-point sends (source, dest) — Harp's DataSender/event substitute.

    Workers not receiving anything get zeros.
    """
    return jax.lax.ppermute(x, axis_name, pairs)
