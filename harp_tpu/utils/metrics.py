"""Metrics & phase timing — the observability layer.

Reference parity (SURVEY §5): Harp logged inline wall-clock per phase with log4j
(KMeansCollectiveMapper.java:190-195 per-iteration compute/merge/aggregate ms),
JVM memory via ``logMemUsage``:686 and GC time via ``logGCTime``:696, and pool
occupancy dumps. No metrics registry existed. Here: a process-local registry of
counters/gauges/timers with the same phase-timing idiom, plus device-memory
introspection replacing the JVM calls.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from collections import defaultdict
from typing import Dict

log = logging.getLogger("harp_tpu")


class Metrics:
    """Process-local metric registry (counters, gauges, timers)."""

    def __init__(self):
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = {}
        self.timers: Dict[str, list] = defaultdict(list)

    def count(self, name: str, value: float = 1.0) -> None:
        self.counters[name] += value

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    @contextlib.contextmanager
    def timer(self, name: str):
        """Phase timer (Harp's per-iteration ms logging idiom)::

            with metrics.timer("iteration"):
                ...
        """
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timers[name].append(time.perf_counter() - t0)

    def timing(self, name: str) -> Dict[str, float]:
        ts = self.timers.get(name, [])
        if not ts:
            return {}
        return {"count": len(ts), "total_s": sum(ts),
                "mean_s": sum(ts) / len(ts), "last_s": ts[-1]}

    def snapshot(self) -> Dict[str, object]:
        return {
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "timers": {k: self.timing(k) for k in self.timers},
        }

    def dump(self, path: str) -> None:
        """Persist a snapshot as JSON (the supervisor drops one next to its
        restart journal so recovery counters survive the process)."""
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=2, sort_keys=True)

    def log_summary(self) -> None:
        for name, t in sorted(self.timers.items()):
            s = self.timing(name)
            log.info("timer %-24s n=%d total=%.3fs mean=%.4fs",
                     name, s["count"], s["total_s"], s["mean_s"])
        for name, v in sorted(self.counters.items()):
            log.info("counter %-22s %.0f", name, v)


DEFAULT = Metrics()


def log_device_mem_usage() -> Dict[str, int]:
    """Device-memory introspection (replaces CollectiveMapper.logMemUsage:686 /
    logGCTime:696 — there is no GC on the device; HBM stats stand in)."""
    import jax           # deferred: registry users (the gang supervisor) must
    #                      not pay a backend init just to count restarts

    out = {}
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:
            continue
        if stats:
            out[str(d)] = stats.get("bytes_in_use", 0)
            log.info("device %s: %d bytes in use", d,
                     stats.get("bytes_in_use", 0))
    return out
