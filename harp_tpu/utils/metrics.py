"""Metrics & phase timing — the observability layer.

Reference parity (SURVEY §5): Harp logged inline wall-clock per phase with log4j
(KMeansCollectiveMapper.java:190-195 per-iteration compute/merge/aggregate ms),
JVM memory via ``logMemUsage``:686 and GC time via ``logGCTime``:696, and pool
occupancy dumps. No metrics registry existed. Here: a process-local registry of
counters/gauges/timers with the same phase-timing idiom, plus device-memory
introspection replacing the JVM calls. Timers keep a BOUNDED reservoir of
samples (exact count/total/last; percentiles over a statistically uniform
subsample), so a multi-day supervised job cannot grow RAM through its phase
timers — the same bug class PR 1 fixed in ``supervise_local``'s capture buffer.
"""

from __future__ import annotations

import contextlib
import json
import logging
import math
import os
import random
import time
from collections import defaultdict
from typing import Dict, Optional

log = logging.getLogger("harp_tpu")

# Bounded timer storage: enough samples that p99 over a uniform reservoir is
# stable, small enough that thousands of timers stay in the low tens of MB.
RESERVOIR_CAP = 2048


class TimerReservoir:
    """Bounded sample store for one timer.

    ``count``/``total``/``last`` are EXACT over every observation; the sample
    buffer holds at most ``cap`` values maintained as a uniform random
    reservoir (Vitter's algorithm R), so percentiles stay representative of
    the whole stream after the cap is reached. The RNG is seeded per
    reservoir: snapshots are reproducible for a deterministic observation
    stream.
    """

    __slots__ = ("count", "total", "last", "samples", "_cap", "_rng")

    def __init__(self, cap: int = RESERVOIR_CAP, seed: int = 0):
        self.count = 0
        self.total = 0.0
        self.last = 0.0
        self.samples = []
        self._cap = cap
        self._rng = random.Random(seed)

    def add(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.last = value
        if len(self.samples) < self._cap:
            self.samples.append(value)
        else:
            j = self._rng.randrange(self.count)
            if j < self._cap:
                self.samples[j] = value

    def merge(self, other: "TimerReservoir") -> None:
        """Fold another reservoir in: count/total stay EXACT (plain sums),
        the sample buffer concatenates and uniformly subsamples back to
        the cap. The single-writer contract stands — merging is for
        per-thread reservoirs joined AFTER their writers stop (the
        serving load generator's pattern), not for concurrent use."""
        self.count += other.count
        self.total += other.total
        if other.count:
            self.last = other.last
        combined = self.samples + list(other.samples)
        if len(combined) > self._cap:
            combined = self._rng.sample(combined, self._cap)
        self.samples = combined

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the reservoir (q in [0, 1])."""
        return self.percentiles([q])[0]

    def percentiles(self, qs) -> list:
        """Several nearest-rank percentiles off ONE sort of the reservoir
        (timing() asks for three; snapshot() calls timing() per timer at
        every gang publish — re-sorting 2048 samples per quantile would
        triple that cost for nothing)."""
        if not self.samples:
            return [float("nan")] * len(qs)
        ordered = sorted(self.samples)
        n = len(ordered)
        return [ordered[min(n - 1, max(0, math.ceil(q * n) - 1))]
                for q in qs]


class Metrics:
    """Process-local metric registry (counters, gauges, timers)."""

    def __init__(self):
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = {}
        self.timers: Dict[str, TimerReservoir] = defaultdict(TimerReservoir)

    def count(self, name: str, value: float = 1.0) -> None:
        self.counters[name] += value

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        """Record one timer sample directly (for durations measured by the
        caller — e.g. the telemetry layer's amortized per-step times)."""
        self.timers[name].add(seconds)

    @contextlib.contextmanager
    def timer(self, name: str):
        """Phase timer (Harp's per-iteration ms logging idiom)::

            with metrics.timer("iteration"):
                ...
        """
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def merge(self, other: "Metrics") -> None:
        """Fold another registry in (counters summed, gauges taken from
        ``other``, timers reservoir-merged) — the serial join step for
        per-thread registries."""
        for name, v in other.counters.items():
            self.counters[name] += v
        self.gauges.update(other.gauges)
        for name, r in other.timers.items():
            self.timers[name].merge(r)

    def timing(self, name: str) -> Dict[str, float]:
        r = self.timers.get(name)
        if r is None or not r.count:
            return {}
        p50, p90, p99 = r.percentiles([0.50, 0.90, 0.99])
        return {"count": r.count, "total_s": r.total,
                "mean_s": r.total / r.count, "last_s": r.last,
                "p50_s": p50, "p90_s": p90, "p99_s": p99}

    def snapshot(self) -> Dict[str, object]:
        return {
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "timers": {k: self.timing(k) for k in self.timers},
        }

    def dump(self, path: str) -> None:
        """Persist a snapshot as JSON (the supervisor drops one next to its
        restart journal so recovery counters survive the process)."""
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=2, sort_keys=True)

    def log_summary(self) -> None:
        for name in sorted(self.timers):
            s = self.timing(name)
            if not s:
                continue
            log.info("timer %-24s n=%d total=%.3fs mean=%.4fs p50=%.4fs "
                     "p99=%.4fs", name, s["count"], s["total_s"], s["mean_s"],
                     s["p50_s"], s["p99_s"])
        for name, v in sorted(self.counters.items()):
            log.info("counter %-22s %.0f", name, v)


DEFAULT = Metrics()


def log_device_mem_usage(metrics: Optional[Metrics] = None
                         ) -> Dict[str, Dict[str, int]]:
    """Device-memory introspection (replaces CollectiveMapper.logMemUsage:686 /
    logGCTime:696 — there is no GC on the device; HBM stats stand in).

    Returns ``{device: {"bytes_in_use": ..., "peak_bytes_in_use": ...}}`` and,
    when a ``metrics`` registry is passed, gauges both values per device.
    Backends without the introspection raise ``NotImplementedError`` (CPU) or
    an ``XlaRuntimeError`` (a ``RuntimeError`` subclass, e.g. remote tunnels
    mid-teardown); those devices are skipped, anything else propagates.
    """
    import jax           # deferred: registry users (the gang supervisor) must
    #                      not pay a backend init just to count restarts

    out: Dict[str, Dict[str, int]] = {}
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except (NotImplementedError, RuntimeError):
            continue
        if stats:
            row = {"bytes_in_use": int(stats.get("bytes_in_use", 0)),
                   "peak_bytes_in_use": int(stats.get(
                       "peak_bytes_in_use", stats.get("bytes_in_use", 0)))}
            out[str(d)] = row
            if metrics is not None:
                metrics.gauge(f"device.{d.id}.bytes_in_use",
                              row["bytes_in_use"])
                metrics.gauge(f"device.{d.id}.peak_bytes_in_use",
                              row["peak_bytes_in_use"])
            log.info("device %s: %d bytes in use (peak %d)", d,
                     row["bytes_in_use"], row["peak_bytes_in_use"])
    return out
