"""Metrics & phase timing — the observability layer.

Reference parity (SURVEY §5): Harp logged inline wall-clock per phase with log4j
(KMeansCollectiveMapper.java:190-195 per-iteration compute/merge/aggregate ms),
JVM memory via ``logMemUsage``:686 and GC time via ``logGCTime``:696, and pool
occupancy dumps. No metrics registry existed. Here: a process-local registry of
counters/gauges/timers with the same phase-timing idiom, plus device-memory
introspection replacing the JVM calls. Timers keep a BOUNDED reservoir of
samples (exact count/total/last; percentiles over a statistically uniform
subsample), so a multi-day supervised job cannot grow RAM through its phase
timers — the same bug class PR 1 fixed in ``supervise_local``'s capture buffer.
"""

from __future__ import annotations

import contextlib
import json
import logging
import math
import os
import random
import threading
import time
from collections import defaultdict
from typing import Dict, Optional

log = logging.getLogger("harp_tpu")

# Bounded timer storage: enough samples that p99 over a uniform reservoir is
# stable, small enough that thousands of timers stay in the low tens of MB.
RESERVOIR_CAP = 2048


class TimerReservoir:
    """Bounded sample store for one timer.

    ``count``/``total``/``last`` are EXACT over every observation; the sample
    buffer holds at most ``cap`` values maintained as a uniform random
    reservoir (Vitter's algorithm R), so percentiles stay representative of
    the whole stream after the cap is reached. The RNG is seeded per
    reservoir: snapshots are reproducible for a deterministic observation
    stream.

    Thread-safe: ``add``/``merge``/``percentiles`` serialize on ``lock``
    (``count += 1`` and the eviction slot write are read-modify-writes —
    concurrent unsynchronized adders lose observations, jaxlint JL302).
    Pass an existing lock to share one lock across a registry (``Metrics``
    does); standalone reservoirs get their own.
    """

    __slots__ = ("count", "total", "last", "samples", "_cap", "_rng",
                 "_lock")

    def __init__(self, cap: int = RESERVOIR_CAP, seed: int = 0,
                 lock: Optional[threading.RLock] = None):
        self.count = 0
        self.total = 0.0
        self.last = 0.0
        self.samples = []
        self._cap = cap
        self._rng = random.Random(seed)
        self._lock = lock if lock is not None else threading.RLock()

    def add(self, value: float) -> None:
        with self._lock:
            self.count += 1
            self.total += value
            self.last = value
            if len(self.samples) < self._cap:
                self.samples.append(value)
            else:
                j = self._rng.randrange(self.count)
                if j < self._cap:
                    self.samples[j] = value

    def merge(self, other: "TimerReservoir") -> None:
        """Fold another reservoir in: count/total stay EXACT (plain sums),
        the sample buffer concatenates and uniformly subsamples back to
        the cap. ``other`` should be quiescent (the serial join step for
        per-thread/per-mix reservoirs after their writers stop); this
        reservoir may keep serving concurrent adds."""
        with self._lock:
            self.count += other.count
            self.total += other.total
            if other.count:
                self.last = other.last
            combined = self.samples + list(other.samples)
            if len(combined) > self._cap:
                combined = self._rng.sample(combined, self._cap)
            self.samples = combined

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the reservoir (q in [0, 1])."""
        return self.percentiles([q])[0]

    def percentiles(self, qs) -> list:
        """Several nearest-rank percentiles off ONE sort of the reservoir
        (timing() asks for three; snapshot() calls timing() per timer at
        every gang publish — re-sorting 2048 samples per quantile would
        triple that cost for nothing). The lock covers only the sample
        COPY; the sort runs outside it so a hot adder never blocks on a
        reader's O(n log n)."""
        with self._lock:
            samples = list(self.samples)
        return _nearest_rank(samples, qs)


def _nearest_rank(samples: list, qs) -> list:
    """Nearest-rank percentiles over an (unsorted) sample copy — pure, no
    lock: callers copy under their lock and compute out here."""
    if not samples:
        return [float("nan")] * len(qs)
    ordered = sorted(samples)
    n = len(ordered)
    return [ordered[min(n - 1, max(0, math.ceil(q * n) - 1))]
            for q in qs]


class Metrics:
    """Process-local metric registry (counters, gauges, timers).

    Thread-safe under ONE registry lock: the serving plane feeds a shared
    registry from the router receive thread, every micro-batcher thread,
    and the exporter's scrape threads at once — ``counters[name] += v``
    is a read-modify-write that silently loses increments unsynchronized
    (jaxlint JL302), and an unlocked ``snapshot()`` iterating the timers
    dict mid-insert raises. The per-timer reservoirs share the same
    (reentrant) lock, so one acquisition covers a whole
    ``observe``/``timing`` and lock order is trivially consistent.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = {}
        self.timers: Dict[str, TimerReservoir] = defaultdict(
            lambda: TimerReservoir(lock=self._lock))

    def count(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += value

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def observe(self, name: str, seconds: float) -> None:
        """Record one timer sample directly (for durations measured by the
        caller — e.g. the telemetry layer's amortized per-step times)."""
        with self._lock:
            self.timers[name].add(seconds)

    @contextlib.contextmanager
    def timer(self, name: str):
        """Phase timer (Harp's per-iteration ms logging idiom)::

            with metrics.timer("iteration"):
                ...
        """
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def merge(self, other: "Metrics") -> None:
        """Fold another registry in (counters summed, gauges taken from
        ``other``, timers reservoir-merged) — the serial join step for
        per-thread registries (``other`` quiescent; this registry may stay
        live)."""
        with self._lock:
            for name, v in other.counters.items():
                self.counters[name] += v
            self.gauges.update(other.gauges)
            for name, r in other.timers.items():
                self.timers[name].merge(r)

    @staticmethod
    def _timing_from_state(count, total, last, samples) -> Dict[str, float]:
        if not count:
            return {}
        p50, p90, p99 = _nearest_rank(samples, [0.50, 0.90, 0.99])
        return {"count": count, "total_s": total, "mean_s": total / count,
                "last_s": last, "p50_s": p50, "p90_s": p90, "p99_s": p99}

    def timing(self, name: str) -> Dict[str, float]:
        with self._lock:
            r = self.timers.get(name)
            if r is None or not r.count:
                return {}
            state = (r.count, r.total, r.last, list(r.samples))
        return self._timing_from_state(*state)

    def snapshot(self) -> Dict[str, object]:
        """A consistent point-in-time view: ONE lock hold copies raw state
        (a scrape never sees the timers dict mid-insert or a counter
        between the load and the store of its increment), and the
        per-timer percentile sorts run OUTSIDE the lock — an exporter
        scrape must never stall the serving hot path for O(n log n) per
        reservoir."""
        with self._lock:
            counters = dict(self.counters)
            gauges = dict(self.gauges)
            states = {k: (r.count, r.total, r.last, list(r.samples))
                      for k, r in self.timers.items()}
        return {
            "counters": counters,
            "gauges": gauges,
            "timers": {k: self._timing_from_state(*s)
                       for k, s in states.items()},
        }

    def dump(self, path: str) -> None:
        """Persist a snapshot as JSON (the supervisor drops one next to its
        restart journal so recovery counters survive the process)."""
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=2, sort_keys=True)

    def log_summary(self) -> None:
        # one consistent copy, then log OUTSIDE the lock (log.info does
        # I/O — holding the registry lock across it would stall every
        # serving thread for the duration of a handler flush)
        snap = self.snapshot()
        for name in sorted(snap["timers"]):
            s = snap["timers"][name]
            if not s:
                continue
            log.info("timer %-24s n=%d total=%.3fs mean=%.4fs p50=%.4fs "
                     "p99=%.4fs", name, s["count"], s["total_s"], s["mean_s"],
                     s["p50_s"], s["p99_s"])
        for name, v in sorted(snap["counters"].items()):
            log.info("counter %-22s %.0f", name, v)


DEFAULT = Metrics()


def log_device_mem_usage(metrics: Optional[Metrics] = None
                         ) -> Dict[str, Dict[str, int]]:
    """Device-memory introspection (replaces CollectiveMapper.logMemUsage:686 /
    logGCTime:696 — there is no GC on the device; HBM stats stand in).

    Returns ``{device: {"bytes_in_use": ..., "peak_bytes_in_use": ...}}`` and,
    when a ``metrics`` registry is passed, gauges both values per device.
    Backends without the introspection raise ``NotImplementedError`` (CPU) or
    an ``XlaRuntimeError`` (a ``RuntimeError`` subclass, e.g. remote tunnels
    mid-teardown); those devices are skipped, anything else propagates.
    """
    import jax           # deferred: registry users (the gang supervisor) must
    #                      not pay a backend init just to count restarts

    out: Dict[str, Dict[str, int]] = {}
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except (NotImplementedError, RuntimeError):
            continue
        if stats:
            row = {"bytes_in_use": int(stats.get("bytes_in_use", 0)),
                   "peak_bytes_in_use": int(stats.get(
                       "peak_bytes_in_use", stats.get("bytes_in_use", 0)))}
            out[str(d)] = row
            if metrics is not None:
                metrics.gauge(f"device.{d.id}.bytes_in_use",
                              row["bytes_in_use"])
                metrics.gauge(f"device.{d.id}.peak_bytes_in_use",
                              row["peak_bytes_in_use"])
            log.info("device %s: %d bytes in use (peak %d)", d,
                     row["bytes_in_use"], row["peak_bytes_in_use"])
    return out
