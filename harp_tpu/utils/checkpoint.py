"""Checkpoint / resume — a capability UPGRADE over the reference.

Reference parity note (SURVEY §5): Harp has NO framework-level checkpointing —
algorithms persist final models to HDFS (KMUtil.storeCentroids,
KMeansCollectiveMapper.java:201-209) and restart means rerunning from iteration
0. This module adds real periodic checkpoint/resume on orbax (with a plain-numpy
fallback when orbax is unavailable), flagged as an upgrade.

``async_save=True`` overlaps the disk write with training: ``save`` takes the
device→host snapshot synchronously (a consistent cut) and hands the
serialization to a background thread, keeping at most one write in flight —
``wait()`` (or the next save/restore) joins it. A failed background write
re-raises on that join, never silently.

Usage::

    ckpt = Checkpointer(dir, async_save=True)
    ckpt.save(step, {"centroids": cen, "opt": opt_state})   # returns fast
    ...train next epochs...
    ckpt.wait()                            # join the in-flight write
    state = ckpt.restore_latest()          # None if no checkpoint
"""

from __future__ import annotations

import json
import logging
import os
import re
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger("harp_tpu.checkpoint")

# tmp dirs from OTHER writers younger than this survive _prune: they may
# belong to a live concurrent writer on a shared work dir (elastic restart
# overlap / cross-host pid collision); older ones are fail-stop orphans
STALE_TMP_SECONDS = 3600.0

# jax and orbax are imported LAZILY: the gang supervisor verifies checkpoints
# (latest_valid_step(deep=False) → verify_step_dir) between relaunches, and
# that path must stay numpy-only — the supervisor must never initialize a jax
# backend (on TPU it would hold the accelerator against the relaunched gang)
# just to CRC a file.
_ORBAX_UNSET = object()
_ocp_cached: Any = _ORBAX_UNSET


def _orbax():
    """orbax.checkpoint, imported on first use (None if unavailable)."""
    global _ocp_cached
    if _ocp_cached is _ORBAX_UNSET:
        try:
            import orbax.checkpoint as ocp
            _ocp_cached = ocp
        except ImportError:  # pragma: no cover - baked-in image has orbax
            _ocp_cached = None
    return _ocp_cached


MANIFEST = "manifest.json"


def state_meta(state: Dict[str, Any], **extra) -> dict:
    """Manifest ``meta`` for a dict-of-arrays state: per-leaf shapes/dtypes
    plus caller fields (``world=``, ``model=``, layout geometry). Written by
    ``Checkpointer.save(..., meta=...)`` next to the CRCs, so a resume at a
    DIFFERENT world size can rebuild a restore template matching the SAVED
    shapes (:func:`meta_like`) before re-partitioning the state
    (collectives.repartition) onto the new gang."""
    return {
        "shapes": {k: [int(d) for d in np.shape(v)] for k, v in state.items()},
        "dtypes": {k: str(getattr(v, "dtype", np.asarray(v).dtype))
                   for k, v in state.items()},
        **extra,
    }


def meta_like(meta: dict) -> Dict[str, np.ndarray]:
    """A restore template (host zeros) with the SAVED leaves' shapes/dtypes,
    from a :func:`state_meta` manifest entry — what ``like_from_meta``
    callbacks hand to ``restore_latest_valid`` when the checkpoint was
    written at another world size (the current session's shapes would not
    match the payload)."""
    return {k: np.zeros(tuple(shape), np.dtype(meta["dtypes"][k]))
            for k, shape in meta["shapes"].items()}


def list_step_numbers(directory: str) -> List[int]:
    """Step numbers under ``directory`` (``step_NNN`` dirs), ascending.

    The single source of truth for the step-dir naming scheme — the
    Checkpointer, the resume scanners and the fault injector
    (``parallel.faults.corrupt_latest``) all go through here."""
    out = []
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            m = re.fullmatch(r"step_(\d+)", name)
            if m:
                out.append(int(m.group(1)))
    return sorted(out)


def _crc(arr) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def verify_step_dir(path: str, deep: bool = True) -> bool:
    """True iff the step directory's manifest checks out (per-array CRC32 and
    leaf count), or it predates manifests (legacy dirs carry none and stay
    trusted). A torn or bit-flipped checkpoint — a member killed mid-fsync, a
    flaky disk — verifies False instead of blowing up the resume path, so
    restore falls back to the previous step. Works for both payload formats:
    ``arrays.npz`` is checked leaf-by-leaf; an orbax payload is re-loaded and
    its leaf CRCs compared as a multiset (orbax's restored container types
    don't guarantee flatten order, but corruption flips bytes, not order).

    ``deep=False`` skips the orbax re-load (the npz CRC check is cheap and
    always runs): the gang supervisor journaling a resumed step must not
    initialize a jax backend — on TPU that would hold the accelerator
    against every relaunched child — or pay a full restore for an advisory
    field. The tmp-dir-then-rename write already makes an orbax step dir's
    existence prove completeness; the deep CRC re-load runs in the training
    child before the state is trusted."""
    man_path = os.path.join(path, MANIFEST)
    if not os.path.exists(man_path):
        return True
    try:
        with open(man_path) as f:
            man = json.load(f)
        npz = os.path.join(path, "arrays.npz")
        if os.path.exists(npz):
            with np.load(npz) as data:
                if len(data.files) != man["leaves"]:
                    return False
                for i in range(man["leaves"]):
                    if _crc(data[str(i)]) != man["arrays"][str(i)]["crc32"]:
                        return False
            return True
        if not deep:
            return True
        if _orbax() is None:
            return False
        import jax

        leaves = jax.tree.leaves(_orbax().PyTreeCheckpointer().restore(path))
        return _leaves_match_manifest(man, leaves)
    except Exception:
        return False


def _load_manifest(path: str) -> Optional[dict]:
    """The step dir's manifest, or None when it predates manifests."""
    man_path = os.path.join(path, MANIFEST)
    if not os.path.exists(man_path):
        return None
    with open(man_path) as f:
        return json.load(f)


def _leaves_match_manifest(man: dict, leaves) -> bool:
    """Leaf count + CRC32 multiset check (order-insensitive: orbax's restored
    container types don't guarantee flatten order, but corruption flips
    bytes, not order)."""
    if len(leaves) != man["leaves"]:
        return False
    want = sorted(a["crc32"] for a in man["arrays"].values())
    return sorted(_crc(leaf) for leaf in leaves) == want


def latest_valid_step(directory: str, deep: bool = True) -> Optional[int]:
    """Newest step under ``directory`` whose manifest verifies — usable
    without constructing a Checkpointer. The gang supervisor reads this with
    ``deep=False`` to journal the step a relaunch will resume from (see
    :func:`verify_step_dir`)."""
    for s in reversed(list_step_numbers(directory)):
        if verify_step_dir(os.path.join(directory, f"step_{s:012d}"), deep):
            return s
    return None


class Checkpointer:
    """Step-indexed pytree checkpoints with keep-last-N retention."""

    def __init__(self, directory: str, keep: int = 3, use_orbax: bool = True,
                 async_save: bool = False):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        # gang mode uses the self-contained numpy format: orbax's save runs
        # its own multihost coordination that expects EVERY process to call
        # it, while the gang contract here is master-only writes of
        # replicated state (save() docstring) — an orbax master-only save
        # deadlocks in that internal sync
        import jax

        self.use_orbax = (use_orbax and _orbax() is not None
                          and jax.process_count() == 1)
        os.makedirs(self.directory, exist_ok=True)
        if self.use_orbax:
            self._ckptr = _orbax().PyTreeCheckpointer()
        self._executor = None
        self._pending = None
        if async_save:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="harp-ckpt")

    # -- paths ---------------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:012d}")

    def steps(self) -> list:
        self.wait()          # a just-saved checkpoint must be visible
        return self._list_steps()

    def _list_steps(self) -> list:
        return list_step_numbers(self.directory)

    # -- save / restore ------------------------------------------------------
    def save(self, step: int, state: Any, meta: Optional[dict] = None) -> str:
        """Save a pytree of arrays; prunes to the newest ``keep`` checkpoints.

        ``meta`` (JSON-serializable, see :func:`state_meta`) rides in the
        step manifest — models record their world size + layout there so a
        relaunched gang of a different size can re-partition on resume.

        With ``async_save`` the device→host snapshot happens here (consistent
        cut) and the disk write runs on the background thread.

        Multi-process gangs: every member calls save at the same logical
        step with IDENTICAL (replicated) state, and only the MASTER writes —
        concurrent writers on a shared work dir would tear step directories
        (the reference's storeCentroids likewise wrote from the master). The
        in-loop collectives keep members from racing past the chunk
        boundary while the master writes. Gang resume assumes the work dir
        is SHARED across members (the reference's HDFS assumption)."""
        import jax

        path = self._step_dir(step)
        if jax.process_count() > 1 and jax.process_index() != 0:
            return path
        state = jax.tree.map(np.asarray, state)    # D2H snapshot
        if self._executor is not None:
            self.wait()                            # one write in flight
            self._pending = self._executor.submit(self._write, path, state,
                                                  meta)
        else:
            self._write(path, state, meta)
        return path

    def wait(self) -> None:
        """Join any in-flight background write (re-raises its error)."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def _write(self, path: str, state: Any,
               meta: Optional[dict] = None) -> None:
        # Write into a tmp dir and rename: a fail-stop kill mid-write
        # (elastic gang restart, r5) must never leave a step dir that lists
        # as restorable but holds a torn payload — _list_steps only matches
        # the final name, so a checkpoint EXISTS iff it is complete. The
        # manifest (per-array CRC32s) then guarantees it is INTACT: resume
        # skips a corrupt step (verify_step_dir) instead of crashing on it.
        # Both payload formats get the same treatment — the numpy fallback
        # stores leaves only (restore() needs `like` to rebuild the tree).
        import jax

        tmp = f"{path}.tmp-{os.getpid()}"
        leaves, _ = jax.tree.flatten(state)
        if self.use_orbax:
            self._ckptr.save(tmp, state, force=True)
        else:
            os.makedirs(tmp, exist_ok=True)
            np.savez(os.path.join(tmp, "arrays.npz"),
                     **{str(i): leaf for i, leaf in enumerate(leaves)})
        manifest = {
            "leaves": len(leaves),
            "arrays": {str(i): {"crc32": _crc(leaf),
                                "shape": list(np.shape(leaf)),
                                "dtype": str(np.asarray(leaf).dtype)}
                       for i, leaf in enumerate(leaves)},
        }
        if meta is not None:
            manifest["meta"] = meta
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump(manifest, f)
        if os.path.isdir(path):      # re-save of the same step
            import shutil

            shutil.rmtree(path)
        os.replace(tmp, path)
        self._prune()

    def restore(self, step: int, like: Optional[Any] = None) -> Any:
        import jax

        self.wait()
        path = self._step_dir(step)
        if self.use_orbax:
            if like is not None:
                # Restore INTO the `like` structure so container types
                # (tuples, NamedTuples, dataclass pytrees) round-trip
                # identically on both backends.
                try:
                    restored = self._ckptr.restore(path, item=like)
                except TypeError:  # newer orbax dropped the item= kwarg
                    restored = self._ckptr.restore(path)
                treedef = jax.tree.structure(like)
                return jax.tree.unflatten(treedef, jax.tree.leaves(restored))
            return self._ckptr.restore(path)
        data = np.load(os.path.join(path, "arrays.npz"))
        leaves = [data[str(i)] for i in range(len(data.files))]
        return self._unflatten(path, leaves, like)

    def _require_leaf_count(self, path: str, count: int,
                            like: Any) -> None:
        import jax

        want = jax.tree.structure(like).num_leaves
        if count != want:
            raise ValueError(
                f"checkpoint {path} holds {count} arrays but the "
                f"requested structure has {want} leaves — it was written "
                f"for a different state shape (wrong work dir, or the "
                f"model's state definition changed)")

    def _unflatten(self, path: str, leaves: List, like: Optional[Any]) -> Any:
        import jax

        if like is None:
            return leaves
        self._require_leaf_count(path, len(leaves), like)
        return jax.tree.unflatten(jax.tree.structure(like), leaves)

    # -- integrity -----------------------------------------------------------
    def verify(self, step: int) -> bool:
        """Checksum-verify one step (manifest-less legacy dirs stay trusted)."""
        self.wait()
        return verify_step_dir(self._step_dir(step))

    def valid_steps(self) -> List[int]:
        """Steps that verify, oldest first; logs (once per call) the corrupt
        ones being passed over. NOTE: verifies EVERY retained step — and for
        orbax payloads each verification is a full restore. Resume paths
        want :meth:`latest_valid_step` (newest-first, stops at the first
        step that verifies); this full scan is for diagnostics/tests."""
        out = []
        for s in self.steps():
            if verify_step_dir(self._step_dir(s)):
                out.append(s)
            else:
                log.warning("checkpoint step %d fails manifest verification "
                            "— skipping it for resume", s)
        return out

    def latest_valid_step(self) -> Optional[int]:
        """Newest step that verifies, scanning newest-first so a resume pays
        for ONE verification in the common all-healthy case (a torn/corrupt
        newest checkpoint costs one save interval, not the whole run)."""
        self.wait()
        for s in reversed(self._list_steps()):
            if verify_step_dir(self._step_dir(s)):
                return s
            log.warning("checkpoint step %d fails manifest verification "
                        "— skipping it for resume", s)
        return None

    def restore_latest_valid(self, like: Optional[Any] = None, *,
                             like_from_meta=None, return_meta: bool = False
                             ) -> Tuple:
        """``(step, state)`` of the newest step whose payload verifies,
        reading each candidate payload ONCE — ``latest_valid_step()``
        followed by ``restore()`` reads the newest checkpoint twice (for
        orbax, two full restores), doubling resume I/O in the common
        all-healthy case. Corrupt/torn/unreadable steps are logged and
        skipped for the previous one; manifest-less legacy steps restore
        untested. ``(None, None)`` when nothing usable exists.

        ``like_from_meta(meta)`` — when given — builds the restore template
        PER candidate step from that step's manifest ``meta`` (None for
        legacy/meta-less steps), overriding ``like``. This is the
        world-size-agnostic resume hook: a checkpoint written by a W-worker
        gang holds W-shaped leaves, and the template must match the SAVED
        shapes (:func:`meta_like`), not the current session's — the model
        then re-partitions the restored state onto the new world. The
        per-step resolution matters: after an elastic resize the newest and
        the fallback step may have been written at DIFFERENT world sizes.

        ``return_meta=True`` appends the restored step's manifest meta:
        ``(step, state, meta)``."""
        import jax

        self.wait()
        for s in reversed(self._list_steps()):
            path = self._step_dir(s)
            try:
                man = _load_manifest(path)
            except Exception as e:
                log.warning("checkpoint step %d has an unreadable manifest "
                            "(%r) — skipping it for resume", s, e)
                continue
            meta = man.get("meta") if man is not None else None
            eff_like = like_from_meta(meta) if like_from_meta is not None \
                else like
            if man is not None and eff_like is not None:
                # BEFORE the restore try-block: a structure mismatch must
                # raise the clear ValueError, not be swallowed as corruption
                # and silently skipped (which would retrain from scratch)
                self._require_leaf_count(path, man["leaves"], eff_like)
            try:
                if self.use_orbax:
                    state = self.restore(s, like=eff_like)
                    leaves = jax.tree.leaves(state)
                else:
                    with np.load(os.path.join(path, "arrays.npz")) as data:
                        leaves = [data[str(i)]
                                  for i in range(len(data.files))]
                    state = None        # unflatten after verification
            except Exception as e:
                log.warning("checkpoint step %d failed to load (%r) — "
                            "skipping it for resume", s, e)
                continue
            if man is not None and not _leaves_match_manifest(man, leaves):
                log.warning("checkpoint step %d fails manifest verification "
                            "— skipping it for resume", s)
                continue
            if state is None:
                # AFTER verification so a structure mismatch raises the
                # clear ValueError instead of being skipped as corruption
                state = self._unflatten(path, leaves, eff_like)
            return (s, state, meta) if return_meta else (s, state)
        return (None, None, None) if return_meta else (None, None)

    def restore_latest(self, like: Optional[Any] = None) -> Optional[Any]:
        return self.restore_latest_valid(like=like)[1]

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def _prune(self) -> None:
        # runs on the writer thread under async_save — must NOT call steps()
        # (its wait() would join the writer's own in-flight future: deadlock)
        import shutil
        import time

        steps = self._list_steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
        now = time.time()
        for name in os.listdir(self.directory):
            # stale tmp dirs from a writer killed mid-write (fail-stop).
            # ADVICE r5: a foreign-pid tmp dir is NOT proof of a dead
            # writer — on a shared work dir it may belong to a concurrently
            # LIVE writer (overlapping elastic restart, pid collision
            # across hosts), whose in-flight save this rmtree would kill.
            # Only reap dirs old enough that any live write would long have
            # renamed them away (writes are seconds; the threshold is an
            # hour).
            if ".tmp-" not in name or name.endswith(f"tmp-{os.getpid()}"):
                continue
            path = os.path.join(self.directory, name)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue          # racing rename/delete: no longer a tmp
            if age >= STALE_TMP_SECONDS:
                shutil.rmtree(path, ignore_errors=True)
