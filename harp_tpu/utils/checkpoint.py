"""Checkpoint / resume — a capability UPGRADE over the reference.

Reference parity note (SURVEY §5): Harp has NO framework-level checkpointing —
algorithms persist final models to HDFS (KMUtil.storeCentroids,
KMeansCollectiveMapper.java:201-209) and restart means rerunning from iteration
0. This module adds real periodic checkpoint/resume on orbax (with a plain-numpy
fallback when orbax is unavailable), flagged as an upgrade.

``async_save=True`` overlaps the disk write with training: ``save`` takes the
device→host snapshot synchronously (a consistent cut) and hands the
serialization to a background thread, keeping at most one write in flight —
``wait()`` (or the next save/restore) joins it. A failed background write
re-raises on that join, never silently.

Usage::

    ckpt = Checkpointer(dir, async_save=True)
    ckpt.save(step, {"centroids": cen, "opt": opt_state})   # returns fast
    ...train next epochs...
    ckpt.wait()                            # join the in-flight write
    state = ckpt.restore_latest()          # None if no checkpoint
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Optional

import jax
import numpy as np

try:
    import orbax.checkpoint as _ocp
    _HAVE_ORBAX = True
except Exception:      # pragma: no cover - baked-in image has orbax
    _ocp = None
    _HAVE_ORBAX = False


class Checkpointer:
    """Step-indexed pytree checkpoints with keep-last-N retention."""

    def __init__(self, directory: str, keep: int = 3, use_orbax: bool = True,
                 async_save: bool = False):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        # gang mode uses the self-contained numpy format: orbax's save runs
        # its own multihost coordination that expects EVERY process to call
        # it, while the gang contract here is master-only writes of
        # replicated state (save() docstring) — an orbax master-only save
        # deadlocks in that internal sync
        self.use_orbax = (use_orbax and _HAVE_ORBAX
                          and jax.process_count() == 1)
        os.makedirs(self.directory, exist_ok=True)
        if self.use_orbax:
            self._ckptr = _ocp.PyTreeCheckpointer()
        self._executor = None
        self._pending = None
        if async_save:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="harp-ckpt")

    # -- paths ---------------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:012d}")

    def steps(self) -> list:
        self.wait()          # a just-saved checkpoint must be visible
        return self._list_steps()

    def _list_steps(self) -> list:
        out = []
        for name in os.listdir(self.directory):
            m = re.fullmatch(r"step_(\d+)", name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- save / restore ------------------------------------------------------
    def save(self, step: int, state: Any) -> str:
        """Save a pytree of arrays; prunes to the newest ``keep`` checkpoints.

        With ``async_save`` the device→host snapshot happens here (consistent
        cut) and the disk write runs on the background thread.

        Multi-process gangs: every member calls save at the same logical
        step with IDENTICAL (replicated) state, and only the MASTER writes —
        concurrent writers on a shared work dir would tear step directories
        (the reference's storeCentroids likewise wrote from the master). The
        in-loop collectives keep members from racing past the chunk
        boundary while the master writes. Gang resume assumes the work dir
        is SHARED across members (the reference's HDFS assumption)."""
        path = self._step_dir(step)
        if jax.process_count() > 1 and jax.process_index() != 0:
            return path
        state = jax.tree.map(np.asarray, state)    # D2H snapshot
        if self._executor is not None:
            self.wait()                            # one write in flight
            self._pending = self._executor.submit(self._write, path, state)
        else:
            self._write(path, state)
        return path

    def wait(self) -> None:
        """Join any in-flight background write (re-raises its error)."""
        if self._pending is not None:
            pending, self._pending = self._pending, None
            pending.result()

    def _write(self, path: str, state: Any) -> None:
        if self.use_orbax:
            self._ckptr.save(path, state, force=True)
        else:
            # numpy fallback stores leaves only; restore() needs `like` to
            # rebuild the tree structure. Write into a tmp dir and rename:
            # a fail-stop kill mid-write (elastic gang restart, r5) must
            # never leave a step dir that lists as restorable but holds a
            # torn npz — _list_steps only matches the final name, so a
            # checkpoint EXISTS iff it is complete
            tmp = f"{path}.tmp-{os.getpid()}"
            os.makedirs(tmp, exist_ok=True)
            leaves, _ = jax.tree.flatten(state)
            np.savez(os.path.join(tmp, "arrays.npz"),
                     **{str(i): leaf for i, leaf in enumerate(leaves)})
            if os.path.isdir(path):      # re-save of the same step
                import shutil

                shutil.rmtree(path)
            os.replace(tmp, path)
        self._prune()

    def restore(self, step: int, like: Optional[Any] = None) -> Any:
        self.wait()
        path = self._step_dir(step)
        if self.use_orbax:
            if like is not None:
                # Restore INTO the `like` structure so container types
                # (tuples, NamedTuples, dataclass pytrees) round-trip
                # identically on both backends.
                try:
                    restored = self._ckptr.restore(path, item=like)
                except TypeError:  # newer orbax dropped the item= kwarg
                    restored = self._ckptr.restore(path)
                treedef = jax.tree.structure(like)
                return jax.tree.unflatten(treedef, jax.tree.leaves(restored))
            return self._ckptr.restore(path)
        data = np.load(os.path.join(path, "arrays.npz"))
        leaves = [data[str(i)] for i in range(len(data.files))]
        if like is not None:
            treedef = jax.tree.structure(like)
            return jax.tree.unflatten(treedef, leaves)
        return leaves

    def restore_latest(self, like: Optional[Any] = None) -> Optional[Any]:
        steps = self.steps()
        if not steps:
            return None
        return self.restore(steps[-1], like=like)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def _prune(self) -> None:
        # runs on the writer thread under async_save — must NOT call steps()
        # (its wait() would join the writer's own in-flight future: deadlock)
        import shutil

        steps = self._list_steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
        for name in os.listdir(self.directory):
            # stale tmp dirs from a writer killed mid-write (fail-stop)
            if ".tmp-" in name and not name.endswith(f"tmp-{os.getpid()}"):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)
