"""Checkpoint / resume — a capability UPGRADE over the reference.

Reference parity note (SURVEY §5): Harp has NO framework-level checkpointing —
algorithms persist final models to HDFS (KMUtil.storeCentroids,
KMeansCollectiveMapper.java:201-209) and restart means rerunning from iteration
0. This module adds real periodic checkpoint/resume on orbax (with a plain-numpy
fallback when orbax is unavailable), flagged as an upgrade.

Usage::

    ckpt = Checkpointer(dir)
    ckpt.save(step, {"centroids": cen, "opt": opt_state})
    state = ckpt.restore_latest()          # None if no checkpoint
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Optional

import jax
import numpy as np

try:
    import orbax.checkpoint as _ocp
    _HAVE_ORBAX = True
except Exception:      # pragma: no cover - baked-in image has orbax
    _ocp = None
    _HAVE_ORBAX = False


class Checkpointer:
    """Step-indexed pytree checkpoints with keep-last-N retention."""

    def __init__(self, directory: str, keep: int = 3, use_orbax: bool = True):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        self.use_orbax = use_orbax and _HAVE_ORBAX
        os.makedirs(self.directory, exist_ok=True)
        if self.use_orbax:
            self._ckptr = _ocp.PyTreeCheckpointer()

    # -- paths ---------------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:012d}")

    def steps(self) -> list:
        out = []
        for name in os.listdir(self.directory):
            m = re.fullmatch(r"step_(\d+)", name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    # -- save / restore ------------------------------------------------------
    def save(self, step: int, state: Any) -> str:
        """Save a pytree of arrays; prunes to the newest ``keep`` checkpoints."""
        path = self._step_dir(step)
        state = jax.tree.map(np.asarray, state)
        if self.use_orbax:
            self._ckptr.save(path, state, force=True)
        else:
            # numpy fallback stores leaves only; restore() needs `like` to
            # rebuild the tree structure
            os.makedirs(path, exist_ok=True)
            leaves, _ = jax.tree.flatten(state)
            np.savez(os.path.join(path, "arrays.npz"),
                     **{str(i): leaf for i, leaf in enumerate(leaves)})
        self._prune()
        return path

    def restore(self, step: int, like: Optional[Any] = None) -> Any:
        path = self._step_dir(step)
        if self.use_orbax:
            if like is not None:
                # Restore INTO the `like` structure so container types
                # (tuples, NamedTuples, dataclass pytrees) round-trip
                # identically on both backends.
                try:
                    restored = self._ckptr.restore(path, item=like)
                except TypeError:  # newer orbax dropped the item= kwarg
                    restored = self._ckptr.restore(path)
                treedef = jax.tree.structure(like)
                return jax.tree.unflatten(treedef, jax.tree.leaves(restored))
            return self._ckptr.restore(path)
        data = np.load(os.path.join(path, "arrays.npz"))
        leaves = [data[str(i)] for i in range(len(data.files))]
        if like is not None:
            treedef = jax.tree.structure(like)
            return jax.tree.unflatten(treedef, leaves)
        return leaves

    def restore_latest(self, like: Optional[Any] = None) -> Optional[Any]:
        steps = self.steps()
        if not steps:
            return None
        return self.restore(steps[-1], like=like)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def _prune(self) -> None:
        import shutil

        steps = self.steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
