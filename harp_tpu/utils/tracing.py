"""Profiler integration — jax.profiler traces replacing Harp's log4j timing.

Reference parity (SURVEY §5): the reference had no dedicated tracer, only inline
wall-clock logs. The TPU build gets real traces: ``trace(dir)`` captures an XLA
profile viewable in TensorBoard/xprof, ``annotate(name)`` marks host spans that
show up on the trace timeline — strictly more capable than the reference, at
parity cost zero.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a profiler trace for the enclosed block."""
    start_trace(log_dir)
    try:
        yield
    finally:
        stop_trace()


def start_trace(log_dir: str) -> None:
    """Open a trace capture (split form of :func:`trace` — for windows that
    span host loop boundaries, e.g. the gang telemetry layer's on-demand
    xprof windows, telemetry/xprof.py)."""
    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    """Close the capture opened by :func:`start_trace`."""
    jax.profiler.stop_trace()


def annotate(name: str):
    """Named span on the profiler timeline (usable as decorator/context)."""
    return jax.profiler.TraceAnnotation(name)


def device_memory_profile(path: str) -> None:
    """Dump a device-memory profile (pprof format)."""
    jax.profiler.save_device_memory_profile(path)
