"""Unified per-algorithm launcher surface — ``python -m harp_tpu.run <algo>``.

Reference parity: Harp shipped one CLI launcher per algorithm (``hadoop jar
harp-java-0.1.0.jar edu.iu.kmeans.regroupallgather.KMeansLauncher ...``,
README.md:148-160) with standardized arg parsing (data_aux/Initialize.java:97).
Here one subcommand per BASELINE workload family, with the algorithm-config
flags derived from the model's config dataclass (harp_tpu.config):

    python -m harp_tpu.run kmeans --num-points 100000 --num-centroids 100 \\
        --dim 100 --iterations 10 --work-dir /tmp/km
    python -m harp_tpu.run sgd_mf --num-users 8192 --num-items 8192 \\
        --epochs 10 --work-dir /tmp/mf --save-every 2      # checkpoint+resume
    python -m harp_tpu.run lda --num-docs 2048 --vocab 2000 --num-topics 32
    python -m harp_tpu.run pca --num-points 65536 --dim 256
    python -m harp_tpu.run nn --num-points 8192 --dim 64 --epochs 10

Every subcommand accepts ``--num-workers N`` (mesh size; defaults to all
devices) and ``--cpu-mesh`` (force an N-device virtual CPU mesh — the
reference's multi-mapper local mode). Data is synthetic by default
(io.datagen — the reference launchers likewise embedded generators); kmeans
accepts ``--points-file`` for CSV input.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time


def _common_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--num-workers", type=int, default=0,
                   help="mesh size (0 = all devices; reference: map tasks)")
    p.add_argument("--cpu-mesh", action="store_true",
                   help="force a virtual CPU mesh of num-workers devices")
    p.add_argument("--work-dir", default="",
                   help="output/checkpoint directory (optional)")
    p.add_argument("--seed", type=int, default=0)


def _session(args):
    if args.cpu_mesh:
        n = args.num_workers or 8
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count={n}")
    import jax

    if args.cpu_mesh:
        jax.config.update("jax_platforms", "cpu")
    from harp_tpu.session import HarpSession

    n = args.num_workers or len(jax.devices())
    return HarpSession(num_workers=min(n, len(jax.devices())))


def _config_from_args(cls, ns, **overrides):
    import typing
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if hints.get(f.name) not in (int, float, str, bool):
            continue
        v = getattr(ns, f.name, None)
        if v is not None:
            kwargs[f.name] = v
    kwargs.update(overrides)
    return cls(**kwargs)


def _add_config_flags(p, cls):
    from harp_tpu.config import add_dataclass_args

    add_dataclass_args(p, cls)


# --------------------------------------------------------------------------- #
# Subcommands (one per BASELINE workload family)
# --------------------------------------------------------------------------- #

def run_kmeans(argv) -> int:
    from harp_tpu.models.kmeans import KMeansConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run kmeans")
    _common_flags(p)
    p.add_argument("--num-points", type=int, default=100_000)
    p.add_argument("--points-file", default="")
    _add_config_flags(p, KMeansConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen, loaders
    from harp_tpu.models import kmeans as km

    cfg = _config_from_args(km.KMeansConfig, args)
    if args.points_file:
        pts = loaders.load_dense_csv([args.points_file])
    else:
        pts = datagen.dense_points(args.num_points, cfg.dim, seed=args.seed,
                                   num_clusters=cfg.num_centroids)
    pts = pts[: len(pts) - len(pts) % sess.num_workers]
    cen0 = datagen.initial_centroids(pts, cfg.num_centroids, seed=args.seed + 1)
    model = km.KMeans(sess, cfg)
    pts_dev, cen_dev = model.prepare(pts, cen0)
    model.fit_prepared(pts_dev, cen_dev)          # compile + warmup
    t0 = time.perf_counter()
    cen, costs = model.fit_prepared(pts_dev, cen_dev)
    costs = np.asarray(costs)
    dt = time.perf_counter() - t0
    print(f"kmeans[{cfg.comm}] workers={sess.num_workers} n={len(pts)} "
          f"k={cfg.num_centroids} d={cfg.dim}: {cfg.iterations / dt:.2f} "
          f"iters/s, cost {costs[0]:.1f} -> {costs[-1]:.1f}")
    if args.work_dir:
        os.makedirs(args.work_dir, exist_ok=True)
        # reference: KMUtil.storeCentroids writes the final model
        np.savetxt(os.path.join(args.work_dir, "centroids.csv"),
                   np.asarray(cen), delimiter=",")
    return 0


def run_sgd_mf(argv) -> int:
    from harp_tpu.models.sgd_mf import SGDMFConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run sgd_mf")
    _common_flags(p)
    p.add_argument("--num-users", type=int, default=8192)
    p.add_argument("--num-items", type=int, default=8192)
    p.add_argument("--density", type=float, default=0.01)
    p.add_argument("--adaptive", action="store_true",
                   help="auto-tune the per-hop budget (adjustMiniBatch analog)")
    p.add_argument("--save-every", type=int, default=0,
                   help="checkpoint every N epochs into work-dir (resumes "
                        "automatically if checkpoints exist)")
    _add_config_flags(p, SGDMFConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import sgd_mf

    cfg = _config_from_args(sgd_mf.SGDMFConfig, args)
    rows, cols, vals = datagen.sparse_ratings(
        args.num_users, args.num_items, rank=min(cfg.rank, 16),
        density=args.density, seed=args.seed)
    model = sgd_mf.SGDMF(sess, cfg)
    state = model.prepare(rows, cols, vals, args.num_users, args.num_items,
                          seed=args.seed)
    t0 = time.perf_counter()
    if args.save_every and args.work_dir:
        from harp_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(os.path.join(args.work_dir, "ckpt"))
        model.warmup_epoch(state)                 # compile outside the timing
        t0 = time.perf_counter()
        w, h, rmse, start = model.fit_checkpointed(
            state, ckpt, save_every=args.save_every)
        ran = cfg.epochs - start
    elif args.adaptive:
        w, h, rmse, tuner = model.fit_adaptive(state)
        ran = cfg.epochs
        print(f"tuned budget: {tuner.chosen} "
              f"(times {dict(sorted(tuner.times.items()))})")
    else:
        model.fit_prepared(state)                 # compile + warmup
        t0 = time.perf_counter()
        w, h, rmse = model.fit_prepared(state)
        ran = cfg.epochs
    dt = time.perf_counter() - t0
    if ran <= 0 or not len(rmse):
        print(f"sgd_mf[{model.last_layout_stats['layout']}] "
              f"workers={sess.num_workers}: fully resumed from checkpoint, "
              f"nothing left to run")
        return 0
    nnz = len(vals) - model.last_layout_stats.get("duplicates_dropped", 0)
    if args.adaptive:
        # the wall-clock region above includes per-candidate AOT compiles and
        # warm-ups; the tuner's own steady-state epoch timings are the honest
        # throughput figure (advisor r2)
        dt = tuner.times[tuner.chosen] * ran
    sps = nnz * ran / dt
    steady = " (tuner steady-state)" if args.adaptive else ""
    print(f"sgd_mf[{model.last_layout_stats['layout']}] "
          f"workers={sess.num_workers} nnz={nnz} rank={cfg.rank}: "
          f"{sps / 1e6:.2f} M samples/s{steady}, rmse {rmse[0]:.4f} -> "
          f"{rmse[-1]:.4f}")
    return 0


def run_lda(argv) -> int:
    from harp_tpu.models.lda import LDAConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run lda")
    _common_flags(p)
    p.add_argument("--num-docs", type=int, default=1024)
    p.add_argument("--doc-len", type=int, default=64)
    _add_config_flags(p, LDAConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import lda

    cfg = _config_from_args(lda.LDAConfig, args)
    num_docs = args.num_docs - args.num_docs % sess.num_workers
    docs = datagen.lda_corpus(num_docs, cfg.vocab,
                              max(2, cfg.num_topics // 2), args.doc_len,
                              seed=args.seed)
    model = lda.LDA(sess, cfg)
    state = model.prepare(docs, seed=args.seed)   # host layout + H2D once
    model.fit_prepared(state)                     # compile + warmup
    t0 = time.perf_counter()
    _, _, ll = model.fit_prepared(state)
    dt = time.perf_counter() - t0
    toks = docs.size * cfg.epochs
    print(f"lda[cgs] workers={sess.num_workers} docs={num_docs} "
          f"vocab={cfg.vocab} K={cfg.num_topics}: {toks / dt / 1e6:.2f} "
          f"M tokens/s, ll {ll[0]:.4e} -> {ll[-1]:.4e}")
    return 0


def run_pca(argv) -> int:
    p = argparse.ArgumentParser(prog="harp_tpu.run pca")
    _common_flags(p)
    p.add_argument("--num-points", type=int, default=65536)
    p.add_argument("--dim", type=int, default=256)
    p.add_argument("--iterations", type=int, default=5,
                   help="timed repeats")
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import stats

    n = args.num_points - args.num_points % sess.num_workers
    x = datagen.dense_points(n, args.dim, seed=args.seed)
    # place once; re-scattering an already-placed array is a no-op, so the
    # timed loop measures compute, not host->device transfer
    x_dev = sess.scatter(x)
    model = stats.PCA(sess)
    model.fit(x_dev)                              # compile + warmup
    t0 = time.perf_counter()
    for _ in range(args.iterations):
        w, comps, mean = model.fit(x_dev)
    dt = time.perf_counter() - t0
    print(f"pca workers={sess.num_workers} n={n} d={args.dim}: "
          f"{args.iterations / dt:.2f} fits/s, top eigenvalue {w[0]:.4f}")
    return 0


def run_nn(argv) -> int:
    from harp_tpu.models.nn import NNConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run nn")
    _common_flags(p)
    p.add_argument("--num-points", type=int, default=8192)
    p.add_argument("--dim", type=int, default=64)
    _add_config_flags(p, NNConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import nn

    cfg = _config_from_args(nn.NNConfig, args)
    n = args.num_points - args.num_points % sess.num_workers
    x, y = datagen.classification_data(n, args.dim, cfg.num_classes,
                                       seed=args.seed)
    model = nn.MLPClassifier(sess, cfg)
    model.fit(x, y, seed=args.seed)               # compile + warmup
    t0 = time.perf_counter()
    losses = model.fit(x, y, seed=args.seed)
    dt = time.perf_counter() - t0
    acc = (model.predict(x) == y).mean()
    samples = n * cfg.epochs
    print(f"nn workers={sess.num_workers} n={n} d={args.dim} "
          f"layers={cfg.layers}: {samples / dt / 1e6:.2f} M samples/s, "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f}, "
          f"train acc {acc:.3f}")
    return 0


COMMANDS = {
    "kmeans": run_kmeans,
    "sgd_mf": run_sgd_mf,
    "lda": run_lda,
    "pca": run_pca,
    "nn": run_nn,
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("subcommands:", ", ".join(sorted(COMMANDS)))
        return 0
    cmd = argv[0]
    if cmd not in COMMANDS:
        print(f"unknown subcommand {cmd!r}; choose from "
              f"{', '.join(sorted(COMMANDS))}", file=sys.stderr)
        return 2
    return COMMANDS[cmd](argv[1:])


if __name__ == "__main__":
    sys.exit(main())
