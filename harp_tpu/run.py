"""Unified per-algorithm launcher surface — ``python -m harp_tpu.run <algo>``.

Reference parity: Harp shipped one CLI launcher per algorithm (``hadoop jar
harp-java-0.1.0.jar edu.iu.kmeans.regroupallgather.KMeansLauncher ...``,
README.md:148-160) with standardized arg parsing (data_aux/Initialize.java:97).
Here one subcommand per BASELINE workload family, with the algorithm-config
flags derived from the model's config dataclass (harp_tpu.config):

    python -m harp_tpu.run kmeans --num-points 100000 --num-centroids 100 \\
        --dim 100 --iterations 10 --work-dir /tmp/km
    python -m harp_tpu.run sgd_mf --num-users 8192 --num-items 8192 \\
        --epochs 10 --work-dir /tmp/mf --save-every 2      # checkpoint+resume
    python -m harp_tpu.run lda --num-docs 2048 --vocab 2000 --num-topics 32
    python -m harp_tpu.run pca --num-points 65536 --dim 256
    python -m harp_tpu.run nn --num-points 8192 --dim 64 --epochs 10

Every subcommand accepts ``--num-workers N`` (mesh size; defaults to all
devices) and ``--cpu-mesh`` (force an N-device virtual CPU mesh — the
reference's multi-mapper local mode). Data is synthetic by default
(io.datagen — the reference launchers likewise embedded generators); file
input mirrors the reference's per-algorithm datasets/ (tiny canonical
fixtures ship in ``datasets/``, regenerate with ``datasets/generate.py``):
``kmeans``/``pca`` ``--points-file``, ``svm`` ``--train-file`` (label in
the last column), ``sgd_mf``/``als`` ``--ratings-file`` (COO), ``lda``
``--corpus-file``, ``subgraph`` ``--template-file`` — each takes a file,
a directory of part-files, or a glob, local or ``scheme://`` remote
(io.loaders.list_files).

Fault tolerance: every subcommand accepts ``--max-restarts N`` — outside a
gang the job re-execs under the elastic supervisor
(parallel.supervisor) and a crash relaunches from the latest verified
checkpoint; under the gang launcher the gang-level supervisor owns
restarts. ``HARP_FAULT`` (parallel.faults) scripts deterministic faults at
the checkpointed loops' iteration boundaries (README: Fault tolerance).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time
from typing import Optional


def _common_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--num-workers", type=int, default=0,
                   help="mesh size (0 = all devices; reference: map tasks)")
    p.add_argument("--cpu-mesh", action="store_true",
                   help="force a virtual CPU mesh of num-workers devices")
    p.add_argument("--work-dir", default="",
                   help="output/checkpoint directory (optional)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-restarts", type=int, default=0,
                   help="elastic supervision: on a crash, relaunch the job "
                        "from the latest verified checkpoint up to N times "
                        "(parallel.supervisor; restart journal lands in "
                        "work-dir). Inside a gang this is handled by the "
                        "gang-level supervisor and ignored here.")
    p.add_argument("--telemetry-dir", default="",
                   help="enable gang telemetry (harp_tpu.telemetry): "
                        "per-step JSONL events + comm-volume gauges land in "
                        "DIR/rank<r>/, gang mode adds the straggler report "
                        "and the events-triggered xprof window. Empty = off "
                        "(zero overhead).")
    p.add_argument("--telemetry-interval", type=int, default=16,
                   help="telemetry cadence in CHUNK BOUNDARIES (count-based "
                        "so gang ranks stay aligned): flush + gang straggler "
                        "publish every N boundaries")
    p.add_argument("--metrics-port", type=int, default=-1,
                   help="start the per-process pull exporter "
                        "(telemetry.exporter: /metrics Prometheus text, "
                        "/snapshot JSON, /gang aggregated view in gang "
                        "mode). 0 = ephemeral port (printed at startup), "
                        ">0 = that port + this member's rank (same-host "
                        "gang members never collide), negative = off.")
    p.add_argument("--slo-p99-ms", type=float, default=0.0,
                   help="arm the SLO watchdog (telemetry.watchdog) at this "
                        "rolling p99 target over the CHUNK-BOUNDARY walls "
                        "(compiled chunk + checkpoint + any host drag): on "
                        "sustained burn it auto-arms an xprof window (the "
                        "trigger-file path, every rank), dumps the "
                        "straggler-format snapshot, and journals the "
                        "incident under --telemetry-dir. 0 = off; requires "
                        "--telemetry-dir.")
    p.add_argument("--compile-cache-dir", default="",
                   help="jax persistent compilation cache directory "
                        "(harp_tpu.aot.cache): every XLA compile this run "
                        "performs is written there and every later run — "
                        "or serving worker/spare pointed at the same dir — "
                        "loads instead of compiling. Composable with the "
                        "AOT export artifacts (`aot warm`), which kill the "
                        "trace; this kills the compile. Empty = off.")
    p.add_argument("--slo-window-s", type=float, default=30.0,
                   help="SLO watchdog rolling-window length, seconds")
    p.add_argument("--slo-error-budget", type=float, default=0.1,
                   help="SLO watchdog tolerated error fraction over the "
                        "window (the serving path feeds errors; training "
                        "step walls are all ok=True, so only the p99 "
                        "target fires there)")


def _session(args):
    if args.cpu_mesh:
        n = args.num_workers or 8
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count={n}")
    import jax

    if args.cpu_mesh:
        jax.config.update("jax_platforms", "cpu")
    # join the gang when launched by parallel.launch (HARP_COORDINATOR in
    # the environment — the reference's launchers always ran under the
    # gang), so
    #   python -m harp_tpu.parallel.launch nodes -- python -m harp_tpu.run …
    # trains ONE distributed model across the gang's global mesh instead of
    # N independent copies. Gated on the LAUNCHER env specifically: the
    # broader TPU-pod auto-detect (TPU_WORKER_HOSTNAMES) misfires on
    # single-chip tunnel hosts that export pod-shaped variables
    if os.environ.get("HARP_COORDINATOR"):
        from harp_tpu.parallel import distributed

        distributed.initialize()
    if getattr(args, "compile_cache_dir", ""):
        from harp_tpu.aot.cache import enable_compile_cache

        enable_compile_cache(args.compile_cache_dir)
    from harp_tpu.session import HarpSession

    n = args.num_workers or len(jax.devices())
    if jax.process_count() > 1:
        # gang mode: --num-workers sized this member's VIRTUAL device share
        # (the cpu-mesh flag above); the session always spans the global mesh
        n = len(jax.devices())
    sess = HarpSession(num_workers=min(n, len(jax.devices())))
    if getattr(args, "telemetry_dir", ""):
        _enable_telemetry(sess, args.telemetry_dir, args.telemetry_interval,
                          slo_p99_ms=getattr(args, "slo_p99_ms", 0.0),
                          slo_window_s=getattr(args, "slo_window_s", 30.0),
                          slo_error_budget=getattr(args, "slo_error_budget",
                                                   0.1),
                          metrics_port=getattr(args, "metrics_port", -1))
    elif getattr(args, "metrics_port", -1) >= 0:
        # the exporter is useful without the JSONL layer (scrape-only runs)
        _start_exporter(getattr(args, "metrics_port", -1), collector=None)
    return sess


def _start_exporter(metrics_port: int, collector):
    from harp_tpu.telemetry.exporter import MetricsExporter

    rank = int(os.environ.get("HARP_PROCESS_ID", "0"))
    port = metrics_port + rank if metrics_port > 0 else 0
    exporter = MetricsExporter(
        port=port, rank=rank,
        gang=collector.snapshots if collector is not None else None)
    print(f"harp_tpu.telemetry: metrics exporter on "
          f"http://{exporter.host}:{exporter.port} "
          f"(/metrics, /snapshot{', /gang' if collector else ''})",
          file=sys.stderr, flush=True)
    return exporter


def _enable_telemetry(sess, directory: str, interval: int, *,
                      slo_p99_ms: float = 0.0, slo_window_s: float = 30.0,
                      slo_error_budget: float = 0.1,
                      metrics_port: int = -1) -> None:
    """Bring up the telemetry layer for this run (harp_tpu.telemetry):
    per-step JSONL + comm gauges always; in gang mode also the straggler
    publisher and the xprof window controller as chunk-boundary hooks —
    count-based cadence, safe because every member runs the same SPMD host
    loop (same argv, shared checkpoint state). Optionally the pull
    exporter (--metrics-port) and the SLO watchdog (--slo-p99-ms) ride the
    same boundary-hook surface."""
    import jax

    from harp_tpu import telemetry

    log = telemetry.configure(directory, interval=interval)
    if log is None:
        return
    from harp_tpu.telemetry.xprof import XprofController

    # the operator trigger: `echo '{"steps": 20}' > DIR/xprof_request.json`
    # while the job runs opens a window on every rank at its next boundary
    log.add_boundary_hook(XprofController(
        sess, trigger_path=os.path.join(directory, "xprof_request.json"),
        default_dir=os.path.join(directory, "xprof")))
    collector = None
    if jax.process_count() > 1:
        from harp_tpu.telemetry.gang import GangCollector

        collector = GangCollector(sess, directory)
        log.add_boundary_hook(collector)
    if metrics_port >= 0:
        _start_exporter(metrics_port, collector)
    if slo_p99_ms > 0:
        from harp_tpu.telemetry.watchdog import SLOWatchdog

        # fed the inter-boundary wall at every chunk boundary; on burn the
        # xprof trigger file arms EVERY rank's controller (installed above).
        # min_samples=3, not the request-stream default of 20: boundaries
        # are CHUNKY (a job may only have tens of them), and 3 is the same
        # cold-rank floor the straggler detector trusts a p50 at
        wd = SLOWatchdog(slo_p99_ms / 1e3, window_s=slo_window_s,
                         error_budget=slo_error_budget, min_samples=3,
                         telemetry_dir=directory, metrics=log.metrics)
        log.add_boundary_hook(wd.boundary_hook())


def _config_from_args(cls, ns, **overrides):
    import typing
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if hints.get(f.name) not in (int, float, str, bool):
            continue
        v = getattr(ns, f.name, None)
        if v is not None:
            kwargs[f.name] = v
    kwargs.update(overrides)
    return cls(**kwargs)


def _add_config_flags(p, cls, skip=None):
    from harp_tpu.config import add_dataclass_args

    add_dataclass_args(p, cls, skip=skip)


# --------------------------------------------------------------------------- #
# Subcommands (one per BASELINE workload family)
# --------------------------------------------------------------------------- #

def run_kmeans(argv) -> int:
    from harp_tpu.models.kmeans import KMeansConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run kmeans")
    _common_flags(p)
    p.add_argument("--num-points", type=int, default=100_000)
    p.add_argument("--points-file", default="")
    p.add_argument("--save-every", type=int, default=0,
                   help="checkpoint centroids every N iterations into "
                        "work-dir (resumes automatically)")
    p.add_argument("--format", default="dense", choices=["dense", "csr"],
                   help="csr = sparse-input variant "
                        "(daal_kmeans/allreducecsr); synthetic data is "
                        "sparsified at --density")
    p.add_argument("--density", type=float, default=0.05,
                   help="synthetic sparsity for --format csr")
    p.add_argument("--stream", action="store_true",
                   help="stream --points-file through the chunked "
                        "prefetching ingestion pipeline (harp_tpu.io."
                        "pipeline) instead of loading it whole: bounded "
                        "host memory, H2D overlapped with assembly, "
                        "bitwise-identical centroids")
    p.add_argument("--chunk-rows", type=int, default=65536,
                   help="rows per streamed chunk (--stream)")
    _add_config_flags(p, KMeansConfig)
    args = p.parse_args(argv)
    if args.save_every and not args.work_dir:
        # argparse usage error — fail before data gen / session / prepare
        p.error("--save-every requires --work-dir (nowhere to checkpoint)")
    if args.stream and not args.points_file:
        p.error("--stream streams part-files: it requires --points-file")
    if args.stream and args.save_every:
        p.error("--stream runs the fit as one compiled program over the "
                "assembled block — checkpointing applies to the in-memory "
                "path (drop --stream or --save-every)")
    cfg = _config_from_args(KMeansConfig, args)
    if args.format == "csr" and (args.points_file or args.save_every
                                 or cfg.comm != "regroupallgather"):
        # same fail-before-session idiom as the --save-every guard
        p.error("--format csr supports synthetic data with the fixed "
                "allreduce collective (daal_kmeans/allreducecsr) — "
                "--points-file/--save-every/--comm do not apply")
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen, loaders
    from harp_tpu.models import kmeans as km

    if args.format == "csr":
        from harp_tpu.models import sparse as sp

        n = args.num_points - args.num_points % sess.num_workers
        rows, cols, vals = datagen.sparse_points(n, cfg.dim, args.density,
                                                 seed=args.seed)
        dense0 = np.zeros((cfg.num_centroids, cfg.dim), np.float32)
        head = rows < cfg.num_centroids
        dense0[rows[head], cols[head]] = vals[head]
        model = sp.SparseKMeans(sess, sp.SparseKMeansConfig(
            cfg.num_centroids, cfg.dim, cfg.iterations))
        state = model.prepare(rows, cols, vals, n)
        model.fit_prepared(state, dense0)                  # compile+warm
        t0 = time.perf_counter()
        cen, costs = model.fit_prepared(state, dense0)
        dt = time.perf_counter() - t0
        print(f"kmeans[csr-allreduce] workers={sess.num_workers} n={n} "
              f"k={cfg.num_centroids} d={cfg.dim} nnz={len(vals)}: "
              f"{cfg.iterations / dt:.2f} iters/s, cost "
              f"{costs[0]:.1f} -> {costs[-1]:.1f}")
        return 0
    if args.stream:
        from harp_tpu.io import pipeline as pl

        paths = loaders.list_files(args.points_file)
        # the head part alone seeds the centroids — streaming exists so the
        # full set never sits in host memory at once
        head = loaders.load_dense_csv([paths[0]])
        cfg = dataclasses.replace(cfg, dim=head.shape[1])
        loader = pl.StreamLoader(paths, chunk_rows=args.chunk_rows)
        total = loader.total_rows
        if total is None:             # native counter unavailable, or URLs
            total = 0
            for pth in paths:
                opener = (loaders._fsspec_open(pth) if loaders._is_url(pth)
                          else open(pth, "rb"))
                with opener as f:
                    total += sum(1 for ln in f if ln.strip())
        n_fit = total - total % sess.num_workers
        if n_fit <= 0:
            p.error(f"--stream input has {total} rows, fewer than the "
                    f"{sess.num_workers}-worker mesh needs")
        cen0 = datagen.initial_centroids(head, cfg.num_centroids,
                                         seed=args.seed + 1)
        model = km.KMeans(sess, cfg)
        t0 = time.perf_counter()
        cen, costs = model.fit_from_stream(
            pl.DevicePrefetcher(loader, sess.replicate_put), cen0, n_fit)
        costs = np.asarray(costs)
        dt = time.perf_counter() - t0
        print(f"kmeans[stream/{cfg.comm}] workers={sess.num_workers} "
              f"n={n_fit} k={cfg.num_centroids} d={cfg.dim} "
              f"chunk_rows={args.chunk_rows}: {cfg.iterations / dt:.2f} "
              f"iters/s (incl stream+assembly), cost "
              f"{costs[0]:.1f} -> {costs[-1]:.1f}")
        import jax

        if args.work_dir and jax.process_index() == 0:
            os.makedirs(args.work_dir, exist_ok=True)
            np.savetxt(os.path.join(args.work_dir, "centroids.csv"),
                       np.asarray(cen), delimiter=",")
        return 0
    if args.points_file:
        # file, directory of part-files, or glob — local or scheme:// remote
        pts = loaders.load_dense_csv(loaders.list_files(args.points_file))
        cfg = dataclasses.replace(cfg, dim=pts.shape[1])
        pts = loaders.truncate_to_workers(pts, sess.num_workers)
    else:
        pts = datagen.dense_points(args.num_points, cfg.dim, seed=args.seed,
                                   num_clusters=cfg.num_centroids)
        pts = pts[: len(pts) - len(pts) % sess.num_workers]
    cen0 = datagen.initial_centroids(pts, cfg.num_centroids, seed=args.seed + 1)
    model = km.KMeans(sess, cfg)
    pts_dev, cen_dev = model.prepare(pts, cen0)
    if args.save_every:
        from harp_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(os.path.join(args.work_dir, "ckpt"))
        t0 = time.perf_counter()
        cen, costs, start = model.fit_checkpointed(
            pts_dev, cen_dev, ckpt, save_every=args.save_every)
        ran = cfg.iterations - start
        dt = time.perf_counter() - t0
        timing = " (incl compile)"
    else:
        model.fit_prepared(pts_dev, cen_dev)      # compile + warmup
        t0 = time.perf_counter()
        cen, costs = model.fit_prepared(pts_dev, cen_dev)
        ran = cfg.iterations
        dt = time.perf_counter() - t0
        timing = ""
    if ran > 0:
        costs = np.asarray(costs)
        print(f"kmeans[{cfg.comm}] workers={sess.num_workers} n={len(pts)} "
              f"k={cfg.num_centroids} d={cfg.dim}: {ran / dt:.2f} "
              f"iters/s{timing}, cost {costs[0]:.1f} -> {costs[-1]:.1f}")
    else:
        print(f"kmeans[{cfg.comm}] workers={sess.num_workers}: fully "
              f"resumed from checkpoint, nothing left to run")
    import jax

    if args.work_dir and jax.process_index() == 0:
        os.makedirs(args.work_dir, exist_ok=True)
        # reference: KMUtil.storeCentroids writes the final model from the
        # MASTER (also on a fully-resumed run — the restored centroids ARE
        # the model); gang members skip the write
        np.savetxt(os.path.join(args.work_dir, "centroids.csv"),
                   np.asarray(cen), delimiter=",")
    return 0


def run_sgd_mf(argv) -> int:
    from harp_tpu.models.sgd_mf import SGDMFConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run sgd_mf")
    _common_flags(p)
    p.add_argument("--num-users", type=int, default=8192)
    p.add_argument("--num-items", type=int, default=8192)
    p.add_argument("--density", type=float, default=0.01)
    p.add_argument("--ratings-file", default="",
                   help="COO 'row col value' file/dir/glob (e.g. "
                        "datasets/sgd_mf); overrides the synthetic data")
    p.add_argument("--adaptive", action="store_true",
                   help="auto-tune the per-hop budget (adjustMiniBatch analog)")
    p.add_argument("--save-every", type=int, default=0,
                   help="checkpoint every N epochs into work-dir (resumes "
                        "automatically if checkpoints exist)")
    _add_config_flags(p, SGDMFConfig)
    args = p.parse_args(argv)
    if args.save_every and not args.work_dir:
        # argparse usage error — fail before data gen / session / prepare
        # (was silently ignored here while kmeans/lda errored)
        p.error("--save-every requires --work-dir (nowhere to checkpoint)")
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import sgd_mf

    cfg = _config_from_args(sgd_mf.SGDMFConfig, args)
    if args.ratings_file:
        from harp_tpu.io import loaders

        rows, cols, vals = loaders.load_coo(
            loaders.list_files(args.ratings_file))
        # shapes come from the data; --num-users/--num-items are ignored
        nu, ni = int(rows.max()) + 1, int(cols.max()) + 1
    else:
        rows, cols, vals = datagen.sparse_ratings(
            args.num_users, args.num_items, rank=min(cfg.rank, 16),
            density=args.density, seed=args.seed)
        nu, ni = args.num_users, args.num_items
    model = sgd_mf.SGDMF(sess, cfg)
    state = model.prepare(rows, cols, vals, nu, ni, seed=args.seed)
    t0 = time.perf_counter()
    if args.save_every:
        from harp_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(os.path.join(args.work_dir, "ckpt"))
        model.warmup_epoch(state)                 # compile outside the timing
        t0 = time.perf_counter()
        w, h, rmse, start = model.fit_checkpointed(
            state, ckpt, save_every=args.save_every)
        ran = cfg.epochs - start
    elif args.adaptive:
        w, h, rmse, tuner = model.fit_adaptive(state)
        ran = cfg.epochs
        print(f"tuned budget: {tuner.chosen} "
              f"(times {dict(sorted(tuner.times.items()))})")
    else:
        model.fit_prepared(state)                 # compile + warmup
        t0 = time.perf_counter()
        w, h, rmse = model.fit_prepared(state)
        ran = cfg.epochs
    dt = time.perf_counter() - t0
    if ran <= 0 or not len(rmse):
        print(f"sgd_mf[{model.last_layout_stats['layout']}] "
              f"workers={sess.num_workers}: fully resumed from checkpoint, "
              f"nothing left to run")
        return 0
    nnz = len(vals) - model.last_layout_stats.get("duplicates_dropped", 0)
    if args.adaptive:
        # the wall-clock region above includes per-candidate AOT compiles and
        # warm-ups; the tuner's own steady-state epoch timings are the honest
        # throughput figure (advisor r2)
        dt = tuner.times[tuner.chosen] * ran
    sps = nnz * ran / dt
    steady = " (tuner steady-state)" if args.adaptive else ""
    print(f"sgd_mf[{model.last_layout_stats['layout']}] "
          f"workers={sess.num_workers} nnz={nnz} rank={cfg.rank}: "
          f"{sps / 1e6:.2f} M samples/s{steady}, rmse {rmse[0]:.4f} -> "
          f"{rmse[-1]:.4f}")
    return 0


def run_lda(argv) -> int:
    from harp_tpu.models.lda import LDAConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run lda")
    _common_flags(p)
    p.add_argument("--num-docs", type=int, default=1024)
    p.add_argument("--doc-len", type=int, default=64)
    p.add_argument("--corpus-file", default="",
                   help="token-id corpus file/dir/glob (one doc per line, "
                        "fixed length — e.g. datasets/lda); overrides the "
                        "synthetic corpus; vocab grows to fit the data")
    p.add_argument("--save-every", type=int, default=0,
                   help="checkpoint the chain (z + word-topic model) every "
                        "N epochs into work-dir (printModel parity; resumes "
                        "automatically)")
    _add_config_flags(p, LDAConfig)
    args = p.parse_args(argv)
    if args.save_every and not args.work_dir:
        # argparse usage error — fail before data gen / session / prepare
        p.error("--save-every requires --work-dir (nowhere to checkpoint)")
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import lda

    cfg = _config_from_args(lda.LDAConfig, args)
    if args.corpus_file:
        from harp_tpu.io import loaders

        docs = loaders.truncate_to_workers(loaders.load_corpus(
            args.corpus_file), sess.num_workers)
        num_docs = len(docs)
        if docs.size and int(docs.max()) >= cfg.vocab:
            cfg = dataclasses.replace(cfg, vocab=int(docs.max()) + 1)
    else:
        num_docs = args.num_docs - args.num_docs % sess.num_workers
        docs = datagen.lda_corpus(num_docs, cfg.vocab,
                                  max(2, cfg.num_topics // 2), args.doc_len,
                                  seed=args.seed)
    model = lda.LDA(sess, cfg)
    state = model.prepare(docs, seed=args.seed)   # host layout + H2D once
    if args.save_every:
        from harp_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(os.path.join(args.work_dir, "ckpt"))
        t0 = time.perf_counter()
        _, _, ll, start = model.fit_checkpointed(
            state, ckpt, save_every=args.save_every)
        ran = cfg.epochs - start
        dt = time.perf_counter() - t0
        timing = " (incl compile)"
        if ran <= 0:
            print(f"lda[cgs] workers={sess.num_workers}: fully resumed "
                  f"from checkpoint, nothing left to run")
            return 0
    else:
        model.fit_prepared(state)                 # compile + warmup
        t0 = time.perf_counter()
        _, _, ll = model.fit_prepared(state)
        ran = cfg.epochs
        dt = time.perf_counter() - t0
        timing = ""
    toks = docs.size * ran
    print(f"lda[cgs] workers={sess.num_workers} docs={num_docs} "
          f"vocab={cfg.vocab} K={cfg.num_topics}: {toks / dt / 1e6:.2f} "
          f"M tokens/s{timing}, ll {ll[0]:.4e} -> {ll[-1]:.4e}")
    return 0


def run_pca(argv) -> int:
    p = argparse.ArgumentParser(prog="harp_tpu.run pca")
    _common_flags(p)
    p.add_argument("--num-points", type=int, default=65536)
    p.add_argument("--dim", type=int, default=256)
    p.add_argument("--iterations", type=int, default=5,
                   help="timed repeats")
    p.add_argument("--method", default="cor", choices=["cor", "svd"],
                   help="cor = cordensedistr; svd = svddensedistr "
                        "(z-score + TSQR-SVD)")
    p.add_argument("--format", default="dense", choices=["dense", "csr"],
                   help="csr = daal_pca/corcsrdistr from sparse input")
    p.add_argument("--density", type=float, default=0.05,
                   help="synthetic sparsity for --format csr")
    p.add_argument("--points-file", default="",
                   help="dense CSV file/dir/glob (e.g. datasets/pca); "
                        "overrides the synthetic data (dense format only)")
    args = p.parse_args(argv)
    if args.points_file and args.format == "csr":
        p.error("--points-file applies to --format dense only")
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import stats

    n = args.num_points - args.num_points % sess.num_workers
    if args.format == "csr":
        from harp_tpu.models import sparse as sp

        if args.method != "cor":
            p.error("--format csr implements the correlation method only "
                    "(daal_pca/corcsrdistr — the reference has no svd-csr "
                    "variant)")
        rows, cols, vals = datagen.sparse_points(n, args.dim, args.density,
                                                 seed=args.seed)
        t0 = time.perf_counter()
        w, comps, mean = sp.CSRPCA(sess).fit(rows, cols, vals, n, args.dim)
        dt = time.perf_counter() - t0
        print(f"pca[csr] workers={sess.num_workers} n={n} d={args.dim} "
              f"nnz={len(vals)}: fit in {dt:.2f}s (incl compile), top "
              f"eigenvalue {w[0]:.4f}")
        return 0
    if args.points_file:
        from harp_tpu.io import loaders

        x = loaders.truncate_to_workers(
            loaders.load_dense_csv(loaders.list_files(args.points_file)),
            sess.num_workers)
        n = len(x)
    else:
        x = datagen.dense_points(n, args.dim, seed=args.seed)
    # place once; re-scattering an already-placed array is a no-op, and the
    # repeats loop runs INSIDE one compiled program (stats.PCA.fit_repeated)
    # so the timing is compute, not transfers or per-call dispatch
    x_dev = sess.scatter(x)
    model = stats.PCA(sess, method=args.method)
    if args.method == "svd":
        # the repeated-fits-in-one-program harness is the correlation
        # path's benchmark surface; svd runs plain fits
        model.fit(x_dev)                          # compile + warmup
        t0 = time.perf_counter()
        w, comps, mean = model.fit(x_dev)
        dt = time.perf_counter() - t0
        print(f"pca[svd] workers={sess.num_workers} n={n} d={x.shape[1]}: "
              f"{1.0 / dt:.2f} fits/s, top eigenvalue {w[0]:.4f}")
        return 0
    model.fit_repeated(x_dev, args.iterations)    # compile + warmup
    t0 = time.perf_counter()
    w, comps, mean = model.fit_repeated(x_dev, args.iterations)
    dt = time.perf_counter() - t0
    print(f"pca workers={sess.num_workers} n={n} d={x.shape[1]}: "
          f"{args.iterations / dt:.2f} fits/s, top eigenvalue {w[0]:.4f}")
    return 0


def run_nn(argv) -> int:
    from harp_tpu.models.nn import NNConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run nn")
    _common_flags(p)
    p.add_argument("--num-points", type=int, default=8192)
    p.add_argument("--dim", type=int, default=64)
    _add_config_flags(p, NNConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import nn

    cfg = _config_from_args(nn.NNConfig, args)
    n = args.num_points - args.num_points % sess.num_workers
    x, y = datagen.classification_data(n, args.dim, cfg.num_classes,
                                       seed=args.seed)
    model = nn.MLPClassifier(sess, cfg)
    model.fit(x, y, seed=args.seed)               # compile + warmup
    t0 = time.perf_counter()
    losses = model.fit(x, y, seed=args.seed)
    dt = time.perf_counter() - t0
    acc = (model.predict(x) == y).mean()
    samples = n * cfg.epochs
    print(f"nn workers={sess.num_workers} n={n} d={args.dim} "
          f"layers={cfg.layers}: {samples / dt / 1e6:.2f} M samples/s, "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f}, "
          f"train acc {acc:.3f}")
    return 0


def run_als(argv) -> int:
    from harp_tpu.models.als import ALSConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run als")
    _common_flags(p)
    p.add_argument("--num-users", type=int, default=2048)
    p.add_argument("--num-items", type=int, default=2048)
    p.add_argument("--density", type=float, default=0.01)
    p.add_argument("--ratings-file", default="",
                   help="COO 'row col value' file/dir/glob (e.g. "
                        "datasets/als); overrides the synthetic data")
    _add_config_flags(p, ALSConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    from harp_tpu.io import datagen
    from harp_tpu.models import als

    cfg = _config_from_args(als.ALSConfig, args)
    if args.ratings_file:
        from harp_tpu.io import loaders

        rows, cols, vals = loaders.load_coo(
            loaders.list_files(args.ratings_file))
        # shapes come from the data; --num-users/--num-items are ignored
        nu, ni = int(rows.max()) + 1, int(cols.max()) + 1
    else:
        rows, cols, vals = datagen.sparse_ratings(
            args.num_users, args.num_items, rank=min(cfg.rank, 16),
            density=args.density, seed=args.seed)
        nu, ni = args.num_users, args.num_items
    if cfg.implicit:
        import numpy as np

        vals = np.abs(vals)      # implicit mode consumes interaction counts
    model = als.ALS(sess, cfg)
    state = model.prepare(rows, cols, vals, nu, ni, seed=args.seed)
    model.train_prepared(state)                   # compile + warmup
    t0 = time.perf_counter()
    u, v, rmse = model.fit_prepared(state)
    dt = time.perf_counter() - t0
    mode = "implicit" if cfg.implicit else "explicit"
    print(f"als[{mode}] workers={sess.num_workers} nnz={len(vals)} "
          f"rank={cfg.rank}: {cfg.iterations / dt:.2f} iters/s, "
          f"rmse {rmse[0]:.4f} -> {rmse[-1]:.4f}")
    return 0


def run_ccd(argv) -> int:
    from harp_tpu.models.ccd import CCDConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run ccd")
    _common_flags(p)
    p.add_argument("--num-users", type=int, default=1024)
    p.add_argument("--num-items", type=int, default=1024)
    p.add_argument("--density", type=float, default=0.02)
    _add_config_flags(p, CCDConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    from harp_tpu.io import datagen
    from harp_tpu.models import ccd

    cfg = _config_from_args(ccd.CCDConfig, args)
    rows, cols, vals = datagen.sparse_ratings(
        args.num_users, args.num_items, rank=min(cfg.rank, 8),
        density=args.density, seed=args.seed)
    t0 = time.perf_counter()
    _, _, rmse = ccd.CCD(sess, cfg).fit(rows, cols, vals, args.num_users,
                                        args.num_items, seed=args.seed)
    dt = time.perf_counter() - t0
    print(f"ccd workers={sess.num_workers} nnz={len(vals)} rank={cfg.rank}: "
          f"{cfg.outer_iterations / dt:.2f} sweeps/s (incl compile), "
          f"rmse {rmse[0]:.4f} -> {rmse[-1]:.4f}")
    return 0


def run_mds(argv) -> int:
    from harp_tpu.models.mds import MDSConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run mds")
    _common_flags(p)
    p.add_argument("--num-points", type=int, default=256)
    p.add_argument("--source-dim", type=int, default=8,
                   help="dimensionality of the synthetic source points")
    _add_config_flags(p, MDSConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import mds

    cfg = _config_from_args(mds.MDSConfig, args)
    n = args.num_points - args.num_points % sess.num_workers
    pts = datagen.dense_points(n, args.source_dim, seed=args.seed)
    d = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1)).astype(np.float32)
    t0 = time.perf_counter()
    x, stress = mds.WDAMDS(sess, cfg).fit(d, seed=args.seed)
    dt = time.perf_counter() - t0
    print(f"mds workers={sess.num_workers} n={n} dim={cfg.dim}: "
          f"{cfg.iterations / dt:.2f} iters/s (incl compile), "
          f"stress {stress[0]:.4f} -> {stress[-1]:.4f}")
    return 0


def run_pagerank(argv) -> int:
    from harp_tpu.models.pagerank import PageRankConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run pagerank")
    _common_flags(p)
    p.add_argument("--num-vertices", type=int, default=4096)
    p.add_argument("--num-edges", type=int, default=32768)
    _add_config_flags(p, PageRankConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.models import pagerank

    cfg = _config_from_args(pagerank.PageRankConfig, args)
    rng = np.random.default_rng(args.seed)
    src = rng.integers(0, args.num_vertices, args.num_edges)
    dst = rng.integers(0, args.num_vertices, args.num_edges)
    t0 = time.perf_counter()
    ranks, deltas = pagerank.PageRank(sess, cfg).run(src, dst,
                                                     args.num_vertices)
    dt = time.perf_counter() - t0
    print(f"pagerank workers={sess.num_workers} v={args.num_vertices} "
          f"e={args.num_edges}: {cfg.iterations / dt:.2f} iters/s "
          f"(incl compile), final L1 delta {deltas[-1]:.2e}, "
          f"top rank {ranks.max():.5f}")
    return 0


def run_subgraph(argv) -> int:
    from harp_tpu.models.subgraph import SubgraphConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run subgraph")
    _common_flags(p)
    p.add_argument("--num-vertices", type=int, default=256)
    p.add_argument("--num-edges", type=int, default=1024)
    p.add_argument("--template", default="",
                   help="tree edges like '0-1,1-2,1-3' (default: a path of "
                        "--template-size vertices)")
    p.add_argument("--template-file", default="",
                   help="a reference-format .template file (vertex count, "
                        "edge count, then one edge per line — the "
                        "datasets/daal_subgraph/templates format)")
    _add_config_flags(p, SubgraphConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.models import subgraph

    cfg = _config_from_args(subgraph.SubgraphConfig, args)
    rng = np.random.default_rng(args.seed)
    src = rng.integers(0, args.num_vertices, args.num_edges)
    dst = rng.integers(0, args.num_vertices, args.num_edges)
    counter = subgraph.SubgraphCounter(sess, cfg)
    t0 = time.perf_counter()
    if args.template_file:
        edges = subgraph.load_template_file(args.template_file)
        est, trials = counter.count_template(edges, src, dst,
                                             args.num_vertices,
                                             seed=args.seed)
        shape = os.path.basename(args.template_file)
    elif args.template:
        edges = [tuple(map(int, e.split("-"))) for e in
                 args.template.split(",")]
        est, trials = counter.count_template(edges, src, dst,
                                             args.num_vertices,
                                             seed=args.seed)
        shape = args.template
    else:
        est, trials = counter.count_paths(src, dst, args.num_vertices,
                                          seed=args.seed)
        shape = f"path{cfg.template_size}"
    dt = time.perf_counter() - t0
    print(f"subgraph[{shape}] workers={sess.num_workers} "
          f"v={args.num_vertices} e={args.num_edges}: estimate {est:.1f} "
          f"({cfg.trials} trials in {dt:.1f}s, cv "
          f"{np.std(trials) / max(np.mean(trials), 1e-9):.2f})")
    return 0


def run_svm(argv) -> int:
    """daal_svm: ``--kernel linear`` trains the primal LinearSVM; rbf/poly
    train the dual KernelSVM; ``--num-classes > 2`` runs the one-vs-one
    MultiClassSVM (MultiClassDenseBatch parity)."""
    from harp_tpu.models.svm import KernelSVMConfig, SVMConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run svm")
    _common_flags(p)
    p.add_argument("--num-points", type=int, default=4096)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--num-classes", type=int, default=2)
    p.add_argument("--kernel", default="linear",
                   choices=["linear", "rbf", "poly"],
                   help="linear = primal subgradient; rbf/poly = dual "
                        "kernel machine (rotation-blocked Gram)")
    _add_config_flags(p, KernelSVMConfig, skip={"kernel", "iterations"})
    p.add_argument("--iterations", type=int, default=None,
                   help="default: 200 primal / 400 dual (the per-path "
                        "dataclass defaults)")
    p.add_argument("--lr", type=float, default=0.1,
                   help="primal (linear) path only")
    p.add_argument("--train-file", default="",
                   help="labeled dense CSV file/dir/glob, label in the LAST "
                        "column (e.g. datasets/svm); overrides synthetic")
    args = p.parse_args(argv)
    sess = _session(args)
    from harp_tpu.io import datagen
    from harp_tpu.models import svm

    if args.train_file:
        import numpy as np

        from harp_tpu.io import loaders

        x, y_raw = loaders.load_labeled_csv(args.train_file)
        x = loaders.truncate_to_workers(x, sess.num_workers)
        n = len(x)
        # the trainers take labels 0..k-1 (mapped internally to ±1); CSV
        # labels may use any convention (±1, 1..k) — remap via unique
        classes, y = np.unique(y_raw[:n], return_inverse=True)
        y = y.astype(np.int32)
        k = max(2, len(classes))
    else:
        n = args.num_points - args.num_points % sess.num_workers
        k = max(2, args.num_classes)
        x, y = datagen.classification_data(n, args.dim, k, seed=args.seed)
    dim = x.shape[1]
    t0 = time.perf_counter()
    if args.kernel == "linear" and k == 2:
        cfg = svm.SVMConfig(c=args.c, lr=args.lr,
                            iterations=args.iterations or 200)
        model = svm.LinearSVM(sess, cfg)
        losses = model.fit(x, y)
        dt = time.perf_counter() - t0
        acc = (model.predict(x) == y).mean()
        print(f"svm[linear-primal] workers={sess.num_workers} n={n} "
              f"d={dim}: {cfg.iterations / dt:.1f} iters/s (incl "
              f"compile), hinge {losses[0]:.4f} -> {losses[-1]:.4f}, "
              f"train acc {acc:.3f}")
        return 0
    kcfg = _config_from_args(svm.KernelSVMConfig, args, kernel=args.kernel)
    if k == 2:
        model = svm.KernelSVM(sess, kcfg)
        duals = model.fit(x, y)
        dt = time.perf_counter() - t0
        acc = (model.predict(x) == y).mean()
        print(f"svm[{args.kernel}-dual] workers={sess.num_workers} n={n} "
              f"d={dim}: {kcfg.iterations / dt:.1f} iters/s (incl "
              f"compile), dual {duals[0]:.2f} -> {duals[-1]:.2f}, "
              f"{len(model.sv_x)} SVs, train acc {acc:.3f}")
    else:
        model = svm.MultiClassSVM(sess, kcfg).fit(x, y)
        dt = time.perf_counter() - t0
        acc = (model.predict(x) == y).mean()
        print(f"svm[{args.kernel}-ovo] workers={sess.num_workers} n={n} "
              f"d={dim} classes={k}: {len(model._machines)} machines "
              f"in {dt:.1f}s, train acc {acc:.3f}")
    return 0


def run_forest(argv) -> int:
    from harp_tpu.models.forest import TreeConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run forest")
    _common_flags(p)
    p.add_argument("--num-points", type=int, default=4096)
    p.add_argument("--dim", type=int, default=16)
    _add_config_flags(p, TreeConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    from harp_tpu.io import datagen
    from harp_tpu.models import forest

    cfg = _config_from_args(forest.TreeConfig, args)
    n = args.num_points - args.num_points % sess.num_workers
    x, y = datagen.classification_data(n, args.dim, cfg.num_classes,
                                       seed=args.seed)
    t0 = time.perf_counter()
    if cfg.num_trees > 1:
        model = forest.RandomForest(sess, cfg).fit(x, y, seed=args.seed)
        kind = f"forest x{cfg.num_trees}"
    else:
        model = forest.DecisionTree(sess, cfg).fit(x, y)
        kind = "dtree"
    dt = time.perf_counter() - t0
    acc = (model.predict(x) == y).mean()
    print(f"forest[{kind}] workers={sess.num_workers} n={n} d={args.dim} "
          f"depth={cfg.depth}: trained in {dt:.1f}s, train acc {acc:.3f}")
    return 0


def run_boosting(argv) -> int:
    from harp_tpu.models.boosting import BoostConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run boosting")
    _common_flags(p)
    p.add_argument("--kind", default="ada",
                   choices=["stump", "ada", "brown", "logit"])
    p.add_argument("--num-points", type=int, default=4096)
    p.add_argument("--dim", type=int, default=16)
    _add_config_flags(p, BoostConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    from harp_tpu.io import datagen
    from harp_tpu.models import boosting

    cfg = _config_from_args(boosting.BoostConfig, args)
    n = args.num_points - args.num_points % sess.num_workers
    x, y = datagen.classification_data(n, args.dim, 2, seed=args.seed)
    cls = {"stump": boosting.DecisionStump, "ada": boosting.AdaBoost,
           "brown": boosting.BrownBoost, "logit": boosting.LogitBoost}
    t0 = time.perf_counter()
    model = cls[args.kind](sess, cfg).fit(x, y)
    dt = time.perf_counter() - t0
    acc = (model.predict(x) == y).mean()
    print(f"boosting[{args.kind}] workers={sess.num_workers} n={n} "
          f"d={args.dim} rounds={cfg.rounds}: trained in {dt:.1f}s, "
          f"train acc {acc:.3f}")
    return 0


def run_solver(argv) -> int:
    from harp_tpu.models.solvers import SolverConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run solver")
    _common_flags(p)
    p.add_argument("--kind", default="lbfgs",
                   choices=["sgd", "sgd_minibatch", "sgd_momentum",
                            "adagrad", "lbfgs"])
    p.add_argument("--num-points", type=int, default=4096)
    p.add_argument("--dim", type=int, default=32)
    _add_config_flags(p, SolverConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import solvers

    cfg = _config_from_args(solvers.SolverConfig, args)
    n = args.num_points - args.num_points % sess.num_workers
    x, y, _ = datagen.regression_data(n, args.dim, seed=args.seed)
    y = y.reshape(-1)
    theta0 = np.zeros(args.dim, np.float32)
    t0 = time.perf_counter()
    theta, losses = solvers.Solver(sess, args.kind, cfg).minimize(
        solvers.mse_objective, x, y, theta0)
    dt = time.perf_counter() - t0
    print(f"solver[{args.kind}] workers={sess.num_workers} n={n} "
          f"d={args.dim}: {cfg.iterations / dt:.1f} iters/s (incl compile), "
          f"mse {losses[0]:.4f} -> {losses[-1]:.6f}")
    return 0


def run_stats(argv) -> int:
    p = argparse.ArgumentParser(prog="harp_tpu.run stats")
    _common_flags(p)
    p.add_argument("--op", default="cov",
                   choices=["cov", "moments", "zscore", "minmax", "qr",
                            "pivoted_qr", "svd", "cholesky", "quantiles",
                            "sort", "outlier"])
    p.add_argument("--num-points", type=int, default=8192)
    p.add_argument("--dim", type=int, default=64)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import stats

    n = args.num_points - args.num_points % sess.num_workers
    x = datagen.dense_points(n, args.dim, seed=args.seed)
    t0 = time.perf_counter()
    if args.op == "cov":
        cov, mean = stats.Covariance(sess).compute(x)
        res = f"trace {np.trace(cov):.4f}"
    elif args.op == "moments":
        m = stats.LowOrderMoments(sess).compute(x)
        res = f"mean[0] {m.mean[0]:.4f} var[0] {m.variance[0]:.4f}"
    elif args.op == "zscore":
        z = stats.ZScore(sess).transform(x)
        res = f"col0 mean {z[:, 0].mean():.2e} std {z[:, 0].std():.4f}"
    elif args.op == "minmax":
        mm = stats.MinMax(sess).transform(x)
        res = f"range [{mm.min():.3f}, {mm.max():.3f}]"
    elif args.op == "qr":
        q, r = stats.QR(sess).compute(x)
        res = f"||QR-X|| {np.abs(q @ r - x).max():.2e}"
    elif args.op == "pivoted_qr":
        q, r, piv = stats.PivotedQR(sess).compute(x)
        res = f"||QR-X[:,piv]|| {np.abs(q @ r - x[:, piv]).max():.2e}"
    elif args.op == "svd":
        u, s, vt = stats.SVD(sess).compute(x)
        res = f"top sv {s[0]:.4f}"
    elif args.op == "cholesky":
        l = stats.Cholesky(sess).compute(x)
        res = f"diag[0] {l[0, 0]:.4f}"
    elif args.op == "quantiles":
        q = stats.Quantiles(sess).compute(x, [0.25, 0.5, 0.75])
        res = f"col0 quartiles {np.round(q[:, 0], 4).tolist()}"
    elif args.op == "sort":
        s = stats.Sorting(sess).compute(x)
        res = f"col0 sorted: {bool((np.diff(s[:, 0]) >= 0).all())}"
    else:
        flags = stats.OutlierDetection(sess).compute(x)
        res = f"outliers {int(flags.sum())}/{n}"
    dt = time.perf_counter() - t0
    print(f"stats[{args.op}] workers={sess.num_workers} n={n} "
          f"d={args.dim}: {res} ({dt:.1f}s incl compile)")
    return 0


def run_linear(argv) -> int:
    p = argparse.ArgumentParser(prog="harp_tpu.run linear")
    _common_flags(p)
    p.add_argument("--num-points", type=int, default=8192)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--l2", type=float, default=0.0,
                   help="> 0 selects ridge (daal_ridgereg)")
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import linear

    n = args.num_points - args.num_points % sess.num_workers
    x, y, _ = datagen.regression_data(n, args.dim, seed=args.seed)
    t0 = time.perf_counter()
    model = linear.LinearRegression(sess, l2=args.l2).fit(x, y)
    dt = time.perf_counter() - t0
    pred = model.predict(x)
    mse = float(np.mean((pred - y.reshape(pred.shape)) ** 2))
    kind = "ridge" if args.l2 > 0 else "linreg"
    print(f"linear[{kind}] workers={sess.num_workers} n={n} d={args.dim}: "
          f"mse {mse:.6f} ({dt:.1f}s incl compile)")
    return 0


def run_classifiers(argv) -> int:
    """naive_bayes / knn / mlr / em — the remaining daal classifier families."""
    p = argparse.ArgumentParser(prog="harp_tpu.run classifiers")
    _common_flags(p)
    p.add_argument("--kind", default="mlr",
                   choices=["multinomial_nb", "gaussian_nb", "knn", "mlr",
                            "em"])
    p.add_argument("--num-points", type=int, default=4096)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--num-classes", type=int, default=4)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen

    n = args.num_points - args.num_points % sess.num_workers
    x, y = datagen.classification_data(n, args.dim, args.num_classes,
                                       seed=args.seed)
    t0 = time.perf_counter()
    if args.kind == "em":
        from harp_tpu.models.em import EMConfig, EMGMM

        _, _, _, ll = EMGMM(sess, EMConfig(
            num_components=args.num_classes)).fit(x, seed=args.seed)
        dt = time.perf_counter() - t0
        print(f"classifiers[em] workers={sess.num_workers} n={n} "
              f"d={args.dim} K={args.num_classes}: "
              f"ll {ll[0]:.1f} -> {ll[-1]:.1f} ({dt:.1f}s incl compile)")
        return 0
    if args.kind == "multinomial_nb":
        from harp_tpu.models.naive_bayes import MultinomialNB

        model = MultinomialNB(sess, num_classes=args.num_classes).fit(
            np.abs(x), y)
        pred = model.predict(np.abs(x))
    elif args.kind == "gaussian_nb":
        from harp_tpu.models.naive_bayes import GaussianNB

        model = GaussianNB(sess, num_classes=args.num_classes).fit(x, y)
        pred = model.predict(x)
    elif args.kind == "knn":
        from harp_tpu.models.knn import KNNClassifier

        model = KNNClassifier(sess, k=5, num_classes=args.num_classes
                              ).fit(x, y)
        pred = model.predict(x[:256])
        y = y[:256]
    else:
        from harp_tpu.models.logistic import MLR, MLRConfig

        model = MLR(sess, MLRConfig(num_classes=args.num_classes))
        model.fit(x, y)
        pred = model.predict(x)
    dt = time.perf_counter() - t0
    acc = (pred == y).mean()
    print(f"classifiers[{args.kind}] workers={sess.num_workers} n={n} "
          f"d={args.dim} C={args.num_classes}: train acc {acc:.3f} "
          f"({dt:.1f}s incl compile)")
    return 0


def run_apriori(argv) -> int:
    from harp_tpu.models.assoc import AprioriConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run apriori")
    _common_flags(p)
    p.add_argument("--num-transactions", type=int, default=2048)
    p.add_argument("--num-items", type=int, default=32)
    _add_config_flags(p, AprioriConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.models import assoc

    cfg = _config_from_args(assoc.AprioriConfig, args)
    rng = np.random.default_rng(args.seed)
    n = args.num_transactions - args.num_transactions % sess.num_workers
    # correlated items so some multi-item sets clear min_support
    base = rng.random((n, 4)) < 0.5
    tx = np.zeros((n, args.num_items), np.float32)
    for j in range(args.num_items):
        tx[:, j] = base[:, j % 4] if j < 8 else (rng.random(n) < 0.05)
    t0 = time.perf_counter()
    model = assoc.Apriori(sess, cfg).fit(tx)
    dt = time.perf_counter() - t0
    print(f"apriori workers={sess.num_workers} n={n} d={args.num_items}: "
          f"{len(model.itemsets)} frequent itemsets, {len(model.rules)} "
          f"rules ({dt:.1f}s incl compile)")
    return 0


def run_sgxsimu(argv) -> int:
    """experimental/kmeans/sgxsimu parity: K-means with modeled trusted-
    enclave (SGX/TEE) overheads (KMeansLauncher.java of that package)."""
    from harp_tpu.models.kmeans import KMeansConfig

    p = argparse.ArgumentParser(prog="harp_tpu.run sgxsimu")
    _common_flags(p)
    p.add_argument("--num-points", type=int, default=20000)
    p.add_argument("--enclave-total-mb", type=int, default=96,
                   help="total enclave capacity (reference ENCLAVE_TOTAL)")
    p.add_argument("--enclave-per-thd-mb", type=int, default=96,
                   help="effective enclave per thread (ENCLAVE_PER_THD)")
    p.add_argument("--threads-per-worker", type=int, default=1)
    p.add_argument("--page-swap", action="store_true",
                   help="include the page-swap term the reference defines "
                        "but ships commented out")
    p.add_argument("--simulate", action="store_true",
                   help="sleep the modeled overheads so the wall clock "
                        "shows the enclave-cost shape (simuOverhead parity)")
    _add_config_flags(p, KMeansConfig)
    args = p.parse_args(argv)
    sess = _session(args)
    import numpy as np

    from harp_tpu.io import datagen
    from harp_tpu.models import kmeans as km
    from harp_tpu.models.sgxsimu import SGXSimuConfig, SGXSimuKMeans

    cfg = _config_from_args(km.KMeansConfig, args)
    pts = datagen.dense_points(args.num_points, cfg.dim, seed=args.seed,
                               num_clusters=cfg.num_centroids)
    pts = pts[: len(pts) - len(pts) % sess.num_workers]
    cen0 = datagen.initial_centroids(pts, cfg.num_centroids, seed=args.seed + 1)
    simu = SGXSimuConfig(enclave_total_mb=args.enclave_total_mb,
                         enclave_per_thd_mb=args.enclave_per_thd_mb,
                         threads_per_worker=args.threads_per_worker,
                         include_page_swap=args.page_swap)
    t0 = time.perf_counter()
    cen, costs, rep = SGXSimuKMeans(sess, cfg, simu).fit(
        pts, cen0, simulate=args.simulate)
    dt = time.perf_counter() - t0
    # the reference's five LOG.info totals (KMeansCollectiveMapper.java:368)
    print(f"sgxsimu workers={sess.num_workers} n={len(pts)} "
          f"k={cfg.num_centroids} d={cfg.dim}: "
          f"init {rep['init_ms']:.1f} ms; per-iter ecall "
          f"{rep['comp_ecall_ms_per_iter']:.3f} / ocall "
          f"{rep['comp_ocall_ms_per_iter']:.3f} / swap "
          f"{rep['comp_swap_ms_per_iter']:.3f} / comm "
          f"{rep['comm_ms_per_iter']:.3f} ms; clean "
          f"{rep['clean_ms_per_iter']:.3f} ms/iter -> modeled slowdown "
          f"{rep['modeled_slowdown']:.2f}x"
          f"{' (simulated in wall clock)' if args.simulate else ''}; "
          f"cost {np.asarray(costs)[0]:.1f} -> {np.asarray(costs)[-1]:.1f} "
          f"in {dt:.1f}s")
    return 0


def run_aot(argv) -> int:
    """AOT dispatch artifacts (ISSUE 15): offline prebuild + store tools.

    ``aot warm`` exports every (model, bucket) resident serving dispatch
    of a fleet's deterministic model specs into ``--aot-dir`` — run it
    once per deploy (or per jax upgrade / mesh change), point the fleet's
    ``aot_dir`` at the store, and every worker cold start — initial OR
    elastic spare — becomes a load: no trace, compile absorbed before
    rendezvous. ``aot ls`` lists the store; ``aot check`` verifies the
    pinned compiled-program manifest (the jaxlint --artifacts-only gate).
    """
    p = argparse.ArgumentParser(prog="harp_tpu.run aot")
    p.add_argument("action", choices=["warm", "ls", "check"])
    p.add_argument("--aot-dir", default="",
                   help="artifact store directory (warm/ls)")
    p.add_argument("--spec", default="",
                   help="fleet spec JSON (a ProcessServeGang workdir's "
                        "fleet_spec.json) — models + mesh width come from "
                        "it")
    p.add_argument("--models-json", default="",
                   help="inline {model: spec} JSON instead of --spec "
                        "(fleet.build_endpoint spec shapes)")
    p.add_argument("--mesh-workers", type=int, default=2,
                   help="mesh width to export at (must match the serving "
                        "fleet's; overridden by --spec)")
    p.add_argument("--version", type=int, default=0,
                   help="factor epoch to build the endpoints at (the "
                        "PROGRAM is epoch-independent; this only seeds "
                        "the throwaway state)")
    p.add_argument("--compile-cache-dir", default="",
                   help="also populate the persistent compilation cache "
                        "while warming")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="ls: one JSON object per artifact (machine-"
                        "readable rows incl. the memory and hlo meta, "
                        "null-safe for artifacts exported before either "
                        "row existed) instead of the table")
    args = p.parse_args(argv)
    import json as json_mod

    if args.action == "check":
        # the manifest gate without the rest of jaxlint (CI convenience)
        from tools.jaxlint.__main__ import main as jaxlint_main

        return jaxlint_main(["--artifacts-only"])
    if not args.aot_dir:
        p.error("--aot-dir is required for warm/ls")
    if args.action == "ls":
        from harp_tpu.aot.store import ArtifactStore

        for meta in ArtifactStore(args.aot_dir).list():
            # foreign/partial metas list with placeholders — the listing
            # tool survives the same seams the store's readers do; the
            # static memory row (resident/peak HBM bytes, ISSUE 19) is
            # optional metadata, so its columns degrade the same way
            mem = meta.get("memory") or {}
            if args.as_json:
                # the stable machine row fleet tooling consumes instead
                # of screen-scraping the table: key axes + sizes, the
                # r20 res/peak columns, and the r21 hlo row — absent
                # meta (pre-r20/r21 artifacts) serializes as null, never
                # a missing key
                print(json_mod.dumps({
                    "name": meta.get("name"),
                    "format": meta.get("format"),
                    "world": meta.get("world"),
                    "device_kind": meta.get("device_kind"),
                    "jax_version": meta.get("jax_version"),
                    "quant": meta.get("quant"),
                    "payload_bytes": meta.get("payload_bytes"),
                    "content_hash": meta.get("content_hash"),
                    "resident_arg_bytes": mem.get("resident_arg_bytes"),
                    "peak_live_bytes": mem.get("peak_live_bytes"),
                    "transient_peak_ratio": mem.get(
                        "transient_peak_ratio"),
                    "hlo": meta.get("hlo"),
                }, sort_keys=False))
                continue
            resident = mem.get("resident_arg_bytes")
            peak = mem.get("peak_live_bytes")
            mem_col = (f"res={int(resident):>8d} B peak={int(peak):>8d} B"
                       if resident is not None and peak is not None
                       else "res=       ? B peak=       ? B")
            print(f"{str(meta.get('name') or '?'):32s} "
                  f"{str(meta.get('format') or '?'):18s} "
                  f"world={meta.get('world')} "
                  f"{int(meta.get('payload_bytes') or 0):>8d} B  "
                  f"{mem_col}  "
                  f"{str(meta.get('content_hash') or '')[:12]}")
        return 0
    # warm: the export traces run on a virtual CPU mesh at the fleet's
    # width — never on an accelerator a training gang may hold (the
    # serving workers themselves run CPU-forced the same way)
    mesh_workers = args.mesh_workers
    models = None
    if args.spec:
        with open(args.spec) as f:
            spec = json_mod.load(f)
        models = spec.get("models") or {}
        mesh_workers = int(spec.get("mesh_workers", mesh_workers))
    if args.models_json:
        models = json_mod.loads(args.models_json)
    if not models:
        p.error("warm needs --spec or --models-json")
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               f" --xla_force_host_platform_device_count="
                               f"{mesh_workers}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if args.compile_cache_dir:
        from harp_tpu.aot.cache import enable_compile_cache

        enable_compile_cache(args.compile_cache_dir)
    from harp_tpu.serve import fleet as fleet_mod

    t0 = time.perf_counter()
    warmed = fleet_mod.warm_artifacts(models, args.aot_dir,
                                      mesh_workers=mesh_workers,
                                      version=args.version)
    dt = time.perf_counter() - t0
    n = sum(len(b) for b in warmed.values())
    print(f"aot warm: exported {n} dispatch artifact(s) for "
          f"{len(warmed)} model(s) at mesh width {mesh_workers} into "
          f"{args.aot_dir} ({dt:.1f}s): " +
          ", ".join(f"{m}={b}" for m, b in sorted(warmed.items())))
    return 0


COMMANDS = {
    "aot": run_aot,
    "kmeans": run_kmeans,
    "sgxsimu": run_sgxsimu,
    "sgd_mf": run_sgd_mf,
    "lda": run_lda,
    "pca": run_pca,
    "nn": run_nn,
    "als": run_als,
    "ccd": run_ccd,
    "mds": run_mds,
    "pagerank": run_pagerank,
    "subgraph": run_subgraph,
    "svm": run_svm,
    "forest": run_forest,
    "boosting": run_boosting,
    "solver": run_solver,
    "stats": run_stats,
    "linear": run_linear,
    "classifiers": run_classifiers,
    "apriori": run_apriori,
}


def _flag_value(argv, name):
    """Last occurrence of ``--name V`` / ``--name=V`` in argv, or None."""
    val = None
    for i, tok in enumerate(argv):
        if tok == name and i + 1 < len(argv):
            val = argv[i + 1]
        elif tok.startswith(name + "="):
            val = tok.split("=", 1)[1]
    return val


def _maybe_self_supervise(argv) -> Optional[int]:
    """``--max-restarts N`` outside a gang: re-exec this job under the
    elastic supervisor (parallel.supervisor.supervise_local) so a crash —
    scripted via HARP_FAULT or real — relaunches from the latest verified
    checkpoint. Under a gang launcher (HARP_COORDINATOR) the gang-level
    supervisor owns restarts; in the supervised child (HARP_SUPERVISED)
    recursing would nest supervisors."""
    try:
        restarts = int(_flag_value(argv, "--max-restarts") or 0)
    except ValueError:
        return None                  # let the subcommand parser reject it
    if restarts <= 0 or os.environ.get("HARP_COORDINATOR") \
            or os.environ.get("HARP_SUPERVISED"):
        return None
    from harp_tpu.parallel import supervisor

    work = _flag_value(argv, "--work-dir") or ""
    outcome = supervisor.supervise_local(
        [sys.executable, "-m", "harp_tpu.run"] + argv,
        # no per-attempt deadline: an unsupervised run has none either, and
        # a long legitimate fit must not be killed just because supervision
        # was enabled (the gang CLI keeps the 1800 s default — there a hung
        # MEMBER blocks the whole gang)
        timeout=None,
        policy=supervisor.RestartPolicy(max_restarts=restarts),
        checkpoint_dir=os.path.join(work, "ckpt") if work else None,
        journal_path=(os.path.join(work, "restart_journal.jsonl")
                      if work else None),
        metrics_path=(os.path.join(work, "supervisor_metrics.json")
                      if work else None),
        telemetry_dir=_flag_value(argv, "--telemetry-dir") or None,
        echo=True)
    if outcome.ok:
        return 0
    # surface the child's own exit code (an argparse usage error must still
    # exit 2 under supervision); signal deaths report negative — map to 1
    rc = (outcome.results.first_failed_rc
          if outcome.results is not None else None)
    return rc if rc is not None and rc > 0 else 1


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        print("subcommands:", ", ".join(sorted(COMMANDS)))
        return 0
    cmd = argv[0]
    if cmd not in COMMANDS:
        print(f"unknown subcommand {cmd!r}; choose from "
              f"{', '.join(sorted(COMMANDS))}", file=sys.stderr)
        return 2
    supervised = _maybe_self_supervise(argv)
    if supervised is not None:
        return supervised
    return COMMANDS[cmd](argv[1:])


if __name__ == "__main__":
    sys.exit(main())
